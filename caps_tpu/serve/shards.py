"""Shard groups: partitioned graphs behind :class:`QueryServer`.

ROADMAP item 2: PR 5 scaled *throughput* (N replicas, each holding the
whole graph); capacity stayed capped at one device's HBM.  This module
adds the capacity member type: a :class:`ShardGroup` is a set of member
devices fronting ONE hash-partitioned graph, mixed into the same
:class:`~caps_tpu.serve.devices.ReplicaSet` next to plain replicas.

* **Partitioning** (:func:`partition_graph`): node rows hash by the
  value of a designated partition property (nodes without it hash by
  node id — a property-equality query can never match them, so they
  never need routing); relationship rows follow their SOURCE node's
  partition.  Partitions are kept as host-side column slices — the
  "snapshot base" a member rebuild re-ingests from and the host arrays
  cold partitions spill to.  Every partition keeps the SAME table
  structure (mapping + column types) as the source graph, so every
  member's schema is identical to the unsharded graph's.

* **Routing** (:meth:`ShardGroup._route`): a query provably resident on
  one shard — a single node pattern, no relationships, with an equality
  on the partition property, and nothing in WHERE/RETURN that escapes
  the matched rows (no EXISTS sub-queries, no other variables) —
  executes on the OWNING member's partition session alone.  Everything
  else is a cross-shard pattern and executes on the group's sharded
  session: one engine session over a ``parallel/mesh.py`` mesh of the
  group's devices, whose tables row-shard over the ``shard`` axis and
  whose joins ride the existing okapi distributed-join machinery
  (radix / salted / broadcast — MULTICHIP_r05).  Either way results are
  exactly the unsharded session's (the digest-parity tests).

* **Group health ladder** (the robustness core): member states ride the
  same three-state breaker machine the device ladder uses, under a
  ``serve.shard_breaker`` metric prefix.  ``member_failure_threshold``
  consecutive member-attributed device faults quarantine the member and
  DEGRADE the group — healthy members keep serving their shards, the
  server's retry ladder covers the rest.  A background maintenance pass
  (per-member canary probes on the breaker's cooldown cadence) rebuilds
  the lost member onto a spare session — a fresh clone re-ingested from
  the host partition slices — and reinstates it after its canary
  passes.  ``group_failure_threshold`` failed rebuild cycles (or every
  member down at once) QUARANTINE the group: the server sheds
  group-routed traffic at admission with an honest ``retry_after_s``
  while replica members keep serving, and claimed group batches requeue.
  A dead shard device can never take the server down.

* **Host-memory partition paging** (:class:`ShardGroup` pager): with a
  ``page_budget_bytes`` per member, cold partitions spill to their host
  slices (device buffers dropped, member plan-cache entries for the
  spilled graph evicted) and fault back in on access — LRU per member,
  placement decided from the member's resident-byte ledger plus
  ``obs.ledger.device_bytes_in_use`` where the platform reports it.  A
  graph larger than one device's budget serves correctly: cold
  partitions are slower (re-ingest + re-plan), never wrong.
  ``paging.faults`` / ``paging.spills`` counters and
  ``paging.resident_bytes`` / ``paging.host_bytes`` gauges account it.

* **Sharded writes** (the durable-writes PR): Cypher CREATE / SET /
  DELETE through the group commits on an INTERNAL versioned lineage
  over the cross-shard clone — the session's normal write path, so
  staging, failure atomicity, and digest parity with an unsharded
  versioned graph hold by construction — and distributes each commit
  to the member shards through a prepare/commit round
  (:meth:`ShardGroup._prepare_commit`): the new overlay splits per
  shard along :func:`partition_graph`'s exact placement, every
  resident partition's new overlay graph builds under that member's
  string-pool mark (prepare — ANY failure rolls every member back and
  aborts the commit with no shard partially applied), the group WAL
  append is the commit point when the group is durable
  (``ShardGroupConfig.wal_dir``), and only then do the prepared
  overlays swap in — pure reference swaps that cannot fail.  Routed
  single-shard reads see writes through their member's overlay;
  cross-shard reads resolve the lineage's current snapshot.

Locking: the group serves ONE dispatch stream (``self.lock``, held by
the server exactly like a replica's execution lock); every residency
mutation (fault-in, spill, rebuild) happens under it, so the pager
needs no lock of its own.  Group state transitions sit behind the
separate ``_state_lock``, which is never held across an engine call.
"""
from __future__ import annotations

import contextlib
import dataclasses
import os
import threading
import zlib
from collections import OrderedDict
from typing import Any, Dict, List, Mapping, Optional, Tuple

from caps_tpu.obs import clock
from caps_tpu.obs.lockgraph import make_lock
from caps_tpu.serve.breaker import (CLOSED, HALF_OPEN, OPEN, REJECT, TRIAL,
                                    CircuitBreaker)
from caps_tpu.serve.deadline import cancel_scope
from caps_tpu.serve.errors import ShardMemberDown, ShardingUnsupported
from caps_tpu.serve.failure import device_fault

#: group health ladder states (``stats()["shards"]``)
GROUP_HEALTHY = "healthy"
GROUP_DEGRADED = "degraded"
GROUP_QUARANTINED = "quarantined"

#: member states mirror the device ladder's
MEMBER_HEALTHY = "healthy"
MEMBER_QUARANTINED = "quarantined"
MEMBER_PROBING = "probing"

_BREAKER_TO_MEMBER = {CLOSED: MEMBER_HEALTHY, OPEN: MEMBER_QUARANTINED,
                      HALF_OPEN: MEMBER_PROBING}

#: the group-level breaker key (member keys are ``("member", index)``)
_GROUP_KEY = ("group",)

#: per-member canary: a plain scan over a resident partition, so a
#: fault scoped to this member's operator stream fails the probe too
_CANARY_QUERY = "MATCH (n) RETURN n LIMIT 1"

#: bounded ring of group state transitions (bench reporting)
_MAX_TRANSITIONS = 64

#: routing decisions cached per query text (parse once per text)
_ROUTE_CACHE_CAP = 128

_shard_tls = threading.local()

_gauge_guard = make_lock("shards._gauge_guard")


def executing_shard() -> Optional[Tuple[str, Optional[int]]]:
    """``(group_name, member_index)`` for the calling thread's current
    shard-group execution bracket — ``member_index`` is None for a
    group-wide (cross-shard) execution, which runs on EVERY member's
    device at once.  The shard-scoped fault injectors
    (``testing/faults.py`` ``shard_loss`` / ``sick_shard``) key off
    this; None outside any group bracket."""
    return getattr(_shard_tls, "shard", None)


def _attribute_member(exc: BaseException, member_index: int) -> None:
    """Stamp the member index a group execution failure was observed on
    (first-writer-wins, like ``attribute_device``)."""
    try:
        if getattr(exc, "caps_shard_member", None) is None:
            exc.caps_shard_member = member_index
    except Exception:  # pragma: no cover — immutable exception types
        pass


def member_of(exc: BaseException) -> Optional[int]:
    """The member index stamped on a group execution failure (None for
    group-wide / unattributed failures)."""
    return getattr(exc, "caps_shard_member", None)


# -- partitioning ------------------------------------------------------------

def hash_value(value: Any) -> int:
    """Stable, process-independent hash of a partition-property value
    (``hash()`` is salted per process and would re-partition every
    restart).  Numerically-equal ints and floats hash IDENTICALLY —
    Cypher's ``5 = 5.0`` is true, so a float-typed parameter against an
    int-stored property must route to the shard that stored it (a
    type-sensitive hash would silently return empty results).  Booleans
    are not Cypher numbers and hash apart from 0/1."""
    if isinstance(value, bool):
        token = f"b:{value}"
    elif isinstance(value, float) and value.is_integer():
        token = f"i:{int(value)}"
    elif isinstance(value, int):
        token = f"i:{value}"
    elif isinstance(value, float):
        token = f"f:{value!r}"
    elif isinstance(value, str):
        token = f"s:{value}"
    elif value is None:
        token = "n:"
    else:
        token = f"o:{value!r}"
    return zlib.crc32(token.encode("utf-8"))


@dataclasses.dataclass
class _HostSlice:
    """One entity table's rows for one partition, held as host columns —
    the rebuild source and the paging spill target.  ``mapping`` is the
    SOURCE table's mapping, so the rebuilt table's schema is identical
    by construction."""

    kind: str                     # "node" | "rel"
    mapping: Any                  # NodeMapping | RelationshipMapping
    data: Dict[str, List[Any]]
    types: Dict[str, Any]
    rows: int

    def host_nbytes(self) -> int:
        """Rough host footprint (the ``paging.host_bytes`` gauge): 8
        bytes per scalar cell plus string payloads — an estimate, not
        an allocator read (host lists have no exact nbytes)."""
        total = 0
        for vals in self.data.values():
            total += 8 * len(vals)
            for v in vals:
                if isinstance(v, str):
                    total += len(v)
        return total


@dataclasses.dataclass
class GraphPartition:
    """One hash partition of the served graph: host-side slices of every
    entity table (same table structure as the source, rows filtered to
    this partition)."""

    index: int
    node_slices: List[_HostSlice]
    rel_slices: List[_HostSlice]

    @property
    def rows(self) -> int:
        return sum(s.rows for s in self.node_slices) + \
            sum(s.rows for s in self.rel_slices)

    def host_nbytes(self) -> int:
        return sum(s.host_nbytes() for s in self.node_slices) + \
            sum(s.host_nbytes() for s in self.rel_slices)

    def build(self, session):
        """Ingest this partition through ``session``'s table factory —
        per-shard CSR ingest: the member ends up with its own
        device-resident buffers for exactly its rows."""
        from caps_tpu.relational.entity_tables import (NodeTable,
                                                       RelationshipTable)
        factory = session.table_factory
        nts = [NodeTable(s.mapping,
                         factory.from_columns(s.data, s.types))
               for s in self.node_slices]
        rts = [RelationshipTable(s.mapping,
                                 factory.from_columns(s.data, s.types))
               for s in self.rel_slices]
        return session.create_graph(nts, rts)


def _table_host_columns(table) -> Dict[str, List[Any]]:
    return {c: list(table.column_values(c)) for c in table.columns}


def partition_graph(graph, n_partitions: int,
                    partition_property: str = "id",
                    home_out: Optional[Dict[int, int]] = None
                    ) -> List[GraphPartition]:
    """Hash-partition a scan graph's rows into ``n_partitions`` host
    slices.  Node rows hash by ``partition_property``'s value when the
    table maps that property (else by node id); relationship rows
    follow their source node's partition, so each partition's CSR holds
    the edges fanning out of its own nodes.  ``home_out`` (when given)
    receives the node-id -> partition map the split decided — the
    sharded commit protocol routes delta tombstones with it."""
    from caps_tpu.relational.graphs import ScanGraph
    if not isinstance(graph, ScanGraph):
        raise ShardingUnsupported(
            f"only scan graphs partition (got {type(graph).__name__}); "
            f"versioned/union/catalog graphs stay on replica members")
    n = max(1, int(n_partitions))
    node_home: Dict[int, int] = {}
    node_parts: List[List[Tuple[Any, Dict[str, List[Any]], Dict, int]]] = \
        [[] for _ in range(n)]
    for nt in graph.node_tables:
        table = nt.table
        cols = _table_host_columns(table)
        types = {c: table.column_type(c) for c in table.columns}
        ids = cols[nt.mapping.id_col]
        pcol = nt.mapping.property_cols.get(partition_property)
        pvals = cols.get(pcol) if pcol is not None else None
        rows_by_part: List[List[int]] = [[] for _ in range(n)]
        for i, nid in enumerate(ids):
            v = pvals[i] if pvals is not None else None
            p = (hash_value(v) if v is not None
                 else hash_value(f"#id:{int(nid)}")) % n
            node_home[int(nid)] = p
            rows_by_part[p].append(i)
        for p in range(n):
            rows = rows_by_part[p]
            node_parts[p].append((
                nt.mapping,
                {c: [vals[i] for i in rows] for c, vals in cols.items()},
                types, len(rows)))
    rel_parts: List[List[Tuple[Any, Dict[str, List[Any]], Dict, int]]] = \
        [[] for _ in range(n)]
    for rt in graph.rel_tables:
        table = rt.table
        cols = _table_host_columns(table)
        types = {c: table.column_type(c) for c in table.columns}
        srcs = cols[rt.mapping.source_col]
        rows_by_part = [[] for _ in range(n)]
        for i, src in enumerate(srcs):
            p = node_home.get(int(src))
            if p is None:  # dangling edge: hash the source id itself
                p = hash_value(f"#id:{int(src)}") % n
            rows_by_part[p].append(i)
        for p in range(n):
            rows = rows_by_part[p]
            rel_parts[p].append((
                rt.mapping,
                {c: [vals[i] for i in rows] for c, vals in cols.items()},
                types, len(rows)))
    if home_out is not None:
        home_out.update(node_home)
    out = []
    for p in range(n):
        out.append(GraphPartition(
            p,
            [_HostSlice("node", m, d, t, r)
             for m, d, t, r in node_parts[p]],
            [_HostSlice("rel", m, d, t, r)
             for m, d, t, r in rel_parts[p]]))
    return out


# -- configuration -----------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class ShardGroupConfig:
    #: group name — the fault injectors and stats key by it
    name: str = "shard0"
    #: member devices fronting the partitioned graph
    members: int = 2
    #: the node property whose value equality routes a query to one
    #: shard; nodes without it hash by id (and are never routed to)
    partition_property: str = "id"
    #: partitions per member (> 1 gives the pager units to spill)
    partitions_per_member: int = 1
    #: per-member device budget for resident partitions; None = no
    #: paging pressure (everything stays resident)
    page_budget_bytes: Optional[int] = None
    #: consecutive member-attributed device faults before a member
    #: quarantines (degrading the group)
    member_failure_threshold: int = 2
    #: cooldown before each background probe/rebuild attempt
    member_cooldown_s: float = 1.0
    #: failed rebuild cycles (or unattributed group-wide device faults)
    #: before the whole GROUP quarantines and its traffic sheds
    group_failure_threshold: int = 3
    #: build the cross-shard session over a ``parallel/mesh.py`` mesh of
    #: ``members`` devices (row-sharded tables + okapi dist joins); off
    #: (or on backends without a mesh) the cross session is a plain
    #: full-graph clone — same results, no capacity win for that path
    cross_shard_mesh: bool = True
    #: durable writes (caps_tpu/durability): when set, every group
    #: commit appends its cumulative overlay to a group WAL under
    #: ``{wal_dir}/wal-shard-{name}`` BEFORE the prepared overlays swap
    #: in, and a fresh group over the same directory recovers the
    #: lineage on construction
    wal_dir: Optional[str] = None
    #: group WAL fsync policy (``"always"`` / ``"rotate"`` / ``"never"``)
    wal_fsync: str = "rotate"


# -- members -----------------------------------------------------------------

class ShardMember:
    """One member device's serving state: its own session (per-member
    plan cache / string pool — compiled state never crosses members,
    docs/tpu.md), the partitions it owns, and which of them are
    device-resident right now (insertion order = LRU)."""

    def __init__(self, index: int, session, partitions: List[int]):
        self.index = index
        self.session = session
        #: partition indices this member owns
        self.partitions = list(partitions)
        #: pidx -> (graph, page_cost_bytes); insertion-ordered LRU.
        #: The cost is the partition's HOST-slice estimate — one stable
        #: currency for every budget decision, known before first build.
        self.resident: "OrderedDict[int, Tuple[Any, int]]" = OrderedDict()
        #: pidx -> the UNWRAPPED base partition graph behind a resident
        #: entry (the sharded commit protocol re-anchors each commit's
        #: shard overlay on it; identical to the resident graph until
        #: the first write touches the shard)
        self.base_graphs: Dict[int, Any] = {}
        #: pidx -> measured device-table bytes (reporting; populated at
        #: each build)
        self.measured_nbytes: Dict[int, int] = {}
        #: bumped on every rebuild: the "spare/recovered device"
        self.incarnation = 0
        self.requests = 0
        self.failed = 0
        self.rebuilds = 0
        self.probes = 0
        self.quarantines = 0
        self.reinstates = 0
        self.page_faults = 0
        self.page_spills = 0

    def resident_bytes(self) -> int:
        """Resident page cost (host-estimate currency — what the budget
        is checked against)."""
        return sum(nb for _g, nb in self.resident.values())

    def resident_device_bytes(self) -> int:
        """Measured device-table bytes of the resident partitions."""
        return sum(self.measured_nbytes.get(p, 0) for p in self.resident)

    def snapshot(self) -> Dict[str, Any]:
        return {"member": self.index,
                "partitions": list(self.partitions),
                "resident": list(self.resident.keys()),
                "resident_bytes": self.resident_bytes(),
                "resident_device_bytes": self.resident_device_bytes(),
                "incarnation": self.incarnation,
                "requests": self.requests, "failed": self.failed,
                "rebuilds": self.rebuilds, "probes": self.probes,
                "quarantines": self.quarantines,
                "reinstates": self.reinstates,
                "page_faults": self.page_faults,
                "page_spills": self.page_spills}


def _register_group_gauges(registry) -> None:
    """Registry-level ``shard.*`` / ``paging.*`` gauges over the LIVE
    groups on this registry (several servers can share one session —
    the admission depth gauge's live-set pattern): groups join the set
    at construction and leave it in :meth:`ShardGroup.close`, so a dead
    server's groups neither report stale bytes nor stay pinned."""
    with _gauge_guard:
        live = getattr(registry, "_shard_live_groups", None)
        if live is None:
            live = registry._shard_live_groups = []
            registry.gauge("shard.groups", fn=lambda: len(live))
            registry.gauge(
                "shard.degraded",
                fn=lambda: sum(1 for g in live
                               if g.health() != GROUP_HEALTHY))
            registry.gauge(
                "paging.resident_bytes",
                fn=lambda: sum(m.resident_bytes()
                               for g in live for m in g.members))
            registry.gauge(
                "paging.host_bytes",
                fn=lambda: sum(g.cold_host_bytes() for g in live))


class _GroupSessionFacade:
    """The session-shaped surface the server executes a group through:
    ``cypher_on_graph`` / ``cypher_batch`` / ``cypher_degraded`` route
    each query to the owning member's partition session or the group's
    sharded cross-shard session.  The server's whole containment
    machinery (micro-batching, retry ladder, breakers, telemetry) works
    on a group exactly as on a replica because of this seam."""

    def __init__(self, group: "ShardGroup"):
        self._group = group

    @property
    def tracer(self):
        return self._group.template_session.tracer

    def cypher_on_graph(self, graph, query, parameters=None):
        return self._group.execute(query, parameters)

    def cypher_batch(self, graph, items, scopes=None):
        out: List[Any] = []
        for i, (query, params) in enumerate(items):
            scope = scopes[i] if scopes is not None else None
            try:
                with cancel_scope(scope):
                    out.append(self._group.execute(query, params))
            except Exception as ex:
                out.append(ex)
        return out

    def cypher_degraded(self, graph, query, parameters=None, *,
                        no_plan_cache: bool = True,
                        no_fused: bool = False):
        return self._group.execute(query, parameters,
                                   degraded=(no_plan_cache, no_fused))


class ShardGroup:
    """N member devices fronting one hash-partitioned graph — a
    capacity member of the :class:`~caps_tpu.serve.devices.ReplicaSet`,
    duck-typed as a replica (``index`` / ``lock`` / ``session`` /
    ``activate`` / ``graph_for`` / ``note``) so the server's dispatch,
    retry, and telemetry paths treat it like any other execution
    stream."""

    def __init__(self, session, graph, config: ShardGroupConfig,
                 registry, event_log=None, index: int = 0,
                 on_change=None):
        if config.members < 1:
            raise ShardingUnsupported("a shard group needs >= 1 member")
        if getattr(graph, "graph_is_versioned", False):
            raise ShardingUnsupported(
                "shard groups partition static scan graphs and version "
                "them INTERNALLY (writes commit through the group's own "
                "lineage); an externally versioned input would split "
                "the commit lock across two handles")
        self.config = config
        self.name = config.name
        self.graph = graph
        self.index = index
        self.template_session = session
        self._registry = registry
        self._event_log = event_log
        self._on_change = on_change
        #: ONE dispatch stream per group (the server holds it around
        #: every execution, probes and rebuilds take it too) — all
        #: residency mutations happen under it
        self.lock = make_lock("shards.ShardGroup.lock")
        self._state_lock = make_lock("shards.ShardGroup._state_lock")
        n = config.members
        n_parts = n * max(1, config.partitions_per_member)
        #: node id -> partition of the BASE rows (tombstone routing in
        #: the sharded commit split)
        self._node_home: Dict[int, int] = {}
        self.partitions = partition_graph(graph, n_parts,
                                          config.partition_property,
                                          home_out=self._node_home)
        self.members: List[ShardMember] = [
            ShardMember(i, self._member_session(),
                        [p for p in range(n_parts) if p % n == i])
            for i in range(n)]
        #: cross-shard path: one session over a mesh of the group's
        #: devices (tables row-shard over the mesh axis, joins ride the
        #: okapi dist-join machinery); falls back to a plain full-graph
        #: clone when the backend has no mesh or devices are short
        self.cross_session, self.cross_meshed = self._cross_shard_session()
        from caps_tpu.serve.devices import replicate_graph
        with self._bracket(None):
            self.cross_graph = replicate_graph(graph, self.cross_session)
        #: the group's OWN versioned lineage over the cross-shard clone:
        #: writes commit here through the session's normal write path
        #: (digest parity with an unsharded versioned graph by
        #: construction) and distribute to the member shards via the
        #: prepare/commit round before publishing.  The lineage never
        #: compacts — a fold would move delta rows into the cross base
        #: without re-partitioning the member shards.
        from caps_tpu.relational.updates import VersionedGraph
        with self._bracket(None):
            self._versioned = VersionedGraph(self.cross_session,
                                             self.cross_graph)
        #: pidx -> that shard's slice of the current delta overlay
        #: (only shards with a non-empty slice appear)
        self._shard_states: Dict[int, Any] = {}
        self.wal = None
        if config.wal_dir is not None:
            self._init_durability()
        self._versioned.pre_publish = self._prepare_commit
        self._facade = _GroupSessionFacade(self)
        #: member + group ladder: the same three-state breaker machine
        #: as the device ladder, group-scoped metric prefix
        self._breaker = CircuitBreaker(
            registry, failure_threshold=config.member_failure_threshold,
            cooldown_s=config.member_cooldown_s,
            metric_prefix="serve.shard_breaker")
        #: group-level consecutive failures (rebuild cycles that failed,
        #: unattributed group-wide device faults) — NOT the member count
        self._group_failures = 0
        self._group_open_t: Optional[float] = None
        self._requests_single = registry.counter("shard.requests.single")
        self._requests_cross = registry.counter("shard.requests.cross")
        self._member_quarantined_c = registry.counter(
            "shard.member.quarantined")
        self._member_reinstated_c = registry.counter(
            "shard.member.reinstated")
        self._rebuilds_c = registry.counter("shard.rebuilds")
        self._rebuild_failures_c = registry.counter(
            "shard.rebuild_failures")
        self._probes_c = registry.counter("shard.probes")
        self._group_quarantined_c = registry.counter(
            "shard.group_quarantined")
        self._shed_c = registry.counter("shard.shed")
        self._requests_write = registry.counter("shard.requests.write")
        self._commits_c = registry.counter("shard.commits")
        self._commit_rollbacks_c = registry.counter(
            "shard.commit_rollbacks")
        self._faults_c = registry.counter("paging.faults")
        self._spills_c = registry.counter("paging.spills")
        self._route_cache: "OrderedDict[str, Optional[Tuple]]" = \
            OrderedDict()
        self._transitions: List[Dict[str, Any]] = [
            {"t": clock.now(), "state": GROUP_HEALTHY}]
        self._state = GROUP_HEALTHY
        self._next_tick_t = 0.0
        self._maint_stop = threading.Event()
        self._maint_thread: Optional[threading.Thread] = None
        self._closed = False
        # replica-compatible counters (server _note_device_outcomes)
        self._stats_lock = make_lock("shards.ShardGroup._stats_lock")
        self.requests = 0
        self.completed = 0
        self.failed = 0
        #: eager ingest up to the page budget: serving pays no surprise
        #: re-ingest for the hot set, cold partitions stay on the host
        with self.lock:
            for m in self.members:
                for pidx in m.partitions:
                    if not self._fits(m, self.partitions[pidx]):
                        break
                    self._fault_in(m, pidx, count_fault=False)
        _register_group_gauges(registry)
        registry._shard_live_groups.append(self)

    # -- replica duck type ---------------------------------------------

    @property
    def session(self):
        return self._facade

    @property
    def device(self):  # placement string for summaries
        return f"shard-group:{self.name}"

    @contextlib.contextmanager
    def activate(self):
        """Group-wide execution bracket (cross-shard dispatch runs on
        every member's device at once): stamps ``executing_shard()``
        with ``(name, None)``.  Member-scoped brackets nest inside."""
        with self._bracket(None):
            yield

    @contextlib.contextmanager
    def _bracket(self, member_index: Optional[int]):
        prev = getattr(_shard_tls, "shard", None)
        _shard_tls.shard = (self.name, member_index)
        try:
            yield
        finally:
            _shard_tls.shard = prev

    def graph_for(self, graph):
        """Identity: routing happens inside the facade, per query."""
        return graph

    def serves(self, graph) -> bool:
        return graph is self.graph

    def note(self, *, requests: int = 0, completed: int = 0,
             failed: int = 0) -> None:
        with self._stats_lock:
            self.requests += requests
            self.completed += completed
            self.failed += failed

    # -- construction helpers ------------------------------------------

    def _member_session(self):
        """A fresh mesh-free clone for one member: the member's
        partition is a single-device graph whatever the template's own
        mesh config is."""
        cfg = getattr(self.template_session, "config", None)
        if cfg is not None and getattr(cfg, "mesh_shape", ()):
            return type(self.template_session)(
                config=dataclasses.replace(cfg, mesh_shape=()))
        return self.template_session.clone()

    def _cross_shard_session(self):
        """The cross-shard session: a clone over ``mesh_shape =
        (members,)`` when the backend supports meshes and the platform
        has the devices; else a plain clone (correct, unsharded)."""
        cfg = getattr(self.template_session, "config", None)
        if self.config.cross_shard_mesh and cfg is not None \
                and hasattr(cfg, "mesh_shape") \
                and hasattr(self.template_session, "backend"):
            try:
                s = type(self.template_session)(
                    config=dataclasses.replace(
                        cfg, mesh_shape=(self.config.members,)))
                if getattr(s.backend, "mesh", None) is not None \
                        or self.config.members == 1:
                    return s, getattr(s.backend, "mesh", None) is not None
            except Exception:  # pragma: no cover — meshless platform
                pass           # fall through to the unmeshed clone
        return self.template_session.clone(), False

    def _init_durability(self) -> None:
        """Open the group WAL and recover the lineage from it: the best
        intact entry (entries are cumulative — the group lineage never
        compacts, so they overlay the spec'd base directly) installs
        into the internal versioned handle at its logged version, and
        the recovered overlay re-splits per shard so the eager ingest
        below wraps every resident partition at the recovered state."""
        from caps_tpu.durability import CommitLog
        from caps_tpu.relational.updates import delta_state_from_payload
        self.wal = CommitLog(
            os.path.join(self.config.wal_dir, f"wal-shard-{self.name}"),
            fsync=self.config.wal_fsync, registry=self._registry,
            event_log=self._event_log)
        rec = self.wal.recover()
        if rec.version > 0:
            state = delta_state_from_payload(rec.state)
            with self._bracket(None):
                self._versioned.install_state(state, rec.version)
            self._shard_states = self._split_state(state)

    # -- paging ---------------------------------------------------------

    def _partition_cost(self, pidx: int) -> int:
        """The pager's ONE byte currency: the partition's host-slice
        estimate — stable, known before the first build, identical on
        both sides of every budget comparison (a never-built partition
        has no measured device size yet; mixing currencies would make
        admission decisions erratic)."""
        return self.partitions[pidx].host_nbytes()

    def _device_pressure(self, member: ShardMember) -> int:
        """The pager's placement input: this member's tracked resident
        bytes, raised to the platform's reported per-device allocator
        bytes when the device can report them (obs/ledger.py — honest
        zero on platforms that cannot)."""
        tracked = member.resident_bytes()
        from caps_tpu.obs.ledger import device_bytes_in_use
        n = max(1, len(self.members))
        return max(tracked, device_bytes_in_use() // n)

    def _fits(self, member: ShardMember, partition: GraphPartition
              ) -> bool:
        budget = self.config.page_budget_bytes
        if budget is None:
            return True
        return self._device_pressure(member) \
            + partition.host_nbytes() <= budget

    def _fault_in(self, member: ShardMember, pidx: int,
                  count_fault: bool = True):
        """Make a partition device-resident (caller holds the group
        lock): spill LRU siblings while over budget, then ingest from
        the host slice.  The incoming partition is always admitted —
        serving a query beats honoring the budget to the byte."""
        got = member.resident.get(pidx)
        if got is not None:
            member.resident.move_to_end(pidx)
            return got[0]
        budget = self.config.page_budget_bytes
        incoming = self._partition_cost(pidx)
        if budget is not None:
            # same pressure reading as the eager-ingest _fits check —
            # ONE currency on both sides of every budget decision
            while member.resident and \
                    self._device_pressure(member) + incoming > budget:
                self._spill(member, next(iter(member.resident)))
        with self._bracket(member.index):
            built = self.partitions[pidx].build(member.session)
            graph = built
            # re-anchor the shard's slice of the current delta overlay
            # on the freshly built base: a spilled-then-faulted
            # partition must come back at the lineage's CURRENT state
            sstate = self._shard_states.get(pidx)
            if sstate is not None:
                graph = self._overlay_graph(
                    member.session, built, sstate,
                    self._versioned.current().snapshot_version)
        member.base_graphs[pidx] = built
        from caps_tpu.obs.ledger import tables_nbytes
        member.measured_nbytes[pidx] = tables_nbytes(
            tuple(built.node_tables) + tuple(built.rel_tables))
        member.resident[pidx] = (graph, incoming)
        if count_fault:
            member.page_faults += 1
            self._faults_c.inc()
        return graph

    def _spill(self, member: ShardMember, pidx: int) -> None:
        """Drop a partition's device residency: the graph (and its
        device buffers) go, the member session's plan-cache entries
        anchored on it are evicted (a later fault-in is a NEW graph
        object — stale entries would only pin memory), and the host
        slice remains the truth."""
        graph, _nb = member.resident.pop(pidx)
        base = member.base_graphs.pop(pidx, None)
        for g in (graph, base if base is not graph else None):
            token = getattr(g, "_plan_token", None) if g is not None \
                else None
            if token is not None:
                try:
                    member.session.plan_cache.evict_graph(token)
                except Exception:  # pragma: no cover — accounting only
                    pass
        member.page_spills += 1
        self._spills_c.inc()

    def cold_host_bytes(self) -> int:
        """Host bytes of partitions currently NOT device-resident."""
        total = 0
        for m in self.members:
            for pidx in m.partitions:
                if pidx not in m.resident:
                    total += self.partitions[pidx].host_nbytes()
        return total

    # -- routing --------------------------------------------------------

    def _route(self, query: str) -> Optional[Tuple[str, Any]]:
        """``("param", name)`` / ``("lit", value)`` when the query is
        provably resident on the shard owning that partition-property
        value; None = cross-shard.  Cached per query text."""
        with self._state_lock:
            if query in self._route_cache:
                self._route_cache.move_to_end(query)
                return self._route_cache[query]
        route = self._compute_route(query)
        with self._state_lock:
            self._route_cache[query] = route
            while len(self._route_cache) > _ROUTE_CACHE_CAP:
                self._route_cache.popitem(last=False)
        return route

    def _compute_route(self, query: str) -> Optional[Tuple[str, Any]]:
        from caps_tpu.frontend import ast
        from caps_tpu.frontend.parser import parse_query, query_mode
        from caps_tpu.ir import exprs as E
        mode, body = query_mode(query)
        if mode is not None:
            return None  # EXPLAIN/PROFILE: run on the cross session
        try:
            from caps_tpu.relational.updates import is_update_query
            if is_update_query(body):
                return None
            stmt = parse_query(body)
        except Exception:
            return None  # let the normal path raise the real error
        if not isinstance(stmt, ast.SingleQuery):
            return None
        matches = [c for c in stmt.clauses
                   if isinstance(c, ast.MatchClause)]
        if len(matches) != 1 or any(
                not isinstance(c, (ast.MatchClause, ast.WithClause,
                                   ast.ReturnClause))
                for c in stmt.clauses):
            return None
        m = matches[0]
        if m.optional or len(m.pattern.parts) != 1:
            return None
        part = m.pattern.parts[0]
        if part.rels or len(part.nodes) != 1 or part.path_var:
            return None
        node = part.nodes[0]
        cand = None
        if isinstance(node.properties, E.MapLit):
            for k, v in zip(node.properties.keys, node.properties.values):
                if k == self.config.partition_property and \
                        isinstance(v, (E.Param, E.Lit)):
                    cand = v
        if cand is None and m.where is not None and node.var is not None:
            conjs = m.where.exprs if isinstance(m.where, E.Ands) \
                else (m.where,)
            for e in conjs:
                if not isinstance(e, E.Equals):
                    continue
                for lhs, rhs in ((e.lhs, e.rhs), (e.rhs, e.lhs)):
                    if isinstance(lhs, E.Property) \
                            and lhs.entity == E.Var(node.var) \
                            and lhs.key == self.config.partition_property \
                            and isinstance(rhs, (E.Param, E.Lit)):
                        cand = rhs
                        break
                if cand is not None:
                    break
        if cand is None:
            return None
        # nothing may escape the matched rows: a variable outside the
        # running binding set (the node var, plus projection aliases
        # WITH derives FROM it), or a sub-query/path construct anywhere
        # in WHERE / WITH / RETURN, could read graph data living on
        # OTHER shards
        escape = (E.ExistsSubQuery, E.Exists, E.PathExpr, E.PathSeg,
                  E.PathNode, E.PathNodes)

        def clean(tree, allowed) -> bool:
            for n_ in tree.walk():
                if isinstance(n_, escape):
                    return False
                if isinstance(n_, E.Var) and n_.name not in allowed:
                    return False
            return True

        allowed = {node.var} if node.var is not None else set()
        for clause in stmt.clauses:
            if isinstance(clause, ast.MatchClause):
                if clause.where is not None and \
                        not clean(clause.where, allowed):
                    return None
                continue
            body = clause.body
            introduced = set()
            for item in body.items:
                if not clean(item.expr, allowed):
                    return None
                if item.alias is not None:
                    introduced.add(item.alias)
                elif isinstance(item.expr, E.Var):
                    introduced.add(item.expr.name)
            visible = allowed | introduced
            for o in body.order_by:
                if not clean(o.expr, visible):
                    return None
            where = getattr(clause, "where", None)
            if where is not None and not clean(where, visible):
                return None
            if isinstance(clause, ast.WithClause):
                allowed = visible if body.star else introduced
        if isinstance(cand, E.Param):
            return ("param", cand.name)
        return ("lit", cand.value)

    def owning_member(self, value: Any) -> Tuple[int, ShardMember]:
        pidx = hash_value(value) % len(self.partitions)
        return pidx, self.members[pidx % len(self.members)]

    # -- execution ------------------------------------------------------

    def execute(self, query: str,
                parameters: Optional[Mapping[str, Any]] = None,
                degraded: Optional[Tuple[bool, bool]] = None):
        """One query through the group (caller holds ``self.lock`` via
        the server's dispatch): route to the owning member's partition
        session or the cross-shard session; failures are attributed to
        the executing member for the health ladder."""
        params = dict(parameters or {})
        from caps_tpu.relational.updates import is_update_query
        from caps_tpu.frontend.parser import query_mode
        mode, body = query_mode(query)
        if is_update_query(body if mode is not None else query):
            return self._execute_update(query, params, degraded)
        route = self._route(query)
        value: Any = None
        routed = False
        if route is not None:
            kind, token = route
            if kind == "lit":
                value, routed = token, True
            elif token in params:
                value, routed = params[token], True
        if routed:
            pidx, member = self.owning_member(value)
            return self._execute_member(member, pidx, query, params,
                                        degraded)
        return self._execute_cross(query, params, degraded)

    def _execute_member(self, member: ShardMember, pidx: int, query,
                        params, degraded):
        state = self.member_state(member.index)
        if state != MEMBER_HEALTHY:
            # fast transient failure: the server's retry ladder backs
            # off while the background rebuild brings the member back
            raise ShardMemberDown(
                f"shard member {member.index} of group {self.name!r} is "
                f"{state}; rebuild in progress", member=member.index)
        member.requests += 1
        self._requests_single.inc()
        try:
            with self._bracket(member.index):
                graph = self._fault_in(member, pidx)
                out = self._run(member.session, graph, query, params,
                                degraded)
        except BaseException as ex:
            member.failed += 1
            _attribute_member(ex, member.index)
            raise
        # consecutive-failure semantics for the MEMBER ladder too: a
        # served request ends the member's streak (the device ladder
        # does the same per request).  Guarded on CLOSED so a trip that
        # raced in from another request's bookkeeping is never undone
        # by a success that started before it.
        key = ("member", member.index)
        if self._breaker.state(key) == CLOSED:
            self._breaker.record_success(key)
        return out

    def _execute_cross(self, query, params, degraded):
        self._requests_cross.inc()
        with self._bracket(None):
            # the lineage's current snapshot, not the static clone:
            # cross-shard reads see every committed write (a snapshot
            # is a stable plan-cache anchor exactly like the clone was)
            return self._run(self.cross_session,
                             self._versioned.current(),
                             query, params, degraded)

    @staticmethod
    def _run(session, graph, query, params, degraded):
        if degraded is not None:
            no_plan_cache, no_fused = degraded
            return session.cypher_degraded(graph, query, params,
                                           no_plan_cache=no_plan_cache,
                                           no_fused=no_fused)
        return session.cypher_on_graph(graph, query, params)

    # -- sharded commits (the durable-writes protocol) ------------------

    def _execute_update(self, query, params, degraded):
        """A Cypher write through the group: the session's NORMAL write
        path runs against the internal versioned lineage (same staging,
        same failure atomicity, digest parity with an unsharded
        versioned session by construction); publication runs the
        prepare/commit round via the lineage's ``pre_publish`` hook."""
        self._requests_write.inc()
        with self._bracket(None):
            return self._run(self.cross_session, self._versioned,
                             query, params, degraded)

    @staticmethod
    def _overlay_graph(session, base, state, version):
        """One shard's overlay: the member-local base partition plus
        this shard's slice of the lineage's delta, as an ordinary
        immutable snapshot (plan-cacheable per commit version)."""
        from caps_tpu.relational.updates import (GraphSnapshot,
                                                 build_delta_graph)
        delta = build_delta_graph(session, state)
        return GraphSnapshot(session, base, delta, state, version,
                             handle=None)

    def _split_state(self, state) -> Dict[int, Any]:
        """Split one cumulative delta overlay into per-shard overlays,
        mirroring :func:`partition_graph`'s placement exactly: delta
        node records hash by their partition-property value (id-token
        without one), delta relationships follow their source node's
        CURRENT home, and tombstones go where the base row they mask
        lives — a SET that moves the partition property emits the
        record on the new home and the tombstone on the old, so a
        routed query for either value answers correctly.  Shards whose
        slice is empty are omitted."""
        from caps_tpu.relational.updates import DeltaState
        n = len(self.partitions)
        prop = self.config.partition_property
        delta_home: Dict[int, int] = {}
        for rec in state.nodes:
            v = rec.props_dict().get(prop)
            delta_home[rec.id] = (hash_value(v) if v is not None
                                  else hash_value(f"#id:{rec.id}")) % n

        def base_home(nid: int) -> int:
            got = self._node_home.get(nid)
            return got if got is not None \
                else hash_value(f"#id:{nid}") % n

        def node_home(nid: int) -> int:
            got = delta_home.get(nid)
            return got if got is not None else base_home(nid)

        hn: Dict[int, set] = {}
        hr: Dict[int, set] = {}
        nodes: Dict[int, List[Any]] = {}
        rels: Dict[int, List[Any]] = {}
        for rec in state.nodes:
            nodes.setdefault(delta_home[rec.id], []).append(rec)
        for rec in state.rels:
            rels.setdefault(node_home(rec.src), []).append(rec)
        for nid in state.hidden_nodes:
            hn.setdefault(base_home(nid), set()).add(nid)
        base_rels = self.graph.rel_lookup()
        for rid in state.hidden_rels:
            got = base_rels.get(rid)
            p = base_home(got[0]) if got is not None \
                else hash_value(f"#id:{rid}") % n
            hr.setdefault(p, set()).add(rid)
        out: Dict[int, Any] = {}
        for p in set(hn) | set(hr) | set(nodes) | set(rels):
            out[p] = DeltaState(
                hidden_nodes=frozenset(hn.get(p, ())),
                hidden_rels=frozenset(hr.get(p, ())),
                nodes=tuple(nodes.get(p, ())),
                rels=tuple(rels.get(p, ())))
        return out

    def _prepare_commit(self, new_snap) -> None:
        """The prepare/commit round (``VersionedGraph.pre_publish`` —
        the commit lock and the group's dispatch lock are both held).

        **Prepare**: split the new cumulative overlay per shard and
        build each changed resident partition's new overlay graph under
        that member's string-pool mark.  Any failure — a device fault
        on one member, an injected abort, a failed WAL append — rolls
        EVERY member's pool back and aborts the commit; no shard is
        ever partially applied (the outer publish rolls the cross
        session back the same way).

        **Commit point**: the group WAL append (durable groups).  An
        acknowledged write is on disk before any reader can see it.

        **Commit**: swap the prepared overlays in, member by member —
        pure reference swaps that cannot fail — and evict each replaced
        graph's plan-cache entries (a superseded shard overlay can
        never be read again)."""
        shard_states = self._split_state(new_snap.state)
        staged: List[Tuple[Any, Any]] = []
        prepared: List[Tuple[ShardMember, int, Any]] = []
        try:
            for member in self.members:
                pool = getattr(getattr(member.session, "backend", None),
                               "pool", None)
                staged.append((pool,
                               pool.mark() if pool is not None else None))
                for pidx in member.resident:
                    new_state = shard_states.get(pidx)
                    if new_state == self._shard_states.get(pidx):
                        continue
                    base = member.base_graphs.get(pidx)
                    if base is None:  # pragma: no cover — resident ⊆ built
                        continue
                    if new_state is None:
                        # the shard's slice emptied out: back to the base
                        prepared.append((member, pidx, base))
                        continue
                    with self._bracket(member.index):
                        prepared.append((member, pidx, self._overlay_graph(
                            member.session, base, new_state,
                            new_snap.snapshot_version)))
            if self.wal is not None:
                from caps_tpu.relational.updates import \
                    delta_state_to_payload
                self.wal.append(new_snap.snapshot_version,
                                delta_state_to_payload(new_snap.state))
        except BaseException:
            for pool, mark in staged:
                if pool is not None:
                    pool.rollback(mark)
            self._commit_rollbacks_c.inc()
            raise
        for member, pidx, graph in prepared:
            old, cost = member.resident[pidx]
            if old is not graph:
                token = getattr(old, "_plan_token", None)
                if token is not None:
                    try:
                        member.session.plan_cache.evict_graph(token)
                    except Exception:  # pragma: no cover — accounting
                        pass
            member.resident[pidx] = (graph, cost)
        self._shard_states = shard_states
        self._commits_c.inc()

    def quarantine_family(self, query: str,
                          params: Mapping[str, Any]) -> None:
        """Poisoned-plan quarantine, group-routed: evict the cached
        plan entry on the session that actually served this family
        (the owning member or the cross session)."""
        from caps_tpu.serve.failure import quarantine_plan_state
        route = self._route(query)
        params = dict(params or {})
        session, graph = self.cross_session, self._versioned.current()
        if route is not None:
            kind, token = route
            value = token if kind == "lit" else params.get(token)
            if kind == "lit" or token in params:
                pidx, member = self.owning_member(value)
                got = member.resident.get(pidx)
                if got is None:
                    return  # nothing resident: nothing cached to poison
                session, graph = member.session, got[0]
        # the shared eviction sequence (serve/failure.py), under the
        # group's one dispatch stream lock
        quarantine_plan_state(session, graph, query, params,
                              exec_lock=self.lock)
        # member sessions carry their own result caches when serving is
        # cache-enabled: a poisoned family's materialized rows (and the
        # shared memoized intermediates) go with the plan
        rcache = getattr(session, "result_cache", None)
        if rcache is not None:
            from caps_tpu.frontend.parser import normalize_query
            rcache.evict_family(normalize_query(query))

    # -- ladder bookkeeping (the server's outcome feed) ----------------

    def record_success(self) -> None:
        self.note(completed=1)
        # consecutive-failure semantics, like every other breaker in
        # the tier: a served group request ends the group-level streak
        # (an OPEN group never serves, so this can never mask a real
        # quarantine — only prevent a slow trickle of transient
        # cross-shard wobbles from ever summing to one)
        with self._state_lock:
            if self._group_open_t is None:
                self._group_failures = 0

    def record_failure(self, exc: BaseException) -> Optional[str]:
        """Fold one group execution failure in.  Returns ``"member"`` /
        ``"group"`` when THIS failure tripped that ladder level (the
        server flight-dumps and events it), else None.  Only
        device-attributed failures climb — a user's bad query never
        degrades a group."""
        self.note(failed=1)
        if not device_fault(exc):
            return None
        member_idx = member_of(exc)
        tripped: Optional[str] = None
        if member_idx is not None and 0 <= member_idx < len(self.members):
            if self._breaker.record_failure(("member", member_idx), exc):
                self.members[member_idx].quarantines += 1
                self._member_quarantined_c.inc()
                tripped = "member"
        else:
            # group-wide (cross-shard) device fault with no member
            # attribution: counts against the GROUP ladder directly
            if self._note_group_failure(exc):
                tripped = "group"
        if self._all_members_down() and self._group_open_t is None:
            with self._state_lock:
                self._group_open_t = clock.now()
            self._group_quarantined_c.inc()
            tripped = "group"
        self._recompute_state()
        return tripped

    def _note_group_failure(self, exc: Optional[BaseException]) -> bool:
        with self._state_lock:
            self._group_failures += 1
            if self._group_failures >= \
                    self.config.group_failure_threshold \
                    and self._group_open_t is None:
                self._group_open_t = clock.now()
                quarantined = True
            else:
                quarantined = False
        if quarantined:
            self._group_quarantined_c.inc()
        return quarantined

    def _note_group_success(self) -> None:
        with self._state_lock:
            self._group_failures = 0
            self._group_open_t = None

    def _all_members_down(self) -> bool:
        return all(self.member_state(m.index) != MEMBER_HEALTHY
                   for m in self.members)

    def member_state(self, index: int) -> str:
        return _BREAKER_TO_MEMBER[self._breaker.state(("member", index))]

    def member_health(self) -> Dict[int, str]:
        return {m.index: self.member_state(m.index) for m in self.members}

    def health(self) -> str:
        """``healthy`` (every member serving) / ``degraded`` (>= 1
        member down or probing — the rest keep serving their shards) /
        ``quarantined`` (group-level trip or every member down: the
        server sheds group traffic with an honest retry hint)."""
        if self._group_open_t is not None or self._all_members_down():
            return GROUP_QUARANTINED
        if any(self.member_state(m.index) != MEMBER_HEALTHY
               for m in self.members):
            return GROUP_DEGRADED
        return GROUP_HEALTHY

    def shed_retry_after(self) -> Optional[float]:
        """Non-None when group-routed traffic should shed at admission:
        the remaining member cooldown — the earliest time the
        background rebuild could have changed anything."""
        if self.health() != GROUP_QUARANTINED:
            return None
        self._shed_c.inc()
        with self._state_lock:
            opened = self._group_open_t
        if opened is None:
            return self.config.member_cooldown_s
        remaining = self.config.member_cooldown_s - (clock.now() - opened)
        return max(0.001, remaining)

    def _recompute_state(self) -> None:
        state = self.health()
        changed = False
        with self._state_lock:
            if state != self._state:
                self._state = state
                self._transitions.append({"t": clock.now(),
                                          "state": state})
                del self._transitions[:-_MAX_TRANSITIONS]
                changed = True
        if changed:
            tracer = self.template_session.tracer
            if tracer.enabled:
                tracer.event("shard.group_state", group=self.name,
                             state=state)
            if self._event_log is not None:
                self._event_log.emit(
                    "shard.group_state", request_id=None, family=None,
                    group=self.name, state=state)
            if self._on_change is not None:
                try:
                    self._on_change()
                except Exception:  # pragma: no cover — bookkeeping only
                    pass

    # -- background probe / rebuild ------------------------------------

    def probe_gate(self) -> Tuple[str, float]:
        """Rate limit for the maintenance driver (the server's
        quarantined-worker idle loop calls through here): ``(TRIAL, 0)``
        at most once per nap interval — :meth:`maintenance_tick` itself
        respects each member's breaker cooldown."""
        nap = min(self.config.member_cooldown_s, 0.05)
        now = clock.now()
        with self._state_lock:
            if now < self._next_tick_t:
                return REJECT, self._next_tick_t - now
            self._next_tick_t = now + nap
        return TRIAL, 0.0

    def maintenance_tick(self) -> bool:
        """One background maintenance pass: for every quarantined member
        whose cooldown elapsed, rebuild it onto a spare session from the
        host partition slices (the snapshot base) and canary-probe it.
        Success reinstates the member (and feeds the group ladder a
        success); failure buys another cooldown and counts toward group
        quarantine.  Returns True when any member was reinstated."""
        reinstated = False
        for member in self.members:
            key = ("member", member.index)
            if self._breaker.state(key) == CLOSED:
                continue
            verdict, _retry = self._breaker.admit(key)
            if verdict != TRIAL:
                continue
            member.probes += 1
            self._probes_c.inc()
            ok = self._rebuild_member(member)
            if ok:
                self._breaker.record_success(key)
                member.reinstates += 1
                self._member_reinstated_c.inc()
                self._note_group_success()
                reinstated = True
                if self._event_log is not None:
                    self._event_log.emit(
                        "shard.member_reinstated", request_id=None,
                        family=None, group=self.name,
                        member=member.index,
                        incarnation=member.incarnation)
            else:
                self._breaker.record_failure(key)
                self._rebuild_failures_c.inc()
                self._note_group_failure(None)
        if reinstated and not self._all_members_down():
            # a serving member back up un-quarantines the group (its
            # failure streak is over by construction)
            self._note_group_success()
        # group-level recovery: a group quarantined by UNATTRIBUTED
        # cross-shard faults has no tripped member for the loop above
        # to rebuild — and its shed traffic can never record a success.
        # Probe the cross-shard session itself on the same cooldown
        # cadence; a passing canary clears the group trip, a failing
        # one buys another cooldown.
        with self._state_lock:
            opened = self._group_open_t
        if opened is not None and all(
                self._breaker.state(("member", m.index)) == CLOSED
                for m in self.members):
            if clock.now() - opened >= self.config.member_cooldown_s:
                self._probes_c.inc()
                if self._cross_canary():
                    self._note_group_success()
                    reinstated = True
                else:
                    with self._state_lock:
                        self._group_open_t = clock.now()
        self._recompute_state()
        return reinstated

    def _cross_canary(self) -> bool:
        """A plain scan through the cross-shard session's own operator
        stream (group-wide bracket: faults spanning any member fail
        it)."""
        try:
            with self.lock, self._bracket(None), cancel_scope(None):
                self._versioned.current().cypher(_CANARY_QUERY)
            return True
        except BaseException:
            return False

    def _rebuild_member(self, member: ShardMember) -> bool:
        """Rebuild one member onto a spare/recovered device: a FRESH
        session clone re-ingests the member's partitions from their
        host slices (budget-bounded — cold ones stay on the host), then
        the canary scan must pass ON that member's stream.  The swap is
        atomic under the group lock; a failed rebuild leaves the old
        state untouched."""
        try:
            fresh = self._member_session()
            resident: "OrderedDict[int, Tuple[Any, int]]" = OrderedDict()
            bases: Dict[int, Any] = {}
            measured: Dict[int, int] = {}
            with self.lock, self._bracket(member.index):
                from caps_tpu.obs.ledger import tables_nbytes
                budget = self.config.page_budget_bytes
                used = 0
                for pidx in member.partitions:
                    cost = self._partition_cost(pidx)
                    if resident and budget is not None \
                            and used + cost > budget:
                        continue
                    built = self.partitions[pidx].build(fresh)
                    measured[pidx] = tables_nbytes(
                        tuple(built.node_tables)
                        + tuple(built.rel_tables))
                    graph = built
                    # committed writes survive the rebuild: the shard's
                    # current overlay re-anchors on the fresh base
                    sstate = self._shard_states.get(pidx)
                    if sstate is not None:
                        graph = self._overlay_graph(
                            fresh, built, sstate,
                            self._versioned.current().snapshot_version)
                    bases[pidx] = built
                    resident[pidx] = (graph, cost)
                    used += cost
                # the canary runs the rebuilt member's own operator
                # stream: a fault scoped to this member fails it here
                probe_graph = next(iter(resident.values()))[0]
                with cancel_scope(None):
                    probe_graph.cypher(_CANARY_QUERY)
                member.session = fresh
                member.resident = resident
                member.base_graphs = bases
                member.measured_nbytes = measured
                member.incarnation += 1
                member.rebuilds += 1
            self._rebuilds_c.inc()
            return True
        except BaseException:
            return False

    # -- maintenance thread (serving-mode background driver) -----------

    def start_maintenance(self) -> None:
        """Background maintenance loop for a RUNNING server: probes and
        rebuilds happen off the serving path (a degraded group keeps
        serving healthy shards while the victim rebuilds).  Tests drive
        :meth:`maintenance_tick` directly on the fake clock instead."""
        if self._maint_thread is not None:
            return
        self._maint_stop.clear()
        t = threading.Thread(target=self._maintenance_loop,
                             name=f"caps-tpu-shard-{self.name}",
                             daemon=True)
        self._maint_thread = t
        t.start()

    def _maintenance_loop(self) -> None:
        nap = min(self.config.member_cooldown_s, 0.05)
        while not self._maint_stop.is_set():
            try:
                if self.health() != GROUP_HEALTHY:
                    self.maintenance_tick()
            except Exception:  # pragma: no cover — must keep driving
                pass
            clock.wait(self._maint_stop, nap)

    def close(self) -> None:
        """Server shutdown: stop the maintenance loop and leave the
        registry's live-group gauge set (a dead server's groups must
        not keep reporting bytes)."""
        if self._closed:
            return
        self._closed = True
        self._maint_stop.set()
        t = self._maint_thread
        if t is not None and t.is_alive():
            t.join(timeout=5.0)
        if self.wal is not None:
            try:
                self.wal.close()
            except Exception:  # pragma: no cover — shutdown best-effort
                pass
        with _gauge_guard:
            live = getattr(self._registry, "_shard_live_groups", [])
            if self in live:
                live.remove(self)

    # -- reporting ------------------------------------------------------

    def warmup_bindings(self) -> List[Dict[str, Any]]:
        """Compile-charging bindings recorded ANYWHERE in the group
        (member + cross sessions) — the plan-store collection seam: a
        family served only by this group must still round-trip into the
        persistent store so a cold process can warm it
        (serve/warmup.py ``ServerWarmup.save``)."""
        out: List[Dict[str, Any]] = []
        seen: set = set()
        for s in [m.session for m in self.members] + [self.cross_session]:
            fn = getattr(s, "warmup_bindings", None)
            if fn is None:
                continue
            for b in fn():
                if b["family"] not in seen:
                    seen.add(b["family"])
                    out.append(b)
        return out

    def compiled_families(self) -> set:
        """Plan families that compiled ANYWHERE in this group (member
        sessions + the cross-shard session) — ``warmup_report()``'s
        coverage input: a family warmed only on the group must count as
        compiled."""
        out: set = set()
        for s in [m.session for m in self.members] + [self.cross_session]:
            ledger = getattr(s, "compile_ledger", None)
            if ledger is not None:
                out.update(ledger.families())
        return out

    def summary(self) -> Dict[str, Any]:
        with self._state_lock:
            transitions = [dict(t) for t in self._transitions]
            group_failures = self._group_failures
        return {
            "name": self.name,
            "index": self.index,
            "state": self.health(),
            "version": self._versioned.current().snapshot_version,
            "durable": self.wal is not None,
            "partitions": len(self.partitions),
            "partition_property": self.config.partition_property,
            "cross_shard_meshed": self.cross_meshed,
            "members": [dict(m.snapshot(),
                             health=self.member_state(m.index))
                        for m in self.members],
            "group_failures": group_failures,
            "transitions": transitions,
            "paging": {
                "budget_bytes": self.config.page_budget_bytes,
                "resident_bytes": sum(m.resident_bytes()
                                      for m in self.members),
                "resident_device_bytes": sum(m.resident_device_bytes()
                                             for m in self.members),
                "host_bytes": self.cold_host_bytes(),
                "faults": sum(m.page_faults for m in self.members),
                "spills": sum(m.page_spills for m in self.members),
            },
            "requests": {"total": self.requests,
                         "completed": self.completed,
                         "failed": self.failed},
        }

"""AOT server warmup: precompile the hot path before traffic arrives.

PR 9 built the measurement (``server.warmup_report()`` — which hot plan
families never compiled on this process); this module spends it.  At
server start a :class:`ServerWarmup` drives each target family through
the NORMAL compile boundaries — ``session.cypher_on_graph`` on every
live device replica, under the replica's execution lock — so the
compile ledger itself proves coverage: after a successful warmup,
``warmup_report()["cold_families"]`` is empty and the first client
query of a warmed family is a plan-cache hit (compile charge 0.0).

Targets come from, in priority order:

* ``WarmupConfig.families`` — an explicit ``(query, params)`` list (a
  deploy pipeline's curated hot set);
* a persistent plan store (``WarmupConfig.store_path`` →
  ``relational/plan_store.py``): per family the original query text and
  a shape-faithful recorded binding, plus the fused executor's
  param-generic size streams (seeded BEFORE execution, so the warmup
  run itself replays sync-free where the store matches) and the
  shape-bucket lattice boundaries.

Progress and outcome surface in ``server.stats()["warmup"]`` and
``health_report()["warmup"]`` (state machine ``idle → running →
done | failed``), in ``warmup.*`` counters, and as structured
``warmup.start`` / ``warmup.family_failed`` / ``warmup.done`` events.
A family that fails to warm is recorded and SKIPPED — warmup is an
optimization pass; it must never keep a server from serving.
"""
from __future__ import annotations

import dataclasses
import threading
from typing import Any, Dict, List, Optional, Tuple

from caps_tpu.obs import clock
from caps_tpu.obs.lockgraph import make_lock


@dataclasses.dataclass(frozen=True)
class WarmupConfig:
    #: persistent plan store path (relational/plan_store.py); None = no
    #: store — warmup then only covers ``families``
    store_path: Optional[str] = None
    #: explicit hot set: items are ``(query, params)`` pairs or bare
    #: query strings (params {})
    families: Optional[Tuple] = None
    #: run warmup on a background thread (server start returns
    #: immediately; progress is visible in ``stats()["warmup"]``) or
    #: inline (start blocks until the hot set is compiled)
    background: bool = True
    #: persist the session's warm state back to ``store_path`` when the
    #: server fully shuts down — the cross-process round trip
    save_on_shutdown: bool = True
    #: wall-clock budget; families left over when it expires are
    #: reported as skipped (the report's ``truncated`` flag)
    max_seconds: Optional[float] = None
    #: fold observed op_stats sizes (and the store's recorded lattice)
    #: into the session's shape-bucket lattice before executing
    seed_shape_buckets: bool = True


class ServerWarmup:
    """One server's warmup driver + progress report."""

    def __init__(self, server, config: WarmupConfig):
        self.server = server
        self.config = config
        registry = server.session.metrics_registry
        self._completed_c = registry.counter("warmup.completed")
        self._failed_c = registry.counter("warmup.failed")
        self._seconds_c = registry.counter("warmup.seconds")
        self._streams_c = registry.counter("warmup.streams_seeded")
        self._lock = make_lock("warmup.ServerWarmup._lock")
        self._state = "idle"
        self._report: Dict[str, Any] = {}
        self._done = threading.Event()
        #: cooperative cancel: checked between family executions, set by
        #: :meth:`finalize` so an early shutdown bounds the run
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._finalized = False
        self.store = None
        if config.store_path is not None:
            from caps_tpu.relational.plan_store import PlanStore
            self.store = PlanStore(config.store_path, registry=registry,
                                   event_log=server.event_log)

    # -- lifecycle -----------------------------------------------------

    def start(self) -> None:
        """Kick off warmup (idempotent): inline when
        ``config.background`` is False, else on a daemon thread."""
        with self._lock:
            if self._state != "idle":
                return
            self._state = "running"
        if self.config.background:
            t = threading.Thread(target=self._run_guarded,
                                 name="caps-tpu-warmup", daemon=True)
            self._thread = t
            t.start()
        else:
            self._run_guarded()

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until warmup finished (True) or ``timeout`` elapsed."""
        return self._done.wait(timeout)

    def finalize(self) -> None:
        """Shutdown hook: cancel + join a background run and persist
        the warm state when configured.  A run that outlives the join
        timeout is NOT saved over — a mid-run snapshot would persist
        half-warm state.  Idempotent; never raises."""
        with self._lock:
            if self._finalized:
                return
            self._finalized = True
        self._stop.set()  # the run breaks at the next family boundary
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=5.0)
        if t is not None and t.is_alive():  # pragma: no cover — wedged
            return                          # device call: don't race it
        if self.store is not None and self.config.save_on_shutdown:
            self.save()

    def save(self) -> bool:
        """Persist the session's CURRENT warm state to the store
        (bindings, fused streams, lattice).  Failure degrades with a
        ``planstore.rejected`` event — never raises."""
        if self.store is None:
            return False
        from caps_tpu.relational.plan_store import collect_warm_state
        try:
            payload = collect_warm_state(
                self.server.session, graph=self.server._default_graph)
            # shard groups record their warm bindings on THEIR member /
            # cross sessions, not the template — merge them in so a
            # family served only by a group still round-trips into the
            # store (the cold-process sharded warmup's targets)
            known = {f["family"] for f in payload["families"]}
            for group in getattr(self.server, "shard_groups", ()):
                for b in group.warmup_bindings():
                    if b["family"] in known:
                        continue
                    known.add(b["family"])
                    payload["families"].append({
                        "family": b["family"], "query": b["query"],
                        "params": b["params"],
                        "bindings": b.get("bindings") or [b["params"]],
                        "stream": None, "rows_max": 0})
        except Exception as ex:  # collection must not break shutdown
            self.store._reject(
                f"collect failed: {type(ex).__name__}: {ex}")
            return False
        return self.store.save(payload)

    # -- the run -------------------------------------------------------

    def _run_guarded(self) -> None:
        try:
            self._run()
        except Exception as ex:  # warmup must never take the server down
            with self._lock:
                self._state = "failed"
                self._report["error"] = f"{type(ex).__name__}: {ex}"
            if not self._stop.is_set():
                self.server.event_log.emit(
                    "warmup.done", request_id=None, family=None,
                    outcome="failed",
                    error=f"{type(ex).__name__}: {ex}"[:200])
        finally:
            self._done.set()

    def _targets(self, payload) -> List[Tuple[str, Dict[str, Any]]]:
        if self.config.families is not None:
            out = []
            for item in self.config.families:
                if isinstance(item, str):
                    out.append((item, {}))
                else:
                    query, params = item
                    out.append((query, dict(params or {})))
            return out
        if payload is not None:
            out = []
            for f in payload["families"]:
                bindings = f.get("bindings") or [f["params"]]
                for b in bindings:
                    out.append((f["query"], dict(b)))
            return out
        return []

    def _seed(self, payload) -> int:
        """Pre-execution seeding: statistics prior + lattice boundaries
        + fused streams."""
        session = self.server.session
        if payload is not None and payload.get("stats"):
            # the load half of collect_warm_state's ``stats`` field:
            # price this process's first plans (the warmup runs
            # themselves) from the previous process's observed sketch
            # instead of paying the host recompute on the serving path
            graph = self.server._default_graph
            if getattr(graph, "graph_is_versioned", False):
                graph = graph.current()
            if hasattr(graph, "seed_statistics"):
                graph.seed_statistics(payload["stats"])
        if self.config.seed_shape_buckets:
            if payload is not None:
                session.shape_lattice.seed(
                    [b for b in payload.get("lattice", [])
                     if isinstance(b, int)])
                session.shape_lattice.seed(
                    [f.get("rows_max", 0) for f in payload["families"]
                     if isinstance(f.get("rows_max"), int)])
            session.seed_shape_buckets()
        streams = 0
        fused = getattr(session, "fused", None)
        if payload is not None and fused is not None:
            from caps_tpu.relational.plan_store import deserialize_stream
            graph = self.server._default_graph
            if getattr(graph, "graph_is_versioned", False):
                graph = graph.current()
            lat = session.shape_lattice
            for fam in payload["families"]:
                raw = fam.get("stream")
                if not isinstance(raw, dict):
                    continue
                entries = deserialize_stream(raw.get("entries"))
                pool_len = raw.get("pool_len")
                if entries is None or not isinstance(pool_len, int):
                    continue
                # Pad-and-pack headroom: widen recorded row counts and
                # capacity-relation sizes to their bucket boundary, so
                # any binding whose sizes land in the SAME buckets
                # replays without a violation re-record.  Sound by the
                # relation contract (backends/tpu/table.py): "rows" and
                # "cap" values serve correctly at any value >= actual,
                # and consumers re-bucket capacities — the compiled
                # shape is identical, the exactness comes from the
                # per-table live-row masks generic replay already
                # carries.
                entries = [
                    ("rows", lat.bucket(e[1])) if e[0] == "rows"
                    else (("size", lat.bucket(e[1]), "cap")
                          if e[0] == "size" and e[2] == "cap" else e)
                    for e in entries]
                if fused.seed_generic(graph, fam["query"], pool_len,
                                      entries):
                    streams += 1
        if streams:
            self._streams_c.inc(streams)
        return streams

    def _run(self) -> None:
        server = self.server
        t0 = clock.now()
        payload = self.store.load() if self.store is not None else None
        streams = self._seed(payload)
        targets = self._targets(payload)
        server.event_log.emit(
            "warmup.start", request_id=None, family=None,
            families=len(targets), streams_seeded=streams,
            store_loaded=payload is not None)
        completed_q, failures, truncated = set(), [], False
        failed_queries = set()
        graph = server._default_graph
        if getattr(graph, "graph_is_versioned", False):
            # warmup is read-only: resolve the mutable handle to the
            # latest committed snapshot once, exactly like the serving
            # read path — replicas cannot (and must not) replicate the
            # writable handle itself
            graph = graph.current()
        group = server.devices.group_for(graph)
        if group is not None:
            # shard-group-served default graph: every target executes
            # THROUGH the group's routing seam, so the compile charges
            # land on the member (or cross-shard) session that will
            # actually serve that family's traffic — per-member compile
            # boundaries, per-member plan caches.  warmup_report()
            # unions the group sessions' ledgers, so a family that only
            # compiled on the group counts as covered.
            replicas = [group]
        elif server.config.devices is not None:
            replicas = list(server.devices.replicas)
        else:
            replicas = [server.devices.replicas[0]]

        def pool_sizes():
            out = {}
            for r in replicas:
                backend = getattr(r.session, "backend", None)
                if backend is not None:
                    out[id(r)] = len(backend.pool)
            return out

        def streams_stale() -> bool:
            # Only a STALE stream (exists, but the pool moved) warrants
            # another pass: re-executing pre-pays its record run.  An
            # absent stream (use_fused off, unfuseable params, never
            # recorded) would stay absent however many passes ran —
            # treating it as stale would burn every pass and report a
            # false non-convergence.
            for r in replicas:
                fused = getattr(r.session, "fused", None)
                if fused is None:
                    continue
                try:
                    rg = r.graph_for(graph)
                except Exception:  # pragma: no cover — replica without
                    continue       # this graph yet: nothing to converge
                for query, _params in targets:
                    if query not in failed_queries and \
                            fused.generic_state(rg, query) == "stale":
                        return True
            return False

        # Bounded convergence loop.  One pass executes every target
        # family on every replica through the normal compile path.  A
        # family's execution can GROW the string pool, which silently
        # invalidates pool-keyed warm state built earlier in the same
        # pass — other families' param-generic fused streams AND the
        # count-pushdown closures keyed (graph, params, pool, plan).
        # Whenever a pass grew any pool, or left a target's generic
        # stream pool-stale, run one more pass (the re-compiles land
        # HERE, inside warmup, instead of on first traffic).  Three
        # passes bound the worst case; an unconverged exit is reported,
        # never silent.
        converged, passes = False, 0
        for _pass in range(3):
            if truncated or not targets:
                converged = not targets
                break
            passes += 1
            before = pool_sizes()
            for query, params in targets:
                if query in failed_queries:
                    continue
                if self._stop.is_set() or (
                        self.config.max_seconds is not None
                        and clock.now() - t0 > self.config.max_seconds):
                    truncated = True
                    break
                ok = True
                for replica in replicas:
                    try:
                        with replica.lock, replica.activate():
                            replica.session.cypher_on_graph(
                                replica.graph_for(graph), query, params)
                    except Exception as ex:
                        ok = False
                        failed_queries.add(query)
                        failures.append({"query": query[:120],
                                         "device": replica.index,
                                         "pass": passes,
                                         "error": f"{type(ex).__name__}: "
                                                  f"{str(ex)[:160]}"})
                        server.event_log.emit(
                            "warmup.family_failed", request_id=None,
                            family=query[:120], device=replica.index,
                            error=f"{type(ex).__name__}: "
                                  f"{str(ex)[:160]}")
                        break
                if ok:
                    completed_q.add(query)
            if truncated:
                break
            if pool_sizes() == before and not streams_stale():
                converged = True
                break
        # a family is completed only when EVERY one of its bindings
        # warmed — a half-warmed rotation must not read as coverage
        completed = len(completed_q - failed_queries)
        seconds = clock.now() - t0
        self._completed_c.inc(completed)
        self._failed_c.inc(len(failures))
        self._seconds_c.inc(seconds)
        report = {
            "families_total": len({q for q, _p in targets}),
            "bindings_total": len(targets),
            "completed": completed,
            "failures": failures,
            "seconds": round(seconds, 6),
            "truncated": truncated,
            "streams_seeded": streams,
            "converged": converged,
            "passes": passes,
            "store": None if self.store is None else {
                "path": self.store.path,
                "loaded": payload is not None,
                "rejected": self.store.last_rejection,
            },
        }
        with self._lock:
            self._state = "done"
            self._report = report
        if not self._stop.is_set():
            # a cancelled run skips the emit: the server may already
            # have closed the event-log file sink, and a late write
            # would lazily reopen it
            server.event_log.emit(
                "warmup.done", request_id=None, family=None,
                outcome="done", families=len(targets),
                completed=completed, failures=len(failures),
                seconds=round(seconds, 6), truncated=truncated)

    # -- reads ---------------------------------------------------------

    def report(self) -> Dict[str, Any]:
        """The ``stats()["warmup"]`` / ``health_report()["warmup"]``
        section: state machine position plus the finished run's
        outcome."""
        with self._lock:
            out = {"state": self._state}
            out.update(self._report)
            return out

"""Fleet wire protocol: length-prefixed JSON frames over local sockets.

The thinnest transport that can carry the serving tier's typed surface
between processes: one frame is a 4-byte big-endian length header
followed by a UTF-8 JSON body.  Requests are ``{"op": ..., **fields}``;
replies are ``{"ok": true, "result": ...}`` or ``{"ok": false,
"error": <ServeError.to_payload()>}`` — the error payload reconstructs
the EXACT typed exception on the caller's side
(``serve/errors.py error_from_payload``), so ``Overloaded.retry_after_s``,
``QueryFailed.attempts``, and deadline phase attribution survive the
process boundary with full fidelity.

Transport failures (peer died, connection dropped, malformed or
oversized frame) raise :class:`~caps_tpu.serve.errors.WireError` —
marked transient, so the router retries the request on the next ring
node.  ``faults.slow_network`` / ``faults.drop_connection``
(testing/faults.py) patch :func:`send_frame` under the shared fault
lock, which makes router failover tests deterministic.

Durable fleets fence writes AT this layer: write frames carry the
router's last-known lease ``epoch`` field, the owning backend checks it
against the live lease before staging anything
(serve/fleet.py ``_fence_write``), and a mismatch reconstructs as the
typed :class:`~caps_tpu.serve.errors.StaleEpoch` on the caller's side —
``epoch`` / ``lease_epoch`` / ``owner`` payload fields intact — so a
zombie owner's frames die on the wire instead of splitting the log.

Frame traffic counts under ``wire.*`` in the process-global registry
(frames/bytes in both directions, drops), so a fleet soak can assert
how much actually crossed the wire.
"""
from __future__ import annotations

import json
import socket
import struct
import threading
from typing import Any, Dict, Optional

from caps_tpu.obs.lockgraph import make_lock
from caps_tpu.obs.metrics import global_registry
from caps_tpu.serve.errors import (QueryFailed, ServeError, WireError,
                                   error_from_payload)

#: 4-byte big-endian frame length header
_HEADER = struct.Struct(">I")

#: hard bound on one frame's body — a corrupt header must not make the
#: receiver allocate gigabytes
MAX_FRAME_BYTES = 64 * 1024 * 1024


def _count(name: str, n: int = 1) -> None:
    global_registry().counter(name).inc(n)


def send_frame(sock: socket.socket, obj: Dict[str, Any]) -> None:
    """Serialize + send one frame.  Raises :class:`WireError` on any
    transport failure (connection reset, closed socket) and on a body
    that cannot be JSON-encoded or exceeds :data:`MAX_FRAME_BYTES`."""
    try:
        body = json.dumps(obj, separators=(",", ":")).encode("utf-8")
    except (TypeError, ValueError) as ex:
        raise WireError(f"frame body is not JSON-serializable: "
                        f"{type(ex).__name__}: {ex}")
    if len(body) > MAX_FRAME_BYTES:
        raise WireError(f"frame of {len(body)} bytes exceeds the "
                        f"{MAX_FRAME_BYTES}-byte bound")
    try:
        sock.sendall(_HEADER.pack(len(body)) + body)
    except OSError as ex:
        _count("wire.drops")
        raise WireError(f"send failed: {type(ex).__name__}: {ex}")
    _count("wire.frames_sent")
    _count("wire.bytes_sent", _HEADER.size + len(body))


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    """Read exactly ``n`` bytes; None on clean EOF at a frame boundary
    (nothing read yet), WireError on a mid-frame disconnect."""
    chunks = []
    got = 0
    while got < n:
        try:
            chunk = sock.recv(min(65536, n - got))
        except OSError as ex:
            _count("wire.drops")
            raise WireError(f"recv failed: {type(ex).__name__}: {ex}")
        if not chunk:
            if got == 0:
                return None
            _count("wire.drops")
            raise WireError(f"connection closed mid-frame "
                            f"({got}/{n} bytes)")
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def recv_frame(sock: socket.socket) -> Optional[Dict[str, Any]]:
    """Receive one frame.  Returns the decoded object, or None on a
    clean EOF between frames (the peer hung up); raises
    :class:`WireError` on a torn frame or undecodable body."""
    header = _recv_exact(sock, _HEADER.size)
    if header is None:
        return None
    (length,) = _HEADER.unpack(header)
    if length > MAX_FRAME_BYTES:
        _count("wire.drops")
        raise WireError(f"frame header announces {length} bytes "
                        f"(bound {MAX_FRAME_BYTES})")
    body = _recv_exact(sock, length)
    if body is None:
        _count("wire.drops")
        raise WireError("connection closed between header and body")
    try:
        obj = json.loads(body.decode("utf-8"))
    except (ValueError, UnicodeDecodeError) as ex:
        _count("wire.drops")
        raise WireError(f"undecodable frame body: "
                        f"{type(ex).__name__}: {ex}")
    if not isinstance(obj, dict):
        _count("wire.drops")
        raise WireError(f"frame body must be an object, got "
                        f"{type(obj).__name__}")
    _count("wire.frames_received")
    _count("wire.bytes_received", _HEADER.size + length)
    return obj


class WireClient:
    """One connection to a fleet backend: synchronous request/reply.

    Thread-safe (one in-flight call at a time per client — the router
    holds one client per backend and serializes on it; concurrent
    routing across backends still parallelizes).  A transport failure
    closes the socket and raises :class:`WireError`; the next call
    reconnects, so a healed backend is reusable without rebuilding the
    client."""

    def __init__(self, host: str, port: int, timeout_s: float = 30.0):
        self.host = host
        self.port = int(port)
        self.timeout_s = timeout_s
        self._sock: Optional[socket.socket] = None
        self._lock = make_lock("wire.WireClient._lock")

    def _connect(self) -> socket.socket:
        try:
            sock = socket.create_connection((self.host, self.port),
                                            timeout=self.timeout_s)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError as ex:
            _count("wire.connect_failures")
            raise WireError(f"connect to {self.host}:{self.port} failed: "
                            f"{type(ex).__name__}: {ex}")
        return sock

    def call(self, op: str, **fields: Any) -> Any:
        """Send ``{"op": op, **fields}``, wait for the reply, return its
        ``result``.  A remote typed error re-raises HERE as the exact
        class the backend raised; transport failures raise
        :class:`WireError` after closing the connection."""
        with self._lock:
            if self._sock is None:
                self._sock = self._connect()
            try:
                send_frame(self._sock, {"op": op, **fields})
                reply = recv_frame(self._sock)
            except ServeError:
                self._close_locked()
                raise
            if reply is None:
                self._close_locked()
                _count("wire.drops")
                raise WireError(f"{self.host}:{self.port} closed the "
                                f"connection before replying to {op!r}")
        if reply.get("ok"):
            return reply.get("result")
        raise error_from_payload(reply.get("error"))

    def _close_locked(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:  # pragma: no cover — close must not raise
                pass
            self._sock = None

    def close(self) -> None:
        with self._lock:
            self._close_locked()

    def __enter__(self) -> "WireClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def serve_connection(conn: socket.socket, handler,
                     shutting_down: Optional[threading.Event] = None
                     ) -> None:
    """One connection's serve loop: frame in → ``handler(msg)`` →
    reply frame out, until the peer hangs up (or ``shutting_down``
    fires).  Every failure crosses the wire typed: a ServeError
    serializes as itself, anything else wraps into a
    :class:`QueryFailed` carrying the original class name — the remote
    client never sees an untyped error."""
    try:
        while shutting_down is None or not shutting_down.is_set():
            msg = recv_frame(conn)
            if msg is None:
                return
            try:
                reply = {"ok": True, "result": handler(msg)}
            except ServeError as ex:
                reply = {"ok": False, "error": ex.to_payload()}
            except Exception as ex:
                reply = {"ok": False,
                         "error": QueryFailed(
                             f"{type(ex).__name__}: {ex}").to_payload()}
            send_frame(conn, reply)
    except ServeError:
        # torn connection mid-serve: the client saw its own WireError;
        # nothing to reply to
        _count("wire.connections_torn")
    finally:
        try:
            conn.close()
        except OSError:  # pragma: no cover — close must not raise
            pass

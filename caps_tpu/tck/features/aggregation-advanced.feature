Feature: Aggregation edge cases

  Scenario: avg of integers is a float
    Given an empty graph
    When executing query:
      """
      UNWIND [1, 2] AS v RETURN avg(v) AS a
      """
    Then the result should be, in any order:
      | a   |
      | 1.5 |

  Scenario: sum of mixed int and float is float
    Given an empty graph
    When executing query:
      """
      UNWIND [1, 2.5] AS v RETURN sum(v) AS s
      """
    Then the result should be, in any order:
      | s   |
      | 3.5 |

  Scenario: min and max over strings are lexicographic
    Given an empty graph
    When executing query:
      """
      UNWIND ['pear', 'apple', 'fig'] AS v RETURN min(v) AS mn, max(v) AS mx
      """
    Then the result should be, in any order:
      | mn      | mx     |
      | 'apple' | 'pear' |

  Scenario: min and max over negative and zero values
    Given an empty graph
    When executing query:
      """
      UNWIND [-3, 0, 2] AS v RETURN min(v) AS mn, max(v) AS mx
      """
    Then the result should be, in any order:
      | mn | mx |
      | -3 | 2  |

  Scenario: count of rows vs count of values
    Given an empty graph
    And having executed:
      """
      CREATE (:P {v: 1}), (:P)
      """
    When executing query:
      """
      MATCH (p:P) RETURN count(*) AS rows, count(p.v) AS vals
      """
    Then the result should be, in any order:
      | rows | vals |
      | 2    | 1    |

  Scenario: grouping keys include rows whose aggregate input is null
    Given an empty graph
    And having executed:
      """
      CREATE (:P {g: 'x', v: 1}), (:P {g: 'x'}), (:P {g: 'y'})
      """
    When executing query:
      """
      MATCH (p:P) RETURN p.g AS g, count(p.v) AS c
      """
    Then the result should be, in any order:
      | g   | c |
      | 'x' | 1 |
      | 'y' | 0 |

  Scenario: null grouping key forms its own group
    Given an empty graph
    And having executed:
      """
      CREATE (:P {g: 'x', v: 1}), (:P {v: 2}), (:P {v: 3})
      """
    When executing query:
      """
      MATCH (p:P) RETURN p.g AS g, sum(p.v) AS s
      """
    Then the result should be, in any order:
      | g    | s |
      | 'x'  | 1 |
      | null | 5 |

  Scenario: aggregation without grouping keys over zero rows yields one row
    Given an empty graph
    When executing query:
      """
      MATCH (p:Nope) RETURN count(p) AS c, sum(p.v) AS s, collect(p.v) AS l
      """
    Then the result should be, in any order:
      | c | s | l  |
      | 0 | 0 | [] |

  Scenario: grouped aggregation over zero rows yields no rows
    Given an empty graph
    When executing query:
      """
      MATCH (p:Nope) RETURN p.g AS g, count(*) AS c
      """
    Then the result should be empty

  Scenario: multiple aggregates in one projection share the grouping
    Given an empty graph
    And having executed:
      """
      CREATE (:P {g: 'x', v: 1}), (:P {g: 'x', v: 3})
      """
    When executing query:
      """
      MATCH (p:P)
      RETURN p.g AS g, count(*) AS c, sum(p.v) AS s, min(p.v) AS mn,
             max(p.v) AS mx, avg(p.v) AS a
      """
    Then the result should be, in any order:
      | g   | c | s | mn | mx | a   |
      | 'x' | 2 | 4 | 1  | 3  | 2.0 |

  Scenario: collect preserves duplicates
    Given an empty graph
    When executing query:
      """
      UNWIND [1, 1, 2] AS v WITH v ORDER BY v RETURN collect(v) AS l
      """
    Then the result should be, in any order:
      | l         |
      | [1, 1, 2] |

  Scenario: aggregate of an arithmetic expression
    Given an empty graph
    When executing query:
      """
      UNWIND [1, 2, 3] AS v RETURN sum(v * v) AS s
      """
    Then the result should be, in any order:
      | s  |
      | 14 |

  Scenario: count distinct on a grouped query
    Given an empty graph
    And having executed:
      """
      CREATE (:P {g: 'x', v: 1}), (:P {g: 'x', v: 1}), (:P {g: 'x', v: 2}),
             (:P {g: 'y', v: 1})
      """
    When executing query:
      """
      MATCH (p:P) RETURN p.g AS g, count(DISTINCT p.v) AS c
      """
    Then the result should be, in any order:
      | g   | c |
      | 'x' | 2 |
      | 'y' | 1 |

  Scenario: min over mixed int and float compares numerically
    Given an empty graph
    When executing query:
      """
      UNWIND [2, 1.5, 3] AS v RETURN min(v) AS mn
      """
    Then the result should be, in any order:
      | mn  |
      | 1.5 |

  Scenario: sum over floats keeps float type
    Given an empty graph
    When executing query:
      """
      UNWIND [0.5, 0.25] AS v RETURN sum(v) AS s
      """
    Then the result should be, in any order:
      | s    |
      | 0.75 |

  Scenario: grouping by two keys
    Given an empty graph
    And having executed:
      """
      CREATE (:P {a: 1, b: 'x'}), (:P {a: 1, b: 'x'}), (:P {a: 1, b: 'y'}),
             (:P {a: 2, b: 'x'})
      """
    When executing query:
      """
      MATCH (p:P) RETURN p.a AS a, p.b AS b, count(*) AS c
      """
    Then the result should be, in any order:
      | a | b   | c |
      | 1 | 'x' | 2 |
      | 1 | 'y' | 1 |
      | 2 | 'x' | 1 |

  Scenario: aggregation result feeds arithmetic in a later stage
    Given an empty graph
    When executing query:
      """
      UNWIND [1, 2, 3, 4] AS v WITH count(v) AS n, sum(v) AS s
      RETURN s / n AS mean
      """
    Then the result should be, in any order:
      | mean |
      | 2    |

  Scenario: percentileDisc uses the nearest-rank method
    Given an empty graph
    And having executed:
      """
      CREATE ({v: 10}), ({v: 20}), ({v: 30}), ({v: 40})
      """
    When executing query:
      """
      MATCH (n)
      RETURN percentileDisc(n.v, 0.0) AS p0, percentileDisc(n.v, 0.5) AS p50,
             percentileDisc(n.v, 0.51) AS p51, percentileDisc(n.v, 1.0) AS p100
      """
    Then the result should be, in any order:
      | p0 | p50 | p51 | p100 |
      | 10 | 20  | 30  | 40   |

  Scenario: percentileCont interpolates linearly
    Given an empty graph
    And having executed:
      """
      CREATE ({v: 10}), ({v: 20}), ({v: 40})
      """
    When executing query:
      """
      MATCH (n)
      RETURN percentileCont(n.v, 0.5) AS med, percentileCont(n.v, 0.75) AS q3
      """
    Then the result should be, in any order:
      | med  | q3   |
      | 20.0 | 30.0 |

  Scenario: percentile of no rows is null and skips nulls
    Given an empty graph
    And having executed:
      """
      CREATE ({w: 1}), ({v: 5}), ({v: 7})
      """
    When executing query:
      """
      MATCH (n)
      RETURN percentileDisc(n.v, 0.5) AS d, percentileCont(n.w, 0.5) AS c
      """
    Then the result should be, in any order:
      | d | c   |
      | 5 | 1.0 |

  Scenario: grouped percentile over string groups
    Given an empty graph
    And having executed:
      """
      CREATE ({g: 'a', v: 1}), ({g: 'a', v: 3}), ({g: 'b', v: 9})
      """
    When executing query:
      """
      MATCH (n) RETURN n.g AS g, percentileDisc(n.v, 1.0) AS mx
      """
    Then the result should be, in any order:
      | g   | mx |
      | 'a' | 3  |
      | 'b' | 9  |

  Scenario: percentileDisc and percentileCont honour DISTINCT
    Given an empty graph
    And having executed:
      """
      CREATE (:P {v: 1}), (:P {v: 2}), (:P {v: 2}), (:P {v: 2})
      """
    When executing query:
      """
      MATCH (p:P)
      RETURN percentileDisc(DISTINCT p.v, 0.5) AS pd,
             percentileCont(DISTINCT p.v, 0.5) AS pc,
             percentileDisc(p.v, 0.5) AS pn
      """
    Then the result should be, in any order:
      | pd | pc  | pn |
      | 1  | 1.5 | 2  |

  Scenario: count and collect DISTINCT over grouped entities
    Given an empty graph
    And having executed:
      """
      CREATE (a:U {n: 'a'}), (b:U {n: 'b'}),
             (a)-[:L]->(:M {t: 'x'}), (a)-[:L]->(:M {t: 'x'}),
             (b)-[:L]->(:M {t: 'y'})
      """
    When executing query:
      """
      MATCH (u:U)-[:L]->(m:M)
      RETURN u.n AS n, count(DISTINCT m.t) AS c, collect(DISTINCT m.t) AS ts
      """
    Then the result should be, in any order:
      | n   | c | ts    |
      | 'a' | 1 | ['x'] |
      | 'b' | 1 | ['y'] |

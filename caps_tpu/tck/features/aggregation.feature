Feature: Aggregation

  Scenario: count star over all rows
    Given an empty graph
    And having executed:
      """
      CREATE (:P), (:P), (:Q)
      """
    When executing query:
      """
      MATCH (n) RETURN count(*) AS c
      """
    Then the result should be, in any order:
      | c |
      | 3 |

  Scenario: count of an expression skips nulls
    Given an empty graph
    And having executed:
      """
      CREATE (:P {x: 1}), (:P {x: 2}), (:P)
      """
    When executing query:
      """
      MATCH (p:P) RETURN count(p.x) AS c
      """
    Then the result should be, in any order:
      | c |
      | 2 |

  Scenario: count on an empty match returns zero
    Given an empty graph
    When executing query:
      """
      MATCH (n) RETURN count(*) AS c
      """
    Then the result should be, in any order:
      | c |
      | 0 |

  Scenario: min max sum avg on an empty match return null
    Given an empty graph
    When executing query:
      """
      MATCH (n:Nope) RETURN count(*) AS c, min(n.v) AS mn, max(n.v) AS mx, sum(n.v) AS s, avg(n.v) AS a
      """
    Then the result should be, in any order:
      | c | mn   | mx   | s | a    |
      | 0 | null | null | 0 | null |

  Scenario: min max over an all-null property return null
    Given an empty graph
    And having executed:
      """
      CREATE (:P), (:P)
      """
    When executing query:
      """
      MATCH (p:P) RETURN min(p.x) AS mn, max(p.x) AS mx
      """
    Then the result should be, in any order:
      | mn   | mx   |
      | null | null |

  Scenario: collect on an empty match returns the empty list
    Given an empty graph
    When executing query:
      """
      MATCH (n:Nope) RETURN collect(n.v) AS l
      """
    Then the result should be, in any order:
      | l  |
      | [] |

  Scenario: sum avg min max over a grouping key
    Given an empty graph
    And having executed:
      """
      CREATE (:P {g: 'a', x: 1}), (:P {g: 'a', x: 3}), (:P {g: 'b', x: 5})
      """
    When executing query:
      """
      MATCH (p:P) RETURN p.g AS g, sum(p.x) AS s, avg(p.x) AS a, min(p.x) AS mn, max(p.x) AS mx
      """
    Then the result should be, in any order:
      | g   | s | a   | mn | mx |
      | 'a' | 4 | 2.0 | 1  | 3  |
      | 'b' | 5 | 5.0 | 5  | 5  |

  Scenario: aggregates ignore null values
    Given an empty graph
    And having executed:
      """
      CREATE (:P {x: 2}), (:P)
      """
    When executing query:
      """
      MATCH (p:P) RETURN sum(p.x) AS s, min(p.x) AS mn, avg(p.x) AS a
      """
    Then the result should be, in any order:
      | s | mn | a   |
      | 2 | 2  | 2.0 |

  Scenario: collect gathers values and skips nulls
    Given an empty graph
    And having executed:
      """
      CREATE (:P {x: 1}), (:P {x: 2}), (:P)
      """
    When executing query:
      """
      MATCH (p:P) WITH p.x AS x ORDER BY x RETURN collect(x) AS l
      """
    Then the result should be, in any order:
      | l      |
      | [1, 2] |

  Scenario: count DISTINCT
    Given an empty graph
    And having executed:
      """
      CREATE (:P {x: 1}), (:P {x: 1}), (:P {x: 2})
      """
    When executing query:
      """
      MATCH (p:P) RETURN count(DISTINCT p.x) AS c
      """
    Then the result should be, in any order:
      | c |
      | 2 |

  Scenario: grouped count over relationships
    Given an empty graph
    And having executed:
      """
      CREATE (a:P {n: 'a'}), (b:P {n: 'b'}), (c:P {n: 'c'}), (a)-[:T]->(b), (a)-[:T]->(c), (b)-[:T]->(c)
      """
    When executing query:
      """
      MATCH (p:P)-[:T]->() RETURN p.n AS n, count(*) AS deg
      """
    Then the result should be, in any order:
      | n   | deg |
      | 'a' | 2   |
      | 'b' | 1   |

  Scenario: aggregation then further processing with WITH
    Given an empty graph
    And having executed:
      """
      CREATE (:P {g: 'a', x: 1}), (:P {g: 'a', x: 2}), (:P {g: 'b', x: 9})
      """
    When executing query:
      """
      MATCH (p:P) WITH p.g AS g, sum(p.x) AS s WHERE s > 2 RETURN g, s ORDER BY g
      """
    Then the result should be, in order:
      | g   | s |
      | 'a' | 3 |
      | 'b' | 9 |

  Scenario: aggregation with zero groups returns a single row for the global aggregate
    Given an empty graph
    When executing query:
      """
      MATCH (n:Missing) RETURN count(n) AS c, sum(n.v) AS s
      """
    Then the result should be, in any order:
      | c | s |
      | 0 | 0 |

  Scenario: grouped aggregation over an empty match returns no rows
    Given an empty graph
    When executing query:
      """
      MATCH (n:Missing) RETURN n.v AS v, count(*) AS c
      """
    Then the result should be, in any order:
      | v | c |

  Scenario: avg over a mix of ints and floats
    Given an empty graph
    And having executed:
      """
      CREATE (:N {v: 1}), (:N {v: 2.0}), (:N {v: 3})
      """
    When executing query:
      """
      MATCH (n:N) RETURN avg(n.v) AS a
      """
    Then the result should be, in any order:
      | a   |
      | 2.0 |

Feature: CASE expressions

  Scenario: generic CASE picks the first true branch
    Given an empty graph
    When executing query:
      """
      UNWIND [1, 5, 9] AS x
      RETURN x, CASE WHEN x < 3 THEN 'small' WHEN x < 7 THEN 'mid'
                ELSE 'big' END AS c
      """
    Then the result should be, in any order:
      | x | c       |
      | 1 | 'small' |
      | 5 | 'mid'   |
      | 9 | 'big'   |

  Scenario: simple CASE matches on value equality
    Given an empty graph
    When executing query:
      """
      UNWIND [1, 2, 3] AS x
      RETURN CASE x WHEN 1 THEN 'one' WHEN 2 THEN 'two' ELSE 'many' END AS c
      """
    Then the result should be, in any order:
      | c      |
      | 'one'  |
      | 'two'  |
      | 'many' |

  Scenario: simple CASE without default yields null on no match
    Given an empty graph
    When executing query:
      """
      UNWIND [9] AS x RETURN CASE x WHEN 1 THEN 'one' END AS c
      """
    Then the result should be, in any order:
      | c    |
      | null |

  Scenario: CASE branches can yield different numeric kinds
    Given an empty graph
    When executing query:
      """
      UNWIND [1, 2] AS x RETURN CASE WHEN x = 1 THEN 10 ELSE 2.5 END AS c
      """
    Then the result should be, in any order:
      | c   |
      | 10  |
      | 2.5 |

  Scenario: CASE result usable in WHERE
    Given an empty graph
    When executing query:
      """
      UNWIND [1, 2, 3] AS x
      WITH x, CASE WHEN x % 2 = 0 THEN 'even' ELSE 'odd' END AS p
      WHERE p = 'odd' RETURN x
      """
    Then the result should be, in any order:
      | x |
      | 1 |
      | 3 |

  Scenario: nested CASE expressions
    Given an empty graph
    When executing query:
      """
      UNWIND [1, 2, 3] AS x
      RETURN CASE WHEN x < 3 THEN CASE WHEN x = 1 THEN 'a' ELSE 'b' END
             ELSE 'c' END AS c
      """
    Then the result should be, in any order:
      | c   |
      | 'a' |
      | 'b' |
      | 'c' |

  Scenario: CASE over a null scrutinee with simple form matches nothing
    Given an empty graph
    And having executed:
      """
      CREATE (:P)
      """
    When executing query:
      """
      MATCH (p:P) RETURN CASE p.x WHEN 1 THEN 'one' ELSE 'other' END AS c
      """
    Then the result should be, in any order:
      | c       |
      | 'other' |

  Scenario: CASE branch conditions evaluate in order
    Given an empty graph
    When executing query:
      """
      UNWIND [4] AS x
      RETURN CASE WHEN x > 1 THEN 'first' WHEN x > 2 THEN 'second' END AS c
      """
    Then the result should be, in any order:
      | c       |
      | 'first' |

  Scenario: CASE can return null explicitly
    Given an empty graph
    When executing query:
      """
      UNWIND [1, 2] AS x RETURN CASE WHEN x = 1 THEN null ELSE x END AS c
      """
    Then the result should be, in any order:
      | c    |
      | null |
      | 2    |

  Scenario: CASE in ORDER BY key
    Given an empty graph
    When executing query:
      """
      UNWIND ['b', 'a', 'c'] AS x
      RETURN x ORDER BY CASE WHEN x = 'c' THEN 0 ELSE 1 END, x
      """
    Then the result should be, in order:
      | x   |
      | 'c' |
      | 'a' |
      | 'b' |

  Scenario: searched CASE falls through to ELSE on null input
    Given an empty graph
    And having executed:
      """
      CREATE (:N {v: 1}), (:N {v: 10}), (:N)
      """
    When executing query:
      """
      MATCH (n:N)
      RETURN CASE WHEN n.v < 5 THEN 'small' WHEN n.v >= 5 THEN 'big' ELSE 'none' END AS bucket
      """
    Then the result should be, in any order:
      | bucket  |
      | 'small' |
      | 'big'   |
      | 'none'  |

  Scenario: simple CASE with no ELSE yields null when nothing matches
    Given an empty graph
    And having executed:
      """
      CREATE (:N {v: 1}), (:N {v: 7})
      """
    When executing query:
      """
      MATCH (n:N) RETURN CASE n.v WHEN 1 THEN 'one' END AS w
      """
    Then the result should be, in any order:
      | w     |
      | 'one' |
      | null  |

Feature: Type conversions and boundary forms
  # Written from openCypher spec semantics (not engine behavior):
  # toInteger/toFloat/toBoolean/toString coercion tables, numeric
  # function edge cases, empty/degenerate var-length ranges, and
  # list-function boundaries.

  Scenario: toInteger over numbers and strings
    Given an empty graph
    When executing query:
      """
      RETURN toInteger(42) AS a, toInteger(3.9) AS b,
             toInteger('17') AS c, toInteger('42.9') AS d,
             toInteger('nope') AS e, toInteger(null) AS f
      """
    Then the result should be, in any order:
      | a  | b | c  | d  | e    | f    |
      | 42 | 3 | 17 | 42 | null | null |

  Scenario: toFloat over numbers and strings
    Given an empty graph
    When executing query:
      """
      RETURN toFloat(2) AS a, toFloat('3.25') AS b, toFloat('x') AS c,
             toFloat(null) AS d
      """
    Then the result should be, in any order:
      | a   | b    | c    | d    |
      | 2.0 | 3.25 | null | null |

  Scenario: toString over every primitive
    Given an empty graph
    When executing query:
      """
      RETURN toString(7) AS a, toString(1.5) AS b, toString(true) AS c,
             toString('s') AS d, toString(null) AS e
      """
    Then the result should be, in any order:
      | a   | b     | c      | d   | e    |
      | '7' | '1.5' | 'true' | 's' | null |

  Scenario: toBoolean over strings
    Given an empty graph
    When executing query:
      """
      RETURN toBoolean('true') AS a, toBoolean('FALSE') AS b,
             toBoolean('maybe') AS c, toBoolean(null) AS d
      """
    Then the result should be, in any order:
      | a    | b     | c    | d    |
      | true | false | null | null |

  Scenario: numeric functions at domain edges yield null, not errors
    Given an empty graph
    When executing query:
      """
      RETURN sqrt(-1.0) AS a, log(0.0) AS b, log(-2.0) AS c,
             log10(0.0) AS d
      """
    Then the result should be, in any order:
      | a    | b    | c    | d    |
      | null | null | null | null |

  Scenario: sign and abs over signs and zero
    Given an empty graph
    When executing query:
      """
      UNWIND [-5, 0, 3] AS v RETURN v, sign(v) AS s, abs(v) AS a
      """
    Then the result should be, in any order:
      | v  | s  | a |
      | -5 | -1 | 5 |
      | 0  | 0  | 0 |
      | 3  | 1  | 3 |

  Scenario: round half up including negatives
    Given an empty graph
    When executing query:
      """
      RETURN round(0.5) AS a, round(1.5) AS b, round(-0.5) AS c,
             round(-1.5) AS d, round(2.4) AS e
      """
    Then the result should be, in any order:
      | a   | b   | c   | d    | e   |
      | 1.0 | 2.0 | 0.0 | -1.0 | 2.0 |

  Scenario: zero-length var expand binds source as target
    Given an empty graph
    And having executed:
      """
      CREATE (a:P {n: 'a'})-[:R]->(b:P {n: 'b'})
      """
    When executing query:
      """
      MATCH (x:P)-[:R*0..0]->(y) RETURN x.n AS x, y.n AS y
      """
    Then the result should be, in any order:
      | x   | y   |
      | 'a' | 'a' |
      | 'b' | 'b' |

  Scenario: var expand over an empty graph region matches nothing
    Given an empty graph
    And having executed:
      """
      CREATE (:P {n: 'a'}), (:P {n: 'b'})
      """
    When executing query:
      """
      MATCH (x:P)-[:R*1..3]->(y) RETURN x.n AS x
      """
    Then the result should be, in any order:
      | x |

  Scenario: head last and tail on empty and null lists
    Given an empty graph
    When executing query:
      """
      RETURN head([]) AS a, last([]) AS b, tail([]) AS c,
             head(null) AS d, tail(null) AS e
      """
    Then the result should be, in any order:
      | a    | b    | c  | d    | e    |
      | null | null | [] | null | null |

  Scenario: range with step and descending direction
    Given an empty graph
    When executing query:
      """
      RETURN range(1, 5) AS a, range(0, 10, 3) AS b, range(5, 1, -2) AS c
      """
    Then the result should be, in any order:
      | a               | b             | c         |
      | [1, 2, 3, 4, 5] | [0, 3, 6, 9]  | [5, 3, 1] |

  Scenario: substring boundaries
    Given an empty graph
    When executing query:
      """
      RETURN substring('hello', 1, 2) AS a, substring('hello', 3) AS b,
             substring('hello', 0, 99) AS c, substring('', 0, 2) AS d
      """
    Then the result should be, in any order:
      | a    | b    | c       | d  |
      | 'el' | 'lo' | 'hello' | '' |

  Scenario: left right and replace boundaries
    Given an empty graph
    When executing query:
      """
      RETURN left('abc', 99) AS a, right('abc', 2) AS b,
             replace('aaa', 'a', 'b') AS c, replace('abc', 'x', 'y') AS d
      """
    Then the result should be, in any order:
      | a     | b    | c     | d     |
      | 'abc' | 'bc' | 'bbb' | 'abc' |

  Scenario: trim family and case conversions
    Given an empty graph
    When executing query:
      """
      RETURN trim('  x  ') AS a, ltrim('  x') AS b, rtrim('x  ') AS c,
             toUpper('mIx') AS d, toLower('mIx') AS e
      """
    Then the result should be, in any order:
      | a   | b   | c   | d     | e     |
      | 'x' | 'x' | 'x' | 'MIX' | 'mix' |

  Scenario: reverse strings and lists
    Given an empty graph
    When executing query:
      """
      RETURN reverse('abc') AS a, reverse([1, 2, 3]) AS b, reverse([]) AS c
      """
    Then the result should be, in any order:
      | a     | b         | c  |
      | 'cba' | [3, 2, 1] | [] |

  Scenario: split produces lists of strings
    Given an empty graph
    When executing query:
      """
      RETURN split('a,b,c', ',') AS a, split('abc', 'x') AS b
      """
    Then the result should be, in any order:
      | a               | b       |
      | ['a', 'b', 'c'] | ['abc'] |

  Scenario: conversions compose with aggregation and WHERE
    Given an empty graph
    And having executed:
      """
      CREATE ({v: '10'}), ({v: '20'}), ({v: 'x'}), ({v: '30'})
      """
    When executing query:
      """
      MATCH (n) WITH toInteger(n.v) AS i WHERE i IS NOT NULL
      RETURN count(i) AS c, sum(i) AS s, min(i) AS mn
      """
    Then the result should be, in any order:
      | c | s  | mn |
      | 3 | 60 | 10 |

  Scenario: WITH plus WHERE over an aggregate acts as HAVING
    Given an empty graph
    And having executed:
      """
      CREATE ({g: 'a'}), ({g: 'a'}), ({g: 'b'})
      """
    When executing query:
      """
      MATCH (n) WITH n.g AS g, count(*) AS c WHERE c > 1
      RETURN g, c
      """
    Then the result should be, in any order:
      | g   | c |
      | 'a' | 2 |

  Scenario: inverse trig outside the domain is null
    Given an empty graph
    When executing query:
      """
      RETURN asin(2.0) AS a, acos(-1.5) AS b, asin(1.0) AS c
      """
    Then the result should be, in any order:
      | a    | b    | c                  |
      | null | null | 1.5707963267948966 |

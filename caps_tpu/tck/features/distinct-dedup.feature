Feature: Duplicate elimination semantics

  Scenario: DISTINCT treats null values as equal
    Given an empty graph
    And having executed:
      """
      CREATE (:P), (:P), (:P {v: 1})
      """
    When executing query:
      """
      MATCH (p:P) RETURN DISTINCT p.v AS v
      """
    Then the result should be, in any order:
      | v    |
      | null |
      | 1    |

  Scenario: DISTINCT over multiple columns dedups tuples not columns
    Given an empty graph
    And having executed:
      """
      CREATE (:P {a: 1, b: 1}), (:P {a: 1, b: 2}), (:P {a: 1, b: 1})
      """
    When executing query:
      """
      MATCH (p:P) RETURN DISTINCT p.a AS a, p.b AS b
      """
    Then the result should be, in any order:
      | a | b |
      | 1 | 1 |
      | 1 | 2 |

  Scenario: DISTINCT distinguishes int from bool
    Given an empty graph
    And having executed:
      """
      CREATE (:P {v: 1}), (:P {v: true}), (:P {v: 1})
      """
    When executing query:
      """
      MATCH (p:P) RETURN DISTINCT p.v AS v
      """
    Then the result should be, in any order:
      | v    |
      | 1    |
      | true |

  Scenario: count DISTINCT skips nulls but dedups values
    Given an empty graph
    And having executed:
      """
      CREATE (:P {v: 1}), (:P {v: 1}), (:P {v: 2}), (:P)
      """
    When executing query:
      """
      MATCH (p:P) RETURN count(DISTINCT p.v) AS c
      """
    Then the result should be, in any order:
      | c |
      | 2 |

  Scenario: sum DISTINCT adds each value once
    Given an empty graph
    And having executed:
      """
      CREATE (:P {v: 3}), (:P {v: 3}), (:P {v: 4})
      """
    When executing query:
      """
      MATCH (p:P) RETURN sum(DISTINCT p.v) AS s
      """
    Then the result should be, in any order:
      | s |
      | 7 |

  Scenario: collect DISTINCT dedups collected values
    Given an empty graph
    And having executed:
      """
      CREATE (:P {v: 1}), (:P {v: 1}), (:P {v: 2})
      """
    When executing query:
      """
      MATCH (p:P) WITH DISTINCT p.v AS v ORDER BY v RETURN collect(v) AS l
      """
    Then the result should be, in any order:
      | l      |
      | [1, 2] |

  Scenario: UNION dedups identical rows across arms
    Given an empty graph
    When executing query:
      """
      UNWIND [1, 2] AS v RETURN v
      UNION
      UNWIND [2, 3] AS v RETURN v
      """
    Then the result should be, in any order:
      | v |
      | 1 |
      | 2 |
      | 3 |

  Scenario: UNION ALL keeps duplicates across arms
    Given an empty graph
    When executing query:
      """
      UNWIND [1, 2] AS v RETURN v
      UNION ALL
      UNWIND [2, 3] AS v RETURN v
      """
    Then the result should be, in any order:
      | v |
      | 1 |
      | 2 |
      | 2 |
      | 3 |

  Scenario: UNION also dedups within each arm
    Given an empty graph
    When executing query:
      """
      UNWIND [1, 1] AS v RETURN v
      UNION
      UNWIND [2] AS v RETURN v
      """
    Then the result should be, in any order:
      | v |
      | 1 |
      | 2 |

  Scenario: UNION dedups rows containing nulls
    Given an empty graph
    And having executed:
      """
      CREATE (:P)
      """
    When executing query:
      """
      MATCH (p:P) RETURN p.x AS v
      UNION
      MATCH (p:P) RETURN p.x AS v
      """
    Then the result should be, in any order:
      | v    |
      | null |

  Scenario: DISTINCT on node values dedups by identity
    Given an empty graph
    And having executed:
      """
      CREATE (a:P {n: 'a'})-[:R]->(:Q), (a)-[:R]->(:Q)
      """
    When executing query:
      """
      MATCH (p:P)-[:R]->() RETURN DISTINCT p.n AS n
      """
    Then the result should be, in any order:
      | n   |
      | 'a' |

  Scenario: WITH DISTINCT limits downstream cardinality
    Given an empty graph
    And having executed:
      """
      CREATE (:P {g: 'x', v: 1}), (:P {g: 'x', v: 2}), (:P {g: 'y', v: 3})
      """
    When executing query:
      """
      MATCH (p:P) WITH DISTINCT p.g AS g RETURN count(*) AS c
      """
    Then the result should be, in any order:
      | c |
      | 2 |

  Scenario: DISTINCT on float and int of equal value dedups
    Given an empty graph
    When executing query:
      """
      UNWIND [1, 1.0] AS v RETURN DISTINCT v
      """
    Then the result should be, in any order:
      | v |
      | 1 |

  Scenario: DISTINCT lists compare elementwise
    Given an empty graph
    When executing query:
      """
      UNWIND [[1, 2], [1, 2], [2, 1]] AS l RETURN DISTINCT l
      """
    Then the result should be, in any order:
      | l      |
      | [1, 2] |
      | [2, 1] |

Feature: Error reporting

  Scenario: unclosed node pattern is a syntax error
    Given an empty graph
    When executing query:
      """
      MATCH (a RETURN a
      """
    Then a SyntaxError should be raised at compile time: InvalidSyntax

  Scenario: returning an undefined variable is an error
    Given an empty graph
    When executing query:
      """
      RETURN undefinedVar
      """
    Then a SyntaxError should be raised at compile time: UndefinedVariable

  Scenario: aggregation inside WHERE is an error
    Given an empty graph
    When executing query:
      """
      MATCH (n) WHERE count(n) > 1 RETURN n
      """
    Then a SyntaxError should be raised at compile time: InvalidAggregation

  Scenario: ORDER BY on a variable not in scope is an error
    Given an empty graph
    When executing query:
      """
      MATCH (n) RETURN n.x AS x ORDER BY banana
      """
    Then a SyntaxError should be raised at compile time: UndefinedVariable

  Scenario: quantified predicate without WHERE is a syntax error
    Given an empty graph
    When executing query:
      """
      RETURN all(x IN [1, 2]) AS a
      """
    Then a SyntaxError should be raised at compile time: InvalidSyntax

  Scenario: reduce without an accumulator is a syntax error
    Given an empty graph
    When executing query:
      """
      RETURN reduce(x IN [1, 2] | x) AS r
      """
    Then a SyntaxError should be raised at compile time: InvalidSyntax

  Scenario: comprehension variable is not visible outside its expression
    Given an empty graph
    When executing query:
      """
      RETURN [x IN [1, 2] | x] AS l, x AS leak
      """
    Then a SyntaxError should be raised at compile time: UndefinedVariable

  Scenario: DISTINCT inside a non-aggregating function is an error
    Given an empty graph
    When executing query:
      """
      RETURN size(DISTINCT [1, 2]) AS n
      """
    Then a SyntaxError should be raised at compile time: InvalidSyntax

  Scenario: date with a malformed string is a runtime error
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS one RETURN date('not-a-date') AS d
      """
    Then a TypeError should be raised at runtime: InvalidArgumentValue

  Scenario: with DISTINCT, ORDER BY an unprojected expression is an error
    Given an empty graph
    And having executed:
      """
      CREATE (:P {a: 1, b: 2})
      """
    When executing query:
      """
      MATCH (p:P) RETURN DISTINCT p.a AS a ORDER BY p.b
      """
    Then a SyntaxError should be raised at compile time: InvalidSyntax

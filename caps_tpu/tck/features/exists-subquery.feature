Feature: Exists subqueries

  Scenario: EXISTS filters to rows with a match
    Given an empty graph
    And having executed:
      """
      CREATE (a:P {n: 'a'}), (b:P {n: 'b'}), (c:Q), (a)-[:T]->(c)
      """
    When executing query:
      """
      MATCH (p:P) WHERE EXISTS { (p)-[:T]->(:Q) } RETURN p.n AS n
      """
    Then the result should be, in any order:
      | n   |
      | 'a' |

  Scenario: NOT EXISTS keeps only rows without a match
    Given an empty graph
    And having executed:
      """
      CREATE (a:P {n: 'a'}), (b:P {n: 'b'}), (c:Q), (a)-[:T]->(c)
      """
    When executing query:
      """
      MATCH (p:P) WHERE NOT EXISTS { MATCH (p)-[:T]->() } RETURN p.n AS n
      """
    Then the result should be, in any order:
      | n   |
      | 'b' |

  Scenario: EXISTS with an inner WHERE
    Given an empty graph
    And having executed:
      """
      CREATE (a:P {n: 'a'}), (b:P {n: 'b'}),
             (x:Q {v: 1}), (y:Q {v: 9}),
             (a)-[:T]->(x), (b)-[:T]->(y)
      """
    When executing query:
      """
      MATCH (p:P) WHERE EXISTS { MATCH (p)-[:T]->(q:Q) WHERE q.v > 5 } RETURN p.n AS n
      """
    Then the result should be, in any order:
      | n   |
      | 'b' |

  Scenario: EXISTS as a returned value
    Given an empty graph
    And having executed:
      """
      CREATE (a:P {n: 'a'}), (b:P {n: 'b'}), (a)-[:T]->(a)
      """
    When executing query:
      """
      MATCH (p:P) RETURN p.n AS n, EXISTS { (p)-[:T]->() } AS has
      """
    Then the result should be, in any order:
      | n   | has   |
      | 'a' | true  |
      | 'b' | false |

  Scenario: EXISTS does not multiply rows for multiple matches
    Given an empty graph
    And having executed:
      """
      CREATE (a:P {n: 'a'}), (b:Q), (c:Q), (a)-[:T]->(b), (a)-[:T]->(c)
      """
    When executing query:
      """
      MATCH (p:P) WHERE EXISTS { (p)-[:T]->(:Q) } RETURN count(*) AS c
      """
    Then the result should be, in any order:
      | c |
      | 1 |

  Scenario: nested EXISTS applies label constraints on enclosing-pattern vars
    Given an empty graph
    And having executed:
      """
      CREATE (a:P {n: 'a'}), (b:P), (a)-[:T]->(b)
      """
    When executing query:
      """
      MATCH (a:P) WHERE EXISTS { (a)-[:T]->(b) WHERE EXISTS { (b:Robot)-[:T]->(c) } } RETURN a.n AS n
      """
    Then the result should be empty

  Scenario: EXISTS with a label constraint on an outer-bound variable
    Given an empty graph
    And having executed:
      """
      CREATE (a:P {n: 'a'}), (b:Q), (a)-[:T]->(b)
      """
    When executing query:
      """
      MATCH (a:P) WHERE EXISTS { (a:Robot)-[:T]->(b) } RETURN a.n AS n
      """
    Then the result should be empty

  Scenario: ORDER BY an EXISTS subquery
    Given an empty graph
    And having executed:
      """
      CREATE (a:P {n: 'a'}), (b:P {n: 'b'}), (c:P {n: 'c'}), (a)-[:T]->(b), (c)-[:T]->(b)
      """
    When executing query:
      """
      MATCH (p:P) RETURN p.n AS n ORDER BY EXISTS { (p)-[:T]->() } DESC, n
      """
    Then the result should be, in order:
      | n   |
      | 'a' |
      | 'c' |
      | 'b' |

  Scenario: EXISTS over a disconnected pattern
    Given an empty graph
    And having executed:
      """
      CREATE (:P {n: 'a'}), (:R)
      """
    When executing query:
      """
      MATCH (p:P) WHERE EXISTS { MATCH (:R) } RETURN p.n AS n
      """
    Then the result should be, in any order:
      | n   |
      | 'a' |

  Scenario: NOT EXISTS keeps rows whose pattern has no match
    Given an empty graph
    And having executed:
      """
      CREATE (a:P {n: 'a'}), (b:P {n: 'b'}), (x:X), (a)-[:T]->(x)
      """
    When executing query:
      """
      MATCH (p:P) WHERE NOT EXISTS { (p)-[:T]->(:X) } RETURN p.n AS p
      """
    Then the result should be, in any order:
      | p   |
      | 'b' |

  Scenario: EXISTS with a WHERE clause inside the subquery
    Given an empty graph
    And having executed:
      """
      CREATE (a:P {n: 'a'}), (b:P {n: 'b'}),
             (x:X {v: 1}), (y:X {v: 9}),
             (a)-[:T]->(x), (b)-[:T]->(y)
      """
    When executing query:
      """
      MATCH (p:P) WHERE EXISTS { MATCH (p)-[:T]->(q:X) WHERE q.v > 5 } RETURN p.n AS p
      """
    Then the result should be, in any order:
      | p   |
      | 'b' |

  Scenario: EXISTS in RETURN projects a boolean per row
    Given an empty graph
    And having executed:
      """
      CREATE (a:P {n: 'a'}), (b:P {n: 'b'}), (x:X), (a)-[:T]->(x)
      """
    When executing query:
      """
      MATCH (p:P) RETURN p.n AS p, EXISTS { (p)-[:T]->(:X) } AS has
      """
    Then the result should be, in any order:
      | p   | has   |
      | 'a' | true  |
      | 'b' | false |

Feature: List comprehensions, quantified predicates and reduce

  Scenario: property access on entities inside a list comprehension
    Given an empty graph
    And having executed:
      """
      CREATE (:Person {name: 'Alice'})-[:KNOWS]->(:Person {name: 'Bob'})
      """
    When executing query:
      """
      MATCH (a)-[:KNOWS]->(b) RETURN [n IN [a, b] | n.name] AS names
      """
    Then the result should be, in any order:
      | names            |
      | ['Alice', 'Bob'] |

  Scenario: label predicate on entities inside a list comprehension
    Given an empty graph
    And having executed:
      """
      CREATE (:A {v: 1})-[:T]->(:B {v: 2})
      """
    When executing query:
      """
      MATCH (a)-[:T]->(b) RETURN [n IN [a, b] WHERE n:B | n.v] AS vs
      """
    Then the result should be, in any order:
      | vs  |
      | [2] |

  Scenario: labels and keys of comprehension-bound entities
    Given an empty graph
    And having executed:
      """
      CREATE (:A:B {x: 1, y: 2})
      """
    When executing query:
      """
      MATCH (n:A) RETURN [m IN [n] | labels(m)] AS ls, [m IN [n] | keys(m)] AS ks
      """
    Then the result should be, in any order:
      | ls         | ks           |
      | [['A', 'B']] | [['x', 'y']] |

  Scenario: relationship accessors inside a list comprehension
    Given an empty graph
    And having executed:
      """
      CREATE (:A {v: 1})-[:T {w: 9}]->(:B {v: 2})
      """
    When executing query:
      """
      MATCH (a)-[r:T]->(b)
      RETURN [x IN [r] | type(x)] AS ts, [x IN [r] | x.w] AS ws
      """
    Then the result should be, in any order:
      | ts    | ws  |
      | ['T'] | [9] |

  Scenario: comprehension over collected entities after WITH
    Given an empty graph
    And having executed:
      """
      CREATE (:P {name: 'Alice', age: 30}), (:P {name: 'Bob', age: 17})
      """
    When executing query:
      """
      MATCH (p:P) WITH collect(p) AS ps
      RETURN [x IN ps WHERE x.age >= 18 | x.name] AS adults
      """
    Then the result should be, in any order:
      | adults    |
      | ['Alice'] |

  Scenario: comprehension variable shadows an outer entity variable
    Given an empty graph
    And having executed:
      """
      CREATE (:P {v: 1})-[:T]->(:P {v: 2})
      """
    When executing query:
      """
      MATCH (a)-[:T]->(b) RETURN [a IN [b] | a.v] AS vs
      """
    Then the result should be, in any order:
      | vs  |
      | [2] |

  Scenario: nested comprehensions see the enclosing lambda variable
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS one
      RETURN [x IN [1, 2] | [y IN [10, 20] | x * y]] AS m
      """
    Then the result should be, in any order:
      | m                      |
      | [[10, 20], [20, 40]]   |

  Scenario: comprehension over a null list is null
    Given an empty graph
    And having executed:
      """
      CREATE (:P)
      """
    When executing query:
      """
      MATCH (p:P) RETURN [x IN p.missing | x + 1] AS l
      """
    Then the result should be, in any order:
      | l    |
      | null |

  Scenario: all with true, false and null verdicts
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS one
      RETURN all(x IN [1, 2] WHERE x > 0) AS t,
             all(x IN [1, -1] WHERE x > 0) AS f,
             all(x IN [1, null] WHERE x > 0) AS u,
             all(x IN [] WHERE x > 0) AS e
      """
    Then the result should be, in any order:
      | t    | f     | u    | e    |
      | true | false | null | true |

  Scenario: any with true, false and null verdicts
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS one
      RETURN any(x IN [-1, 2] WHERE x > 0) AS t,
             any(x IN [-1, -2] WHERE x > 0) AS f,
             any(x IN [null, -1] WHERE x > 0) AS u,
             any(x IN [null, 2] WHERE x > 0) AS tn,
             any(x IN [] WHERE x > 0) AS e
      """
    Then the result should be, in any order:
      | t    | f     | u    | tn   | e     |
      | true | false | null | true | false |

  Scenario: none is the negation of any
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS one
      RETURN none(x IN [-1, -2] WHERE x > 0) AS t,
             none(x IN [-1, 2] WHERE x > 0) AS f,
             none(x IN [null] WHERE x > 0) AS u
      """
    Then the result should be, in any order:
      | t    | f     | u    |
      | true | false | null |

  Scenario: single demands exactly one match
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS one
      RETURN single(x IN [1, -1] WHERE x > 0) AS t,
             single(x IN [1, 2] WHERE x > 0) AS f,
             single(x IN [-1, -2] WHERE x > 0) AS z,
             single(x IN [1, null] WHERE x > 0) AS u,
             single(x IN [1, 2, null] WHERE x > 0) AS fn
      """
    Then the result should be, in any order:
      | t    | f     | z     | u    | fn    |
      | true | false | false | null | false |

  Scenario: quantifier over entity list in WHERE
    Given an empty graph
    And having executed:
      """
      CREATE (:P {name: 'Alice', age: 30})-[:K]->(:P {name: 'Bob', age: 17}),
             (:P {name: 'Carol', age: 40})-[:K]->(:P {name: 'Dan', age: 45})
      """
    When executing query:
      """
      MATCH (a)-[:K]->(b)
      WHERE all(n IN [a, b] WHERE n.age >= 18)
      RETURN a.name AS nm
      """
    Then the result should be, in any order:
      | nm      |
      | 'Carol' |

  Scenario: reduce over integers and strings
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS one
      RETURN reduce(t = 0, x IN [1, 2, 3] | t + x) AS s,
             reduce(s = '!', x IN ['a', 'b'] | s + x) AS c,
             reduce(t = 0, x IN [] | t + x) AS e
      """
    Then the result should be, in any order:
      | s | c     | e |
      | 6 | '!ab' | 0 |

  Scenario: reduce over entity properties
    Given an empty graph
    And having executed:
      """
      CREATE (:P {v: 10})-[:T]->(:P {v: 32})
      """
    When executing query:
      """
      MATCH (a)-[:T]->(b)
      RETURN reduce(t = 0, n IN [a, b] | t + n.v) AS s
      """
    Then the result should be, in any order:
      | s  |
      | 42 |

  Scenario: reduce over a null list is null
    Given an empty graph
    And having executed:
      """
      CREATE (:P)
      """
    When executing query:
      """
      MATCH (p:P) RETURN reduce(t = 0, x IN p.missing | t + x) AS s
      """
    Then the result should be, in any order:
      | s    |
      | null |

  Scenario: filter and extract legacy forms
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS one
      RETURN filter(x IN [1, -2, 3] WHERE x > 0) AS f,
             extract(x IN [1, 2] | x * 10) AS e
      """
    Then the result should be, in any order:
      | f      | e        |
      | [1, 3] | [10, 20] |

  Scenario: comprehension projecting entities returns entity values
    Given an empty graph
    And having executed:
      """
      CREATE (:A {v: 1})-[:T]->(:B {v: 2})
      """
    When executing query:
      """
      MATCH (a:A)-[:T]->(b) RETURN [n IN [a, b] WHERE n.v > 1 | n] AS ns
      """
    Then the result should be, in any order:
      | ns             |
      | [(:B {v: 2})]  |

  Scenario: nodes on a var-length path inside a comprehension
    Given an empty graph
    And having executed:
      """
      CREATE (:P {name: 'Alice'})-[:K]->(:P {name: 'Bob'})-[:K]->(:P {name: 'Carol'})
      """
    When executing query:
      """
      MATCH p = (:P {name: 'Alice'})-[:K*1..2]->(x)
      RETURN [n IN nodes(p) | n.name] AS names
      """
    Then the result should be, in any order:
      | names                     |
      | ['Alice', 'Bob']          |
      | ['Alice', 'Bob', 'Carol'] |

  Scenario: unwinding nodes of a var-length path rehydrates entities
    Given an empty graph
    And having executed:
      """
      CREATE (:P {name: 'Alice'})-[:K]->(:P {name: 'Bob'})-[:K]->(:P {name: 'Carol'})
      """
    When executing query:
      """
      MATCH p = (:P {name: 'Alice'})-[:K*2]->(x)
      UNWIND nodes(p) AS n RETURN n.name AS nm
      """
    Then the result should be, in any order:
      | nm      |
      | 'Alice' |
      | 'Bob'   |
      | 'Carol' |

  Scenario: size of nodes on a var-length path after projection
    Given an empty graph
    And having executed:
      """
      CREATE (:P {v: 1})-[:K]->(:P {v: 2})-[:K]->(:P {v: 3})
      """
    When executing query:
      """
      MATCH p = (:P {v: 1})-[:K*2]->(x) WITH p AS q
      RETURN size(nodes(q)) AS n, [m IN nodes(q) | m.v] AS vs
      """
    Then the result should be, in any order:
      | n | vs        |
      | 3 | [1, 2, 3] |

  Scenario: relationship properties over a var-length path comprehension
    Given an empty graph
    And having executed:
      """
      CREATE (:P)-[:K {w: 1}]->(:P)-[:K {w: 2}]->(:P)
      """
    When executing query:
      """
      MATCH p = (:P)-[:K*2]->(x)
      RETURN [r IN relationships(p) | r.w] AS ws
      """
    Then the result should be, in any order:
      | ws     |
      | [1, 2] |

  Scenario: quantifier over relationships of a var-length path
    Given an empty graph
    And having executed:
      """
      CREATE (:P {name: 'a'})-[:K {w: 1}]->(:P)-[:K {w: 5}]->(:P {name: 'c'})
      """
    When executing query:
      """
      MATCH p = (:P {name: 'a'})-[:K*2]->(x)
      RETURN all(r IN relationships(p) WHERE r.w > 0) AS pos,
             any(r IN relationships(p) WHERE r.w > 3) AS big
      """
    Then the result should be, in any order:
      | pos  | big  |
      | true | true |

  Scenario: startNode and endNode inside a comprehension
    Given an empty graph
    And having executed:
      """
      CREATE (:A {v: 1})-[:T]->(:B {v: 2})
      """
    When executing query:
      """
      MATCH (a)-[r:T]->(b)
      RETURN [x IN [r] | id(startNode(x)) = id(a)] AS s,
             [x IN [r] | id(endNode(x)) = id(b)] AS e
      """
    Then the result should be, in any order:
      | s      | e      |
      | [true] | [true] |

  Scenario: comprehension over map values yields property lookups
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS one
      RETURN [m IN [{a: 1}, {a: 2}] | m.a] AS vs
      """
    Then the result should be, in any order:
      | vs     |
      | [1, 2] |

  Scenario: quantifiers treat a null list as null
    Given an empty graph
    And having executed:
      """
      CREATE (:P)
      """
    When executing query:
      """
      MATCH (p:P)
      RETURN all(x IN p.missing WHERE x > 0) AS a,
             any(x IN p.missing WHERE x > 0) AS y
      """
    Then the result should be, in any order:
      | a    | y    |
      | null | null |

Feature: List values, indexing and slicing

  Scenario: list literal round-trips
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS x RETURN [1, 2, 3] AS l
      """
    Then the result should be, in any order:
      | l         |
      | [1, 2, 3] |

  Scenario: empty list literal
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS x RETURN [] AS l, size([]) AS s
      """
    Then the result should be, in any order:
      | l  | s |
      | [] | 0 |

  Scenario: positive indexing is zero-based
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS x RETURN [7, 8, 9][0] AS a, [7, 8, 9][2] AS b
      """
    Then the result should be, in any order:
      | a | b |
      | 7 | 9 |

  Scenario: negative indexing counts from the end
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS x RETURN [7, 8, 9][-1] AS a, [7, 8, 9][-3] AS b
      """
    Then the result should be, in any order:
      | a | b |
      | 9 | 7 |

  Scenario: out-of-range index is null
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS x RETURN [7, 8, 9][5] AS a, [7, 8, 9][-4] AS b
      """
    Then the result should be, in any order:
      | a    | b    |
      | null | null |

  Scenario: indexing with a null index is null
    Given an empty graph
    And having executed:
      """
      CREATE (:P)
      """
    When executing query:
      """
      MATCH (p:P) RETURN [1, 2][p.i] AS v
      """
    Then the result should be, in any order:
      | v    |
      | null |

  Scenario: list slicing with both bounds
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS x RETURN [1, 2, 3, 4][1..3] AS l
      """
    Then the result should be, in any order:
      | l      |
      | [2, 3] |

  Scenario: list slicing with open ends
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS x RETURN [1, 2, 3][1..] AS a, [1, 2, 3][..2] AS b
      """
    Then the result should be, in any order:
      | a      | b      |
      | [2, 3] | [1, 2] |

  Scenario: list slicing with negative bounds
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS x RETURN [1, 2, 3, 4][-2..] AS a
      """
    Then the result should be, in any order:
      | a      |
      | [3, 4] |

  Scenario: size of a list literal
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS x RETURN size([1, 2, 3]) AS s
      """
    Then the result should be, in any order:
      | s |
      | 3 |

  Scenario: UNWIND over a list produces one row per element
    Given an empty graph
    When executing query:
      """
      UNWIND [10, 20, 30] AS v RETURN v
      """
    Then the result should be, in any order:
      | v  |
      | 10 |
      | 20 |
      | 30 |

  Scenario: UNWIND of an empty list produces no rows
    Given an empty graph
    When executing query:
      """
      UNWIND [] AS v RETURN v
      """
    Then the result should be empty

  Scenario: UNWIND of null produces no rows
    Given an empty graph
    And having executed:
      """
      CREATE (:P)
      """
    When executing query:
      """
      MATCH (p:P) UNWIND p.missing AS v RETURN v
      """
    Then the result should be empty

  Scenario: UNWIND preserves duplicates
    Given an empty graph
    When executing query:
      """
      UNWIND [1, 1, 2] AS v RETURN v
      """
    Then the result should be, in any order:
      | v |
      | 1 |
      | 1 |
      | 2 |

  Scenario: nested UNWIND forms the cross product
    Given an empty graph
    When executing query:
      """
      UNWIND [1, 2] AS a UNWIND [10, 20] AS b RETURN a, b
      """
    Then the result should be, in any order:
      | a | b  |
      | 1 | 10 |
      | 1 | 20 |
      | 2 | 10 |
      | 2 | 20 |

  Scenario: list equality is elementwise
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS x RETURN [1, 2] = [1, 2] AS a, [1, 2] = [2, 1] AS b
      """
    Then the result should be, in any order:
      | a    | b     |
      | true | false |

  Scenario: list of strings round-trips
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS x RETURN ['a', 'b'] AS l
      """
    Then the result should be, in any order:
      | l          |
      | ['a', 'b'] |

  Scenario: collect builds a list that UNWIND flattens back
    Given an empty graph
    And having executed:
      """
      CREATE (:P {v: 1}), (:P {v: 2})
      """
    When executing query:
      """
      MATCH (p:P) WITH collect(p.v) AS l UNWIND l AS v RETURN v ORDER BY v
      """
    Then the result should be, in order:
      | v |
      | 1 |
      | 2 |

  Scenario: range function produces an inclusive sequence
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS x RETURN range(1, 4) AS l
      """
    Then the result should be, in any order:
      | l            |
      | [1, 2, 3, 4] |

  Scenario: range with a step
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS x RETURN range(0, 6, 2) AS l
      """
    Then the result should be, in any order:
      | l            |
      | [0, 2, 4, 6] |

  Scenario: head last and tail of a list
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS x
      RETURN head([1, 2, 3]) AS h, last([1, 2, 3]) AS l, tail([1, 2, 3]) AS t
      """
    Then the result should be, in any order:
      | h | l | t      |
      | 1 | 3 | [2, 3] |

  Scenario: head and last of an empty list are null
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS x RETURN head([]) AS h, last([]) AS l
      """
    Then the result should be, in any order:
      | h    | l    |
      | null | null |

  Scenario: reverse of a list
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS x RETURN reverse([1, 2, 3]) AS r
      """
    Then the result should be, in any order:
      | r         |
      | [3, 2, 1] |

  Scenario: list concatenation with plus
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS x RETURN [1, 2] + [3] AS l
      """
    Then the result should be, in any order:
      | l         |
      | [1, 2, 3] |

  Scenario: IN over a list parameter
    Given an empty graph
    And parameters are:
      | xs | [1, 3] |
    When executing query:
      """
      UNWIND [1, 2, 3] AS v WITH v WHERE v IN $xs RETURN v
      """
    Then the result should be, in any order:
      | v |
      | 1 |
      | 3 |

Feature: Pattern matching shapes

  Scenario: undirected pattern matches both orientations
    Given an empty graph
    And having executed:
      """
      CREATE (:P {n: 'a'})-[:R]->(:P {n: 'b'})
      """
    When executing query:
      """
      MATCH (x:P)-[:R]-(y:P) RETURN x.n AS x, y.n AS y
      """
    Then the result should be, in any order:
      | x   | y   |
      | 'a' | 'b' |
      | 'b' | 'a' |

  Scenario: self loop matches with both endpoints bound to the same node
    Given an empty graph
    And having executed:
      """
      CREATE (a:P {n: 'a'})-[:R]->(a)
      """
    When executing query:
      """
      MATCH (x:P)-[:R]->(y:P) RETURN x.n AS x, y.n AS y
      """
    Then the result should be, in any order:
      | x   | y   |
      | 'a' | 'a' |

  Scenario: repeated node variable forces a cycle
    Given an empty graph
    And having executed:
      """
      CREATE (a:P {n: 'a'})-[:R]->(b:P {n: 'b'}), (b)-[:R]->(a),
             (b)-[:R]->(:P {n: 'c'})
      """
    When executing query:
      """
      MATCH (x)-[:R]->(y)-[:R]->(x) RETURN x.n AS x, y.n AS y
      """
    Then the result should be, in any order:
      | x   | y   |
      | 'a' | 'b' |
      | 'b' | 'a' |

  Scenario: two comma patterns share bound variables
    Given an empty graph
    And having executed:
      """
      CREATE (a:P {n: 'a'})-[:R]->(b:Q), (a)-[:S]->(:T)
      """
    When executing query:
      """
      MATCH (x:P)-[:R]->(q:Q), (x)-[:S]->(t:T) RETURN x.n AS n
      """
    Then the result should be, in any order:
      | n   |
      | 'a' |

  Scenario: disconnected comma patterns form a cartesian product
    Given an empty graph
    And having executed:
      """
      CREATE (:A {v: 1}), (:A {v: 2}), (:B {w: 10})
      """
    When executing query:
      """
      MATCH (a:A), (b:B) RETURN a.v AS v, b.w AS w
      """
    Then the result should be, in any order:
      | v | w  |
      | 1 | 10 |
      | 2 | 10 |

  Scenario: multiple labels on a node pattern require all of them
    Given an empty graph
    And having executed:
      """
      CREATE (:A:B {n: 'ab'}), (:A {n: 'a'}), (:B {n: 'b'})
      """
    When executing query:
      """
      MATCH (x:A:B) RETURN x.n AS n
      """
    Then the result should be, in any order:
      | n    |
      | 'ab' |

  Scenario: relationship type alternation
    Given an empty graph
    And having executed:
      """
      CREATE (a:P {n: 'a'})-[:R]->(:Q {n: 'q1'}), (a)-[:S]->(:Q {n: 'q2'}),
             (a)-[:T]->(:Q {n: 'q3'})
      """
    When executing query:
      """
      MATCH (:P)-[:R|S]->(q:Q) RETURN q.n AS n
      """
    Then the result should be, in any order:
      | n    |
      | 'q1' |
      | 'q2' |

  Scenario: relationship property predicate in the pattern
    Given an empty graph
    And having executed:
      """
      CREATE (a:P)-[:R {w: 1}]->(:Q {n: 'light'}), (a)-[:R {w: 9}]->(:Q {n: 'heavy'})
      """
    When executing query:
      """
      MATCH (:P)-[r:R {w: 9}]->(q:Q) RETURN q.n AS n
      """
    Then the result should be, in any order:
      | n       |
      | 'heavy' |

  Scenario: node property map predicate in the pattern
    Given an empty graph
    And having executed:
      """
      CREATE (:P {n: 'x', v: 1}), (:P {n: 'y', v: 2})
      """
    When executing query:
      """
      MATCH (p:P {v: 2}) RETURN p.n AS n
      """
    Then the result should be, in any order:
      | n   |
      | 'y' |

  Scenario: anonymous intermediate nodes are not deduplicated
    Given an empty graph
    And having executed:
      """
      CREATE (a:P {n: 'a'})-[:R]->(:Q), (a)-[:R]->(:Q)
      """
    When executing query:
      """
      MATCH (p:P)-[:R]->() RETURN p.n AS n
      """
    Then the result should be, in any order:
      | n   |
      | 'a' |
      | 'a' |

  Scenario: incoming direction arrowhead
    Given an empty graph
    And having executed:
      """
      CREATE (:P {n: 'src'})-[:R]->(:P {n: 'dst'})
      """
    When executing query:
      """
      MATCH (x)<-[:R]-(y) RETURN x.n AS x, y.n AS y
      """
    Then the result should be, in any order:
      | x     | y     |
      | 'dst' | 'src' |

  Scenario: relationship uniqueness within one MATCH
    Given an empty graph
    And having executed:
      """
      CREATE (a:P {n: 'a'})-[:R]->(b:P {n: 'b'}), (b)-[:R]->(a)
      """
    When executing query:
      """
      MATCH (x)-[r1:R]->(y)-[r2:R]->(x) WHERE x.n = 'a' RETURN x.n AS n
      """
    Then the result should be, in any order:
      | n   |
      | 'a' |

  Scenario: same relationship cannot be used twice in one pattern
    Given an empty graph
    And having executed:
      """
      CREATE (a:P {n: 'a'})-[:R]->(a)
      """
    When executing query:
      """
      MATCH (x)-[r1:R]->(x)-[r2:R]->(x) RETURN x.n AS n
      """
    Then the result should be empty

  Scenario: var-length lower bound zero includes the start node
    Given an empty graph
    And having executed:
      """
      CREATE (a:P {n: 'a'})-[:R]->(:P {n: 'b'})
      """
    When executing query:
      """
      MATCH (x:P {n: 'a'})-[:R*0..1]->(y) RETURN y.n AS n
      """
    Then the result should be, in any order:
      | n   |
      | 'a' |
      | 'b' |

  Scenario: var-length exact bound
    Given an empty graph
    And having executed:
      """
      CREATE (:P {n: 'a'})-[:R]->(:P {n: 'b'})-[:R]->(:P {n: 'c'})
      """
    When executing query:
      """
      MATCH (x:P {n: 'a'})-[:R*2..2]->(y) RETURN y.n AS n
      """
    Then the result should be, in any order:
      | n   |
      | 'c' |

  Scenario: var-length undirected walks both ways without edge reuse
    Given an empty graph
    And having executed:
      """
      CREATE (a:P {n: 'a'})-[:R]->(b:P {n: 'b'})
      """
    When executing query:
      """
      MATCH (x:P {n: 'a'})-[:R*1..2]-(y) RETURN y.n AS n
      """
    Then the result should be, in any order:
      | n   |
      | 'b' |

  Scenario: matching a label that does not exist yields nothing
    Given an empty graph
    And having executed:
      """
      CREATE (:P)
      """
    When executing query:
      """
      MATCH (x:Nope) RETURN x
      """
    Then the result should be empty

  Scenario: match returns whole nodes structurally
    Given an empty graph
    And having executed:
      """
      CREATE (:P {n: 'a', v: 1})
      """
    When executing query:
      """
      MATCH (p:P) RETURN p
      """
    Then the result should be, in any order:
      | p                   |
      | (:P {n: 'a', v: 1}) |

  Scenario: match returns whole relationships structurally
    Given an empty graph
    And having executed:
      """
      CREATE (:P)-[:R {w: 2}]->(:Q)
      """
    When executing query:
      """
      MATCH ()-[r:R]->() RETURN r
      """
    Then the result should be, in any order:
      | r           |
      | [:R {w: 2}] |

  Scenario: longer chain across mixed labels
    Given an empty graph
    And having executed:
      """
      CREATE (:A {n: 1})-[:R]->(:B {n: 2})-[:S]->(:C {n: 3})-[:T]->(:D {n: 4})
      """
    When executing query:
      """
      MATCH (a:A)-[:R]->(b)-[:S]->(c)-[:T]->(d:D)
      RETURN a.n AS a, b.n AS b, c.n AS c, d.n AS d
      """
    Then the result should be, in any order:
      | a | b | c | d |
      | 1 | 2 | 3 | 4 |

Feature: Null propagation through operators and functions

  Scenario: equality with null is null on both sides
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS x RETURN null = 1 AS a, 1 = null AS b, null = null AS c
      """
    Then the result should be, in any order:
      | a    | b    | c    |
      | null | null | null |

  Scenario: inequality with null is null
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS x RETURN null <> 1 AS a, null <> null AS b
      """
    Then the result should be, in any order:
      | a    | b    |
      | null | null |

  Scenario: ordering comparisons with null are null
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS x
      RETURN null < 1 AS a, null <= 1 AS b, null > 1 AS c, null >= null AS d
      """
    Then the result should be, in any order:
      | a    | b    | c    | d    |
      | null | null | null | null |

  Scenario: NOT null is null
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS x RETURN NOT null AS a
      """
    Then the result should be, in any order:
      | a    |
      | null |

  Scenario: AND truth table with null
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS x
      RETURN (true AND null) AS a, (false AND null) AS b, (null AND null) AS c
      """
    Then the result should be, in any order:
      | a    | b     | c    |
      | null | false | null |

  Scenario: OR truth table with null
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS x
      RETURN (true OR null) AS a, (false OR null) AS b, (null OR null) AS c
      """
    Then the result should be, in any order:
      | a    | b    | c    |
      | true | null | null |

  Scenario: XOR with null is null
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS x RETURN (true XOR null) AS a, (false XOR null) AS b
      """
    Then the result should be, in any order:
      | a    | b    |
      | null | null |

  Scenario: arithmetic operators all propagate null
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS x
      RETURN null + 1 AS a, 1 - null AS b, null * 2 AS c, 4 / null AS d,
             null % 3 AS e
      """
    Then the result should be, in any order:
      | a    | b    | c    | d    | e    |
      | null | null | null | null | null |

  Scenario: unary minus of null is null
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS x RETURN -null AS a
      """
    Then the result should be, in any order:
      | a    |
      | null |

  Scenario: IS NULL and IS NOT NULL are never null
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS x
      RETURN null IS NULL AS a, null IS NOT NULL AS b,
             1 IS NULL AS c, 1 IS NOT NULL AS d
      """
    Then the result should be, in any order:
      | a    | b     | c     | d    |
      | true | false | false | true |

  Scenario: string predicates with null are null
    Given an empty graph
    And having executed:
      """
      CREATE (:P)
      """
    When executing query:
      """
      MATCH (p:P)
      RETURN p.s STARTS WITH 'a' AS a, p.s ENDS WITH 'a' AS b,
             p.s CONTAINS 'a' AS c
      """
    Then the result should be, in any order:
      | a    | b    | c    |
      | null | null | null |

  Scenario: coalesce returns the first non-null value
    Given an empty graph
    And having executed:
      """
      CREATE (:P {b: 2})
      """
    When executing query:
      """
      MATCH (p:P) RETURN coalesce(p.a, p.b, 99) AS v
      """
    Then the result should be, in any order:
      | v |
      | 2 |

  Scenario: coalesce of all nulls is null
    Given an empty graph
    And having executed:
      """
      CREATE (:P)
      """
    When executing query:
      """
      MATCH (p:P) RETURN coalesce(p.a, p.b) AS v
      """
    Then the result should be, in any order:
      | v    |
      | null |

  Scenario: toUpper of null is null
    Given an empty graph
    And having executed:
      """
      CREATE (:P)
      """
    When executing query:
      """
      MATCH (p:P) RETURN toUpper(p.s) AS u, toLower(p.s) AS l
      """
    Then the result should be, in any order:
      | u    | l    |
      | null | null |

  Scenario: size of null is null
    Given an empty graph
    And having executed:
      """
      CREATE (:P)
      """
    When executing query:
      """
      MATCH (p:P) RETURN size(p.s) AS s
      """
    Then the result should be, in any order:
      | s    |
      | null |

  Scenario: abs and sqrt of null are null
    Given an empty graph
    And having executed:
      """
      CREATE (:P)
      """
    When executing query:
      """
      MATCH (p:P) RETURN abs(p.x) AS a, sqrt(p.x) AS b
      """
    Then the result should be, in any order:
      | a    | b    |
      | null | null |

  Scenario: null IN a list is null
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS x RETURN null IN [1, 2] AS a
      """
    Then the result should be, in any order:
      | a    |
      | null |

  Scenario: value found in a list containing null is true
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS x RETURN 1 IN [1, null] AS a
      """
    Then the result should be, in any order:
      | a    |
      | true |

  Scenario: value not found in a list containing null is null
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS x RETURN 2 IN [1, null] AS a
      """
    Then the result should be, in any order:
      | a    |
      | null |

  Scenario: value not found in a null-free list is false
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS x RETURN 3 IN [1, 2] AS a
      """
    Then the result should be, in any order:
      | a     |
      | false |

  Scenario: WHERE treats null as false
    Given an empty graph
    And having executed:
      """
      CREATE (:P {n: 'a'}), (:P {n: 'b', flag: true}), (:P {n: 'c', flag: false})
      """
    When executing query:
      """
      MATCH (p:P) WHERE p.flag RETURN p.n AS n
      """
    Then the result should be, in any order:
      | n   |
      | 'b' |

  Scenario: property access on a null entity is null
    Given an empty graph
    And having executed:
      """
      CREATE (:P {n: 'solo'})
      """
    When executing query:
      """
      MATCH (p:P) OPTIONAL MATCH (p)-[:R]->(q) RETURN p.n AS n, q.x AS x
      """
    Then the result should be, in any order:
      | n      | x    |
      | 'solo' | null |

  Scenario: null modulo and division keep null even with zero divisor
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS x RETURN null / 0 AS a, null % 0 AS b
      """
    Then the result should be, in any order:
      | a    | b    |
      | null | null |

  Scenario: CASE with null condition takes the default
    Given an empty graph
    And having executed:
      """
      CREATE (:P)
      """
    When executing query:
      """
      MATCH (p:P) RETURN CASE WHEN p.x > 1 THEN 'big' ELSE 'dunno' END AS v
      """
    Then the result should be, in any order:
      | v       |
      | 'dunno' |

  Scenario: CASE without default yields null when nothing matches
    Given an empty graph
    When executing query:
      """
      UNWIND [5] AS x RETURN CASE WHEN x < 3 THEN 'small' END AS v
      """
    Then the result should be, in any order:
      | v    |
      | null |

  Scenario: equality between different types is false not null
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS x RETURN 1 = 'a' AS a, true = 1 AS b, 'a' = false AS c
      """
    Then the result should be, in any order:
      | a     | b     | c     |
      | false | false | false |

  Scenario: integer and float compare numerically
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS x RETURN 1 = 1.0 AS a, 2 > 1.5 AS b, 1.0 < 2 AS c
      """
    Then the result should be, in any order:
      | a    | b    | c    |
      | true | true | true |

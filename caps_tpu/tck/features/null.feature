Feature: Null semantics

  Scenario: null equality is null and filters the row
    Given an empty graph
    And having executed:
      """
      CREATE (:P {n: 'a'}), (:P {n: 'b', x: 1})
      """
    When executing query:
      """
      MATCH (p:P) WHERE p.x = p.x RETURN p.n AS n
      """
    Then the result should be, in any order:
      | n   |
      | 'b' |

  Scenario: null inequality also filters
    Given an empty graph
    And having executed:
      """
      CREATE (:P {n: 'a'})
      """
    When executing query:
      """
      MATCH (p:P) WHERE p.x <> 1 RETURN p.n AS n
      """
    Then the result should be empty

  Scenario: arithmetic with null is null
    Given an empty graph
    And having executed:
      """
      CREATE (:P)
      """
    When executing query:
      """
      MATCH (p:P) RETURN p.x + 1 AS a, p.x * 2 AS b
      """
    Then the result should be, in any order:
      | a    | b    |
      | null | null |

  Scenario: three-valued OR short-circuits through null
    Given an empty graph
    And having executed:
      """
      CREATE (:P {n: 'a', keep: true}), (:P {n: 'b'})
      """
    When executing query:
      """
      MATCH (p:P) WHERE p.keep OR p.missing = 1 RETURN p.n AS n
      """
    Then the result should be, in any order:
      | n   |
      | 'a' |

  Scenario: three-valued AND with a false operand is false not null
    Given an empty graph
    And having executed:
      """
      CREATE (:P {n: 'a', f: false})
      """
    When executing query:
      """
      MATCH (p:P) WHERE NOT (p.f AND p.missing = 1) RETURN p.n AS n
      """
    Then the result should be, in any order:
      | n   |
      | 'a' |

  Scenario: IN with null element yields null when no match
    Given an empty graph
    And having executed:
      """
      CREATE (:P {x: 1}), (:P {x: 9})
      """
    When executing query:
      """
      MATCH (p:P) WHERE p.x IN [1, p.missing] RETURN p.x AS x
      """
    Then the result should be, in any order:
      | x |
      | 1 |

  Scenario: returning a missing property yields null
    Given an empty graph
    And having executed:
      """
      CREATE (:P {n: 'a'})
      """
    When executing query:
      """
      MATCH (p:P) RETURN p.nope AS v
      """
    Then the result should be, in any order:
      | v    |
      | null |

  Scenario: null propagates through arithmetic
    Given an empty graph
    And having executed:
      """
      CREATE (:N {v: 1}), (:N {v: 2}), (:N)
      """
    When executing query:
      """
      MATCH (n:N) RETURN n.v + 1 AS plus, n.v AS raw
      """
    Then the result should be, in any order:
      | plus | raw  |
      | 2    | 1    |
      | 3    | 2    |
      | null | null |

  Scenario: IN with a null element is null when no match is found
    Given an empty graph
    And having executed:
      """
      CREATE (:N {v: 1}), (:N {v: 3})
      """
    When executing query:
      """
      MATCH (n:N) RETURN n.v AS v, n.v IN [1, null] AS found
      """
    Then the result should be, in any order:
      | v | found |
      | 1 | true  |
      | 3 | null  |

  Scenario: comparison with null is null and filters the row out
    Given an empty graph
    And having executed:
      """
      CREATE (:N {v: 1}), (:N)
      """
    When executing query:
      """
      MATCH (n:N) WHERE n.v > 0 RETURN n.v AS v
      """
    Then the result should be, in any order:
      | v |
      | 1 |

  Scenario: coalesce returns the first non-null argument per row
    Given an empty graph
    And having executed:
      """
      CREATE (:N {a: 1}), (:N {b: 2}), (:N)
      """
    When executing query:
      """
      MATCH (n:N) RETURN coalesce(n.a, n.b, -1) AS c
      """
    Then the result should be, in any order:
      | c  |
      | 1  |
      | 2  |
      | -1 |

  Scenario: min and max ignore nulls and are null over only-null input
    Given an empty graph
    And having executed:
      """
      CREATE (:N {v: 5}), (:N), (:M)
      """
    When executing query:
      """
      MATCH (n:N) OPTIONAL MATCH (m:Missing) RETURN min(n.v) AS lo, max(m) AS hi
      """
    Then the result should be, in any order:
      | lo | hi   |
      | 5  | null |

  Scenario: count of an expression skips nulls while count star does not
    Given an empty graph
    And having executed:
      """
      CREATE (:N {v: 1}), (:N {v: 2}), (:N)
      """
    When executing query:
      """
      MATCH (n:N) RETURN count(n.v) AS cv, count(*) AS cs
      """
    Then the result should be, in any order:
      | cv | cs |
      | 2  | 3  |

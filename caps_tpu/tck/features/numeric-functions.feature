Feature: Numeric functions and arithmetic semantics

  Scenario: abs of negative int and float
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS x RETURN abs(-5) AS a, abs(-2.5) AS b, abs(3) AS c
      """
    Then the result should be, in any order:
      | a | b   | c |
      | 5 | 2.5 | 3 |

  Scenario: sign function
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS x RETURN sign(-7) AS a, sign(0) AS b, sign(4) AS c
      """
    Then the result should be, in any order:
      | a  | b | c |
      | -1 | 0 | 1 |

  Scenario: sqrt of a perfect square is float
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS x RETURN sqrt(9) AS a
      """
    Then the result should be, in any order:
      | a   |
      | 3.0 |

  Scenario: ceil and floor
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS x
      RETURN ceil(2.1) AS a, floor(2.9) AS b, ceil(-2.1) AS c, floor(-2.1) AS d
      """
    Then the result should be, in any order:
      | a   | b   | c    | d    |
      | 3.0 | 2.0 | -2.0 | -3.0 |

  Scenario: round to nearest
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS x RETURN round(2.4) AS a, round(2.5) AS b
      """
    Then the result should be, in any order:
      | a   | b   |
      | 2.0 | 3.0 |

  Scenario: integer division truncates toward zero
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS x RETURN 7 / 2 AS a, -7 / 2 AS b
      """
    Then the result should be, in any order:
      | a | b  |
      | 3 | -3 |

  Scenario: float division keeps fractions
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS x RETURN 7.0 / 2 AS a, 7 / 2.0 AS b
      """
    Then the result should be, in any order:
      | a   | b   |
      | 3.5 | 3.5 |

  Scenario: modulo follows the dividend sign
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS x RETURN 7 % 3 AS a, -7 % 3 AS b, 7 % -3 AS c
      """
    Then the result should be, in any order:
      | a | b  | c |
      | 1 | -1 | 1 |

  Scenario: power is float valued
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS x RETURN 2 ^ 10 AS a, 4 ^ 0.5 AS b
      """
    Then the result should be, in any order:
      | a      | b   |
      | 1024.0 | 2.0 |

  Scenario: toInteger truncates floats
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS x RETURN toInteger(2.9) AS a, toInteger(-2.9) AS b
      """
    Then the result should be, in any order:
      | a | b  |
      | 2 | -2 |

  Scenario: toFloat widens integers
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS x RETURN toFloat(3) AS a
      """
    Then the result should be, in any order:
      | a   |
      | 3.0 |

  Scenario: operator precedence multiplication before addition
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS x RETURN 2 + 3 * 4 AS a, (2 + 3) * 4 AS b
      """
    Then the result should be, in any order:
      | a  | b  |
      | 14 | 20 |

  Scenario: unary minus binds tighter than comparison
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS x RETURN -2 < 1 AS a, -(2 + 1) AS b
      """
    Then the result should be, in any order:
      | a    | b  |
      | true | -3 |

  Scenario: large integers survive round trips
    Given an empty graph
    And having executed:
      """
      CREATE (:P {big: 9007199254740993})
      """
    When executing query:
      """
      MATCH (p:P) RETURN p.big AS b, p.big + 1 AS b1
      """
    Then the result should be, in any order:
      | b                | b1               |
      | 9007199254740993 | 9007199254740994 |

  Scenario: negative zero float equals zero
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS x RETURN -0.0 = 0.0 AS a
      """
    Then the result should be, in any order:
      | a    |
      | true |

  Scenario: log and exp invert
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS x RETURN round(log(exp(2.0)) * 10) AS a
      """
    Then the result should be, in any order:
      | a    |
      | 20.0 |

  Scenario: integer division by zero raises an error
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS x RETURN 1 / 0 AS a
      """
    Then a ArithmeticError should be raised

  Scenario: arithmetic on booleans is not implicit
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS x RETURN true + true AS a
      """
    Then the result should be, in any order:
      | a |
      | 2 |

  Scenario: parameter arithmetic
    Given an empty graph
    And parameters are:
      | n | 4 |
    When executing query:
      """
      UNWIND [1] AS x RETURN $n * 2 AS a, $n % 3 AS b
      """
    Then the result should be, in any order:
      | a | b |
      | 8 | 1 |

  Scenario: aggregates over computed numeric functions
    Given an empty graph
    When executing query:
      """
      UNWIND [-2, -1, 3] AS v RETURN sum(abs(v)) AS s, max(sign(v)) AS m
      """
    Then the result should be, in any order:
      | s | m |
      | 6 | 1 |

  Scenario: float formatting preserves integral floats
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS x RETURN 1.0 + 2.0 AS a
      """
    Then the result should be, in any order:
      | a   |
      | 3.0 |

  Scenario: mixed numeric comparison chain in WHERE
    Given an empty graph
    When executing query:
      """
      UNWIND [0.5, 1, 1.5, 2] AS v WITH v WHERE v >= 1 AND v < 2 RETURN v
      """
    Then the result should be, in any order:
      | v   |
      | 1   |
      | 1.5 |

  Scenario: integer overflow boundary stays exact at 2^53
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS x
      RETURN 9007199254740992 + 1 = 9007199254740992 AS collides
      """
    Then the result should be, in any order:
      | collides |
      | false    |

  Scenario: round half away from zero on negative numbers
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS x RETURN round(-2.5) AS a
      """
    Then the result should be, in any order:
      | a    |
      | -2.0 |

  Scenario: data-dependent integer division by zero raises an error
    Given an empty graph
    And having executed:
      """
      CREATE (:P {v: 0}), (:P {v: 2})
      """
    When executing query:
      """
      MATCH (p:P) RETURN 10 / p.v AS a
      """
    Then a ArithmeticError should be raised

  Scenario: modulo by zero raises an error
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS x RETURN 5 % 0 AS a
      """
    Then a ArithmeticError should be raised

  Scenario: division guarded by WHERE does not raise
    Given an empty graph
    And having executed:
      """
      CREATE (:P {v: 0}), (:P {v: 2})
      """
    When executing query:
      """
      MATCH (p:P) WHERE p.v > 0 RETURN 10 / p.v AS a
      """
    Then the result should be, in any order:
      | a |
      | 5 |

  Scenario: division by a null divisor is null not an error
    Given an empty graph
    And having executed:
      """
      CREATE (:P)
      """
    When executing query:
      """
      MATCH (p:P) RETURN 10 / p.missing AS a
      """
    Then the result should be, in any order:
      | a    |
      | null |

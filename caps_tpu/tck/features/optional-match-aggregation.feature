Feature: OPTIONAL MATCH interacting with aggregation and predicates

  Scenario: count of a null-padded variable skips the null
    Given an empty graph
    And having executed:
      """
      CREATE (a:P {n: 'a'})-[:R]->(:Q), (:P {n: 'b'})
      """
    When executing query:
      """
      MATCH (p:P) OPTIONAL MATCH (p)-[:R]->(q:Q)
      RETURN p.n AS n, count(q) AS c
      """
    Then the result should be, in any order:
      | n   | c |
      | 'a' | 1 |
      | 'b' | 0 |

  Scenario: count star counts null-padded rows
    Given an empty graph
    And having executed:
      """
      CREATE (a:P {n: 'a'})-[:R]->(:Q), (:P {n: 'b'})
      """
    When executing query:
      """
      MATCH (p:P) OPTIONAL MATCH (p)-[:R]->(q:Q)
      RETURN p.n AS n, count(*) AS c
      """
    Then the result should be, in any order:
      | n   | c |
      | 'a' | 1 |
      | 'b' | 1 |

  Scenario: collect over optional rows skips nulls
    Given an empty graph
    And having executed:
      """
      CREATE (a:P)-[:R]->(:Q {v: 1}), (a)-[:R]->(:Q {v: 2}), (:P {n: 'lonely'})
      """
    When executing query:
      """
      MATCH (p:P) OPTIONAL MATCH (p)-[:R]->(q:Q)
      WITH p, q.v AS v ORDER BY v
      RETURN collect(v) AS l
      """
    Then the result should be, in any order:
      | l      |
      | [1, 2] |

  Scenario: WHERE inside OPTIONAL MATCH pads instead of filtering
    Given an empty graph
    And having executed:
      """
      CREATE (a:P {n: 'a'})-[:R]->(:Q {v: 1}), (b:P {n: 'b'})-[:R]->(:Q {v: 9})
      """
    When executing query:
      """
      MATCH (p:P) OPTIONAL MATCH (p)-[:R]->(q:Q) WHERE q.v < 5
      RETURN p.n AS n, q.v AS v
      """
    Then the result should be, in any order:
      | n   | v    |
      | 'a' | 1    |
      | 'b' | null |

  Scenario: WHERE after WITH filters the padded rows
    Given an empty graph
    And having executed:
      """
      CREATE (a:P {n: 'a'})-[:R]->(:Q {v: 1}), (:P {n: 'b'})
      """
    When executing query:
      """
      MATCH (p:P) OPTIONAL MATCH (p)-[:R]->(q:Q)
      WITH p, q WHERE q IS NOT NULL RETURN p.n AS n
      """
    Then the result should be, in any order:
      | n   |
      | 'a' |

  Scenario: two OPTIONAL MATCHes pad independently
    Given an empty graph
    And having executed:
      """
      CREATE (a:P {n: 'a'})-[:R]->(:Q {v: 1}), (a)-[:S]->(:T {w: 2}),
             (:P {n: 'b'})
      """
    When executing query:
      """
      MATCH (p:P)
      OPTIONAL MATCH (p)-[:R]->(q:Q)
      OPTIONAL MATCH (p)-[:S]->(t:T)
      RETURN p.n AS n, q.v AS v, t.w AS w
      """
    Then the result should be, in any order:
      | n   | v    | w    |
      | 'a' | 1    | 2    |
      | 'b' | null | null |

  Scenario: min and max over an all-null optional column are null
    Given an empty graph
    And having executed:
      """
      CREATE (:P {n: 'a'}), (:P {n: 'b'})
      """
    When executing query:
      """
      MATCH (p:P) OPTIONAL MATCH (p)-[:R]->(q:Q)
      RETURN min(q.v) AS mn, max(q.v) AS mx, sum(q.v) AS s
      """
    Then the result should be, in any order:
      | mn   | mx   | s |
      | null | null | 0 |

  Scenario: avg ignores nulls in the mix
    Given an empty graph
    And having executed:
      """
      CREATE (a:P)-[:R]->(:Q {v: 2}), (a)-[:R]->(:Q), (a)-[:R]->(:Q {v: 4})
      """
    When executing query:
      """
      MATCH (:P)-[:R]->(q:Q) RETURN avg(q.v) AS a, count(q.v) AS c
      """
    Then the result should be, in any order:
      | a   | c |
      | 3.0 | 2 |

  Scenario: optional variable usable in later expressions
    Given an empty graph
    And having executed:
      """
      CREATE (a:P {n: 'a'})-[:R]->(:Q {v: 10}), (:P {n: 'b'})
      """
    When executing query:
      """
      MATCH (p:P) OPTIONAL MATCH (p)-[:R]->(q:Q)
      RETURN p.n AS n, q.v + 1 AS v1
      """
    Then the result should be, in any order:
      | n   | v1   |
      | 'a' | 11   |
      | 'b' | null |

  Scenario: OPTIONAL MATCH on an empty graph yields one null row
    Given an empty graph
    When executing query:
      """
      OPTIONAL MATCH (n:Nothing) RETURN n
      """
    Then the result should be, in any order:
      | n    |
      | null |

  Scenario: grouping key can be a null-padded value
    Given an empty graph
    And having executed:
      """
      CREATE (a:P {n: 'a'})-[:R]->(:Q {g: 'x'}),
             (b:P {n: 'b'})-[:R]->(:Q {g: 'x'}), (:P {n: 'c'})
      """
    When executing query:
      """
      MATCH (p:P) OPTIONAL MATCH (p)-[:R]->(q:Q)
      RETURN q.g AS g, count(*) AS c
      """
    Then the result should be, in any order:
      | g    | c |
      | 'x'  | 2 |
      | null | 1 |

  Scenario: OPTIONAL MATCH relationship variable is null when unmatched
    Given an empty graph
    And having executed:
      """
      CREATE (:P {n: 'a'})
      """
    When executing query:
      """
      MATCH (p:P) OPTIONAL MATCH (p)-[r:R]->() RETURN r IS NULL AS isnull
      """
    Then the result should be, in any order:
      | isnull |
      | true   |

  Scenario: aggregation after optional var-length expand
    Given an empty graph
    And having executed:
      """
      CREATE (a:P {n: 'a'})-[:R]->(b:P {n: 'b'})-[:R]->(c:P {n: 'c'})
      """
    When executing query:
      """
      MATCH (p:P) OPTIONAL MATCH (p)-[:R*1..2]->(q:P)
      RETURN p.n AS n, count(q) AS c
      """
    Then the result should be, in any order:
      | n   | c |
      | 'a' | 2 |
      | 'b' | 1 |
      | 'c' | 0 |

Feature: Optional match

  Scenario: OPTIONAL MATCH pads non-matching rows with null
    Given an empty graph
    And having executed:
      """
      CREATE (a:P {n: 'a'}), (b:P {n: 'b'}), (a)-[:T]->(b)
      """
    When executing query:
      """
      MATCH (p:P) OPTIONAL MATCH (p)-[:T]->(q) RETURN p.n AS p, q.n AS q
      """
    Then the result should be, in any order:
      | p   | q    |
      | 'a' | 'b'  |
      | 'b' | null |

  Scenario: OPTIONAL MATCH that never matches returns all nulls
    Given an empty graph
    And having executed:
      """
      CREATE (:P {n: 'a'})
      """
    When executing query:
      """
      MATCH (p:P) OPTIONAL MATCH (p)-[:MISSING]->(q) RETURN p.n AS p, q AS q
      """
    Then the result should be, in any order:
      | p   | q    |
      | 'a' | null |

  Scenario: OPTIONAL MATCH with WHERE folds the predicate into the match
    Given an empty graph
    And having executed:
      """
      CREATE (a:P {n: 'a'}), (b:Q {v: 1}), (c:Q {v: 2}), (a)-[:T]->(b), (a)-[:T]->(c)
      """
    When executing query:
      """
      MATCH (p:P) OPTIONAL MATCH (p)-[:T]->(q:Q) WHERE q.v > 1 RETURN p.n AS p, q.v AS v
      """
    Then the result should be, in any order:
      | p   | v |
      | 'a' | 2 |

  Scenario: properties of an unmatched optional variable are null
    Given an empty graph
    And having executed:
      """
      CREATE (:P {n: 'solo'})
      """
    When executing query:
      """
      MATCH (p:P) OPTIONAL MATCH (p)-[:T]->(q) RETURN p.n AS p, q.n AS qn, q IS NULL AS missing
      """
    Then the result should be, in any order:
      | p      | qn   | missing |
      | 'solo' | null | true    |

  Scenario: uncorrelated OPTIONAL MATCH pairs every lhs row with every match
    Given an empty graph
    And having executed:
      """
      CREATE (:P {n: 'a'}), (:P {n: 'b'}), (:Q {v: 1}), (:Q {v: 2})
      """
    When executing query:
      """
      MATCH (p:P) OPTIONAL MATCH (q:Q) RETURN p.n AS p, q.v AS v
      """
    Then the result should be, in any order:
      | p   | v |
      | 'a' | 1 |
      | 'a' | 2 |
      | 'b' | 1 |
      | 'b' | 2 |

  Scenario: uncorrelated OPTIONAL MATCH over an empty pattern null-pads every lhs row
    Given an empty graph
    And having executed:
      """
      CREATE (:P {n: 'a'}), (:P {n: 'b'})
      """
    When executing query:
      """
      MATCH (p:P) OPTIONAL MATCH (q:Missing) RETURN p.n AS p, q AS q
      """
    Then the result should be, in any order:
      | p   | q    |
      | 'a' | null |
      | 'b' | null |

  Scenario: chained OPTIONAL MATCHes keep earlier nulls
    Given an empty graph
    And having executed:
      """
      CREATE (a:P {n: 'a'}), (b:P {n: 'b'}), (c:C {v: 7}), (a)-[:T]->(c)
      """
    When executing query:
      """
      MATCH (p:P)
      OPTIONAL MATCH (p)-[:T]->(c:C)
      OPTIONAL MATCH (c)-[:U]->(d)
      RETURN p.n AS p, c.v AS c, d AS d
      """
    Then the result should be, in any order:
      | p   | c    | d    |
      | 'a' | 7    | null |
      | 'b' | null | null |

  Scenario: aggregation over an OPTIONAL MATCH counts null matches as zero
    Given an empty graph
    And having executed:
      """
      CREATE (a:P {n: 'a'}), (b:P {n: 'b'}), (x:X), (a)-[:T]->(x)
      """
    When executing query:
      """
      MATCH (p:P) OPTIONAL MATCH (p)-[:T]->(x:X)
      RETURN p.n AS p, count(x) AS c
      """
    Then the result should be, in any order:
      | p   | c |
      | 'a' | 1 |
      | 'b' | 0 |

Feature: ORDER BY edge cases

  Scenario: nulls sort last ascending and first descending
    Given an empty graph
    And having executed:
      """
      CREATE (:P {v: 2}), (:P), (:P {v: 1})
      """
    When executing query:
      """
      MATCH (p:P) RETURN p.v AS v ORDER BY v
      """
    Then the result should be, in order:
      | v    |
      | 1    |
      | 2    |
      | null |

  Scenario: descending puts nulls first
    Given an empty graph
    And having executed:
      """
      CREATE (:P {v: 2}), (:P), (:P {v: 1})
      """
    When executing query:
      """
      MATCH (p:P) RETURN p.v AS v ORDER BY v DESC
      """
    Then the result should be, in order:
      | v    |
      | null |
      | 2    |
      | 1    |

  Scenario: mixed type ordering follows the global sort order
    Given an empty graph
    When executing query:
      """
      UNWIND ['b', 3, true, 'a', 1.5] AS v RETURN v ORDER BY v
      """
    Then the result should be, in order:
      | v     |
      | 'a'   |
      | 'b'   |
      | true  |
      | 1.5   |
      | 3     |

  Scenario: ORDER BY an expression over a pre-projection variable
    Given an empty graph
    And having executed:
      """
      CREATE (:P {a: 1, b: 9}), (:P {a: 2, b: 1})
      """
    When executing query:
      """
      MATCH (p:P) RETURN p.a AS a ORDER BY p.b
      """
    Then the result should be, in order:
      | a |
      | 2 |
      | 1 |

  Scenario: ORDER BY an alias shadowing a property expression
    Given an empty graph
    And having executed:
      """
      CREATE (:P {v: 1}), (:P {v: 3}), (:P {v: 2})
      """
    When executing query:
      """
      MATCH (p:P) RETURN -p.v AS v ORDER BY v
      """
    Then the result should be, in order:
      | v  |
      | -3 |
      | -2 |
      | -1 |

  Scenario: multi-key sort with mixed directions
    Given an empty graph
    And having executed:
      """
      CREATE (:P {g: 'a', v: 1}), (:P {g: 'a', v: 2}),
             (:P {g: 'b', v: 1})
      """
    When executing query:
      """
      MATCH (p:P) RETURN p.g AS g, p.v AS v ORDER BY g ASC, v DESC
      """
    Then the result should be, in order:
      | g   | v |
      | 'a' | 2 |
      | 'a' | 1 |
      | 'b' | 1 |

  Scenario: ORDER BY with SKIP and LIMIT slices the sorted stream
    Given an empty graph
    When executing query:
      """
      UNWIND [5, 3, 1, 4, 2] AS v RETURN v ORDER BY v SKIP 1 LIMIT 2
      """
    Then the result should be, in order:
      | v |
      | 2 |
      | 3 |

  Scenario: ORDER BY a list column sorts elementwise
    Given an empty graph
    When executing query:
      """
      UNWIND [[1, 2], [1], [2], []] AS l RETURN l ORDER BY l
      """
    Then the result should be, in order:
      | l      |
      | []     |
      | [1]    |
      | [1, 2] |
      | [2]    |

  Scenario: ORDER BY booleans false before true
    Given an empty graph
    When executing query:
      """
      UNWIND [true, false, true] AS b RETURN b ORDER BY b
      """
    Then the result should be, in order:
      | b     |
      | false |
      | true  |
      | true  |

  Scenario: ORDER BY after aggregation uses the aggregated value
    Given an empty graph
    And having executed:
      """
      CREATE (:P {g: 'a'}), (:P {g: 'a'}), (:P {g: 'b'})
      """
    When executing query:
      """
      MATCH (p:P) RETURN p.g AS g, count(*) AS c ORDER BY c DESC, g
      """
    Then the result should be, in order:
      | g   | c |
      | 'a' | 2 |
      | 'b' | 1 |

  Scenario: ORDER BY is stable for equal keys after WITH
    Given an empty graph
    When executing query:
      """
      UNWIND [3, 1, 2] AS v WITH v ORDER BY v
      RETURN collect(v) AS l
      """
    Then the result should be, in any order:
      | l         |
      | [1, 2, 3] |

  Scenario: SKIP 0 LIMIT 0 yields no rows
    Given an empty graph
    When executing query:
      """
      UNWIND [1, 2, 3] AS v RETURN v ORDER BY v SKIP 0 LIMIT 0
      """
    Then the result should be empty

  Scenario: negative LIMIT is an error
    Given an empty graph
    When executing query:
      """
      UNWIND [1, 2, 3] AS v RETURN v LIMIT -1
      """
    Then a SyntaxError should be raised at compile time: NegativeIntegerArgument

  Scenario: negative SKIP is an error
    Given an empty graph
    When executing query:
      """
      UNWIND [1, 2, 3] AS v RETURN v SKIP -2
      """
    Then a SyntaxError should be raised at compile time: NegativeIntegerArgument

Feature: ORDER BY, SKIP, LIMIT and cross-type comparability

  Scenario: nulls order last ascending
    Given an empty graph
    And having executed:
      """
      CREATE (:P {v: 2}), (:P), (:P {v: 1})
      """
    When executing query:
      """
      MATCH (p:P) RETURN p.v AS v ORDER BY v ASC
      """
    Then the result should be, in order:
      | v    |
      | 1    |
      | 2    |
      | null |

  Scenario: nulls order first descending
    Given an empty graph
    And having executed:
      """
      CREATE (:P {v: 2}), (:P), (:P {v: 1})
      """
    When executing query:
      """
      MATCH (p:P) RETURN p.v AS v ORDER BY v DESC
      """
    Then the result should be, in order:
      | v    |
      | null |
      | 2    |
      | 1    |

  Scenario: multi-key ordering applies keys left to right
    Given an empty graph
    And having executed:
      """
      CREATE (:P {a: 1, b: 2}), (:P {a: 1, b: 1}), (:P {a: 0, b: 9})
      """
    When executing query:
      """
      MATCH (p:P) RETURN p.a AS a, p.b AS b ORDER BY a ASC, b DESC
      """
    Then the result should be, in order:
      | a | b |
      | 0 | 9 |
      | 1 | 2 |
      | 1 | 1 |

  Scenario: ORDER BY a computed expression
    Given an empty graph
    When executing query:
      """
      UNWIND [3, 1, 2] AS v RETURN v ORDER BY -v
      """
    Then the result should be, in order:
      | v |
      | 3 |
      | 2 |
      | 1 |

  Scenario: ORDER BY boolean sorts false before true
    Given an empty graph
    When executing query:
      """
      UNWIND [true, false] AS v RETURN v ORDER BY v ASC
      """
    Then the result should be, in order:
      | v     |
      | false |
      | true  |

  Scenario: ORDER BY mixes ints and floats numerically
    Given an empty graph
    When executing query:
      """
      UNWIND [2.5, 1, 3, 0.5] AS v RETURN v ORDER BY v
      """
    Then the result should be, in order:
      | v   |
      | 0.5 |
      | 1   |
      | 2.5 |
      | 3   |

  Scenario: SKIP drops leading rows after ordering
    Given an empty graph
    When executing query:
      """
      UNWIND [5, 3, 1, 4, 2] AS v RETURN v ORDER BY v SKIP 2
      """
    Then the result should be, in order:
      | v |
      | 3 |
      | 4 |
      | 5 |

  Scenario: LIMIT keeps leading rows after ordering
    Given an empty graph
    When executing query:
      """
      UNWIND [5, 3, 1, 4, 2] AS v RETURN v ORDER BY v LIMIT 2
      """
    Then the result should be, in order:
      | v |
      | 1 |
      | 2 |

  Scenario: SKIP and LIMIT page through results
    Given an empty graph
    When executing query:
      """
      UNWIND [5, 3, 1, 4, 2] AS v RETURN v ORDER BY v SKIP 1 LIMIT 2
      """
    Then the result should be, in order:
      | v |
      | 2 |
      | 3 |

  Scenario: SKIP beyond the result size yields nothing
    Given an empty graph
    When executing query:
      """
      UNWIND [1, 2] AS v RETURN v SKIP 5
      """
    Then the result should be empty

  Scenario: LIMIT zero yields nothing
    Given an empty graph
    When executing query:
      """
      UNWIND [1, 2] AS v RETURN v LIMIT 0
      """
    Then the result should be empty

  Scenario: cross-type ordering comparison is null
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS x RETURN 1 < 'a' AS a, true < 1 AS b
      """
    Then the result should be, in any order:
      | a    | b    |
      | null | null |

  Scenario: cross-type WHERE comparison filters the row
    Given an empty graph
    And having executed:
      """
      CREATE (:P {v: 1}), (:P {v: 'str'})
      """
    When executing query:
      """
      MATCH (p:P) WHERE p.v > 0 RETURN p.v AS v
      """
    Then the result should be, in any order:
      | v |
      | 1 |

  Scenario: ORDER BY on strings is lexicographic
    Given an empty graph
    When executing query:
      """
      UNWIND ['pear', 'apple', 'fig'] AS v RETURN v ORDER BY v
      """
    Then the result should be, in order:
      | v       |
      | 'apple' |
      | 'fig'   |
      | 'pear'  |

  Scenario: ordering is stable across equal keys with a secondary key
    Given an empty graph
    And having executed:
      """
      CREATE (:P {g: 1, n: 'b'}), (:P {g: 1, n: 'a'}), (:P {g: 0, n: 'z'})
      """
    When executing query:
      """
      MATCH (p:P) RETURN p.g AS g, p.n AS n ORDER BY g, n
      """
    Then the result should be, in order:
      | g | n   |
      | 0 | 'z' |
      | 1 | 'a' |
      | 1 | 'b' |

  Scenario: LIMIT applies after aggregation
    Given an empty graph
    And having executed:
      """
      CREATE (:P {g: 'a'}), (:P {g: 'a'}), (:P {g: 'b'}), (:P {g: 'c'})
      """
    When executing query:
      """
      MATCH (p:P) RETURN p.g AS g, count(*) AS c ORDER BY c DESC, g LIMIT 2
      """
    Then the result should be, in order:
      | g   | c |
      | 'a' | 2 |
      | 'b' | 1 |

  Scenario: ORDER BY an alias defined in WITH
    Given an empty graph
    When executing query:
      """
      UNWIND [1, 2, 3] AS v WITH v * -1 AS neg RETURN neg ORDER BY neg
      """
    Then the result should be, in order:
      | neg |
      | -3  |
      | -2  |
      | -1  |

  Scenario: SKIP LIMIT inside WITH bounds intermediate cardinality
    Given an empty graph
    When executing query:
      """
      UNWIND [5, 4, 3, 2, 1] AS v WITH v ORDER BY v LIMIT 3
      RETURN sum(v) AS s
      """
    Then the result should be, in any order:
      | s |
      | 6 |

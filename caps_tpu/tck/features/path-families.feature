Feature: Path families — undirected, zero-hop, cyclic

  Scenario: undirected named path matches both orientations
    Given an empty graph
    And having executed:
      """
      CREATE (:A {n: 'a'})-[:T]->(:B {n: 'b'})
      """
    When executing query:
      """
      MATCH p = (x)-[:T]-(y) RETURN x.n AS x, y.n AS y, length(p) AS l
      """
    Then the result should be, in any order:
      | x   | y   | l |
      | 'a' | 'b' | 1 |
      | 'b' | 'a' | 1 |

  Scenario: zero-hop var-length path binds start node only
    Given an empty graph
    And having executed:
      """
      CREATE (:A {n: 'a'})-[:T]->(:B {n: 'b'})
      """
    When executing query:
      """
      MATCH p = (x:A)-[:T*0..1]->(y)
      RETURN y.n AS y, length(p) AS l
      """
    Then the result should be, in any order:
      | y   | l |
      | 'a' | 0 |
      | 'b' | 1 |

  Scenario: nodes of a zero-hop path is the single start node
    Given an empty graph
    And having executed:
      """
      CREATE (:A {n: 'a'})
      """
    When executing query:
      """
      MATCH p = (x:A) RETURN [n IN nodes(p) | n.n] AS ns,
                            size(relationships(p)) AS nr
      """
    Then the result should be, in any order:
      | ns    | nr |
      | ['a'] | 0  |

  Scenario: cyclic pattern with repeated node variable
    Given an empty graph
    And having executed:
      """
      CREATE (a:A {n: 'a'})-[:T]->(b:B)-[:T]->(a)
      """
    When executing query:
      """
      MATCH (x:A)-[:T]->(y)-[:T]->(x) RETURN x.n AS n
      """
    Then the result should be, in any order:
      | n   |
      | 'a' |

  Scenario: self-loop matches directed and counts once per direction undirected
    Given an empty graph
    And having executed:
      """
      CREATE (a:A {n: 'a'})-[:T]->(a)
      """
    When executing query:
      """
      MATCH (x:A)-[:T]->(x) RETURN x.n AS n
      """
    Then the result should be, in any order:
      | n   |
      | 'a' |

  Scenario: relationship isomorphism forbids reusing an edge in one match
    Given an empty graph
    And having executed:
      """
      CREATE (:A)-[:T]->(:B)
      """
    When executing query:
      """
      MATCH (x)-[r1:T]->(y)<-[r2:T]-(z) RETURN count(*) AS c
      """
    Then the result should be, in any order:
      | c |
      | 0 |

  Scenario: undirected var-length path does not retraverse the same edge
    Given an empty graph
    And having executed:
      """
      CREATE (:A {n: 'a'})-[:T]->(:B {n: 'b'})
      """
    When executing query:
      """
      MATCH (x:A)-[:T*2..2]-(y) RETURN count(*) AS c
      """
    Then the result should be, in any order:
      | c |
      | 0 |

  Scenario: path value through OPTIONAL MATCH is null on no match
    Given an empty graph
    And having executed:
      """
      CREATE (:A {n: 'a'})
      """
    When executing query:
      """
      MATCH (x:A) OPTIONAL MATCH p = (x)-[:T]->(y)
      RETURN x.n AS n, p IS NULL AS nop
      """
    Then the result should be, in any order:
      | n   | nop  |
      | 'a' | true |

  Scenario: two named paths in one MATCH are independent values
    Given an empty graph
    And having executed:
      """
      CREATE (:A {n: 'a'})-[:T]->(:B {n: 'b'})-[:S]->(:C {n: 'c'})
      """
    When executing query:
      """
      MATCH p = (x:A)-[:T]->(y), q = (y)-[:S]->(z)
      RETURN length(p) AS lp, length(q) AS lq,
             [n IN nodes(q) | n.n] AS qn
      """
    Then the result should be, in any order:
      | lp | lq | qn         |
      | 1  | 1  | ['b', 'c'] |

  Scenario: path equality compares start and relationship sequence
    Given an empty graph
    And having executed:
      """
      CREATE (:A {n: 'a'})-[:T]->(:B {n: 'b'})
      """
    When executing query:
      """
      MATCH p = (x:A)-[:T]->(y)
      MATCH q = (x)-[:T]->(y)
      RETURN p = q AS eq
      """
    Then the result should be, in any order:
      | eq   |
      | true |

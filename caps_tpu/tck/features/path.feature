Feature: Named paths

  Scenario: returning a single-hop path
    Given an empty graph
    And having executed:
      """
      CREATE (a:A {name: 'a'})-[:T]->(b:B {name: 'b'})
      """
    When executing query:
      """
      MATCH p = (a:A)-[:T]->(b) RETURN p
      """
    Then the result should be, in any order:
      | p                                           |
      | <(:A {name: 'a'})-[:T]->(:B {name: 'b'})>   |

  Scenario: returning a path matched against the stored direction
    Given an empty graph
    And having executed:
      """
      CREATE (a:A)-[:T]->(b:B)
      """
    When executing query:
      """
      MATCH p = (b:B)<-[:T]-(a:A) RETURN p
      """
    Then the result should be, in any order:
      | p                     |
      | <(:B)<-[:T]-(:A)>     |

  Scenario: zero-hop path is a single node
    Given an empty graph
    And having executed:
      """
      CREATE (a:A {name: 'a'})
      """
    When executing query:
      """
      MATCH p = (a:A) RETURN p, length(p)
      """
    Then the result should be, in any order:
      | p                  | length(p) |
      | <(:A {name: 'a'})> | 0         |

  Scenario: length of a fixed two-hop path
    Given an empty graph
    And having executed:
      """
      CREATE (a:A)-[:T]->(b:B)-[:T]->(c:C)
      """
    When executing query:
      """
      MATCH p = (a:A)-[:T]->()-[:T]->(c) RETURN length(p)
      """
    Then the result should be, in any order:
      | length(p) |
      | 2         |

  Scenario: nodes() of a fixed-length path
    Given an empty graph
    And having executed:
      """
      CREATE (a:A {n: 1})-[:T]->(b:B {n: 2})
      """
    When executing query:
      """
      MATCH p = (a:A)-[:T]->(b) RETURN nodes(p)
      """
    Then the result should be, in any order:
      | nodes(p)                     |
      | [(:A {n: 1}), (:B {n: 2})]   |

  Scenario: relationships() of a fixed-length path
    Given an empty graph
    And having executed:
      """
      CREATE (a:A)-[:T {k: 1}]->(b:B)-[:S {k: 2}]->(c:C)
      """
    When executing query:
      """
      MATCH p = (a:A)-[:T]->()-[:S]->(c) RETURN relationships(p)
      """
    Then the result should be, in any order:
      | relationships(p)            |
      | [[:T {k: 1}], [:S {k: 2}]]  |

  Scenario: length of a variable-length path
    Given an empty graph
    And having executed:
      """
      CREATE (a:A)-[:T]->(b:B)-[:T]->(c:C)
      """
    When executing query:
      """
      MATCH p = (a:A)-[:T*1..2]->(x) RETURN length(p)
      """
    Then the result should be, in any order:
      | length(p) |
      | 1         |
      | 2         |

  Scenario: returning a variable-length path
    Given an empty graph
    And having executed:
      """
      CREATE (a:A)-[:T {i: 1}]->(b:B)-[:T {i: 2}]->(c:C)
      """
    When executing query:
      """
      MATCH p = (a:A)-[:T*2]->(c) RETURN p
      """
    Then the result should be, in any order:
      | p                                        |
      | <(:A)-[:T {i: 1}]->(:B)-[:T {i: 2}]->(:C)> |

  Scenario: relationships() of a variable-length path
    Given an empty graph
    And having executed:
      """
      CREATE (a:A)-[:T {i: 1}]->(b:B)-[:T {i: 2}]->(c:C)
      """
    When executing query:
      """
      MATCH p = (a:A)-[:T*2]->(c) RETURN relationships(p)
      """
    Then the result should be, in any order:
      | relationships(p)            |
      | [[:T {i: 1}], [:T {i: 2}]]  |

  Scenario: filtering on path length in WHERE
    Given an empty graph
    And having executed:
      """
      CREATE (a:A)-[:T]->(b:B)-[:T]->(c:C)-[:T]->(d:D)
      """
    When executing query:
      """
      MATCH p = (a:A)-[:T*1..3]->(x) WHERE length(p) >= 2 RETURN length(p)
      """
    Then the result should be, in any order:
      | length(p) |
      | 2         |
      | 3         |

  Scenario: path variable survives WITH
    Given an empty graph
    And having executed:
      """
      CREATE (a:A)-[:T]->(b:B)
      """
    When executing query:
      """
      MATCH p = (a:A)-[:T]->(b) WITH p RETURN p, length(p)
      """
    Then the result should be, in any order:
      | p                 | length(p) |
      | <(:A)-[:T]->(:B)> | 1         |

  Scenario: aliased path through WITH keeps its shape
    Given an empty graph
    And having executed:
      """
      CREATE (a:A)-[:T]->(b:B)
      """
    When executing query:
      """
      MATCH p = (a:A)-[:T]->(b) WITH p AS q RETURN q, length(q), nodes(q)
      """
    Then the result should be, in any order:
      | q                 | length(q) | nodes(q)     |
      | <(:A)-[:T]->(:B)> | 1         | [(:A), (:B)] |

  Scenario: undirected named path reports traversal orientation
    Given an empty graph
    And having executed:
      """
      CREATE (a:A)-[:T]->(b:B)
      """
    When executing query:
      """
      MATCH p = (b:B)-[:T]-(a:A) RETURN p
      """
    Then the result should be, in any order:
      | p                 |
      | <(:B)<-[:T]-(:A)> |

  Scenario: multiple named paths in one MATCH
    Given an empty graph
    And having executed:
      """
      CREATE (a:A)-[:T]->(b:B), (b)-[:S]->(c:C)
      """
    When executing query:
      """
      MATCH p = (a:A)-[:T]->(b), q = (b)-[:S]->(c) RETURN length(p), length(q)
      """
    Then the result should be, in any order:
      | length(p) | length(q) |
      | 1         | 1         |

  Scenario: zero-length var-length path binds start node only
    Given an empty graph
    And having executed:
      """
      CREATE (a:A {n: 1})-[:T]->(b:B {n: 2})
      """
    When executing query:
      """
      MATCH p = (a:A)-[:T*0..1]->(x) RETURN p
      """
    Then the result should be, in any order:
      | p                                  |
      | <(:A {n: 1})>                      |
      | <(:A {n: 1})-[:T]->(:B {n: 2})>    |

  Scenario: distinct paths are distinct values
    Given an empty graph
    And having executed:
      """
      CREATE (a:A)-[:T]->(b:B), (a)-[:T]->(c:B)
      """
    When executing query:
      """
      MATCH p = (a:A)-[:T]->(b) RETURN DISTINCT p
      """
    Then the result should be, in any order:
      | p                 |
      | <(:A)-[:T]->(:B)> |
      | <(:A)-[:T]->(:B)> |

  Scenario: path through an OPTIONAL MATCH that finds nothing is null
    Given an empty graph
    And having executed:
      """
      CREATE (a:A)
      """
    When executing query:
      """
      MATCH (a:A) OPTIONAL MATCH p = (a)-[:T]->(b) RETURN p
      """
    Then the result should be, in any order:
      | p    |
      | null |

  Scenario: unwinding the nodes of a path
    Given an empty graph
    And having executed:
      """
      CREATE (a:A {n: 1})-[:T]->(b:B {n: 2})
      """
    When executing query:
      """
      MATCH p = (a:A)-[:T]->(b) UNWIND nodes(p) AS x RETURN x.n AS n
      """
    Then the result should be, in any order:
      | n |
      | 1 |
      | 2 |

  Scenario: counting paths groups by path identity
    Given an empty graph
    And having executed:
      """
      CREATE (a:A)-[:T]->(b:B), (a)-[:T]->(c:B)
      """
    When executing query:
      """
      MATCH p = (a:A)-[:T]->(b) RETURN length(p) AS l, count(*) AS c
      """
    Then the result should be, in any order:
      | l | c |
      | 1 | 2 |

  Scenario: ordering by path length
    Given an empty graph
    And having executed:
      """
      CREATE (a:A)-[:T]->(b:B)-[:T]->(c:C)
      """
    When executing query:
      """
      MATCH p = (a:A)-[:T*1..2]->(x) RETURN length(p) AS l ORDER BY l DESC
      """
    Then the result should be, in order:
      | l |
      | 2 |
      | 1 |

Feature: Return and order

  Scenario: Return a literal from a unit query
    Given an empty graph
    When executing query:
      """
      RETURN 1 AS one
      """
    Then the result should be, in any order:
      | one |
      | 1   |

  Scenario: Return an arithmetic expression
    Given an empty graph
    And having executed:
      """
      CREATE (:P {x: 3})
      """
    When executing query:
      """
      MATCH (p:P) RETURN p.x * 2 + 1 AS y, p.x / 2.0 AS half
      """
    Then the result should be, in any order:
      | y | half |
      | 7 | 1.5  |

  Scenario: RETURN DISTINCT removes duplicate rows
    Given an empty graph
    And having executed:
      """
      CREATE (:P {x: 1}), (:P {x: 1}), (:P {x: 2})
      """
    When executing query:
      """
      MATCH (p:P) RETURN DISTINCT p.x AS x
      """
    Then the result should be, in any order:
      | x |
      | 1 |
      | 2 |

  Scenario: ORDER BY ascending
    Given an empty graph
    And having executed:
      """
      CREATE (:P {x: 3}), (:P {x: 1}), (:P {x: 2})
      """
    When executing query:
      """
      MATCH (p:P) RETURN p.x AS x ORDER BY x
      """
    Then the result should be, in order:
      | x |
      | 1 |
      | 2 |
      | 3 |

  Scenario: ORDER BY descending with LIMIT
    Given an empty graph
    And having executed:
      """
      CREATE (:P {x: 3}), (:P {x: 1}), (:P {x: 2})
      """
    When executing query:
      """
      MATCH (p:P) RETURN p.x AS x ORDER BY x DESC LIMIT 2
      """
    Then the result should be, in order:
      | x |
      | 3 |
      | 2 |

  Scenario: SKIP and LIMIT paginate an ordered result
    Given an empty graph
    And having executed:
      """
      CREATE (:P {x: 1}), (:P {x: 2}), (:P {x: 3}), (:P {x: 4})
      """
    When executing query:
      """
      MATCH (p:P) RETURN p.x AS x ORDER BY x SKIP 1 LIMIT 2
      """
    Then the result should be, in order:
      | x |
      | 2 |
      | 3 |

  Scenario: ORDER BY two keys
    Given an empty graph
    And having executed:
      """
      CREATE (:P {a: 1, b: 'y'}), (:P {a: 1, b: 'x'}), (:P {a: 0, b: 'z'})
      """
    When executing query:
      """
      MATCH (p:P) RETURN p.a AS a, p.b AS b ORDER BY a, b
      """
    Then the result should be, in order:
      | a | b   |
      | 0 | 'z' |
      | 1 | 'x' |
      | 1 | 'y' |

  Scenario: ORDER BY an expression not in the projection
    Given an empty graph
    And having executed:
      """
      CREATE (:P {n: 'a', x: 2}), (:P {n: 'b', x: 1})
      """
    When executing query:
      """
      MATCH (p:P) RETURN p.n AS n ORDER BY p.x
      """
    Then the result should be, in order:
      | n   |
      | 'b' |
      | 'a' |

  Scenario: Return a list literal and a map literal
    Given an empty graph
    When executing query:
      """
      RETURN [1, 2, 3] AS l, {a: 1, b: 'two'} AS m
      """
    Then the result should be, in any order:
      | l         | m               |
      | [1, 2, 3] | {a: 1, b: 'two'} |

  Scenario: WITH chains projections
    Given an empty graph
    And having executed:
      """
      CREATE (:P {x: 1}), (:P {x: 2}), (:P {x: 3})
      """
    When executing query:
      """
      MATCH (p:P) WITH p.x AS x WHERE x > 1 RETURN x * 10 AS y
      """
    Then the result should be, in any order:
      | y  |
      | 20 |
      | 30 |

  Scenario: ORDER BY a column not in the projection
    Given an empty graph
    And having executed:
      """
      CREATE (:N {n: 'b', v: 2}), (:N {n: 'a', v: 3}), (:N {n: 'c', v: 1})
      """
    When executing query:
      """
      MATCH (x:N) RETURN x.n AS n ORDER BY x.v
      """
    Then the result should be, in order:
      | n   |
      | 'c' |
      | 'b' |
      | 'a' |

  Scenario: ORDER BY with SKIP and LIMIT windows the sorted rows
    Given an empty graph
    And having executed:
      """
      CREATE (:N {v: 4}), (:N {v: 1}), (:N {v: 3}), (:N {v: 2})
      """
    When executing query:
      """
      MATCH (x:N) RETURN x.v AS v ORDER BY v SKIP 1 LIMIT 2
      """
    Then the result should be, in order:
      | v |
      | 2 |
      | 3 |

  Scenario: DESC ordering puts nulls first
    Given an empty graph
    And having executed:
      """
      CREATE (:N {v: 1}), (:N), (:N {v: 2})
      """
    When executing query:
      """
      MATCH (x:N) RETURN x.v AS v ORDER BY v DESC
      """
    Then the result should be, in order:
      | v    |
      | null |
      | 2    |
      | 1    |

Feature: String functions and predicates

  Scenario: case conversion round trip
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS x RETURN toUpper('MixEd') AS u, toLower('MixEd') AS l
      """
    Then the result should be, in any order:
      | u       | l       |
      | 'MIXED' | 'mixed' |

  Scenario: trim variants
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS x
      RETURN trim('  pad  ') AS t, lTrim('  pad') AS lt, rTrim('pad  ') AS rt
      """
    Then the result should be, in any order:
      | t     | lt    | rt    |
      | 'pad' | 'pad' | 'pad' |

  Scenario: reverse of a string
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS x RETURN reverse('abc') AS r
      """
    Then the result should be, in any order:
      | r     |
      | 'cba' |

  Scenario: size of strings counts characters
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS x RETURN size('') AS a, size('abc') AS b
      """
    Then the result should be, in any order:
      | a | b |
      | 0 | 3 |

  Scenario: every string starts with and ends with the empty string
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS x
      RETURN 'abc' STARTS WITH '' AS a, 'abc' ENDS WITH '' AS b,
             'abc' CONTAINS '' AS c
      """
    Then the result should be, in any order:
      | a    | b    | c    |
      | true | true | true |

  Scenario: string predicates are case sensitive
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS x
      RETURN 'Apple' STARTS WITH 'a' AS a, 'Apple' STARTS WITH 'A' AS b
      """
    Then the result should be, in any order:
      | a     | b    |
      | false | true |

  Scenario: CONTAINS finds interior substrings
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS x
      RETURN 'banana' CONTAINS 'nan' AS a, 'banana' CONTAINS 'nano' AS b
      """
    Then the result should be, in any order:
      | a    | b     |
      | true | false |

  Scenario: ENDS WITH on exact match
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS x
      RETURN 'abc' ENDS WITH 'abc' AS a, 'abc' ENDS WITH 'dabc' AS b
      """
    Then the result should be, in any order:
      | a    | b     |
      | true | false |

  Scenario: string concatenation with plus
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS x RETURN 'foo' + 'bar' AS s
      """
    Then the result should be, in any order:
      | s        |
      | 'foobar' |

  Scenario: substring extraction
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS x RETURN substring('hello', 1, 3) AS s
      """
    Then the result should be, in any order:
      | s     |
      | 'ell' |

  Scenario: substring without length runs to the end
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS x RETURN substring('hello', 2) AS s
      """
    Then the result should be, in any order:
      | s     |
      | 'llo' |

  Scenario: left and right prefixes
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS x RETURN left('hello', 2) AS l, right('hello', 2) AS r
      """
    Then the result should be, in any order:
      | l    | r    |
      | 'he' | 'lo' |

  Scenario: replace substitutes every occurrence
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS x RETURN replace('aXbXc', 'X', '-') AS s
      """
    Then the result should be, in any order:
      | s       |
      | 'a-b-c' |

  Scenario: split produces a list
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS x RETURN split('a,b,c', ',') AS l
      """
    Then the result should be, in any order:
      | l               |
      | ['a', 'b', 'c'] |

  Scenario: toString of numbers and booleans
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS x RETURN toString(42) AS a, toString(true) AS b
      """
    Then the result should be, in any order:
      | a    | b      |
      | '42' | 'true' |

  Scenario: string ordering is lexicographic
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS x
      RETURN 'abc' < 'abd' AS a, 'Z' < 'a' AS b, 'ab' < 'abc' AS c
      """
    Then the result should be, in any order:
      | a    | b    | c    |
      | true | true | true |

  Scenario: string property comparison filters rows
    Given an empty graph
    And having executed:
      """
      CREATE (:P {n: 'ant'}), (:P {n: 'bee'}), (:P {n: 'cat'})
      """
    When executing query:
      """
      MATCH (p:P) WHERE p.n >= 'bee' RETURN p.n AS n
      """
    Then the result should be, in any order:
      | n     |
      | 'bee' |
      | 'cat' |

  Scenario: strings with special characters round-trip
    Given an empty graph
    And having executed:
      """
      CREATE (:P {s: 'tab\tand "quotes"'})
      """
    When executing query:
      """
      MATCH (p:P) RETURN p.s CONTAINS 'and' AS c
      """
    Then the result should be, in any order:
      | c    |
      | true |

  Scenario: empty string is not null
    Given an empty graph
    And having executed:
      """
      CREATE (:P {s: ''})
      """
    When executing query:
      """
      MATCH (p:P) RETURN p.s IS NULL AS a, size(p.s) AS b
      """
    Then the result should be, in any order:
      | a     | b |
      | false | 0 |

  Scenario: toInteger parses strings
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS x RETURN toInteger('42') AS a, toInteger('nope') AS b
      """
    Then the result should be, in any order:
      | a  | b    |
      | 42 | null |

  Scenario: toFloat parses strings
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS x RETURN toFloat('2.5') AS a, toFloat('nope') AS b
      """
    Then the result should be, in any order:
      | a   | b    |
      | 2.5 | null |

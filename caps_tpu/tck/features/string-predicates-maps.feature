Feature: String predicates, regex, maps and keys
  # openCypher STARTS WITH / ENDS WITH / CONTAINS / =~ three-valued
  # semantics, map literals and key access, keys()/properties().

  Scenario: STARTS WITH ENDS WITH CONTAINS basics
    Given an empty graph
    And having executed:
      """
      CREATE ({s: 'Carlsberg'}), ({s: 'carl'}), ({s: 'Berg'}), ({t: 1})
      """
    When executing query:
      """
      MATCH (n) WHERE n.s STARTS WITH 'Carl' RETURN n.s AS s
      """
    Then the result should be, in any order:
      | s           |
      | 'Carlsberg' |

  Scenario: CONTAINS and ENDS WITH are case sensitive
    Given an empty graph
    And having executed:
      """
      CREATE ({s: 'Carlsberg'}), ({s: 'carlsberg'})
      """
    When executing query:
      """
      MATCH (n) WHERE n.s CONTAINS 'lsb' AND n.s ENDS WITH 'berg'
      RETURN count(*) AS c
      """
    Then the result should be, in any order:
      | c |
      | 2 |

  Scenario: string predicates on null or non-existent are null-filtered
    Given an empty graph
    And having executed:
      """
      CREATE ({s: 'abc'}), ({t: 1})
      """
    When executing query:
      """
      MATCH (n) WHERE n.s STARTS WITH 'a' RETURN count(*) AS c
      """
    Then the result should be, in any order:
      | c |
      | 1 |

  Scenario: regex match with =~
    Given an empty graph
    And having executed:
      """
      CREATE ({s: 'mail-42'}), ({s: 'mail-x'}), ({s: 'other'})
      """
    When executing query:
      """
      MATCH (n) WHERE n.s =~ 'mail-[0-9]+' RETURN n.s AS s
      """
    Then the result should be, in any order:
      | s         |
      | 'mail-42' |

  Scenario: map literal projection and nested access
    Given an empty graph
    When executing query:
      """
      WITH {a: 1, b: {c: 'x'}} AS m
      RETURN m.a AS a, m.b.c AS c
      """
    Then the result should be, in any order:
      | a | c   |
      | 1 | 'x' |

  Scenario: keys of a node and of a map
    Given an empty graph
    And having executed:
      """
      CREATE ({name: 'n', age: 3})
      """
    When executing query:
      """
      MATCH (n) UNWIND keys(n) AS k
      RETURN k ORDER BY k
      """
    Then the result should be, in order:
      | k      |
      | 'age'  |
      | 'name' |

  Scenario: properties() materializes the property map
    Given an empty graph
    And having executed:
      """
      CREATE ({name: 'n', age: 3})
      """
    When executing query:
      """
      MATCH (n) WITH properties(n) AS p
      RETURN p.name AS name, p.age AS age
      """
    Then the result should be, in any order:
      | name | age |
      | 'n'  | 3   |

  Scenario: CASE over string predicate results
    Given an empty graph
    And having executed:
      """
      CREATE ({s: 'alpha'}), ({s: 'beta'}), ({t: 0})
      """
    When executing query:
      """
      MATCH (n)
      RETURN CASE WHEN n.s STARTS WITH 'a' THEN 'A'
                  WHEN n.s IS NULL THEN 'none'
                  ELSE 'other' END AS tag, count(*) AS c
      """
    Then the result should be, in any order:
      | tag     | c |
      | 'A'     | 1 |
      | 'none'  | 1 |
      | 'other' | 1 |

  Scenario: startNode and endNode property access follows stored orientation
    Given an empty graph
    And having executed:
      """
      CREATE (a:P {v: 1})-[:K]->(b:P {v: 2}), (b)-[:K]->(c:P {v: 3})
      """
    When executing query:
      """
      MATCH (x)-[r:K]->(y)
      RETURN startNode(r).v AS s, endNode(r).v AS e
      """
    Then the result should be, in any order:
      | s | e |
      | 1 | 2 |
      | 2 | 3 |

  Scenario: startNode property under an undirected match is the stored source
    Given an empty graph
    And having executed:
      """
      CREATE (a:P {v: 1})-[:K]->(b:P {v: 2})
      """
    When executing query:
      """
      MATCH (x)-[r:K]-(y)
      RETURN x.v AS x, startNode(r).v AS s, endNode(r).v AS e
      """
    Then the result should be, in any order:
      | x | s | e |
      | 1 | 1 | 2 |
      | 2 | 1 | 2 |

  Scenario: labels type and id functions
    Given an empty graph
    And having executed:
      """
      CREATE (a:Person:Admin {v: 1})-[:KNOWS]->(b:Person {v: 2})
      """
    When executing query:
      """
      MATCH (n:Admin)-[r]->(m)
      RETURN labels(n) AS ln, type(r) AS t, labels(m) AS lm,
             id(n) = id(m) AS same
      """
    Then the result should be, in any order:
      | ln                  | t       | lm         | same  |
      | ['Admin', 'Person'] | 'KNOWS' | ['Person'] | false |

  Scenario: coalesce picks the first non-null
    Given an empty graph
    And having executed:
      """
      CREATE ({a: 1}), ({b: 2}), ({c: 3})
      """
    When executing query:
      """
      MATCH (n) RETURN coalesce(n.a, n.b, 99) AS v
      """
    Then the result should be, in any order:
      | v  |
      | 1  |
      | 2  |
      | 99 |

  Scenario: label predicate in WHERE
    Given an empty graph
    And having executed:
      """
      CREATE (:A:B {v: 1}), (:A {v: 2}), (:B {v: 3})
      """
    When executing query:
      """
      MATCH (n) WHERE n:A AND NOT n:B RETURN n.v AS v
      """
    Then the result should be, in any order:
      | v |
      | 2 |

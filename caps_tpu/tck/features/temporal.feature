Feature: Temporal values — date, datetime, duration

  Scenario: date literal roundtrips through toString
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS one RETURN toString(date('2020-01-15')) AS s
      """
    Then the result should be, in any order:
      | s            |
      | '2020-01-15' |

  Scenario: date accessors
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS one
      WITH date('2020-03-07') AS d
      RETURN d.year AS y, d.month AS m, d.day AS dd
      """
    Then the result should be, in any order:
      | y    | m | dd |
      | 2020 | 3 | 7  |

  Scenario: date from a component map
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS one
      RETURN toString(date({year: 1999, month: 12, day: 31})) AS s,
             toString(date({year: 2024})) AS t
      """
    Then the result should be, in any order:
      | s            | t            |
      | '1999-12-31' | '2024-01-01' |

  Scenario: date comparison and ordering
    Given an empty graph
    And having executed:
      """
      CREATE (:E {n: 'a', d: date('2020-01-15')}),
             (:E {n: 'b', d: date('2019-06-30')}),
             (:E {n: 'c', d: date('2020-03-01')})
      """
    When executing query:
      """
      MATCH (e:E) WHERE e.d >= date('2020-01-01')
      RETURN e.n AS n ORDER BY e.d DESC
      """
    Then the result should be, in order:
      | n   |
      | 'c' |
      | 'a' |

  Scenario: date equality and inequality
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS one
      RETURN date('2020-01-15') = date('2020-01-15') AS eq,
             date('2020-01-15') = date('2020-01-16') AS ne,
             date('2020-01-15') < date('2020-01-16') AS lt
      """
    Then the result should be, in any order:
      | eq   | ne    | lt   |
      | true | false | true |

  Scenario: datetime accessors and comparison
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS one
      WITH datetime('2020-01-15T10:30:45') AS t
      RETURN t.year AS y, t.hour AS h, t.minute AS m, t.second AS s,
             t < datetime('2020-01-15T11:00:00') AS lt
      """
    Then the result should be, in any order:
      | y    | h  | m  | s  | lt   |
      | 2020 | 10 | 30 | 45 | true |

  Scenario: duration components from a map
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS one
      WITH duration({years: 1, months: 2, days: 3, hours: 4}) AS du
      RETURN du.months AS mo, du.days AS d, du.hours AS h
      """
    Then the result should be, in any order:
      | mo | d | h |
      | 14 | 3 | 4 |

  Scenario: duration from an ISO 8601 string
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS one
      WITH duration('P1Y2M3DT4H5M6S') AS du
      RETURN du.months AS mo, du.days AS d, du.seconds AS s
      """
    Then the result should be, in any order:
      | mo | d | s     |
      | 14 | 3 | 14706 |

  Scenario: date plus and minus duration
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS one
      RETURN toString(date('2020-01-31') + duration({months: 1})) AS clamped,
             toString(date('2020-03-06') - duration({days: 6})) AS back
      """
    Then the result should be, in any order:
      | clamped      | back         |
      | '2020-02-29' | '2020-02-29' |

  Scenario: datetime plus duration crosses a day boundary
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS one
      RETURN toString(datetime('2020-01-15T23:30:00')
                      + duration({hours: 1})) AS t
      """
    Then the result should be, in any order:
      | t                     |
      | '2020-01-16T00:30:00' |

  Scenario: temporal values stored as properties survive grouping
    Given an empty graph
    And having executed:
      """
      CREATE (:E {g: 'x', d: date('2020-01-15')}),
             (:E {g: 'x', d: date('2019-06-30')}),
             (:E {g: 'y', d: date('2021-05-05')})
      """
    When executing query:
      """
      MATCH (e:E) RETURN e.g AS g, toString(min(e.d)) AS first,
                         count(DISTINCT e.d) AS n
      """
    Then the result should be, in any order:
      | g   | first        | n |
      | 'x' | '2019-06-30' | 2 |
      | 'y' | '2021-05-05' | 1 |

  Scenario: null propagates through temporal constructors and arithmetic
    Given an empty graph
    And having executed:
      """
      CREATE (:E)
      """
    When executing query:
      """
      MATCH (e:E)
      RETURN date(e.missing) AS d, e.missing + duration({days: 1}) AS p
      """
    Then the result should be, in any order:
      | d    | p    |
      | null | null |

  Scenario: date and datetime are not equal to each other
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS one
      RETURN date('2020-01-15') = datetime('2020-01-15T00:00:00') AS x
      """
    Then the result should be, in any order:
      | x     |
      | false |

  Scenario: dates inside lists and comprehensions
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS one
      RETURN [d IN [date('2020-01-15'), date('2021-05-05')] | d.year] AS ys
      """
    Then the result should be, in any order:
      | ys           |
      | [2020, 2021] |

  Scenario: datetime truncation to date
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS one
      RETURN toString(date(datetime('2020-01-15T10:30:00'))) AS d
      """
    Then the result should be, in any order:
      | d            |
      | '2020-01-15' |

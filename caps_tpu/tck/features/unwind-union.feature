Feature: Unwind and union

  Scenario: UNWIND a list literal
    Given an empty graph
    When executing query:
      """
      UNWIND [1, 2, 3] AS x RETURN x
      """
    Then the result should be, in any order:
      | x |
      | 1 |
      | 2 |
      | 3 |

  Scenario: UNWIND an empty list produces no rows
    Given an empty graph
    When executing query:
      """
      UNWIND [] AS x RETURN x
      """
    Then the result should be empty

  Scenario: UNWIND preserves other bindings
    Given an empty graph
    And having executed:
      """
      CREATE (:P {n: 'a'}), (:P {n: 'b'})
      """
    When executing query:
      """
      MATCH (p:P) UNWIND [1, 2] AS x RETURN p.n AS n, x
      """
    Then the result should be, in any order:
      | n   | x |
      | 'a' | 1 |
      | 'a' | 2 |
      | 'b' | 1 |
      | 'b' | 2 |

  Scenario: UNWIND a parameter list
    Given an empty graph
    And parameters are:
      | xs | [10, 20] |
    When executing query:
      """
      UNWIND $xs AS x RETURN x * 2 AS y
      """
    Then the result should be, in any order:
      | y  |
      | 20 |
      | 40 |

  Scenario: UNION removes duplicate rows
    Given an empty graph
    And having executed:
      """
      CREATE (:A {x: 1}), (:B {x: 1}), (:B {x: 2})
      """
    When executing query:
      """
      MATCH (a:A) RETURN a.x AS x UNION MATCH (b:B) RETURN b.x AS x
      """
    Then the result should be, in any order:
      | x |
      | 1 |
      | 2 |

  Scenario: UNION ALL keeps duplicate rows
    Given an empty graph
    And having executed:
      """
      CREATE (:A {x: 1}), (:B {x: 1})
      """
    When executing query:
      """
      MATCH (a:A) RETURN a.x AS x UNION ALL MATCH (b:B) RETURN b.x AS x
      """
    Then the result should be, in any order:
      | x |
      | 1 |
      | 1 |

  Scenario: UNION with different return columns is an error
    Given an empty graph
    When executing query:
      """
      MATCH (a) RETURN a UNION MATCH (b) RETURN b
      """
    Then a SyntaxError should be raised at compile time: DifferentColumnsInUnion

  Scenario: UNWIND of an empty list produces no rows
    Given an empty graph
    And having executed:
      """
      CREATE (:N {v: 1})
      """
    When executing query:
      """
      MATCH (n:N) UNWIND [] AS x RETURN n.v AS v, x AS x
      """
    Then the result should be, in any order:
      | v | x |

  Scenario: UNWIND of null produces no rows
    Given an empty graph
    And having executed:
      """
      CREATE (:N {v: 1})
      """
    When executing query:
      """
      MATCH (n:N) UNWIND n.missing AS x RETURN x AS x
      """
    Then the result should be, in any order:
      | x |

  Scenario: nested UNWIND forms the cross product of the lists
    Given an empty graph
    When executing query:
      """
      UNWIND [1, 2] AS a UNWIND ['x', 'y'] AS b RETURN a, b
      """
    Then the result should be, in any order:
      | a | b   |
      | 1 | 'x' |
      | 1 | 'y' |
      | 2 | 'x' |
      | 2 | 'y' |

  Scenario: UNION deduplicates rows containing nulls
    Given an empty graph
    And having executed:
      """
      CREATE (:N {v: 1}), (:N)
      """
    When executing query:
      """
      MATCH (n:N) RETURN n.v AS v UNION MATCH (n:N) RETURN n.v AS v
      """
    Then the result should be, in any order:
      | v    |
      | 1    |
      | null |

  Scenario: UNION ALL keeps duplicates from both branches
    Given an empty graph
    And having executed:
      """
      CREATE (:N {v: 1})
      """
    When executing query:
      """
      MATCH (n:N) RETURN n.v AS v UNION ALL MATCH (n:N) RETURN n.v AS v
      """
    Then the result should be, in any order:
      | v |
      | 1 |
      | 1 |

Feature: Var-length expand

  Scenario: fixed range variable expansion
    Given an empty graph
    And having executed:
      """
      CREATE (a:P {n: 'a'})-[:T]->(b:P {n: 'b'})-[:T]->(c:P {n: 'c'})
      """
    When executing query:
      """
      MATCH (x:P {n: 'a'})-[:T*1..2]->(y) RETURN y.n AS n
      """
    Then the result should be, in any order:
      | n   |
      | 'b' |
      | 'c' |

  Scenario: zero-length expansion includes the start node
    Given an empty graph
    And having executed:
      """
      CREATE (a:P {n: 'a'})-[:T]->(b:P {n: 'b'})
      """
    When executing query:
      """
      MATCH (x:P {n: 'a'})-[:T*0..1]->(y) RETURN y.n AS n
      """
    Then the result should be, in any order:
      | n   |
      | 'a' |
      | 'b' |

  Scenario: relationship uniqueness prevents re-walking an edge
    Given an empty graph
    And having executed:
      """
      CREATE (a:P {n: 'a'}), (b:P {n: 'b'}), (a)-[:T]->(b), (b)-[:T]->(a)
      """
    When executing query:
      """
      MATCH (x:P {n: 'a'})-[:T*1..3]->(y) RETURN y.n AS n
      """
    Then the result should be, in any order:
      | n   |
      | 'b' |
      | 'a' |

  Scenario: exact length expansion
    Given an empty graph
    And having executed:
      """
      CREATE (a:P {n: 'a'})-[:T]->(b:P {n: 'b'})-[:T]->(c:P {n: 'c'})-[:T]->(d:P {n: 'd'})
      """
    When executing query:
      """
      MATCH (x:P {n: 'a'})-[:T*3]->(y) RETURN y.n AS n
      """
    Then the result should be, in any order:
      | n   |
      | 'd' |

  Scenario: uniqueness between a fixed and a var-length relationship
    Given an empty graph
    And having executed:
      """
      CREATE (a:P {n: 'a'})-[:T]->(b:P {n: 'b'})
      """
    When executing query:
      """
      MATCH (a)-[r:T]-(b)-[:T*1..1]-(c) RETURN count(*) AS c
      """
    Then the result should be, in any order:
      | c |
      | 0 |

  Scenario: uniqueness between two var-length relationships
    Given an empty graph
    And having executed:
      """
      CREATE (a:P {n: 'a'})-[:T]->(b:P {n: 'b'})
      """
    When executing query:
      """
      MATCH (a)-[:T*1..1]-(b)-[:T*1..1]-(c) RETURN count(*) AS c
      """
    Then the result should be, in any order:
      | c |
      | 0 |

  Scenario: two var-length expansions over distinct edges both match
    Given an empty graph
    And having executed:
      """
      CREATE (a:P {n: 'a'})-[:T]->(b:P {n: 'b'})-[:T]->(c:P {n: 'c'})
      """
    When executing query:
      """
      MATCH (x:P {n: 'a'})-[:T*1..1]->(y)-[:T*1..1]->(z) RETURN z.n AS n
      """
    Then the result should be, in any order:
      | n   |
      | 'c' |

  Scenario: undirected variable expansion
    Given an empty graph
    And having executed:
      """
      CREATE (a:P {n: 'a'}), (b:P {n: 'b'}), (c:P {n: 'c'}), (b)-[:T]->(a), (b)-[:T]->(c)
      """
    When executing query:
      """
      MATCH (x:P {n: 'a'})-[:T*1..2]-(y) RETURN y.n AS n
      """
    Then the result should be, in any order:
      | n   |
      | 'b' |
      | 'c' |

  Scenario: zero-length var expansion binds the start node itself
    Given an empty graph
    And having executed:
      """
      CREATE (a:N {n: 'a'}), (b:N {n: 'b'}), (a)-[:T]->(b)
      """
    When executing query:
      """
      MATCH (a:N {n: 'a'})-[:T*0..1]->(x) RETURN x.n AS x
      """
    Then the result should be, in any order:
      | x   |
      | 'a' |
      | 'b' |

  Scenario: var-length lower bound above the longest path matches nothing
    Given an empty graph
    And having executed:
      """
      CREATE (a:N), (b:N), (a)-[:T]->(b)
      """
    When executing query:
      """
      MATCH (a:N)-[:T*3..4]->(x) RETURN x AS x
      """
    Then the result should be, in any order:
      | x |

  Scenario: undirected var-length reaches both directions
    Given an empty graph
    And having executed:
      """
      CREATE (a:N {n: 'a'}), (b:N {n: 'b'}), (c:N {n: 'c'}),
             (a)-[:T]->(b), (c)-[:T]->(b)
      """
    When executing query:
      """
      MATCH (s:N {n: 'a'})-[:T*1..2]-(x) RETURN DISTINCT x.n AS x
      """
    Then the result should be, in any order:
      | x   |
      | 'b' |
      | 'c' |

  Scenario: var-length relationship list has one entry per hop
    Given an empty graph
    And having executed:
      """
      CREATE (a:N {n: 'a'}), (b:N {n: 'b'}), (c:N {n: 'c'}),
             (a)-[:T]->(b), (b)-[:T]->(c)
      """
    When executing query:
      """
      MATCH (a:N {n: 'a'})-[rs:T*1..2]->(x) RETURN x.n AS x, size(rs) AS hops
      """
    Then the result should be, in any order:
      | x   | hops |
      | 'b' | 1    |
      | 'c' | 2    |

Feature: WITH projection, scoping and pipeline composition

  Scenario: WITH narrows the variable scope
    Given an empty graph
    And having executed:
      """
      CREATE (:P {a: 1, b: 2})
      """
    When executing query:
      """
      MATCH (p:P) WITH p.a AS a RETURN a
      """
    Then the result should be, in any order:
      | a |
      | 1 |

  Scenario: expression aliases compose across WITH stages
    Given an empty graph
    When executing query:
      """
      UNWIND [1, 2] AS v WITH v * 10 AS tens WITH tens + 1 AS ones
      RETURN ones
      """
    Then the result should be, in any order:
      | ones |
      | 11   |
      | 21   |

  Scenario: WHERE after WITH filters on the alias
    Given an empty graph
    When executing query:
      """
      UNWIND [1, 2, 3, 4] AS v WITH v WHERE v % 2 = 0 RETURN v
      """
    Then the result should be, in any order:
      | v |
      | 2 |
      | 4 |

  Scenario: aggregation inside WITH groups by the other projections
    Given an empty graph
    And having executed:
      """
      CREATE (:P {g: 'x', v: 1}), (:P {g: 'x', v: 2}), (:P {g: 'y', v: 5})
      """
    When executing query:
      """
      MATCH (p:P) WITH p.g AS g, sum(p.v) AS s RETURN g, s
      """
    Then the result should be, in any order:
      | g   | s |
      | 'x' | 3 |
      | 'y' | 5 |

  Scenario: aggregate of an aggregate via two WITH stages
    Given an empty graph
    And having executed:
      """
      CREATE (:P {g: 'x'}), (:P {g: 'x'}), (:P {g: 'y'})
      """
    When executing query:
      """
      MATCH (p:P) WITH p.g AS g, count(*) AS c RETURN max(c) AS biggest
      """
    Then the result should be, in any order:
      | biggest |
      | 2       |

  Scenario: match continues after WITH carrying a node variable
    Given an empty graph
    And having executed:
      """
      CREATE (a:P {n: 'a'})-[:R]->(:Q {v: 1}), (a)-[:R]->(:Q {v: 2})
      """
    When executing query:
      """
      MATCH (p:P) WITH p MATCH (p)-[:R]->(q:Q) RETURN p.n AS n, q.v AS v
      """
    Then the result should be, in any order:
      | n   | v |
      | 'a' | 1 |
      | 'a' | 2 |

  Scenario: variables not projected by WITH are out of scope
    Given an empty graph
    And having executed:
      """
      CREATE (:P {a: 1, b: 2})
      """
    When executing query:
      """
      MATCH (p:P) WITH p.a AS a RETURN b
      """
    Then a SyntaxError should be raised

  Scenario: RETURN alias shadows the original property name
    Given an empty graph
    And having executed:
      """
      CREATE (:P {v: 7})
      """
    When executing query:
      """
      MATCH (p:P) RETURN p.v AS v ORDER BY v
      """
    Then the result should be, in any order:
      | v |
      | 7 |

  Scenario: WITH star keeps every variable in scope
    Given an empty graph
    And having executed:
      """
      CREATE (:P {a: 1})
      """
    When executing query:
      """
      MATCH (p:P) WITH * RETURN p.a AS a
      """
    Then the result should be, in any order:
      | a |
      | 1 |

  Scenario: chained MATCH WITH MATCH multiplies cardinality correctly
    Given an empty graph
    And having executed:
      """
      CREATE (:A {v: 1}), (:A {v: 2}), (:B {w: 10}), (:B {w: 20})
      """
    When executing query:
      """
      MATCH (a:A) WITH a MATCH (b:B) RETURN a.v AS v, b.w AS w
      """
    Then the result should be, in any order:
      | v | w  |
      | 1 | 10 |
      | 1 | 20 |
      | 2 | 10 |
      | 2 | 20 |

  Scenario: aliasing a constant expression
    Given an empty graph
    When executing query:
      """
      UNWIND [1] AS x RETURN 1 + 2 AS three, 'a' AS letter
      """
    Then the result should be, in any order:
      | three | letter |
      | 3     | 'a'    |

  Scenario: parameter values flow through WITH
    Given an empty graph
    And parameters are:
      | lim | 2 |
    When executing query:
      """
      UNWIND [1, 2, 3] AS v WITH v WHERE v <= $lim RETURN v
      """
    Then the result should be, in any order:
      | v |
      | 1 |
      | 2 |

  Scenario: RETURN can reference an alias in the same clause ordering
    Given an empty graph
    When executing query:
      """
      UNWIND [2, 1] AS v RETURN v AS x ORDER BY x
      """
    Then the result should be, in order:
      | x |
      | 1 |
      | 2 |

  Scenario: unwinding an aggregated collect after WITH
    Given an empty graph
    And having executed:
      """
      CREATE (:P {v: 2}), (:P {v: 1})
      """
    When executing query:
      """
      MATCH (p:P) WITH collect(p.v) AS l RETURN size(l) AS s
      """
    Then the result should be, in any order:
      | s |
      | 2 |

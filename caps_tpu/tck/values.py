"""TCK result-table value literals.

Parses the value syntax used in openCypher TCK expected-result tables —
integers, floats, strings, booleans, null, lists, maps, node literals
``(:L1:L2 {k: v})`` and relationship literals ``[:T {k: v}]`` — into
Python values / structural matchers comparable against engine output
(ref: opencypher TCK tck-api value model — reconstructed; SURVEY.md §4.3).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Tuple

from caps_tpu.okapi.values import CypherNode, CypherPath, CypherRelationship


@dataclasses.dataclass(frozen=True)
class NodeMatcher:
    """Structural node expectation: labels + properties (TCK compares
    nodes structurally, not by id)."""
    labels: Tuple[str, ...]
    properties: Tuple[Tuple[str, Any], ...]

    def matches(self, v: Any) -> bool:
        return (isinstance(v, CypherNode)
                and tuple(sorted(v.labels)) == self.labels
                and values_equal(dict(self.properties), dict(v.properties)))

    def __repr__(self):
        lbl = "".join(f":{l}" for l in self.labels)
        props = ", ".join(f"{k}: {v!r}" for k, v in self.properties)
        return f"({lbl} {{{props}}})" if props else f"({lbl})"


@dataclasses.dataclass(frozen=True)
class RelMatcher:
    rel_type: str
    properties: Tuple[Tuple[str, Any], ...]

    def matches(self, v: Any) -> bool:
        return (isinstance(v, CypherRelationship)
                and v.rel_type == self.rel_type
                and values_equal(dict(self.properties), dict(v.properties)))

    def __repr__(self):
        props = ", ".join(f"{k}: {v!r}" for k, v in self.properties)
        return f"[:{self.rel_type}" + (f" {{{props}}}]" if props else "]")


@dataclasses.dataclass(frozen=True)
class PathMatcher:
    """Structural path expectation ``<(:A)-[:T]->(:B)>``: node/rel matchers
    in order plus per-hop direction (True = forward as written)."""
    nodes: Tuple[NodeMatcher, ...]
    rels: Tuple[RelMatcher, ...]
    forward: Tuple[bool, ...]

    def matches(self, v: Any) -> bool:
        if not isinstance(v, CypherPath):
            return False
        if len(v.nodes) != len(self.nodes) or len(v.rels) != len(self.rels):
            return False
        if not all(m.matches(n) for m, n in zip(self.nodes, v.nodes)):
            return False
        for i, (m, r) in enumerate(zip(self.rels, v.rels)):
            if not m.matches(r):
                return False
            prev, nxt = v.nodes[i].id, v.nodes[i + 1].id
            want = (prev, nxt) if self.forward[i] else (nxt, prev)
            if (r.start, r.end) != want:
                return False
        return True

    def __repr__(self):
        parts = [repr(self.nodes[0])]
        for i, r in enumerate(self.rels):
            arrow = f"-{r!r}->" if self.forward[i] else f"<-{r!r}-"
            parts.append(arrow)
            parts.append(repr(self.nodes[i + 1]))
        return "<" + "".join(parts) + ">"


def values_equal(expected: Any, actual: Any) -> bool:
    """Structural equality between a parsed TCK value and an engine value.
    Booleans are distinct from integers (Cypher has no bool/int coercion)."""
    if isinstance(expected, (NodeMatcher, RelMatcher, PathMatcher)):
        return expected.matches(actual)
    if expected is None or actual is None:
        return expected is None and actual is None
    if isinstance(expected, bool) or isinstance(actual, bool):
        return isinstance(expected, bool) and isinstance(actual, bool) \
            and expected == actual
    if isinstance(expected, float) or isinstance(actual, float):
        if not isinstance(actual, (int, float)) or isinstance(actual, bool):
            return False
        if not isinstance(expected, (int, float)):
            return False
        return abs(float(expected) - float(actual)) <= 1e-9 * max(
            1.0, abs(float(expected)), abs(float(actual)))
    if isinstance(expected, list):
        return (isinstance(actual, (list, tuple)) and
                len(expected) == len(actual) and
                all(values_equal(e, a) for e, a in zip(expected, actual)))
    if isinstance(expected, dict):
        return (isinstance(actual, dict) and
                set(expected) == set(actual) and
                all(values_equal(v, actual[k]) for k, v in expected.items()))
    return type(expected) == type(actual) and expected == actual


class _Parser:
    def __init__(self, text: str):
        self.text = text
        self.pos = 0

    def error(self, msg: str) -> ValueError:
        return ValueError(f"TCK value parse error at {self.pos} in "
                          f"{self.text!r}: {msg}")

    def skip_ws(self):
        while self.pos < len(self.text) and self.text[self.pos] in " \t":
            self.pos += 1

    def peek(self) -> str:
        return self.text[self.pos] if self.pos < len(self.text) else ""

    def expect(self, ch: str):
        if self.peek() != ch:
            raise self.error(f"expected {ch!r}, found {self.peek()!r}")
        self.pos += 1

    def accept(self, ch: str) -> bool:
        if self.peek() == ch:
            self.pos += 1
            return True
        return False

    def parse(self) -> Any:
        self.skip_ws()
        v = self.value()
        self.skip_ws()
        if self.pos != len(self.text):
            raise self.error("trailing input")
        return v

    def value(self) -> Any:
        self.skip_ws()
        c = self.peek()
        if c == "'":
            return self.string()
        if c == "[":
            return self.bracket()
        if c == "{":
            return self.map_literal()
        if c == "(":
            return self.node()
        if c == "<":
            return self.path()
        if c.isdigit() or c == "-":
            return self.number()
        return self.word()

    def path(self) -> "PathMatcher":
        self.expect("<")
        self.skip_ws()
        nodes = [self.node()]
        rels: List[RelMatcher] = []
        forward: List[bool] = []
        while True:
            self.skip_ws()
            if self.accept(">"):
                return PathMatcher(tuple(nodes), tuple(rels), tuple(forward))
            if self.accept("<"):  # <-[:T]-
                self.expect("-")
                self.skip_ws()
                rel = self.bracket_rel()
                self.skip_ws()
                self.expect("-")
                forward.append(False)
            else:                 # -[:T]->
                self.expect("-")
                self.skip_ws()
                rel = self.bracket_rel()
                self.skip_ws()
                self.expect("-")
                self.expect(">")
                forward.append(True)
            rels.append(rel)
            self.skip_ws()
            nodes.append(self.node())

    def bracket_rel(self) -> "RelMatcher":
        v = self.bracket()
        if not isinstance(v, RelMatcher):
            raise self.error("expected a relationship in path")
        return v

    def string(self) -> str:
        self.expect("'")
        out = []
        while True:
            if self.pos >= len(self.text):
                raise self.error("unterminated string")
            c = self.text[self.pos]
            self.pos += 1
            if c == "\\":
                out.append(self.text[self.pos])
                self.pos += 1
            elif c == "'":
                return "".join(out)
            else:
                out.append(c)

    def number(self) -> Any:
        start = self.pos
        if self.accept("-"):
            pass
        while self.peek().isdigit():
            self.pos += 1
        is_float = False
        if self.peek() == "." and self.pos + 1 < len(self.text) \
                and self.text[self.pos + 1].isdigit():
            is_float = True
            self.pos += 1
            while self.peek().isdigit():
                self.pos += 1
        if self.peek() and self.peek() in "eE":
            is_float = True
            self.pos += 1
            if self.peek() and self.peek() in "+-":
                self.pos += 1
            while self.peek().isdigit():
                self.pos += 1
        text = self.text[start:self.pos]
        return float(text) if is_float else int(text)

    def word(self) -> Any:
        start = self.pos
        while self.peek().isalnum() or self.peek() == "_":
            self.pos += 1
        w = self.text[start:self.pos]
        if w == "null":
            return None
        if w == "true":
            return True
        if w == "false":
            return False
        raise self.error(f"unknown literal {w!r}")

    def bracket(self) -> Any:
        # list [1, 2] or relationship [:T {...}]
        self.expect("[")
        self.skip_ws()
        if self.peek() == ":":
            self.pos += 1
            rel_type = self.identifier()
            props: Dict[str, Any] = {}
            self.skip_ws()
            if self.peek() == "{":
                props = self.map_literal()
            self.skip_ws()
            self.expect("]")
            return RelMatcher(rel_type, tuple(sorted(props.items())))
        items: List[Any] = []
        if not self.accept("]"):
            while True:
                items.append(self.value())
                self.skip_ws()
                if self.accept("]"):
                    break
                self.expect(",")
        return items

    def identifier(self) -> str:
        start = self.pos
        while self.peek().isalnum() or self.peek() == "_":
            self.pos += 1
        if start == self.pos:
            raise self.error("expected identifier")
        return self.text[start:self.pos]

    def map_literal(self) -> Dict[str, Any]:
        self.expect("{")
        out: Dict[str, Any] = {}
        self.skip_ws()
        if self.accept("}"):
            return out
        while True:
            self.skip_ws()
            key = self.identifier()
            self.skip_ws()
            self.expect(":")
            out[key] = self.value()
            self.skip_ws()
            if self.accept("}"):
                return out
            self.expect(",")

    def node(self) -> NodeMatcher:
        self.expect("(")
        labels: List[str] = []
        self.skip_ws()
        while self.peek() == ":":
            self.pos += 1
            labels.append(self.identifier())
            self.skip_ws()
        props: Dict[str, Any] = {}
        if self.peek() == "{":
            props = self.map_literal()
        self.skip_ws()
        self.expect(")")
        return NodeMatcher(tuple(sorted(labels)), tuple(sorted(props.items())))


def parse_value(cell: str) -> Any:
    return _Parser(cell.strip()).parse()

"""Seeded chaos: deterministic fault schedules over the locked patch
points, with invariant checkers for live-fleet soaks.

The fault injectors built across PRs 7–19 (testing/faults.py) each
prove ONE failure mode in a hand-scripted test.  This module composes
them: a :class:`ChaosSchedule` draws fault events — which injector,
which target, when — from a seeded PRNG, so a soak exercises fault
*combinations* while staying perfectly reproducible:

* same seed ⇒ the identical event list, byte-for-byte, attested by
  :meth:`ChaosSchedule.digest` (a sha256 over the canonical JSON of the
  schedule — the bench prints it, CI can diff it);
* every in-process event resolves to a ``testing/faults.py``-style
  injector over the SAME locked patch points (``OPERATOR_PATCH._lock``)
  with the same budget discipline, so chaos and hand-scripted faults
  can never fight over a monkey-patch;
* process-level events (SIGKILL a backend, SIGKILL the *active
  router* — the headline scenario) are delegated to host-provided
  actions, keeping this module free of process management.

The :class:`ChaosRunner` is a pure *pump*: the soak loop calls
:meth:`~ChaosRunner.poll` with its own elapsed time and due events
fire — no hidden thread, no wall-clock reads, so a fake-clock test
drives an entire schedule in zero real time.

:class:`ChaosInvariants` collects the soak's observations and renders
the verdicts the chaos bench reports: zero acked-write loss (digest
parity against a serial oracle), no stale reads (per-reader snapshot
versions never regress), an availability floor, and no zombie
application (every fence probe refused).

Chaos-attributed faults are stamped ``caps_chaos_fault``
(first-writer-wins, like every containment marker) so a failure
surfacing through the serving tier's classify/retry ladder stays
attributable to the schedule that injected it.
"""
from __future__ import annotations

import contextlib
import dataclasses
import hashlib
import json
import random
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from caps_tpu.obs import clock
from caps_tpu.obs.metrics import MetricsRegistry, global_registry
from caps_tpu.serve.errors import WireError
from caps_tpu.testing.faults import OPERATOR_PATCH, _Budget, _count_injection

__all__ = [
    "ChaosEvent", "ChaosSchedule", "ChaosRunner", "ChaosInvariants",
    "chaos_fault", "slow_backend", "PATCH_INJECTORS", "DEFAULT_MENU",
]


# -- chaos-owned injectors ---------------------------------------------------


@contextlib.contextmanager
def chaos_fault(n_times: Optional[int] = 1, every_n: int = 1):
    """While active, eligible fleet wire sends fail with a fresh
    :class:`~caps_tpu.serve.errors.WireError` stamped
    ``caps_chaos_fault`` — the generic chaos-attributed transport
    fault.  Unlike :func:`~caps_tpu.testing.faults.drop_connection`
    the marker names the SCHEDULE as the origin, so a soak's failure
    report can separate injected chaos from organic breakage.  Patches
    the module attribute under the shared fault lock; injections count
    ``faults.injected.chaos_fault``.  Yields the budget."""
    from caps_tpu.serve import wire
    budget = _Budget(n_times, every_n)

    with OPERATOR_PATCH._lock:
        orig = wire.send_frame

        def chaotic(sock, obj):
            if budget.take():
                _count_injection("chaos_fault")
                err = WireError("injected: chaos schedule dropped the "
                                "frame")
                if getattr(err, "caps_chaos_fault", None) is None:
                    # first-writer-wins marker discipline
                    err.caps_chaos_fault = True
                raise err
            return orig(sock, obj)

        wire.send_frame = chaotic
    try:
        yield budget
    finally:
        with OPERATOR_PATCH._lock:
            wire.send_frame = orig


@contextlib.contextmanager
def slow_backend(port: int, delay_s: float,
                 n_times: Optional[int] = None, every_n: int = 1):
    """While active, fleet wire sends TO ONE PEER (matched by remote
    port) sleep ``delay_s`` through ``obs.clock`` first — the targeted
    straggler.  :func:`~caps_tpu.testing.faults.slow_network` slows
    every link; this slows exactly one backend, which is the shape the
    hedged-read path exists for (one slow replica must not own the
    fleet's p99).  Injections count ``faults.injected.slow_backend``;
    yields the budget."""
    from caps_tpu.serve import wire
    port = int(port)
    budget = _Budget(n_times, every_n)

    with OPERATOR_PATCH._lock:
        orig = wire.send_frame

        def slowed(sock, obj):
            try:
                peer = sock.getpeername()[1]
            except OSError:
                peer = None
            if peer == port and budget.take():
                _count_injection("slow_backend")
                clock.sleep(delay_s)
            return orig(sock, obj)

        wire.send_frame = slowed
    try:
        yield budget
    finally:
        with OPERATOR_PATCH._lock:
            wire.send_frame = orig


# -- the schedule ------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ChaosEvent:
    """One scheduled fault: when (seconds from soak start), which
    injector, against which target (a backend/router name, or None for
    untargeted patch faults), with which parameters."""

    at_s: float
    injector: str
    target: Optional[str]
    params: Tuple[Tuple[str, Any], ...]

    def param(self, key: str, default: Any = None) -> Any:
        return dict(self.params).get(key, default)

    def as_dict(self) -> Dict[str, Any]:
        return {"at_s": self.at_s, "injector": self.injector,
                "target": self.target, "params": dict(self.params)}


def _freeze_params(params: Dict[str, Any]) -> Tuple[Tuple[str, Any], ...]:
    return tuple(sorted(params.items()))


#: parameter samplers per injector — every drawn float is rounded so
#: the canonical JSON (and therefore the digest) is platform-stable
_PARAM_SAMPLERS: Dict[str, Callable[[random.Random], Dict[str, Any]]] = {
    "chaos_fault": lambda rng: {"n_times": rng.randint(1, 2)},
    "drop_connection": lambda rng: {"n_times": rng.randint(1, 2)},
    "slow_network": lambda rng: {
        "delay_s": round(rng.uniform(0.002, 0.02), 6),
        "n_times": rng.randint(1, 4)},
    "slow_backend": lambda rng: {
        "delay_s": round(rng.uniform(0.005, 0.05), 6),
        "n_times": rng.randint(2, 6)},
    "torn_wal": lambda rng: {"n_bytes": rng.randint(0, 8), "n_times": 1},
    "failing_fsync": lambda rng: {"n_times": 1},
    "kill_backend": lambda rng: {},
    "kill_router_active": lambda rng: {},
}

#: the untargeted patch-fault menu ``compose`` draws from by default —
#: transport and durability faults that any soak can absorb
DEFAULT_MENU: Tuple[str, ...] = (
    "chaos_fault", "drop_connection", "slow_network")


def _patch_injector(name: str) -> Callable[[ChaosEvent], Any]:
    from caps_tpu.testing import faults

    def build(ev: ChaosEvent):
        if name == "chaos_fault":
            return chaos_fault(n_times=ev.param("n_times", 1))
        if name == "drop_connection":
            return faults.drop_connection(n_times=ev.param("n_times", 1))
        if name == "slow_network":
            return faults.slow_network(ev.param("delay_s", 0.005),
                                       n_times=ev.param("n_times", 1))
        if name == "torn_wal":
            return faults.torn_wal(n_bytes=ev.param("n_bytes", 6),
                                   n_times=ev.param("n_times", 1))
        if name == "failing_fsync":
            return faults.failing_fsync(n_times=ev.param("n_times", 1))
        raise KeyError(name)  # pragma: no cover — registry covers all
    return build


#: in-process injectors the runner can apply itself (each returns a
#: live context manager over the locked patch points); anything else
#: must come through the host's ``actions``
PATCH_INJECTORS: Dict[str, Callable[[ChaosEvent], Any]] = {
    name: _patch_injector(name)
    for name in ("chaos_fault", "drop_connection", "slow_network",
                 "torn_wal", "failing_fsync")}


class ChaosSchedule:
    """A deterministic, seed-addressed fault schedule."""

    def __init__(self, seed: int, duration_s: float,
                 events: Sequence[ChaosEvent]):
        self.seed = int(seed)
        self.duration_s = float(duration_s)
        self.events: Tuple[ChaosEvent, ...] = tuple(
            sorted(events, key=lambda e: (e.at_s, e.injector,
                                          e.target or "")))

    @classmethod
    def compose(cls, seed: int, duration_s: float, *,
                menu: Sequence[str] = DEFAULT_MENU,
                targets: Sequence[str] = (),
                n_events: int = 8,
                headline: Optional[str] = None,
                headline_at_frac: float = 0.4,
                registry: Optional[MetricsRegistry] = None
                ) -> "ChaosSchedule":
        """Draw ``n_events`` fault events from ``random.Random(seed)``
        over ``menu`` — which injector, which target, when — plus the
        optional ``headline`` event pinned at ``headline_at_frac`` of
        the soak (the chaos bench pins ``kill_router_active`` there).
        The draw order is fixed (time, injector, target per event, in
        sequence), so the same seed composes the identical schedule on
        any host."""
        rng = random.Random(int(seed))
        duration_s = float(duration_s)
        menu = list(menu)
        targets = list(targets)
        events: List[ChaosEvent] = []
        for _ in range(int(n_events)):
            at = round(rng.uniform(0.05, 0.95) * duration_s, 6)
            name = rng.choice(menu)
            target = rng.choice(targets) if targets else None
            sampler = _PARAM_SAMPLERS.get(name, lambda _rng: {})
            events.append(ChaosEvent(at, name, target,
                                     _freeze_params(sampler(rng))))
        if headline is not None:
            events.append(ChaosEvent(
                round(duration_s * float(headline_at_frac), 6),
                headline, None, ()))
        reg = registry if registry is not None else global_registry()
        reg.counter("chaos.schedules_composed").inc()
        return cls(seed, duration_s, events)

    def as_dict(self) -> Dict[str, Any]:
        return {"seed": self.seed, "duration_s": self.duration_s,
                "events": [e.as_dict() for e in self.events]}

    def digest(self) -> str:
        """sha256 of the canonical JSON — same seed ⇒ same digest, on
        any host, or the run is not the run you think it is."""
        canon = json.dumps(self.as_dict(), sort_keys=True)
        return hashlib.sha256(canon.encode("utf-8")).hexdigest()


class ChaosRunner:
    """Apply a schedule's events as a soak's own clock passes them.

    A pure pump: :meth:`poll` fires every event whose ``at_s`` the
    caller-supplied elapsed time has passed.  Patch events enter their
    injector context managers on a shared exit stack (unwound when the
    runner exits — budgets usually retire them long before); events
    whose injector appears in ``actions`` are delegated to the host
    (process kills), with the event as the single argument."""

    def __init__(self, schedule: ChaosSchedule, *,
                 actions: Optional[Dict[str, Callable[[ChaosEvent],
                                                      Any]]] = None,
                 registry: Optional[MetricsRegistry] = None):
        self.schedule = schedule
        self._actions = dict(actions or {})
        self._registry = registry if registry is not None \
            else global_registry()
        self._stack = contextlib.ExitStack()
        self._next = 0
        self.applied: List[ChaosEvent] = []
        unknown = [e.injector for e in schedule.events
                   if e.injector not in self._actions
                   and e.injector not in PATCH_INJECTORS]
        if unknown:
            raise KeyError(
                f"schedule names injectors this runner cannot apply: "
                f"{sorted(set(unknown))} — pass actions for them")

    def __enter__(self) -> "ChaosRunner":
        return self

    def __exit__(self, *exc) -> None:
        self._stack.close()

    def pending(self) -> int:
        return len(self.schedule.events) - self._next

    def poll(self, elapsed_s: float) -> List[ChaosEvent]:
        """Fire every not-yet-applied event due at ``elapsed_s``;
        returns the events fired by THIS call."""
        fired: List[ChaosEvent] = []
        events = self.schedule.events
        while self._next < len(events) \
                and events[self._next].at_s <= elapsed_s:
            ev = events[self._next]
            self._next += 1
            action = self._actions.get(ev.injector)
            if action is not None:
                action(ev)
            else:
                self._stack.enter_context(PATCH_INJECTORS[ev.injector](ev))
            self._registry.counter("chaos.events_applied").inc()
            self.applied.append(ev)
            fired.append(ev)
        return fired


# -- invariants --------------------------------------------------------------


class ChaosInvariants:
    """The soak's ledger of observations, rendered into verdicts.

    * **zero acked-write loss** — every acknowledged write must be in
      the surviving state: digest parity between the fleet's final read
      and a serial oracle replaying the same acked statements;
    * **no stale reads** — per reader, observed snapshot versions never
      regress (a cache or a rejoined peer served yesterday's graph);
    * **availability floor** — failed reads stay under the budgeted
      fraction (hedges that won do NOT count twice: one logical read,
      one outcome);
    * **no zombie application** — every fence probe from a deposed
      owner or router was refused (StaleEpoch), none applied.
    """

    def __init__(self, registry: Optional[MetricsRegistry] = None):
        self._registry = registry if registry is not None \
            else global_registry()
        self.reads_ok = 0
        self.reads_failed = 0
        self.stale_reads = 0
        self.acked_writes = 0
        self.fence_refusals = 0
        self.fence_violations = 0
        self._reader_versions: Dict[str, int] = {}

    def note_read(self, reader: str, ok: bool,
                  version: Optional[int] = None) -> None:
        if not ok:
            self.reads_failed += 1
            return
        self.reads_ok += 1
        if version is None:
            return
        last = self._reader_versions.get(reader)
        if last is not None and int(version) < last:
            self.stale_reads += 1
        self._reader_versions[reader] = max(
            int(version), last if last is not None else int(version))

    def note_write_ack(self) -> None:
        self.acked_writes += 1

    def note_fence(self, refused: bool) -> None:
        if refused:
            self.fence_refusals += 1
        else:
            self.fence_violations += 1

    def availability(self) -> float:
        total = self.reads_ok + self.reads_failed
        return (self.reads_ok / total) if total else 1.0

    def report(self, *, availability_floor: float = 0.0,
               oracle_digest: Optional[str] = None,
               observed_digest: Optional[str] = None) -> Dict[str, Any]:
        """The verdicts; failed checks count
        ``chaos.invariant_failures`` (one per failed check)."""
        checks: Dict[str, bool] = {
            "availability": self.availability() >= availability_floor,
            "no_stale_reads": self.stale_reads == 0,
            "no_zombie_application": self.fence_violations == 0,
        }
        if oracle_digest is not None or observed_digest is not None:
            checks["acked_write_parity"] = (
                oracle_digest is not None
                and oracle_digest == observed_digest)
        failures = sum(1 for ok in checks.values() if not ok)
        if failures:
            self._registry.counter("chaos.invariant_failures").inc(failures)
        return {"ok": failures == 0, "checks": checks,
                "availability": self.availability(),
                "reads_ok": self.reads_ok,
                "reads_failed": self.reads_failed,
                "stale_reads": self.stale_reads,
                "acked_writes": self.acked_writes,
                "fence_refusals": self.fence_refusals,
                "fence_violations": self.fence_violations}

"""CREATE-string graph factory.

Mirrors the reference's ``CreateGraphFactory``/``CypherCreateParser`` +
``CAPSScanGraphFactory`` (ref: okapi-testing and spark-cypher-testing —
reconstructed, mount empty; SURVEY.md §3.5): parse a ``CREATE`` pattern
through the engine's own front-end, build an in-memory property graph,
group nodes by label-set and relationships by type into scan tables.

This is how every acceptance test bootstraps its graph:

    g = create_graph(session, "CREATE (a:Person {name:'Alice'})-[:KNOWS]->(b)")
"""
from __future__ import annotations

from typing import Any, Dict, List, Mapping, Optional, Tuple

from caps_tpu.frontend import ast
from caps_tpu.frontend.parser import parse_query
from caps_tpu.ir import exprs as E
from caps_tpu.relational.entity_tables import NodeTable, RelationshipTable
from caps_tpu.relational.graphs import ScanGraph


class GraphFactoryError(Exception):
    pass


def _eval_literal(expr: E.Expr, params: Mapping[str, Any]) -> Any:
    if isinstance(expr, E.Lit):
        return expr.value
    if isinstance(expr, E.Param):
        return params[expr.name]
    if isinstance(expr, E.Negate):
        return -_eval_literal(expr.expr, params)
    if isinstance(expr, E.ListLit):
        return [_eval_literal(i, params) for i in expr.items]
    if isinstance(expr, E.MapLit):
        return {k: _eval_literal(v, params)
                for k, v in zip(expr.keys, expr.values)}
    if isinstance(expr, E.FunctionExpr) \
            and expr.name in ("date", "datetime", "localdatetime",
                              "duration"):
        from caps_tpu.okapi.values import temporal_construct
        try:
            return temporal_construct(
                expr.name, *[_eval_literal(a, params) for a in expr.args])
        except (ValueError, TypeError) as ex:
            raise GraphFactoryError(str(ex))
    raise GraphFactoryError(
        f"CREATE properties must be literals, got {expr!r}")


class InMemoryTestGraph:
    """Plain node/rel records before table grouping (the reference's
    ``InMemoryTestGraph``)."""

    def __init__(self):
        self.nodes: Dict[int, Tuple[Tuple[str, ...], Dict[str, Any]]] = {}
        self.rels: List[Tuple[int, int, int, str, Dict[str, Any]]] = []
        self._next_id = 0

    def add_node(self, labels: Tuple[str, ...], props: Dict[str, Any]) -> int:
        nid = self._next_id
        self._next_id += 1
        self.nodes[nid] = (tuple(sorted(labels)), props)
        return nid

    def add_rel(self, src: int, tgt: int, rel_type: str,
                props: Dict[str, Any]) -> int:
        rid = self._next_id
        self._next_id += 1
        self.rels.append((rid, src, tgt, rel_type, props))
        return rid


def parse_create(create_query: str,
                 parameters: Optional[Mapping[str, Any]] = None
                 ) -> InMemoryTestGraph:
    """Parse one-or-more CREATE clauses into an in-memory graph."""
    params = dict(parameters or {})
    stmt = parse_query(create_query)
    if not isinstance(stmt, ast.SingleQuery):
        raise GraphFactoryError("factory expects a plain CREATE statement")
    g = InMemoryTestGraph()
    env: Dict[str, int] = {}
    for clause in stmt.clauses:
        if isinstance(clause, ast.UnwindClause):
            raise GraphFactoryError("UNWIND is not supported in the factory")
        if not isinstance(clause, ast.CreateClause):
            raise GraphFactoryError(
                f"factory only supports CREATE clauses, got "
                f"{type(clause).__name__}")
        for part in clause.pattern.parts:
            prev: Optional[int] = None
            pending_rel: Optional[ast.RelPattern] = None
            for el in part.elements:
                if isinstance(el, ast.NodePattern):
                    if el.var is not None and el.var in env:
                        if el.labels or el.properties is not None:
                            raise GraphFactoryError(
                                f"variable `{el.var}` already declared; "
                                "reference it without labels/properties")
                        nid = env[el.var]
                    else:
                        props = {}
                        if el.properties is not None:
                            props = _eval_literal(el.properties, params)
                        nid = g.add_node(el.labels, props)
                        if el.var is not None:
                            env[el.var] = nid
                    if pending_rel is not None:
                        rel = pending_rel
                        props = {}
                        if rel.properties is not None:
                            props = _eval_literal(rel.properties, params)
                        if len(rel.rel_types) != 1:
                            raise GraphFactoryError(
                                "CREATE relationships need exactly one type")
                        if rel.direction == ast.Direction.INCOMING:
                            g.add_rel(nid, prev, rel.rel_types[0], props)
                        elif rel.direction == ast.Direction.OUTGOING:
                            g.add_rel(prev, nid, rel.rel_types[0], props)
                        else:
                            raise GraphFactoryError(
                                "CREATE relationships must be directed")
                        pending_rel = None
                    prev = nid
                else:
                    pending_rel = el
    return g


def tables_from_memory(session, g: InMemoryTestGraph
                       ) -> Tuple[List[NodeTable], List[RelationshipTable]]:
    """Group in-memory records into scan tables.  Delegates to the
    shared record-grouping builders in relational/updates.py — the SAME
    code that materializes delta stores and compacted bases, so the
    factory, the write path, and compaction agree on layout by
    construction."""
    from caps_tpu.relational.updates import (build_node_tables,
                                             build_rel_tables)
    factory = session.table_factory
    node_tables = build_node_tables(
        factory, [(nid, labels, props)
                  for nid, (labels, props) in g.nodes.items()])
    rel_tables = build_rel_tables(factory, g.rels)
    return node_tables, rel_tables


def create_graph(session, create_query: str = "",
                 parameters: Optional[Mapping[str, Any]] = None) -> ScanGraph:
    """Build a ScanGraph from a CREATE statement (empty string → empty graph)."""
    if not create_query.strip():
        return session.create_graph((), ())
    g = parse_create(create_query, parameters)
    node_tables, rel_tables = tables_from_memory(session, g)
    return session.create_graph(node_tables, rel_tables)

"""Composable, thread-safe fault injection.

SURVEY.md §5.3: the reference inherits failure detection from Spark
(lineage re-execution, executor blacklisting) and ships no fault-injection
tests of its own; single-controller JAX has no task retry, so our
failure-containment layer (``caps_tpu/serve/``: transient retry, plan
quarantine, degraded execution) needs faults it can practice against.
This module provides them:

* :func:`failing_operator` — raise a chosen exception from one
  relational operator's ``_compute``, transiently (``n_times=1`` fails
  the next execution then heals) or permanently (``n_times=None``);
* :func:`slow_operator` — deterministic per-operator delay (deadline /
  cancellation tests without sleep-and-hope timing);
* :func:`slow_compile` — deterministic delay + accounting inflation at
  every compile-boundary charge (obs/compile.py), so cold-cliff and
  AOT-warmup tests run on the fake clock instead of real XLA compiles;
* :func:`device_oom` — a realistic ``XlaRuntimeError``-shaped
  ``RESOURCE_EXHAUSTED``, injected at an operator boundary or into
  ingest placement;
* :func:`device_loss` / :func:`sick_device` — device-SCOPED faults for
  the fault-domain serving tier (serve/devices.py): a permanent
  ``UNAVAILABLE`` stream (dead device) or a deterministic error-rate
  trickle (flaky device), injected ONLY into the replica whose
  ``executing_device_index()`` matches — other devices' operator
  streams never see them;
* :func:`shard_loss` / :func:`sick_shard` — shard-SCOPED faults for the
  shard-group serving tier (serve/shards.py): a member's death (or a
  deterministic error trickle) injected ONLY into executions of the
  targeted group that touch the targeted member — its own single-shard
  stream plus the group-wide cross-shard programs that physically span
  it, keyed by ``executing_shard()`` — so group-degradation tests are
  deterministic and other members / plain replicas never see the fault;
* :func:`flaky_ingest` — fail the first N table ingests of a session
  with a transient device error;
* :func:`abort_write` — abort a versioned-graph commit after N delta
  columns placed (the failure-atomicity probe: the commit must roll
  back completely and a retried write must succeed);
* :func:`flaky_compaction` — fail a deterministic fraction of
  compaction folds, scoped to the compaction thread only (serving
  and writes never see it);
* :func:`torn_wal` — tear the next commit-log frame write mid-frame
  (caps_tpu/durability): the on-disk image is exactly what a SIGKILL
  leaves, so crash-recovery tests can prove the torn tail drops
  honestly without killing a process;
* :func:`failing_fsync` — fail the next commit-log fsync with a
  ``caps_wal_fault``-marked OSError (the "disk went away at the
  durability barrier" probe: typed transient error, never a silent
  acknowledgement);
* :func:`stale_cache` — forge a wrong-version result-cache entry
  (relational/result_cache.py) at the load seam, proving the
  snapshot-version check rejects it (a served forgery raises a fresh
  ``caps_stale_cache``-marked error instead of silent wrong rows);
* :func:`corrupt_shard` — silent data damage on one shard (digest /
  parity detection tests);
* :func:`stale_statistics` — distort one graph's ingest-time
  statistics sketch (relational/stats.py) by a scale factor, the
  deterministic "stats-violating workload": the cost model prices
  plans from the distorted prior while executions observe the true
  cardinalities, so model divergence → quarantine → re-planning
  (relational/session.py ``_maybe_replan``) can be practiced
  end-to-end (tests/test_cost.py);
* :class:`FaultPlan` — compose any of the above into one context
  manager.

All operator-level faults route through ONE locked patch point
(:class:`_OperatorPatch`): each operator class is monkey-patched at most
once, active hooks stack in installation order, nesting and concurrent
``with`` blocks from different threads are safe, and the original
``_compute`` is restored exactly when the last hook leaves.  Injection
counts land in the process-global MetricsRegistry under
``faults.injected.*`` so a soak run can assert how much damage was
actually dealt.

Exception freshness: injectors construct a NEW exception object per
injection (an instance argument is treated as a template and re-built
via ``type(exc)(*exc.args)``).  Two batch members hit by "the same"
fault must never share one mutable error object — the serving tier's
per-member isolation contract depends on it (tests/test_faults.py).
"""
from __future__ import annotations

import contextlib
import warnings
from typing import Callable, Dict, List, Optional, Type, Union

import jax.numpy as jnp

from caps_tpu.obs import clock
from caps_tpu.obs.lockgraph import make_lock, make_rlock
from caps_tpu.obs.metrics import global_registry


def xla_runtime_error_class() -> Type[BaseException]:
    """The real jaxlib ``XlaRuntimeError`` when available (so injected
    device faults are indistinguishable from genuine ones), else a
    same-named stub."""
    try:
        from jaxlib.xla_extension import XlaRuntimeError
        return XlaRuntimeError
    except Exception:  # pragma: no cover — stub for jaxlib-less installs
        class XlaRuntimeError(RuntimeError):
            pass
        return XlaRuntimeError


def make_oom(note: str = "") -> BaseException:
    """A fresh ``RESOURCE_EXHAUSTED`` in the exact shape the TPU runtime
    raises it (message prefix included — serve/failure.py classifies by
    those status words)."""
    cls = xla_runtime_error_class()
    return cls("RESOURCE_EXHAUSTED: Attempting to allocate 2.50G. That was"
               " not possible. There are 1.25G free."
               + (f" [{note}]" if note else ""))


def _resolve_operator(op_name: str) -> type:
    """Resolve ``"Filter"``/``"FilterOp"`` to its operator class.  Looks
    in relational/ops.py first, then the satellite operator modules
    (count_pattern's SpMV pushdown, var_expand) — a fault aimed at
    ``"CountPattern"`` must hook the operator that actually executes
    when the planner pushes an aggregate down."""
    from caps_tpu.relational import count_pattern as CP
    from caps_tpu.relational import ops as R
    from caps_tpu.relational import var_expand as VE
    from caps_tpu.relational import wcoj as WJ
    cls_name = op_name if op_name.endswith("Op") else op_name + "Op"
    for mod in (R, CP, VE, WJ):
        cls = getattr(mod, cls_name, None)
        if isinstance(cls, type) and issubclass(cls, R.RelationalOperator):
            return cls
    raise ValueError(f"unknown relational operator {op_name!r}")


ExcSpec = Union[BaseException, Type[BaseException],
                Callable[[], BaseException], None]


def _fresh_exception(spec: ExcSpec) -> BaseException:
    """Build a NEW exception object from a spec (see module docstring)."""
    if spec is None:
        return make_oom()
    if isinstance(spec, BaseException):
        try:
            return type(spec)(*spec.args)
        except Exception:
            return type(spec)(str(spec))
    return spec()  # class or zero-arg factory


class _Budget:
    """Locked injection schedule shared across threads: fire on every
    ``every_n``-th eligible invocation (1 = every one), at most
    ``n_times`` total (None = unlimited — a permanent fault).

    ``every_n > 1`` is the deterministic "~1/N of executions fail once"
    shape the soak acceptance uses: an immediate retry is invocation
    k+1, never again on the every-N boundary, so a single-shot retry
    always heals — no luck involved."""

    def __init__(self, n_times: Optional[int], every_n: int = 1):
        self._n = n_times
        self._every = max(1, int(every_n))
        self._lock = make_lock("faults._Budget._lock")
        self._calls = 0
        self.injected = 0

    def take(self) -> bool:
        with self._lock:
            if self._n is not None and self._n <= 0:
                return False
            self._calls += 1
            if (self._calls - 1) % self._every:
                return False
            if self._n is not None:
                self._n -= 1
            self.injected += 1
            return True


class _OperatorPatch:
    """The ONE patch point for relational-operator fault hooks.

    Each operator class's ``_compute`` is replaced (at most once, under
    the lock) by a dispatcher that runs the class's active hooks in
    installation order and then calls the original.  Hooks are plain
    callables ``hook(op_instance) -> None`` that may sleep or raise.
    When a class's last hook is removed its original ``_compute`` is
    restored — nothing stays patched after the outermost ``with``
    exits, however the contexts were nested or threaded."""

    def __init__(self):
        self._lock = make_rlock("faults._OperatorPatch._lock")
        self._originals: Dict[type, Callable] = {}
        self._hooks: Dict[type, List[Callable]] = {}

    def _dispatcher(self, cls: type) -> Callable:
        def _compute_with_hooks(op_self):
            with self._lock:
                hooks = list(self._hooks.get(cls, ()))
                orig = self._originals.get(cls)
            for hook in hooks:  # hooks run OUTSIDE the lock: they sleep
                hook(op_self)
            if orig is None:  # pragma: no cover — unpatch raced us; the
                return cls._compute(op_self)  # restored original is live
            return orig(op_self)
        return _compute_with_hooks

    @contextlib.contextmanager
    def hooked(self, cls: type, hook: Callable):
        with self._lock:
            if cls not in self._originals:
                # the class's own _compute if it defines one, else the
                # inherited one (restored verbatim either way)
                self._originals[cls] = cls.__dict__.get(
                    "_compute", cls._compute)
                cls._compute = self._dispatcher(cls)
            self._hooks.setdefault(cls, []).append(hook)
        try:
            yield
        finally:
            with self._lock:
                hooks = self._hooks.get(cls, [])
                if hook in hooks:
                    hooks.remove(hook)
                if not hooks:
                    self._hooks.pop(cls, None)
                    orig = self._originals.pop(cls, None)
                    if orig is not None:
                        cls._compute = orig


#: process-wide patch point (module-level: every FaultPlan and bare
#: context manager composes through the same locks)
OPERATOR_PATCH = _OperatorPatch()


def _count_injection(name: str) -> None:
    global_registry().counter(f"faults.injected.{name}").inc()


@contextlib.contextmanager
def _patched_place_column(backend, wrap: Callable[[Callable], Callable]):
    """The ONE install/restore path for ingest-placement faults
    (flaky_ingest, corrupt_shard): replaces ``backend.place_column``
    with ``wrap(original)`` under the shared fault lock and restores the
    captured original on exit.  Nesting is LIFO (each context captures
    whatever is installed when it enters, like the operator hooks)."""
    with OPERATOR_PATCH._lock:
        orig = backend.place_column
        backend.place_column = wrap(orig)
    try:
        yield
    finally:
        with OPERATOR_PATCH._lock:
            backend.place_column = orig


@contextlib.contextmanager
def slow_operator(op_name: str, delay_s: float):
    """While active, every ``_compute`` of the named relational operator
    class (``"Filter"`` or ``"FilterOp"``) sleeps ``delay_s`` first —
    process-wide, so any session's queries slow down deterministically.

    The serving tests use this to force a deadline to expire INSIDE the
    execute phase: the delayed operator finishes (cancellation is
    cooperative — dispatched work is never torn down), and the next
    operator boundary's checkpoint raises ``DeadlineExceeded`` with
    ``phase="execute"``.  No test ever has to guess how long a real
    query takes."""
    cls = _resolve_operator(op_name)

    def hook(_op):
        _count_injection("slow_operator")
        clock.sleep(delay_s)

    with OPERATOR_PATCH.hooked(cls, hook):
        yield


@contextlib.contextmanager
def slow_compile(delay_s: float, n_times: Optional[int] = None,
                 kinds=None):
    """While active, compile-boundary charges are deterministically slow:
    every :class:`caps_tpu.obs.compile.CompileLedger` charge (optionally
    filtered to ``kinds`` — e.g. ``("plan", "fused_record")``) sleeps
    ``delay_s`` through ``obs.clock`` and reports ``seconds + delay_s``,
    so on a fake clock a "35-second cold compile" costs zero real time
    and its ledger accounting is exactly assertable.

    The cold-cliff and AOT-warmup tests (tests/test_warmup.py) use this
    instead of relying on real XLA compile times: ``n_times=1`` makes
    only the FIRST boundary slow (the cliff a warmed process must not
    pay again), ``n_times=None`` slows every one.  Installed/restored
    under the shared fault lock like every other patch point; injections
    count ``faults.injected.slow_compile``.  Yields the budget
    (``.injected``)."""
    from caps_tpu.obs.compile import CompileLedger
    budget = _Budget(n_times)
    want = None if kinds is None else frozenset(kinds)

    with OPERATOR_PATCH._lock:
        orig = CompileLedger.charge

        def slowed(self, family, kind, seconds, shape=None):
            if (want is None or kind in want) and budget.take():
                _count_injection("slow_compile")
                clock.sleep(delay_s)
                seconds = float(seconds) + delay_s
            return orig(self, family, kind, seconds, shape=shape)

        CompileLedger.charge = slowed
    try:
        yield budget
    finally:
        with OPERATOR_PATCH._lock:
            CompileLedger.charge = orig


@contextlib.contextmanager
def slow_network(delay_s: float, n_times: Optional[int] = None,
                 every_n: int = 1):
    """While active, fleet wire sends (``serve/wire.py send_frame``) are
    deterministically slow: each eligible send sleeps ``delay_s``
    through ``obs.clock`` before hitting the socket — on a fake clock a
    "congested fleet link" costs zero real time, and router latency /
    snapshot-lag assertions become exact.

    Patches the MODULE attribute (both the backend's reply path and the
    client's request path resolve ``wire.send_frame`` at call time, so
    one patch point covers every direction) under the shared fault
    lock; injections count ``faults.injected.slow_network``.  Yields
    the budget (``.injected``)."""
    from caps_tpu.serve import wire
    budget = _Budget(n_times, every_n)

    with OPERATOR_PATCH._lock:
        orig = wire.send_frame

        def slowed(sock, obj):
            if budget.take():
                _count_injection("slow_network")
                clock.sleep(delay_s)
            return orig(sock, obj)

        wire.send_frame = slowed
    try:
        yield budget
    finally:
        with OPERATOR_PATCH._lock:
            wire.send_frame = orig


class _ForgedCacheEntry:
    """A wrong-version result-cache entry (see :func:`stale_cache`):
    the version reads one AHEAD of the real entry's, and touching
    ``rows`` — which only a BROKEN version check would do — raises a
    fresh marked exception.  A correct lookup rejects the forgery on
    version alone and never trips the trap."""

    def __init__(self, real, exc_spec: ExcSpec):
        self._real = real
        self._exc_spec = exc_spec
        self.key = real.key
        self.version = real.version + 1
        self.nbytes = real.nbytes
        self.service_s = real.service_s
        self.hits = real.hits
        self.stored_t = real.stored_t
        self.last_t = real.last_t

    @property
    def rows(self):
        err = _fresh_exception(self._exc_spec)
        if getattr(err, "caps_stale_cache", None) is None:
            # first-writer-wins marker discipline (serve/failure.py):
            # never overwrite a classification already stamped
            try:
                err.caps_stale_cache = True
            except Exception:  # pragma: no cover — slotted exception
                pass
        raise err


@contextlib.contextmanager
def stale_cache(n_times: Optional[int] = 1, every_n: int = 1,
                exc: ExcSpec = None):
    """While active, eligible result-cache loads
    (:meth:`caps_tpu.relational.result_cache.ResultCache._load`) return
    a FORGED entry whose snapshot version is wrong (one ahead of the
    real entry's) — the deterministic probe that the cache's version
    check actually rejects stale entries.

    A correct ``lookup`` sees the version mismatch, counts a
    ``rescache.stale_rejects``, drops the (real) entry, and reports a
    miss — the caller re-executes and repopulates; the forgery's
    ``rows`` are NEVER touched.  A broken check that served the forgery
    would raise a fresh ``AssertionError`` per injection (template
    overridable via ``exc``), marked ``caps_stale_cache`` first-writer-
    wins — so the failure is attributable even after the serving tier's
    classify/retry ladder wraps it.  Loads that find no entry inject
    nothing (there is nothing to forge).  Installed/restored under the
    shared fault lock; injections count ``faults.injected.stale_cache``.
    Yields the budget (``.injected``)."""
    from caps_tpu.relational.result_cache import ResultCache
    if exc is None:
        exc = lambda: AssertionError(  # noqa: E731 — fresh per injection
            "injected: stale result-cache entry was served")
    budget = _Budget(n_times, every_n)

    with OPERATOR_PATCH._lock:
        orig = ResultCache._load

        def forging(self, key):
            entry = orig(self, key)
            if entry is not None and budget.take():
                _count_injection("stale_cache")
                return _ForgedCacheEntry(entry, exc)
            return entry

        ResultCache._load = forging
    try:
        yield budget
    finally:
        with OPERATOR_PATCH._lock:
            ResultCache._load = orig


@contextlib.contextmanager
def drop_connection(exc: ExcSpec = None, n_times: Optional[int] = 1,
                    every_n: int = 1):
    """While active, eligible fleet wire sends fail with a FRESH
    connection-level error (default: ``ConnectionResetError``) instead
    of reaching the socket — the deterministic stand-in for a backend
    process dying mid-call.

    The injected OSError surfaces exactly as the real path would —
    wrapped into a transient :class:`~caps_tpu.serve.errors.WireError`
    (what ``send_frame`` raises when ``sendall`` fails), counting a
    ``wire.drops`` — so what the router must do next — degrade the
    ring segment, retry the request on the next node — is exercised
    without killing a real process.  ``n_times=1`` (the default) is
    the canonical one-shot drop: the first affected call fails, the
    failover lands, traffic continues.  Yields the budget
    (``.injected``); injections count
    ``faults.injected.drop_connection``."""
    from caps_tpu.serve import wire
    from caps_tpu.serve.errors import ServeError, WireError
    if exc is None:
        exc = ConnectionResetError("injected: connection dropped")
    budget = _Budget(n_times, every_n)

    with OPERATOR_PATCH._lock:
        orig = wire.send_frame

        def dropping(sock, obj):
            if budget.take():
                _count_injection("drop_connection")
                err = _fresh_exception(exc)
                if isinstance(err, ServeError):
                    raise err
                # the patch point sits where send_frame's own OSError
                # conversion lives — surface the same typed shape
                global_registry().counter("wire.drops").inc()
                raise WireError(
                    f"send failed: {type(err).__name__}: {err}")
            return orig(sock, obj)

        wire.send_frame = dropping
    try:
        yield budget
    finally:
        with OPERATOR_PATCH._lock:
            wire.send_frame = orig


@contextlib.contextmanager
def failing_operator(op_name: str, exc: ExcSpec = None,
                     n_times: Optional[int] = None, every_n: int = 1):
    """While active, the named operator's ``_compute`` raises before
    computing — a FRESH exception per injection, built from ``exc`` (an
    exception template, an exception class, a zero-arg factory, or None
    for a realistic device OOM).

    ``n_times`` bounds the total injections across all threads:
    ``n_times=1`` is the canonical transient fault (fails once, then
    heals — the retry path must succeed), ``n_times=None`` is a
    permanent fault (the circuit-breaker path must trip).  ``every_n``
    spaces injections out deterministically — ``every_n=5`` fails every
    5th execution, i.e. ~20% of requests fail exactly once and every
    single retry lands between boundaries and heals (the soak
    acceptance's fault shape).  Yields the budget object so tests can
    read ``.injected``."""
    cls = _resolve_operator(op_name)
    budget = _Budget(n_times, every_n)

    def hook(_op):
        if budget.take():
            _count_injection("failing_operator")
            raise _fresh_exception(exc)

    with OPERATOR_PATCH.hooked(cls, hook):
        yield budget


@contextlib.contextmanager
def failing_wcoj(exc: ExcSpec = None, n_times: Optional[int] = 1):
    """Fail the worst-case-optimal multiway join's DEVICE path
    (relational/wcoj.py ``MultiwayJoinOp._compute_wcoj``) — the
    degraded-ladder probe: the operator must catch the fault, count
    ``wcoj.fallbacks``, and serve the SAME answer through its embedded
    binary-cascade child, so tests of the fallback are deterministic
    instead of hoping for a real device fault.

    A FRESH exception per injection (``exc`` semantics as
    :func:`failing_operator`; default a realistic device OOM), stamped
    ``caps_wcoj_fault`` first-writer-wins at construction so assertions
    can attribute what they caught.  ``n_times=1`` fails exactly the
    next WCOJ execution then heals (the following execution must take
    the fast path again); ``n_times=None`` is permanent (every cyclic
    query serves via cascade).  Installed/restored on the shared fault
    lock like every other patch point; injections count
    ``faults.injected.wcoj``.  Yields the budget (``.injected``)."""
    from caps_tpu.relational.wcoj import MultiwayJoinOp
    budget = _Budget(n_times)

    with OPERATOR_PATCH._lock:
        orig = MultiwayJoinOp._compute_wcoj

        def faulted(op_self):
            if budget.take():
                _count_injection("wcoj")
                e = _fresh_exception(exc)
                if getattr(e, "caps_wcoj_fault", None) is None:
                    e.caps_wcoj_fault = True
                raise e
            return orig(op_self)

        MultiwayJoinOp._compute_wcoj = faulted
    try:
        yield budget
    finally:
        with OPERATOR_PATCH._lock:
            MultiwayJoinOp._compute_wcoj = orig


@contextlib.contextmanager
def failing_algo(exc: ExcSpec = None, n_times: Optional[int] = 1):
    """Fail the graph-algorithm procedure's DEVICE fixpoint path
    (algo/op.py ``AlgoProcedureOp._compute_device``) — the analytics
    tier's degraded-mode probe: the operator must catch the fault, count
    ``algo.fallbacks``, and serve the SAME answer through the NumPy
    host kernel (``algo/kernels.py``), so fallback-parity tests are
    deterministic instead of hoping for a real device fault.

    A FRESH exception per injection (``exc`` semantics as
    :func:`failing_operator`; default a realistic device OOM), stamped
    ``caps_algo_fault`` first-writer-wins at construction so assertions
    can attribute what they caught.  ``n_times=1`` fails exactly the
    next device dispatch then heals (the following execution must take
    the device path again); ``n_times=None`` is permanent (every CALL
    serves from the host twin).  Installed/restored on the shared fault
    lock like every other patch point; injections count
    ``faults.injected.algo``.  Yields the budget (``.injected``)."""
    from caps_tpu.algo.op import AlgoProcedureOp
    budget = _Budget(n_times)

    with OPERATOR_PATCH._lock:
        orig = AlgoProcedureOp._compute_device

        def faulted(op_self, data, bound):
            if budget.take():
                _count_injection("algo")
                e = _fresh_exception(exc)
                if getattr(e, "caps_algo_fault", None) is None:
                    e.caps_algo_fault = True
                raise e
            return orig(op_self, data, bound)

        AlgoProcedureOp._compute_device = faulted
    try:
        yield budget
    finally:
        with OPERATOR_PATCH._lock:
            AlgoProcedureOp._compute_device = orig


def _make_device_down(device_index: int) -> BaseException:
    """A fresh ``UNAVAILABLE`` in the shape a dead/preempted device
    raises it (serve/failure.py classifies the status word TRANSIENT —
    the retry lands on a DIFFERENT device — and ``device_fault`` counts
    it against this device's health ladder)."""
    cls = xla_runtime_error_class()
    exc = cls(f"UNAVAILABLE: device {device_index} has halted; "
              f"transport closed [injected device loss]")
    exc.caps_device_fault = True
    return exc


@contextlib.contextmanager
def device_loss(device_index: int, n_times: Optional[int] = None,
                op_name: str = "Scan"):
    """Kill ONE device replica: while active, every ``_compute`` of the
    named operator (default ``Scan`` — every query plan scans) raises a
    fresh device-``UNAVAILABLE`` error, but ONLY on the replica whose
    ``serve.devices.executing_device_index()`` matches ``device_index``
    — other replicas' operator streams are untouched, which is the
    fault-domain isolation the multi-device soak asserts.

    ``n_times=None`` (default) is a permanent loss: the device keeps
    failing — including its background reinstate probes — until the
    context exits, so the server must quarantine it and degrade to N-1
    devices.  ``n_times=K`` is a K-shot glitch (the probe after it
    heals the device).  Composable with :class:`FaultPlan`; yields the
    injection budget (``.injected``)."""
    cls = _resolve_operator(op_name)
    budget = _Budget(n_times)

    def hook(_op):
        from caps_tpu.serve.devices import executing_device_index
        if executing_device_index() != device_index:
            return
        if budget.take():
            _count_injection("device_loss")
            raise _make_device_down(device_index)

    with OPERATOR_PATCH.hooked(cls, hook):
        yield budget


@contextlib.contextmanager
def sick_device(device_index: int, error_rate: float = 0.2,
                n_times: Optional[int] = None, op_name: str = "Scan"):
    """A flaky (not dead) device replica: a deterministic ~``error_rate``
    fraction of the named operator's executions ON THIS DEVICE fail once
    with a transient device error (every ``round(1/error_rate)``-th
    eligible invocation — the same deterministic spacing as
    ``failing_operator(every_n=)``, so a single retry on another device
    always heals).  Scoped by ``executing_device_index()`` like
    :func:`device_loss`; yields the injection budget."""
    if not 0.0 < error_rate <= 1.0:
        raise ValueError(f"error_rate must be in (0, 1], got {error_rate}")
    cls = _resolve_operator(op_name)
    budget = _Budget(n_times, every_n=max(1, int(round(1.0 / error_rate))))

    def hook(_op):
        from caps_tpu.serve.devices import executing_device_index
        if executing_device_index() != device_index:
            return
        if budget.take():
            _count_injection("sick_device")
            raise _make_device_down(device_index)

    with OPERATOR_PATCH.hooked(cls, hook):
        yield budget


def _make_shard_down(group: str, member: Optional[int]) -> BaseException:
    """A fresh device-``UNAVAILABLE`` attributed to one shard-group
    member (serve/shards.py): ``caps_device_fault`` makes the group's
    ladder count it, ``caps_shard_member`` attributes the member so the
    MEMBER breaker (not the group's) climbs."""
    cls = xla_runtime_error_class()
    exc = cls(f"UNAVAILABLE: shard member {member} of group {group!r} "
              f"has halted; transport closed [injected shard loss]")
    exc.caps_device_fault = True
    if member is not None:
        exc.caps_shard_member = member
    return exc


def _shard_scope_matches(group: str, member: Optional[int]) -> bool:
    """True when the calling thread is executing inside the targeted
    shard scope: the member's own bracket, or — because a dead device
    also breaks every group-wide (cross-shard) program that spans it —
    the group-wide bracket (member None)."""
    from caps_tpu.serve.shards import executing_shard
    scope = executing_shard()
    if scope is None or scope[0] != group:
        return False
    if member is None:
        return True
    return scope[1] is None or scope[1] == member


@contextlib.contextmanager
def shard_loss(group: str, member: int, n_times: Optional[int] = None,
               op_name: str = "Scan"):
    """Kill ONE shard-group member: while active, every ``_compute`` of
    the named operator raises a fresh member-attributed device
    ``UNAVAILABLE`` — but ONLY inside executions of group ``group``
    that touch member ``member``: the member's own single-shard stream,
    AND the group-wide cross-shard programs (which physically span the
    dead device).  Other groups, other members' single-shard streams,
    and plain replica members never see it — the fault-domain isolation
    the sharded soak asserts.

    ``n_times=None`` is a permanent loss (the group must degrade and
    keep serving its other shards); ``n_times=K`` is a K-shot glitch —
    the background rebuild's canary after it heals the member (the
    "recovered device" the ISSUE's rebuild path targets).  Yields the
    injection budget (``.injected``)."""
    cls = _resolve_operator(op_name)
    budget = _Budget(n_times)

    def hook(_op):
        if not _shard_scope_matches(group, member):
            return
        if budget.take():
            _count_injection("shard_loss")
            raise _make_shard_down(group, member)

    with OPERATOR_PATCH.hooked(cls, hook):
        yield budget


@contextlib.contextmanager
def sick_shard(group: str, member: Optional[int] = None,
               error_rate: float = 0.2, n_times: Optional[int] = None,
               op_name: str = "Scan"):
    """A flaky (not dead) shard scope: a deterministic ~``error_rate``
    fraction of the named operator's executions inside group ``group``
    (optionally narrowed to one ``member``) fail once with a transient
    member-attributed device error — the same deterministic every-Nth
    spacing as ``sick_device``, so a single retry through the server's
    ladder always heals.  Yields the injection budget."""
    if not 0.0 < error_rate <= 1.0:
        raise ValueError(f"error_rate must be in (0, 1], got {error_rate}")
    cls = _resolve_operator(op_name)
    budget = _Budget(n_times, every_n=max(1, int(round(1.0 / error_rate))))

    def hook(_op):
        if not _shard_scope_matches(group, member):
            return
        if budget.take():
            _count_injection("sick_shard")
            from caps_tpu.serve.shards import executing_shard
            scope = executing_shard()
            raise _make_shard_down(group, scope[1] if scope else member)

    with OPERATOR_PATCH.hooked(cls, hook):
        yield budget


@contextlib.contextmanager
def device_oom(phase: str = "execute", op_name: str = "Scan",
               session=None, n_times: Optional[int] = 1):
    """A realistic device ``RESOURCE_EXHAUSTED`` (XlaRuntimeError-shaped,
    classified TRANSIENT by serve/failure.py).

    ``phase="execute"`` raises from the named operator's compute (any
    query touching it); ``phase="ingest"`` raises from ``session``'s
    column placement — ingest faults need the session whose backend is
    being damaged.  Yields the injection budget."""
    if phase == "execute":
        with failing_operator(op_name, make_oom, n_times=n_times) as budget:
            yield budget
        return
    if phase != "ingest":
        raise ValueError(f"device_oom phase must be 'execute' or "
                         f"'ingest', got {phase!r}")
    if session is None:
        raise ValueError("device_oom(phase='ingest') needs session=")
    with flaky_ingest(session, n_times=n_times, exc=make_oom) as budget:
        yield budget


def _make_write_abort() -> BaseException:
    """A fresh ``ABORTED`` in device-runtime shape: serve/failure.py
    classifies it TRANSIENT, so the server retries the write — which is
    SAFE precisely because the commit it interrupted rolled back
    completely (the atomicity the abort_write tests assert)."""
    cls = xla_runtime_error_class()
    return cls("ABORTED: transfer interrupted mid-commit "
               "[injected write abort]")


@contextlib.contextmanager
def abort_write(session, after_n_columns: int = 1,
                n_times: Optional[int] = 1, every_n: int = 1):
    """Abort a versioned-graph commit MID-APPLY: the first
    ``after_n_columns`` device column placements of each injection
    window succeed, then the next placement raises a fresh transient
    ``ABORTED`` device error — exactly the torn-write shape the
    failure-atomic commit (relational/updates.py) must roll back
    (delta tables dropped, string pool rolled back to the pre-commit
    mark, snapshot unchanged).

    ``n_times`` bounds total injections (None = permanent),
    ``every_n`` spaces them out over eligible placements — the soak
    acceptance's "~20% of writes abort once, every retry heals" shape.
    Compaction folds are NOT targeted (use :func:`flaky_compaction`).
    Yields the injection budget (``.injected``)."""
    backend = getattr(session, "backend", None)
    if backend is None or not hasattr(backend, "place_column"):
        raise ValueError("abort_write needs a device-backed session")
    budget = _Budget(n_times, every_n)
    survived = {"n": 0}
    state_lock = make_lock("faults.abort_write.state_lock")

    def wrap(orig):
        def poisoned(col):
            from caps_tpu.relational.updates import in_compaction
            if in_compaction():
                return orig(col)
            with state_lock:
                survived["n"] += 1
                fire = survived["n"] > after_n_columns
                if fire:
                    survived["n"] = 0  # next window builds afresh
            if fire and budget.take():
                _count_injection("abort_write")
                raise _make_write_abort()
            return orig(col)
        return poisoned

    with _patched_place_column(backend, wrap):
        yield budget


@contextlib.contextmanager
def flaky_compaction(session, error_rate: float = 0.5,
                     n_times: Optional[int] = None):
    """Fail a deterministic ~``error_rate`` fraction of COMPACTION
    column placements with a transient device error — scoped by the
    compaction thread-local (relational/updates.py ``in_compaction``),
    so concurrent writes and reads never see it.  The obligations under
    this fault: the fold rolls back (pool restored, snapshot
    unchanged), ``compaction.failures``/``faults.injected.*`` count it,
    serving continues, and the next fold attempt succeeds once the
    budget is spent.  Yields the injection budget."""
    if not 0.0 < error_rate <= 1.0:
        raise ValueError(f"error_rate must be in (0, 1], got {error_rate}")
    backend = getattr(session, "backend", None)
    if backend is None or not hasattr(backend, "place_column"):
        raise ValueError("flaky_compaction needs a device-backed session")
    budget = _Budget(n_times, every_n=max(1, int(round(1.0 / error_rate))))

    def wrap(orig):
        def poisoned(col):
            from caps_tpu.relational.updates import in_compaction
            if in_compaction() and budget.take():
                _count_injection("flaky_compaction")
                raise _make_write_abort()
            return orig(col)
        return poisoned

    with _patched_place_column(backend, wrap):
        yield budget


@contextlib.contextmanager
def flaky_ingest(session, n_times: Optional[int] = 1, exc: ExcSpec = None):
    """Fail the session's next ``n_times`` device column placements with
    a transient device error (default: the realistic OOM).  The engine's
    containment obligations under this fault: the ingest raises cleanly,
    and the string pool rolls back to its pre-ingest size so fused
    replayability is not silently invalidated (backends/tpu/table.py).
    Yields the injection budget."""
    backend = getattr(session, "backend", None)
    if backend is None or not hasattr(backend, "place_column"):
        raise ValueError("flaky_ingest needs a device-backed session")
    budget = _Budget(n_times)

    def wrap(orig):
        def poisoned(col):
            if budget.take():
                _count_injection("flaky_ingest")
                raise _fresh_exception(exc)
            return orig(col)
        return poisoned

    with _patched_place_column(backend, wrap):
        yield budget


@contextlib.contextmanager
def corrupt_shard(session, shard: int = 0, flip_bits: int = 1):
    """While active, every *data* buffer placed on the backend's mesh gets
    ``flip_bits`` added to the rows landing on ``shard`` (validity masks
    are left intact — the corruption is silent, like real bit damage).
    Only affects tables ingested inside the ``with`` block.

    A column the injector CANNOT damage (row count not divisible by the
    shard count, or a bool dtype where "+1" is not bit damage) is
    skipped with a warning, and if NOTHING was corrupted by the time the
    block exits the context raises — a fault test that injected no fault
    must fail loudly, not pass vacuously."""
    backend = session.backend
    if backend.mesh is None:
        raise ValueError("corrupt_shard needs a sharded session "
                         "(EngineConfig.mesh_shape)")
    n_shards = backend.mesh.devices.size
    counts = {"corrupted": 0, "skipped": 0}

    def wrap(orig):
        def poisoned(col):
            n = col.data.shape[0]
            if n % n_shards == 0 and col.data.dtype != jnp.bool_:
                rows = n // n_shards
                lo, hi = shard * rows, (shard + 1) * rows
                idx = jnp.arange(n)
                in_shard = (idx >= lo) & (idx < hi)
                bump = jnp.asarray(flip_bits, col.data.dtype)
                col = type(col)(col.kind,
                                jnp.where(in_shard, col.data + bump,
                                          col.data),
                                col.valid, col.ctype, col.lens)
                counts["corrupted"] += 1
                _count_injection("corrupt_shard")
            else:
                counts["skipped"] += 1
                reason = ("bool dtype" if col.data.dtype == jnp.bool_
                          else f"{n} rows not divisible by "
                               f"{n_shards} shards")
                warnings.warn(f"corrupt_shard skipped a column ({reason}) "
                              f"— this column was placed UNDAMAGED",
                              stacklevel=2)
            return orig(col)
        return poisoned

    with _patched_place_column(backend, wrap):
        yield counts
    # only reached on a CLEAN exit (an exception unwinding the body
    # propagates above and must not be masked by the vacuity check)
    if counts["corrupted"] == 0:
        raise RuntimeError(
            "corrupt_shard corrupted NOTHING "
            f"({counts['skipped']} column(s) skipped) — the fault "
            "test would pass vacuously; ingest a divisible-row, "
            "non-bool column inside the block")


@contextlib.contextmanager
def stale_statistics(graph, scale: float = 0.001):
    """While active, ``graph`` reports a statistics sketch whose node
    and relationship cardinalities are scaled by ``scale`` — the
    deterministic stats-violating workload.  The cost model
    (relational/cost.py) prices plans from the distorted prior while
    executions observe the TRUE cardinalities, so ``opstats``
    divergence fires on real model error and the divergence →
    quarantine → re-plan loop can be asserted end-to-end.  Exiting
    restores the honest sketch (the "updated statistics" a re-plan
    prices with).  Statistics are advisory by contract: results must
    stay exact throughout.

    Works on any graph exposing ``statistics()`` (ScanGraph,
    GraphSnapshot, VersionedGraph); raises for graphs without a sketch
    — a fault test that distorts nothing must fail loudly."""
    import dataclasses as _dc

    from caps_tpu.relational.stats import GraphStatistics

    real = graph.statistics()
    if not isinstance(real, GraphStatistics) or not real.total_nodes:
        raise ValueError("stale_statistics needs a graph with a "
                         "non-empty statistics sketch")
    scale = float(scale)
    distorted = GraphStatistics(
        {combo: max(1, int(n * scale))
         for combo, n in real.node_combos.items()},
        {t: _dc.replace(r, rows=max(1, int(r.rows * scale)))
         for t, r in real.rels.items()},
        real.property_distinct, version=real.version)
    _count_injection("stale_statistics")
    # instance attribute shadows the class method; VersionedGraph
    # delegates to its current snapshot, so the shadow covers every
    # snapshot resolved while the fault is active
    graph.statistics = lambda: distorted
    try:
        yield distorted
    finally:
        del graph.statistics


@contextlib.contextmanager
def torn_wal(n_bytes: int = 6, n_times: Optional[int] = 1):
    """While active, the next ``n_times`` commit-log frame writes TEAR:
    only the first ``n_bytes`` bytes of the frame reach the file (then
    a flush, then a fresh ``caps_wal_fault``-marked RuntimeError) — the
    on-disk image is exactly what a SIGKILL mid-write leaves.
    Deliberately NOT an OSError: ``CommitLog.append``'s OSError path
    truncates the partial frame away (clean-failure containment), and
    this injector exists to prove RECOVERY drops a torn tail honestly,
    so the torn bytes must survive on disk.  Patches the
    ``durability/wal.py`` module attribute under the shared fault lock;
    injections count ``faults.injected.torn_wal``.  Yields the
    budget."""
    from caps_tpu.durability import wal
    budget = _Budget(n_times)

    with OPERATOR_PATCH._lock:
        orig = wal._write_frame

        def tearing(f, body):
            if budget.take():
                _count_injection("torn_wal")
                frame = wal.frame_bytes(body)
                f.write(frame[:max(0, int(n_bytes))])
                f.flush()
                ex = RuntimeError(
                    f"injected torn WAL write ({n_bytes} of "
                    f"{len(frame)} bytes reached disk)")
                ex.caps_wal_fault = True
                raise ex
            return orig(f, body)

        wal._write_frame = tearing
    try:
        yield budget
    finally:
        with OPERATOR_PATCH._lock:
            wal._write_frame = orig


@contextlib.contextmanager
def failing_fsync(n_times: Optional[int] = 1):
    """While active, the next ``n_times`` commit-log fsyncs fail with a
    fresh ``caps_wal_fault``-marked OSError.  The commit must abort
    with a typed TRANSIENT
    :class:`~caps_tpu.serve.errors.WalWriteError` — never a silent
    acknowledgement — with the graph unchanged, and a retried write
    must succeed once the device heals.  Patches the
    ``durability/wal.py`` module attribute under the shared fault lock;
    injections count ``faults.injected.failing_fsync``.  Yields the
    budget."""
    from caps_tpu.durability import wal
    budget = _Budget(n_times)

    with OPERATOR_PATCH._lock:
        orig = wal._fsync

        def failing(f):
            if budget.take():
                _count_injection("failing_fsync")
                ex = OSError("injected fsync failure")
                ex.caps_wal_fault = True
                raise ex
            return orig(f)

        wal._fsync = failing
    try:
        yield budget
    finally:
        with OPERATOR_PATCH._lock:
            wal._fsync = orig


class FaultPlan:
    """Compose several faults into one context manager.

    >>> plan = FaultPlan(slow_operator("Filter", 0.01),
    ...                  failing_operator("Scan", n_times=1))
    >>> with plan:
    ...     ...  # both faults active, LIFO-unwound on exit

    ``add()`` appends before (not during) activation; plans nest freely
    with each other and with bare fault context managers — every
    operator hook goes through the same locked patch point."""

    def __init__(self, *faults):
        self._faults = list(faults)
        self._stack: Optional[contextlib.ExitStack] = None

    def add(self, fault) -> "FaultPlan":
        if self._stack is not None:
            raise RuntimeError("FaultPlan is active; build a nested "
                               "FaultPlan instead")
        self._faults.append(fault)
        return self

    def __enter__(self) -> "FaultPlan":
        if self._stack is not None:
            raise RuntimeError("FaultPlan is not re-entrant")
        stack = contextlib.ExitStack()
        try:
            for fault in self._faults:
                stack.enter_context(fault)
        except BaseException:
            stack.close()
            raise
        self._stack = stack
        return self

    def __exit__(self, *exc) -> bool:
        stack, self._stack = self._stack, None
        return stack.__exit__(*exc)

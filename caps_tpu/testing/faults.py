"""Test-only fault injection.

SURVEY.md §5.3: the reference inherits failure detection from Spark
(lineage re-execution, executor blacklisting) and ships no fault-injection
tests of its own; single-controller JAX has no task retry, so our
equivalent machinery is (a) deterministic replay + digest comparison
(``EngineConfig.determinism_check`` / ``result_digest``) and (b) this
module: :func:`corrupt_shard` silently damages one shard's buffers on
ingest so tests can prove the detection machinery notices, and
:func:`slow_operator` injects a deterministic delay into one relational
operator so deadline/cancellation paths (``caps_tpu/serve/``) are
testable without sleep-and-hope timing races.
"""
from __future__ import annotations

import contextlib
import time

import jax.numpy as jnp


@contextlib.contextmanager
def slow_operator(op_name: str, delay_s: float):
    """While active, every ``_compute`` of the named relational operator
    class (``"Filter"`` or ``"FilterOp"``) sleeps ``delay_s`` first —
    process-wide, so any session's queries slow down deterministically.

    The serving tests use this to force a deadline to expire INSIDE the
    execute phase: the delayed operator finishes (cancellation is
    cooperative — dispatched work is never torn down), and the next
    operator boundary's checkpoint raises ``DeadlineExceeded`` with
    ``phase="execute"``.  No test ever has to guess how long a real
    query takes."""
    from caps_tpu.relational import ops as R
    cls_name = op_name if op_name.endswith("Op") else op_name + "Op"
    cls = getattr(R, cls_name, None)
    if cls is None or not isinstance(cls, type) \
            or not issubclass(cls, R.RelationalOperator):
        raise ValueError(f"unknown relational operator {op_name!r}")
    orig = cls._compute

    def slowed(self):
        time.sleep(delay_s)
        return orig(self)

    cls._compute = slowed
    try:
        yield
    finally:
        cls._compute = orig


@contextlib.contextmanager
def corrupt_shard(session, shard: int = 0, flip_bits: int = 1):
    """While active, every *data* buffer placed on the backend's mesh gets
    ``flip_bits`` added to the rows landing on ``shard`` (validity masks
    are left intact — the corruption is silent, like real bit damage).
    Only affects tables ingested inside the ``with`` block."""
    backend = session.backend
    if backend.mesh is None:
        raise ValueError("corrupt_shard needs a sharded session "
                         "(EngineConfig.mesh_shape)")
    n_shards = backend.mesh.devices.size
    orig = backend.place_column

    def poisoned(col):
        n = col.data.shape[0]
        if n % n_shards == 0 and col.data.dtype != jnp.bool_:
            rows = n // n_shards
            lo, hi = shard * rows, (shard + 1) * rows
            idx = jnp.arange(n)
            in_shard = (idx >= lo) & (idx < hi)
            bump = jnp.asarray(flip_bits, col.data.dtype)
            col = type(col)(col.kind,
                            jnp.where(in_shard, col.data + bump, col.data),
                            col.valid, col.ctype, col.lens)
        return orig(col)

    backend.place_column = poisoned
    try:
        yield
    finally:
        backend.place_column = orig

"""Columnar-input example — building a graph from raw columns + entity
mappings, the analog of the reference's DataFrameInputExample (DataFrames
→ CAPSNodeTable/CAPSRelationshipTable; ref: spark-cypher-examples —
reconstructed, mount empty; SURVEY.md §2).

Run:  python examples/columnar_input.py
"""
import caps_tpu
from caps_tpu.okapi.types import CTFloat, CTInteger, CTString
from caps_tpu.relational.entity_tables import (
    NodeMapping, NodeTable, RelationshipMapping, RelationshipTable,
)


def main(backend: str = "tpu"):
    session = caps_tpu.local_session(backend=backend)
    f = session.table_factory

    products = NodeTable(
        NodeMapping.on("id").with_implied_labels("Product")
        .with_property("title").with_property("price"),
        f.from_columns(
            {"id": [0, 1, 2],
             "title": ["keyboard", "mouse", "monitor"],
             "price": [49.0, 19.0, 249.0]},
            {"id": CTInteger, "title": CTString, "price": CTFloat}))

    customers = NodeTable(
        NodeMapping.on("id").with_implied_labels("Customer")
        .with_property("name"),
        f.from_columns(
            {"id": [10, 11], "name": ["Nia", "Omar"]},
            {"id": CTInteger, "name": CTString}))

    bought = RelationshipTable(
        RelationshipMapping.on("BOUGHT"),
        f.from_columns(
            {"_id": [100, 101, 102], "_src": [10, 10, 11],
             "_tgt": [0, 2, 1]},
            {"_id": CTInteger, "_src": CTInteger, "_tgt": CTInteger}))

    graph = session.create_graph([products, customers], [bought])
    rows = graph.cypher("""
        MATCH (c:Customer)-[:BOUGHT]->(p:Product)
        RETURN c.name AS customer, sum(p.price) AS total
        ORDER BY customer
    """).records.to_maps()
    for r in rows:
        print(f"{r['customer']} spent {r['total']}")
    return rows


if __name__ == "__main__":
    main()

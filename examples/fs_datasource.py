"""Filesystem data-source example — store a graph to Parquet, register
the directory as a catalog namespace, query it back with FROM GRAPH
(ref: spark-cypher FSGraphSource / Neo4jWorkflowExample workflow shape —
reconstructed, mount empty; SURVEY.md §2, §3.3).

Run:  python examples/fs_datasource.py
"""
import tempfile

import caps_tpu
from caps_tpu.io.fs import FSGraphSource
from caps_tpu.okapi.graph import GraphName
from caps_tpu.testing.factory import create_graph


def main(backend: str = "tpu"):
    session = caps_tpu.local_session(backend=backend)
    graph = create_graph(session, """
        CREATE (:City {name: 'Kyoto', pop: 1463723}),
               (:City {name: 'Oslo', pop: 709037})
    """)

    with tempfile.TemporaryDirectory() as root:
        fs = FSGraphSource(session, root, fmt="parquet")
        session.catalog.register_source("fs", fs)
        fs.store(GraphName("cities"), graph)

        rows = session.cypher("""
            FROM GRAPH fs.cities
            MATCH (c:City) WHERE c.pop > 1000000
            RETURN c.name AS n
        """).records.to_maps()
        print("big cities from the fs source:", [r["n"] for r in rows])
        return rows


if __name__ == "__main__":
    main()

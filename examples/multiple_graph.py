"""Multiple-graph example — catalog, CONSTRUCT, RETURN GRAPH, FROM GRAPH
(benchmark config 5; ref: spark-cypher-examples MultipleGraphExample —
reconstructed, mount empty; SURVEY.md §2, §3.4).

Run:  python examples/multiple_graph.py
"""
import caps_tpu
from caps_tpu.testing.factory import create_graph


def main(backend: str = "tpu"):
    session = caps_tpu.local_session(backend=backend)

    social = create_graph(session, """
        CREATE (a:Person {name: 'Alice'}), (b:Person {name: 'Bob'}),
               (a)-[:KNOWS]->(b)
    """)
    purchases = create_graph(session, """
        CREATE (a:Person {name: 'Alice'}), (p:Product {title: 'book'}),
               (a)-[:BOUGHT]->(p)
    """)
    session.catalog.store("social", social)
    session.catalog.store("purchases", purchases)

    # Query a catalog graph by name
    rows = session.cypher("""
        FROM GRAPH session.social
        MATCH (p:Person) RETURN p.name AS n ORDER BY n
    """).records.to_maps()
    print("people in session.social:", [r["n"] for r in rows])

    # CONSTRUCT a recommendation graph linking friends to what they bought
    result = session.cypher("""
        FROM GRAPH session.social
        MATCH (a:Person)-[:KNOWS]->(b:Person)
        CONSTRUCT
          NEW (a)-[:SHOULD_ASK]->(b)
        RETURN GRAPH
    """)
    rec = result.graph
    edges = rec.cypher("""
        MATCH (x)-[:SHOULD_ASK]->(y) RETURN x.name AS x, y.name AS y
    """).records.to_maps()
    print("constructed SHOULD_ASK edges:", edges)
    return rows, edges


if __name__ == "__main__":
    main()

"""Parameterized-workload example — steady-state latency on a remote
device (ref: the reference's Spark/Tungsten whole-stage-codegen plan
reuse across parameter values — reconstructed, mount empty;
SURVEY.md §3.1).

An interactive service runs the SAME query text with rotating
parameters (the LDBC short-read shape). On a remote TPU transport the
dominant steady-state cost is device→host size syncs; the engine's
param-generic fused replay converges those to ~1 per query regardless
of parameter value, while keeping results exact (device-checked served
sizes; a parameter whose sizes exceed every recorded bound
transparently re-records).

Run:  python examples/parameterized_reads.py
"""
import caps_tpu
from caps_tpu.testing.factory import create_graph


def main(backend: str = "tpu"):
    session = caps_tpu.local_session(backend=backend)
    graph = create_graph(session, """
        CREATE (ana:Person {name: 'Ana', age: 34}),
               (bo:Person {name: 'Bo', age: 51}),
               (cleo:Person {name: 'Cleo', age: 27}),
               (dev:Person {name: 'Dev', age: 45}),
               (ana)-[:KNOWS]->(bo), (bo)-[:KNOWS]->(cleo),
               (cleo)-[:KNOWS]->(dev), (dev)-[:KNOWS]->(ana),
               (ana)-[:KNOWS]->(cleo)
    """)
    query = ("MATCH (a:Person)-[:KNOWS]->(b:Person) "
             "WHERE a.age > $min_age "
             "RETURN a.name AS person, b.name AS knows ORDER BY person, knows")
    out = []
    for min_age in (30, 40, 25, 50, 30):
        result = graph.cypher(query, {"min_age": min_age})
        rows = result.records.to_maps()
        syncs = (result.metrics or {}).get("size_syncs")
        out.append((min_age, len(rows), syncs))
        print(f"min_age={min_age}: {len(rows)} rows"
              + (f", {syncs} host syncs" if syncs is not None else ""))
    return out


if __name__ == "__main__":
    main()

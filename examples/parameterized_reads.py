"""Parameterized-workload example — steady-state latency on a remote
device (ref: the reference's Spark/Tungsten whole-stage-codegen plan
reuse across parameter values — reconstructed, mount empty;
SURVEY.md §3.1).

An interactive service runs the SAME query text with rotating
parameters (the LDBC short-read shape).  Two session caches amortize
that shape end to end:

* the **plan cache** (``session.prepare``): parse → IR → logical →
  relational planning runs once; every later ``.run(params)`` re-binds
  parameter values into the cached operator tree (keys are
  value-independent — names + coarse types);
* the **fused executor** (TPU backend): device→host size syncs converge
  to ~1 per query regardless of parameter value, while keeping results
  exact (device-checked served sizes; a parameter whose sizes exceed
  every recorded bound transparently re-records).

Run:  python examples/parameterized_reads.py
"""
import caps_tpu
from caps_tpu.testing.factory import create_graph


def main(backend: str = "tpu"):
    session = caps_tpu.local_session(backend=backend)
    graph = create_graph(session, """
        CREATE (ana:Person {name: 'Ana', age: 34}),
               (bo:Person {name: 'Bo', age: 51}),
               (cleo:Person {name: 'Cleo', age: 27}),
               (dev:Person {name: 'Dev', age: 45}),
               (ana)-[:KNOWS]->(bo), (bo)-[:KNOWS]->(cleo),
               (cleo)-[:KNOWS]->(dev), (dev)-[:KNOWS]->(ana),
               (ana)-[:KNOWS]->(cleo)
    """)
    prepared = graph.prepare(
        "MATCH (a:Person)-[:KNOWS]->(b:Person) "
        "WHERE a.age > $min_age "
        "RETURN a.name AS person, b.name AS knows ORDER BY person, knows")
    out = []
    for min_age in (30, 40, 25, 50, 30):
        result = prepared.run({"min_age": min_age})
        rows = result.records.to_maps()
        metrics = result.metrics or {}
        syncs = metrics.get("size_syncs")
        out.append((min_age, len(rows), syncs))
        print(f"min_age={min_age}: {len(rows)} rows, "
              f"plan_cache={metrics.get('plan_cache')}"
              + (f", {syncs} host syncs" if syncs is not None else ""))
    stats = session.plan_cache.stats()
    print(f"plan cache: {stats['hits']} hits / {stats['misses']} misses, "
          f"{stats['saved_s'] * 1e3:.2f} ms of planning skipped")
    return out


if __name__ == "__main__":
    main()

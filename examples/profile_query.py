"""Observability example — EXPLAIN, PROFILE, and trace export.

``EXPLAIN <query>`` plans without executing (the rendered IR / logical /
relational trees); ``PROFILE <query>`` executes and annotates every
relational operator with its measured span — rows, wall time, bytes
pulled through memory, and device time.  Both are plain query prefixes,
so they work through every API that takes query text, on every backend.
``session.metrics_snapshot()`` exposes the session's counters (plan
cache, device backend, fused executor) as one flat dict, and
``session.export_trace(path)`` writes the collected spans as a
``chrome://tracing``-loadable file.

Run:  python examples/profile_query.py
"""
import json
import os
import tempfile

import caps_tpu
from caps_tpu.testing.factory import create_graph


def main(backend: str = "tpu"):
    session = caps_tpu.local_session(backend=backend)
    graph = create_graph(session, """
        CREATE (ana:Person {name: 'Ana', age: 34}),
               (bo:Person {name: 'Bo', age: 51}),
               (cleo:Person {name: 'Cleo', age: 27}),
               (ana)-[:KNOWS]->(bo), (bo)-[:KNOWS]->(cleo),
               (ana)-[:KNOWS]->(cleo)
    """)
    query = ("MATCH (a:Person)-[:KNOWS]->(b:Person) "
             "WHERE a.age > $min_age "
             "RETURN a.name AS person, b.name AS knows "
             "ORDER BY person, knows")

    # EXPLAIN: the plan, nothing executed (records is None)
    explained = graph.cypher("EXPLAIN " + query, {"min_age": 30})
    print("=== EXPLAIN ===")
    print(explained.plans["relational"])

    # PROFILE: execute + per-operator measurements
    profiled = graph.cypher("PROFILE " + query, {"min_age": 30})
    rows = profiled.records.to_maps()
    print("\n=== PROFILE ===")
    print(profiled.plans["profile"])

    # the spans PROFILE collected export to chrome://tracing
    trace_path = os.path.join(tempfile.mkdtemp(), "caps_tpu_trace.json")
    session.export_trace(trace_path)
    n_events = len(json.load(open(trace_path))["traceEvents"])
    print(f"\nwrote {n_events} trace events to {trace_path} "
          "(open in chrome://tracing)")

    snapshot = session.metrics_snapshot()
    print(f"plan_cache.hits={snapshot['plan_cache.hits']} "
          f"plan_cache.misses={snapshot['plan_cache.misses']}")
    return rows, explained, profiled, n_events


if __name__ == "__main__":
    main()

"""Recommendation example — multi-hop collaborative filtering in one
Cypher query (ref: spark-cypher-examples RecommendationExample —
reconstructed, mount empty; SURVEY.md §2).

Customers who bought the same product as you are taste-neighbours; rank
what they bought that you haven't.

Run:  python examples/recommendation.py
"""
import caps_tpu
from caps_tpu.testing.factory import create_graph


def main(backend: str = "tpu"):
    session = caps_tpu.local_session(backend=backend)
    graph = create_graph(session, """
        CREATE (nia:Customer {name: 'Nia'}),
               (omar:Customer {name: 'Omar'}),
               (vera:Customer {name: 'Vera'}),
               (kb:Product {title: 'keyboard'}),
               (ms:Product {title: 'mouse'}),
               (mn:Product {title: 'monitor'}),
               (hd:Product {title: 'headset'}),
               (nia)-[:BOUGHT]->(kb), (nia)-[:BOUGHT]->(ms),
               (omar)-[:BOUGHT]->(kb), (omar)-[:BOUGHT]->(mn),
               (vera)-[:BOUGHT]->(ms), (vera)-[:BOUGHT]->(mn),
               (vera)-[:BOUGHT]->(hd)
    """)
    rows = graph.cypher("""
        MATCH (me:Customer {name: 'Nia'})-[:BOUGHT]->(:Product)
              <-[:BOUGHT]-(peer:Customer)-[:BOUGHT]->(rec:Product)
        WHERE peer.name <> 'Nia'
        OPTIONAL MATCH (me)-[own:BOUGHT]->(rec)
        WITH rec, count(*) AS score, count(own) AS owned
        WHERE owned = 0
        RETURN rec.title AS recommend, score
        ORDER BY score DESC, recommend
    """).records.to_maps()
    print("recommendations for Nia:")
    for r in rows:
        print(f"  {r['recommend']} (score {r['score']})")
    return rows


if __name__ == "__main__":
    main()

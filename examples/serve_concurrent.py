"""Concurrent serving example — many client threads, one prepared plan.

``caps_tpu/serve/`` turns a session into a multi-client service: clients
``submit()`` from any thread and block on Future-style handles while a
worker pool executes through the session's prepared-plan path.  The
micro-batcher coalesces compatible in-flight requests (same normalized
query + parameter signature = same plan-cache key family) into ONE pass
over the cached operator tree — the serving analogue of continuous
batching in TPU LLM inference, with the cached plan playing the
compiled program's role.

The demo submits a burst from 4 client threads, then compares against
the same workload as sequential ``PreparedQuery.run()`` calls, and
prints the batch-size histogram the server actually achieved: a max
batch size > 1 is the amortization made visible — those requests shared
one plan-cache lookup, one execution lock acquisition, and (on the TPU
backend) one uninterrupted fused dispatch stream.

Run:  python examples/serve_concurrent.py
"""
import threading

import caps_tpu
from caps_tpu.serve import QueryServer, ServerConfig
from caps_tpu.testing.factory import create_graph

QUERY = ("MATCH (a:Person)-[:KNOWS]->(b:Person) WHERE a.age > $min_age "
         "RETURN b.name AS knows ORDER BY knows")
BINDINGS = [{"min_age": a} for a in (20, 30, 40, 50)]
N_CLIENTS, PER_CLIENT = 4, 6


def main(backend: str = "tpu"):
    session = caps_tpu.local_session(backend=backend)
    graph = create_graph(session, """
        CREATE (ana:Person {name: 'Ana', age: 34}),
               (bo:Person {name: 'Bo', age: 51}),
               (cleo:Person {name: 'Cleo', age: 27}),
               (dev:Person {name: 'Dev', age: 45}),
               (ana)-[:KNOWS]->(bo), (bo)-[:KNOWS]->(cleo),
               (cleo)-[:KNOWS]->(dev), (dev)-[:KNOWS]->(ana),
               (ana)-[:KNOWS]->(cleo)
    """)

    # Sequential reference: one prepared statement, one caller.
    prep = graph.prepare(QUERY)
    expected = {b["min_age"]: [r["knows"] for r in
                               prep.run(b).records.to_maps()]
                for b in BINDINGS}

    # Serving tier: the burst is queued before the workers start, so
    # the very first batch demonstrably coalesces.
    server = QueryServer(session, graph=graph, start=False,
                         config=ServerConfig(workers=2, max_batch=8))
    handles = []

    def client(i):
        for j in range(PER_CLIENT):
            binding = BINDINGS[(i + j) % len(BINDINGS)]
            handles.append((binding, server.submit(QUERY, binding)))

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(N_CLIENTS)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    server.start()

    ok = 0
    for binding, handle in handles:
        rows = [r["knows"] for r in handle.rows(timeout=30)]
        assert rows == expected[binding["min_age"]], (binding, rows)
        ok += 1
    server.shutdown()

    stats = server.stats()
    n = N_CLIENTS * PER_CLIENT
    print(f"{ok}/{n} served correctly across {N_CLIENTS} client threads")
    print(f"batches: {stats['batches']} for {stats['completed']} requests "
          f"(mean size {stats['batch_size.mean']:.2f}, "
          f"max {stats['batch_size.max']})")
    print(f"vs sequential run(): every request in a size-"
          f"{stats['batch_size.max']} batch shared one plan-cache lookup "
          f"and one execution-lock acquisition instead of paying its own")
    return ok, int(stats["batch_size.max"])


if __name__ == "__main__":
    main()

"""SocialNetworkExample — the bundled Alice/Bob/Carol KNOWS graph
(benchmark config 1; ref: spark-cypher-examples SocialNetworkExample —
reconstructed, mount empty; SURVEY.md §2).

Run:  python examples/social_network.py [--backend local|tpu]
"""
import argparse

import caps_tpu
from caps_tpu.testing.factory import create_graph


def main(backend: str = "tpu"):
    session = caps_tpu.local_session(backend=backend)

    graph = create_graph(session, """
        CREATE (alice:Person {name: 'Alice', age: 23}),
               (bob:Person {name: 'Bob', age: 42}),
               (carol:Person {name: 'Carol', age: 31}),
               (alice)-[:KNOWS {since: 2010}]->(bob),
               (bob)-[:KNOWS {since: 2015}]->(carol),
               (alice)-[:KNOWS {since: 2018}]->(carol)
    """)

    result = graph.cypher("""
        MATCH (a:Person)-[:KNOWS]->(b:Person)
        WHERE a.age < 40
        RETURN a.name AS a, b.name AS b
        ORDER BY a, b
    """)
    rows = result.records.to_maps()
    print("who knows whom (a.age < 40):")
    for r in rows:
        print(f"  {r['a']} -> {r['b']}")

    foaf = graph.cypher("""
        MATCH (a:Person {name: 'Alice'})-[:KNOWS]->()-[:KNOWS]->(c)
        RETURN c.name AS foaf
    """).records.to_maps()
    print("Alice's friends-of-friends:", [r["foaf"] for r in foaf])
    return rows, foaf


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--backend", default="tpu", choices=["local", "tpu"])
    main(**vars(ap.parse_args()))

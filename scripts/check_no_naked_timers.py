#!/usr/bin/env python3
"""Lint: no naked timers inside ``caps_tpu/``.

All timing reads must go through ``caps_tpu.obs.clock`` (the single
monotonic base every span, operator metric, and trace export shares —
ISSUE 3 satellite).  This script greps ``caps_tpu/`` for
``time.perf_counter(`` / ``time.time(`` calls outside ``caps_tpu/obs/``
(aliased imports like ``import time as _time`` are caught too: the
pattern matches the attribute access, not the import name binding).

Exit status: 0 clean, 1 with findings (one ``path:line: text`` per
offence).  Run standalone or via the CI workflow.
"""
from __future__ import annotations

import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "caps_tpu")
EXEMPT = os.path.join(PKG, "obs") + os.sep

# matches `time.perf_counter(` / `time.time(` including aliased modules
# (`_time.perf_counter(`) — any attribute access ending in these names
PATTERN = re.compile(r"time\.(?:perf_counter|time)\s*\(")


def findings():
    out = []
    for root, _dirs, files in os.walk(PKG):
        for fname in sorted(files):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(root, fname)
            if path.startswith(EXEMPT):
                continue
            with open(path, encoding="utf-8") as f:
                for lineno, line in enumerate(f, 1):
                    if PATTERN.search(line):
                        rel = os.path.relpath(path, REPO)
                        out.append(f"{rel}:{lineno}: {line.strip()}")
    return out


def main() -> int:
    bad = findings()
    if bad:
        print("naked timers found (use caps_tpu.obs.clock instead):")
        for b in bad:
            print(f"  {b}")
        return 1
    print("check_no_naked_timers: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python3
"""Lint shim: no naked timers inside ``caps_tpu/`` — all timing reads
go through ``caps_tpu.obs.clock``.

This script is now a thin delegate to capslint's ``clock-discipline``
pass (``python -m caps_tpu.analysis --only clock-discipline``), which
replaces the old regex with AST import resolution and closes the
``from time import perf_counter`` hole (a name import never produces a
``time.`` attribute access for a regex to match).  Same contract as
before: exit 0 clean / 1 with findings, one indented ``path:line:
message`` per offence.  Prefer running capslint directly.
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from caps_tpu.analysis import run_shim  # noqa: E402


def main() -> int:
    return run_shim(
        "clock-discipline",
        header="naked timers found (use caps_tpu.obs.clock instead):",
        clean_message="check_no_naked_timers: clean")


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python3
"""Lint: every exception constructed and raised inside ``caps_tpu/serve/``
inherits :class:`caps_tpu.serve.errors.ServeError`.

The serving tier's client contract (docs/guide.md "Failure handling") is
that ONE except clause — ``except ServeError`` — catches everything the
tier itself can signal: shedding, deadlines, cancellation, retry
give-ups, breaker fast-fails, wait timeouts.  A stray ``raise
TimeoutError(...)`` silently breaks that contract for every client, so
this script walks the AST of each ``caps_tpu/serve/*.py`` file, finds
``raise SomeName(...)`` statements, resolves ``SomeName`` against the
module's imported/defined names, and fails unless the resolved class
subclasses ``ServeError``.

Skipped (not statically checkable, and legitimately outside the
contract): bare ``raise`` re-raises and ``raise some_variable`` — e.g.
``QueryHandle.result`` re-raising the ENGINE's error, which is the
client's query failing, not the serving tier signalling.

Exit status: 0 clean, 1 with findings.  Run standalone or via CI.
"""
from __future__ import annotations

import ast
import importlib
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SERVE = os.path.join(REPO, "caps_tpu", "serve")

#: the serve/ modules this lint MUST see — a rename/move that silently
#: drops a module from the walk would turn the whole check vacuous for
#: it, so missing expected files are findings, not skips.  New serve/
#: modules are picked up automatically by the directory walk; add them
#: here too so the coverage stays pinned.
EXPECTED_MODULES = frozenset({
    "__init__.py", "admission.py", "batcher.py", "breaker.py",
    "deadline.py", "devices.py", "errors.py", "failure.py",
    "request.py", "retry.py", "server.py",
})


def _raised_names(tree: ast.AST):
    """(lineno, name) for every ``raise Name(...)`` / ``raise Name``
    with a plain-name callee.  Raises inside a ``__getattr__`` are
    exempt: the module/attribute protocol REQUIRES AttributeError there
    (it signals "name not exported", not a serving failure)."""
    exempt = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name == "__getattr__":
            exempt.update(id(n) for n in ast.walk(node))
    for node in ast.walk(tree):
        if not isinstance(node, ast.Raise) or node.exc is None \
                or id(node) in exempt:
            continue
        exc = node.exc
        if isinstance(exc, ast.Call):
            exc = exc.func
        if isinstance(exc, ast.Name):
            yield node.lineno, exc.id


def findings():
    sys.path.insert(0, REPO)
    from caps_tpu.serve.errors import ServeError
    out = []
    present = {f for f in os.listdir(SERVE) if f.endswith(".py")}
    for missing in sorted(EXPECTED_MODULES - present):
        out.append(f"caps_tpu/serve/{missing}: expected serve module "
                   f"is MISSING from the lint walk (moved/renamed? "
                   f"update EXPECTED_MODULES)")
    for fname in sorted(present):
        path = os.path.join(SERVE, fname)
        with open(path, encoding="utf-8") as f:
            tree = ast.parse(f.read(), filename=path)
        module = importlib.import_module(
            f"caps_tpu.serve.{fname[:-3]}" if fname != "__init__.py"
            else "caps_tpu.serve")
        rel = os.path.relpath(path, REPO)
        for lineno, name in _raised_names(tree):
            obj = getattr(module, name, None)
            if obj is None:
                out.append(f"{rel}:{lineno}: raises unresolvable "
                           f"name {name!r}")
            elif not (isinstance(obj, type)
                      and issubclass(obj, ServeError)):
                out.append(f"{rel}:{lineno}: raises {name}, which does "
                           f"not inherit ServeError")
    return out


def main() -> int:
    bad = findings()
    if bad:
        print("serve/ raises non-ServeError exceptions "
              "(clients must be able to catch ONE base type):")
        for b in bad:
            print(f"  {b}")
        return 1
    print("check_serve_errors: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())

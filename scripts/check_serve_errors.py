#!/usr/bin/env python3
"""Lint shim: every exception raised inside ``caps_tpu/serve/`` inherits
:class:`caps_tpu.serve.errors.ServeError`.

This script is now a thin delegate to capslint's ``error-taxonomy``
pass (``python -m caps_tpu.analysis --only error-taxonomy``), which
carries the original check — AST-resolved, no package import needed —
plus the PR 4 extensions (exception-mutation discipline, swallowed
broad handlers, the worker path routing through ``failure.classify``).
Same contract as before: exit 0 clean / 1 with findings, one indented
``path:line: message`` per offence.  Prefer running capslint directly.
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from caps_tpu.analysis import run_shim  # noqa: E402


def main() -> int:
    return run_shim(
        "error-taxonomy",
        header="serve/ raises non-ServeError exceptions "
               "(clients must be able to catch ONE base type):",
        clean_message="check_serve_errors: clean")


if __name__ == "__main__":
    sys.exit(main())

"""Acceptance-suite fixtures, mirroring the reference's CAPSTestSuite +
GraphConstructionFixture pattern (SURVEY.md §4): a shared session per
backend, `init_graph` from CREATE strings, Bag comparison.

The `session` fixture is parametrized by backend so every behaviour suite
runs against the local oracle AND the TPU backend once it lands.
"""
import pytest

from caps_tpu.testing.bag import Bag
from caps_tpu.testing.factory import create_graph
from caps_tpu.testing.sessions import BACKENDS, make_backend_session as _make_session


@pytest.fixture(params=BACKENDS, scope="module")
def session(request):
    try:
        return _make_session(request.param)
    except (ImportError, ModuleNotFoundError):
        pytest.skip(f"backend {request.param!r} not available yet")


@pytest.fixture()
def init_graph(session):
    def make(create_query: str, **params):
        return create_graph(session, create_query, params)
    return make


@pytest.fixture()
def run():
    def _run(graph, query, **params):
        return graph.cypher(query, params).records.to_maps()
    return _run


@pytest.fixture()
def bag():
    return Bag

"""Aggregation behaviour (mirrors the reference's AggregationBehaviour)."""


def test_count_star_and_column(init_graph, run):
    g = init_graph("CREATE ({v: 1}), ({v: 2}), ({w: 3})")
    assert run(g, "MATCH (n) RETURN count(*) AS c") == [{"c": 3}]
    # count(expr) skips nulls
    assert run(g, "MATCH (n) RETURN count(n.v) AS c") == [{"c": 2}]


def test_count_distinct(init_graph, run):
    g = init_graph("CREATE ({v: 1}), ({v: 1}), ({v: 2})")
    assert run(g, "MATCH (n) RETURN count(DISTINCT n.v) AS c") == [{"c": 2}]


def test_sum_avg_min_max(init_graph, run):
    g = init_graph("CREATE ({v: 1}), ({v: 2}), ({v: 3})")
    rows = run(g, "MATCH (n) RETURN sum(n.v) AS s, avg(n.v) AS a, "
                  "min(n.v) AS mn, max(n.v) AS mx")
    assert rows == [{"s": 6, "a": 2.0, "mn": 1, "mx": 3}]


def test_collect(init_graph, run):
    g = init_graph("CREATE ({v: 1}), ({v: 2}), ({w: 0})")
    rows = run(g, "MATCH (n) RETURN collect(n.v) AS l")
    assert sorted(rows[0]["l"]) == [1, 2]  # nulls skipped


def test_grouped_aggregation(init_graph, run, bag):
    g = init_graph("CREATE ({g: 'a', v: 1}), ({g: 'a', v: 2}), ({g: 'b', v: 3})")
    rows = run(g, "MATCH (n) RETURN n.g AS g, sum(n.v) AS s")
    assert bag(rows) == [{"g": "a", "s": 3}, {"g": "b", "s": 3}]


def test_group_by_entity(init_graph, run, bag):
    g = init_graph("CREATE (a {v: 1})-[:R]->(), (a)-[:R]->(), (b {v: 2})-[:R]->()")
    rows = run(g, "MATCH (n)-[:R]->() RETURN n.v AS v, count(*) AS c")
    assert bag(rows) == [{"v": 1, "c": 2}, {"v": 2, "c": 1}]


def test_aggregation_on_empty_match(init_graph, run):
    g = init_graph("CREATE ({v: 1})")
    rows = run(g, "MATCH (n:Nope) RETURN count(*) AS c, sum(n.v) AS s, "
                  "min(n.v) AS mn, collect(n.v) AS l")
    assert rows == [{"c": 0, "s": 0, "mn": None, "l": []}]


def test_aggregation_expression_post_processing(init_graph, run):
    g = init_graph("CREATE ({v: 1}), ({v: 2})")
    assert run(g, "MATCH (n) RETURN count(*) * 10 + 1 AS c") == [{"c": 21}]


def test_avg_of_empty_is_null(init_graph, run):
    g = init_graph("CREATE ({v: 1})")
    assert run(g, "MATCH (n:X) RETURN avg(n.v) AS a") == [{"a": None}]


def test_aggregation_then_order(init_graph, run):
    g = init_graph("CREATE ({g: 'a', v: 1}), ({g: 'b', v: 5}), ({g: 'a', v: 2})")
    rows = run(g, "MATCH (n) RETURN n.g AS g, sum(n.v) AS s ORDER BY s DESC")
    assert rows == [{"g": "b", "s": 5}, {"g": "a", "s": 3}]


def test_with_aggregation_pipeline(init_graph, run, bag):
    g = init_graph("CREATE ({g: 'a', v: 1}), ({g: 'a', v: 2}), ({g: 'b', v: 9})")
    rows = run(g, "MATCH (n) WITH n.g AS g, count(*) AS c WHERE c > 1 "
                  "RETURN g, c")
    assert rows == [{"g": "a", "c": 2}]


def test_percentile_disc_and_cont(init_graph, run, bag):
    g = init_graph("CREATE ({v: 10}), ({v: 20}), ({v: 30}), ({v: 40}), "
                   "({w: 1})")
    rows = run(g, "MATCH (n) RETURN percentileDisc(n.v, 0.5) AS d, "
                  "percentileCont(n.v, 0.5) AS c")
    assert rows == [{"d": 20, "c": 25.0}]
    rows = run(g, "MATCH (n) RETURN percentileDisc(n.v, 0.0) AS lo, "
                  "percentileDisc(n.v, 1.0) AS hi, "
                  "percentileCont(n.v, 0.25) AS q1")
    assert rows == [{"lo": 10, "hi": 40, "q1": 17.5}]


def test_percentile_grouped(init_graph, run, bag):
    g = init_graph("CREATE ({g: 'a', v: 1}), ({g: 'a', v: 3}), "
                   "({g: 'a', v: 5}), ({g: 'b', v: 7}), ({g: 'c', w: 0})")
    rows = run(g, "MATCH (n) RETURN n.g AS g, "
                  "percentileDisc(n.v, 0.5) AS d, "
                  "percentileCont(n.v, 0.5) AS c")
    assert bag(rows) == [{"g": "a", "d": 3, "c": 3.0},
                         {"g": "b", "d": 7, "c": 7.0},
                         {"g": "c", "d": None, "c": None}]


def test_percentile_float_values(init_graph, run):
    g = init_graph("CREATE ({v: 1.5}), ({v: 2.5}), ({v: 4.0})")
    rows = run(g, "MATCH (n) RETURN percentileCont(n.v, 0.5) AS c, "
                  "percentileDisc(n.v, 0.75) AS d")
    assert rows == [{"c": 2.5, "d": 4.0}]


def test_percentile_after_filter(init_graph, run):
    # regression: ungrouped percentile over a COMPACTED table — capacity
    # padding duplicates row values and must not enter the value run
    g = init_graph("CREATE ({v: 5}), ({v: 9}), ({v: 2}), ({v: 100}), "
                   "({v: 101}), ({v: 102})")
    rows = run(g, "MATCH (n) WHERE n.v < 50 "
                  "RETURN percentileDisc(n.v, 1.0) AS mx, "
                  "percentileCont(n.v, 1.0) AS cmx, "
                  "percentileDisc(n.v, 0.5) AS med")
    assert rows == [{"mx": 9, "cmx": 9.0, "med": 5}]


def test_percentile_distinct(init_graph, run):
    # round-5: DISTINCT was silently dropped for percentiles (parser never
    # passed it through); [1,2,2,2] p50 differs between the two semantics
    g = init_graph("CREATE (:P {g:'x', v: 1}), (:P {g:'x', v: 2}), "
                   "(:P {g:'x', v: 2}), (:P {g:'x', v: 2}), "
                   "(:P {g:'y', v: 5}), (:P {g:'y', v: 5})")
    rows = run(g, "MATCH (p:P) RETURN p.g AS g, "
                  "percentileDisc(DISTINCT p.v, 0.5) AS pd, "
                  "percentileCont(DISTINCT p.v, 0.5) AS pc, "
                  "percentileDisc(p.v, 0.5) AS pn ORDER BY g")
    assert rows == [
        {"g": "x", "pd": 1, "pc": 1.5, "pn": 2},
        {"g": "y", "pd": 5, "pc": 5.0, "pn": 5},
    ]

"""List-expression behaviour over entity values: comprehensions whose
lambda variable ranges over nodes/relationships, quantified predicates
(all/any/none/single), reduce, and nodes(p) on var-length paths
(round-5 VERDICT items 2; the reference gets these from the Neo4j
front-end's IterablePredicateExpression / PathExpression families —
reconstructed, mount empty)."""


def test_entity_property_in_list_comprehension(init_graph, run):
    # round-4 VERDICT Weak #2 repro: silent [None, None] on all backends
    g = init_graph("CREATE (:Person {name:'Alice'})-[:KNOWS]->"
                   "(:Person {name:'Bob'})")
    rows = run(g, "MATCH (a)-[:KNOWS]->(b) RETURN [n IN [a, b] | n.name] AS r")
    assert rows == [{"r": ["Alice", "Bob"]}]


def test_entity_labels_and_predicate_in_comprehension(init_graph, run):
    g = init_graph("CREATE (:A {v: 1})-[:T]->(:B {v: 2})")
    rows = run(g, "MATCH (a)-[:T]->(b) "
                  "RETURN [n IN [a, b] WHERE n:B | labels(n)] AS r")
    assert rows == [{"r": [["B"]]}]


def test_rel_accessors_in_comprehension(init_graph, run):
    g = init_graph("CREATE (:A)-[:T {w: 7}]->(:B)")
    rows = run(g, "MATCH (a)-[r:T]->(b) "
                  "RETURN [x IN [r] | type(x)] AS t, "
                  "[x IN [r] | x.w] AS w, "
                  "[x IN [r] | id(startNode(x)) = id(a)] AS s")
    assert rows == [{"t": ["T"], "w": [7], "s": [True]}]


def test_comprehension_over_collected_entities(init_graph, run):
    g = init_graph("CREATE (:P {name:'Alice', age: 30}), "
                   "(:P {name:'Bob', age: 25})")
    rows = run(g, "MATCH (p:P) WITH collect(p) AS ps "
                  "RETURN [x IN ps WHERE x.age > 26 | x.name] AS r")
    assert rows == [{"r": ["Alice"]}]


def test_comprehension_var_shadows_outer_entity(init_graph, run):
    # the lambda var deliberately reuses an outer entity var's name:
    # inside the comprehension `a` must be the element, not the column
    g = init_graph("CREATE (:P {v: 1})-[:T]->(:P {v: 2})")
    rows = run(g, "MATCH (a)-[:T]->(b) RETURN [a IN [b] | a.v] AS r")
    assert rows == [{"r": [2]}]


def test_nested_comprehension_sees_outer_lambda(init_graph, run):
    g = init_graph("CREATE (:Z)")
    rows = run(g, "MATCH (z:Z) RETURN "
                  "[x IN [1, 2] | [y IN [10] | x + y]] AS r")
    assert rows == [{"r": [[11], [12]]}]


def test_quantifiers_3vl(init_graph, run):
    g = init_graph("CREATE (:Z)")
    rows = run(g, "MATCH (z:Z) RETURN "
                  "all(x IN [1, 2, 3] WHERE x > 0) AS a, "
                  "all(x IN [1, null] WHERE x > 0) AS an, "
                  "all(x IN [1, -1, null] WHERE x > 0) AS af, "
                  "any(x IN [-1, null, 2] WHERE x > 0) AS y, "
                  "any(x IN [null, -1] WHERE x > 0) AS yn, "
                  "any(x IN [] WHERE x > 0) AS ye, "
                  "none(x IN [-1, -2] WHERE x > 0) AS n, "
                  "none(x IN [null] WHERE x > 0) AS nn, "
                  "single(x IN [1, -1] WHERE x > 0) AS s, "
                  "single(x IN [1, 2] WHERE x > 0) AS s2, "
                  "single(x IN [1, null] WHERE x > 0) AS sn")
    assert rows == [{"a": True, "an": None, "af": False,
                     "y": True, "yn": None, "ye": False,
                     "n": True, "nn": None,
                     "s": True, "s2": False, "sn": None}]


def test_quantifier_over_entities(init_graph, run):
    g = init_graph("CREATE (:P {age: 30})-[:K]->(:P {age: 17})")
    rows = run(g, "MATCH (a)-[:K]->(b) "
                  "RETURN all(n IN [a, b] WHERE n.age >= 18) AS adults, "
                  "any(n IN [a, b] WHERE n.age >= 18) AS some")
    assert rows == [{"adults": False, "some": True}]


def test_quantifier_in_where(init_graph, run):
    g = init_graph("CREATE (:P {name:'Alice', age: 30})-[:K]->"
                   "(:P {name:'Bob', age: 17})")
    rows = run(g, "MATCH (a)-[:K]->(b) "
                  "WHERE any(n IN [a, b] WHERE n.age < 18) "
                  "RETURN a.name AS nm")
    assert rows == [{"nm": "Alice"}]


def test_reduce(init_graph, run):
    g = init_graph("CREATE (:Z)")
    rows = run(g, "MATCH (z:Z) RETURN "
                  "reduce(t = 0, x IN [1, 2, 3] | t + x) AS s, "
                  "reduce(s = '', x IN ['a', 'b'] | s + x) AS c")
    assert rows == [{"s": 6, "c": "ab"}]


def test_reduce_over_entity_properties(init_graph, run):
    g = init_graph("CREATE (:P {v: 10})-[:T]->(:P {v: 32})")
    rows = run(g, "MATCH (a)-[:T]->(b) "
                  "RETURN reduce(t = 0, n IN [a, b] | t + n.v) AS s")
    assert rows == [{"s": 42}]


def test_filter_extract_legacy_forms(init_graph, run):
    g = init_graph("CREATE (:Z)")
    rows = run(g, "MATCH (z:Z) RETURN "
                  "filter(x IN [1, -2, 3] WHERE x > 0) AS f, "
                  "extract(x IN [1, 2] | x * 10) AS e")
    assert rows == [{"f": [1, 3], "e": [10, 20]}]


def test_nodes_on_var_length_path(init_graph, run):
    # round-4 VERDICT Missing #3: previously hard-refused in the IR
    g = init_graph("CREATE (:P {name:'Alice'})-[:K]->(:P {name:'Bob'})"
                   "-[:K]->(:P {name:'Carol'})")
    rows = run(g, "MATCH p = (:P {name:'Alice'})-[:K*1..2]->(x) "
                  "RETURN [n IN nodes(p) | n.name] AS names")
    assert sorted((r["names"] for r in rows), key=len) == [
        ["Alice", "Bob"], ["Alice", "Bob", "Carol"]]


def test_nodes_on_var_length_path_unwind(init_graph, run):
    g = init_graph("CREATE (:P {name:'Alice'})-[:K]->(:P {name:'Bob'})"
                   "-[:K]->(:P {name:'Carol'})")
    rows = run(g, "MATCH p = (:P {name:'Alice'})-[:K*2]->(x) "
                  "UNWIND nodes(p) AS n RETURN n.name AS nm")
    assert sorted(r["nm"] for r in rows) == ["Alice", "Bob", "Carol"]


def test_nodes_var_length_through_projection(init_graph, run):
    g = init_graph("CREATE (:P {v: 1})-[:K]->(:P {v: 2})-[:K]->(:P {v: 3})")
    rows = run(g, "MATCH p = (:P {v: 1})-[:K*2]->(x) WITH p AS q "
                  "RETURN size(nodes(q)) AS n, "
                  "[m IN nodes(q) | m.v] AS vs")
    assert rows == [{"n": 3, "vs": [1, 2, 3]}]


def test_comprehension_over_relationships_var_length(init_graph, run):
    g = init_graph("CREATE (:P)-[:K {w: 1}]->(:P)-[:K {w: 2}]->(:P)")
    rows = run(g, "MATCH p = (:P)-[:K*2]->(x) "
                  "RETURN [r IN relationships(p) | r.w] AS ws")
    assert rows == [{"ws": [1, 2]}]


def test_size_of_comprehension_and_null_list(init_graph, run):
    g = init_graph("CREATE (:P {xs: [1, 2, 3]}), (:P)")
    rows = run(g, "MATCH (p:P) RETURN "
                  "size([x IN p.xs WHERE x > 1]) AS n")
    assert sorted((r["n"] for r in rows),
                  key=lambda v: (v is None, v)) == [2, None]


def test_mixed_literal_list_does_not_coerce_ints(init_graph, run):
    # round-5 review finding: [n, 5] must not treat the literal 5 as a
    # node id and leak another node's properties
    g = init_graph("CREATE (:P {name:'zero'}), (:P {name:'one'}), "
                   "(:P {name:'two'}), (:P {name:'three'}), "
                   "(:P {name:'four'}), (:P {name:'five'})")
    rows = run(g, "MATCH (n:P) WHERE n.name = 'zero' "
                  "RETURN [x IN [n, 5] | x.name] AS r")
    assert rows == [{"r": ["zero", None]}]


def test_keys_properties_on_bound_map_values(init_graph, run):
    g = init_graph("CREATE (:Z)")
    rows = run(g, "MATCH (z:Z) RETURN [m IN [{a: 1}] | keys(m)] AS ks, "
                  "[m IN [{a: 1, b: 2}] | properties(m)] AS ps")
    assert rows == [{"ks": [["a"]], "ps": [{"a": 1, "b": 2}]}]

"""Functions behaviour (mirrors the reference's FunctionsBehaviour)."""


def test_id_labels_type(init_graph, run):
    g = init_graph("CREATE (:A:B {v: 1})-[:R]->(:C)")
    rows = run(g, "MATCH (n:A)-[r]->(m) RETURN labels(n) AS l, type(r) AS t, "
                  "labels(m) AS lm")
    assert rows == [{"l": ["A", "B"], "t": "R", "lm": ["C"]}]


def test_string_functions(init_graph, run):
    g = init_graph("CREATE ({s: '  Hello World  '})")
    rows = run(g, "MATCH (n) RETURN toUpper(trim(n.s)) AS up, "
                  "toLower(trim(n.s)) AS lo, size(trim(n.s)) AS n")
    assert rows == [{"up": "HELLO WORLD", "lo": "hello world", "n": 11}]


def test_substring_split_replace(init_graph, run):
    g = init_graph("CREATE ({s: 'a,b,c'})")
    rows = run(g, "MATCH (n) RETURN split(n.s, ',') AS parts, "
                  "replace(n.s, ',', '-') AS r, substring(n.s, 2, 3) AS sub")
    assert rows == [{"parts": ["a", "b", "c"], "r": "a-b-c", "sub": "b,c"}]


def test_numeric_functions(init_graph, run):
    g = init_graph("CREATE ({v: -2.5})")
    rows = run(g, "MATCH (n) RETURN abs(n.v) AS a, sign(n.v) AS s, "
                  "floor(n.v) AS f, ceil(n.v) AS c, sqrt(4.0) AS q")
    assert rows == [{"a": 2.5, "s": -1, "f": -3.0, "c": -2.0, "q": 2.0}]


def test_conversions(init_graph, run):
    g = init_graph("CREATE ({v: 42})")
    rows = run(g, "MATCH (n) RETURN toString(n.v) AS s, toFloat(n.v) AS f, "
                  "toInteger('17') AS i, toBoolean('true') AS b")
    assert rows == [{"s": "42", "f": 42.0, "i": 17, "b": True}]


def test_coalesce(init_graph, run, bag):
    g = init_graph("CREATE ({v: 1}), ({w: 2})")
    rows = run(g, "MATCH (n) RETURN coalesce(n.v, n.w, -1) AS x")
    assert bag(rows) == [{"x": 1}, {"x": 2}]


def test_list_functions(init_graph, run):
    g = init_graph("CREATE ({v: 1})")
    rows = run(g, "RETURN head([1,2,3]) AS h, last([1,2,3]) AS l, "
                  "tail([1,2,3]) AS t, size([1,2,3]) AS s, "
                  "range(1, 4) AS r, reverse([1,2]) AS rev")
    assert rows == [{"h": 1, "l": 3, "t": [2, 3], "s": 3,
                     "r": [1, 2, 3, 4], "rev": [2, 1]}]


def test_list_indexing_and_slicing(init_graph, run):
    g = init_graph("CREATE ({v: 1})")
    rows = run(g, "RETURN [10,20,30][1] AS i, [10,20,30][-1] AS neg, "
                  "[10,20,30][1..] AS s1, [10,20,30][..2] AS s2")
    assert rows == [{"i": 20, "neg": 30, "s1": [20, 30], "s2": [10, 20]}]


def test_list_comprehension(init_graph, run):
    g = init_graph("CREATE ({v: 1})")
    rows = run(g, "RETURN [x IN range(1, 5) WHERE x % 2 = 1 | x * 10] AS l")
    assert rows == [{"l": [10, 30, 50]}]


def test_string_concat_and_arith(init_graph, run):
    g = init_graph("CREATE ({a: 'foo', n: 7})")
    rows = run(g, "MATCH (x) RETURN x.a + 'bar' AS s, x.n % 3 AS m, "
                  "2 ^ 3 AS p, x.n / 2 AS d")
    assert rows == [{"s": "foobar", "m": 1, "p": 8.0, "d": 3}]


def test_startnode_endnode(init_graph, run):
    g = init_graph("CREATE ({v: 1})-[:R]->({v: 2})")
    rows = run(g, "MATCH (a)-[r]->(b) RETURN id(startNode(r)) = id(a) AS s, "
                  "id(endNode(r)) = id(b) AS e")
    assert rows == [{"s": True, "e": True}]


def test_keys_and_properties(init_graph, run):
    g = init_graph("CREATE ({a: 1, b: 'x'})")
    rows = run(g, "MATCH (n) RETURN keys(n) AS k, properties(n) AS p")
    assert rows == [{"k": ["a", "b"], "p": {"a": 1, "b": "x"}}]

"""Match behaviour (mirrors the reference's MatchBehaviour suite)."""


def test_match_all_nodes(init_graph, run, bag):
    g = init_graph("CREATE (:A {v: 1}), (:B {v: 2}), ({v: 3})")
    rows = run(g, "MATCH (n) RETURN n.v AS v")
    assert bag(rows) == [{"v": 1}, {"v": 2}, {"v": 3}]


def test_match_by_label(init_graph, run, bag):
    g = init_graph("CREATE (:A {v: 1}), (:B {v: 2}), (:A:B {v: 3})")
    assert bag(run(g, "MATCH (n:A) RETURN n.v AS v")) == [{"v": 1}, {"v": 3}]
    assert bag(run(g, "MATCH (n:A:B) RETURN n.v AS v")) == [{"v": 3}]
    assert bag(run(g, "MATCH (n:B) RETURN n.v AS v")) == [{"v": 2}, {"v": 3}]


def test_match_inline_property(init_graph, run, bag):
    g = init_graph("CREATE (:P {name: 'x', k: 1}), (:P {name: 'y', k: 2})")
    assert run(g, "MATCH (n:P {name: 'y'}) RETURN n.k AS k") == [{"k": 2}]


def test_single_expand(init_graph, run, bag):
    g = init_graph("CREATE (a {v: 1})-[:R]->(b {v: 2}), (b)-[:R]->(c {v: 3})")
    rows = run(g, "MATCH (x)-[:R]->(y) RETURN x.v AS x, y.v AS y")
    assert bag(rows) == [{"x": 1, "y": 2}, {"x": 2, "y": 3}]


def test_triangle_cycle(init_graph, run, bag):
    g = init_graph(
        "CREATE (a {v: 1})-[:R]->(b {v: 2}), (b)-[:R]->(c {v: 3}), (c)-[:R]->(a)")
    rows = run(g, "MATCH (x)-[:R]->(y)-[:R]->(z)-[:R]->(x) RETURN x.v AS v")
    assert bag(rows) == [{"v": 1}, {"v": 2}, {"v": 3}]


def test_diamond_multiple_paths(init_graph, run, bag):
    g = init_graph(
        "CREATE (a {v: 0})-[:R]->(b {v: 1}), (a)-[:R]->(c {v: 2}), "
        "(b)-[:R]->(d {v: 3}), (c)-[:R]->(d)")
    rows = run(g, "MATCH (x {v: 0})-[:R]->()-[:R]->(z) RETURN z.v AS v")
    assert bag(rows) == [{"v": 3}, {"v": 3}]


def test_rel_type_disjunction(init_graph, run, bag):
    g = init_graph("CREATE (a {v: 1})-[:X]->(b {v: 2}), (a)-[:Y]->(c {v: 3}), "
                   "(a)-[:Z]->(d {v: 4})")
    rows = run(g, "MATCH ({v: 1})-[:X|Y]->(t) RETURN t.v AS v")
    assert bag(rows) == [{"v": 2}, {"v": 3}]


def test_rel_var_binding(init_graph, run, bag):
    g = init_graph("CREATE (a)-[:R {w: 10}]->(b), (b)-[:R {w: 20}]->(c)")
    rows = run(g, "MATCH ()-[r:R]->() RETURN r.w AS w, type(r) AS t")
    assert bag(rows) == [{"w": 10, "t": "R"}, {"w": 20, "t": "R"}]


def test_undirected_and_incoming(init_graph, run, bag):
    g = init_graph("CREATE (a {v: 1})-[:R]->(b {v: 2})")
    assert bag(run(g, "MATCH (x)-[:R]-(y) RETURN x.v AS x, y.v AS y")) == [
        {"x": 1, "y": 2}, {"x": 2, "y": 1}]
    assert run(g, "MATCH (x)<-[:R]-(y) RETURN x.v AS x, y.v AS y") == [
        {"x": 2, "y": 1}]


def test_self_loop_undirected_matches_once_per_orientation(init_graph, run, bag):
    g = init_graph("CREATE (a {v: 1})-[:R]->(a)")
    rows = run(g, "MATCH (x)-[:R]-(y) RETURN x.v AS x, y.v AS y")
    assert bag(rows) == [{"x": 1, "y": 1}]


def test_multiple_patterns_same_var(init_graph, run, bag):
    g = init_graph("CREATE (a {v: 1})-[:X]->(b {v: 2}), (a)-[:Y]->(c {v: 3})")
    rows = run(g, "MATCH (n)-[:X]->(x) MATCH (n)-[:Y]->(y) "
                  "RETURN x.v AS x, y.v AS y")
    assert rows == [{"x": 2, "y": 3}]


def test_var_length_star(init_graph, run, bag):
    g = init_graph("CREATE (a {v: 1})-[:R]->(b {v: 2}), (b)-[:R]->(c {v: 3})")
    rows = run(g, "MATCH ({v: 1})-[rs:R*]->(t) RETURN t.v AS v, size(rs) AS n")
    assert bag(rows) == [{"v": 2, "n": 1}, {"v": 3, "n": 2}]


def test_var_length_zero_lower_bound(init_graph, run, bag):
    g = init_graph("CREATE (a {v: 1})-[:R]->(b {v: 2})")
    rows = run(g, "MATCH (s {v: 1})-[rs:R*0..1]->(t) RETURN t.v AS v, size(rs) AS n")
    assert bag(rows) == [{"v": 1, "n": 0}, {"v": 2, "n": 1}]


def test_var_length_edge_isomorphism(init_graph, run, bag):
    # one edge: a-b; paths of length 2 would need to reuse it — forbidden
    g = init_graph("CREATE (a {v: 1})-[:R]->(b {v: 2}), (b)-[:R]->(a)")
    rows = run(g, "MATCH ({v: 1})-[rs:R*2..2]->(t) RETURN t.v AS v")
    assert bag(rows) == [{"v": 1}]  # a->b->a uses two distinct edges


def test_empty_graph_matches_nothing(init_graph, run):
    g = init_graph("")
    assert run(g, "MATCH (n) RETURN n") == []
    assert run(g, "MATCH (a)-[r]->(b) RETURN a") == []

"""OPTIONAL MATCH behaviour (mirrors the reference's OptionalMatchBehaviour)."""


def test_optional_null_padding(init_graph, run, bag):
    g = init_graph("CREATE (a:P {v: 1})-[:R]->(b:P {v: 2}), (:P {v: 3})")
    rows = run(g, "MATCH (n:P) OPTIONAL MATCH (n)-[:R]->(m) "
                  "RETURN n.v AS n, m.v AS m")
    assert bag(rows) == [{"n": 1, "m": 2}, {"n": 2, "m": None},
                         {"n": 3, "m": None}]


def test_optional_preserves_duplicates(init_graph, run, bag):
    g = init_graph("CREATE (a {v: 1})-[:R]->({w: 1}), (a)-[:R]->({w: 2})")
    rows = run(g, "MATCH (n {v: 1}) OPTIONAL MATCH (n)-[:R]->(m) "
                  "RETURN m.w AS w")
    assert bag(rows) == [{"w": 1}, {"w": 2}]


def test_optional_with_predicate_inside(init_graph, run, bag):
    g = init_graph("CREATE (a:P {v: 1})-[:R {w: 5}]->(b), (c:P {v: 2})-[:R {w: 1}]->(d)")
    rows = run(g, "MATCH (n:P) OPTIONAL MATCH (n)-[r:R]->(m) WHERE r.w > 3 "
                  "RETURN n.v AS n, r.w AS w")
    assert bag(rows) == [{"n": 1, "w": 5}, {"n": 2, "w": None}]


def test_optional_match_entity_is_null(init_graph, run, bag):
    g = init_graph("CREATE (:P {v: 1})")
    rows = run(g, "MATCH (n:P) OPTIONAL MATCH (n)-[:R]->(m) RETURN m")
    assert rows == [{"m": None}]


def test_chained_optional_matches(init_graph, run, bag):
    g = init_graph("CREATE (a:P {v: 1})-[:R]->(b {v: 2}), (b)-[:S]->(c {v: 3})")
    rows = run(g, "MATCH (n:P) OPTIONAL MATCH (n)-[:R]->(m) "
                  "OPTIONAL MATCH (m)-[:S]->(o) "
                  "RETURN n.v AS n, m.v AS m, o.v AS o")
    assert rows == [{"n": 1, "m": 2, "o": 3}]


def test_optional_then_aggregate(init_graph, run, bag):
    g = init_graph("CREATE (:P {v: 1})-[:R]->(), (:P {v: 2})")
    rows = run(g, "MATCH (n:P) OPTIONAL MATCH (n)-[r:R]->() "
                  "RETURN n.v AS v, count(r) AS c")
    assert bag(rows) == [{"v": 1, "c": 1}, {"v": 2, "c": 0}]

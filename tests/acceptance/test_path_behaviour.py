"""Named-path behaviour: p = (a)-[r]->(b), nodes()/relationships()/length(),
path values through WITH, var-length paths (round-4 VERDICT item 3; the
reference carries these through okapi-ir Pattern / front-end
PathExpression — reconstructed, mount empty)."""
from caps_tpu.okapi.values import CypherPath


def test_return_path_value(init_graph, run):
    g = init_graph("CREATE (:A {n: 1})-[:T {w: 5}]->(:B {n: 2})")
    rows = run(g, "MATCH p = (a:A)-[:T]->(b) RETURN p")
    assert len(rows) == 1
    p = rows[0]["p"]
    assert isinstance(p, CypherPath)
    assert [n.labels for n in p.nodes] == [("A",), ("B",)]
    assert [n.properties for n in p.nodes] == [{"n": 1}, {"n": 2}]
    assert [r.rel_type for r in p.rels] == ["T"]
    assert p.rels[0].properties == {"w": 5}
    assert p.rels[0].start == p.nodes[0].id
    assert p.rels[0].end == p.nodes[1].id


def test_length_nodes_relationships_fixed(init_graph, run):
    g = init_graph("CREATE (:A {n: 1})-[:T]->(:B {n: 2})-[:S]->(:C {n: 3})")
    rows = run(g, "MATCH p = (:A)-[:T]->()-[:S]->(c) "
                  "RETURN length(p) AS l, nodes(p) AS ns, "
                  "relationships(p) AS rs")
    assert len(rows) == 1
    assert rows[0]["l"] == 2
    assert [n.properties["n"] for n in rows[0]["ns"]] == [1, 2, 3]
    assert [r.rel_type for r in rows[0]["rs"]] == ["T", "S"]


def test_zero_hop_path(init_graph, run):
    g = init_graph("CREATE (:A {n: 1})")
    rows = run(g, "MATCH p = (a:A) RETURN p, length(p) AS l")
    assert rows[0]["l"] == 0
    assert len(rows[0]["p"].nodes) == 1
    assert rows[0]["p"].rels == ()


def test_var_length_path_value_and_length(init_graph, run):
    g = init_graph("CREATE (:A {n: 1})-[:T]->(:B {n: 2})-[:T]->(:C {n: 3})")
    rows = run(g, "MATCH p = (:A)-[:T*1..2]->(x) RETURN length(p) AS l")
    assert sorted(r["l"] for r in rows) == [1, 2]
    rows = run(g, "MATCH p = (:A)-[:T*2]->(x) RETURN p")
    p = rows[0]["p"]
    assert [n.properties["n"] for n in p.nodes] == [1, 2, 3]
    assert len(p.rels) == 2


def test_path_through_with_and_alias(init_graph, run):
    g = init_graph("CREATE (:A)-[:T]->(:B)")
    rows = run(g, "MATCH p = (:A)-[:T]->(b) WITH p AS q "
                  "RETURN q, length(q) AS l, nodes(q) AS ns")
    assert rows[0]["l"] == 1
    assert isinstance(rows[0]["q"], CypherPath)
    assert len(rows[0]["ns"]) == 2


def test_incoming_and_undirected_path_orientation(init_graph, run):
    g = init_graph("CREATE (:A {n: 1})-[:T]->(:B {n: 2})")
    rows = run(g, "MATCH p = (b:B)<-[:T]-(a:A) RETURN p")
    p = rows[0]["p"]
    # traversal starts at b; the rel is stored a->b
    assert p.nodes[0].labels == ("B",)
    assert p.rels[0].start == p.nodes[1].id
    rows = run(g, "MATCH p = (b:B)-[:T]-(a) RETURN p")
    assert rows[0]["p"].nodes[0].labels == ("B",)


def test_optional_match_null_path(init_graph, run):
    g = init_graph("CREATE (:A)")
    rows = run(g, "MATCH (a:A) OPTIONAL MATCH p = (a)-[:T]->(b) RETURN p")
    assert rows == [{"p": None}]


def test_path_length_filter_on_matrix_friendly_query(init_graph, run):
    g = init_graph("CREATE (:A {n: 1})-[:T]->(:B)-[:T]->(:C)-[:T]->(:D)")
    rows = run(g, "MATCH p = (:A {n: 1})-[:T*1..3]->(x) "
                  "WHERE length(p) > 1 RETURN length(p) AS l")
    assert sorted(r["l"] for r in rows) == [2, 3]


def test_unwind_path_nodes_rehydrates_entities(init_graph, run):
    g = init_graph("CREATE (:A {n: 1})-[:T]->(:B {n: 2})")
    rows = run(g, "MATCH p = (:A)-[:T]->(b) UNWIND nodes(p) AS x "
                  "RETURN x.n AS n")
    assert sorted(r["n"] for r in rows) == [1, 2]


def test_unwind_path_relationships_rehydrates(init_graph, run):
    g = init_graph("CREATE (:A)-[:T {w: 7}]->(:B)")
    rows = run(g, "MATCH p = (:A)-[:T]->(b) UNWIND relationships(p) AS r "
                  "RETURN type(r) AS t, r.w AS w")
    assert rows == [{"t": "T", "w": 7}]


def test_distinct_and_count_on_paths(init_graph, run):
    g = init_graph("CREATE (a:A)-[:T]->(:B), (a)-[:T]->(:B)")
    rows = run(g, "MATCH p = (:A)-[:T]->(b) RETURN DISTINCT p")
    assert len(rows) == 2
    rows = run(g, "MATCH p = (:A)-[:T]->(b) RETURN p, count(*) AS c")
    assert sorted(r["c"] for r in rows) == [1, 1]


def test_multiple_paths_one_match(init_graph, run):
    g = init_graph("CREATE (a:A)-[:T]->(b:B), (b)-[:S]->(:C)")
    rows = run(g, "MATCH p = (a:A)-[:T]->(b), q = (b)-[:S]->(c) "
                  "RETURN length(p) AS lp, length(q) AS lq")
    assert rows == [{"lp": 1, "lq": 1}]


def test_paths_in_collect(init_graph, run):
    g = init_graph("CREATE (:A)-[:T]->(:B)-[:T]->(:C)")
    rows = run(g, "MATCH p = (:A)-[:T*1..2]->(x) "
                  "RETURN length(p) AS l ORDER BY l")
    assert [r["l"] for r in rows] == [1, 2]


def test_count_path_null_witness(init_graph, run):
    """count(p) counts non-null paths; the witness column must be one the
    OPTIONAL MATCH itself binds (the first hop's rel), since the start
    node can be bound outside and stays non-null on a failed match."""
    g = init_graph("CREATE (a:A {n: 1})-[:T]->(b:B {n: 2})")
    cases = [
        ("MATCH (x:A) OPTIONAL MATCH p = (x)-[:T]->(y) "
         "RETURN count(p) AS c", 1),
        ("MATCH (x:B) OPTIONAL MATCH p = (x)-[:T]->(y) "
         "RETURN count(p) AS c", 0),
        ("MATCH (x:B) OPTIONAL MATCH p = (x)-[:T*1..2]->(y) "
         "RETURN count(p) AS c", 0),
        ("MATCH (x:B) OPTIONAL MATCH p = (x)-[:T]->(y) WITH p "
         "RETURN count(p) AS c", 0),
        ("OPTIONAL MATCH p = (x:Zed) RETURN count(p) AS c", 0),
    ]
    for q, want in cases:
        assert run(g, q) == [{"c": want}], q


def test_aggregating_path_value_raises(init_graph, run):
    import pytest
    from caps_tpu.ir.builder import IRBuildError
    g = init_graph("CREATE (:A)-[:T]->(:B)")
    with pytest.raises(IRBuildError):
        run(g, "MATCH p = (:A)-[:T]->(b) RETURN collect(p) AS c")


def test_unwind_list_with_null_keeps_null_row(init_graph, run):
    """UNWIND of an entity list containing null keeps the null row on
    every backend (the rehydration left-join must retain null-key rows)."""
    g = init_graph("CREATE (:A {n: 1})")
    rows = run(g, "MATCH (a:A) WITH [a, null] AS l UNWIND l AS x "
                  "RETURN x.n AS n")
    assert sorted(rows, key=str) == [{"n": 1}, {"n": None}]


def test_path_equality_and_null_checks(init_graph, run):
    """p = q compares start node + relationship id sequence; IS NULL uses
    the first hop's binding as witness."""
    g = init_graph("CREATE (a:A)-[:T]->(b:B), (a)-[:S]->(b)")
    rows = run(g, "MATCH p = (:A)-[:T]->(x) MATCH q = (:A)-[:T]->(y) "
                  "RETURN p = q AS eq")
    assert rows == [{"eq": True}]
    rows = run(g, "MATCH p = (:A)-[:T]->(x) MATCH q = (:A)-[:S]->(y) "
                  "RETURN p = q AS eq, p <> q AS ne")
    assert rows == [{"eq": False, "ne": True}]
    rows = run(g, "MATCH (x:B) OPTIONAL MATCH p = (x)-[:T]->(y) "
                  "RETURN p IS NULL AS isn")
    assert rows == [{"isn": True}]


def test_projected_path_equality_and_reuse_guard(init_graph, run):
    import pytest
    from caps_tpu.ir.builder import IRBuildError
    g = init_graph("CREATE (a:A {n: 1})-[:T {w: 5}]->(b:B), (a)-[:S]->(b)")
    rows = run(g, "MATCH p = (:A)-[:T]->(x) MATCH q = (:A)-[:T]->(y) "
                  "WITH p, q RETURN p = q AS eq")
    assert rows == [{"eq": True}]
    with pytest.raises(IRBuildError):
        run(g, "MATCH p = (a:A)-[:T]->(b) MATCH (p) RETURN p")


def test_indexing_into_path_decomposition(init_graph, run):
    """nodes(p)[i] / relationships(p)[i] materialize full entities via the
    graph lookup even though the indexed value is a bare id column."""
    g = init_graph("CREATE (:A {n: 1})-[:T {w: 5}]->(:B {n: 2})")
    rows = run(g, "MATCH p = (:A)-[:T]->(x) RETURN nodes(p)[0] AS h")
    assert rows[0]["h"].labels == ("A",) and rows[0]["h"].properties == {"n": 1}
    rows = run(g, "MATCH p = (:A)-[:T]->(x) RETURN relationships(p)[0] AS r")
    assert rows[0]["r"].rel_type == "T" and rows[0]["r"].properties == {"w": 5}

"""Predicate behaviour: WHERE with 3-valued logic (mirrors the reference's
PredicateBehaviour)."""


def test_comparisons(init_graph, run, bag):
    g = init_graph("CREATE ({v: 1}), ({v: 2}), ({v: 3})")
    assert bag(run(g, "MATCH (n) WHERE n.v > 1 RETURN n.v AS v")) == [
        {"v": 2}, {"v": 3}]
    assert bag(run(g, "MATCH (n) WHERE n.v <= 2 RETURN n.v AS v")) == [
        {"v": 1}, {"v": 2}]
    assert bag(run(g, "MATCH (n) WHERE n.v <> 2 RETURN n.v AS v")) == [
        {"v": 1}, {"v": 3}]


def test_null_comparisons_drop_rows(init_graph, run, bag):
    g = init_graph("CREATE ({v: 1}), ({w: 9})")
    assert run(g, "MATCH (n) WHERE n.v > 0 RETURN n.v AS v") == [{"v": 1}]
    assert run(g, "MATCH (n) WHERE n.v = n.v RETURN n.v AS v") == [{"v": 1}]


def test_is_null_predicates(init_graph, run, bag):
    g = init_graph("CREATE ({v: 1, name: 'a'}), ({v: 2})")
    assert run(g, "MATCH (n) WHERE n.name IS NULL RETURN n.v AS v") == [{"v": 2}]
    assert run(g, "MATCH (n) WHERE n.name IS NOT NULL RETURN n.v AS v") == [{"v": 1}]


def test_boolean_connectives(init_graph, run, bag):
    g = init_graph("CREATE ({v: 1}), ({v: 2}), ({v: 3}), ({v: 4})")
    assert bag(run(g, "MATCH (n) WHERE n.v > 1 AND n.v < 4 RETURN n.v AS v")) == [
        {"v": 2}, {"v": 3}]
    assert bag(run(g, "MATCH (n) WHERE n.v = 1 OR n.v = 4 RETURN n.v AS v")) == [
        {"v": 1}, {"v": 4}]
    assert bag(run(g, "MATCH (n) WHERE NOT n.v = 1 RETURN n.v AS v")) == [
        {"v": 2}, {"v": 3}, {"v": 4}]
    assert bag(run(g, "MATCH (n) WHERE n.v = 1 XOR n.v > 3 RETURN n.v AS v")) == [
        {"v": 1}, {"v": 4}]


def test_three_valued_or_with_null(init_graph, run, bag):
    # null OR true = true — row with missing prop still matches second leg
    g = init_graph("CREATE ({v: 1}), ({w: 5})")
    rows = run(g, "MATCH (n) WHERE n.v = 1 OR n.w = 5 RETURN id(n) IS NOT NULL AS ok")
    assert bag(rows) == [{"ok": True}, {"ok": True}]


def test_string_predicates(init_graph, run, bag):
    g = init_graph("CREATE ({s: 'apple'}), ({s: 'banana'}), ({s: 'apricot'})")
    assert bag(run(g, "MATCH (n) WHERE n.s STARTS WITH 'ap' RETURN n.s AS s")) == [
        {"s": "apple"}, {"s": "apricot"}]
    assert bag(run(g, "MATCH (n) WHERE n.s ENDS WITH 'a' RETURN n.s AS s")) == [
        {"s": "banana"}]
    assert bag(run(g, "MATCH (n) WHERE n.s CONTAINS 'an' RETURN n.s AS s")) == [
        {"s": "banana"}]


def test_regex_match(init_graph, run, bag):
    g = init_graph("CREATE ({s: 'abc1'}), ({s: 'xyz'})")
    assert run(g, "MATCH (n) WHERE n.s =~ '[a-c]+1' RETURN n.s AS s") == [
        {"s": "abc1"}]


def test_in_list(init_graph, run, bag):
    g = init_graph("CREATE ({v: 1}), ({v: 2}), ({v: 5})")
    assert bag(run(g, "MATCH (n) WHERE n.v IN [1, 5, 9] RETURN n.v AS v")) == [
        {"v": 1}, {"v": 5}]


def test_label_predicate(init_graph, run, bag):
    g = init_graph("CREATE (:A {v: 1}), (:B {v: 2}), (:A:B {v: 3})")
    assert bag(run(g, "MATCH (n) WHERE n:A RETURN n.v AS v")) == [
        {"v": 1}, {"v": 3}]
    assert bag(run(g, "MATCH (n) WHERE n:A AND NOT n:B RETURN n.v AS v")) == [
        {"v": 1}]


def test_exists_property(init_graph, run, bag):
    g = init_graph("CREATE ({v: 1, x: 0}), ({v: 2})")
    assert run(g, "MATCH (n) WHERE exists(n.x) RETURN n.v AS v") == [{"v": 1}]


def test_predicate_on_rel_property(init_graph, run, bag):
    g = init_graph("CREATE (a)-[:R {w: 1}]->(b), (a)-[:R {w: 2}]->(c)")
    assert run(g, "MATCH ()-[r:R]->() WHERE r.w > 1 RETURN r.w AS w") == [
        {"w": 2}]


def test_case_expression(init_graph, run, bag):
    g = init_graph("CREATE ({v: 1}), ({v: 10})")
    rows = run(g, "MATCH (n) RETURN CASE WHEN n.v < 5 THEN 'small' "
                  "ELSE 'big' END AS size")
    assert bag(rows) == [{"size": "small"}, {"size": "big"}]

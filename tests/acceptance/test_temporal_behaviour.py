"""Temporal value behaviour: date/datetime/duration construction,
accessors, comparison/ordering, arithmetic, device storage (round-5
VERDICT item 6; ref: okapi-api temporal value family — reconstructed,
mount empty)."""
from caps_tpu.okapi.values import CypherDate, CypherDateTime, CypherDuration


def test_date_roundtrip_and_accessors(init_graph, run):
    g = init_graph("CREATE (:E {d: date('2020-03-07')})")
    rows = run(g, "MATCH (e:E) RETURN e.d AS d, e.d.year AS y, "
                  "e.d.month AS m, e.d.day AS dd")
    assert rows == [{"d": CypherDate.parse("2020-03-07"),
                     "y": 2020, "m": 3, "dd": 7}]


def test_date_filter_and_order_on_device(init_graph, run):
    g = init_graph("CREATE (:E {n:'a', d: date('2020-01-15')}), "
                   "(:E {n:'b', d: date('2019-06-30')}), "
                   "(:E {n:'c', d: date('2020-03-01')})")
    rows = run(g, "MATCH (e:E) WHERE e.d >= date('2020-01-01') "
                  "RETURN e.n AS n ORDER BY e.d DESC")
    assert rows == [{"n": "c"}, {"n": "a"}]


def test_datetime_and_duration_arithmetic(init_graph, run):
    g = init_graph("CREATE (:Z)")
    rows = run(g, "MATCH (z:Z) RETURN "
                  "date('2020-01-31') + duration({months: 1}) AS clamped, "
                  "datetime('2020-01-15T23:30:00') + duration({hours: 1}) AS t, "
                  "duration({days: 1}) + duration({hours: 2}) AS dd")
    assert rows == [{
        "clamped": CypherDate.parse("2020-02-29"),
        "t": CypherDateTime.parse("2020-01-16T00:30:00"),
        "dd": CypherDuration(days=1, seconds=7200),
    }]


def test_temporal_aggregation(init_graph, run):
    g = init_graph("CREATE (:E {g:'x', d: date('2020-01-15')}), "
                   "(:E {g:'x', d: date('2019-06-30')}), "
                   "(:E {g:'y', d: date('2021-05-05')})")
    rows = run(g, "MATCH (e:E) RETURN e.g AS g, min(e.d) AS mn, "
                  "max(e.d) AS mx, count(DISTINCT e.d) AS n ORDER BY g")
    assert rows == [
        {"g": "x", "mn": CypherDate.parse("2019-06-30"),
         "mx": CypherDate.parse("2020-01-15"), "n": 2},
        {"g": "y", "mn": CypherDate.parse("2021-05-05"),
         "mx": CypherDate.parse("2021-05-05"), "n": 1},
    ]


def test_temporal_in_collections(init_graph, run):
    g = init_graph("CREATE (:Z)")
    rows = run(g, "MATCH (z:Z) RETURN "
                  "[d IN [date('2020-01-15'), date('2021-05-05')] "
                  "WHERE d.year > 2020 | toString(d)] AS ds")
    assert rows == [{"ds": ["2021-05-05"]}]


def test_temporal_null_and_errors(init_graph, run):
    import pytest
    g = init_graph("CREATE (:Z)")
    rows = run(g, "MATCH (z:Z) RETURN date(z.missing) AS d")
    assert rows == [{"d": None}]
    with pytest.raises(Exception, match="non-deterministic|argument"):
        run(g, "MATCH (z:Z) RETURN date() AS d")
    with pytest.raises(Exception):
        run(g, "MATCH (z:Z) RETURN date('not-a-date') AS d")

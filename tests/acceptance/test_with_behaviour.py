"""WITH / projection / slicing behaviour."""


def test_with_narrows_scope(init_graph, run, bag):
    g = init_graph("CREATE ({v: 1, w: 10}), ({v: 2, w: 20})")
    rows = run(g, "MATCH (n) WITH n.v AS v RETURN v")
    assert bag(rows) == [{"v": 1}, {"v": 2}]


def test_with_distinct(init_graph, run, bag):
    g = init_graph("CREATE ({v: 1}), ({v: 1}), ({v: 2})")
    rows = run(g, "MATCH (n) WITH DISTINCT n.v AS v RETURN v")
    assert bag(rows) == [{"v": 1}, {"v": 2}]


def test_with_entity_passthrough_and_expand(init_graph, run, bag):
    g = init_graph("CREATE (a:P {v: 1})-[:R]->(b {w: 2})")
    rows = run(g, "MATCH (n:P) WITH n MATCH (n)-[:R]->(m) RETURN m.w AS w")
    assert rows == [{"w": 2}]


def test_entity_alias(init_graph, run, bag):
    g = init_graph("CREATE (:P {v: 1})")
    rows = run(g, "MATCH (n:P) WITH n AS m RETURN m.v AS v, labels(m) AS l")
    assert rows == [{"v": 1, "l": ["P"]}]


def test_order_skip_limit(init_graph, run):
    g = init_graph("CREATE ({v: 3}), ({v: 1}), ({v: 4}), ({v: 2})")
    rows = run(g, "MATCH (n) RETURN n.v AS v ORDER BY v SKIP 1 LIMIT 2")
    assert rows == [{"v": 2}, {"v": 3}]


def test_order_desc_with_nulls(init_graph, run):
    g = init_graph("CREATE ({v: 1}), ({w: 0}), ({v: 2})")
    rows = run(g, "MATCH (n) RETURN n.v AS v ORDER BY v DESC")
    assert rows == [{"v": None}, {"v": 2}, {"v": 1}]
    rows2 = run(g, "MATCH (n) RETURN n.v AS v ORDER BY v ASC")
    assert rows2 == [{"v": 1}, {"v": 2}, {"v": None}]


def test_order_by_two_keys(init_graph, run):
    g = init_graph("CREATE ({a: 1, b: 2}), ({a: 1, b: 1}), ({a: 0, b: 9})")
    rows = run(g, "MATCH (n) RETURN n.a AS a, n.b AS b ORDER BY a, b DESC")
    assert rows == [{"a": 0, "b": 9}, {"a": 1, "b": 2}, {"a": 1, "b": 1}]


def test_unwind_from_collect(init_graph, run, bag):
    g = init_graph("CREATE ({v: 1}), ({v: 2})")
    rows = run(g, "MATCH (n) WITH collect(n.v) AS vs UNWIND vs AS v "
                  "RETURN v * 2 AS d")
    assert bag(rows) == [{"d": 2}, {"d": 4}]


def test_unwind_parameter(init_graph, run, bag):
    g = init_graph("CREATE ({v: 1})")
    rows = run(g, "UNWIND $xs AS x RETURN x + 1 AS y", xs=[1, 2, 3])
    assert rows == [{"y": 2}, {"y": 3}, {"y": 4}]


def test_with_where_filters_projection(init_graph, run, bag):
    g = init_graph("CREATE ({v: 1}), ({v: 2}), ({v: 3})")
    rows = run(g, "MATCH (n) WITH n.v AS v WHERE v % 2 = 1 RETURN v")
    assert bag(rows) == [{"v": 1}, {"v": 3}]


def test_union_distinct_and_all(init_graph, run, bag):
    g = init_graph("CREATE ({v: 1}), ({v: 2})")
    rows_all = run(g, "MATCH (n) RETURN n.v AS v UNION ALL MATCH (n) RETURN n.v AS v")
    assert len(rows_all) == 4
    rows_dist = run(g, "MATCH (n) RETURN n.v AS v UNION MATCH (n) RETURN n.v AS v")
    assert bag(rows_dist) == [{"v": 1}, {"v": 2}]


def test_return_star(init_graph, run, bag):
    g = init_graph("CREATE (:A {v: 1})-[:R]->(:B {w: 2})")
    rows = run(g, "MATCH (a:A)-[:R]->(b:B) RETURN *")
    assert len(rows) == 1
    assert rows[0]["a"].properties == {"v": 1}
    assert rows[0]["b"].properties == {"w": 2}

"""Test configuration.

Tests run on CPU with 8 virtual XLA devices so that sharded (`shard_map`)
code paths execute exactly as they would on a v5e-8 — same program, mesh
size is config (SURVEY.md §4 carry-over (c)).  The real-TPU benchmark path
is exercised by bench.py, not the unit suite.
"""
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

"""Test configuration.

Tests run on CPU with 8 virtual XLA devices so that sharded (`shard_map`)
code paths execute exactly as they would on a v5e-8 — same program, mesh
size is config (SURVEY.md §4 carry-over (c)).  The real-TPU benchmark path
is exercised by bench.py, not the unit suite.
"""
import os
import sys

# Force CPU: the agent environment pins JAX_PLATFORMS=axon (a tunnel to one
# real TPU chip) via sitecustomize; unit tests must not touch it.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

# The axon sitecustomize registers its PJRT plugin in every interpreter and
# hooks jax's backend lookup; with the factory registered, the first array
# creation initializes the tunnel client even under JAX_PLATFORMS=cpu.
# Deregister it so tests stay purely local.
try:
    import jax
    jax.config.update("jax_platforms", "cpu")  # register() pins the config
    from jax._src import xla_bridge as _xb
    _xb._backend_factories.pop("axon", None)
except Exception:
    pass

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest  # noqa: E402


@pytest.fixture()
def make_session():
    """Session factory by backend name ('local' | 'tpu' | 'sharded')."""
    from caps_tpu.testing.sessions import make_backend_session
    return make_backend_session


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_between_modules():
    """Drop jit/executable caches at every module boundary.

    XLA:CPU's backend_compile segfaults once a single process has
    accumulated a few hundred test files' worth of compiled programs
    (reproduced on an unmodified tree: the full suite dies
    deterministically inside jax's backend_compile at whichever
    compile crosses the threshold, while the same test passes in
    isolation).  Tests never rely on cross-module cache warmth — the
    persistent-compile-cache tests use the on-disk cache, and
    zero-compile replay assertions hold live references to their
    executables, which clear_caches() does not invalidate — so a
    boundary clear only costs per-module rewarming.
    """
    yield
    try:
        import jax
        jax.clear_caches()
    except Exception:  # pragma: no cover — cache clear is best-effort
        pass

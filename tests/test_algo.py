"""Graph-algorithm procedures (``CALL algo.*`` — caps_tpu/algo/*): the
analytics tier over the shared iterative-fixpoint executor.

Correctness contract throughout: the device fixpoint is a physical
choice — it must NEVER change results.  Every behavioural test asserts
parity between the device backend and the local (NumPy-oracle) backend,
including on base+delta snapshots, and under injected device faults the
host fallback must be digest-equal.
"""
from __future__ import annotations

import numpy as np
import pytest

from caps_tpu.algo import registry
from caps_tpu.algo import kernels
from caps_tpu.backends.local.session import LocalCypherSession
from caps_tpu.backends.tpu.session import TPUCypherSession
from caps_tpu.frontend.semantic import CypherSemanticError
from caps_tpu.obs.metrics import global_registry
from caps_tpu.relational.session import result_digest
from caps_tpu.testing import faults
from tests.util import make_graph


def _random_graph(session, n=60, e=240, seed=7, self_loops=True,
                  weighted=True):
    rng = np.random.RandomState(seed)
    nodes = {("P",): [{"_id": i, "name": f"n{i % 11}"} for i in range(n)]}
    edges = [(int(rng.randint(n)), int(rng.randint(n)),
              ({"w": float(1 + (i % 5))} if weighted else {}))
             for i in range(e)]
    if not self_loops:
        edges = [(a, b, p) for a, b, p in edges if a != b]
    return make_graph(session, nodes, {"K": edges})


def _two_islands(session):
    """Two disconnected components (0-1-2 and 3-4), plus an isolate."""
    nodes = {("P",): [{"_id": i} for i in range(6)]}
    edges = [(0, 1, {}), (1, 2, {}), (3, 4, {})]
    return make_graph(session, nodes, {"K": edges})


PROCEDURE_QUERIES = [
    "CALL algo.degree() YIELD node, degree "
    "RETURN node, degree ORDER BY node",
    "CALL algo.pagerank() YIELD node, score "
    "RETURN node, score ORDER BY node",
    "CALL algo.wcc() YIELD node, component "
    "RETURN node, component ORDER BY node",
    "CALL algo.bfs(0) YIELD node, dist RETURN node, dist ORDER BY node",
    "CALL algo.sssp(0, 'w') YIELD node, dist "
    "RETURN node, dist ORDER BY node",
]


def _algo_op(result):
    return [m for m in result.metrics["operators"]
            if m["op"] == "AlgoProcedure"]


# -- cross-backend parity (the oracle contract) ----------------------------

@pytest.mark.parametrize("query", PROCEDURE_QUERIES)
def test_device_matches_local_oracle(query):
    local = _random_graph(LocalCypherSession())
    device = _random_graph(TPUCypherSession())
    assert device.cypher(query).records.to_maps() == \
        local.cypher(query).records.to_maps()


@pytest.mark.parametrize("query", PROCEDURE_QUERIES)
def test_empty_graph(query):
    for session in (LocalCypherSession(), TPUCypherSession()):
        g = make_graph(session, {("P",): []}, {"K": []})
        assert g.cypher(query).records.to_maps() == []


def test_self_loops_and_parallel_edges_parity():
    nodes = {("P",): [{"_id": i} for i in range(4)]}
    edges = [(0, 0, {}), (0, 1, {}), (0, 1, {}), (2, 3, {}), (3, 2, {})]
    local = make_graph(LocalCypherSession(), nodes, {"K": edges})
    device = make_graph(TPUCypherSession(), nodes, {"K": edges})
    for q in PROCEDURE_QUERIES:
        assert device.cypher(q).records.to_maps() == \
            local.cypher(q).records.to_maps(), q
    # self-loop + parallel edges count per edge occurrence
    deg = {r["node"]: r["degree"]
           for r in local.cypher(PROCEDURE_QUERIES[0]).records.to_maps()}
    # node 0: self-loop (1 out + 1 in) + 2 parallel out-edges = 4
    assert deg[0] == 4 and deg[1] == 2 and deg[2] == 2


def test_dense_tile_layout_parity():
    """A graph dense enough to approach the full capacity tile routes to
    the matrix-product (dense-tile) program family — a physical layout
    choice that must never change results vs the NumPy oracle."""
    def dense(session, n=64, m=8192, seed=5):
        rng = np.random.RandomState(seed)
        nodes = {("P",): [{"_id": i} for i in range(n)]}
        edges = [(int(s), int(t), {"w": float(w)}) for s, t, w in
                 zip(rng.randint(0, n, m), rng.randint(0, n, m),
                     np.round(rng.rand(m) * 9 + 1, 3))]
        return make_graph(session, nodes, {"K": edges})

    local = dense(LocalCypherSession())
    device = dense(TPUCypherSession())
    for q in PROCEDURE_QUERIES:
        profiled = device.cypher("PROFILE " + q)
        (op,) = _algo_op(profiled)
        assert op["strategy"] == "device-fixpoint", q
        assert op["layout"] == "dense-tile", q
        assert profiled.records.to_maps() == \
            local.cypher(q).records.to_maps(), q
    # the ordinary sparse graph keeps the edge-list layout
    sparse = _random_graph(TPUCypherSession())
    (op,) = _algo_op(sparse.cypher("PROFILE " + PROCEDURE_QUERIES[1]))
    assert op["layout"] == "edge-list"


def test_sparse_id_space_parity():
    """Node ids far apart (span >> n) take the binary-search compaction
    path instead of the O(1) lookup table — same results either way."""
    ids = [0, 70_000, 140_000, 999_999]
    nodes = {("P",): [{"_id": i} for i in ids]}
    edges = [(ids[0], ids[1], {"w": 2.0}), (ids[1], ids[2], {"w": 3.0}),
             (ids[2], ids[3], {"w": 1.0}), (ids[3], ids[0], {"w": 4.0})]
    local = make_graph(LocalCypherSession(), nodes, {"K": edges})
    device = make_graph(TPUCypherSession(), nodes, {"K": edges})
    for q in PROCEDURE_QUERIES:
        assert device.cypher(q).records.to_maps() == \
            local.cypher(q).records.to_maps(), q
    bfs = ("CALL algo.bfs(0) YIELD node, dist "
           "RETURN node, dist ORDER BY node")
    assert local.cypher(bfs).records.to_maps() == [
        {"node": 0, "dist": 0}, {"node": 70_000, "dist": 1},
        {"node": 140_000, "dist": 2}, {"node": 999_999, "dist": 3}]


def test_disconnected_components():
    local = _two_islands(LocalCypherSession())
    device = _two_islands(TPUCypherSession())
    q = ("CALL algo.wcc() YIELD node, component "
         "RETURN node, component ORDER BY node")
    rows = local.cypher(q).records.to_maps()
    assert device.cypher(q).records.to_maps() == rows
    comp = {r["node"]: r["component"] for r in rows}
    # components are named by their smallest member id
    assert comp[0] == comp[1] == comp[2] == 0
    assert comp[3] == comp[4] == 3
    assert comp[5] == 5  # the isolate is its own component
    # BFS yields REACHED nodes only: the far island never appears
    bq = "CALL algo.bfs(0) YIELD node, dist RETURN node, dist ORDER BY node"
    brows = local.cypher(bq).records.to_maps()
    assert device.cypher(bq).records.to_maps() == brows
    assert [r["node"] for r in brows] == [0, 1, 2]
    assert [r["dist"] for r in brows] == [0, 1, 2]


def test_sssp_weighted_vs_unit():
    nodes = {("P",): [{"_id": i} for i in range(4)]}
    # direct hop 0->3 costs 10; the 3-hop detour costs 3
    edges = [(0, 3, {"w": 10.0}), (0, 1, {"w": 1.0}),
             (1, 2, {"w": 1.0}), (2, 3, {"w": 1.0})]
    for session in (LocalCypherSession(), TPUCypherSession()):
        g = make_graph(session, nodes, {"K": edges})
        q = ("CALL algo.sssp(0, 'w') YIELD node, dist "
             "RETURN node, dist ORDER BY node")
        assert [r["dist"] for r in g.cypher(q).records.to_maps()] == \
            [0.0, 1.0, 2.0, 3.0]
        # unknown weight property degrades to unit weights (= hop count)
        q_unit = ("CALL algo.sssp(0, 'nope') YIELD node, dist "
                  "RETURN node, dist ORDER BY node")
        assert [r["dist"] for r in g.cypher(q_unit).records.to_maps()] == \
            [0.0, 1.0, 2.0, 1.0]  # the direct hop 0->3 wins unweighted


def test_bfs_absent_source_yields_nothing():
    for session in (LocalCypherSession(), TPUCypherSession()):
        g = _two_islands(session)
        q = "CALL algo.bfs(999) YIELD node, dist RETURN node, dist"
        assert g.cypher(q).records.to_maps() == []


def test_degree_directions():
    nodes = {("P",): [{"_id": i} for i in range(3)]}
    edges = [(0, 1, {}), (0, 2, {}), (1, 2, {})]
    for session in (LocalCypherSession(), TPUCypherSession()):
        g = make_graph(session, nodes, {"K": edges})
        def deg(direction):
            q = (f"CALL algo.degree('{direction}') YIELD node, degree "
                 "RETURN node, degree ORDER BY node")
            return [r["degree"] for r in g.cypher(q).records.to_maps()]
        assert deg("out") == [2, 1, 0]
        assert deg("in") == [0, 1, 2]
        assert deg("both") == [2, 2, 2]


def test_pagerank_scores_sum_to_one():
    for session in (LocalCypherSession(), TPUCypherSession()):
        g = _random_graph(session)
        rows = g.cypher(PROCEDURE_QUERIES[1]).records.to_maps()
        assert abs(sum(r["score"] for r in rows) - 1.0) < 1e-6


# -- delta overlay: live writes visible through the snapshot seam ----------

def test_delta_overlay_parity_after_live_writes():
    from caps_tpu.relational.updates import versioned
    nodes = {("P",): [{"_id": i, "name": f"n{i}"} for i in range(5)]}
    edges = [(0, 1, {}), (1, 2, {})]
    q = ("CALL algo.wcc() YIELD node, component "
         "RETURN node, component ORDER BY node")
    results = []
    for make_session in (LocalCypherSession, TPUCypherSession):
        s = make_session()
        vg = versioned(s, make_graph(s, nodes, {"K": edges}))
        before = s.cypher_on_graph(vg, q).records.to_maps()
        comp = {r["node"]: r["component"] for r in before}
        assert comp[3] == 3 and comp[4] == 4  # islands before the write
        # bridge the islands live: the overlay must be visible
        s.cypher_on_graph(
            vg, "MATCH (a:P), (b:P) WHERE a.name = 'n2' AND b.name = 'n4' "
                "CREATE (a)-[:K]->(b)")
        s.cypher_on_graph(
            vg, "MATCH (a:P), (b:P) WHERE a.name = 'n4' AND b.name = 'n3' "
                "CREATE (a)-[:K]->(b)")
        after = s.cypher_on_graph(vg, q)
        assert all(r["component"] == 0 for r in after.records.to_maps())
        results.append(result_digest(after))
    assert results[0] == results[1]  # device == oracle on base+delta


# -- convergence & iteration bounds ----------------------------------------

def test_pagerank_converges_within_bound():
    g = _random_graph(TPUCypherSession())
    r = g.cypher("PROFILE CALL algo.pagerank() YIELD node, score "
                 "RETURN node, score")
    (op,) = _algo_op(r)
    assert op["converged"] is True
    assert 0 < op["iterations"] <= 20


def test_pagerank_max_iteration_cutoff():
    g = _random_graph(TPUCypherSession())
    r = g.cypher("PROFILE CALL algo.pagerank(0.85, 2, 0.0) "
                 "YIELD node, score RETURN node, score")
    (op,) = _algo_op(r)
    assert op["iterations"] == 2 and op["converged"] is False
    # the truncated run still matches the oracle exactly
    lg = _random_graph(LocalCypherSession())
    q = ("CALL algo.pagerank(0.85, 2, 0.0) YIELD node, score "
         "RETURN node, score ORDER BY node")
    assert g.cypher(q).records.to_maps() == lg.cypher(q).records.to_maps()


# -- composition: YIELD into the relational pipeline -----------------------

def test_yield_composes_with_return_pipeline():
    for session in (LocalCypherSession(), TPUCypherSession()):
        g = _two_islands(session)
        q = ("CALL algo.wcc() YIELD node, component "
             "WHERE component = 0 "
             "RETURN component, count(*) AS size")
        assert g.cypher(q).records.to_maps() == \
            [{"component": 0, "size": 3}]


def test_call_after_match_joins_on_yield():
    for session in (LocalCypherSession(), TPUCypherSession()):
        g = _random_graph(session, n=12, e=30)
        q = ("MATCH (p:P) CALL algo.degree() YIELD node, degree "
             "WHERE id(p) = node AND degree > 0 "
             "RETURN p.name AS name, degree ORDER BY node")
        rows = g.cypher(q).records.to_maps()
        assert rows and all(r["degree"] > 0 for r in rows)
    # cross-backend digest parity on the composed pipeline
    lg = _random_graph(LocalCypherSession(), n=12, e=30)
    dg = _random_graph(TPUCypherSession(), n=12, e=30)
    assert dg.cypher(q).records.to_maps() == lg.cypher(q).records.to_maps()


def test_yield_aliases_avoid_rebinding():
    g = _two_islands(LocalCypherSession())
    q = ("MATCH (node:P) CALL algo.degree() "
         "YIELD node AS nid, degree AS d "
         "WHERE id(node) = nid RETURN id(node) AS i, d ORDER BY i")
    rows = g.cypher(q).records.to_maps()
    assert [r["i"] for r in rows] == list(range(6))


# -- typed semantic errors (parser/semantic hardening satellite) -----------

def test_unknown_procedure_names_registered_signatures():
    g = _two_islands(LocalCypherSession())
    with pytest.raises(registry.UnknownProcedureError) as ei:
        g.cypher("CALL algo.nope() YIELD node RETURN node")
    msg = str(ei.value)
    assert "algo.nope" in msg and "algo.pagerank" in msg
    assert "damping" in msg  # renders full signatures, not just names


def test_arity_mismatch_is_typed_and_names_signature():
    g = _two_islands(LocalCypherSession())
    with pytest.raises(registry.ProcedureArgumentError) as ei:
        g.cypher("CALL algo.degree('out', 1, 2) YIELD node RETURN node")
    assert "algo.degree" in str(ei.value)
    assert "0..1" in str(ei.value)
    with pytest.raises(registry.ProcedureArgumentError):
        g.cypher("CALL algo.bfs() YIELD node, dist RETURN node")  # missing


def test_argument_type_mismatch_is_typed():
    g = _two_islands(LocalCypherSession())
    with pytest.raises(registry.ProcedureArgumentError) as ei:
        g.cypher("CALL algo.bfs('zero') YIELD node, dist RETURN node")
    msg = str(ei.value)
    assert "algo.bfs" in msg and "INTEGER" in msg and "source" in msg


def test_bad_yield_column_and_rebind_are_typed():
    g = _two_islands(LocalCypherSession())
    with pytest.raises(registry.ProcedureYieldError):
        g.cypher("CALL algo.degree() YIELD node, rank RETURN rank")
    with pytest.raises(CypherSemanticError, match="alias them with AS"):
        g.cypher("MATCH (node:P) CALL algo.degree() YIELD node, degree "
                 "RETURN degree")
    # errors are also CypherSemanticError: existing catchers keep working
    assert issubclass(registry.UnknownProcedureError, CypherSemanticError)


# -- compile ledger: once per first-seen shape, then zero ------------------

def test_compile_ledger_once_then_zero():
    s = TPUCypherSession()
    g = _random_graph(s)
    q = PROCEDURE_QUERIES[1]  # pagerank: priced onto the device path
    r1 = g.cypher(q)
    charges = [c for c in r1.metrics.get("compile_charges", ())
               if c["kind"] == "algo"]
    assert charges and charges[0]["seconds"] > 0.0
    (op,) = [m for m in r1.metrics["operators"]
             if m["op"] == "AlgoProcedure"]
    assert op["strategy"] == "device-fixpoint"
    r2 = g.cypher(q)
    assert r2.metrics["compile_s_charged"] == 0.0
    # a second graph landing in the same shape buckets reuses the program
    g2 = _random_graph(s, seed=11)
    r3 = g2.cypher(q)
    assert [c for c in r3.metrics.get("compile_charges", ())
            if c["kind"] == "algo"] == []


def test_cost_model_note_and_explain_render():
    g = _random_graph(TPUCypherSession())
    r = g.cypher("EXPLAIN " + PROCEDURE_QUERIES[1])
    assert "AlgoProcedure(algo.pagerank() YIELD node, score)" \
        in r.plans["relational"]
    assert "algo_strategy: procedure=algo.pagerank, " \
        "chosen=device-fixpoint" in r.plans["cost"]
    # tiny graphs price out: the pushdown must NOT win under the launch
    # overhead floor
    tiny = _two_islands(TPUCypherSession())
    rt = tiny.cypher("EXPLAIN " + PROCEDURE_QUERIES[1])
    assert "chosen=host" in rt.plans["cost"]


# -- fault injection: host fallback parity, then heal ----------------------

def test_injected_fault_falls_back_to_host_with_parity():
    s = TPUCypherSession()
    g = _random_graph(s)
    q = PROCEDURE_QUERIES[1]
    clean_rows = g.cypher(q).records.to_maps()
    fb0 = s.metrics_registry.snapshot().get("algo.fallbacks", 0)
    inj0 = global_registry().snapshot().get("faults.injected.algo", 0)
    with faults.failing_algo(n_times=1) as budget:
        faulted = g.cypher("PROFILE " + q)
        assert budget.injected == 1
    (op,) = _algo_op(faulted)
    assert op["strategy"] == "fallback-host"
    assert faulted.records.to_maps() == clean_rows  # digest-equal
    snap = s.metrics_registry.snapshot()
    assert snap["algo.fallbacks"] == fb0 + 1
    assert global_registry().snapshot()["faults.injected.algo"] == inj0 + 1
    # healed: the next execution takes the device path again
    healed = g.cypher("PROFILE " + q)
    (hop,) = _algo_op(healed)
    assert hop["strategy"] == "device-fixpoint"
    assert healed.records.to_maps() == clean_rows
    assert s.metrics_registry.snapshot()["algo.fallbacks"] == fb0 + 1


def test_fault_marker_is_stamped():
    class Boom(RuntimeError):
        pass
    with faults.failing_algo(exc=Boom, n_times=1):
        s = TPUCypherSession()
        g = _random_graph(s)
        rows = g.cypher(PROCEDURE_QUERIES[1]).records.to_maps()
    lg = _random_graph(LocalCypherSession())
    assert rows == lg.cypher(PROCEDURE_QUERIES[1]).records.to_maps()


# -- serve tier: warmed families & snapshot-keyed result cache -------------

def test_server_warmed_algo_family_charges_zero():
    from caps_tpu.relational.result_cache import ResultCacheConfig
    from caps_tpu.serve.server import QueryServer, ServerConfig
    s = TPUCypherSession()
    g = _random_graph(s)
    q = PROCEDURE_QUERIES[1]
    cfg = ServerConfig(workers=1,
                       result_cache=ResultCacheConfig(enabled=True))
    with QueryServer(s, graph=g, config=cfg) as server:
        h1 = server.submit(q)
        rows1 = h1.rows(timeout=60)
        assert h1.info["ledger"]["compile_s"] > 0.0
        h2 = server.submit(q)
        assert h2.rows(timeout=60) == rows1
        assert h2.info["ledger"]["compile_s"] == 0.0
        # the algo family is warm: nothing cold remains
        rep = server.warmup_report()
        assert rep["cold_families"] == []
        assert rep["compiled_hot_families"] == rep["hot_families"] == 1
        # the repeat was a snapshot-keyed cache hit (flight recorder)
        dump = server.dump_flight_recorder()
        assert dump["records"][-1]["outcome"] == "cache_hit"
        assert h2.info.get("cache") is not None


# -- host kernels as their own oracle (unit level) -------------------------

def test_host_kernels_unit_oracle():
    src = np.array([0, 1, 2, 0], dtype=np.int64)
    tgt = np.array([1, 2, 0, 2], dtype=np.int64)
    w = np.ones(4)
    deg, it, done = kernels.degree(4, src, tgt, "both")
    assert deg.tolist() == [3, 2, 3, 0] and done
    labels, _, done = kernels.wcc(4, src, tgt, 100)
    assert labels.tolist() == [0, 0, 0, 3] and done
    dist, _, done = kernels.bfs(4, src, tgt, 0, -1)
    assert dist[:3].tolist() == [0, 1, 1] and done
    assert dist[3] == kernels.UNREACHED
    r, it, done = kernels.pagerank(4, src, tgt, 0.85, 50, 1e-9)
    assert done and abs(r.sum() - 1.0) < 1e-6
    # quantized to the published decimal contract
    assert np.array_equal(r, np.round(r, kernels.SCORE_DECIMALS))

"""capslint (ISSUE 7): the multi-pass static-analysis framework.

The contracts under test:

* each pass FIRES on a fixture violation with the right path:line —
  a known lock cycle, a purity violation inside jitted code, a
  non-ServeError raise, a naked ``from``-imported timer (the hole the
  old regex lint could not see), and a duplicate metric name;
* inline ``# capslint: disable=<pass>`` suppressions work;
* the LIVE repo is clean under all five passes, and docs/metrics.md
  matches the source (the CI drift check);
* the runtime lock graph (caps_tpu/obs/lockgraph.py) records edges,
  raises on cycles in strict mode, ignores re-entrant re-acquisition,
  and is a plain ``threading`` primitive when the env opt-in is off;
* the legacy lint scripts still run with their old exit-code contract.
"""
from __future__ import annotations

import dataclasses
import json
import os
import subprocess
import sys
import threading

import pytest

from caps_tpu.analysis import (AnalysisConfig, Project, check_metrics_doc,
                               generate_metrics_doc, load_project,
                               pass_names, run_passes)
from caps_tpu.analysis.__main__ import main as capslint_main
from caps_tpu.analysis.locks import static_lock_graph
from caps_tpu.obs import lockgraph

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _project(tmp_path, files, config=None) -> Project:
    for rel, text in files.items():
        path = tmp_path / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(text)
    return Project(str(tmp_path), config)


def _findings(project, only):
    return run_passes(project, only=[only])


def _lines(findings):
    return {(f.path, f.line) for f in findings}


# -- lock-order --------------------------------------------------------------

LOCK_CYCLE = """\
import threading

_a = threading.Lock()
_b = threading.Lock()


def forwards():
    with _a:
        with _b:
            pass


def backwards():
    with _b:
        with _a:
            pass
"""


def test_lock_order_cycle_fires(tmp_path):
    p = _project(tmp_path, {"caps_tpu/serve/locky.py": LOCK_CYCLE})
    found = _findings(p, "lock-order")
    assert len(found) == 1
    f = found[0]
    assert f.path == "caps_tpu/serve/locky.py"
    assert "cycle" in f.message and "locky._a" in f.message \
        and "locky._b" in f.message


def test_lock_order_one_level_call_resolution(tmp_path):
    src = """\
import threading

_a = threading.Lock()
_b = threading.Lock()


def inner():
    with _b:
        with _a:
            pass


def outer():
    with _a:
        inner()
"""
    p = _project(tmp_path, {"caps_tpu/serve/callres.py": src})
    found = _findings(p, "lock-order")
    # outer holds _a and calls inner, which takes _b then _a: the
    # resolved _a -> _b edge closes a cycle with inner's _b -> _a
    assert len(found) == 1 and "cycle" in found[0].message


def test_lock_order_foreign_attr_may_alias(tmp_path):
    """A foreign-attribute lock defined on SEVERAL classes (the
    duck-typed execution seam: a ShardGroup standing in for a
    DeviceReplica behind one ``member.lock`` call site) resolves to
    EVERY candidate instead of being dropped — each alias keeps its
    nesting edges, and no edge is fabricated BETWEEN the aliases."""
    src = """\
import threading

_inner = threading.Lock()


class Replica:
    def __init__(self):
        self.lock = threading.Lock()


class Group:
    def __init__(self):
        self.lock = threading.Lock()


def dispatch(member):
    with member.lock:
        with _inner:
            pass
"""
    from caps_tpu.analysis.locks import static_lock_graph
    p = _project(tmp_path, {"caps_tpu/serve/alias.py": src})
    assert _findings(p, "lock-order") == []  # acyclic: clean
    edges, _index, _info = static_lock_graph(p)
    assert ("alias.Replica.lock", "alias._inner") in edges
    assert ("alias.Group.lock", "alias._inner") in edges
    # aliases of ONE runtime lock must not order against each other
    assert ("alias.Replica.lock", "alias.Group.lock") not in edges
    assert ("alias.Group.lock", "alias.Replica.lock") not in edges


def test_lock_order_del_and_atexit_fire(tmp_path):
    src = """\
import atexit
import threading

_a = threading.Lock()


class Holder:
    def __del__(self):
        with _a:
            pass


def _cleanup():
    with _a:
        pass


atexit.register(_cleanup)
"""
    p = _project(tmp_path, {"caps_tpu/obs/fin.py": src})
    msgs = [f.message for f in _findings(p, "lock-order")]
    assert any("__del__" in m for m in msgs)
    assert any("atexit" in m for m in msgs)


def test_lock_order_same_basename_modules_stay_distinct(tmp_path):
    # two __init__.py (same basename) each hold their own module-level
    # _lock in a consistent order: no merged node, no phantom cycle —
    # and the node ids disambiguate via the dotted path
    a = """\
import threading

_lock = threading.Lock()
_inner = threading.Lock()


def use():
    with _lock:
        with _inner:
            pass
"""
    b = a.replace("with _lock:\n        with _inner:",
                  "with _inner:\n        with _lock:")
    p = _project(tmp_path, {"caps_tpu/serve/__init__.py": a,
                            "caps_tpu/obs/__init__.py": b})
    assert _findings(p, "lock-order") == []
    _edges, index, _info = static_lock_graph(p)
    assert "serve.__init__._lock" in index.ids
    assert "obs.__init__._lock" in index.ids


def test_lock_order_acyclic_is_clean(tmp_path):
    src = LOCK_CYCLE.replace("with _b:\n        with _a:",
                             "with _a:\n        with _b:")
    p = _project(tmp_path, {"caps_tpu/serve/locky.py": src})
    assert _findings(p, "lock-order") == []


# -- tracer-purity -----------------------------------------------------------

PURITY_BAD = """\
import time
import random
import jax

_SEEN = []


@jax.jit
def kernel(x):
    t = time.perf_counter()
    r = random.random()
    _SEEN.append(x)
    return x + t + r


def helper(x):
    return time.time()


def outer(x):
    return jax.jit(inner)(x)


def inner(x):
    return helper(x)
"""


def test_purity_fires_inside_jitted_code(tmp_path):
    p = _project(tmp_path, {"caps_tpu/ops/hot.py": PURITY_BAD})
    found = _findings(p, "tracer-purity")
    lines = _lines(found)
    assert ("caps_tpu/ops/hot.py", 10) in lines   # time.perf_counter
    assert ("caps_tpu/ops/hot.py", 11) in lines   # random.random
    assert ("caps_tpu/ops/hot.py", 12) in lines   # _SEEN.append
    # closure: helper() reached via jax.jit(inner) -> inner -> helper
    assert ("caps_tpu/ops/hot.py", 17) in lines
    # nothing outside traced code is flagged
    assert all(path == "caps_tpu/ops/hot.py" for path, _ in lines)


def test_purity_global_write_fires(tmp_path):
    src = """\
import jax

_calls = 0


@jax.jit
def kernel(x):
    global _calls
    _calls += 1
    return x
"""
    p = _project(tmp_path, {"caps_tpu/ops/gm.py": src})
    found = _findings(p, "tracer-purity")
    assert ("caps_tpu/ops/gm.py", 9) in _lines(found)
    assert any("writes module-level '_calls'" in f.message
               for f in found)


def test_purity_ignores_untraced_code(tmp_path):
    src = """\
import time


def host_side():
    return time.perf_counter()
"""
    p = _project(tmp_path, {"caps_tpu/ops/cold.py": src})
    assert _findings(p, "tracer-purity") == []


def test_purity_wcoj_kernel_clock_read_fires(tmp_path):
    """The WCOJ kernel layer's jit roots are auto-discovered by the
    purity closure: a clock read inside a wcoj-shaped probe (the exact
    decorator/searchsorted structure of ops/wcoj.py) is flagged at its
    line — the fixture proof that the new kernel functions sit in the
    tracer-purity root set."""
    src = """\
import time

import jax
import jax.numpy as jnp


@jax.jit
def probe_adj(keys_sorted, u, ok, n):
    drift = time.perf_counter()
    base = u.astype(jnp.int64) * n
    lo = jnp.searchsorted(keys_sorted, base, side="left")
    hi = jnp.searchsorted(keys_sorted, base + n, side="left")
    return jnp.where(ok, hi - lo, 0) + drift, lo


def extend(keys_sorted, perm, u, ok, n, out_cap):
    counts, lo = probe_adj(keys_sorted, u, ok, n)
    return counts
"""
    p = _project(tmp_path, {"caps_tpu/ops/wcoj_fix.py": src})
    found = _findings(p, "tracer-purity")
    assert ("caps_tpu/ops/wcoj_fix.py", 9) in _lines(found)
    # the un-jitted composition wrapper is NOT itself a root
    assert all(line != 17 for _p, line in _lines(found))


def test_purity_live_wcoj_kernels_are_roots():
    """On the LIVE tree the ops/wcoj.py probes must be reached by the
    purity closure (jit-decorated roots) — and clean (the repo-clean
    test covers cleanliness; this asserts REACHABILITY, so a future
    refactor dropping the jit decorators cannot silently un-check the
    kernel layer)."""
    from caps_tpu.analysis.purity import traced_functions
    project = load_project(REPO)
    reached = {(path, fn) for path, fn in traced_functions(project)}
    wcoj_fns = {fn for path, fn in reached
                if path.endswith("caps_tpu/ops/wcoj.py")}
    assert {"probe_adj", "probe_pair", "multiplicity",
            "probe_id", "edge_keys"} <= wcoj_fns, wcoj_fns


def test_purity_fused_record_path_compute(tmp_path):
    src = """\
from caps_tpu.obs import clock


class ScanOp:
    def _compute(self):
        return clock.now()
"""
    p = _project(tmp_path, {"caps_tpu/relational/oppy.py": src})
    found = _findings(p, "tracer-purity")
    assert _lines(found) == {("caps_tpu/relational/oppy.py", 6)}
    assert "fused record path" in found[0].message


# -- error-taxonomy ----------------------------------------------------------

SERVE_ERRORS = """\
class ServeError(RuntimeError):
    pass


class Overloaded(ServeError):
    pass
"""

_TAXO_CONFIG = dataclasses.replace(
    AnalysisConfig(),
    expected_serve_modules=frozenset({"errors.py", "foo.py"}),
    worker_roots=())

SERVE_BAD_RAISE = """\
from caps_tpu.serve.errors import Overloaded


def shed():
    raise Overloaded("ok")


def wrong():
    raise TimeoutError("not a ServeError")
"""


def test_taxonomy_non_serve_error_raise_fires(tmp_path):
    p = _project(tmp_path, {
        "caps_tpu/serve/errors.py": SERVE_ERRORS,
        "caps_tpu/serve/foo.py": SERVE_BAD_RAISE,
    }, _TAXO_CONFIG)
    found = _findings(p, "error-taxonomy")
    assert _lines(found) == {("caps_tpu/serve/foo.py", 9)}
    assert "TimeoutError" in found[0].message
    assert "does not inherit ServeError" in found[0].message


def test_taxonomy_resolves_serve_errors_via_sibling_modules(tmp_path):
    # a ServeError subclass imported from a SIBLING serve module (or
    # relatively) is valid provenance — the pass must not misreport it
    src = """\
from caps_tpu.serve.other import Overloaded
from .errors import ServeError


def shed():
    raise Overloaded("ok")


def base():
    raise ServeError("ok")
"""
    cfg = dataclasses.replace(
        _TAXO_CONFIG,
        expected_serve_modules=frozenset({"errors.py", "foo.py",
                                          "other.py"}))
    p = _project(tmp_path, {
        "caps_tpu/serve/errors.py": SERVE_ERRORS,
        "caps_tpu/serve/other.py": "",
        "caps_tpu/serve/foo.py": src,
    }, cfg)
    assert _findings(p, "error-taxonomy") == []


def test_taxonomy_missing_expected_module_fires(tmp_path):
    p = _project(tmp_path, {"caps_tpu/serve/errors.py": SERVE_ERRORS},
                 _TAXO_CONFIG)
    found = _findings(p, "error-taxonomy")
    assert any("foo.py" in f.path and "MISSING" in f.message
               for f in found)


def test_taxonomy_exception_mutation_fires(tmp_path):
    src = """\
def handler():
    try:
        pass
    except Exception as ex:
        ex.my_note = "boom"
        raise
"""
    p = _project(tmp_path, {
        "caps_tpu/serve/errors.py": SERVE_ERRORS,
        "caps_tpu/serve/foo.py": src,
    }, _TAXO_CONFIG)
    found = _findings(p, "error-taxonomy")
    assert ("caps_tpu/serve/foo.py", 5) in _lines(found)
    assert any("mutates caught exception" in f.message for f in found)


def test_taxonomy_unguarded_marker_stamp_fires(tmp_path):
    src = """\
def handler():
    try:
        pass
    except Exception as ex:
        ex.caps_failed_op = "Scan"
        raise
"""
    p = _project(tmp_path, {
        "caps_tpu/serve/errors.py": SERVE_ERRORS,
        "caps_tpu/serve/foo.py": src,
    }, _TAXO_CONFIG)
    found = _findings(p, "error-taxonomy")
    assert any("first-writer-wins" in f.message for f in found)
    # the guarded idiom is clean
    guarded = src.replace(
        '        ex.caps_failed_op = "Scan"',
        '        if getattr(ex, "caps_failed_op", None) is None:\n'
        '            ex.caps_failed_op = "Scan"')
    p2 = _project(tmp_path / "g", {
        "caps_tpu/serve/errors.py": SERVE_ERRORS,
        "caps_tpu/serve/foo.py": guarded,
    }, _TAXO_CONFIG)
    assert _findings(p2, "error-taxonomy") == []


def test_taxonomy_swallowed_handler_fires(tmp_path):
    src = """\
def swallow():
    try:
        pass
    except Exception as ex:
        return None
"""
    p = _project(tmp_path, {
        "caps_tpu/serve/errors.py": SERVE_ERRORS,
        "caps_tpu/serve/foo.py": src,
    }, _TAXO_CONFIG)
    found = _findings(p, "error-taxonomy")
    assert ("caps_tpu/serve/foo.py", 4) in _lines(found)
    assert "never uses it" in found[0].message


def test_taxonomy_worker_must_reach_classify(tmp_path):
    src = """\
class Server:
    def _worker_loop(self):
        self._step()

    def _step(self):
        pass
"""
    cfg = dataclasses.replace(
        _TAXO_CONFIG,
        worker_roots=(("caps_tpu/serve/srv.py", "Server._worker_loop"),),
        expected_serve_modules=frozenset({"errors.py", "srv.py"}))
    p = _project(tmp_path, {
        "caps_tpu/serve/errors.py": SERVE_ERRORS,
        "caps_tpu/serve/srv.py": src,
    }, cfg)
    found = _findings(p, "error-taxonomy")
    assert any("never reaches" in f.message for f in found)
    fixed = src.replace("def _step(self):\n        pass",
                        "def _step(self):\n        classify(None)")
    p2 = _project(tmp_path / "ok", {
        "caps_tpu/serve/errors.py": SERVE_ERRORS,
        "caps_tpu/serve/srv.py": fixed,
    }, cfg)
    assert _findings(p2, "error-taxonomy") == []


# -- clock-discipline --------------------------------------------------------

#: synthetic trees don't carry the real repo's pinned clock modules —
#: the vacuity-guard test covers that contract explicitly
_CLOCK_CONFIG = dataclasses.replace(
    AnalysisConfig(), expected_clock_modules=frozenset())


def test_clock_from_import_hole_fires(tmp_path):
    src = """\
from time import perf_counter


def t():
    return perf_counter()
"""
    p = _project(tmp_path, {"caps_tpu/serve/t.py": src}, _CLOCK_CONFIG)
    found = _findings(p, "clock-discipline")
    # the import line itself is the finding — the exact form the old
    # regex (matching `time.perf_counter(`) could never see
    assert _lines(found) == {("caps_tpu/serve/t.py", 1)}
    assert "from time import perf_counter" in found[0].message


def test_clock_aliased_module_fires(tmp_path):
    src = """\
import time as _t

now = _t.perf_counter
"""
    p = _project(tmp_path, {"caps_tpu/relational/t.py": src}, _CLOCK_CONFIG)
    found = _findings(p, "clock-discipline")
    assert _lines(found) == {("caps_tpu/relational/t.py", 3)}


def test_clock_exempts_clock_module(tmp_path):
    src = "import time as _time\nnow = _time.perf_counter\n"
    p = _project(tmp_path, {"caps_tpu/obs/clock.py": src}, _CLOCK_CONFIG)
    assert _findings(p, "clock-discipline") == []


def test_clock_expected_module_vacuity_guard(tmp_path):
    """A pinned clock module missing from the walk is a FINDING — the
    pass must not silently stop covering code whose correctness depends
    on the sanctioned clock (the result cache's recency decay)."""
    p = _project(tmp_path, {"caps_tpu/serve/t.py": "x = 1\n"})
    found = _findings(p, "clock-discipline")
    assert _lines(found) == {
        ("caps_tpu/relational/result_cache.py", 1)}
    assert "vacuous" in found[0].message
    # present → clean (and the module itself is checked as usual)
    p2 = _project(tmp_path / "ok", {
        "caps_tpu/serve/t.py": "x = 1\n",
        "caps_tpu/relational/result_cache.py":
            "from caps_tpu.obs import clock\nnow_t = clock.now\n"})
    assert _findings(p2, "clock-discipline") == []


# -- metric-names ------------------------------------------------------------

def test_metric_duplicate_kind_fires(tmp_path):
    src = """\
def wire(reg):
    reg.counter("serve.widgets").inc()
    reg.histogram("serve.widgets").observe(1.0)
"""
    p = _project(tmp_path, {"caps_tpu/serve/m.py": src})
    found = _findings(p, "metric-names")
    assert len(found) == 1
    assert "2 different kinds" in found[0].message
    assert "serve.widgets" in found[0].message


def test_metric_prefix_and_shape_fire(tmp_path):
    src = """\
def wire(reg):
    reg.counter("bogusprefix.x").inc()
    reg.counter("UPPER").inc()
"""
    p = _project(tmp_path, {"caps_tpu/serve/m.py": src})
    msgs = [f.message for f in _findings(p, "metric-names")]
    assert any("unsanctioned prefix" in m for m in msgs)
    assert any("dotted lowercase convention" in m for m in msgs)


def test_metric_histogram_snapshot_collision_fires(tmp_path):
    src = """\
def wire(reg):
    reg.histogram("serve.latency").observe(0.1)
    reg.counter("serve.latency.count").inc()
"""
    p = _project(tmp_path, {"caps_tpu/serve/m.py": src})
    msgs = [f.message for f in _findings(p, "metric-names")]
    assert any("snapshot expansion" in m for m in msgs)


# -- suppressions / framework ------------------------------------------------

def test_inline_suppression(tmp_path):
    src = ("from time import perf_counter  "
           "# capslint: disable=clock-discipline\n")
    p = _project(tmp_path, {"caps_tpu/serve/t.py": src}, _CLOCK_CONFIG)
    assert _findings(p, "clock-discipline") == []
    # disable=all works too, and an unrelated pass name does NOT suppress
    src2 = "from time import perf_counter  # capslint: disable=lock-order\n"
    p2 = _project(tmp_path / "b", {"caps_tpu/serve/t.py": src2},
                  _CLOCK_CONFIG)
    assert len(_findings(p2, "clock-discipline")) == 1


def test_unknown_pass_rejected(tmp_path):
    p = _project(tmp_path, {"caps_tpu/x.py": "pass\n"})
    with pytest.raises(KeyError):
        run_passes(p, only=["no-such-pass"])


def test_cli_json_and_exit_codes(tmp_path, capsys):
    (tmp_path / "caps_tpu").mkdir()
    (tmp_path / "caps_tpu" / "bad.py").write_text(
        "from time import perf_counter\n")
    # satisfy the default config's pinned-module vacuity guard so the
    # single finding below is exactly the naked import
    (tmp_path / "caps_tpu" / "relational").mkdir()
    (tmp_path / "caps_tpu" / "relational" / "result_cache.py").write_text(
        "from caps_tpu.obs import clock\n")
    rc = capslint_main(["--root", str(tmp_path), "--json",
                        "--only", "clock-discipline"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1 and len(out) == 1
    assert out[0]["pass"] == "clock-discipline"
    assert out[0]["path"] == "caps_tpu/bad.py"
    rc = capslint_main(["--list"])
    assert rc == 0
    listed = capsys.readouterr().out
    for name in pass_names():
        assert name in listed


# -- structured-log ----------------------------------------------------------

LOG_MODULE = """\
class EventLog:
    def emit(self, event, *, request_id, family, **fields):
        return {"event": event, "request_id": request_id,
                "family": family, **fields}
"""


def test_structured_log_missing_field_fires(tmp_path):
    caller = """\
def trip(log, req):
    log.emit("breaker.trip", request_id=req.request_id)
"""
    p = _project(tmp_path, {"caps_tpu/obs/log.py": LOG_MODULE,
                            "caps_tpu/serve/caller.py": caller})
    found = _findings(p, "structured-log")
    assert len(found) == 1
    f = found[0]
    assert f.path == "caps_tpu/serve/caller.py" and f.line == 2
    assert "family" in f.message and "request_id" not in f.message.split(
        "field(s) ")[1].split(" —")[0]


def test_structured_log_explicit_none_and_splat_pass(tmp_path):
    caller = """\
def ok(log, extra):
    log.emit("compaction.failure", request_id=None, family=None)
    log.emit("odd", **extra)  # splat: present-ness unverifiable
"""
    p = _project(tmp_path, {"caps_tpu/obs/log.py": LOG_MODULE,
                            "caps_tpu/serve/caller.py": caller})
    assert _findings(p, "structured-log") == []


def test_structured_log_missing_module_is_a_finding(tmp_path):
    p = _project(tmp_path, {"caps_tpu/serve/caller.py": "x = 1\n"})
    found = _findings(p, "structured-log")
    assert len(found) == 1
    assert found[0].path == "caps_tpu/obs/log.py"
    assert "missing" in found[0].message


def test_structured_log_module_without_anchor_is_a_finding(tmp_path):
    p = _project(tmp_path, {"caps_tpu/obs/log.py": "def emit(x):\n"
                                                   "    return x\n"})
    found = _findings(p, "structured-log")
    assert len(found) == 1 and "no anchor" in found[0].message


def test_structured_log_bare_emit_call_checked(tmp_path):
    log_mod = LOG_MODULE + """\


def emit(event, *, request_id, family):
    return (event, request_id, family)
"""
    caller = """\
from caps_tpu.obs.log import emit


def fire():
    emit("loose")
"""
    p = _project(tmp_path, {"caps_tpu/obs/log.py": log_mod,
                            "caps_tpu/serve/caller.py": caller})
    found = _findings(p, "structured-log")
    assert len(found) == 1 and found[0].line == 5


# -- the live repo -----------------------------------------------------------

def test_live_repo_is_clean():
    project = load_project(REPO)
    findings = run_passes(project)
    assert findings == [], "\n".join(f.format() for f in findings)
    assert set(pass_names()) == {"lock-order", "tracer-purity",
                                 "error-taxonomy", "clock-discipline",
                                 "metric-names", "structured-log"}


def test_live_repo_static_lock_graph_has_serve_edges():
    edges, index, _info = static_lock_graph(load_project(REPO))
    assert "devices.DeviceReplica.lock" in index.ids
    assert "plan_cache.PlanCache._lock" in index.ids
    assert "telemetry.ServingTelemetry._lock" in index.ids
    # the serve tier's real nesting is visible statically: admission's
    # condition is held while the shed is noted into the telemetry
    # window / the windowed service time is read for retry_after (the
    # device stream lock no longer nests the admission condition — the
    # service-time fold moved outside it)
    assert ("admission.AdmissionController._cond",
            "telemetry.ServingTelemetry._lock") in edges
    assert ("devices.DeviceReplica.lock",
            "devices.DeviceReplica._graphs_lock") in edges


def test_metrics_doc_has_no_drift():
    project = load_project(REPO)
    assert check_metrics_doc(project) is None
    doc = generate_metrics_doc(project)
    assert "| `serve.completed` | counter |" in doc


def test_run_shim_separates_parse_failures(tmp_path, capsys):
    from caps_tpu.analysis import run_shim
    (tmp_path / "caps_tpu").mkdir()
    (tmp_path / "caps_tpu" / "broken.py").write_text("def oops(:\n")
    (tmp_path / "caps_tpu" / "relational").mkdir()
    (tmp_path / "caps_tpu" / "relational" / "result_cache.py").write_text(
        "from caps_tpu.obs import clock\n")
    rc = run_shim("clock-discipline", header="naked timers found:",
                  clean_message="clean", root=str(tmp_path))
    out = capsys.readouterr().out
    assert rc == 1
    assert "failed to parse" in out
    assert "naked timers found:" not in out  # not misattributed


def test_legacy_shims_keep_contract():
    for script in ("check_serve_errors.py", "check_no_naked_timers.py"):
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "scripts", script)],
            capture_output=True, text=True, timeout=120)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "clean" in proc.stdout


# -- runtime lock graph ------------------------------------------------------

def test_lockgraph_disabled_returns_plain_locks(monkeypatch):
    monkeypatch.delenv("CAPS_TPU_LOCK_GRAPH", raising=False)
    assert isinstance(lockgraph.make_lock("x.y"), type(threading.Lock()))


def test_lockgraph_records_edges_and_raises_on_cycle(monkeypatch):
    monkeypatch.setenv("CAPS_TPU_LOCK_GRAPH", "1")
    lockgraph.reset()
    a = lockgraph.make_lock("t.a")
    b = lockgraph.make_lock("t.b")
    with a:
        with b:
            pass
    snap = lockgraph.lock_graph_snapshot()
    assert ("t.a", "t.b") in snap["edges"]
    assert lockgraph.find_cycle() is None
    with pytest.raises(lockgraph.LockOrderViolation) as exc_info:
        with b:
            with a:
                pass
    assert "t.a" in str(exc_info.value) and "t.b" in str(exc_info.value)
    # the offending edge is recorded, so the snapshot now shows the cycle
    assert lockgraph.find_cycle() is not None
    lockgraph.reset()


def test_lockgraph_record_mode_never_raises(monkeypatch):
    monkeypatch.setenv("CAPS_TPU_LOCK_GRAPH", "record")
    lockgraph.reset()
    a = lockgraph.make_lock("r.a")
    b = lockgraph.make_lock("r.b")
    with a:
        with b:
            pass
    with b:
        with a:
            pass
    cycle = lockgraph.find_cycle()
    assert cycle is not None and cycle[0] == cycle[-1]
    lockgraph.reset()


def test_lockgraph_reentrant_rlock_records_no_self_edge(monkeypatch):
    monkeypatch.setenv("CAPS_TPU_LOCK_GRAPH", "1")
    lockgraph.reset()
    r = lockgraph.make_rlock("t.r")
    with r:
        with r:
            pass
    snap = lockgraph.lock_graph_snapshot()
    assert snap["edges"] == [] and snap["nodes"] == ["t.r"]
    lockgraph.reset()


def test_lockgraph_condition_is_reentrant_like_stdlib(monkeypatch):
    # Condition() defaults to an RLock backing lock; the tracked
    # replacement must keep that, or code that legally nests
    # `with cond:` would deadlock ONLY under instrumentation
    monkeypatch.setenv("CAPS_TPU_LOCK_GRAPH", "1")
    lockgraph.reset()
    cond = lockgraph.make_condition("t.recond")
    with cond:
        with cond:                   # re-entrant: must not deadlock
            cond.notify_all()
        # wait() from a re-entrant depth must release every level for
        # another thread, then restore them (the RLock save/restore
        # protocol through the proxy)
        woke = []

        def waker():
            with cond:
                cond.notify_all()
                woke.append(True)

        t = threading.Thread(target=waker)
        t.start()
        cond.wait(timeout=2)
        t.join(5)
        assert woke == [True]
    snap = lockgraph.lock_graph_snapshot()
    assert snap["edges"] == []       # reentrancy records no self-edges
    lockgraph.reset()


def test_lockgraph_condition_wait_releases(monkeypatch):
    monkeypatch.setenv("CAPS_TPU_LOCK_GRAPH", "1")
    lockgraph.reset()
    cond = lockgraph.make_condition("t.cond")
    other = lockgraph.make_lock("t.other")
    done = threading.Event()

    def waiter():
        with cond:
            cond.wait(timeout=0.2)
            done.set()

    t = threading.Thread(target=waiter)
    t.start()
    # the waiter released the condition's lock inside wait(): another
    # thread can take it and notify
    with cond:
        cond.notify_all()
    t.join(5)
    assert done.is_set()
    with cond:
        with other:
            pass
    assert ("t.cond", "t.other") in lockgraph.lock_graph_snapshot()["edges"]
    lockgraph.reset()

"""Aux subsystems (SURVEY.md §5): per-operator metrics, determinism
check/replay digests, fault injection, device health check."""
import pytest

from caps_tpu.okapi.config import EngineConfig
from caps_tpu.relational.session import (
    NondeterministicResultError, result_digest,
)
from caps_tpu.testing.bag import Bag
from caps_tpu.testing.factory import create_graph
from caps_tpu.testing.faults import corrupt_shard
from caps_tpu.testing.sessions import make_backend_session

CREATE = ("CREATE (a:P {name:'a', x: 1}), (b:P {name:'b', x: 2}), "
          "(c:P {name:'c', x: 3}), (a)-[:T]->(b), (b)-[:T]->(c)")
QUERY = "MATCH (p:P)-[:T]->(q) WHERE p.x < 3 RETURN q.name AS n"


@pytest.mark.parametrize("backend", ["local", "tpu"])
def test_operator_metrics(backend):
    s = make_backend_session(backend)
    g = create_graph(s, CREATE, {})
    r = g.cypher(QUERY)
    ops = r.metrics["operators"]
    assert ops, "per-operator metrics missing"
    names = [o["op"] for o in ops]
    assert any("Join" in n or "Expand" in n or "Scan" in n for n in names)
    assert all(o["seconds"] >= 0 and o["rows"] >= 0 for o in ops)
    # phase timings still present
    assert {"parse_s", "ir_s", "plan_s", "execute_s"} <= set(r.metrics)


def test_result_digest_is_order_insensitive():
    s = make_backend_session("local")
    g = create_graph(s, CREATE, {})
    a = g.cypher("MATCH (p:P) RETURN p.name AS n ORDER BY n ASC")
    b = g.cypher("MATCH (p:P) RETURN p.name AS n ORDER BY n DESC")
    c = g.cypher("MATCH (p:P) WHERE p.x > 1 RETURN p.name AS n")
    assert result_digest(a) == result_digest(b)
    assert result_digest(a) != result_digest(c)


def test_determinism_check_passes_and_records_digest():
    from caps_tpu.backends.tpu.session import TPUCypherSession
    s = TPUCypherSession(config=EngineConfig(determinism_check=True))
    g = create_graph(s, CREATE, {})
    r = g.cypher(QUERY)
    assert Bag(r.records.to_maps()) == [{"n": "b"}, {"n": "c"}]
    assert "determinism_digest" in r.metrics


def test_fault_injection_is_detected_by_parity():
    """A silently corrupted shard must change results — proving the digest
    / parity machinery can detect shard damage (SURVEY.md §5.3)."""
    from caps_tpu.backends.tpu.session import TPUCypherSession
    clean = TPUCypherSession(config=EngineConfig(mesh_shape=(8,)))
    g_clean = create_graph(clean, CREATE, {})
    want = result_digest(g_clean.cypher("MATCH (p:P) RETURN p.x AS x"))

    hurt = TPUCypherSession(config=EngineConfig(mesh_shape=(8,)))
    with corrupt_shard(hurt, shard=0, flip_bits=100):
        g_hurt = create_graph(hurt, CREATE, {})
    got = result_digest(g_hurt.cypher("MATCH (p:P) RETURN p.x AS x"))
    assert got != want


def test_corrupt_shard_requires_mesh():
    from caps_tpu.backends.tpu.session import TPUCypherSession
    s = TPUCypherSession()
    with pytest.raises(ValueError):
        with corrupt_shard(s):
            pass


def test_health_check_all_devices_ok():
    from caps_tpu.backends.tpu.session import TPUCypherSession
    s = TPUCypherSession(config=EngineConfig(mesh_shape=(8,)))
    status = s.health_check()
    assert len(status) == 8
    assert all(status.values())
    s1 = TPUCypherSession()
    assert all(s1.health_check().values())


def test_nondeterminism_error_surface(monkeypatch):
    """Force a digest mismatch to prove the check raises."""
    from caps_tpu.backends.tpu.session import TPUCypherSession
    import caps_tpu.relational.session as rs
    s = TPUCypherSession(config=EngineConfig(determinism_check=True))
    g = create_graph(s, CREATE, {})
    digests = iter(["aaa", "bbb"])
    monkeypatch.setattr(rs, "result_digest", lambda r: next(digests))
    with pytest.raises(NondeterministicResultError):
        g.cypher(QUERY)


def test_shrink_and_reshard_after_device_loss():
    """SURVEY.md §5.3: after a device failure the session rebuilds its
    mesh over the survivors (power-of-two prefix), re-places catalog
    graphs from their ingest host mirrors, rebuilds physical layouts
    (CSR), and answers queries with unchanged results."""
    from caps_tpu.backends.local.session import LocalCypherSession
    from caps_tpu.backends.tpu.session import TPUCypherSession
    from caps_tpu.okapi.config import EngineConfig
    from caps_tpu.testing.bag import Bag
    from caps_tpu.testing.factory import create_graph

    create = ("CREATE (a:Person {name:'Ada'}), (b:Person {name:'Bo'}), "
              "(c:Person {name:'Cy'}), (a)-[:KNOWS]->(b), "
              "(b)-[:KNOWS]->(c), (a)-[:KNOWS]->(c)")
    q = "MATCH (a)-[:KNOWS*1..2]->(b) RETURN a.name AS a, b.name AS b"
    q2 = ("MATCH (a:Person)-[:KNOWS]->(b)-[:KNOWS]->(c) "
          "WHERE a.name='Ada' RETURN count(*) AS c")

    sess = TPUCypherSession(config=EngineConfig(mesh_shape=(8,)))
    g = create_graph(sess, create, {})
    sess.catalog.store("g", g)
    oracle = LocalCypherSession()
    go = create_graph(oracle, create, {})
    want = go.cypher(q).records.to_maps()
    assert Bag(g.cypher(q).records.to_maps()) == want

    # simulate losing 3 devices: 5 survivors -> power-of-two prefix = 4
    survivors = list(sess.backend.mesh.devices.flat)[:5]
    n = sess.shrink_and_reshard(healthy=survivors)
    assert n == 4 and sess.backend.mesh.devices.size == 4

    assert Bag(g.cypher(q).records.to_maps()) == want
    assert g.cypher(q2).records.to_maps() == \
        go.cypher(q2).records.to_maps()
    assert sess.fallback_count == 0, sess.backend.fallback_reasons

    # shrinking to one survivor degrades to single-device (mesh None)
    n = sess.shrink_and_reshard(healthy=survivors[:1])
    assert n == 1 and sess.backend.mesh is None
    assert Bag(g.cypher(q).records.to_maps()) == want


def test_shrink_and_reshard_two_level_mesh():
    """Resharding a multi-slice (DCN x ICI) mesh regroups survivors by
    slice: rows shrink to the smallest surviving power-of-two width and
    the mesh stays two-level (no ring hops across DCN)."""
    from caps_tpu.backends.local.session import LocalCypherSession
    from caps_tpu.backends.tpu.session import TPUCypherSession
    from caps_tpu.okapi.config import EngineConfig
    from caps_tpu.testing.bag import Bag
    from caps_tpu.testing.factory import create_graph

    create = ("CREATE (a:P {v: 1}), (b:P {v: 2}), (c:P {v: 3}), "
              "(a)-[:R]->(b), (b)-[:R]->(c)")
    q = "MATCH (x:P)-[:R]->(y) RETURN x.v AS x, y.v AS y"
    sess = TPUCypherSession(config=EngineConfig(mesh_shape=(2, 4)))
    g = create_graph(sess, create, {})
    sess.catalog.store("g", g)
    want = create_graph(LocalCypherSession(), create, {}
                        ).cypher(q).records.to_maps()
    assert Bag(g.cypher(q).records.to_maps()) == want

    # lose one device from the second slice: widths (4, 3) -> 2 each
    old = sess.backend.mesh.devices
    survivors = list(old[0]) + list(old[1][:3])
    n = sess.shrink_and_reshard(healthy=survivors)
    assert n == 4
    assert sess.backend.mesh.devices.shape == (2, 2)
    assert sess.backend.mesh.axis_names == ("dcn", "shard")
    # every rebuilt row comes from one original slice
    assert all(d in list(old[0]) for d in sess.backend.mesh.devices[0])
    assert all(d in list(old[1]) for d in sess.backend.mesh.devices[1])
    assert Bag(g.cypher(q).records.to_maps()) == want

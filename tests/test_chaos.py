"""Seeded chaos harness (ISSUE 20): deterministic schedules, the
runner pump, the chaos-owned injectors, and the invariant checkers.

The contracts under test:

* determinism — the same seed composes the byte-identical schedule
  (attested by the sha256 digest), a different seed a different one;
  the headline event is pinned at its fraction of the soak;
* schedule validity — every drawn event names a resolvable injector,
  lands inside the soak window, and carries params from the sampler
  menu; events sort by time;
* the runner is a pure pump — ``poll(elapsed)`` fires exactly the due
  events, in order, once; process-level events delegate to host
  actions; a schedule naming an injector the runner cannot apply is
  rejected AT CONSTRUCTION (never half-way into a soak);
* chaos-owned injectors — ``chaos_fault`` stamps ``caps_chaos_fault``
  (first-writer-wins) on the fresh WireError it raises and counts
  ``faults.injected.chaos_fault``; ``slow_backend`` delays frames to
  exactly ONE peer (matched by remote port) and leaves the rest of the
  fleet untouched;
* invariants — per-reader snapshot-version regressions, availability
  floors, fence violations, and oracle-digest mismatches each fail
  their check and count ``chaos.invariant_failures``.
"""
from __future__ import annotations

import pytest

from caps_tpu.obs import clock
from caps_tpu.obs.metrics import MetricsRegistry, global_registry
from caps_tpu.serve.errors import WireError
from caps_tpu.serve.fleet import BackendSpec, FleetBackend
from caps_tpu.serve.wire import WireClient
from caps_tpu.testing.chaos import (DEFAULT_MENU, PATCH_INJECTORS,
                                    ChaosEvent, ChaosInvariants,
                                    ChaosRunner, ChaosSchedule,
                                    chaos_fault, slow_backend)

PEOPLE = "CREATE (a:Person {name: 'Alice', age: 33})"
Q = "MATCH (p:Person) RETURN p.name AS n"


# -- schedule determinism -----------------------------------------------------

def test_same_seed_composes_the_identical_schedule():
    reg = MetricsRegistry()
    a = ChaosSchedule.compose(42, 10.0, n_events=6,
                              headline="kill_router_active", registry=reg)
    b = ChaosSchedule.compose(42, 10.0, n_events=6,
                              headline="kill_router_active", registry=reg)
    assert a.digest() == b.digest()
    assert [e.as_dict() for e in a.events] \
        == [e.as_dict() for e in b.events]
    assert ChaosSchedule.compose(43, 10.0, n_events=6,
                                 registry=reg).digest() != a.digest()
    assert reg.snapshot()["chaos.schedules_composed"] == 3


def test_composed_events_are_valid_and_time_ordered():
    sched = ChaosSchedule.compose(
        7, 20.0, n_events=12, targets=("b0", "b1"),
        headline="kill_router_active", headline_at_frac=0.4,
        registry=MetricsRegistry())
    assert len(sched.events) == 13
    times = [e.at_s for e in sched.events]
    assert times == sorted(times)
    headline = [e for e in sched.events
                if e.injector == "kill_router_active"]
    assert len(headline) == 1
    assert headline[0].at_s == pytest.approx(8.0)  # pinned at 0.4×20s
    for ev in sched.events:
        assert ev.injector in set(DEFAULT_MENU) | {"kill_router_active"}
        assert 0.0 < ev.at_s < 20.0
        if ev.injector != "kill_router_active":
            assert ev.target in ("b0", "b1")


def test_digest_covers_every_event_field():
    base = ChaosSchedule(1, 5.0, [ChaosEvent(1.0, "chaos_fault", None,
                                             (("n_times", 1),))])
    for other in (
            ChaosSchedule(1, 5.0, [ChaosEvent(2.0, "chaos_fault", None,
                                              (("n_times", 1),))]),
            ChaosSchedule(1, 5.0, [ChaosEvent(1.0, "drop_connection",
                                              None, (("n_times", 1),))]),
            ChaosSchedule(1, 5.0, [ChaosEvent(1.0, "chaos_fault", "b0",
                                              (("n_times", 1),))]),
            ChaosSchedule(1, 5.0, [ChaosEvent(1.0, "chaos_fault", None,
                                              (("n_times", 2),))]),
            ChaosSchedule(1, 6.0, [ChaosEvent(1.0, "chaos_fault", None,
                                              (("n_times", 1),))])):
        assert other.digest() != base.digest()


# -- the runner pump ----------------------------------------------------------

def test_runner_fires_due_events_once_in_order():
    sched = ChaosSchedule(1, 10.0, [
        ChaosEvent(2.0, "kill_router_active", None, ()),
        ChaosEvent(5.0, "kill_backend", "b1", ()),
        ChaosEvent(8.0, "kill_backend", "b2", ()),
    ])
    fired = []
    reg = MetricsRegistry()
    actions = {"kill_router_active": lambda ev: fired.append("router"),
               "kill_backend": lambda ev: fired.append(ev.target)}
    with ChaosRunner(sched, actions=actions, registry=reg) as runner:
        assert runner.poll(1.0) == []
        assert runner.pending() == 3
        assert [e.at_s for e in runner.poll(5.0)] == [2.0, 5.0]
        assert runner.poll(5.0) == []          # never re-fires
        assert runner.poll(20.0)[0].at_s == 8.0
        assert runner.pending() == 0
    assert fired == ["router", "b1", "b2"]
    assert len(runner.applied) == 3
    assert reg.snapshot()["chaos.events_applied"] == 3


def test_runner_rejects_unresolvable_injectors_at_construction():
    sched = ChaosSchedule(1, 5.0, [
        ChaosEvent(1.0, "unplugged_rack", None, ())])
    with pytest.raises(KeyError, match="unplugged_rack"):
        ChaosRunner(sched, registry=MetricsRegistry())
    # the same schedule is fine once the host supplies the action
    ChaosRunner(sched, actions={"unplugged_rack": lambda ev: None},
                registry=MetricsRegistry())


def test_every_menu_injector_resolves_in_process():
    for name in DEFAULT_MENU:
        assert name in PATCH_INJECTORS


# -- chaos-owned injectors ----------------------------------------------------

@pytest.fixture
def backend():
    b = FleetBackend(BackendSpec(name="c0", backend="local",
                                 graph={"kind": "script",
                                        "create": PEOPLE}))
    yield b
    b.shutdown(drain=False)


def test_chaos_fault_stamps_marker_and_counts(backend):
    before = global_registry().snapshot().get(
        "faults.injected.chaos_fault", 0)
    with WireClient("127.0.0.1", backend.port) as client:
        assert client.call("ping")["name"] == "c0"
        with chaos_fault(n_times=1) as budget:
            with pytest.raises(WireError) as exc_info:
                client.call("query", query=Q)
            # attribution: the SCHEDULE injected this, first-writer-wins
            assert exc_info.value.caps_chaos_fault is True
            # budgeted: the next send goes through untouched
            assert [r["n"] for r in
                    client.call("query", query=Q)["rows"]] == ["Alice"]
        assert budget.injected == 1
    assert global_registry().snapshot()[
        "faults.injected.chaos_fault"] == before + 1


def test_slow_backend_delays_exactly_one_peer(backend):
    other = FleetBackend(BackendSpec(name="c1", backend="local",
                                     graph={"kind": "script",
                                            "create": PEOPLE}))
    try:
        sleeps = []
        orig_sleep = clock.sleep
        with WireClient("127.0.0.1", backend.port) as slow_c, \
                WireClient("127.0.0.1", other.port) as fast_c:
            slow_c.call("ping"), fast_c.call("ping")
            with slow_backend(backend.port, 0.01) as budget:
                # record rather than wait: the injector sleeps through
                # obs.clock, so the test observes without paying
                clock.sleep = sleeps.append
                try:
                    slow_c.call("query", query=Q)
                    fast_c.call("query", query=Q)
                    fast_c.call("query", query=Q)
                finally:
                    clock.sleep = orig_sleep
        # only frames TO the targeted port were delayed — the other
        # peer's traffic never consumed the budget
        assert sleeps == [0.01]
        assert budget.injected == 1
    finally:
        other.shutdown(drain=False)


# -- invariants ---------------------------------------------------------------

def test_invariants_all_green():
    inv = ChaosInvariants(registry=MetricsRegistry())
    inv.note_read("r0", True, version=1)
    inv.note_read("r0", True, version=2)
    inv.note_write_ack()
    inv.note_fence(refused=True)
    report = inv.report(availability_floor=0.9, oracle_digest="d",
                        observed_digest="d")
    assert report["ok"] is True
    assert all(report["checks"].values())
    assert report["availability"] == 1.0


def test_stale_read_is_a_version_regression_per_reader():
    reg = MetricsRegistry()
    inv = ChaosInvariants(registry=reg)
    inv.note_read("r0", True, version=3)
    inv.note_read("r1", True, version=1)   # another reader lags: fine
    inv.note_read("r0", True, version=2)   # r0 went BACK in time
    report = inv.report()
    assert report["checks"]["no_stale_reads"] is False
    assert report["stale_reads"] == 1
    assert reg.snapshot()["chaos.invariant_failures"] == 1


def test_availability_floor_and_fence_violations_fail_checks():
    reg = MetricsRegistry()
    inv = ChaosInvariants(registry=reg)
    inv.note_read("r0", True)
    inv.note_read("r0", False)
    inv.note_fence(refused=False)          # a zombie write APPLIED
    report = inv.report(availability_floor=0.9)
    assert report["availability"] == 0.5
    assert report["checks"]["availability"] is False
    assert report["checks"]["no_zombie_application"] is False
    assert reg.snapshot()["chaos.invariant_failures"] == 2


def test_acked_write_parity_requires_matching_digests():
    inv = ChaosInvariants(registry=MetricsRegistry())
    report = inv.report(oracle_digest="aa", observed_digest="bb")
    assert report["checks"]["acked_write_parity"] is False
    # no digests supplied → the check is absent, not vacuously true
    assert "acked_write_parity" not in ChaosInvariants(
        registry=MetricsRegistry()).report()["checks"]

"""Cost-based planning (relational/stats.py + relational/cost.py,
ROADMAP item 3): ingest-time cardinality/degree/skew sketches, the
tensor-path cost model that prices plans in padded-bucket device terms,
cost-ranked join-order enumeration, model-chosen physical strategies,
and the divergence → quarantine → re-plan feedback loop.

Correctness contract throughout: statistics are ADVISORY — a distorted
sketch may mis-price a plan, it must never change results.  Every test
that exercises a model decision asserts exact parity against a
model-blind oracle.
"""
from __future__ import annotations

import numpy as np
import pytest

from caps_tpu.backends.local.session import LocalCypherSession
from caps_tpu.backends.tpu.session import TPUCypherSession
from caps_tpu.okapi.config import EngineConfig
from caps_tpu.relational.cost import (
    CostModel, ROW_BYTES, choose_dist_strategy,
)
from caps_tpu.relational.stats import (
    GraphStatistics, _sketch, graph_statistics,
)
from caps_tpu.relational.shapes import ShapeBucketLattice
from caps_tpu.obs.telemetry import OpStatsStore
from caps_tpu.serve.server import QueryServer, ServerConfig
from caps_tpu.testing import faults
from caps_tpu.testing.factory import create_graph
from tests.util import make_graph


# -- graph builders ----------------------------------------------------------


def _skewed_graph(session, n_person=1500, n_city=30, seed=7):
    """Many Persons, few Cities, LIVES_IN edges: a chain whose cheap
    root is the City end (selective eq predicate over few rows)."""
    rng = np.random.RandomState(seed)
    return make_graph(
        session,
        {("Person",): [{"_id": i, "name": f"p{i}"} for i in range(n_person)],
         ("City",): [{"_id": n_person + i, "name": f"c{i}"}
                     for i in range(n_city)]},
        {"LIVES_IN": [(i, n_person + int(rng.randint(0, n_city)), {})
                      for i in range(n_person)]})


CHAIN_Q = ("MATCH (a:Person)-[:LIVES_IN]->(c:City) WHERE c.name = $city "
           "RETURN a.name AS n")


def _rows(result, key="n"):
    return sorted(m[key] for m in result.records.to_maps())


def _ops(result):
    return [m["op"] for m in result.metrics["operators"]]


# -- statistics sketches -----------------------------------------------------


def test_degree_sketch():
    keys = np.array([0] * 40 + [1, 2, 3, 4] * 2, dtype=np.int64)
    sk = _sketch(keys)
    assert sk.rows == 48 and sk.distinct == 5
    assert sk.max == 40
    assert sk.skew == pytest.approx(40 / (48 / 5))
    # node 0 is the lone heavy hitter (> 4x the mean degree of 9.6)
    assert sk.hot_keys == ((0, 40),)


def test_graph_statistics_lookups_and_caching():
    session = TPUCypherSession()
    g = _skewed_graph(session, n_person=200, n_city=10)
    stats = graph_statistics(g)
    assert stats.node_cardinality(["Person"]) == 200
    assert stats.node_cardinality(["City"]) == 10
    assert stats.node_cardinality() == 210
    assert stats.rel_cardinality(["LIVES_IN"]) == 200
    assert stats.rel_cardinality(["NOPE"]) == 0
    assert stats.label_fraction(["City"]) == pytest.approx(10 / 210)
    # names are unique per label set -> distinct == cardinality
    assert stats.eq_distinct(["Person"], "name") == 200
    assert stats.eq_distinct(["Person"], "nope") is None
    assert stats.summary()["rel_types"] == ["LIVES_IN"]
    # lazily computed once, cached on the graph
    snap = session.metrics_snapshot()
    assert snap["stats.computed"] == 1
    assert g.statistics() is stats
    assert session.metrics_snapshot()["stats.computed"] == 1


def test_stats_payload_roundtrip():
    session = TPUCypherSession()
    g = _skewed_graph(session, n_person=100, n_city=8)
    stats = graph_statistics(g)
    back = GraphStatistics.from_payload(stats.to_payload())
    assert back.node_cardinality(["Person"]) == 100
    assert back.rel_cardinality(["LIVES_IN"]) == 100
    assert back.eq_distinct(["City"], "name") == 8
    assert back.rels["LIVES_IN"].out.max == stats.rels["LIVES_IN"].out.max
    # the store is a hint, never an authority: malformed -> None
    assert GraphStatistics.from_payload({"node_combos": 7}) is None
    assert GraphStatistics.from_payload(
        {"rels": {"K": {"rows": "NaN-ish", "out": []}}}) is None


def test_seed_statistics_adopts_persisted_prior():
    """The plan store's ``stats`` field has a LOAD half: a fresh
    graph adopts the persisted sketch as its prior (no host
    recompute), a live sketch always wins, and malformed payloads are
    hints — refused, never raised."""
    s1 = TPUCypherSession()
    g1 = _skewed_graph(s1, n_person=100, n_city=8)
    payload = g1.statistics().to_payload()

    s2 = TPUCypherSession()
    g2 = _skewed_graph(s2, n_person=10, n_city=2)
    assert g2.seed_statistics(payload) is True
    # the prior IS the previous process's sketch, not this graph's
    assert g2.statistics().node_cardinality(["Person"]) == 100
    m = s2.metrics_snapshot()
    assert m.get("stats.seeded", 0) == 1
    assert m.get("stats.computed", 0) == 0

    # a graph that already computed refuses the seed
    s3 = TPUCypherSession()
    g3 = _skewed_graph(s3, n_person=10, n_city=2)
    g3.statistics()
    assert g3.seed_statistics(payload) is False
    assert g3.statistics().node_cardinality(["Person"]) == 10

    # malformed / empty payloads are refused
    s4 = TPUCypherSession()
    g4 = _skewed_graph(s4, n_person=10, n_city=2)
    assert g4.seed_statistics({"node_combos": 7}) is False
    assert g4.seed_statistics({}) is False


def test_fold_delta_refreshes_across_commits():
    from caps_tpu.relational.updates import versioned
    session = TPUCypherSession()
    vg = versioned(session, create_graph(session, """
        CREATE (a:P {name: 'x'}), (b:P {name: 'y'}), (a)-[:K]->(b)
    """))
    base = vg.statistics()
    assert base.node_cardinality(["P"]) == 2
    vg.cypher("CREATE (:P {name: 'z'})")
    refreshed = vg.statistics()
    assert refreshed.node_cardinality(["P"]) == 3
    assert refreshed.version > base.version
    vg.cypher("MATCH (n:P {name: 'z'}) DELETE n")
    assert vg.statistics().node_cardinality(["P"]) == 2


# -- the cost model ----------------------------------------------------------


class _Cfg:
    broadcast_join_threshold = 4096
    join_hot_factor = 4.0


def test_choose_dist_strategy_matrix():
    cfg = _Cfg()
    # build side under the broadcast prior -> broadcast
    s, info = choose_dist_strategy(100_000, 1000, 8, cfg)
    assert s == "broadcast" and info["reason"] == "build<=threshold"
    # big balanced sides, no skew -> radix exchange
    s, info = choose_dist_strategy(100_000, 100_000, 8, cfg)
    assert s == "radix" and info["reason"] == "exchange"
    # sketch-predicted skew at/beyond the hot factor -> planned salt
    s, info = choose_dist_strategy(100_000, 100_000, 8, cfg, skew=6.0)
    assert s == "salted" and info["reason"] == "skew_sketch"
    # huge probe vs modest build: decisively cheaper on the wire
    s, info = choose_dist_strategy(10_000_000, 5000, 8, cfg)
    assert s == "broadcast" and info["reason"] == "wire_model"
    # threshold <= 0 disables broadcasting entirely
    cfg.broadcast_join_threshold = 0
    s, _ = choose_dist_strategy(100_000, 10, 8, cfg)
    assert s == "radix"


def test_device_cost_prices_padded_buckets():
    lattice = ShapeBucketLattice()
    lattice.seed([1000, 5000])
    model = CostModel(lattice=lattice)
    bounds = lattice.boundaries()
    assert model.padded_rows(3) == bounds[0]
    assert model.device_cost(3) == bounds[0] * ROW_BYTES
    # 1000 pads to its pow2 ceiling, not itself
    assert model.padded_rows(1000) == 1024
    # beyond every seen boundary: the compile-risk surcharge prices the
    # cold-cliff in (a brand-new bucket is a brand-new XLA program)
    beyond = bounds[-1] * 2
    assert model.device_cost(beyond) == \
        model.padded_rows(beyond) * ROW_BYTES * 2.0


def test_calibrated_rows_prefers_observed_history():
    store = OpStatsStore()
    fam = "FAM"
    entries = [{"op_id": 1, "op": "Scan", "rows": 500, "seconds": 0.0}]
    model = CostModel(op_stats=store, family=fam)
    est, src = model.calibrated_rows(1, "Scan", 7.0)
    assert (est, src) == (7.0, "model")  # no history yet
    store.record(fam, entries)
    store.record(fam, entries)
    model = CostModel(op_stats=store, family=fam)  # fresh snapshot
    est, src = model.calibrated_rows(1, "Scan", 7.0)
    assert (est, src) == (500.0, "observed")


def test_opstats_model_divergence_is_bucket_aware():
    lattice = ShapeBucketLattice()
    store = OpStatsStore(replan_threshold=2, bucket_fn=lattice.bucket)
    fam = "FAM"

    def rec(rows, est):
        store.record(fam, [{"op_id": 1, "op": "Scan", "rows": rows,
                            "est_rows": est, "seconds": 0.0}])

    # >4x error but same padded bucket: no device-cost consequence
    rec(200, 10)
    assert store.summary()["divergences"] == 0
    # >4x error, different bucket, above the floor: model divergence
    rec(5000, 100)
    assert store.summary()["divergences"] == 1
    assert store.take_replan_candidates() == []  # threshold is 2
    rec(5000, 100)
    assert store.take_replan_candidates() == [fam]
    assert store.take_replan_candidates() == []  # handed off exactly once
    # under the floor never diverges, bucket change or not
    store2 = OpStatsStore(divergence_floor=256, bucket_fn=lattice.bucket)
    store2.record(fam, [{"op_id": 1, "op": "Scan", "rows": 100,
                         "est_rows": 1, "seconds": 0.0}])
    assert store2.summary()["divergences"] == 0
    # estimate-vs-actual surfaced per entry
    st = store.stats(fam)["1:Scan"]
    assert st["est_rows"] == 100 and st["est_err"] > 4
    assert store.summary()["estimated_operators"] == 1


# -- cost-ranked join ordering ----------------------------------------------


def test_chain_reroots_at_selective_far_end():
    oracle = _skewed_graph(
        TPUCypherSession(config=EngineConfig(use_cost_model=False)))
    session = TPUCypherSession()
    g = _skewed_graph(session)
    want = _rows(oracle.cypher(CHAIN_Q, {"city": "c3"}))
    res = g.cypher(CHAIN_Q, {"city": "c3"})
    assert _rows(res) == want
    # the model re-rooted the chain: the City scan (selective far end)
    # seeds, the Person scan joins in last
    plan = res.plans["relational"]
    assert plan.index("Scan(c: CTNode(City))") \
        < plan.index("Scan(a: CTNode(Person))")
    assert session.metrics_snapshot()["cost.reorders"] == 1
    # EXPLAIN carries per-operator estimates and the decision log
    exp = g.cypher("EXPLAIN " + CHAIN_Q, {"city": "c3"}).explain()
    assert "~rows=" in exp and "(model)" in exp
    assert "join_order:" in exp and "chosen=reversed" in exp


def test_reorder_hysteresis_keeps_symmetric_chains_forward():
    session = TPUCypherSession()
    rng = np.random.RandomState(11)
    g = make_graph(
        session,
        {("P",): [{"_id": i, "name": f"n{i}"} for i in range(300)]},
        {"K": [(int(rng.randint(300)), int(rng.randint(300)), {})
               for _ in range(600)]})
    # same label both ends, no predicate: both orientations price the
    # same; the margin keeps the forward (written) order — no churn on
    # ties
    res = g.cypher("EXPLAIN MATCH (a:P)-[:K]->(b:P) RETURN a.name AS n")
    assert "chosen=forward" in res.plans["cost"]
    assert session.metrics_snapshot().get("cost.reorders", 0) == 0


def test_cost_model_off_restores_heuristic_planning():
    session = TPUCypherSession(config=EngineConfig(use_cost_model=False))
    g = _skewed_graph(session)
    res = g.cypher("EXPLAIN " + CHAIN_Q, {"city": "c3"})
    assert "cost" not in res.plans
    assert "~rows=" not in res.plans["relational"]
    # written order preserved: Person scans first
    plan = res.plans["relational"]
    assert plan.index("Scan(a: CTNode(Person))") \
        < plan.index("Scan(c: CTNode(City))")


# -- model-chosen physical strategies ----------------------------------------


def test_count_pushdown_stays_fused_when_spmv_wins():
    session = TPUCypherSession()
    g = _skewed_graph(session, n_person=200, n_city=10)
    res = g.cypher("MATCH (a:Person)-[:LIVES_IN]->(c:City) "
                   "RETURN count(*) AS c")
    assert "CountPattern" in _ops(res)
    assert res.records.to_maps()[0]["c"] == 200


def test_count_pushdown_boundary_prices_launches():
    """The decision boundary, on synthetic statistics: the fused SpMV
    is ONE program over every edge, the cascade is 1 + 2*hops launches
    over tiny padded frontiers.  Small graph -> the launch overhead
    keeps the SpMV; huge graph + unique seed -> the edge bytes dwarf
    the cascade's launches and the model routes around the SpMV."""
    from caps_tpu.ir.pattern import Direction
    from caps_tpu.relational.stats import DegreeSketch, RelStats

    def stats(n, e):
        return GraphStatistics(
            {frozenset(["P"]): n},
            {"K": RelStats("K", e, DegreeSketch(rows=e, distinct=n,
                                                mean=e / n))},
            {(frozenset(["P"]), "name"): n})

    lattice = ShapeBucketLattice()
    hops = [(("K",), Direction.OUTGOING, (), 1.0)]
    small = CostModel(stats(5000, 5000), lattice=lattice)
    assert small.count_pushdown_wins(["P"], 1 / 5000, hops)
    huge = CostModel(stats(2_000_000, 2_000_000), lattice=lattice)
    assert not huge.count_pushdown_wins(["P"], 1 / 2_000_000, hops)


def test_count_pushdown_routes_to_cascade_on_selective_seed():
    """End to end: a hyper-selective seed (unique names) on a chain the
    statistics sketch prices as huge — the padded cascade frontiers are
    tiny, the SpMV would touch millions of edges, the planner keeps the
    join cascade.  Counts stay exact (statistics are advisory)."""
    def build(sess):
        rng = np.random.RandomState(3)
        return make_graph(
            sess,
            {("P",): [{"_id": i, "name": f"u{i}"} for i in range(5000)]},
            {"K": [(int(rng.randint(5000)), int(rng.randint(5000)), {})
                   for _ in range(5000)]})
    q = "MATCH (a:P)-[:K]->(b) WHERE a.name = $u RETURN count(*) AS c"
    oracle = build(LocalCypherSession())
    session = TPUCypherSession()
    g = build(session)
    with faults.stale_statistics(g, scale=400):  # sketch says 2M edges
        res = g.cypher(q, {"u": "u17"})
        assert "CountPattern" not in _ops(res), res.plans["relational"]
        assert res.records.to_maps() == \
            oracle.cypher(q, {"u": "u17"}).records.to_maps()
        # the decision is in the EXPLAIN cost log
        exp = g.cypher("EXPLAIN " + q, {"u": "u17"})
        assert "count_strategy" in exp.plans["cost"]
        assert "chosen=cascade" in exp.plans["cost"]
    # honest (small) statistics: the SpMV wins, counts agree
    session2 = TPUCypherSession()
    g2 = build(session2)
    res2 = g2.cypher(q, {"u": "u17"})
    assert "CountPattern" in _ops(res2)
    assert res2.records.to_maps() == res.records.to_maps()


def test_sharded_explain_renders_dist_strategy():
    """EXPLAIN on a sharded-path query renders the distribution
    strategy (radix/salted/broadcast) the model would pick — visible
    BEFORE execution, not only after."""
    def build(sess):
        rng = np.random.RandomState(5)
        return make_graph(
            sess,
            {("P",): [{"_id": i, "v": int(rng.randint(0, 40))}
                      for i in range(400)]},
            {"T": [(int(rng.randint(400)), int(rng.randint(400)),
                    {}) for _ in range(1500)]})
    q = ("MATCH (a:P)-[r:T]->(b:P) WHERE a.v = 7 "
         "RETURN b.v AS v, count(*) AS c ORDER BY v")
    s1 = TPUCypherSession(config=EngineConfig(mesh_shape=(8,),
                                              use_csr=False))
    exp = build(s1).cypher("EXPLAIN " + q)
    assert "dist=broadcast" in exp.plans["relational"]
    assert "dist:" in exp.plans["cost"]
    # broadcasting disabled: the same plan renders the exchange
    s2 = TPUCypherSession(config=EngineConfig(
        mesh_shape=(8,), use_csr=False, broadcast_join_threshold=0))
    exp2 = build(s2).cypher("EXPLAIN " + q)
    assert "dist=radix" in exp2.plans["relational"] \
        or "dist=salted" in exp2.plans["relational"]


# -- shape-keyed count_fused closures (the PR 10 residual) -------------------


def test_count_fused_closures_key_on_param_shape():
    """Unseen bindings of a seen shape stop charging ``count_fused``
    compiles: the closure keys on the param shape signature, predicate
    masks rebuild per binding as eager device args."""
    def build(sess):
        rng = np.random.RandomState(7)
        return make_graph(
            sess,
            {("P",): [{"_id": i, "name": f"n{i % 13}"}
                      for i in range(120)]},
            {"K": [(int(rng.randint(120)), int(rng.randint(120)), {})
                   for _ in range(500)]})
    q = ("MATCH (a:P)-[:K]->(b)-[:K]->(c) WHERE a.name = $seed "
         "RETURN count(*) AS c")
    oracle = build(LocalCypherSession())
    session = TPUCypherSession()
    g = build(session)
    charged = []
    for seed in ("n5", "n3", "n7", "n5"):
        res = g.cypher(q, {"seed": seed})
        assert res.records.to_maps() == \
            oracle.cypher(q, {"seed": seed}).records.to_maps(), seed
        strat = [m for m in res.metrics["operators"]
                 if m["op"] == "CountPattern"]
        assert strat and strat[0]["strategy"] == "fused-spmv"
        charged.append(res.metrics["compile_s_charged"])
    # ONE compile for the family; every unseen binding replays free
    assert charged[0] > 0
    assert charged[1:] == [0.0, 0.0, 0.0]
    assert session.metrics_snapshot()["compile.recompiles"] == 0


# -- divergence -> quarantine -> re-plan, end to end -------------------------


def test_replan_loop_end_to_end_through_server():
    """The full feedback loop through QueryServer: a stats-violating
    workload (distorted sketch via testing/faults.py) diverges the
    model, the cached family retires through the quarantine path
    (``replan.triggered`` + plan_cache.quarantined), the next execution
    re-plans with the updated statistics and CHANGES strategy (the
    chain re-roots), ``replan.completed`` carries the re-plan, its
    compile seconds are charged — and results are exact throughout."""
    oracle = _skewed_graph(
        TPUCypherSession(config=EngineConfig(use_cost_model=False)))
    want = {c: _rows(oracle.cypher(CHAIN_Q, {"city": c}))
            for c in ("c3", "c5")}
    session = TPUCypherSession()
    g = _skewed_graph(session)
    server = QueryServer(session, graph=g,
                         config=ServerConfig(workers=2))
    try:
        with faults.stale_statistics(g, scale=0.001):
            # the distorted prior prices everything under one bucket:
            # the chain keeps its written (forward) order
            plans = []
            for c in ("c3", "c5"):  # replan_threshold executions
                res = server.submit(CHAIN_Q, {"city": c}).result()
                assert _rows(res) == want[c], c  # exact under the fault
                plans.append(res.plans["relational"])
            assert plans[0].index("Scan(a: CTNode(Person))") \
                < plans[0].index("Scan(c: CTNode(City))")
        # the second diverged execution crossed the threshold: the
        # family was retired through the quarantine path
        snap = session.metrics_snapshot()
        assert snap["replan.triggered"] == 1
        assert snap["plan_cache.quarantined"] >= 1
        assert snap["opstats.divergences"] >= 2
        # updated (honest) statistics: the re-plan re-roots the chain
        res = server.submit(CHAIN_Q, {"city": "c3"}).result()
        assert _rows(res) == want["c3"]
        assert res.metrics["plan_cache"] == "miss"
        assert res.metrics["compile_s_charged"] > 0  # the re-plan's cost
        plan = res.plans["relational"]
        assert plan.index("Scan(c: CTNode(City))") \
            < plan.index("Scan(a: CTNode(Person))")
        assert session.metrics_snapshot()["replan.completed"] == 1
        # the loop is observable in the structured event log, in order
        events = [e for e in server.event_log.records()
                  if e["event"].startswith("replan.")]
        assert [e["event"] for e in events] == ["replan.triggered",
                                                "replan.completed"]
        assert events[0]["quarantined_plans"] >= 1
        assert events[1]["plan_s"] > 0
        # estimate-vs-actual surfaced on the serving stats surface
        opstats = server.health_report()["opstats"]
        assert opstats["estimated_operators"] > 0
        # the re-planned family serves warm again, no further churn
        res = server.submit(CHAIN_Q, {"city": "c5"}).result()
        assert _rows(res) == want["c5"]
        assert res.metrics["plan_cache"] == "hit"
        assert session.metrics_snapshot()["replan.triggered"] == 1
    finally:
        server.shutdown()


def test_replan_disabled_never_retires_plans():
    session = TPUCypherSession(config=EngineConfig(replan_threshold=0))
    g = _skewed_graph(session, n_person=400, n_city=10)
    with faults.stale_statistics(g, scale=0.001):
        for c in ("c1", "c2", "c1", "c2"):
            g.cypher(CHAIN_Q, {"city": c})
    snap = session.metrics_snapshot()
    assert snap.get("replan.triggered", 0) == 0
    assert snap.get("plan_cache.quarantined", 0) == 0

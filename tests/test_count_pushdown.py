"""The SpMV count-pushdown planner rule (relational/count_pattern.py):
count-only pattern chains must lower to dense-vector propagation with
exact relationship-isomorphism corrections, match the local oracle on
every backend, and ride the ring schedule on a mesh (round-1 VERDICT
next-step #4; ref analog: okapi-logical LogicalOptimizer — reconstructed,
mount empty; SURVEY.md §3.2)."""
from __future__ import annotations

import numpy as np
import pytest

from caps_tpu.backends.local.session import LocalCypherSession
from caps_tpu.backends.tpu.session import TPUCypherSession
from caps_tpu.okapi.config import EngineConfig
from tests.util import make_graph


def _random_graph(session, n=120, e=500, seed=7, self_loops=True):
    rng = np.random.RandomState(seed)
    nodes = {("P",): [{"_id": i, "name": f"n{i % 13}"} for i in range(n)]}
    edges = [(int(rng.randint(n)), int(rng.randint(n)), {})
             for _ in range(e)]
    if self_loops:
        edges += [(5, 5, {}), (5, 5, {}), (9, 9, {})]
    else:
        # genuinely loop-free (the cycle-probe plan requires it; chance
        # loops from the RNG would force its structural fallback)
        edges = [(a, b, p) for a, b, p in edges if a != b]
    return make_graph(session, nodes, {"K": edges})


PUSHDOWN_QUERIES = [
    "MATCH (a:P)-[:K]->(b) RETURN count(*) AS c",
    "MATCH (a:P)-[:K]->(b)-[:K]->(c) WHERE a.name = 'n5' RETURN count(*) AS c",
    "MATCH (a:P)-[:K]->(b)-[:K]->(c) RETURN count(*) AS c",
    "MATCH (a:P)<-[:K]-(b) WHERE a.name = 'n3' RETURN count(*) AS c",
    "MATCH (a:P)-[:K]->(b)<-[:K]-(c) WHERE a.name = 'n5' RETURN count(*) AS c",
    "MATCH (a:P)<-[:K]-(b)-[:K]->(c) WHERE a.name = 'n5' RETURN count(*) AS c",
    "MATCH (a:P)-[:K*1..2]->(b) WHERE a.name = 'n1' RETURN count(*) AS c",
    "MATCH (a:P)-[:K*0..1]->(b) RETURN count(*) AS c",
    "MATCH (a:P)-[:K*2..2]->(b) WHERE a.name = 'n5' RETURN count(*) AS c",
    "MATCH (a:P)-[:K]->(b) WHERE a.name = 'n5' AND b.name = 'n3' "
    "RETURN count(*) AS c",
    "MATCH (a:P)-[:K]->(b)-[:K]->(c) WHERE a.name = 'n5' AND b.name = 'n2' "
    "AND c.name = 'n7' RETURN count(*) AS c",
]


def _ops(result):
    return [m["op"] for m in result.metrics["operators"]]


@pytest.mark.parametrize("backend_cfg", [
    ("tpu", EngineConfig()),
    ("sharded", EngineConfig(mesh_shape=(8,))),
], ids=["tpu", "sharded"])
@pytest.mark.parametrize("query", PUSHDOWN_QUERIES)
def test_pushdown_matches_oracle(backend_cfg, query):
    _, cfg = backend_cfg
    oracle = _random_graph(LocalCypherSession())
    session = TPUCypherSession(config=cfg)
    g = _random_graph(session)
    want = oracle.cypher(query).records.to_maps()
    res = g.cypher(query)
    assert res.records.to_maps() == want
    assert "CountPattern" in _ops(res), res.plans["relational"]
    strat = [m for m in res.metrics["operators"]
             if m["op"] == "CountPattern"][0]["strategy"]
    assert strat != "fallback-join"
    assert session.fallback_count == 0


def test_ring_strategy_on_mesh_uniform_chain():
    session = TPUCypherSession(config=EngineConfig(mesh_shape=(8,)))
    g = _random_graph(session)
    res = g.cypher("MATCH (a:P)-[:K]->(b)-[:K]->(c) WHERE a.name = 'n5' "
                   "RETURN count(*) AS c")
    strat = [m for m in res.metrics["operators"]
             if m["op"] == "CountPattern"][0]["strategy"]
    assert strat == "ring"
    # parity against the oracle
    want = _random_graph(LocalCypherSession()).cypher(
        "MATCH (a:P)-[:K]->(b)-[:K]->(c) WHERE a.name = 'n5' "
        "RETURN count(*) AS c").records.to_maps()
    assert res.records.to_maps() == want


def test_mixed_direction_chain_not_ring_but_exact():
    session = TPUCypherSession(config=EngineConfig(mesh_shape=(8,)))
    g = _random_graph(session)
    q = ("MATCH (a:P)-[:K]->(b)<-[:K]-(c) WHERE a.name = 'n5' "
         "RETURN count(*) AS c")
    res = g.cypher(q)
    strat = [m for m in res.metrics["operators"]
             if m["op"] == "CountPattern"][0]["strategy"]
    assert strat == "spmv-sharded"
    want = _random_graph(LocalCypherSession()).cypher(q).records.to_maps()
    assert res.records.to_maps() == want


NOT_LOWERED = [
    # 4 fixed hops: beyond the inclusion–exclusion correction's reach
    "MATCH (a:P)-[:K]->(b)-[:K]->(c)-[:K]->(d)-[:K]->(e) "
    "RETURN count(*) AS c",
    # grouped aggregation
    "MATCH (a:P)-[:K]->(b) RETURN a.name AS n, count(*) AS c",
    # materializing query
    "MATCH (a:P)-[:K]->(b) RETURN b.name AS n",
    # var-length upper > 3
    "MATCH (a:P)-[:K*1..4]->(b) RETURN count(*) AS c",
    # undirected hop
    "MATCH (a:P)-[:K]-(b) RETURN count(*) AS c",
]


@pytest.mark.parametrize("query", NOT_LOWERED)
def test_unsupported_shapes_stay_on_join_path(query):
    oracle = _random_graph(LocalCypherSession())
    session = TPUCypherSession()
    g = _random_graph(session)
    res = g.cypher(query)
    assert "CountPattern" not in _ops(res)
    assert res.records.to_maps() == oracle.cypher(query).records.to_maps()


def test_pushdown_disabled_by_config():
    session = TPUCypherSession(config=EngineConfig(use_count_pushdown=False))
    g = _random_graph(session)
    res = g.cypher("MATCH (a:P)-[:K]->(b) RETURN count(*) AS c")
    assert "CountPattern" not in _ops(res)


def test_local_oracle_never_pushes_down():
    g = _random_graph(LocalCypherSession())
    res = g.cypher("MATCH (a:P)-[:K]->(b) RETURN count(*) AS c")
    assert "CountPattern" not in _ops(res)


def test_pushdown_rides_fused_replay():
    session = TPUCypherSession()
    g = _random_graph(session)
    q = "MATCH (a:P)-[:K]->(b)-[:K]->(c) WHERE a.name = 'n5' RETURN count(*) AS c"
    first = g.cypher(q).records.to_maps()
    assert g.cypher(q).records.to_maps() == first
    assert session.fused.replays == 1


def test_dangling_edges_contribute_nothing():
    """Edges referencing node ids with no node row must not create paths."""
    session = TPUCypherSession()
    g = make_graph(session,
                   {("P",): [{"_id": 1}, {"_id": 2}]},
                   {"K": [(1, 2, {}), (1, 77, {}), (77, 2, {})]})
    oracle = make_graph(LocalCypherSession(),
                        {("P",): [{"_id": 1}, {"_id": 2}]},
                        {"K": [(1, 2, {}), (1, 77, {}), (77, 2, {})]})
    q = "MATCH (a:P)-[:K]->(b:P) RETURN count(*) AS c"
    assert (g.cypher(q).records.to_maps()
            == oracle.cypher(q).records.to_maps())


def test_dangling_edges_unlabeled_target():
    """Fixed Expand joins the target node scan even for unlabeled vars, so
    edges to ids without node rows match nothing; the lowering must mask
    by node existence at every hop."""
    nodes = {("P",): [{"_id": 1}, {"_id": 2}]}
    rels = {"K": [(1, 2, {}), (1, 77, {}), (77, 2, {}), (2, 77, {})]}
    oracle = make_graph(LocalCypherSession(), nodes, rels)
    session = TPUCypherSession()
    g = make_graph(session, nodes, rels)
    for q in ["MATCH (a:P)-[:K]->(b) RETURN count(*) AS c",
              "MATCH (a:P)-[:K]->(b)-[:K]->(c) RETURN count(*) AS c",
              "MATCH (a:P)-[:K*1..2]->(b) RETURN count(*) AS c",
              "MATCH (a:P)-[:K*2..2]->(b) RETURN count(*) AS c"]:
        res = g.cypher(q)
        assert "CountPattern" in _ops(res), q
        assert res.records.to_maps() == oracle.cypher(q).records.to_maps(), q


def _multi_type_graph(session, n=60, seed=3):
    """Several rel types with overlapping self-loops and parallel edges —
    the shapes that stress the 3-hop edge-reuse corrections."""
    rng = np.random.RandomState(seed)
    nodes = {("P",): [{"_id": i, "name": f"n{i % 7}"} for i in range(n)]}

    def edges(e):
        return [(int(rng.randint(n)), int(rng.randint(n)), {})
                for _ in range(e)]

    rels = {"K": edges(220) + [(4, 4, {}), (4, 4, {}), (9, 9, {})],
            "L": edges(100) + [(4, 4, {})],
            "M": edges(60)}
    return make_graph(session, nodes, rels)


THREE_HOP_QUERIES = [
    # uniform type, all outgoing (full P = {12,23,13})
    "MATCH (a:P)-[:K]->(b)-[:K]->(c)-[:K]->(d) RETURN count(*) AS c",
    "MATCH (a:P)-[:K]->(b)-[:K]->(c)-[:K]->(d) WHERE a.name = 'n5' "
    "RETURN count(*) AS c",
    # mixed directions: go-and-return edge reuse in every pair position
    "MATCH (a:P)-[:K]->(b)<-[:K]-(c)-[:K]->(d) RETURN count(*) AS c",
    "MATCH (a:P)<-[:K]-(b)-[:K]->(c)<-[:K]-(d) RETURN count(*) AS c",
    "MATCH (a:P)-[:K]->(b)-[:K]->(c)<-[:K]-(d) RETURN count(*) AS c",
    # untyped middle hop: A13 counts hop-2 multiplicity between reused
    # endpoints over the full edge scan
    "MATCH (a:P)-[:K]->(b)-[r2]->(c)-[:K]->(d) RETURN count(*) AS c",
    # overlapping vs disjoint type combos shrink P's effective terms
    "MATCH (a:P)-[:K]->(b)-[:L]->(c)-[:K]->(d) RETURN count(*) AS c",
    "MATCH (a:P)-[:K]->(b)-[:L]->(c)-[:M]->(d) RETURN count(*) AS c",
    "MATCH (a:P)-[:L]->(b)-[:L]->(c)-[:L]->(d) RETURN count(*) AS c",
    # node predicates at inner and end positions
    "MATCH (a:P)-[:K]->(b)-[:K]->(c)-[:K]->(d) WHERE b.name = 'n2' "
    "AND d.name = 'n3' RETURN count(*) AS c",
    # var-length up to 3 (isomorphism within every length)
    "MATCH (a:P)-[:K*1..3]->(b) RETURN count(*) AS c",
    "MATCH (a:P)-[:K*3..3]->(b) WHERE a.name = 'n5' RETURN count(*) AS c",
    "MATCH (a:P)-[:K*0..3]->(b) WHERE b.name = 'n1' RETURN count(*) AS c",
    "MATCH (a:P)-[:L*2..3]->(b) RETURN count(*) AS c",
]


@pytest.mark.parametrize("query", THREE_HOP_QUERIES)
def test_three_hop_pushdown_matches_oracle(query):
    oracle = _multi_type_graph(LocalCypherSession())
    session = TPUCypherSession()
    g = _multi_type_graph(session)
    want = oracle.cypher(query).records.to_maps()
    res = g.cypher(query)
    assert res.records.to_maps() == want, (query, want)
    assert "CountPattern" in _ops(res), res.plans["relational"]
    strat = [m for m in res.metrics["operators"]
             if m["op"] == "CountPattern"][0]["strategy"]
    assert strat == "fused-spmv", strat


def test_three_hop_planner_selects_count_pattern():
    session = TPUCypherSession()
    g = _multi_type_graph(session)
    res = g.cypher("MATCH (a:P)-[:K]->(b)-[:K]->(c)-[:K]->(d) "
                   "RETURN count(*) AS c")
    assert "CountPattern" in res.plans["relational"]


def test_three_hop_tiny_adversarial_shapes():
    """Hand-checkable graphs: pure self-loop chains and go-return paths
    where walks and matches diverge maximally."""
    nodes = {("P",): [{"_id": 0}, {"_id": 1}, {"_id": 2}, {"_id": 3}]}
    cases = [
        # one self loop: walks 0-0-0-0 exist, matches need 3 distinct edges
        {"K": [(0, 0, {})]},
        # two parallel self loops: 3 distinct-edge walks impossible (2 edges)
        {"K": [(0, 0, {}), (0, 0, {})]},
        # three parallel self loops: 3! orderings match
        {"K": [(0, 0, {}), (0, 0, {}), (0, 0, {})]},
        # triangle plus chord
        {"K": [(0, 1, {}), (1, 2, {}), (2, 0, {}), (0, 2, {})]},
        # go-return pair between two nodes
        {"K": [(0, 1, {}), (1, 0, {})]},
        # parallel edges both directions
        {"K": [(0, 1, {}), (0, 1, {}), (1, 0, {}), (1, 0, {})]},
    ]
    queries = [
        "MATCH (a:P)-[:K]->(b)-[:K]->(c)-[:K]->(d) RETURN count(*) AS c",
        "MATCH (a:P)-[:K]->(b)<-[:K]-(c)-[:K]->(d) RETURN count(*) AS c",
        "MATCH (a:P)-[:K*1..3]->(b) RETURN count(*) AS c",
    ]
    for rels in cases:
        oracle = make_graph(LocalCypherSession(), nodes, rels)
        g = make_graph(TPUCypherSession(), nodes, rels)
        for q in queries:
            want = oracle.cypher(q).records.to_maps()
            got = g.cypher(q).records.to_maps()
            assert got == want, (rels, q, want, got)


def test_untyped_and_typed_hops_edge_reuse_correction():
    """An untyped hop scans every edge, so a typed hop's edges overlap it:
    the r1 <> r2 correction must iterate the intersection scan (review
    repro: oracle 0, naive pushdown 2)."""
    nodes = {("P",): [{"_id": 1}, {"_id": 2}, {"_id": 3}]}
    rels = {"K": [(1, 2, {}), (2, 3, {})]}
    oracle = make_graph(LocalCypherSession(), nodes, rels)
    session = TPUCypherSession()
    g = make_graph(session, nodes, rels)
    for q in [
        "MATCH (a:P)-[r1]->(b)<-[r2:K]-(c) RETURN count(*) AS c",
        "MATCH (a:P)-[r1:K]->(b)<-[r2]-(c) RETURN count(*) AS c",
        "MATCH (a:P)-[r1]->(b)<-[r2]-(c) RETURN count(*) AS c",
    ]:
        res = g.cypher(q)
        assert "CountPattern" in _ops(res), q
        want = oracle.cypher(q).records.to_maps()
        assert res.records.to_maps() == want, (q, want)


def test_star_pattern_not_miscounted_as_chain():
    """Round-5 regression: (a)->(b), (a)->(c) type-checks as 2 hops over 3
    node vars but is NOT a chain; the walk must verify source continuity
    (counting it as a->b->c silently returned 0 matches)."""
    q = "MATCH (a:P)-[r:K]->(b), (a)-[s:K]->(c) RETURN count(*) AS c"
    oracle = _random_graph(LocalCypherSession())
    session = TPUCypherSession()
    g = _random_graph(session)
    res = g.cypher(q)
    want = oracle.cypher(q).records.to_maps()
    assert res.records.to_maps() == want
    assert want[0]["c"] > 0


def test_pushdown_does_not_execute_fallback_join_plan():
    """Round-5 regression: the roofline bytes accounting forced the lazy
    fallback child, executing the whole join cascade alongside every
    successful pushdown."""
    session = TPUCypherSession()
    g = _random_graph(session)
    res = g.cypher("MATCH (a:P)-[:K]->(b)-[:K]->(c) RETURN count(*) AS c")
    ops = _ops(res)
    assert "CountPattern" in ops
    assert "Join" not in ops, ops


TRIANGLE_QUERIES = [
    # canonical oriented triangle (benchmark config 4 shape)
    "MATCH (a:P)-[:K]->(b)-[:K]->(c), (a)-[:K]->(c) RETURN count(*) AS c",
    # closing edge written in the reverse orientation
    "MATCH (a:P)-[:K]->(b)-[:K]->(c), (c)-[:K]->(a) RETURN count(*) AS c",
    # closing edge written as an incoming pattern on a
    "MATCH (a:P)-[:K]->(b)-[:K]->(c), (a)<-[:K]-(c) RETURN count(*) AS c",
    # seed predicate + mixed chain directions
    "MATCH (a:P)-[:K]->(b)<-[:K]-(c), (a)-[:K]->(c) "
    "WHERE a.name = 'n5' RETURN count(*) AS c",
]


@pytest.mark.parametrize("query", TRIANGLE_QUERIES)
@pytest.mark.parametrize("self_loops", [False, True],
                         ids=["clean", "self-loops"])
def test_cycle_count_matches_oracle(query, self_loops):
    """The triangle cycle-probe plan must agree with the oracle; graphs
    WITH self-loops must fall back (rel-instance coincidences become
    possible) and still agree."""
    oracle = _random_graph(LocalCypherSession(), self_loops=self_loops)
    session = TPUCypherSession()
    g = _random_graph(session, self_loops=self_loops)
    res = g.cypher(query)
    want = oracle.cypher(query).records.to_maps()
    assert res.records.to_maps() == want, query
    assert "CountCycle" in _ops(res), res.plans["relational"]
    strat = [m for m in res.metrics["operators"]
             if m["op"] == "CountCycle"][0]["strategy"]
    if self_loops:
        assert strat == "fallback-join"
    else:
        assert strat == "cycle-probe"
        assert "Join" not in _ops(res)


def test_cycle_count_parallel_closing_edges():
    """Parallel closing edges each produce a distinct match (the probe
    returns key multiplicity)."""
    nodes = {("P",): [{"_id": i} for i in range(3)]}
    edges = [(0, 1, {}), (1, 2, {}), (0, 2, {}), (0, 2, {})]
    oracle = make_graph(LocalCypherSession(), nodes, {"K": edges})
    session = TPUCypherSession()
    g = make_graph(session, nodes, {"K": edges})
    q = "MATCH (a:P)-[:K]->(b)-[:K]->(c), (a)-[:K]->(c) RETURN count(*) AS c"
    res = g.cypher(q)
    want = oracle.cypher(q).records.to_maps()
    assert res.records.to_maps() == want == [{"c": 2}]

"""Device fault domains (ISSUE 6): multi-device replica serving with
failover behind QueryServer.

The contracts under test:

* replication — each replica owns a re-ingested graph copy and a cloned
  session (per-device plan cache / string pool / fused memos); results
  are digest-equal to the template session's;
* failover — a TRANSIENT device failure retries on a DIFFERENT healthy
  device; consecutive device-attributed failures quarantine the device,
  its claimed work drains back to the dispatcher, and a background
  canary probe reinstates it (quarantine → probing → healthy on the
  fake clock, exactly);
* degraded capacity — the admission controller's retry_after estimator
  is told how many devices are actually live;
* the acceptance soak — 8 clients × mixed queries with one of N devices
  killed mid-run: availability 1.0, digest-equal results, work visibly
  redistributed off the dead device;
* retry-backoff interruptibility (satellite regression) — ``cancel()``
  wakes a backing-off worker immediately instead of burning the rest of
  the backoff, and ``shutdown(drain=False)`` cancels in-flight work.
"""
from __future__ import annotations

import threading
import time

import pytest

import caps_tpu
from caps_tpu.obs import clock, lockgraph
from caps_tpu.serve import (Cancelled, CancellationError, QueryServer,
                            RetryPolicy, ServerConfig, device_fault)
from caps_tpu.serve.devices import (HEALTHY, PROBING, QUARANTINED,
                                    ReplicaSet, executing_device_index,
                                    replicate_graph)
from caps_tpu.serve.errors import ReplicationUnsupported
from caps_tpu.testing.factory import create_graph
from caps_tpu.testing.faults import device_loss, sick_device

SOCIAL = """
    CREATE (a:Person {name: 'Alice', age: 33}),
           (b:Person {name: 'Bob', age: 44}),
           (c:Person {name: 'Carol', age: 27}),
           (d:Person {name: 'Dana', age: 51}),
           (a)-[:KNOWS {since: 2011}]->(b),
           (b)-[:KNOWS {since: 2015}]->(c),
           (a)-[:KNOWS {since: 2019}]->(c),
           (c)-[:KNOWS {since: 2021}]->(d)
"""

Q_ORDER = ("MATCH (p:Person) WHERE p.age > $min "
           "RETURN p.name AS n ORDER BY n")
Q_EDGE = ("MATCH (a:Person)-[:KNOWS]->(b) WHERE a.age > $min "
          "RETURN a.name AS a, b.name AS b")
Q_COUNT = ("MATCH (a:Person)-[k:KNOWS]->(b) WHERE k.since >= $y "
           "RETURN count(*) AS c")


def _session():
    return caps_tpu.local_session(backend="local")


def _graph(session):
    return create_graph(session, SOCIAL)


def _bag(rows):
    return sorted(sorted(r.items()) for r in rows)


def _drive(server, replica):
    """Direct-drive one dispatch cycle: pull the next batch from the
    dispatcher and execute it as ``replica``'s worker would."""
    batch = server.batcher.next_batch(timeout=0)
    if batch:
        server._execute_batch(batch, replica)
    return batch


class FakeClock:
    """Same fake as tests/test_faults.py: ``sleep`` advances ``now``
    instantly; ``wait`` honors an already-fired cancel event with no
    time passing."""

    def __init__(self, t0: float = 1_000.0):
        self._t = t0
        self._lock = threading.Lock()
        self.sleeps: list = []

    def now(self) -> float:
        with self._lock:
            return self._t

    def sleep(self, s: float) -> None:
        with self._lock:
            self._t += s
            self.sleeps.append(s)

    def wait(self, event, timeout: float) -> bool:
        if event.is_set():
            return True
        self.sleep(timeout)
        return event.is_set()

    def advance(self, s: float) -> None:
        with self._lock:
            self._t += s


@pytest.fixture()
def fake_clock(monkeypatch):
    fc = FakeClock()
    monkeypatch.setattr(clock, "now", fc.now)
    monkeypatch.setattr(clock, "sleep", fc.sleep)
    monkeypatch.setattr(clock, "wait", fc.wait)
    return fc


# -- replication (serve/devices.py replicate_graph + session.clone) --------

def test_replicate_graph_digest_parity():
    from caps_tpu.relational.session import result_digest
    src = _session()
    graph = _graph(src)
    dst = src.clone()
    copy = replicate_graph(graph, dst)
    for q, b in [(Q_ORDER, {"min": 30}), (Q_EDGE, {"min": 25}),
                 (Q_COUNT, {"y": 2015})]:
        assert result_digest(graph.cypher(q, b)) \
            == result_digest(copy.cypher(q, b))
    # the copy is anchored to the CLONE session, not the template
    assert copy.session is dst and copy.session is not src


def test_clone_session_shares_no_mutable_state():
    src = _session()
    dst = src.clone()
    assert type(dst) is type(src) and dst.config is src.config
    assert dst.plan_cache is not src.plan_cache
    assert dst.metrics_registry is not src.metrics_registry
    assert dst.catalog is not src.catalog
    # device backend: per-device string pool and fused memos
    tpu = caps_tpu.local_session(backend="tpu")
    tpu2 = tpu.clone()
    assert tpu2.backend is not tpu.backend
    assert tpu2.backend.pool is not tpu.backend.pool
    assert tpu2.fused is not tpu.fused


def test_replicate_graph_rejects_union_graphs():
    session = _session()
    g = _graph(session)
    union = g.union_all(_graph(session))
    with pytest.raises(ReplicationUnsupported):
        replicate_graph(union, session.clone())


def test_replica_set_isolation_and_eager_ingest():
    session = _session()
    graph = _graph(session)
    rs = ReplicaSet(session, graph=graph, n_devices=3,
                    registry=session.metrics_registry)
    assert len(rs) == 3
    assert rs.replicas[0].session is session           # template reuse
    sessions = [r.session for r in rs.replicas]
    assert len({id(s) for s in sessions}) == 3
    assert len({id(s.plan_cache) for s in sessions}) == 3
    # ingest once per device happened at construction; replica copies
    # are distinct objects anchored to their own sessions
    g1 = rs.replicas[1].graph_for(graph)
    g2 = rs.replicas[2].graph_for(graph)
    assert g1 is not graph and g2 is not graph and g1 is not g2
    assert g1.session is sessions[1] and g2.session is sessions[2]
    # replica 0 serves the ORIGINAL graph object
    assert rs.replicas[0].graph_for(graph) is graph


def test_non_replicable_graphs_pin_to_device0():
    """A union graph cannot re-ingest onto other devices: the server
    must still construct with devices=N (other replicas just idle for
    it), serve it on device 0, and keep TRANSIENT retries on device 0
    instead of leaking ReplicationUnsupported to the client."""
    from caps_tpu.testing.faults import failing_operator
    session = _session()
    union = _graph(session).union_all(_graph(session))
    expected = _bag(union.cypher(Q_COUNT, {"y": 2015}).records.to_maps())
    server = QueryServer(session, graph=union, start=False,
                         config=ServerConfig(
                             devices=2,
                             retry=RetryPolicy(backoff_base_s=0.0,
                                               jitter=0.0)))
    r1 = server.devices.replicas[1]
    marked = RuntimeError("flaky backend")
    marked.caps_transient = True
    with failing_operator("Scan", exc=marked, n_times=1):
        h = server.submit(Q_COUNT, {"y": 2015})
        _drive(server, r1)                   # claimed by device 1...
    rows = h.rows(timeout=5)                 # ...served by device 0
    assert _bag(rows) == expected
    assert all(a["device"] == 0 for a in h.info["attempts"])
    server.shutdown(drain=False)


def test_replica_graph_cache_is_bounded():
    from caps_tpu.serve.devices import MAX_REPLICA_GRAPHS
    session = _session()
    rs = ReplicaSet(session, n_devices=2,
                    registry=session.metrics_registry)
    r1 = rs.replicas[1]
    graphs = [create_graph(session, "CREATE (:Person {name: 'solo'})")
              for _ in range(MAX_REPLICA_GRAPHS + 3)]
    for g in graphs:
        r1.graph_for(g)
    assert len(r1._graphs) == MAX_REPLICA_GRAPHS
    # the most recent graphs stayed cached (LRU end), the oldest fell out
    assert id(graphs[-1]) in r1._graphs
    assert id(graphs[0]) not in r1._graphs


# -- multi-device serving --------------------------------------------------

def test_multi_device_server_serves_mixed_queries():
    session = _session()
    graph = _graph(session)
    expected = {
        (Q_ORDER, 30): _bag(graph.cypher(Q_ORDER,
                                         {"min": 30}).records.to_maps()),
        (Q_EDGE, 25): _bag(graph.cypher(Q_EDGE,
                                        {"min": 25}).records.to_maps()),
        (Q_COUNT, 2015): _bag(graph.cypher(Q_COUNT,
                                           {"y": 2015}).records.to_maps()),
    }
    with QueryServer(session, graph=graph,
                     config=ServerConfig(devices=3)) as server:
        handles = []
        for i in range(30):
            q, k, b = [(Q_ORDER, 30, {"min": 30}), (Q_EDGE, 25, {"min": 25}),
                       (Q_COUNT, 2015, {"y": 2015})][i % 3]
            handles.append(((q, k), server.submit(q, b)))
        for key, h in handles:
            assert _bag(h.rows(timeout=30)) == expected[key]
        assert server.health() == "healthy"
        assert server.device_health() == {0: HEALTHY, 1: HEALTHY,
                                          2: HEALTHY}
        devs = server.stats()["devices"]
        assert sum(d["completed"] for d in devs) == 30
        assert all(d["health"] == HEALTHY for d in devs)


def test_transient_device_fault_retries_on_different_device():
    session = _session()
    graph = _graph(session)
    server = QueryServer(session, graph=graph, start=False,
                         config=ServerConfig(
                             devices=2,
                             retry=RetryPolicy(backoff_base_s=0.0,
                                               jitter=0.0)))
    r0 = server.devices.replicas[0]
    with device_loss(0, n_times=1) as budget:
        h = server.submit(Q_ORDER, {"min": 30})
        _drive(server, r0)
    assert budget.injected == 1
    assert [r["n"] for r in h.rows(timeout=5)] == ["Alice", "Bob", "Dana"]
    attempts = h.info["attempts"]
    # first attempt failed ON device 0, the retry succeeded on device 1
    assert attempts[0]["device"] == 0
    assert attempts[0]["classified"] == "transient"
    assert attempts[-1] == {"mode": "fused", "ok": True, "device": 1}
    devs = server.stats()["devices"]
    assert devs[0]["failed"] == 1 and devs[1]["completed"] == 1
    server.shutdown(drain=False)


def test_sick_device_faults_scope_to_one_replica():
    session = _session()
    graph = _graph(session)
    server = QueryServer(session, graph=graph, start=False,
                         config=ServerConfig(
                             devices=2,
                             # out of the way: this test isolates the
                             # injector's per-device scoping, not the
                             # quarantine ladder (its own tests above)
                             device_failure_threshold=100,
                             retry=RetryPolicy(backoff_base_s=0.0,
                                               jitter=0.0)))
    r0, r1 = server.devices.replicas
    with sick_device(1, error_rate=0.5) as budget:
        # device 0's stream never sees the fault
        for _ in range(3):
            h = server.submit(Q_COUNT, {"y": 2015})
            _drive(server, r0)
            assert h.rows(timeout=5) == [{"c": 3}]
        assert budget.injected == 0
        # device 1's stream does — and every hit resolves via failover
        for _ in range(4):
            h = server.submit(Q_COUNT, {"y": 2015})
            _drive(server, r1)
            assert h.rows(timeout=5) == [{"c": 3}]
        assert budget.injected >= 1
    assert executing_device_index() is None  # bracket never leaks
    server.shutdown(drain=False)


# -- quarantine -> probe -> reinstate lifecycle ----------------------------

def test_quarantine_probe_reinstate_lifecycle(fake_clock):
    session = _session()
    graph = _graph(session)
    server = QueryServer(session, graph=graph, start=False,
                         config=ServerConfig(
                             devices=2, device_failure_threshold=1,
                             device_cooldown_s=10.0,
                             retry=RetryPolicy(backoff_base_s=0.0,
                                               jitter=0.0)))
    r0, r1 = server.devices.replicas
    assert server.admission.workers == 2
    loss = device_loss(1)
    budget = loss.__enter__()
    try:
        # one device-attributed failure trips the (threshold-1) ladder:
        # the request itself fails over to device 0 and succeeds
        h = server.submit(Q_ORDER, {"min": 30})
        _drive(server, r1)
        assert [r["n"] for r in h.rows(timeout=5)] == ["Alice", "Bob",
                                                       "Dana"]
        assert h.info["attempts"][-1]["device"] == 0
        assert server.device_health() == {0: HEALTHY, 1: QUARANTINED}
        assert server.health() == "degraded"
        # degraded capacity reaches the retry_after estimator
        assert server.admission.workers == 1
        # a batch CLAIMED by the quarantined device drains back to the
        # dispatcher and is served by the healthy one
        h2 = server.submit(Q_COUNT, {"y": 2015})
        _drive(server, r1)                       # requeues, must not run
        assert not h2.done()
        _drive(server, r0)
        assert h2.rows(timeout=5) == [{"c": 3}]
        assert h2.info["device"] == 0
        assert session.metrics_snapshot()["serve.requeued"] == 1
        # cooldown not elapsed: no probe slot yet
        verdict, retry_after = server.devices.try_probe(r1)
        assert verdict == "reject" and 0 < retry_after <= 10.0
        # cooldown elapsed, fault still active: the background canary
        # probe fails and buys another full cooldown
        fake_clock.advance(10.0)
        verdict, _ = server.devices.try_probe(r1)
        assert verdict == "trial"
        assert server.devices.probe(r1) is False
        assert server.device_health()[1] == QUARANTINED
        assert budget.injected >= 2              # trip + failed probe
    finally:
        loss.__exit__(None, None, None)
    # fault lifted + cooldown elapsed: the probe reinstates the device
    fake_clock.advance(10.0)
    verdict, _ = server.devices.try_probe(r1)
    assert verdict == "trial"
    assert server.devices.state(r1) == PROBING
    assert server.devices.probe(r1) is True
    assert server.device_health() == {0: HEALTHY, 1: HEALTHY}
    assert server.health() == "healthy"
    assert server.admission.workers == 2
    snap = r1.snapshot()
    assert snap["quarantines"] == 1
    assert snap["reinstates"] == 1
    assert snap["probes"] == 2
    reg = session.metrics_snapshot()
    assert reg["serve.devices.quarantined"] == 1
    assert reg["serve.devices.reinstated"] == 1
    assert reg["serve.devices.probes"] == 2
    server.shutdown(drain=False)


def test_device_ladder_disabled_for_single_device():
    """A lone device never quarantines: there is nowhere to fail over,
    so a sick single device must stay a serving (retrying) device."""
    session = _session()
    graph = _graph(session)
    server = QueryServer(session, graph=graph, start=False,
                         config=ServerConfig(
                             devices=1, device_failure_threshold=1,
                             retry=RetryPolicy(max_attempts=2,
                                               backoff_base_s=0.0,
                                               jitter=0.0)))
    r0 = server.devices.replicas[0]
    with device_loss(0, n_times=1):
        h = server.submit(Q_COUNT, {"y": 2015})
        _drive(server, r0)
    assert h.rows(timeout=5) == [{"c": 3}]       # same-device retry
    assert server.device_health() == {0: HEALTHY}
    server.shutdown(drain=False)


def test_user_errors_never_quarantine_a_device():
    session = _session()
    graph = _graph(session)
    server = QueryServer(session, graph=graph, start=False,
                         config=ServerConfig(devices=2,
                                             device_failure_threshold=1))
    r1 = server.devices.replicas[1]
    for _ in range(3):
        h = server.submit("MATCH (p:Person RETURN p")  # syntax error
        _drive(server, r1)
        assert isinstance(h.exception(timeout=5), Exception)
    assert server.device_health()[1] == HEALTHY
    assert not device_fault(SyntaxError("x"))
    server.shutdown(drain=False)


# -- admission accounting --------------------------------------------------

def test_retry_after_accounts_for_live_streams():
    from caps_tpu.obs.metrics import MetricsRegistry
    from caps_tpu.serve.admission import AdmissionController
    adm = AdmissionController(MetricsRegistry(), max_queue=64, workers=4)
    adm.observe_service(1.0)
    assert adm.retry_after_s(depth=8) == pytest.approx(2.0)
    adm.set_active_workers(2)                    # two devices quarantined
    assert adm.retry_after_s(depth=8) == pytest.approx(4.0)
    adm.set_active_workers(0)                    # clamps to 1
    assert adm.retry_after_s(depth=8) == pytest.approx(8.0)


# -- retry-backoff interruptibility (satellite regression) -----------------

def test_cancel_interrupts_retry_backoff_fake_clock(fake_clock):
    """Regression: a cancelled request must stop sleeping immediately —
    the backoff wait returns the moment the cancel event is set, no
    backoff is burned, and the outcome is the budget's verdict."""
    from caps_tpu.testing.faults import make_oom
    session = _session()
    graph = _graph(session)
    server = QueryServer(session, graph=graph, start=False,
                         config=ServerConfig(
                             retry=RetryPolicy(max_attempts=5,
                                               backoff_base_s=100.0,
                                               backoff_max_s=100.0,
                                               jitter=0.0)))
    h = server.submit(Q_COUNT, {"y": 2015})
    req = server.batcher.next_batch(timeout=0)[0]
    req.scope.cancel()
    outcome = server._recover(req, make_oom(), 0,
                              server.devices.replicas[0])
    assert isinstance(outcome, Cancelled)
    assert outcome.phase == "backoff"
    assert fake_clock.sleeps == []               # zero backoff burned
    assert h is req.handle
    server.shutdown(drain=False)


def test_cancel_wakes_real_backoff_sleep_promptly():
    from caps_tpu.serve.deadline import CancelScope
    policy = RetryPolicy(backoff_base_s=5.0, backoff_max_s=5.0, jitter=0.0)
    scope = CancelScope()
    threading.Timer(0.05, scope.cancel).start()
    t0 = time.perf_counter()
    policy.sleep(5.0, scope=scope)
    elapsed = time.perf_counter() - t0
    assert scope.cancelled
    assert elapsed < 2.0                         # woke early, not at 5s


def test_non_drain_shutdown_cancels_inflight_backoff():
    """shutdown(drain=False) must interrupt an in-flight request's
    retry sleep, not wait out its backoff schedule."""
    from caps_tpu.testing.faults import failing_operator
    session = _session()
    graph = _graph(session)
    server = QueryServer(session, graph=graph, config=ServerConfig(
        workers=1, retry=RetryPolicy(max_attempts=1000,
                                     backoff_base_s=0.5, backoff_max_s=0.5,
                                     jitter=0.0)))
    with failing_operator("Filter", n_times=None):  # permanent transient
        h = server.submit(Q_ORDER, {"min": 30})
        # wait until the worker demonstrably entered the retry loop
        deadline = time.perf_counter() + 5.0
        while session.metrics_snapshot().get("serve.retries", 0) == 0 \
                and time.perf_counter() < deadline:
            time.sleep(0.01)
        t0 = time.perf_counter()
        assert server.shutdown(drain=False, timeout=10.0)
        assert time.perf_counter() - t0 < 5.0
    ex = h.exception(timeout=5)
    assert isinstance(ex, CancellationError)


# -- the acceptance soak: device killed mid-run ----------------------------

def _device_loss_soak(n_devices: int, per_thread: int,
                      lock_graph: bool = False):
    if lock_graph:
        # every lock created from here on (server, breaker, admission
        # cond, per-replica session state) is a tracked lock recording
        # per-thread acquisition-order edges; strict mode raises
        # LockOrderViolation mid-soak the moment any two locks are ever
        # taken in both orders
        lockgraph.reset()
    session = _session()
    graph = _graph(session)
    flat = [(Q_ORDER, {"min": m}) for m in (20, 30, 40, 50)] + \
           [(Q_EDGE, {"min": m}) for m in (25, 35, 45)] + \
           [(Q_COUNT, {"y": y}) for y in (2011, 2015, 2020)]
    expected = {i: _bag(graph.cypher(q, b).records.to_maps())
                for i, (q, b) in enumerate(flat)}
    server = QueryServer(session, graph=graph, config=ServerConfig(
        devices=n_devices, max_queue=4096, max_batch=4,
        # threshold 1: the victim quarantines on its FIRST claimed
        # failure — which batch lands on which worker is scheduling
        # noise the soak must not depend on
        device_failure_threshold=1, device_cooldown_s=30.0,
        breaker_threshold=1000,
        retry=RetryPolicy(max_attempts=5, backoff_base_s=0.001,
                          backoff_max_s=0.01)))
    n_threads = 8
    results: dict = {}
    submit_errors: list = []

    def run_phase(phase: int):
        def client(tid: int):
            try:
                for j in range(per_thread):
                    i = (tid * 7 + phase + j) % len(flat)
                    q, b = flat[i]
                    results[(phase, tid, j)] = (i, server.submit(q, b))
            except Exception as ex:  # pragma: no cover — must not happen
                submit_errors.append(ex)
        threads = [threading.Thread(target=client, args=(t,))
                   for t in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for _i, handle in results.values():
            assert handle.wait(timeout=60)

    # phase 1: all devices healthy (warms every replica's plan cache)
    phases = 1
    run_phase(0)
    before_kill = {d["device"]: d["requests"]
                   for d in server.stats()["devices"]}
    victim = 1
    with device_loss(victim):
        # phase 2: device `victim` is DEAD mid-run — requests fail over,
        # the quarantine trips, capacity degrades to N-1
        run_phase(1)
        phases += 1
        # the victim quarantines on its first claimed failure; top up
        # with bounded extra waves in case phase 2's batches all landed
        # on other workers (scheduling noise, not a failover property)
        for extra in range(10):
            if server.device_health()[victim] != HEALTHY:
                break
            run_phase(2 + extra)
            phases += 1
        health = server.device_health()
        assert health[victim] in (QUARANTINED, PROBING)
        assert all(h == HEALTHY for d, h in health.items() if d != victim)
        assert server.health() == "degraded"
        server.shutdown()        # graceful drain completes on N-1 devices
    assert not submit_errors, submit_errors
    # availability 1.0: every request of EVERY phase resolved with
    # digest-equal rows — no typed give-ups, no worker deaths, no
    # untyped injector leaks
    assert len(results) == phases * n_threads * per_thread
    for i, handle in results.values():
        assert handle.done()
        ex = handle.exception()
        assert ex is None, ex
        assert _bag(handle.rows()) == expected[i], i
    # work visibly redistributed: the dead device stopped absorbing
    # requests after its quarantine while the survivors kept serving
    devs = server.stats()["devices"]
    victim_stats = devs[victim]
    assert victim_stats["quarantines"] == 1
    survivor_delta = sum(d["requests"] - before_kill[d["device"]]
                         for d in devs if d["device"] != victim)
    victim_delta = victim_stats["requests"] - before_kill[victim]
    assert survivor_delta > victim_delta
    snap = session.metrics_snapshot()
    assert snap["serve.completed"] == phases * n_threads * per_thread
    return snap


def test_soak_device_killed_mid_run(monkeypatch):
    """The acceptance soak, with the runtime lock-order graph on
    (CAPS_TPU_LOCK_GRAPH=1): 8 clients, a device killed mid-run, AND a
    machine-checked assertion that the locks the quarantine/requeue
    path took form an acyclic acquisition order that agrees with
    capslint's static lock-order graph (every statically predicted
    serve-tier edge that fired at runtime fired in the same
    direction)."""
    monkeypatch.setenv("CAPS_TPU_LOCK_GRAPH", "1")
    _device_loss_soak(n_devices=4, per_thread=6, lock_graph=True)
    snap = lockgraph.lock_graph_snapshot()
    # strict mode would already have raised mid-soak on a cycle; assert
    # anyway so a future `record` default can't silently weaken this
    assert lockgraph.find_cycle() is None, snap["edges"]
    # the soak's lock traffic covers the serve tier's fault-domain
    # machinery: per-device exec locks, the admission condition (offer/
    # requeue), the breaker state machine driving quarantine/probe, and
    # per-replica stats — all under tracked names
    nodes = set(snap["nodes"])
    assert {"devices.DeviceReplica.lock",
            "admission.AdmissionController._cond",
            "breaker.CircuitBreaker._lock",
            "devices.DeviceReplica._stats_lock",
            "plan_cache.PlanCache._lock"} <= nodes, nodes
    edges = set(snap["edges"])
    # execution holds the device stream lock while the engine takes the
    # plan-cache lock; admission counters tick under the queue condition
    assert ("devices.DeviceReplica.lock",
            "plan_cache.PlanCache._lock") in edges, sorted(edges)
    assert ("admission.AdmissionController._cond",
            "metrics.Counter._lock") in edges, sorted(edges)
    # static/dynamic agreement: every statically predicted edge that was
    # observed at runtime was observed in the SAME direction — the
    # reverse direction appearing would be a cycle between the graphs
    from caps_tpu.analysis import load_project
    from caps_tpu.analysis.locks import static_lock_graph
    static_edges, _index, _info = static_lock_graph(load_project())
    for a, b in static_edges:
        assert (b, a) not in edges, (
            f"static order {a} -> {b} reversed at runtime")


@pytest.mark.slow
def test_soak_device_killed_mid_run_long():
    _device_loss_soak(n_devices=4, per_thread=30)

"""Hand-scheduled distributed joins (parallel/dist_join.py): radix
all_to_all exchange, hot-key salting, broadcast join — parity against the
local oracle on the 8-virtual-device CPU mesh, plus strategy-selection
and ICI-accounting checks (SURVEY.md §5.8; round-4 VERDICT item 4)."""
import numpy as np
import pytest

from caps_tpu.backends.local.session import LocalCypherSession
from caps_tpu.backends.tpu.session import TPUCypherSession
from caps_tpu.okapi.config import EngineConfig
from caps_tpu.testing.bag import Bag

from util import make_graph


def _build(session, n=400, m=1500, seed=5, hot_frac=0.0):
    rng = np.random.RandomState(seed)
    src = rng.randint(0, n, m)
    dst = rng.randint(0, n, m)
    if hot_frac:
        # power-law-ish: a fraction of edges all hit node 0 (hot key)
        hot = rng.rand(m) < hot_frac
        dst = np.where(hot, 0, dst)
    return make_graph(
        session,
        {("P",): [{"_id": i, "v": int(rng.randint(0, 40))} for i in range(n)]},
        {"T": [(int(s), int(d), {"w": int(rng.randint(0, 3))})
               for s, d in zip(src, dst)]})


QUERIES = [
    "MATCH (a:P)-[r:T]->(b:P) WHERE a.v = 7 "
    "RETURN b.v AS v, count(*) AS c ORDER BY v",
    "MATCH (a:P {v: 3})-[r:T]->(b:P) RETURN r.w AS w, b.v AS v",
    "MATCH (a:P) OPTIONAL MATCH (a)-[r:T]->(b:P {v: 9}) "
    "RETURN a.v AS av, count(r) AS c ORDER BY av",
]


@pytest.fixture(scope="module")
def oracle_results():
    s = LocalCypherSession()
    g = _build(s)
    return [g.cypher(q).records.to_maps() for q in QUERIES]


def _run_config(cfg, oracle_results, expect_strategy):
    s = TPUCypherSession(config=cfg)
    g = _build(s)
    fired = 0
    for q, want in zip(QUERIES, oracle_results):
        res = g.cypher(q)
        got = res.records.to_maps()
        assert Bag(got) == want, (q, got[:5], want[:5])
        fired += res.metrics[expect_strategy]
        if res.metrics[expect_strategy]:
            assert res.metrics["ici_bytes"] > 0
    assert s.fallback_count == 0, s.backend.fallback_reasons
    assert fired > 0, f"{expect_strategy} never fired"


def test_radix_exchange_join_parity(oracle_results):
    _run_config(EngineConfig(mesh_shape=(8,), use_csr=False,
                             broadcast_join_threshold=0),
                oracle_results, "dist_joins")


def test_radix_salted_join_parity(oracle_results):
    _run_config(EngineConfig(mesh_shape=(8,), use_csr=False,
                             broadcast_join_threshold=0, join_salt=4),
                oracle_results, "dist_joins")


def test_broadcast_join_parity(oracle_results):
    _run_config(EngineConfig(mesh_shape=(8,), use_csr=False,
                             broadcast_join_threshold=1 << 20),
                oracle_results, "broadcast_joins")


def test_skewed_key_parity_with_salt():
    """A hot destination key (power-law guard): salted radix join must
    match the oracle exactly — build rows replicate into every sub-bucket,
    probe rows round-robin across them."""
    s0 = LocalCypherSession()
    g0 = _build(s0, hot_frac=0.4, seed=11)
    q = ("MATCH (a:P)-[r:T]->(b:P) WHERE b.v < 5 "
         "RETURN a.v AS av, b.v AS bv, r.w AS w")
    want = g0.cypher(q).records.to_maps()
    s = TPUCypherSession(config=EngineConfig(
        mesh_shape=(8,), use_csr=False, broadcast_join_threshold=0,
        join_salt=4))
    g = _build(s, hot_frac=0.4, seed=11)
    res = g.cypher(q)
    assert Bag(res.records.to_maps()) == want
    assert res.metrics["dist_joins"] > 0
    assert s.fallback_count == 0, s.backend.fallback_reasons


def test_radix_beats_broadcast_on_ici_bytes(oracle_results):
    """The point of the exchange: each row crosses ICI once, vs once per
    device for all_gather — the static accounting must show it."""
    q = QUERIES[1]
    bytes_by = {}
    for name, thresh in (("radix", 0), ("broadcast", 1 << 20)):
        s = TPUCypherSession(config=EngineConfig(
            mesh_shape=(8,), use_csr=False,
            broadcast_join_threshold=thresh))
        g = _build(s)
        res = g.cypher(q)
        bytes_by[name] = res.metrics["ici_bytes"]
    assert 0 < bytes_by["radix"] < bytes_by["broadcast"], bytes_by


def test_single_chip_unaffected():
    """No mesh → the dist-join path must stand down (returns None)."""
    s = TPUCypherSession()
    g = _build(s, n=100, m=300)
    res = g.cypher(QUERIES[0])
    assert res.metrics["dist_joins"] == 0
    assert res.metrics["broadcast_joins"] == 0


def test_auto_salt_on_skewed_keys():
    """Round-5: hot keys must be DETECTED (no manual join_salt) and salted
    surgically, with parity and the salted_joins metric recording it."""
    s0 = LocalCypherSession()
    g0 = _build(s0, hot_frac=0.5, seed=13)
    q = ("MATCH (a:P)-[r:T]->(b:P) WHERE b.v < 5 "
         "RETURN a.v AS av, b.v AS bv, r.w AS w")
    want = g0.cypher(q).records.to_maps()
    s = TPUCypherSession(config=EngineConfig(
        mesh_shape=(8,), use_csr=False, broadcast_join_threshold=0))
    g = _build(s, hot_frac=0.5, seed=13)
    res = g.cypher(q)
    assert Bag(res.records.to_maps()) == want
    assert res.metrics["dist_joins"] > 0
    assert res.metrics["salted_joins"] > 0, res.metrics
    assert s.fallback_count == 0, s.backend.fallback_reasons


def test_uniform_keys_do_not_salt():
    """Surgical means surgical: uniform keys must not pay the salt tax."""
    s = TPUCypherSession(config=EngineConfig(
        mesh_shape=(8,), use_csr=False, broadcast_join_threshold=0))
    g = _build(s)
    res = g.cypher(QUERIES[1])
    assert res.metrics["dist_joins"] > 0
    assert res.metrics["salted_joins"] == 0, res.metrics


def test_payload_bytes_bracketed_by_wire_estimate():
    """Round-5 VERDICT item 7: the device-measured live-row payload must
    be positive and bounded by the padded-buffer wire estimate."""
    s = TPUCypherSession(config=EngineConfig(
        mesh_shape=(8,), use_csr=False, broadcast_join_threshold=0))
    g = _build(s)
    res = g.cypher(QUERIES[1])
    assert res.metrics["dist_joins"] > 0
    assert 0 < res.metrics["ici_payload_bytes"] <= res.metrics["ici_bytes"], \
        res.metrics


def test_dist_join_on_2d_mesh():
    """Round-5 VERDICT item 8: the radix exchange must fire on a 2-D
    DCN x ICI mesh (tuple-axis collectives), with parity."""
    s0 = LocalCypherSession()
    g0 = _build(s0)
    q = QUERIES[1]
    want = g0.cypher(q).records.to_maps()
    s = TPUCypherSession(config=EngineConfig(
        mesh_shape=(2, 4), use_csr=False, broadcast_join_threshold=0))
    g = _build(s)
    res = g.cypher(q)
    assert Bag(res.records.to_maps()) == want
    assert res.metrics["dist_joins"] > 0, res.metrics
    assert s.fallback_count == 0, s.backend.fallback_reasons


def test_dist_join_carries_list_columns():
    """Round-5 VERDICT item 8: list columns (e.g. var-length rel lists)
    ride the exchange as matrix payloads instead of disabling it."""
    s0 = LocalCypherSession()
    g0 = _build(s0)
    q = ("MATCH (a:P {v: 3})-[rs:T*1..2]->(b:P) "
         "RETURN b.v AS v, size(rs) AS n")
    want = g0.cypher(q).records.to_maps()
    s = TPUCypherSession(config=EngineConfig(
        mesh_shape=(8,), use_csr=False, broadcast_join_threshold=0))
    g = _build(s)
    res = g.cypher(q)
    assert Bag(res.records.to_maps()) == want
    assert s.fallback_count == 0, s.backend.fallback_reasons

"""Documentation snippets are executable — every fenced ``python`` block
in docs/*.md runs top-to-bottom in a per-file namespace (the reference's
documentation module compiled its snippet sources the same way; ref:
documentation/ — reconstructed, mount empty; SURVEY.md §2)."""
from __future__ import annotations

import os
import re

import pytest

DOCS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "docs")

_FENCE = re.compile(r"```python\n(.*?)```", re.DOTALL)


def _doc_files():
    return sorted(f for f in os.listdir(DOCS) if f.endswith(".md"))


def test_docs_exist():
    assert _doc_files(), DOCS


@pytest.mark.parametrize("fname", _doc_files())
def test_doc_snippets_run(fname):
    text = open(os.path.join(DOCS, fname)).read()
    blocks = _FENCE.findall(text)
    assert blocks, f"{fname} has no python snippets"
    ns: dict = {}
    for i, block in enumerate(blocks):
        try:
            exec(compile(block, f"{fname}[snippet {i}]", "exec"), ns)
        except Exception as ex:  # pragma: no cover
            raise AssertionError(
                f"{fname} snippet {i} failed: {ex}\n---\n{block}") from ex

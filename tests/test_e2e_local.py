"""End-to-end acceptance: full pipeline on the local oracle backend.

The minimum E2E slice from SURVEY.md §7 step 5: the 2-hop friend-of-friend
MATCH on the SocialNetworkExample data (benchmark config 1) through
parse → IR → logical → relational → execution.
"""
import pytest

from caps_tpu.backends.local.session import LocalCypherSession
from caps_tpu.okapi.values import CypherNode

from tests.util import bag, social_graph


@pytest.fixture()
def session():
    return LocalCypherSession.local()


@pytest.fixture()
def graph(session):
    return social_graph(session)


def run(graph, query, **params):
    return graph.cypher(query, params).records.to_maps()


def test_node_scan(graph):
    rows = run(graph, "MATCH (a:Person) RETURN a.name")
    assert bag(rows) == [{"a.name": "Alice"}, {"a.name": "Bob"},
                         {"a.name": "Carol"}]


def test_single_hop(graph):
    rows = run(graph, "MATCH (a:Person)-[:KNOWS]->(b:Person) "
                      "RETURN a.name AS a, b.name AS b")
    assert bag(rows) == [{"a": "Alice", "b": "Bob"}, {"a": "Bob", "b": "Carol"}]


def test_two_hop_friend_of_friend(graph):
    # benchmark config 1
    rows = run(graph,
               "MATCH (a:Person)-[:KNOWS]->(b)-[:KNOWS]->(c) "
               "WHERE a.name = 'Alice' RETURN c.name AS foaf")
    assert rows == [{"foaf": "Carol"}]


def test_filter_on_property(graph):
    rows = run(graph, "MATCH (a:Person) WHERE a.age > 40 RETURN a.name AS n")
    assert bag(rows) == [{"n": "Bob"}, {"n": "Carol"}]


def test_return_entity_materializes_node(graph):
    rows = run(graph, "MATCH (a:Person) WHERE a.name = 'Alice' RETURN a")
    assert rows == [{"a": CypherNode(1, ("Person",),
                                     {"name": "Alice", "age": 23})}]
    node = rows[0]["a"]
    assert node.labels == ("Person",)
    assert node.properties == {"name": "Alice", "age": 23}


def test_rel_property_filter(graph):
    rows = run(graph, "MATCH (a)-[k:KNOWS]->(b) WHERE k.since >= 2017 "
                      "RETURN a.name AS a, k.since AS since")
    assert rows == [{"a": "Alice", "since": 2017}]


def test_undirected_match(graph):
    rows = run(graph, "MATCH (a)-[:KNOWS]-(b) WHERE a.name = 'Bob' "
                      "RETURN b.name AS n")
    assert bag(rows) == [{"n": "Alice"}, {"n": "Carol"}]


def test_incoming_match(graph):
    rows = run(graph, "MATCH (a)<-[:KNOWS]-(b) WHERE a.name = 'Bob' "
                      "RETURN b.name AS n")
    assert rows == [{"n": "Alice"}]


def test_aggregation(graph):
    rows = run(graph, "MATCH (a:Person) RETURN count(*) AS c, sum(a.age) AS s")
    assert rows == [{"c": 3, "s": 23 + 42 + 1984}]


def test_grouped_aggregation(graph):
    rows = run(graph, "MATCH (a:Person)-[:KNOWS]->(b) "
                      "RETURN a.name AS n, count(*) AS c")
    assert bag(rows) == [{"n": "Alice", "c": 1}, {"n": "Bob", "c": 1}]


def test_order_by_limit(graph):
    rows = run(graph, "MATCH (a:Person) RETURN a.name AS n ORDER BY a.age DESC LIMIT 2")
    assert rows == [{"n": "Carol"}, {"n": "Bob"}]


def test_with_pipeline(graph):
    rows = run(graph,
               "MATCH (a:Person) WITH a.age AS age WHERE age < 100 "
               "RETURN age ORDER BY age")
    assert rows == [{"age": 23}, {"age": 42}]


def test_optional_match(graph):
    rows = run(graph,
               "MATCH (a:Person) OPTIONAL MATCH (a)-[:KNOWS]->(b) "
               "RETURN a.name AS a, b.name AS b")
    assert bag(rows) == [{"a": "Alice", "b": "Bob"},
                         {"a": "Bob", "b": "Carol"},
                         {"a": "Carol", "b": None}]


def test_unwind(graph):
    rows = run(graph, "UNWIND [1, 2, 3] AS x RETURN x * 10 AS y")
    assert rows == [{"y": 10}, {"y": 20}, {"y": 30}]


def test_union(graph):
    rows = run(graph, "MATCH (a:Person) WHERE a.age < 30 RETURN a.name AS n "
                      "UNION ALL MATCH (a:Person) WHERE a.age > 1000 "
                      "RETURN a.name AS n")
    assert bag(rows) == [{"n": "Alice"}, {"n": "Carol"}]


def test_parameters(graph):
    rows = run(graph, "MATCH (a:Person) WHERE a.name = $who RETURN a.age AS age",
               who="Bob")
    assert rows == [{"age": 42}]


def test_var_length_expand(graph):
    rows = run(graph, "MATCH (a)-[rs:KNOWS*1..2]->(b) WHERE a.name = 'Alice' "
                      "RETURN b.name AS n, size(rs) AS hops")
    assert bag(rows) == [{"n": "Bob", "hops": 1}, {"n": "Carol", "hops": 2}]


def test_var_length_materializes_rels(graph):
    rows = run(graph, "MATCH (a)-[rs:KNOWS*2..2]->(c) RETURN rs")
    assert len(rows) == 1
    rels = rows[0]["rs"]
    assert [r.rel_type for r in rels] == ["KNOWS", "KNOWS"]
    assert rels[0].start == 1 and rels[1].end == 3


def test_distinct(graph):
    rows = run(graph, "MATCH (a:Person)-[:KNOWS]-(b) RETURN DISTINCT a.name AS n")
    assert bag(rows) == [{"n": "Alice"}, {"n": "Bob"}, {"n": "Carol"}]


def test_cartesian_product(graph):
    rows = run(graph, "MATCH (a:Person), (b:Person) RETURN count(*) AS c")
    assert rows == [{"c": 9}]


def test_expand_into_cycle(graph):
    rows = run(graph, "MATCH (a)-[:KNOWS]->(b)-[:KNOWS]->(a) RETURN a.name AS n")
    assert rows == []


def test_functions_in_projection(graph):
    rows = run(graph, "MATCH (a:Person) WHERE a.name = 'Alice' "
                      "RETURN toUpper(a.name) AS up, id(a) AS i, labels(a) AS l")
    assert rows == [{"up": "ALICE", "i": 1, "l": ["Person"]}]


def test_explain(graph):
    result = graph.cypher("MATCH (a:Person) RETURN a.name AS n")
    text = result.explain()
    assert "IR" in text and "LOGICAL" in text and "RELATIONAL" in text
    assert "NodeScan" in text

"""Examples must stay runnable — the analog of the reference's
documentation module compiling its snippet sources (SURVEY.md §4.5).
Each example's main() returns its result rows so we can assert content,
not just exit status."""
import os
import sys

import pytest

EXAMPLES_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "examples")
sys.path.insert(0, EXAMPLES_DIR)


@pytest.mark.parametrize("backend", ["local", "tpu"])
def test_social_network(backend):
    import social_network
    rows, foaf = social_network.main(backend)
    assert rows == [{"a": "Alice", "b": "Bob"}, {"a": "Alice", "b": "Carol"}]
    assert foaf == [{"foaf": "Carol"}]


def test_columnar_input():
    import columnar_input
    rows = columnar_input.main()
    assert rows == [{"customer": "Nia", "total": 298.0},
                    {"customer": "Omar", "total": 19.0}]


def test_multiple_graph():
    import multiple_graph
    people, edges = multiple_graph.main()
    assert [r["n"] for r in people] == ["Alice", "Bob"]
    assert edges == [{"x": "Alice", "y": "Bob"}]


def test_recommendation():
    import recommendation
    rows = recommendation.main()
    assert rows == [{"recommend": "monitor", "score": 2},
                    {"recommend": "headset", "score": 1}]


def test_fs_datasource():
    import fs_datasource
    rows = fs_datasource.main()
    assert rows == [{"n": "Kyoto"}]


@pytest.mark.parametrize("backend", ["local", "tpu"])
def test_parameterized_reads(backend):
    import parameterized_reads
    out = parameterized_reads.main(backend)
    # (min_age, row count, size_syncs) per rotation of the prepared query
    assert [(m, n) for m, n, _ in out] == [
        (30, 4), (40, 2), (25, 5), (50, 1), (30, 4)]


@pytest.mark.parametrize("backend", ["local", "tpu"])
def test_serve_concurrent(backend):
    import serve_concurrent
    ok, batch_max = serve_concurrent.main(backend)
    assert ok == serve_concurrent.N_CLIENTS * serve_concurrent.PER_CLIENT
    assert batch_max > 1  # the micro-batcher demonstrably coalesced


@pytest.mark.parametrize("backend", ["local", "tpu"])
def test_profile_query(backend):
    import profile_query
    rows, explained, profiled, n_events = profile_query.main(backend)
    assert rows == [{"person": "Ana", "knows": "Bo"},
                    {"person": "Ana", "knows": "Cleo"},
                    {"person": "Bo", "knows": "Cleo"}]
    assert explained.records is None
    assert profiled.profile["rows"] == len(rows)
    assert "rows=" in profiled.plans["profile"]
    assert n_events > 0

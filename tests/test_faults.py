"""Failure containment (ISSUE 5): the fault-injection harness, the
transient-retry/backoff path, plan quarantine + the degraded execution
ladder, and the per-plan-family circuit breaker.

Acceptance contract under test: under ``failing_operator(...,
n_times=1)`` transient faults injected into ~20% of requests at 8
concurrent clients, the server stays available — zero worker-thread
deaths, every request resolves to a result or a typed ``ServeError``,
retried results are bag-equal to a fault-free sequential run — and a
permanently failing query family trips its breaker within K attempts
while other families keep serving.

All retry/backoff/breaker TIMING tests run against a fake
``caps_tpu.obs.clock`` whose ``sleep`` advances ``now`` instantly: the
backoff sequence, the deadline-budget interaction, and the breaker's
open → half-open → closed transitions are asserted exactly, with zero
real sleeping.
"""
from __future__ import annotations

import threading

import pytest

import caps_tpu
from caps_tpu.obs import clock
from caps_tpu.obs.metrics import MetricsRegistry
from caps_tpu.okapi.config import EngineConfig
from caps_tpu.serve import (FATAL, POISONED_PLAN, TRANSIENT, Cancelled,
                            CircuitOpen, DeadlineExceeded, Overloaded,
                            QueryFailed, QueryServer, RetryPolicy,
                            ServeError, ServerConfig, WaitTimeout, classify)
from caps_tpu.serve.breaker import (ALLOW, CLOSED, HALF_OPEN, OPEN, REJECT,
                                    TRIAL, CircuitBreaker)
from caps_tpu.testing.factory import create_graph
from caps_tpu.testing.faults import (FaultPlan, corrupt_shard, device_oom,
                                     failing_operator, flaky_ingest,
                                     make_oom, slow_operator,
                                     xla_runtime_error_class)

SOCIAL = """
    CREATE (a:Person {name: 'Alice', age: 33}),
           (b:Person {name: 'Bob', age: 44}),
           (c:Person {name: 'Carol', age: 27}),
           (d:Person {name: 'Dana', age: 51}),
           (a)-[:KNOWS {since: 2011}]->(b),
           (b)-[:KNOWS {since: 2015}]->(c),
           (a)-[:KNOWS {since: 2019}]->(c),
           (c)-[:KNOWS {since: 2021}]->(d)
"""

#: three distinct plan families (ORDER BY makes family 0 the only one
#: that touches OrderByOp — fault it to break ONE family)
Q_ORDER = ("MATCH (p:Person) WHERE p.age > $min "
           "RETURN p.name AS n ORDER BY n")
Q_EDGE = ("MATCH (a:Person)-[:KNOWS]->(b) WHERE a.age > $min "
          "RETURN a.name AS a, b.name AS b")
Q_COUNT = ("MATCH (a:Person)-[k:KNOWS]->(b) WHERE k.since >= $y "
           "RETURN count(*) AS c")


def _session(backend="local", **cfg):
    return caps_tpu.local_session(backend=backend,
                                  config=EngineConfig(**cfg) if cfg else None)


def _graph(session):
    return create_graph(session, SOCIAL)


def _bag(rows):
    return sorted(sorted(r.items()) for r in rows)


class FakeClock:
    """Monotonic fake for caps_tpu.obs.clock: ``sleep`` advances ``now``
    instantly and records what was slept (thread-safe — server workers
    read it concurrently).  ``wait`` — the interruptible backoff
    primitive — honors an already-fired event instantly (no time passes,
    nothing recorded) and otherwise advances like a sleep."""

    def __init__(self, t0: float = 1_000.0):
        self._t = t0
        self._lock = threading.Lock()
        self.sleeps: list = []

    def now(self) -> float:
        with self._lock:
            return self._t

    def sleep(self, s: float) -> None:
        with self._lock:
            self._t += s
            self.sleeps.append(s)

    def wait(self, event, timeout: float) -> bool:
        if event.is_set():
            return True
        self.sleep(timeout)
        return event.is_set()

    def advance(self, s: float) -> None:
        with self._lock:
            self._t += s


@pytest.fixture()
def fake_clock(monkeypatch):
    fc = FakeClock()
    monkeypatch.setattr(clock, "now", fc.now)
    monkeypatch.setattr(clock, "sleep", fc.sleep)
    monkeypatch.setattr(clock, "wait", fc.wait)
    return fc


# -- taxonomy (serve/failure.py) -------------------------------------------

def test_classify_taxonomy():
    from caps_tpu.frontend.lexer import CypherSyntaxError
    assert classify(make_oom()) == TRANSIENT
    assert classify(xla_runtime_error_class()("UNAVAILABLE: socket closed")
                    ) == TRANSIENT
    assert classify(ConnectionError("tunnel reset")) == TRANSIENT
    assert classify(DeadlineExceeded("execute", 0.1, 0.2)) == FATAL
    assert classify(Cancelled()) == FATAL
    assert classify(Overloaded("full")) == FATAL
    assert classify(CypherSyntaxError("bad", "q", 0)) == FATAL
    assert classify(KeyError("missing parameter $x")) == FATAL
    # unexplained execution errors default to poisoned-plan suspicion
    assert classify(RuntimeError("boom")) == POISONED_PLAN
    assert classify(IndexError("gather out of range")) == POISONED_PLAN
    # explicit marker overrides everything
    marked = RuntimeError("flaky thing")
    marked.caps_transient = True
    assert classify(marked) == TRANSIENT


def test_wait_timeout_is_serve_error_and_timeout():
    session = _session()
    graph = _graph(session)
    server = QueryServer(session, graph=graph, start=False)
    h = server.submit(Q_COUNT, {"y": 2015})
    with pytest.raises(TimeoutError):      # backward compatible
        h.result(timeout=0.01)
    with pytest.raises(ServeError):        # one base type catches all
        h.result(timeout=0.01)
    with pytest.raises(WaitTimeout):
        h.exception(timeout=0.01)
    server.shutdown(drain=False)


# -- the harness (testing/faults.py) ---------------------------------------

def test_failing_operator_transient_then_heals():
    session = _session()
    graph = _graph(session)
    with failing_operator("Scan", n_times=1) as budget:
        with pytest.raises(Exception) as ex:
            graph.cypher(Q_COUNT, {"y": 2015})
        assert "RESOURCE_EXHAUSTED" in str(ex.value)
        # healed: the budget is spent, the same query now succeeds
        assert graph.cypher(Q_COUNT, {"y": 2015}).records.to_maps() \
            == [{"c": 3}]
    assert budget.injected == 1


def test_failing_operator_raises_fresh_exception_objects():
    session = _session()
    graph = _graph(session)
    template = RuntimeError("shared template")
    caught = []
    with failing_operator("Scan", exc=template, n_times=2):
        for _ in range(2):
            try:
                graph.cypher(Q_COUNT, {"y": 2015})
            except RuntimeError as ex:
                caught.append(ex)
    assert len(caught) == 2
    assert caught[0] is not caught[1]          # fresh object per injection
    assert caught[0] is not template and caught[1] is not template


def test_fault_plan_composes_and_nests():
    from caps_tpu.relational import ops as R
    orig_scan = R.ScanOp._compute
    orig_filter = R.FilterOp._compute
    session = _session()
    graph = _graph(session)
    with FaultPlan(slow_operator("Filter", 0.0),
                   failing_operator("Scan", n_times=1)):
        with failing_operator("Scan", n_times=1):  # nested, same class
            with pytest.raises(Exception):
                graph.cypher(Q_COUNT, {"y": 2015})
            with pytest.raises(Exception):  # second hook's budget
                graph.cypher(Q_COUNT, {"y": 2015})
        assert graph.cypher(Q_COUNT, {"y": 2015}).records.to_maps() \
            == [{"c": 3}]
    # everything restored, verbatim
    assert R.ScanOp._compute is orig_scan
    assert R.FilterOp._compute is orig_filter


def test_operator_hooks_thread_safe_install_remove():
    from caps_tpu.relational import ops as R
    orig = R.FilterOp._compute
    session = _session()
    graph = _graph(session)
    errors: list = []

    def churn():
        try:
            for _ in range(30):
                with slow_operator("Filter", 0.0):
                    graph.cypher(Q_COUNT, {"y": 2015})
        except Exception as ex:  # pragma: no cover
            errors.append(ex)

    threads = [threading.Thread(target=churn) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors
    assert R.FilterOp._compute is orig


def test_injection_counters_in_registry():
    from caps_tpu.obs.metrics import global_registry
    session = _session()
    graph = _graph(session)
    before = global_registry().counter(
        "faults.injected.failing_operator").value
    with failing_operator("Scan", n_times=2):
        for _ in range(3):  # third execution is past the budget
            try:
                graph.cypher(Q_COUNT, {"y": 2015})
            except Exception:
                pass
    after = global_registry().counter(
        "faults.injected.failing_operator").value
    assert after - before == 2


def test_device_oom_shape_and_phases():
    with pytest.raises(ValueError):
        with device_oom(phase="materialize"):
            pass
    session = _session()
    graph = _graph(session)
    with device_oom(phase="execute", op_name="Scan") as budget:
        with pytest.raises(xla_runtime_error_class()) as ex:
            graph.cypher(Q_COUNT, {"y": 2015})
    assert "RESOURCE_EXHAUSTED" in str(ex.value)
    assert classify(ex.value) == TRANSIENT
    assert budget.injected == 1


def test_flaky_ingest_rolls_back_string_pool():
    from caps_tpu.backends.tpu.pool import StringPool
    from caps_tpu.backends.tpu.session import TPUCypherSession
    session = TPUCypherSession()
    # pin the pure-Python pool: rollback is a documented no-op on the
    # append-only native pool (best-effort there)
    session.backend.pool = StringPool()
    pool_before = len(session.backend.pool)
    with flaky_ingest(session, n_times=1):
        with pytest.raises(Exception) as ex:
            create_graph(session, SOCIAL)
        assert "RESOURCE_EXHAUSTED" in str(ex.value)
        # containment: the FAILED ingest left no pool growth behind
        # (pool size is the fused executor's replayability fence)
        assert len(session.backend.pool) == pool_before
        # budget spent: the retried ingest succeeds
        graph = create_graph(session, SOCIAL)
    assert graph.cypher(Q_COUNT, {"y": 2015}).records.to_maps() == [{"c": 3}]


def test_from_columns_host_fallback_rolls_back_pool():
    """A device-encoding fallback to a host table must also roll the
    string pool back: the local table stores raw values, so codes
    interned for the discarded device columns are pure leaked growth
    (they would move the fused executor's replayability fence)."""
    from caps_tpu.backends.tpu.pool import StringPool
    from caps_tpu.backends.tpu.session import TPUCypherSession
    from caps_tpu.okapi.types import CTInteger, CTList, CTString
    session = TPUCypherSession()
    session.backend.pool = StringPool()  # rollback-capable (see above)
    factory = session.table_factory
    before = len(session.backend.pool)
    t = factory.from_columns(
        # "name" interns strings FIRST, then the null-in-list column is
        # rejected by the device encoding -> host-table fallback
        {"name": ["zz_fallback_a", "zz_fallback_b"],
         "xs": [[1, None], [2]]},
        {"name": CTString, "xs": CTList(CTInteger)})
    assert t.is_local
    assert len(session.backend.pool) == before


def test_string_pool_mark_rollback_unit():
    from caps_tpu.backends.tpu.pool import StringPool
    pool = StringPool()
    a = pool.encode("alpha")
    mark = pool.mark()
    pool.encode("beta")
    pool.encode("gamma")
    assert len(pool) == 3
    assert pool.rollback(mark) is True
    assert len(pool) == 1
    assert pool.decode(a) == "alpha"
    # rolled-back strings re-intern at fresh (reused) codes, cleanly
    assert pool.encode("beta") == 1
    assert pool.rollback(pool.mark()) is True  # no-op at the mark


def test_corrupt_shard_raises_instead_of_vacuous_pass():
    from caps_tpu.backends.tpu.session import TPUCypherSession
    session = TPUCypherSession(config=EngineConfig(mesh_shape=(8,)))
    # nothing ingested inside the block -> nothing corrupted -> loud
    with pytest.raises(RuntimeError, match="vacuous"):
        with corrupt_shard(session):
            pass
    # a column the injector cannot damage (bool dtype) warns AND the
    # block still refuses to pass vacuously
    import jax.numpy as jnp
    from caps_tpu.backends.tpu.column import Column
    from caps_tpu.okapi.types import CTBoolean
    col = Column("bool", jnp.ones(256, bool), jnp.ones(256, bool), CTBoolean)
    with pytest.raises(RuntimeError, match="vacuous"):
        with corrupt_shard(session):
            with pytest.warns(UserWarning, match="UNDAMAGED"):
                session.backend.place_column(col)


# -- retry / backoff (serve/retry.py) --------------------------------------

def test_backoff_sequence_deterministic_and_capped():
    policy = RetryPolicy(max_attempts=6, backoff_base_s=0.1,
                         backoff_multiplier=2.0, backoff_max_s=0.5,
                         jitter=0.1)
    seq = [policy.backoff_s(a, token=7) for a in range(1, 6)]
    # deterministic: same (attempt, token) -> identical backoff
    assert seq == [policy.backoff_s(a, token=7) for a in range(1, 6)]
    # a different token jitters differently
    assert seq != [policy.backoff_s(a, token=8) for a in range(1, 6)]
    # exponential nominal values 0.1, 0.2, 0.4, then capped at 0.5,
    # each within the ±10% jitter band
    for got, nominal in zip(seq, [0.1, 0.2, 0.4, 0.5, 0.5]):
        assert abs(got - nominal) <= 0.1 * nominal + 1e-12
    # no-jitter policy is exact
    exact = RetryPolicy(backoff_base_s=0.1, backoff_max_s=10.0, jitter=0.0)
    assert [exact.backoff_s(a) for a in (1, 2, 3)] == [0.1, 0.2, 0.4]


def test_server_retries_transient_with_fake_clock_backoff(fake_clock):
    session = _session()
    graph = _graph(session)
    policy = RetryPolicy(max_attempts=4, backoff_base_s=0.25, jitter=0.0)
    with QueryServer(session, graph=graph,
                     config=ServerConfig(workers=1, retry=policy)) as server:
        with failing_operator("Filter", n_times=2):
            h = server.submit(Q_ORDER, {"min": 30})
            rows = h.rows(timeout=30)
    assert [r["n"] for r in rows] == ["Alice", "Bob", "Dana"]
    attempts = h.info["attempts"]
    assert [a.get("ok", False) for a in attempts] == [False, False, True]
    assert attempts[0]["classified"] == TRANSIENT
    assert attempts[0]["op"] == "Filter"
    # the exact exponential backoff sequence, slept on the fake clock
    assert attempts[0]["backoff_s"] == 0.25
    assert attempts[1]["backoff_s"] == 0.5
    assert fake_clock.sleeps == [0.25, 0.5]
    assert session.metrics_snapshot()["serve.retries"] == 2


def test_retry_never_fires_when_budget_below_backoff(fake_clock):
    session = _session()
    graph = _graph(session)
    policy = RetryPolicy(max_attempts=5, backoff_base_s=10.0,
                         backoff_max_s=10.0, jitter=0.0)
    with QueryServer(session, graph=graph,
                     config=ServerConfig(workers=1, retry=policy)) as server:
        with failing_operator("Filter", n_times=1):
            h = server.submit(Q_ORDER, {"min": 30}, deadline_s=5.0)
            ex = h.exception(timeout=30)
    # remaining budget (~5s) < next backoff (10s): the give-up error
    # fires IMMEDIATELY — no backoff sleep ever happened
    assert isinstance(ex, QueryFailed)
    assert ex.retry_after_s == 10.0
    assert fake_clock.sleeps == []
    assert len(ex.attempts) == 1 and ex.attempts[0]["classified"] \
        == TRANSIENT
    assert session.metrics_snapshot()["serve.retries"] == 0


def test_retries_exhausted_gives_typed_query_failed(fake_clock):
    session = _session()
    graph = _graph(session)
    policy = RetryPolicy(max_attempts=3, backoff_base_s=0.1, jitter=0.0)
    with QueryServer(session, graph=graph,
                     config=ServerConfig(workers=1, retry=policy)) as server:
        with failing_operator("Filter", n_times=None):  # permanent
            h = server.submit(Q_ORDER, {"min": 30})
            ex = h.exception(timeout=30)
    assert isinstance(ex, QueryFailed)
    assert len(ex.attempts) == 3            # max_attempts executions
    assert all(a["classified"] == TRANSIENT for a in ex.attempts)
    assert ex.retry_after_s > 0             # Overloaded-style hint
    assert fake_clock.sleeps == [0.1, 0.2]  # backoffs BETWEEN attempts


def test_retry_emits_tracer_events():
    session = _session(trace=True)
    graph = _graph(session)
    policy = RetryPolicy(backoff_base_s=0.0, jitter=0.0)
    with QueryServer(session, graph=graph,
                     config=ServerConfig(workers=1, retry=policy)) as server:
        with failing_operator("Filter", n_times=1):
            server.submit(Q_ORDER, {"min": 30}).rows(timeout=30)

    def walk(spans):
        for sp in spans:
            yield sp
            yield from walk(sp.children)

    spans = list(walk(session.tracer.spans))
    retry_events = [sp for sp in spans if sp.name == "retry.attempt"]
    assert retry_events and retry_events[0].attrs["error"] \
        == "XlaRuntimeError"
    assert any(sp.name == "op.error" for sp in spans)


# -- quarantine + degraded ladder ------------------------------------------

def test_poisoned_plan_quarantines_and_recovers_degraded():
    session = _session()
    graph = _graph(session)
    graph.cypher(Q_ORDER, {"min": 30})  # warm: park a cached plan
    key = session._plan_cache_key(graph, Q_ORDER, {"min": 30})
    assert session.plan_cache.lookup(key, {"min": 30}) is not None
    with QueryServer(session, graph=graph,
                     config=ServerConfig(workers=1)) as server:
        # a non-transient, non-fatal error: suspected poisoned plan.
        # n_times=1 — the degraded replan re-execution succeeds.
        with failing_operator("OrderBy", exc=RuntimeError("poison"),
                              n_times=1):
            h = server.submit(Q_ORDER, {"min": 30})
            rows = h.rows(timeout=30)
    assert [r["n"] for r in rows] == ["Alice", "Bob", "Dana"]
    attempts = h.info["attempts"]
    assert attempts[0]["classified"] == POISONED_PLAN
    assert attempts[1] == {"mode": "replan", "ok": True, "device": 0}
    # the suspected entry was evicted (quarantined), not served again
    assert session.plan_cache.quarantined >= 1
    snap = session.metrics_snapshot()
    assert snap["serve.quarantined"] >= 1
    assert snap["serve.degraded_exec"] >= 1
    assert snap["plan_cache.quarantined"] >= 1


def test_degraded_ladder_exhausts_to_query_failed():
    session = _session()
    graph = _graph(session)
    with QueryServer(session, graph=graph,
                     config=ServerConfig(workers=1)) as server:
        with failing_operator("OrderBy", exc=RuntimeError("always"),
                              n_times=None):
            h = server.submit(Q_ORDER, {"min": 30})
            ex = h.exception(timeout=30)
    assert isinstance(ex, QueryFailed)
    # the full ladder ran: fused -> replan -> unfused, each failed
    assert [a["mode"] for a in ex.attempts] == ["fused", "replan",
                                                "unfused"]
    assert "ladder exhausted" in str(ex)


def test_session_cypher_degraded_bypasses_plan_cache():
    session = _session()
    graph = _graph(session)
    graph.cypher(Q_ORDER, {"min": 30})  # park an entry
    hits_before = session.plan_cache.hits
    r = session.cypher_degraded(graph, Q_ORDER, {"min": 30})
    assert [row["n"] for row in r.records.to_maps()] == ["Alice", "Bob",
                                                         "Dana"]
    # no lookup, no store: the cache was not touched in either direction
    assert session.plan_cache.hits == hits_before
    assert r.metrics["plan_cache"] == "off"


def test_fused_memo_forget_on_tpu_backend():
    from caps_tpu.backends.tpu.session import TPUCypherSession
    session = TPUCypherSession()
    graph = create_graph(session, SOCIAL)
    graph.cypher(Q_COUNT, {"y": 2015})
    graph.cypher(Q_COUNT, {"y": 2015})
    assert session.fused.replays >= 1
    dropped = session.fused.forget(graph, Q_COUNT)
    assert dropped >= 1
    recordings = session.fused.recordings
    graph.cypher(Q_COUNT, {"y": 2015})  # re-records from scratch
    assert session.fused.recordings == recordings + 1


def test_fused_replay_keeps_memo_on_transient_device_error():
    from caps_tpu.backends.tpu.session import TPUCypherSession
    session = TPUCypherSession()
    graph = create_graph(session, SOCIAL)
    graph.cypher(Q_COUNT, {"y": 2015})  # record
    graph.cypher(Q_COUNT, {"y": 2015})  # replay ok
    recordings = session.fused.recordings
    mismatches = session.fused.mismatches
    with failing_operator("Scan", n_times=1):  # transient OOM in replay
        with pytest.raises(Exception):
            graph.cypher(Q_COUNT, {"y": 2015})
    # the sound recording was NOT dropped or counted as divergence...
    assert session.fused.mismatches == mismatches
    assert session.fused.recordings == recordings
    replays = session.fused.replays
    # ...so the healed retry replays sync-free again
    assert graph.cypher(Q_COUNT, {"y": 2015}).records.to_maps() \
        == [{"c": 3}]
    assert session.fused.replays == replays + 1


# -- circuit breaker -------------------------------------------------------

def test_breaker_transitions_open_half_open_closed(fake_clock):
    reg = MetricsRegistry()
    br = CircuitBreaker(reg, failure_threshold=2, cooldown_s=10.0)
    key = ("family",)
    assert br.admit(key) == (ALLOW, 0.0)
    assert br.record_failure(key, RuntimeError("a")) is False
    assert br.state(key) == CLOSED
    assert br.record_failure(key, RuntimeError("b")) is True  # trips
    assert br.state(key) == OPEN
    verdict, retry_after = br.admit(key)
    assert verdict == REJECT and 0 < retry_after <= 10.0
    fake_clock.advance(10.0)
    assert br.admit(key) == (TRIAL, 0.0)      # half-open probe
    assert br.state(key) == HALF_OPEN
    assert br.admit(key)[0] == REJECT         # one probe at a time
    br.record_success(key)                    # probe succeeded
    assert br.state(key) == CLOSED
    assert br.admit(key) == (ALLOW, 0.0)
    # failed probe path: straight back to open with a fresh cooldown
    br.record_failure(key, RuntimeError("c"))
    br.record_failure(key, RuntimeError("d"))
    fake_clock.advance(10.0)
    assert br.admit(key) == (TRIAL, 0.0)
    assert br.record_failure(key, RuntimeError("e")) is True
    assert br.state(key) == OPEN
    assert br.admit(key)[0] == REJECT
    assert reg.counter("serve.breaker.opened").value == 3
    assert reg.snapshot()["serve.breaker.open"] == 1


def test_breaker_trips_family_and_isolates_others(fake_clock):
    session = _session()
    graph = _graph(session)
    policy = RetryPolicy(max_attempts=2, backoff_base_s=0.01, jitter=0.0)
    config = ServerConfig(workers=1, retry=policy, breaker_threshold=2,
                          breaker_cooldown_s=30.0)
    with QueryServer(session, graph=graph, config=config) as server:
        with failing_operator("OrderBy", exc=RuntimeError("fam-A dead"),
                              n_times=None):
            # K=2 request-level failures trip family A's breaker
            for _ in range(2):
                ex = server.submit(Q_ORDER, {"min": 30}).exception(
                    timeout=30)
                assert isinstance(ex, QueryFailed)
            assert server.health() == "degraded"
            # family A now fast-fails with the remaining cooldown...
            ex = server.submit(Q_ORDER, {"min": 30}).exception(timeout=30)
            assert isinstance(ex, CircuitOpen)
            assert isinstance(ex, ServeError)
            assert 0 < ex.retry_after_s <= 30.0
            # ...while families B and C keep serving normally
            assert server.run(Q_COUNT, {"y": 2015}).to_maps() == [{"c": 3}]
            assert _bag(server.submit(Q_EDGE, {"min": 40}).rows(
                timeout=30)) == _bag([{"a": "Bob", "b": "Carol"}])
        # fault lifted + cooldown elapsed: the half-open trial heals it
        fake_clock.advance(30.0)
        rows = server.submit(Q_ORDER, {"min": 30}).rows(timeout=30)
        assert [r["n"] for r in rows] == ["Alice", "Bob", "Dana"]
        assert server.health() == "healthy"
        stats = server.stats()
        assert stats["breakers"]["counts"][OPEN] == 0
        assert stats["breaker.opened"] == 1
        assert stats["breaker.closed"] == 1
        assert stats["breaker.fast_fail"] >= 1


def test_half_open_trial_is_single_probe(fake_clock):
    """Exactly ONE probe executes when a batch arrives at a half-open
    breaker; its success closes the breaker and the siblings serve as a
    normal batch."""
    session = _session()
    graph = _graph(session)
    server = QueryServer(session, graph=graph, start=False,
                         config=ServerConfig(workers=1, max_batch=8,
                                             breaker_threshold=1,
                                             breaker_cooldown_s=10.0))
    # trip the family open (threshold 1, workers never started — the
    # test thread drives the worker path directly, deterministically)
    with failing_operator("OrderBy", exc=RuntimeError("poison"),
                          n_times=None):
        bad = server.submit(Q_ORDER, {"min": 30})
        server._execute_batch(server.batcher.next_batch(timeout=0),
                              server.devices.replicas[0])
        assert isinstance(bad.exception(), QueryFailed)
    assert server.health() == "degraded"
    # fault lifted; three same-family requests queue during cooldown
    handles = [server.submit(Q_ORDER, {"min": m}) for m in (30, 40, 20)]
    fake_clock.advance(10.0)
    server._execute_batch(server.batcher.next_batch(timeout=0),
                              server.devices.replicas[0])
    # one probe (batch of 1), then the siblings as one normal batch
    assert handles[0].info["batch_size"] == 1
    assert [h.info["batch_size"] for h in handles[1:]] == [2, 2]
    assert [r["n"] for r in handles[0].rows()] == ["Alice", "Bob", "Dana"]
    assert [r["n"] for r in handles[1].rows()] == ["Bob", "Dana"]
    assert len(handles[2].rows()) == 4
    assert server.health() == "healthy"
    server.shutdown(drain=False)


def test_failed_half_open_probe_fast_fails_siblings(fake_clock):
    session = _session()
    graph = _graph(session)
    server = QueryServer(session, graph=graph, start=False,
                         config=ServerConfig(workers=1, max_batch=8,
                                             breaker_threshold=1,
                                             breaker_cooldown_s=10.0))
    with failing_operator("OrderBy", exc=RuntimeError("poison"),
                          n_times=None):
        bad = server.submit(Q_ORDER, {"min": 30})
        server._execute_batch(server.batcher.next_batch(timeout=0),
                              server.devices.replicas[0])
        assert isinstance(bad.exception(), QueryFailed)
        handles = [server.submit(Q_ORDER, {"min": m}) for m in (30, 40)]
        fake_clock.advance(10.0)
        server._execute_batch(server.batcher.next_batch(timeout=0),
                              server.devices.replicas[0])
        # the probe failed again: it carries the real error, the sibling
        # fast-fails typed without touching the device
        assert isinstance(handles[0].exception(), QueryFailed)
        assert isinstance(handles[1].exception(), CircuitOpen)
    assert server.health() == "degraded"
    server.shutdown(drain=False)


def test_ops_errors_counted_once_per_failure():
    """A leaf-operator failure unwinds through every ancestor's lazy
    child evaluation — the telemetry must still fire exactly once."""
    session = _session()
    graph = _graph(session)
    counter = session.metrics_registry.counter("ops.errors")
    before = counter.value
    with failing_operator("Scan", exc=RuntimeError("leaf"), n_times=1):
        with pytest.raises(RuntimeError):
            graph.cypher(Q_ORDER, {"min": 30})  # Scan under Filter/OrderBy
    assert counter.value - before == 1


# -- batch member isolation (satellite regression) -------------------------

def test_batch_member_retry_isolated_from_siblings():
    session = _session()
    graph = _graph(session)
    graph.cypher(Q_ORDER, {"min": 20})  # warm the family's plan
    server = QueryServer(session, graph=graph, start=False,
                         config=ServerConfig(
                             workers=1, max_batch=8,
                             retry=RetryPolicy(backoff_base_s=0.0,
                                               jitter=0.0)))
    handles = [server.submit(Q_ORDER, {"min": m}) for m in (20, 30, 40)]
    with failing_operator("OrderBy", n_times=1):  # exactly ONE member hit
        server.start()
        server.shutdown()
    # they coalesced into one batch...
    assert [h.info["batch_size"] for h in handles] == [3, 3, 3]
    # ...every member resolved to its own correct rows
    assert [r["n"] for r in handles[0].rows()] == ["Alice", "Bob",
                                                   "Carol", "Dana"]
    assert [r["n"] for r in handles[1].rows()] == ["Alice", "Bob", "Dana"]
    assert [r["n"] for r in handles[2].rows()] == ["Bob", "Dana"]
    # exactly one member carries a retry history; the siblings never saw
    # the injector's exception or anyone else's attempt context
    histories = [h.info.get("attempts") for h in handles]
    with_history = [a for a in histories if a is not None]
    assert len(with_history) == 1
    assert [a.get("ok", False) for a in with_history[0]] == [False, True]
    assert with_history[0][0]["op"] == "OrderBy"
    assert session.metrics_snapshot()["serve.completed"] == 3


def test_cypher_batch_isolates_fresh_exceptions_per_member():
    session = _session()
    graph = _graph(session)
    q = Q_ORDER
    graph.cypher(q, {"min": 20})  # warm
    with failing_operator("OrderBy", exc=RuntimeError("template"),
                          n_times=2):
        out = session.cypher_batch(graph, [(q, {"min": 20}),
                                           (q, {"min": 30})])
    assert isinstance(out[0], RuntimeError)
    assert isinstance(out[1], RuntimeError)
    assert out[0] is not out[1]  # no shared mutable error object


# -- the acceptance soak ---------------------------------------------------

def _soak(n_threads: int, per_thread: int, fault_fraction: float = 0.2):
    session = _session()
    graph = _graph(session)
    flat = [(Q_ORDER, {"min": m}) for m in (20, 30, 40, 50)] + \
           [(Q_EDGE, {"min": m}) for m in (25, 35, 45)] + \
           [(Q_COUNT, {"y": y}) for y in (2011, 2015, 2020)]
    expected = {i: _bag(graph.cypher(q, b).records.to_maps())
                for i, (q, b) in enumerate(flat)}

    total = n_threads * per_thread
    n_faults = int(total * fault_fraction)
    # breaker_threshold is raised out of the way: this soak exercises
    # the RETRY path's availability; the breaker has its own tests
    server = QueryServer(session, graph=graph, config=ServerConfig(
        workers=4, max_queue=4096, max_batch=8, breaker_threshold=100,
        retry=RetryPolicy(max_attempts=4, backoff_base_s=0.001,
                          backoff_max_s=0.01)))
    results: dict = {}
    submit_errors: list = []

    def client(tid: int):
        try:
            for j in range(per_thread):
                i = (tid * 7 + j) % len(flat)
                q, b = flat[i]
                results[(tid, j)] = (i, server.submit(q, b))
        except Exception as ex:  # pragma: no cover — must not happen
            submit_errors.append(ex)

    threads = [threading.Thread(target=client, args=(t,))
               for t in range(n_threads)]
    # transient single-shot faults land in ~fault_fraction of requests:
    # every 1/fraction-th Filter execution fails once (deterministic
    # spacing — an immediate retry lands between boundaries and heals)
    every_n = max(1, int(round(1.0 / fault_fraction)))
    with failing_operator("Filter", n_times=n_faults, every_n=every_n):
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        server.shutdown()  # graceful drain: every request resolves
    assert not submit_errors, submit_errors
    assert len(results) == total
    # availability: zero worker deaths — every handle completed, every
    # outcome is a result or a typed ServeError
    for i, handle in results.values():
        assert handle.done()
        ex = handle.exception()
        if ex is not None:
            # availability contract: failures are TYPED, never a raw
            # injector exception or a dead handle
            assert isinstance(ex, ServeError), ex
        else:
            # retried results are bag-equal to the fault-free run
            assert _bag(handle.rows()) == expected[i], i
    snap = session.metrics_snapshot()
    assert snap["serve.completed"] + snap["serve.failed"] == total
    assert snap["serve.retries"] > 0          # faults actually landed
    # retry containment: the overwhelming majority heal (a request only
    # fails if ALL its retries re-land on injection boundaries)
    assert snap["serve.completed"] >= total * 0.95
    return snap


def test_soak_transient_faults_eight_clients():
    _soak(n_threads=8, per_thread=8)


@pytest.mark.slow
def test_soak_transient_faults_long():
    _soak(n_threads=8, per_thread=40)


# -- lint coverage ---------------------------------------------------------

def test_serve_error_lint_is_clean():
    # the old script is a shim now; the check lives in capslint's
    # error-taxonomy pass (tests/test_analysis.py covers the framework)
    from caps_tpu.analysis import load_project, run_passes
    assert run_passes(load_project(), only=["error-taxonomy"]) == []

"""Fleet serving (ISSUE 16): wire-error round-trip fidelity, consistent
hashing, load-aware spill, kill-a-process failover, snapshot shipping,
and rejoin-warms-from-store.

The in-process fleet fixture runs real sockets and real wire frames —
each backend is a full QueryServer on its own session behind a
listener thread — so every cross-process contract except the GIL is
exercised deterministically (bench.py fleet spawns real interpreters
for the QPS-scaling acceptance)."""
from __future__ import annotations

import json

import pytest

from caps_tpu.obs.metrics import MetricsRegistry, merge_snapshots
from caps_tpu.serve import errors as serve_errors
from caps_tpu.serve.errors import (Cancelled, CancellationError, CircuitOpen,
                                   CompactionFailed, DeadlineExceeded,
                                   FleetUnavailable, Overloaded, QueryFailed,
                                   ReplicationUnsupported, ServeError,
                                   ServerClosed, ShardMemberDown,
                                   ShardingUnsupported, StaleEpoch,
                                   WaitTimeout, WalWriteError, WireError,
                                   error_from_payload)
from caps_tpu.serve.fleet import (BackendSpec, FleetBackend,
                                  foaf_create_script, rows_digest)
from caps_tpu.serve.router import FleetRouter, HashRing, RouterConfig
from caps_tpu.serve.wire import WireClient
from caps_tpu.testing.faults import drop_connection, slow_network

SOCIAL = """
    CREATE (a:Person {name: 'Alice', age: 33}),
           (b:Person {name: 'Bob', age: 44}),
           (c:Person {name: 'Carol', age: 27}),
           (d:Person {name: 'Dana', age: 51}),
           (a)-[:KNOWS {since: 2011}]->(b),
           (b)-[:KNOWS {since: 2015}]->(c),
           (a)-[:KNOWS {since: 2019}]->(c),
           (c)-[:KNOWS {since: 2021}]->(d)
"""

Q_AGE = ("MATCH (p:Person) WHERE p.age > $min "
         "RETURN p.name AS n ORDER BY n")
Q_KNOWS = ("MATCH (a:Person)-[:KNOWS]->(b) "
           "RETURN a.name AS a, b.name AS b ORDER BY a, b")


# -- satellite: wire-error round-trip parity matrix --------------------------

#: one representative instance per ServeError class — the parity test
#: FAILS when serve/errors.py grows a class with no sample here, so the
#: wire contract can never silently lose a type
ERROR_SAMPLES = (
    ServeError("boom"),
    ServerClosed("server is shutting down"),
    Overloaded("queue full", retry_after_s=1.5, queue_depth=7, priority=2),
    WaitTimeout("request not complete"),
    QueryFailed("exhausted containment",
                attempts=({"mode": "fused", "error": "XlaRuntimeError",
                           "classification": "TRANSIENT", "backoff_s": 0.25},
                          {"mode": "unfused", "error": "XlaRuntimeError",
                           "classification": "FATAL"}),
                retry_after_s=0.75),
    CircuitOpen("family quarantined", retry_after_s=3.25),
    CompactionFailed("fold failed"),
    ReplicationUnsupported("graph cannot re-ingest"),
    ShardingUnsupported("writes do not shard"),
    ShardMemberDown("member rebuilding", member=3),
    WalWriteError("WAL append failed (version 7): fsync failed"),
    StaleEpoch("zombie owner fenced", epoch=1, lease_epoch=2, owner="b1"),
    CancellationError("cancelled mid-plan", phase="plan"),
    DeadlineExceeded("execute", 0.5, 0.7531),
    DeadlineExceeded("queued", None, 1.25),
    Cancelled(phase="queued"),
    WireError("connection closed mid-frame"),
    FleetUnavailable("all ring nodes down", retry_after_s=2.0),
)


def test_every_serve_error_class_has_a_wire_sample():
    classes = {type(e) for e in ERROR_SAMPLES}
    missing = [name for name, cls in serve_errors._error_classes().items()
               if cls not in classes]
    assert not missing, (
        f"serve/errors.py classes without a wire round-trip sample: "
        f"{missing} — add one to ERROR_SAMPLES")


@pytest.mark.parametrize("err", ERROR_SAMPLES,
                         ids=lambda e: type(e).__name__)
def test_wire_error_round_trip_exact(err):
    payload = json.loads(json.dumps(err.to_payload()))
    back = error_from_payload(payload)
    assert type(back) is type(err)
    assert str(back) == str(err)
    # every machine-usable field survives: the rebuilt error serializes
    # to the identical payload
    assert back.to_payload() == err.to_payload()
    for attr in ("retry_after_s", "queue_depth", "priority", "attempts",
                 "phase", "budget_s", "elapsed_s", "caps_transient"):
        if hasattr(err, attr):
            assert getattr(back, attr) == getattr(err, attr), attr


def test_unknown_error_class_degrades_to_query_failed():
    back = error_from_payload({"error": "FutureError", "message": "hi"})
    assert type(back) is QueryFailed
    assert "FutureError" in str(back)
    assert error_from_payload("garbage").__class__ is QueryFailed


# -- consistent hashing ------------------------------------------------------

def _placements(ring, keys):
    return {k: ring.lookup(k) for k in keys}


def test_hash_ring_add_moves_about_one_over_n():
    keys = [f"graph|family-{i}" for i in range(1000)]
    ring = HashRing([f"b{i}" for i in range(5)])
    before = _placements(ring, keys)
    ring.add("b5")
    after = _placements(ring, keys)
    moved = sum(1 for k in keys if before[k] != after[k])
    # ideal is 1/6 of keys; virtual nodes keep the variance tight
    assert 0 < moved < len(keys) * 0.35
    # every moved key moved TO the new node — nothing reshuffles
    # between survivors
    assert all(after[k] == "b5" for k in keys if before[k] != after[k])


def test_hash_ring_remove_moves_only_the_dead_nodes_keys():
    keys = [f"g|{i}" for i in range(1000)]
    ring = HashRing([f"b{i}" for i in range(5)])
    before = _placements(ring, keys)
    ring.remove("b2")
    after = _placements(ring, keys)
    for k in keys:
        if before[k] == "b2":
            assert after[k] != "b2"
        else:
            assert after[k] == before[k]


def test_hash_ring_is_stable_across_instances():
    # blake2b placement, not the salted builtin hash: two routers built
    # in different processes MUST agree — here: two instances
    a = HashRing(["x", "y", "z"])
    b = HashRing(["z", "y", "x"])  # insertion order must not matter
    for i in range(200):
        assert a.lookup(f"k{i}") == b.lookup(f"k{i}")


def test_preference_walk_is_distinct_and_starts_at_primary():
    ring = HashRing(["a", "b", "c", "d"])
    for i in range(50):
        prefs = ring.preference(f"key-{i}")
        assert sorted(prefs) == ["a", "b", "c", "d"]
        assert prefs[0] == ring.lookup(f"key-{i}")


# -- in-process fleet fixture ------------------------------------------------

@pytest.fixture
def fleet():
    spec = {"kind": "script", "create": SOCIAL}
    backends = {}
    objs = {}
    for name in ("b0", "b1", "b2"):
        b = FleetBackend(BackendSpec(name=name, backend="local",
                                     graph=spec, versioned=True))
        objs[name] = b
        backends[name] = ("127.0.0.1", b.port)
    router = FleetRouter(backends, owner="b0",
                         config=RouterConfig(max_attempts=3),
                         registry=MetricsRegistry())
    yield router, objs
    router.close()
    for b in objs.values():
        b.shutdown(drain=False)


def test_routing_affinity_keeps_a_family_on_one_backend(fleet):
    router, _objs = fleet
    ran_on = {router.query(Q_AGE, {"min": 30}, family="age")["backend"]
              for _ in range(8)}
    assert len(ran_on) == 1


def test_reply_carries_ledger_and_snapshot_version(fleet):
    router, _objs = fleet
    out = router.query(Q_AGE, {"min": 30}, family="age")
    assert [r["n"] for r in out["rows"]] == ["Alice", "Bob", "Dana"]
    assert out["snapshot_version"] == 0
    assert set(out["ledger"]) >= {"bytes_in", "bytes_out", "compile_s"}


def test_remote_typed_error_reraises_exactly(fleet):
    router, _objs = fleet
    with pytest.raises(QueryFailed) as exc_info:
        router.query("MATCH (n:Person) RETURN bogus(n.age) AS x",
                     family="bad")
    # the error crossed the wire typed, not as a stringly RuntimeError
    assert type(exc_info.value) is QueryFailed


def test_hot_family_spill_overflows_to_next_ring_node(fleet):
    router, _objs = fleet
    primary = router.query(Q_AGE, {"min": 30}, family="hot")["backend"]
    # simulate a scraped hot-spot signal: the primary's windowed queue
    # depth sits over the spill threshold
    router._state[primary]["depth"] = router.config.spill_queue_depth
    spilled = router.query(Q_AGE, {"min": 30}, family="hot")
    assert spilled["backend"] != primary
    assert router.registry.snapshot()["router.spilled"] >= 1
    # the spill target's reply refreshed its depth; the primary heals
    # once its depth signal drops
    router._state[primary]["depth"] = 0
    assert router.query(Q_AGE, {"min": 30},
                        family="hot")["backend"] == primary


def test_kill_a_backend_soak_availability_one_digest_equal(fleet):
    router, objs = fleet
    families = [f"fam-{i}" for i in range(9)]
    want = {f: router.query(Q_AGE, {"min": 30}, family=f,
                            digest=True)["digest"]
            for f in families}
    # kill one process mid-soak (not the write owner; owner loss makes
    # the fleet read-only, which is its own test below)
    victim = next(n for n in objs if n != router.owner)
    objs[victim].shutdown(drain=False)
    ok = 0
    for _round in range(3):
        for f in families:
            out = router.query(Q_AGE, {"min": 30}, family=f, digest=True)
            assert out["digest"] == want[f], f
            assert out["backend"] != victim
            ok += 1
    assert ok == 27  # availability 1.0: every request served
    stats = router.stats()
    assert stats["backends"][victim]["live"] is False
    assert stats["live"] == 2


def test_owner_down_makes_writes_unavailable_reads_fine(fleet):
    router, objs = fleet
    objs[router.owner].shutdown(drain=False)
    router.query(Q_AGE, {"min": 30}, family="f")  # reads keep serving
    router.mark_dead(router.owner)
    with pytest.raises(FleetUnavailable):
        router.write("CREATE (x:Person {name: 'Zed', age: 1})")


def test_snapshot_shipping_read_your_writes_digest_exact(fleet):
    router, objs = fleet
    out = router.write("CREATE (e:Person {name: 'Eve', age: 61})")
    assert out["version"] == 1
    ship = out["ship"]
    assert set(ship["peers"]) == {"b1", "b2"}
    assert all(v == 1 for v in ship["peers"].values())
    assert ship["lag_s"] >= 0.0
    # read-your-writes on EVERY backend, digest-exact
    digests = set()
    for name in objs:
        rep = router._clients[name].call(
            "query", query=Q_AGE, params={"min": 30}, digest=True)
        assert rep["snapshot_version"] == 1
        assert any(r["n"] == "Eve" for r in rep["rows"])
        digests.add(rep["digest"])
    assert len(digests) == 1
    report = router.snapshot_report()
    assert set(report["versions"].values()) == {1}
    assert report["lag_s"] == ship["lag_s"]


def test_snapshot_install_is_monotonic(fleet):
    router, objs = fleet
    router.write("CREATE (e:Person {name: 'Eve', age: 61})")
    # re-shipping the same version is a no-op, never a rollback
    again = router.ship_snapshots()
    assert all(v == 1 for v in again["peers"].values())
    assert objs["b1"].graph.current().snapshot_version == 1


def test_fleet_metrics_text_aggregates_one_scrape(fleet):
    router, objs = fleet
    for f in ("m0", "m1"):
        router.query(Q_AGE, {"min": 30}, family=f)
    text = router.metrics_text()
    assert "fleet_backends_live 3" in text
    assert "router_requests" in text
    # backend-side serve.* counters summed across processes ride the
    # same scrape
    assert "serve_completed" in text
    merged = merge_snapshots([b.session.metrics_registry.snapshot()
                              for b in objs.values()])
    assert merged["serve.completed"] >= 2


# -- fault injectors (satellite) ---------------------------------------------

def test_drop_connection_fails_over_to_next_ring_node(fleet):
    router, objs = fleet
    primary = router.query(Q_AGE, {"min": 30}, family="drop")["backend"]
    with drop_connection(n_times=1) as budget:
        out = router.query(Q_AGE, {"min": 30}, family="drop")
    assert budget.injected == 1
    # the request survived the drop by retrying the next ring node; the
    # dropped backend's segment degraded
    assert out["backend"] != primary
    snap = router.registry.snapshot()
    assert snap["router.retries"] >= 1
    assert snap["router.backend_down"] >= 1
    assert router.stats()["backends"][primary]["live"] is False
    # the process never actually died: rejoin readmits it
    report = router.rejoin(primary)
    assert report["ping"]["name"] == primary
    assert router.stats()["backends"][primary]["live"] is True
    assert router.query(Q_AGE, {"min": 30},
                        family="drop")["backend"] == primary


def test_slow_network_injects_deterministically(fleet):
    router, _objs = fleet
    with slow_network(0.01, n_times=2) as budget:
        router.query(Q_AGE, {"min": 30}, family="slow")
        router.query(Q_AGE, {"min": 30}, family="slow")
        router.query(Q_AGE, {"min": 30}, family="slow")
    assert budget.injected == 2  # bounded: exactly n_times sends slowed


def test_injector_counters_ride_the_global_registry(fleet):
    from caps_tpu.obs.metrics import global_registry
    router, _objs = fleet
    before = global_registry().snapshot().get(
        "faults.injected.slow_network", 0)
    with slow_network(0.001, n_times=1):
        router.query(Q_AGE, {"min": 30}, family="ctr")
    after = global_registry().snapshot()["faults.injected.slow_network"]
    assert after == before + 1


# -- rejoin warms from the shared store --------------------------------------

def test_rejoin_warms_from_store_zero_compile_charge(tmp_path):
    store = str(tmp_path / "plans.json")
    spec = BackendSpec(name="w0", backend="local",
                       graph={"kind": "script", "create": SOCIAL},
                       versioned=False, plan_store_path=store,
                       warm_background=False)
    first = FleetBackend(spec)
    client = WireClient("127.0.0.1", first.port)
    for params in ({"min": 30}, {"min": 40}):
        out = client.call("query", query=Q_AGE, params=params)
        assert out["rows"]
    client.close()
    # shutdown persists the warm state to the shared store
    first.shutdown()

    # a rejoining process warms from the store BEFORE its port opens
    # (inline warmup) — its FIRST client query is a plan-cache hit
    rejoined = FleetBackend(spec)
    client = WireClient("127.0.0.1", rejoined.port)
    try:
        warm = client.call("warmup_wait", timeout=10.0)
        assert warm["done"]
        out = client.call("query", query=Q_AGE, params={"min": 35})
        assert out["ledger"]["compile_s"] == 0.0
        assert [r["n"] for r in out["rows"]] == ["Bob", "Dana"]
    finally:
        client.close()
        rejoined.shutdown(drain=False)


# -- spec / graph determinism ------------------------------------------------

def test_backend_spec_round_trips_json():
    spec = BackendSpec(name="n1", backend="local",
                       graph={"kind": "foaf", "n_people": 10,
                              "n_edges": 20, "seed": 7},
                       versioned=True, plan_store_path="/tmp/x.json")
    assert BackendSpec.from_json(spec.to_json()) == spec


def test_foaf_script_is_deterministic_across_calls():
    assert foaf_create_script(20, 40, 3) == foaf_create_script(20, 40, 3)
    assert foaf_create_script(20, 40, 3) != foaf_create_script(20, 40, 4)


def test_rows_digest_is_order_insensitive():
    rows = [{"a": 1, "b": "x"}, {"a": 2, "b": "y"}]
    assert rows_digest(rows) == rows_digest(list(reversed(rows)))
    assert rows_digest(rows) != rows_digest(rows[:1])

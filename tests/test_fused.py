"""Fused executor (backends/tpu/fused.py): record sizes on a query's
first run, replay them sync-free thereafter, recover from divergence.

The reference's analog is Spark's whole-stage codegen pipeline under
SparkTable (ref: spark-cypher/.../impl/table/SparkTable.scala —
reconstructed, mount empty; SURVEY.md §3.1)."""
from __future__ import annotations

import pytest

from caps_tpu.backends.tpu.session import TPUCypherSession
from caps_tpu.backends.tpu.table import FusedReplayMismatch
from caps_tpu.okapi.config import EngineConfig
from tests.util import make_graph


QUERY = ("MATCH (a:Person)-[:KNOWS]->(b)-[:KNOWS]->(c) "
         "WHERE a.name = 'Alice' RETURN c.name AS n")


def _social(session):
    return make_graph(
        session,
        {("Person",): [
            {"_id": 1, "name": "Alice", "age": 30},
            {"_id": 2, "name": "Bob", "age": 40},
            {"_id": 3, "name": "Eve", "age": 50},
            {"_id": 4, "name": "Mallory", "age": 60},
        ]},
        {"KNOWS": [(1, 2, {}), (2, 3, {}), (2, 4, {}), (3, 1, {})]},
    )


def test_replay_is_sync_free_and_correct():
    session = TPUCypherSession()
    g = _social(session)
    first = g.cypher(QUERY).records.to_maps()
    assert session.fused.recordings == 1 and session.fused.replays == 0
    syncs_after_record = session.backend.syncs
    assert syncs_after_record > 0  # record mode syncs like eager mode

    second = g.cypher(QUERY).records.to_maps()
    assert second == first
    assert session.fused.replays == 1
    # the replay run did ZERO count syncs — the memo served every size
    assert session.backend.syncs == syncs_after_record


def test_distinct_params_never_stale_hit():
    """Distinct parameter values must NEVER serve each other's exact
    sizes as truth.  (They used to force a second recording; with
    param-generic replay the second value may instead ride the merged
    stream — the observable contract is exact per-param results.)"""
    session = TPUCypherSession()
    g = _social(session)
    q = ("MATCH (a:Person)-[:KNOWS]->(b) WHERE a.name = $seed "
         "RETURN count(*) AS c")
    c_alice = g.cypher(q, {"seed": "Alice"}).records.to_maps()[0]["c"]
    c_bob = g.cypher(q, {"seed": "Bob"}).records.to_maps()[0]["c"]
    assert (c_alice, c_bob) == (1, 2)
    # the second value rode EITHER a fresh recording (violation path) or
    # a generic replay of the merged stream — never a stale exact hit
    assert session.fused.recordings + session.fused.generic_replays >= 2
    # repeats serve the right per-param results from either memo level
    assert g.cypher(q, {"seed": "Bob"}).records.to_maps()[0]["c"] == 2
    assert g.cypher(q, {"seed": "Alice"}).records.to_maps()[0]["c"] == 1
    assert session.fused.replays + session.fused.generic_replays >= 3


def test_mismatch_recovery_rerecords():
    session = TPUCypherSession()
    g = _social(session)
    first = g.cypher(QUERY).records.to_maps()
    # poison the memo: truncate the recording so replay exhausts it
    (key, (plen, sizes)), = session.fused._memo.items()
    assert sizes, "expected at least one recorded size"
    session.fused._memo[key] = (plen, sizes[:1])
    again = g.cypher(QUERY).records.to_maps()
    assert again == first
    assert session.fused.mismatches == 1
    # the memo was re-recorded and replays work again
    assert g.cypher(QUERY).records.to_maps() == first
    assert session.fused.replays >= 1


def test_mismatch_surplus_sizes_detected():
    session = TPUCypherSession()
    g = _social(session)
    first = g.cypher(QUERY).records.to_maps()
    (key, (plen, sizes)), = session.fused._memo.items()
    # surplus sizes: replay finishes with leftovers -> audit trips
    session.fused._memo[key] = (plen, list(sizes) + [7])
    assert g.cypher(QUERY).records.to_maps() == first
    assert session.fused.mismatches == 1


def test_determinism_check_rides_replay():
    session = TPUCypherSession(config=EngineConfig(determinism_check=True))
    g = _social(session)
    res = g.cypher(QUERY)
    assert "determinism_digest" in res.metrics
    assert res.records.to_maps() == [{"n": "Eve"}, {"n": "Mallory"}]
    # the replay leg of the determinism check reused the recording
    assert session.fused.replays >= 1


def test_fused_disabled_by_config():
    session = TPUCypherSession(config=EngineConfig(use_fused=False))
    g = _social(session)
    assert g.cypher(QUERY).records.to_maps() == [{"n": "Eve"},
                                                 {"n": "Mallory"}]
    assert session.fused.recordings == 0 and session.fused.replays == 0


def test_sharded_replay_parity():
    session = TPUCypherSession(config=EngineConfig(mesh_shape=(8,)))
    g = _social(session)
    first = g.cypher(QUERY).records.to_maps()
    assert g.cypher(QUERY).records.to_maps() == first
    assert session.fused.replays == 1

"""Param-generic fused replay (backends/tpu/fused.py).

The reference's steady-state latency story is Spark's whole-stage codegen
reusing one compiled plan across parameter values (ref: spark-cypher
SparkTable / Tungsten pipeline — reconstructed, mount empty; SURVEY.md
§3.1).  Our device analog: after recording size streams for a (graph,
query) under a few parameter values, later executions with NEW values
replay a merged stream — capacities widened to the max — with every
served size relation-checked on device and ONE end-of-query sync of the
violation flag.  Row counts become device scalars (DeviceTable._live),
so results stay exact under over-served capacities.

These tests drive rotating-parameter workloads through every op class
that consumes a data-dependent size and assert (a) oracle parity on
every iteration, (b) the steady-state sync count collapses, (c) a
violation (a parameter whose sizes exceed every recorded bound)
transparently re-records with exact results.
"""
import numpy as np
import pytest

import caps_tpu
from caps_tpu.testing.factory import create_graph

SPEC = "CREATE " + ", ".join(
    f"(p{i}:Person {{name:'P{i}', age:{20 + (i * 7) % 50}}})"
    for i in range(30)) + ", " + ", ".join(
    f"(p{i})-[:KNOWS {{w:{i}}}]->(p{(i * 3 + 1) % 30})" for i in range(30))


@pytest.fixture(scope="module")
def graphs():
    oracle = caps_tpu.local_session(backend="local")
    og = create_graph(oracle, SPEC)
    sess = caps_tpu.local_session(backend="tpu")
    g = create_graph(sess, SPEC)
    return og, g, sess


QUERIES = [
    # filter + join + group + order (compact, join, group consumes)
    ("MATCH (a:Person)-[:KNOWS]->(b:Person) WHERE a.age > $lim "
     "RETURN b.name AS n, count(*) AS c ORDER BY n",
     [25, 40, 33, 21, 48, 33, 60, 25]),
    # var-length + distinct
    ("MATCH (a)-[:KNOWS*1..2]->(b) WHERE a.age > $lim "
     "RETURN DISTINCT b.name AS n ORDER BY n", [30, 45, 22, 45, 67]),
    # optional match + limit
    ("MATCH (a:Person) WHERE a.age > $lim OPTIONAL MATCH (a)-[:KNOWS]->(b) "
     "RETURN a.name AS a, b.name AS b ORDER BY a, b LIMIT 7",
     [25, 50, 35, 35, 10]),
    # unwind (explode) + skip/limit
    ("MATCH (a) WHERE a.age > $lim UNWIND [1,2] AS u "
     "RETURN a.name AS n, u ORDER BY n, u SKIP 2 LIMIT 5", [40, 20, 55, 40]),
    # collect + sum aggregates (max_len / lo / hi consumes)
    ("MATCH (a:Person)-[k:KNOWS]->(b) WHERE k.w >= $lim "
     "RETURN collect(b.name) AS cs, sum(k.w) AS s", [5, 20, 1, 28]),
    # union of two param-filtered branches (concat gap compaction)
    ("MATCH (a:Person) WHERE a.age > $lim RETURN a.name AS n "
     "UNION MATCH (b:Person) WHERE b.age < $lim RETURN b.name AS n",
     [30, 55, 24, 30]),
]


def _bag(rows):
    return sorted(sorted(r.items()) for r in rows)


@pytest.mark.parametrize("q,lims", QUERIES)
def test_rotating_params_parity(graphs, q, lims):
    og, g, _ = graphs
    ordered = "ORDER BY" in q
    for lim in lims:
        want = og.cypher(q, {"lim": lim}).records.to_maps()
        got = g.cypher(q, {"lim": lim}).records.to_maps()
        if ordered:
            assert got == want, (q, lim)
        else:  # UNION row order is unspecified
            assert _bag(got) == _bag(want), (q, lim)


def test_steady_state_sync_collapse(graphs):
    og, g, _ = graphs
    q = ("MATCH (a:Person)-[:KNOWS]->(b:Person) WHERE a.age > $x "
         "RETURN b.name AS n ORDER BY n")
    syncs = []
    rng = np.random.RandomState(3)
    for _ in range(10):
        lim = int(rng.randint(18, 60))
        res = g.cypher(q, {"x": lim})
        want = og.cypher(q, {"x": lim}).records.to_maps()
        assert res.records.to_maps() == want
        syncs.append(res.metrics["size_syncs"])
    # first run records (several syncs); the tail must collapse to ONE
    # round trip (the violation-flag read batches the result table's
    # exact row count)
    assert syncs[0] >= 2
    assert max(syncs[-3:]) <= 1, syncs


def test_violation_rerecords_exactly(graphs):
    og, g, sess = graphs
    q = ("MATCH (a:Person)-[:KNOWS]->(b:Person) WHERE a.age >= $x "
         "RETURN a.name AS a, b.name AS b ORDER BY a, b")
    # record with a HIGH threshold (few rows), then query a LOW one whose
    # sizes exceed every recorded cap — the flag must fire and re-record
    res_hi = g.cypher(q, {"x": 65})
    want_lo = og.cypher(q, {"x": 0}).records.to_maps()
    mismatches_before = sess.fused.mismatches
    recordings_before = sess.fused.recordings
    res_lo = g.cypher(q, {"x": 0})
    assert res_lo.records.to_maps() == want_lo
    assert len(want_lo) > len(res_hi.records.to_maps())
    # the low-threshold run must NOT have ridden the stale generic
    # stream to completion: either the violation flag fired (mismatch +
    # re-record) or the run recorded outright
    assert (sess.fused.mismatches > mismatches_before
            or sess.fused.recordings > recordings_before)


def test_exact_replay_still_zero_syncs(graphs):
    og, g, _ = graphs
    q = "MATCH (a)-[:KNOWS]->(b) RETURN count(*) AS c"
    g.cypher(q).records.to_maps()
    res = g.cypher(q)
    assert res.records.to_maps() == og.cypher(q).records.to_maps()
    assert res.metrics["size_syncs"] == 0, res.metrics


def test_uncorrelated_optional_match_emptiness_branch(graphs):
    """The `pattern found nothing -> null-pad` branch of an uncorrelated
    OPTIONAL MATCH is host control flow on table emptiness.  Record with
    a parameter where the pattern matches, then run one where it matches
    NOTHING: the served (non-zero) size would silently take the
    cross-join branch and drop every lhs row — branch_empty() must trip
    the violation flag and re-record instead."""
    og, g, _ = graphs
    q = ("MATCH (a:Person) WHERE a.name = $n "
         "OPTIONAL MATCH (b:Person) WHERE b.age > $x "
         "RETURN a.name AS a, b.name AS b ORDER BY a, b")
    for n, x in [("P0", 30), ("P1", 45), ("P2", 200), ("P3", 64), ("P4", 300)]:
        params = {"n": n, "x": x}
        want = og.cypher(q, params).records.to_maps()
        got = g.cypher(q, params).records.to_maps()
        assert got == want, (params, got, want)
        # the empty-pattern cases must null-pad, not drop
        if x >= 200:
            assert got == [{"a": n, "b": None}], got


def test_merge_streams_rules():
    from caps_tpu.backends.tpu.fused import _merge_streams
    m = [("rows", 5), ("size", 3, "cap"), ("size", -2, "lo"),
         ("size", 0, "exact"), ("size", 9, "stat"), ("__obj__", "old")]
    r = [("rows", 2), ("size", 7, "cap"), ("size", 1, "lo"),
         ("size", 0, "exact"), ("size", 4, "stat"), ("__obj__", "new")]
    out = _merge_streams(m, r)
    assert out == [("rows", 5), ("size", 7, "cap"), ("size", -2, "lo"),
                   ("size", 0, "exact"), ("size", 4, "stat"),
                   ("__obj__", "new")]
    # exact disagreement or tag mismatch → not param-generic
    assert _merge_streams([("size", 0, "exact")], [("size", 1, "exact")]) \
        is None
    assert _merge_streams([("rows", 1)], [("size", 1, "cap")]) is None
    assert _merge_streams([("rows", 1)], []) is None
    # a row cap the new recording EXCEEDED widens to its bucket boundary
    # (convergence headroom); one that still fits does not
    widen = lambda n: 1 << max(0, (n - 1)).bit_length()
    assert _merge_streams([("rows", 5)], [("rows", 9)],
                          widen_rows=widen) == [("rows", 16)]
    assert _merge_streams([("rows", 16)], [("rows", 9)],
                          widen_rows=widen) == [("rows", 16)]

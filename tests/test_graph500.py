"""Benchmark config 4: RMAT generation + triangle counting via the cyclic
multiway-join path, checked against a host-side numpy oracle on both
backends (BASELINE.md config 4; SURVEY.md §3.2 ExpandInto)."""
import numpy as np
import pytest

from caps_tpu.datasets.graph500 import (
    TRIANGLE_QUERY, count_triangles_reference, rmat_edges, triangle_graph,
)


def test_rmat_deterministic_and_shaped():
    s1, d1 = rmat_edges(8, edgefactor=4, seed=7)
    s2, d2 = rmat_edges(8, edgefactor=4, seed=7)
    assert len(s1) == 4 * 256 == len(d1)
    np.testing.assert_array_equal(s1, s2)
    np.testing.assert_array_equal(d1, d2)
    assert s1.max() < 256 and d1.max() < 256 and s1.min() >= 0


def test_rmat_is_skewed():
    # RMAT with A=0.57 concentrates mass: max out-degree far above the mean.
    src, _ = rmat_edges(10, edgefactor=8, seed=3)
    deg = np.bincount(src, minlength=1 << 10)
    assert deg.max() >= 8 * deg.mean()


def test_reference_triangle_counter():
    # Known graph: K4 oriented by id has C(4,3)=4 triangles.
    lo, hi = [], []
    for u in range(4):
        for v in range(u + 1, 4):
            lo.append(u)
            hi.append(v)
    assert count_triangles_reference(np.array(lo), np.array(hi)) == 4


@pytest.mark.parametrize("backend", ["local", "tpu"])
def test_triangle_count_matches_oracle(backend, make_session):
    session = make_session(backend)
    graph, lo, hi = triangle_graph(session, scale=6, edgefactor=4, seed=5)
    want = count_triangles_reference(lo, hi)
    got = graph.cypher(TRIANGLE_QUERY).records.to_maps()
    assert got == [{"triangles": want}]
    assert want > 0  # scale-6 RMAT at ef=4 must actually contain triangles


def test_triangle_count_larger_tpu(make_session):
    session = make_session("tpu")
    graph, lo, hi = triangle_graph(session, scale=9, edgefactor=8, seed=2)
    want = count_triangles_reference(lo, hi)
    got = graph.cypher(TRIANGLE_QUERY).records.to_maps()
    assert got == [{"triangles": want}]

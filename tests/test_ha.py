"""Router high availability (ISSUE 20): epoch-fenced active/standby
routers, end-to-end deadline budgets, and hedged-read tail tolerance.

The contracts under test:

* the router lease — a SECOND ``LeaseStore`` namespace
  (``lease-router``) in the fleet's shared durable directory: exactly
  one ``HARouter`` steps to active, a live lease blocks the rival, and
  takeover after the TTL claims a HIGHER epoch;
* takeover rebuilds from shared truth — the new active adopts the
  write-lease owner (and epoch) from the store, never the dead peer's
  view, and with no published write lease the owner hint follows the
  SAME deterministic election order as the owner failover: longest
  replayed log, ties broken lexicographically by name (both insertion
  orders tested);
* zombie fencing — a deposed active's write frames carry its stale
  ``router_epoch`` and die on the backend with :class:`StaleEpoch`
  naming the surviving router, applying nothing; the zombie demotes
  itself at its next ``step()``;
* ``RouterSet`` — the client facade fails over on :class:`WireError`
  and retries standby refusals (:class:`FleetUnavailable`) until the
  takeover lands, within its wait budget;
* deadline fidelity — ``deadline_s`` is admission-stamped on
  ``obs.clock`` and every hop forwards the REMAINING budget; a 2-hop
  failover (read and write paths, on a fake clock) arrives at the
  second hop with the first hop's stall already deducted, and an
  exhausted budget raises the typed :class:`DeadlineExceeded` without
  touching the next backend;
* hedged reads — after the configured (or p99-learned) delay the read
  races the next ring node, the first reply wins and the loser is
  discarded (no duplication: the hedged reply equals the quiet one),
  a cold family never hedges off a guessed latency, and
  ``hedge_max_fraction`` rate-bounds ``router.hedges``.
"""
from __future__ import annotations

import threading
import time

import pytest

from caps_tpu.durability.lease import ROUTER_LEASE_NAME, LeaseStore
from caps_tpu.obs import clock
from caps_tpu.obs.metrics import MetricsRegistry
from caps_tpu.serve.errors import (DeadlineExceeded, FleetUnavailable,
                                   StaleEpoch, WireError)
from caps_tpu.serve.fleet import BackendSpec, FleetBackend
from caps_tpu.serve.ha import HARouter, RouterSet, RouterSpec
from caps_tpu.serve.router import FleetRouter, RouterConfig
from caps_tpu.serve.wire import WireClient
from caps_tpu.testing.chaos import slow_backend

PEOPLE = """
    CREATE (a:Person {name: 'Alice', age: 33}),
           (b:Person {name: 'Bob', age: 44}),
           (c:Person {name: 'Carol', age: 27})
"""
Q_NAMES = "MATCH (p:Person) RETURN p.name AS n ORDER BY n"
NAMES = ["Alice", "Bob", "Carol"]


class FakeClock:
    """Monotonic fake for caps_tpu.obs.clock (the test_faults idiom):
    ``sleep`` advances ``now`` instantly; ``wait`` honors a fired event
    and otherwise advances like a sleep."""

    def __init__(self, t0: float = 1_000.0):
        self._t = t0
        self._lock = threading.Lock()

    def now(self) -> float:
        with self._lock:
            return self._t

    def sleep(self, s: float) -> None:
        with self._lock:
            self._t += s

    def wait(self, event, timeout: float) -> bool:
        if event.is_set():
            return True
        self.sleep(timeout)
        return event.is_set()

    def advance(self, s: float) -> None:
        with self._lock:
            self._t += s


@pytest.fixture()
def fake_clock(monkeypatch):
    fc = FakeClock()
    monkeypatch.setattr(clock, "now", fc.now)
    monkeypatch.setattr(clock, "sleep", fc.sleep)
    monkeypatch.setattr(clock, "wait", fc.wait)
    return fc


def _backend_spec(name, store=None):
    return BackendSpec(name=name, backend="local",
                       graph={"kind": "script", "create": PEOPLE},
                       versioned=True,
                       durable_dir=store, wal_fsync="always",
                       lease_ttl_s=0.3)


def _router_spec(name, backends, store, **kw):
    kw.setdefault("lease_ttl_s", 0.3)
    kw.setdefault("failover_wait_s", 5.0)
    return RouterSpec(name=name, backends=backends, durable_dir=store,
                      owner="b0", **kw)


@pytest.fixture
def ha_fleet(tmp_path):
    """3 durable in-process backends + 2 HARouters on one shared store.
    The routers listen on real sockets but run NO control thread —
    tests drive elections one deterministic ``step()`` at a time."""
    store = str(tmp_path / "store")
    objs, backends = {}, {}
    for name in ("b0", "b1", "b2"):
        b = FleetBackend(_backend_spec(name, store))
        objs[name] = b
        backends[name] = ("127.0.0.1", b.port)
    routers = {}
    for name in ("r0", "r1"):
        routers[name] = HARouter(
            _router_spec(name, backends, store),
            start=True, control=False, registry=MetricsRegistry())
    yield routers, objs, store
    for r in routers.values():
        r.shutdown()
    for b in objs.values():
        b.shutdown(drain=False)


# -- the router lease: election, takeover, demotion --------------------------

def test_first_step_elects_exactly_one_active(ha_fleet):
    routers, _objs, store = ha_fleet
    r0, r1 = routers["r0"], routers["r1"]
    assert r0.step() == "active"
    assert r1.step() == "standby"
    assert (r0.epoch, r1.epoch) == (1, None)
    # the stamp mirrors into the FleetRouter so write frames carry it
    assert r0.router.router_epoch == 1
    assert r1.router.router_epoch is None
    # the router lease is its OWN namespace: the write lease untouched
    assert LeaseStore(store, lease_name=ROUTER_LEASE_NAME).read()[
        "owner"] == "r0"
    assert LeaseStore(store).read() is None
    assert r0.registry.snapshot()["router.ha_takeovers"] == 1
    assert r0.registry.snapshot()["router.ha_active"] == 1.0


def test_takeover_adopts_write_owner_from_shared_lease(ha_fleet):
    routers, _objs, _store = ha_fleet
    r0, r1 = routers["r0"], routers["r1"]
    r0.step()
    out = r0.router.write("CREATE (d:Person {name: 'Dana', age: 9})")
    assert (out["version"], out["epoch"]) == (1, 1)
    # the active dies; the standby takes over after the TTL from the
    # STORE's view of the fleet — write owner, epoch, backend liveness
    r0.shutdown()
    time.sleep(0.35)
    assert r1.step() == "active"
    assert r1.epoch == 2
    assert r1.router.owner == "b0"
    assert r1.router._owner_epoch == 1
    out = r1.router.write("CREATE (e:Person {name: 'Eve', age: 8})")
    # (the write lease's own TTL may have lapsed during the takeover
    # window, in which case b0 re-claims at a higher epoch — owner
    # identity, not epoch value, is the adoption contract here)
    assert out["version"] == 2
    assert r1.router.owner == "b0"


def test_zombie_router_is_fenced_and_demotes_itself(ha_fleet):
    routers, objs, _store = ha_fleet
    r0, r1 = routers["r0"], routers["r1"]
    r0.step()
    r0.router.write("CREATE (d:Person {name: 'Dana', age: 9})")
    # depose r0 behind its back: the router lease now names r1/epoch 2
    r0.lease._write({"owner": "r1", "epoch": 2,
                     "renewed_t": clock.now()})
    r1.step()
    assert (r1.role, r1.epoch) == ("active", 2)
    version_before = objs["b0"].graph.current().snapshot_version
    # the zombie still stamps epoch 1 on its write frames — the BACKEND
    # refuses them, whether or not the zombie's owner epoch is valid
    with pytest.raises(StaleEpoch) as exc_info:
        r0.router.write("CREATE (z:Person {name: 'Zed', age: 1})")
    assert exc_info.value.epoch == 1
    assert exc_info.value.lease_epoch == 2
    assert exc_info.value.owner == "r1"
    assert objs["b0"].graph.current().snapshot_version == version_before
    # deposition is discovered at the next step: renewal fails, demote
    assert r0.step() == "standby"
    assert r0.epoch is None and r0.router.router_epoch is None
    assert r0.registry.snapshot()["router.ha_demotions"] == 1


@pytest.mark.parametrize("order", [("a", "b"), ("b", "a")],
                         ids=["a-first", "b-first"])
def test_takeover_owner_hint_tie_breaks_lexicographically(tmp_path, order):
    """No published write lease + equal snapshot versions: the takeover
    adopts the lexicographically-first backend as owner hint, whatever
    the spec's insertion order — same rule as the owner election."""
    store = str(tmp_path / "store")
    objs = {name: FleetBackend(BackendSpec(
        name=name, backend="local",
        graph={"kind": "script", "create": PEOPLE}, versioned=True))
        for name in order}
    backends = {name: ("127.0.0.1", objs[name].port) for name in order}
    r = HARouter(RouterSpec(name="r0", backends=backends,
                            durable_dir=store, lease_ttl_s=0.3),
                 start=False, control=False, registry=MetricsRegistry())
    try:
        assert r.step() == "active"
        assert r.router.owner == "a"
    finally:
        for b in objs.values():
            b.shutdown(drain=False)


@pytest.mark.parametrize("order", [("a", "b"), ("b", "a")],
                         ids=["a-first", "b-first"])
def test_owner_election_tie_breaks_lexicographically(order):
    """Equal replayed logs: ``_failover_owner`` elects the
    lexicographically-first peer in BOTH insertion orders."""
    addrs = {name: ("127.0.0.1", 1) for name in ("z",) + order}
    router = FleetRouter(addrs, owner="z",
                         config=RouterConfig(failover_wait_s=0.1),
                         registry=MetricsRegistry())
    attempts = []

    class _Stub:
        def __init__(self, name):
            self.name = name

        def call(self, op, **fields):
            if op == "ping":
                return {"snapshot_version": 5}
            assert op == "acquire_lease"
            attempts.append(self.name)
            return {"durable": True, "epoch": 2}

        def close(self):
            pass

    router._clients = {n: _Stub(n) for n in addrs}
    router.mark_dead("z")
    assert router._failover_owner() is True
    assert attempts == ["a"]
    assert router.owner == "a" and router._owner_epoch == 2


# -- RouterSet: the client facade --------------------------------------------

def test_routerset_fails_over_to_standby_on_active_death(ha_fleet):
    routers, _objs, _store = ha_fleet
    r0, r1 = routers["r0"], routers["r1"]
    r0.step(), r1.step()
    reg = MetricsRegistry()
    rset = RouterSet({"r0": ("127.0.0.1", r0.port),
                      "r1": ("127.0.0.1", r1.port)},
                     wait_s=5.0, registry=reg)
    try:
        assert [r["n"] for r in rset.query(Q_NAMES)["rows"]] == NAMES
        assert rset.active() == "r0"
        # SIGKILL-equivalent: the active's sockets vanish, the lease is
        # NOT released (clean exit must look like a crash)
        r0.shutdown()
        time.sleep(0.35)
        assert r1.step() == "active"
        assert [r["n"] for r in rset.query(Q_NAMES)["rows"]] == NAMES
        assert rset.active() == "r1"
        assert reg.snapshot()["router.ha_client_failovers"] >= 1
    finally:
        rset.close()


def test_standby_refuses_with_bounded_retry_horizon(ha_fleet):
    routers, _objs, _store = ha_fleet
    r0, r1 = routers["r0"], routers["r1"]
    r0.step(), r1.step()
    with WireClient("127.0.0.1", r1.port) as client:
        with pytest.raises(FleetUnavailable) as exc_info:
            client.call("query", query=Q_NAMES)
    # the refusal names the takeover horizon: ~1 TTL, never unbounded
    assert 0.0 < exc_info.value.retry_after_s <= 1.0
    assert r1.registry.snapshot()["router.ha_standby_refusals"] == 1


def test_router_spec_round_trips_json(tmp_path):
    spec = _router_spec("r9", {"b0": ("127.0.0.1", 4242)},
                        str(tmp_path), hedge_reads=True,
                        hedge_delay_s=0.02)
    assert RouterSpec.from_json(spec.to_json()) == spec


# -- deadline fidelity (satellite: fake-clock 2-hop regression) ---------------

class _StubClient:
    def __init__(self, fn):
        self.fn = fn
        self.calls = []

    def call(self, op, **fields):
        self.calls.append((op, dict(fields)))
        return self.fn(op, fields)

    def close(self):
        pass


def _stub_router(fake_clock, stall_s, **cfg):
    """Two stub backends: the ring-preferred one stalls ``stall_s`` on
    the fake clock and dies with WireError; the other answers."""
    addrs = {"a": ("127.0.0.1", 1), "b": ("127.0.0.1", 2)}
    router = FleetRouter(addrs, owner="a",
                         config=RouterConfig(max_attempts=2, **cfg),
                         registry=MetricsRegistry())
    first, second = router.ring.preference(
        FleetRouter.routing_key("default", "fam", "Q"))[:2]

    def die(_op, _fields):
        fake_clock.advance(stall_s)
        raise WireError("stalled, then the socket died")

    def serve(_op, _fields):
        return {"rows": [], "snapshot_version": 0}

    router._clients = {first: _StubClient(die),
                       second: _StubClient(serve)}
    return router, first, second


def test_read_retry_forwards_remaining_budget_not_original(fake_clock):
    router, first, second = _stub_router(fake_clock, stall_s=2.0)
    out = router.query("Q", family="fam", deadline_s=5.0)
    assert out["backend"] == second
    # hop 1 got the full admission budget; hop 2 got what was LEFT
    assert router._clients[first].calls[0][1]["deadline_s"] \
        == pytest.approx(5.0)
    assert router._clients[second].calls[0][1]["deadline_s"] \
        == pytest.approx(3.0)


def test_read_deadline_exhausted_mid_failover_is_typed(fake_clock):
    router, _first, second = _stub_router(fake_clock, stall_s=6.0)
    with pytest.raises(DeadlineExceeded) as exc_info:
        router.query("Q", family="fam", deadline_s=5.0)
    assert exc_info.value.phase == "route"
    # the exhausted budget never reached the second backend
    assert router._clients[second].calls == []


def test_write_failover_forwards_remaining_budget(fake_clock):
    addrs = {"a": ("127.0.0.1", 1), "b": ("127.0.0.1", 2)}
    router = FleetRouter(addrs, owner="a",
                         config=RouterConfig(failover_wait_s=1.0),
                         registry=MetricsRegistry())

    def owner_dies(_op, _fields):
        fake_clock.advance(2.0)
        raise WireError("owner died mid-write")

    def peer(op, _fields):
        if op == "ping":
            return {"snapshot_version": 1}
        if op == "acquire_lease":
            return {"durable": True, "epoch": 2}
        assert op == "write"
        return {"version": 2, "epoch": 2}

    router._clients = {"a": _StubClient(owner_dies),
                       "b": _StubClient(peer)}
    out = router.write("CREATE (x)", ship=False, deadline_s=5.0)
    assert out["version"] == 2
    assert router._clients["a"].calls[0][1]["deadline_s"] \
        == pytest.approx(5.0)
    write_calls = [(op, f) for op, f in router._clients["b"].calls
                   if op == "write"]
    # the elected peer's frame carries the remaining budget AND the
    # freshly-claimed epoch
    assert write_calls[0][1]["deadline_s"] == pytest.approx(3.0)
    assert write_calls[0][1]["epoch"] == 2


# -- hedged reads -------------------------------------------------------------

@pytest.fixture
def plain_fleet():
    objs, backends = {}, {}
    for name in ("b0", "b1", "b2"):
        b = FleetBackend(BackendSpec(
            name=name, backend="local",
            graph={"kind": "script", "create": PEOPLE}, versioned=True))
        objs[name] = b
        backends[name] = ("127.0.0.1", b.port)
    yield objs, backends
    for b in objs.values():
        b.shutdown(drain=False)


def _hedge_router(backends, **cfg):
    cfg.setdefault("hedge_reads", True)
    cfg.setdefault("hedge_max_fraction", 1.0)
    return FleetRouter(backends, owner="b0",
                       config=RouterConfig(**cfg),
                       registry=MetricsRegistry())


def test_hedged_read_wins_over_straggler_without_duplication(plain_fleet):
    objs, backends = plain_fleet
    router = _hedge_router(backends, hedge_delay_s=0.05)
    try:
        primary = router.ring.preference(
            FleetRouter.routing_key("default", "fam", Q_NAMES))[0]
        quiet = router.query(Q_NAMES, family="fam")
        assert quiet["backend"] == primary
        with slow_backend(backends[primary][1], 0.3, n_times=1):
            out = router.query(Q_NAMES, family="fam")
        # the hedge leg won — and the reply is ONE reply, identical to
        # the quiet run (first-wins, loser discarded, nothing merged)
        assert out["backend"] != primary
        assert out["rows"] == quiet["rows"]
        snap = router.registry.snapshot()
        assert snap["router.hedges"] == 1
        assert snap["router.hedge_wins"] == 1
        # the straggler is slow, not dead: once its discarded leg has
        # drained off the shared client, it serves the next read
        time.sleep(0.4)
        assert router.query(Q_NAMES,
                            family="fam")["backend"] == primary
    finally:
        router.close()


def test_hedge_rate_bound_zero_never_hedges(plain_fleet):
    _objs, backends = plain_fleet
    router = _hedge_router(backends, hedge_delay_s=0.01,
                           hedge_max_fraction=0.0)
    try:
        primary = router.ring.preference(
            FleetRouter.routing_key("default", "fam", Q_NAMES))[0]
        with slow_backend(backends[primary][1], 0.05, n_times=1):
            out = router.query(Q_NAMES, family="fam")
        # rate-bounded out of existence: the slow primary still serves
        assert out["backend"] == primary
        assert "router.hedges" not in router.registry.snapshot()
    finally:
        router.close()


def test_cold_family_never_hedges_off_a_guessed_latency(plain_fleet):
    _objs, backends = plain_fleet
    router = _hedge_router(backends)  # hedge_delay_s=None: learn p99
    try:
        primary = router.ring.preference(
            FleetRouter.routing_key("default", "cold", Q_NAMES))[0]
        with slow_backend(backends[primary][1], 0.05, n_times=1):
            out = router.query(Q_NAMES, family="cold")
        # no latency window yet — no delay to hedge after
        assert out["backend"] == primary
        assert "router.hedges" not in router.registry.snapshot()
        # once the family has observations, the p99-derived delay kicks
        # in and the same straggler IS hedged around
        for _ in range(4):
            router.query(Q_NAMES, family="cold")
        with slow_backend(backends[primary][1], 0.5, n_times=1):
            out = router.query(Q_NAMES, family="cold")
        assert out["backend"] != primary
        assert router.registry.snapshot()["router.hedges"] == 1
    finally:
        router.close()

import pytest

from caps_tpu.frontend.parser import parse_query
from caps_tpu.ir import exprs as E
from caps_tpu.ir.blocks import (
    AggregationBlock, CypherQuery, FilterBlock, MatchBlock, OrderAndSliceBlock,
    ProjectBlock, ResultBlock, SelectBlock, UnionOfQueries, UnwindBlock,
)
from caps_tpu.ir.builder import IRBuildError, IRBuilder
from caps_tpu.ir.pattern import Connection, Direction
from caps_tpu.ir.typer import SchemaTyper
from caps_tpu.okapi.schema import Schema
from caps_tpu.okapi.types import (
    CTBoolean, CTFloat, CTInteger, CTList, CTNode, CTRelationship, CTString,
)


def social_schema():
    return (Schema.empty()
            .with_node_property_keys(["Person"], {"name": CTString, "age": CTInteger})
            .with_relationship_property_keys("KNOWS", {"since": CTInteger}))


def build(query, schema=None, **params):
    return IRBuilder(schema or social_schema(), parameters=params).process(
        parse_query(query))


def blocks_of(ir, *types):
    assert isinstance(ir, CypherQuery)
    assert [type(b) for b in ir.blocks] == list(types), ir.blocks
    return ir.blocks


def test_simple_match_blocks():
    ir = build("MATCH (a:Person) RETURN a.name AS name")
    m, p, r = blocks_of(ir, MatchBlock, ProjectBlock, ResultBlock)
    assert m.pattern.entity_type("a") == CTNode(["Person"])
    assert p.items == (("name", E.Property(E.Var("a"), "name")),)
    assert r.fields == ("name",)


def test_two_hop_connections():
    ir = build("MATCH (a)-[r:KNOWS]->(b)<-[s]-(c) RETURN a")
    m = ir.blocks[0]
    conns = m.pattern.connections
    assert conns[0] == Connection("a", "r", "b", Direction.OUTGOING, ("KNOWS",), None)
    # incoming hop is normalized to outgoing from c to b
    assert conns[1].source == "c" and conns[1].target == "b"
    assert conns[1].direction == Direction.OUTGOING


def test_undirected_connection():
    ir = build("MATCH (a)-[r]-(b) RETURN a")
    assert ir.blocks[0].pattern.connections[0].direction == Direction.BOTH


def test_inline_props_become_predicates():
    ir = build("MATCH (a:Person {name: 'Alice'}) RETURN a")
    m = ir.blocks[0]
    assert E.Equals(E.Property(E.Var("a"), "name"), E.Lit("Alice")) in m.predicates


def test_bound_var_relabel_becomes_predicate():
    ir = build("MATCH (a:Person) MATCH (a:Admin)-[r]->(b) RETURN b")
    m2 = ir.blocks[1]
    assert "a" in m2.pattern.bound
    assert E.HasLabel(E.Var("a"), "Admin") in m2.predicates
    assert "a" not in m2.pattern.entity_names


def test_where_splits_ands():
    ir = build("MATCH (a:Person) WHERE a.age > 21 AND a.name = 'Bob' RETURN a")
    assert len(ir.blocks[0].predicates) == 2


def test_anonymous_entities_get_fresh_names():
    ir = build("MATCH (a)-[:KNOWS]->() RETURN a")
    m = ir.blocks[0]
    names = m.pattern.entity_names
    assert len(names) == 3
    assert sum(n.startswith("__") for n in names) == 2


def test_var_length_connection():
    ir = build("MATCH (a)-[r:KNOWS*1..3]->(b) RETURN a")
    conn = ir.blocks[0].pattern.connections[0]
    assert conn.var_length == (1, 3)
    assert ir.blocks[0].pattern.entity_type("r") == CTList(CTRelationship(["KNOWS"]))


def test_aggregation_block_split():
    ir = build("MATCH (a:Person) RETURN a.name AS name, count(*) AS c")
    m, agg, r = blocks_of(ir, MatchBlock, AggregationBlock, ResultBlock)
    assert agg.group == (("name", E.Property(E.Var("a"), "name")),)
    assert agg.aggregations == (("c", E.CountStar()),)


def test_nested_aggregator_gets_post_projection():
    ir = build("MATCH (a:Person) RETURN count(*) + 1 AS c")
    m, agg, post, r = blocks_of(ir, MatchBlock, AggregationBlock, ProjectBlock,
                                ResultBlock)
    (aname, aexpr), = agg.aggregations
    assert aexpr == E.CountStar()
    assert post.items[0][1] == E.Add(E.Var(aname), E.Lit(1))


def test_with_where_becomes_filter():
    ir = build("MATCH (a:Person) WITH a.age AS age WHERE age > 30 RETURN age")
    types = [type(b) for b in ir.blocks]
    assert types == [MatchBlock, ProjectBlock, FilterBlock, ProjectBlock, ResultBlock]


def test_order_by_alias():
    ir = build("MATCH (a:Person) RETURN a.name AS name ORDER BY name DESC LIMIT 5")
    m, p, o, r = blocks_of(ir, MatchBlock, ProjectBlock, OrderAndSliceBlock,
                           ResultBlock)
    assert o.order == ((E.Var("name"), False),)
    assert o.limit == E.Lit(5)


def test_order_by_old_scope_gets_hidden_field():
    ir = build("MATCH (a:Person) RETURN a.name AS name ORDER BY a.age")
    m, p, o, s, r = blocks_of(ir, MatchBlock, ProjectBlock, OrderAndSliceBlock,
                              SelectBlock, ResultBlock)
    assert len(p.items) == 2  # name + hidden order field
    hidden = p.items[1][0]
    assert o.order == ((E.Var(hidden), True),)
    assert s.fields == ("name",)
    assert r.fields == ("name",)


def test_unwind_block_and_env():
    ir = build("UNWIND [1, 2, 3] AS x RETURN x + 1 AS y")
    u = ir.blocks[0]
    assert isinstance(u, UnwindBlock) and u.var == "x"


def test_union_of_queries():
    ir = build("RETURN 1 AS v UNION ALL RETURN 2 AS v")
    assert isinstance(ir, UnionOfQueries) and ir.union_all


def test_return_star_excludes_anon():
    ir = build("MATCH (a)-[:KNOWS]->(b) RETURN *")
    r = ir.blocks[-1]
    assert r.fields == ("a", "b")


def test_rebinding_rel_var_fails():
    with pytest.raises(Exception):
        build("MATCH (a)-[r]->(b)-[r]->(c) RETURN a")


def test_named_path_builds_path_expr():
    q = build("MATCH p = (a)-[:X]->(b) RETURN p")
    from caps_tpu.ir import exprs as E
    from caps_tpu.ir.blocks import ProjectBlock
    proj = [b for b in q.blocks if isinstance(b, ProjectBlock)][0]
    (name, expr), = proj.items
    assert name == "p"
    assert isinstance(expr, E.PathExpr)
    assert expr.nodes == (E.Var("a"), E.Var("b"))
    assert expr.rels == (E.Var("__rel1"),)
    assert expr.varlen == (False,)


def test_named_path_rebinding_refused():
    with pytest.raises(IRBuildError):
        build("MATCH p = (a)-[:X]->(b) MATCH p = (c)-[:X]->(d) RETURN p")


def test_named_path_nodes_on_varlen_builds_pathnodes():
    # round-4 VERDICT Missing #3: previously hard-refused; now lowered to a
    # PathNodes walk over the hop rel ids (evaluated via the entity context)
    ir = build("MATCH p = (a)-[:X*1..2]->(b) RETURN nodes(p) AS ns")
    proj = next(b for b in ir.blocks
                if type(b).__name__ == "ProjectBlock"
                and any(n == "ns" for n, _ in b.items))
    (_, expr), = [(n, x) for n, x in proj.items if n == "ns"]
    assert isinstance(expr, E.PathNodes)
    assert expr.is_list == (True,)
    assert len(expr.pieces) == 1


# -- typer ------------------------------------------------------------------

def test_typer_property_types():
    schema = social_schema()
    typer = SchemaTyper(schema)
    env = {"a": CTNode(["Person"]), "r": CTRelationship(["KNOWS"])}
    assert typer.type_of(E.Property(E.Var("a"), "name"), env) == CTString
    assert typer.type_of(E.Property(E.Var("a"), "age"), env) == CTInteger
    assert typer.type_of(E.Property(E.Var("r"), "since"), env) == CTInteger
    # unknown property types as CTNull
    from caps_tpu.okapi.types import CTNull
    assert typer.type_of(E.Property(E.Var("a"), "nope"), env) == CTNull


def test_typer_comparison_nullability():
    typer = SchemaTyper(social_schema())
    env = {"a": CTNode(["Person"])}
    t = typer.type_of(E.GreaterThan(E.Property(E.Var("a"), "age"), E.Lit(21)), env)
    assert t == CTBoolean
    t2 = typer.type_of(E.Equals(E.Lit(None), E.Lit(1)), env)
    assert t2 == CTBoolean.nullable


def test_typer_arithmetic():
    typer = SchemaTyper(social_schema())
    env = {"a": CTNode(["Person"])}
    assert typer.type_of(E.Add(E.Lit(1), E.Lit(2)), env) == CTInteger
    assert typer.type_of(E.Add(E.Lit(1), E.Lit(2.0)), env) == CTFloat.join(CTInteger)
    assert typer.type_of(E.Add(E.Lit("a"), E.Lit("b")), env) == CTString


def test_typer_aggregators():
    typer = SchemaTyper(social_schema())
    env = {"a": CTNode(["Person"])}
    assert typer.type_of(E.CountStar(), env) == CTInteger
    assert typer.type_of(E.Avg(E.Property(E.Var("a"), "age")), env) == CTFloat
    assert typer.type_of(E.Collect(E.Property(E.Var("a"), "name")), env) == CTList(CTString)


def test_typer_functions():
    typer = SchemaTyper(social_schema())
    env = {"a": CTNode(["Person"])}
    assert typer.type_of(E.FunctionExpr("toupper", (E.Property(E.Var("a"), "name"),)),
                         env) == CTString
    assert typer.type_of(E.FunctionExpr("size", (E.Lit("abc"),)), env) == CTInteger
    assert typer.type_of(E.Id(E.Var("a")), env) == CTInteger

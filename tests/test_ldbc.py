"""LDBC-SNB configs 2/3: short reads IS1-IS7 + complex-read subset.

Parity: every query runs on the TPU backend and the pure-Python oracle and
must agree as a multiset (Bag).  IS1/IS4/IS5 additionally check against
answers computed directly from the generator's raw numpy arrays, so the
two backends can't agree on a shared wrong answer.
"""
from __future__ import annotations

import numpy as np
import pytest

from caps_tpu.backends.local.session import LocalCypherSession
from caps_tpu.backends.tpu.session import TPUCypherSession
from caps_tpu.datasets import ldbc
from caps_tpu.testing.bag import Bag

SCALE, SEED = 0.02, 7
N_PARAM_DRAWS = 3


@pytest.fixture(scope="module")
def graphs():
    local = LocalCypherSession()
    tpu = TPUCypherSession()
    glocal, d = ldbc.build_graph(local, SCALE, SEED)
    gtpu, _ = ldbc.build_graph(tpu, SCALE, SEED)
    return glocal, gtpu, d, tpu


ALL_READS = {**ldbc.SHORT_READS, **ldbc.COMPLEX_READS}


@pytest.mark.parametrize("name", sorted(ALL_READS))
def test_parity(graphs, name):
    glocal, gtpu, d, tpu_session = graphs
    query, make_params = ALL_READS[name]
    rng = np.random.RandomState(11)
    for _ in range(N_PARAM_DRAWS):
        params = make_params(d, rng)
        want = glocal.cypher(query, params).records.to_maps()
        got = gtpu.cypher(query, params).records.to_maps()
        if "ORDER BY" in query and "LIMIT" in query:
            # With ties at the LIMIT cutoff any valid engine may pick a
            # different-but-correct subset; compare on the sort keys only.
            assert len(got) == len(want), (name, params)
        assert Bag(got) == want or _order_limit_compatible(query, got, want), \
            (name, params, got, want)


def _order_limit_compatible(query, got, want):
    """For ORDER BY ... LIMIT queries, accept a tie-broken prefix: rows
    whose sort key falls strictly before the cutoff key must match as full
    rows (multiset equality); only rows AT the cutoff key — where any
    valid engine may pick a different-but-correct subset — are compared by
    count and key alone."""
    if "LIMIT" not in query or "ORDER BY" not in query:
        return False
    if len(got) != len(want):
        return False
    if not want:
        return True
    keys = [k.strip().split()[0] for k in
            query.split("ORDER BY")[1].split("LIMIT")[0].split(",")]
    key_of = lambda r: tuple(r[k] for k in keys)
    cutoff = key_of(want[-1])
    got_nb = [r for r in got if key_of(r) != cutoff]
    want_nb = [r for r in want if key_of(r) != cutoff]
    if Bag(got_nb) != want_nb:
        return False
    n_boundary = len(got) - len(got_nb)
    return (len(want) - len(want_nb) == n_boundary and
            all(key_of(r) == cutoff for r in got[len(got) - n_boundary:]))


def test_is1_vs_numpy(graphs):
    glocal, gtpu, d, _ = graphs
    pid = int(d.person_ids[3])
    q, _mk = ldbc.SHORT_READS["IS1"]
    for g in (glocal, gtpu):
        rows = g.cypher(q, {"personId": pid}).records.to_maps()
        assert rows == [{
            "firstName": d.person_first[3], "lastName": d.person_last[3],
            "birthday": int(d.person_birthday[3]),
            "cityId": int(d.city_ids[d.person_city[3]]),
            "creationDate": int(d.person_creation[3])}]


def test_is4_is5_vs_numpy(graphs):
    glocal, gtpu, d, _ = graphs
    mid = int(d.post_ids[5])
    creator = int(d.post_creator[5])
    for g in (glocal, gtpu):
        rows = g.cypher(ldbc.SHORT_READS["IS4"][0], {"messageId": mid}
                        ).records.to_maps()
        assert rows == [{"messageCreationDate": int(d.post_creation[5]),
                         "messageId": mid}]
        rows = g.cypher(ldbc.SHORT_READS["IS5"][0], {"messageId": mid}
                        ).records.to_maps()
        assert rows == [{"personId": int(d.person_ids[creator]),
                         "firstName": d.person_first[creator],
                         "lastName": d.person_last[creator]}]


def test_is3_vs_numpy(graphs):
    """Friend list parity against a direct numpy computation over the raw
    KNOWS pairs (undirected)."""
    glocal, _, d, _ = graphs
    idx = 1
    pid = int(d.person_ids[idx])
    rows = glocal.cypher(ldbc.SHORT_READS["IS3"][0], {"personId": pid}
                         ).records.to_maps()
    friends = []
    for s, t, c in zip(d.knows_src, d.knows_dst, d.knows_creation):
        if s == idx:
            friends.append((int(d.person_ids[t]), int(c)))
        elif t == idx:
            friends.append((int(d.person_ids[s]), int(c)))
    assert sorted((r["personId"], r["friendshipCreationDate"])
                  for r in rows) == sorted(friends)
    # engine must have sorted by creationDate DESC then id ASC
    assert [(r["friendshipCreationDate"], r["personId"]) for r in rows] == \
        sorted(((c, p) for p, c in friends), key=lambda t: (-t[0], t[1]))


def test_no_device_fallbacks(graphs):
    _, _, _, tpu_session = graphs
    assert tpu_session.fallback_count == 0, \
        tpu_session.backend.fallback_reasons


def test_ic7_vs_numpy(graphs):
    """IC7 (likes feed) against a direct numpy computation."""
    glocal, _gtpu, d, _tpu = graphs
    rng = np.random.RandomState(17)
    pid = int(d.person_ids[rng.randint(0, len(d.person_ids))])
    pidx = int(np.where(d.person_ids == pid)[0][0])
    rows = glocal.cypher(ldbc.COMPLEX_READS["IC7"][0],
                         {"personId": pid}).records.to_maps()
    # numpy expectation: likes on messages created by pidx
    msg_creator = np.concatenate([d.post_creator, d.comment_creator])
    like_msg_global = np.where(d.likes_is_post, d.likes_target,
                               d.likes_target + len(d.post_ids))
    like_on_p = msg_creator[like_msg_global] == pidx
    expect = int(like_on_p.sum())
    # the query LIMITs to 20; compare against the capped count
    assert len(rows) == min(20, expect), (len(rows), expect)
    # ordering: likeTime descending
    times = [r["likeTime"] for r in rows]
    assert times == sorted(times, reverse=True) or len(times) <= 1


def test_ic13_vs_numpy(graphs):
    """IC13 equals a numpy BFS: the exact bounded shortest-path length,
    and exactly null (LDBC's -1 analog) for pairs farther than the
    bound."""
    glocal, _gtpu, d, _tpu = graphs
    q, _ = ldbc.COMPLEX_READS["IC13"]
    n = len(d.person_ids)
    adj = [[] for _ in range(n)]
    for a, b in zip(d.knows_src, d.knows_dst):
        adj[a].append(b)
        adj[b].append(a)

    def bfs_len(src, dst, bound=3):
        if src == dst:
            return None  # *1..3 never matches a zero-length path…
        frontier, depth = {src}, 0
        while frontier and depth < bound:
            depth += 1
            frontier = {w for v in frontier for w in adj[v]}
            if dst in frontier:
                return depth
        return None

    rng = np.random.RandomState(23)
    # sample pairs, plus an exhaustive scan for any beyond-bound pair
    pairs = [(int(rng.randint(0, n)), int(rng.randint(0, n)))
             for _ in range(15)]
    pairs += [(i, j) for i in range(n) for j in range(n)
              if i != j and bfs_len(i, j) is None][:3]
    checked_len = 0
    for i, j in pairs:
        # skip self-pairs: their expectation needs cycle enumeration,
        # not plain BFS
        if i == j:
            continue
        want = bfs_len(i, j)
        rows = glocal.cypher(q, {"person1Id": int(d.person_ids[i]),
                                 "person2Id": int(d.person_ids[j])}
                             ).records.to_maps()
        assert len(rows) == 1
        assert rows[0]["shortestPathLength"] == want, (i, j, rows, want)
        checked_len += want is not None
    assert checked_len > 0

    # the null (no path within bound) outcome, on a graph where it is
    # guaranteed: two components, one beyond any 3-hop reach
    from caps_tpu.testing.factory import create_graph
    iso = create_graph(LocalCypherSession(), """
        CREATE (a:Person {id: 1}), (b:Person {id: 2}),
               (c:Person {id: 3}), (a)-[:KNOWS]->(c)
    """, {})
    rows = iso.cypher(q, {"person1Id": 1, "person2Id": 2}
                      ).records.to_maps()
    assert rows == [{"shortestPathLength": None}]


def test_sharded_parity_smoke():
    """A slice of the LDBC reads on the 8-device mesh: the distributed
    engine answers the same rows as the oracle (configs 2/3 sharded)."""
    from caps_tpu.okapi.config import EngineConfig
    sharded = TPUCypherSession(config=EngineConfig(mesh_shape=(8,)))
    glocal, d = ldbc.build_graph(LocalCypherSession(), SCALE, SEED)
    gs, _ = ldbc.build_graph(sharded, SCALE, SEED)
    rng = np.random.RandomState(41)
    for name in ("IS3", "IC1", "IC10", "IC13"):
        q, mk = ALL_READS[name]
        params = mk(d, rng)
        want = glocal.cypher(q, params).records.to_maps()
        got = gs.cypher(q, params).records.to_maps()
        if "ORDER BY" in q and "LIMIT" in q:
            assert len(got) == len(want), (name, params)
            assert Bag(got) == want or \
                _order_limit_compatible(q, got, want), (name, params)
        else:
            assert Bag(got) == want, (name, params)
    assert sharded.fallback_count == 0, sharded.backend.fallback_reasons

"""Compile & memory observability (ISSUE 10): the per-query resource
ledger, compile telemetry, and the structured slow-query log.

* compile ledger: cold plan phases, fused record runs, and program-cache
  misses charge per plan family with first-seen-vs-re-compile semantics;
  a plan-cache hit / fused replay charges ZERO compile seconds, and a
  post-quarantine re-record shows up as a re-compile for its family;
* memory ledger: ``mem.*`` gauges over the plan cache, string pool,
  tracked graphs (base/delta split per snapshot version), and device
  allocator stats (graceful CPU fallback);
* byte-based compaction: ``compaction_threshold_bytes`` folds a
  versioned graph whose delta grew heavy before the row count would;
* structured logs: the bounded event ring (JSON-lines sink, correlation
  by request id / family) and the slow-query log whose records share
  the flight recorder's shape.
"""
from __future__ import annotations

import json

import pytest

import caps_tpu
from caps_tpu.obs import clock
from caps_tpu.obs.compile import (CompileLedger, attributed, charge,
                                  charged, global_compile_ledger)
from caps_tpu.obs.ledger import (MemoryLedger, device_memory,
                                 snapshot_footprint)
from caps_tpu.obs.log import EventLog, SlowQueryLog
from caps_tpu.obs.metrics import MetricsRegistry
from caps_tpu.relational.updates import versioned
from caps_tpu.serve import QueryServer, ServerConfig
from caps_tpu.testing.factory import create_graph

SOCIAL = """
    CREATE (a:Person {name: 'Alice', age: 33}),
           (b:Person {name: 'Bob', age: 44}),
           (c:Person {name: 'Carol', age: 27}),
           (a)-[:KNOWS {since: 2011}]->(b),
           (b)-[:KNOWS {since: 2015}]->(c)
"""

Q_AGE = ("MATCH (p:Person) WHERE p.age > $min "
         "RETURN p.name AS n ORDER BY n")


def _session(backend="tpu"):
    return caps_tpu.local_session(backend=backend)


# -- compile ledger (unit) ---------------------------------------------------

def test_compile_ledger_first_seen_vs_recompile():
    reg = MetricsRegistry()
    led = CompileLedger(registry=reg)
    c1 = led.charge("famA", "plan", 0.5, shape="sig1")
    assert c1["first_seen"] and not c1["recompile"]
    # a different shape of the same family is NOT a re-compile
    c2 = led.charge("famA", "plan", 0.25, shape="sig2")
    assert not c2["recompile"]
    # the same (kind, shape) again IS
    c3 = led.charge("famA", "plan", 0.25, shape="sig1")
    assert c3["recompile"] and not c3["first_seen"]
    st = led.stats("famA")
    assert st["compiles"] == 3 and st["recompiles"] == 1
    assert st["total_s"] == pytest.approx(1.0)
    assert st["by_kind"]["plan"]["count"] == 3
    snap = reg.snapshot()
    assert snap["compile.events"] == 3
    assert snap["compile.recompiles"] == 1
    assert snap["compile.seconds"] == pytest.approx(1.0)
    assert snap["compile.families"] == 1
    summary = led.summary()
    assert summary["families"] == 1 and summary["events"] == 3
    assert "famA" in summary["by_family"]


def test_compile_ledger_lru_bound():
    led = CompileLedger(max_families=3)
    for i in range(5):
        led.charge(f"f{i}", "plan", 0.01)
    assert led.family_count() == 3
    assert led.families() == ["f2", "f3", "f4"]
    # touching an old survivor keeps it live past the next insert
    led.charge("f2", "plan", 0.01)
    led.charge("f9", "plan", 0.01)
    assert "f2" in led.families() and "f3" not in led.families()


def test_attributed_scope_collects_and_nests():
    led = CompileLedger()
    with attributed(led, "outer") as charges:
        charge("plan", 0.5)
        # a nested scope (subquery) re-attributes the family but shares
        # the OUTER charge list — request totals include subqueries
        with attributed(led, "inner"):
            charge("count_fused", 0.25)
    assert [c["family"] for c in charges] == ["outer", "inner"]
    assert sum(c["seconds"] for c in charges) == pytest.approx(0.75)
    assert led.seconds_for("outer") == pytest.approx(0.5)


def test_unattributed_charge_lands_in_global_ledger():
    g = global_compile_ledger()
    before = g.seconds_for("(unattributed)")
    charge("dist_join", 0.125)
    assert g.seconds_for("(unattributed)") - before == pytest.approx(0.125)


def test_charged_context_times_the_region():
    led = CompileLedger()
    with attributed(led, "f") as charges:
        with charged("count_fused", shape="s"):
            pass
    assert len(charges) == 1 and charges[0]["kind"] == "count_fused"
    assert charges[0]["seconds"] >= 0.0


# -- session integration: cold charges, warm zeros, quarantine re-compiles --

@pytest.mark.parametrize("backend", ["local", "tpu"])
def test_cold_plan_charges_and_cache_hit_charges_zero(backend):
    s = _session(backend)
    g = create_graph(s, SOCIAL)
    r1 = s.cypher_on_graph(g, Q_AGE, {"min": 30})
    assert r1.metrics["compile_s_charged"] > 0.0
    kinds = {c["kind"] for c in r1.metrics["compile_charges"]}
    assert "plan" in kinds
    if backend == "tpu":
        assert "fused_record" in kinds
    # warm path: same family, new binding — plan-cache hit (and fused
    # replay on the TPU backend) must charge ZERO compile seconds
    r2 = s.cypher_on_graph(g, Q_AGE, {"min": 40})
    assert r2.metrics["plan_cache"] == "hit"
    assert r2.metrics["compile_s_charged"] == 0.0
    assert "compile_charges" not in r2.metrics
    assert len(s.compile_ledger.families()) == 1


def test_fused_replay_zero_charge_and_quarantine_rerecord_is_recompile():
    """The satellite regression: a replayed (cache-hit) execution
    charges nothing; after the serving tier's quarantine path (plan
    cache entry + fused memos evicted) the re-execution re-records and
    the ledger counts a re-compile for that family."""
    s = _session("tpu")
    g = create_graph(s, SOCIAL)
    params = {"min": 30}
    r1 = s.cypher_on_graph(g, Q_AGE, params)
    assert any(c["kind"] == "fused_record"
               for c in r1.metrics["compile_charges"])
    replays0 = s.fused.replays
    r2 = s.cypher_on_graph(g, Q_AGE, params)
    assert s.fused.replays == replays0 + 1  # replayed, not re-recorded
    assert r2.metrics["compile_s_charged"] == 0.0
    family = s.compile_ledger.families()[0]
    assert s.compile_ledger.stats(family)["recompiles"] == 0
    # quarantine exactly what serve/server.py _quarantine evicts
    key = s._plan_cache_key(g, Q_AGE, params)
    assert s.plan_cache.quarantine(key) >= 1
    assert s.fused.forget(g, Q_AGE) >= 1
    r3 = s.cypher_on_graph(g, Q_AGE, params)
    assert r3.metrics["compile_s_charged"] > 0.0
    charges = {c["kind"]: c for c in r3.metrics["compile_charges"]}
    # same family, same shapes → every charge is a re-compile
    assert charges["plan"]["recompile"]
    assert charges["fused_record"]["recompile"]
    assert s.compile_ledger.stats(family)["recompiles"] >= 2


# -- server surfaces: stats()/health_report()/warmup_report()/telemetry -----

def test_server_compile_surfaces_and_warmup_report():
    s = _session("tpu")
    g = create_graph(s, SOCIAL)
    server = QueryServer(s, graph=g)
    try:
        assert server.run(Q_AGE, {"min": 30}).to_maps() == [
            {"n": "Alice"}, {"n": "Bob"}]
        st = server.stats()
        assert st["compile"]["families"] >= 1
        assert st["compile"]["total_s"] > 0.0
        report = server.health_report()
        assert report["compile"]["families"] >= 1
        # the opstats satellite: the item-4 re-plan signal without
        # scraping the registry
        ops = report["opstats"]
        assert ops["families"] >= 1 and ops["recorded"] >= 1
        assert "divergences" in ops
        # windowed compile seconds: the cold charge landed in-window
        assert report["window"]["compile"]["events"] >= 1
        assert report["window"]["compile"]["seconds"] > 0.0
        # warmed: every hot family compiled on this process
        warm = server.warmup_report()
        assert warm["hot_families"] >= 1
        assert warm["cold_families"] == []
        assert any(v > 0.0 for v in warm["compile_s_by_family"].values())
        # a cold start planned from an external hot-family list
        cold = server.warmup_report(families=["never-seen-family"])
        assert cold["cold_families"] == ["never-seen-family"]
        assert cold["compiled_hot_families"] == 0
    finally:
        server.shutdown()


def test_expose_text_carries_compile_and_mem_samples():
    s = _session("tpu")
    g = create_graph(s, SOCIAL)
    server = QueryServer(s, graph=g)
    try:
        server.run(Q_AGE, {"min": 30})
        text = server.metrics_text()
    finally:
        server.shutdown()
    assert "\ncompile_seconds " in text or \
        text.startswith("compile_seconds ")
    for name in ("compile_events", "compile_families",
                 "mem_plan_cache_bytes", "mem_string_pool_bytes",
                 "mem_device_bytes_in_use", "telemetry_compile_s"):
        assert f"\n{name} " in text, name


# -- memory ledger -----------------------------------------------------------

def test_memory_ledger_gauges_and_report():
    s = _session("tpu")
    g = create_graph(s, SOCIAL)
    s.cypher_on_graph(g, Q_AGE, {"min": 30})  # cache a plan, intern strings
    snap = s.metrics_snapshot()
    assert snap["mem.plan_cache_bytes"] > 0
    assert snap["mem.string_pool_bytes"] > 0
    assert snap["mem.plan_cache_bytes"] == s.plan_cache.stats()["bytes"]
    s.memory_ledger.track("g", g)
    rep = s.memory_ledger.report()
    assert rep["graphs"]["g"]["bytes"] > 0
    assert rep["tracked_graph_bytes"] == rep["graphs"]["g"]["bytes"]
    assert isinstance(rep["devices"], dict) and rep["devices"]
    # CPU fallback is honest: every device entry says whether it can
    # measure; the rollup only sums the ones that can
    for entry in rep["devices"].values():
        assert "available" in entry
    s.memory_ledger.untrack("g")
    assert s.memory_ledger.report()["graphs"] == {}


def test_device_memory_graceful_fallback():
    mem = device_memory()
    assert isinstance(mem, dict)
    for entry in mem.values():
        if not entry["available"]:
            assert "bytes_in_use" not in entry


def test_snapshot_footprint_versioned_base_delta_split():
    s = _session("tpu")
    vg = versioned(s, create_graph(s, SOCIAL))
    base = snapshot_footprint(vg)
    assert base["base_bytes"] > 0 and base["delta_bytes"] == 0
    assert base["snapshot_version"] == 0
    vg.cypher("CREATE (:Person {name:'Dave', age:52})")
    vg.cypher("MATCH (p:Person {name:'Carol'}) DETACH DELETE p")
    after = snapshot_footprint(vg)
    assert after["snapshot_version"] == 2
    assert after["delta_rows"] == vg.delta_rows() > 0
    assert after["delta_bytes"] > 0
    assert after["bytes"] == after["base_bytes"] + after["delta_bytes"]
    assert vg.delta_nbytes() == after["delta_bytes"]


def test_server_tracks_default_graph_in_memory_report():
    s = _session("tpu")
    g = create_graph(s, SOCIAL)
    server = QueryServer(s, graph=g, start=False)
    try:
        mem = server.stats()["memory"]
        assert mem["graphs"]["default"]["bytes"] > 0
        assert mem["plan_cache_bytes"] >= 0
    finally:
        server.shutdown()


# -- byte-based compaction ---------------------------------------------------

def test_compaction_threshold_bytes_triggers_fold():
    s = _session("tpu")
    vg = versioned(s, create_graph(s, SOCIAL))
    vg.cypher("CREATE (:Person {name:'Dave', age:52})")
    backlog = vg.delta_nbytes()
    assert backlog > 0
    server = QueryServer(s, graph=vg, config=ServerConfig(
        compaction_threshold_rows=None,
        compaction_threshold_bytes=max(1, backlog // 2),
        compaction_interval_s=0.005))
    try:
        assert server.compactor is not None
        assert server.compactor.threshold_rows is None
        deadline = clock.now() + 10.0
        while vg.delta_rows() > 0 and clock.now() < deadline:
            clock.sleep(0.01)
        assert vg.delta_rows() == 0, "byte-threshold compaction never ran"
        summary = server.stats()["compaction"]
        assert summary["threshold_bytes"] == max(1, backlog // 2)
        assert summary["backlog_bytes"] == 0
    finally:
        server.shutdown()


# -- structured event log ----------------------------------------------------

def test_event_log_ring_bound_filter_and_correlation():
    log = EventLog(capacity=4)
    for i in range(6):
        log.emit("tick", request_id=i, family=f"f{i % 2}")
    recs = log.records()
    assert len(recs) == 4  # bounded: oldest evicted
    assert [r["request_id"] for r in recs] == [2, 3, 4, 5]
    assert all({"event", "t", "wall", "request_id", "family"} <= set(r)
               for r in recs)
    assert [r["request_id"] for r in log.records("tick")] == [2, 3, 4, 5]
    assert log.records("nope") == []
    assert [r["family"] for r in log.for_request(4)] == ["f0"]
    assert log.emitted == 6


def test_event_log_jsonl_sinks(tmp_path):
    live = tmp_path / "live.jsonl"
    log = EventLog(capacity=8, path=str(live))
    log.emit("a", request_id=1, family="f", payload={"x": 1})
    log.emit("b", request_id=None, family=None, odd=object())
    log.close()
    lines = [json.loads(ln) for ln in
             live.read_text().strip().splitlines()]
    assert [ln["event"] for ln in lines] == ["a", "b"]
    assert lines[0]["payload"] == {"x": 1}
    assert isinstance(lines[1]["odd"], str)  # non-JSON values repr()'d
    dumped = tmp_path / "dump.jsonl"
    log.write(str(dumped))
    assert len(dumped.read_text().strip().splitlines()) == 2


def test_slow_query_log_threshold_and_event():
    events = EventLog(capacity=8)
    reg = MetricsRegistry()
    slow = SlowQueryLog(0.5, capacity=2, registry=reg, event_log=events)
    fast = {"request_id": 1, "family": "f", "latency_s": 0.1,
            "outcome": "ok"}
    assert slow.consider(fast) is False
    rec = {"request_id": 2, "family": "f", "latency_s": 0.9,
           "outcome": "ok"}
    assert slow.consider(rec, plan="Scan", operators=[{"op": "Scan"}])
    got = slow.records()[0]
    assert got["plan"] == "Scan" and got["slow_threshold_s"] == 0.5
    assert reg.snapshot()["slowlog.captured"] == 1
    assert [e["event"] for e in events.records()] == ["slow_query"]
    assert events.records()[0]["request_id"] == 2


# -- the slow-query log through the server -----------------------------------

def test_server_slow_query_capture_with_ledger():
    s = _session("tpu")
    g = create_graph(s, SOCIAL)
    server = QueryServer(s, graph=g, config=ServerConfig(
        slow_query_threshold_s=0.0))  # everything is "slow"
    try:
        h = server.submit(Q_AGE, {"min": 30})
        assert h.rows() == [{"n": "Alice"}, {"n": "Bob"}]
        # the per-request resource ledger on the handle
        ledger = h.info["ledger"]
        assert ledger["bytes_in"] > 0
        assert ledger["bytes_out"] > 0
        assert ledger["compile_s"] > 0.0  # cold execution compiled
        assert ledger["peak_rows"] >= 2
        slow = server.slow_queries()
        assert len(slow) == 1
        rec = slow[0]
        # the acceptance assertion: captured ledger fields are non-empty
        assert rec["ledger"] == ledger
        assert rec["plan"]  # relational plan text
        assert rec["operators"] and all("op" in e for e in rec["operators"])
        # mergeable with flight dumps: the slow record is a strict
        # superset of the flight recorder's record for the same request
        flight = [r for r in server.telemetry.recorder.snapshot()
                  if r["request_id"] == rec["request_id"]][0]
        assert set(flight) <= set(rec)
        assert flight["ledger"] == rec["ledger"]
        # correlated events: compile charge + slow capture for this id
        kinds = {e["event"] for e in server.event_log.for_request(
            rec["request_id"])}
        assert {"compile.charged", "slow_query"} <= kinds
        assert server.stats()["slow_queries"] == 1
    finally:
        server.shutdown()


def test_server_slow_log_disabled_and_high_threshold():
    s = _session("tpu")
    g = create_graph(s, SOCIAL)
    server = QueryServer(s, graph=g)  # no threshold: disabled
    try:
        server.run(Q_AGE, {"min": 30})
        assert server.slow_queries() == []
        assert server.stats()["slow_queries"] is None
    finally:
        server.shutdown()
    server = QueryServer(s, graph=g, config=ServerConfig(
        slow_query_threshold_s=3600.0))
    try:
        server.run(Q_AGE, {"min": 40})
        assert server.slow_queries() == []  # nothing that slow
        assert server.stats()["slow_queries"] == 0
    finally:
        server.shutdown()


def test_flight_records_always_carry_a_ledger():
    s = _session("tpu")
    g = create_graph(s, SOCIAL)
    server = QueryServer(s, graph=g)
    try:
        server.run(Q_AGE, {"min": 30})
        with pytest.raises(Exception):
            server.run("MATCH (p:Person) RETURN boom(p.name) AS x")
        recs = server.telemetry.recorder.snapshot()
        assert len(recs) == 2
        for rec in recs:
            assert {"bytes_in", "bytes_out", "compile_s",
                    "peak_rows"} <= set(rec["ledger"])
        ok = [r for r in recs if r["outcome"] == "ok"][0]
        assert ok["ledger"]["bytes_in"] > 0
    finally:
        server.shutdown()


# -- review regressions ------------------------------------------------------

def test_event_log_sink_failure_never_fails_emit(tmp_path):
    log = EventLog(capacity=4, path=str(tmp_path / "no-such-dir" / "e.jsonl"))
    rec = log.emit("tick", request_id=1, family="f")  # must not raise
    assert rec["event"] == "tick"
    assert log.sink_failed is True
    assert len(log.records()) == 1  # ring logging survives a dead sink
    log.emit("tock", request_id=2, family="f")
    assert len(log.records()) == 2


def test_server_survives_misconfigured_event_log_path(tmp_path):
    s = _session("tpu")
    g = create_graph(s, SOCIAL)
    server = QueryServer(s, graph=g, config=ServerConfig(
        slow_query_threshold_s=0.0,
        event_log_path=str(tmp_path / "missing" / "events.jsonl")))
    try:
        # the finish path emits compile.charged + slow_query: a broken
        # sink must degrade to ring-only, never fail the request
        assert server.run(Q_AGE, {"min": 30}).to_maps() == [
            {"n": "Alice"}, {"n": "Bob"}]
        assert server.event_log.sink_failed is True
        assert server.slow_queries()
    finally:
        server.shutdown()


def test_fused_record_charge_excludes_nested_build_charges():
    """Compile seconds sum the wall clock once: an inner count-fused
    build charged during a record run is subtracted from the
    fused_record charge, so the non-plan charges never exceed the
    execute phase they all live inside."""
    s = _session("tpu")
    g = create_graph(s, SOCIAL)
    r = s.cypher_on_graph(
        g, "MATCH (a:Person)-[:KNOWS]->(b:Person) RETURN count(*) AS c")
    assert r.to_maps() == [{"c": 2}]
    charges = r.metrics.get("compile_charges") or []
    kinds = {c["kind"] for c in charges}
    assert "fused_record" in kinds
    non_plan = sum(c["seconds"] for c in charges if c["kind"] != "plan")
    assert non_plan <= r.metrics["execute_s"] + 1e-6, charges


def test_shutdown_releases_graph_tracking_unless_replaced():
    s = _session("tpu")
    g1 = create_graph(s, SOCIAL)
    g2 = create_graph(s, "CREATE (:Person {name:'Zoe', age:9})")
    a = QueryServer(s, graph=g1, start=False)
    assert s.memory_ledger.report()["graphs"]["default"]["bytes"] > 0
    a.shutdown()
    assert "default" not in s.memory_ledger.report()["graphs"]
    # a newer server's slot survives the OLD server's (second) shutdown
    a2 = QueryServer(s, graph=g1, start=False)
    b = QueryServer(s, graph=g2, start=False)  # replaces the slot
    a2.shutdown()
    assert "default" in s.memory_ledger.report()["graphs"]
    b.shutdown()
    assert "default" not in s.memory_ledger.report()["graphs"]


def test_shape_eviction_is_flagged_not_silent():
    led = CompileLedger(max_shapes=2)
    for i in range(3):
        led.charge("fam", "plan", 0.01, shape=f"s{i}")
    st = led.stats("fam")
    assert st["shapes_evicted"] is True
    # a re-charge of the EVICTED shape cannot be told from a first
    # compile — the flag (and the summary bound marker) says so
    assert led.charge("fam", "plan", 0.01, shape="s0")["recompile"] is False
    assert led.summary()["recompiles_lower_bound"] is True
    led2 = CompileLedger()
    led2.charge("f", "plan", 0.01, shape="x")
    assert led2.summary()["recompiles_lower_bound"] is False

import pytest

from caps_tpu.frontend.lexer import (
    EOF, FLOAT, IDENT, INT, KEYWORD, STRING, SYM, CypherSyntaxError, tokenize,
)


def kinds(q):
    return [(t.kind, t.text) for t in tokenize(q)[:-1]]


def test_keywords_case_insensitive():
    assert kinds("match RETURN Where") == [
        (KEYWORD, "MATCH"), (KEYWORD, "RETURN"), (KEYWORD, "WHERE")]


def test_identifiers_and_backticks():
    assert kinds("foo `weird name` _x1") == [
        (IDENT, "foo"), (IDENT, "weird name"), (IDENT, "_x1")]


def test_numbers():
    toks = tokenize("42 3.14 1e3 0x1F")
    assert [(t.kind, t.value) for t in toks[:-1]] == [
        (INT, 42), (FLOAT, 3.14), (FLOAT, 1000.0), (INT, 31)]


def test_leading_dot_float_in_expression_context():
    toks = tokenize("(.5)")
    assert [(t.kind, t.value) for t in toks[:-1]] == [
        (SYM, "("), (FLOAT, 0.5), (SYM, ")")]


def test_range_vs_float():
    toks = tokenize("[*1..3]")
    assert [(t.kind, t.text) for t in toks[:-1]] == [
        (SYM, "["), (SYM, "*"), (INT, "1"), (SYM, ".."), (INT, "3"), (SYM, "]")]


def test_property_access_not_float():
    toks = tokenize("a.5")  # not valid cypher but lexer must not merge
    assert toks[0].kind == IDENT


def test_strings_and_escapes():
    toks = tokenize(r"'it\'s' " + '"two\\nlines"')
    assert toks[0].value == "it's"
    assert toks[1].value == "two\nlines"


def test_comments_stripped():
    assert kinds("a // line\n b /* block */ c") == [
        (IDENT, "a"), (IDENT, "b"), (IDENT, "c")]


def test_multichar_symbols():
    assert [t.text for t in tokenize("<= >= <> =~ -> <- ..")[:-1]] == [
        "<=", ">=", "<>", "=~", "->", "<-", ".."]


def test_arrows_in_pattern():
    assert [t.text for t in tokenize("(a)-[r]->(b)")[:-1]] == [
        "(", "a", ")", "-", "[", "r", "]", "->", "(", "b", ")"]


def test_unterminated_string_raises():
    with pytest.raises(CypherSyntaxError):
        tokenize("'oops")


def test_eof_token():
    assert tokenize("")[-1].kind == EOF

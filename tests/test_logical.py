import pytest

from caps_tpu.frontend.parser import parse_query
from caps_tpu.ir import exprs as E
from caps_tpu.ir.builder import IRBuilder
from caps_tpu.ir.pattern import Direction
from caps_tpu.logical import ops as L
from caps_tpu.logical.optimizer import LogicalOptimizer
from caps_tpu.logical.planner import LogicalPlanner, LogicalPlanningError
from caps_tpu.okapi.schema import Schema
from caps_tpu.okapi.types import CTInteger, CTNode, CTString


def social_schema():
    return (Schema.empty()
            .with_node_property_keys(["Person"], {"name": CTString, "age": CTInteger})
            .with_relationship_property_keys("KNOWS", {"since": CTInteger}))


def plan(query, optimize=False, **params):
    schema = social_schema()
    ir = IRBuilder(schema, parameters=params).process(parse_query(query))
    p = LogicalPlanner(schema, parameters=params).process(ir)
    if optimize:
        p = LogicalOptimizer().process(p)
    return p


def chain(plan_):
    """Linearize a single-input op chain from root down."""
    out = []
    op = plan_.root
    while op is not None:
        out.append(op)
        kids = [c for c in op.children if isinstance(c, L.LogicalOperator)]
        op = kids[0] if kids else None
    return out


def test_single_scan_plan():
    p = plan("MATCH (a:Person) RETURN a.name AS name")
    ops = chain(p)
    assert [type(o) for o in ops] == [L.Select, L.Project, L.NodeScan, L.Start]
    scan = ops[2]
    assert scan.var == "a" and scan.labels == frozenset({"Person"})
    assert p.result_fields == ("name",)


def test_expand_plan():
    p = plan("MATCH (a:Person)-[r:KNOWS]->(b:Person) RETURN b.name AS n")
    ops = chain(p)
    expand = next(o for o in ops if isinstance(o, L.Expand))
    assert expand.source == "a" and expand.target == "b"
    assert expand.rel_types == ("KNOWS",)
    assert expand.direction == Direction.OUTGOING
    assert not expand.into
    assert ("r", ) [0] in expand.field_names and "b" in expand.field_names


def test_expand_into_for_cycle():
    p = plan("MATCH (a)-[r:KNOWS]->(b)-[s:KNOWS]->(a) RETURN a")
    expands = [o for o in plan_ops(p) if isinstance(o, L.Expand)]
    assert len(expands) == 2
    assert any(e.into for e in expands)


def plan_ops(p):
    return [o for o in p.root.walk() if isinstance(o, L.LogicalOperator)]


def test_reverse_expand_when_only_target_bound():
    # b is scanned first (appears in connection), a reached via incoming.
    p = plan("MATCH (a)-[r:KNOWS]->(b:Person) WHERE b.age > 30 RETURN a")
    expands = [o for o in plan_ops(p) if isinstance(o, L.Expand)]
    assert len(expands) == 1
    e = expands[0]
    # planner picks either endpoint first; both orientations are legal
    assert (e.source, e.target, e.direction) in (
        ("a", "b", Direction.OUTGOING), ("b", "a", Direction.INCOMING))


def test_disconnected_patterns_cartesian():
    p = plan("MATCH (a:Person), (b:Person) RETURN a, b")
    assert any(isinstance(o, L.CartesianProduct) for o in plan_ops(p))


def test_optional_match():
    p = plan("MATCH (a:Person) OPTIONAL MATCH (a)-[r:KNOWS]->(b) RETURN a, b")
    opt = next(o for o in plan_ops(p) if isinstance(o, L.Optional))
    assert isinstance(opt.lhs, L.NodeScan)
    assert any(isinstance(o, L.Expand) for o in opt.rhs.walk())


def test_var_length_plan():
    p = plan("MATCH (a)-[rs:KNOWS*1..3]->(b) RETURN b")
    vle = next(o for o in plan_ops(p) if isinstance(o, L.BoundedVarLengthExpand))
    assert vle.lower == 1 and vle.upper == 3


def test_aggregation_plan():
    p = plan("MATCH (a:Person) RETURN a.name AS name, count(*) AS c")
    agg = next(o for o in plan_ops(p) if isinstance(o, L.Aggregate))
    assert agg.group[0][0] == "name"
    assert agg.aggregations[0][0] == "c"
    assert dict(agg.fields)["c"] == CTInteger


def test_order_skip_limit_plan():
    p = plan("MATCH (a:Person) RETURN a.age AS age ORDER BY age DESC SKIP 1 LIMIT 2")
    types = [type(o) for o in chain(p)]
    assert types[:4] == [L.Limit, L.Skip, L.OrderBy, L.Select]


def test_union_plan():
    p = plan("RETURN 1 AS v UNION RETURN 2 AS v")
    assert isinstance(p.root, L.Distinct)
    assert isinstance(p.root.parent, L.TabularUnionAll)


def test_unwind_plan():
    p = plan("UNWIND [1,2] AS x RETURN x")
    u = next(o for o in plan_ops(p) if isinstance(o, L.Unwind))
    assert dict(u.fields)["x"] == CTInteger


def test_label_pushdown_into_scan():
    p = plan("MATCH (a) WHERE a:Person RETURN a", optimize=True)
    ops = plan_ops(p)
    assert not any(isinstance(o, L.Filter) for o in ops)
    scan = next(o for o in ops if isinstance(o, L.NodeScan))
    assert scan.labels == frozenset({"Person"})


def test_filter_pushdown_below_expand():
    p = plan("MATCH (a:Person)-[r:KNOWS]->(b) WHERE a.age > 30 RETURN b",
             optimize=True)
    ops = chain(p)
    # the filter on a must sit below the expand, right above the scan
    fi = next(i for i, o in enumerate(ops) if isinstance(o, L.Filter))
    ei = next(i for i, o in enumerate(ops) if isinstance(o, L.Expand))
    assert fi > ei  # deeper in the chain == later in list


def test_filter_on_rel_stays_above_expand():
    p = plan("MATCH (a)-[r:KNOWS]->(b) WHERE r.since > 2000 RETURN b",
             optimize=True)
    ops = chain(p)
    fi = next(i for i, o in enumerate(ops) if isinstance(o, L.Filter))
    ei = next(i for i, o in enumerate(ops) if isinstance(o, L.Expand))
    assert fi < ei


def test_optional_without_binding_plans_against_unit_row():
    # openCypher: a leading OPTIONAL MATCH left-joins the single unit
    # driving row, yielding one all-null row when nothing matches.
    out = plan("OPTIONAL MATCH (a) RETURN a")
    assert "Optional" in out.pretty()

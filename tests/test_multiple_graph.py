"""Multiple-graph queries: FROM GRAPH, CONSTRUCT, RETURN GRAPH,
CATALOG CREATE GRAPH, fs data source round-trip, federated MATCH
(benchmark config 5)."""
import pytest

from caps_tpu.backends.local.session import LocalCypherSession
from caps_tpu.backends.tpu.session import TPUCypherSession
from caps_tpu.io.fs import FSGraphSource
from caps_tpu.okapi.graph import Namespace
from caps_tpu.testing.bag import Bag
from caps_tpu.testing.factory import create_graph


@pytest.fixture(params=["local", "tpu"])
def session(request):
    return (LocalCypherSession() if request.param == "local"
            else TPUCypherSession())


def test_from_graph_switches_graph(session):
    g1 = create_graph(session, "CREATE (:A {v: 1})")
    g2 = create_graph(session, "CREATE (:A {v: 2})")
    session.catalog.store("g1", g1)
    session.catalog.store("g2", g2)
    rows = session.cypher(
        "FROM GRAPH session.g1 MATCH (n:A) RETURN n.v AS v").records.to_maps()
    assert rows == [{"v": 1}]
    rows = session.cypher(
        "FROM GRAPH session.g2 MATCH (n:A) RETURN n.v AS v").records.to_maps()
    assert rows == [{"v": 2}]


def test_union_branches_use_own_graphs(session):
    g1 = create_graph(session, "CREATE (:A {v: 'g1'})")
    g2 = create_graph(session, "CREATE (:A {v: 'g2'})")
    session.catalog.store("g1", g1)
    session.catalog.store("g2", g2)
    rows = session.cypher(
        "FROM GRAPH session.g1 MATCH (n:A) RETURN n.v AS v "
        "UNION ALL FROM GRAPH session.g2 MATCH (m:A) RETURN m.v AS v"
    ).records.to_maps()
    assert Bag(rows) == [{"v": "g1"}, {"v": "g2"}]


def test_construct_new_graph(session):
    g = create_graph(session, "CREATE (:Person {name: 'Alice'}), "
                              "(:Person {name: 'Bob'})")
    result = g.cypher(
        "MATCH (p:Person) CONSTRUCT NEW (:Copy {name: p.name}) RETURN GRAPH")
    out = result.graph
    assert out is not None
    rows = out.cypher("MATCH (c:Copy) RETURN c.name AS n").records.to_maps()
    assert Bag(rows) == [{"n": "Alice"}, {"n": "Bob"}]


def test_construct_clone_and_new_edge(session):
    g = create_graph(session, "CREATE (:P {name: 'a'}), (:P {name: 'b'})")
    result = g.cypher(
        "MATCH (p:P) CONSTRUCT CLONE p NEW (p)-[:TAGGED]->(:Tag {of: p.name}) "
        "RETURN GRAPH")
    out = result.graph
    rows = out.cypher("MATCH (p:P)-[:TAGGED]->(t:Tag) "
                      "RETURN p.name AS p, t.of AS t").records.to_maps()
    assert Bag(rows) == [{"p": "a", "t": "a"}, {"p": "b", "t": "b"}]


def test_construct_on_unions_with_base_graph(session):
    base = create_graph(session, "CREATE (:X {v: 1})")
    session.catalog.store("base", base)
    g = create_graph(session, "CREATE (:Y {v: 2})")
    result = g.cypher(
        "MATCH (y:Y) CONSTRUCT ON session.base NEW (:Z {v: y.v}) RETURN GRAPH")
    out = result.graph
    rows = out.cypher("MATCH (n) RETURN labels(n) AS l, n.v AS v").records.to_maps()
    assert Bag(rows) == [{"l": ["X"], "v": 1}, {"l": ["Z"], "v": 2}]


def test_construct_set(session):
    g = create_graph(session, "CREATE (:P {name: 'a'})")
    out = g.cypher("MATCH (p:P) CONSTRUCT CLONE p SET p.seen = true "
                   "SET p:Checked RETURN GRAPH").graph
    rows = out.cypher("MATCH (p:Checked) RETURN p.name AS n, p.seen AS s"
                      ).records.to_maps()
    assert rows == [{"n": "a", "s": True}]


def test_catalog_create_graph(session):
    g = create_graph(session, "CREATE (:A {v: 1})-[:R]->(:B {v: 2})")
    session.catalog.store("src", g)
    session.cypher(
        "CATALOG CREATE GRAPH session.snapshot { FROM GRAPH session.src "
        "MATCH (a:A)-[r:R]->(b:B) CONSTRUCT CLONE a, b NEW (a)-[:R2]->(b) "
        "RETURN GRAPH }")
    snap = session.catalog.graph("session.snapshot")
    rows = snap.cypher("MATCH (a)-[:R2]->(b) RETURN a.v AS a, b.v AS b"
                       ).records.to_maps()
    assert rows == [{"a": 1, "b": 2}]


def test_return_graph_of_from_graph(session):
    g = create_graph(session, "CREATE (:A {v: 7})")
    session.catalog.store("g", g)
    out = session.cypher("FROM GRAPH session.g RETURN GRAPH").graph
    rows = out.cypher("MATCH (n:A) RETURN n.v AS v").records.to_maps()
    assert rows == [{"v": 7}]


@pytest.mark.parametrize("fmt", ["parquet", "csv", "orc"])
def test_fs_roundtrip(session, tmp_path, fmt):
    src = FSGraphSource(session, str(tmp_path), fmt=fmt)
    session.catalog.register_source(Namespace("fs"), src)
    g = create_graph(session,
                     "CREATE (a:Person {name: 'Alice', age: 23})"
                     "-[:KNOWS {since: 2020}]->(b:Person:Admin {name: 'Bob'})")
    session.catalog.store("fs.people", g)
    # read back through the catalog
    g2 = session.catalog.graph("fs.people")
    assert g2.schema == g.schema
    rows = g2.cypher("MATCH (a:Person)-[k:KNOWS]->(b:Admin) "
                     "RETURN a.name AS a, k.since AS s, b.name AS b"
                     ).records.to_maps()
    assert rows == [{"a": "Alice", "s": 2020, "b": "Bob"}]


def test_federated_match_across_sources(session, tmp_path):
    """Config 5: a query touching graphs from two data sources."""
    src = FSGraphSource(session, str(tmp_path))
    session.catalog.register_source(Namespace("fs"), src)
    products = create_graph(session, "CREATE (:Product {sku: 1, name: 'x'})")
    session.catalog.store("fs.products", products)
    customers = create_graph(session, "CREATE (:Customer {name: 'c', wants: 1})")
    session.catalog.store("customers", customers)

    rows = session.cypher(
        "FROM GRAPH session.customers MATCH (c:Customer) "
        "WITH c.name AS cname, c.wants AS sku "
        "FROM GRAPH fs.products MATCH (p:Product) WHERE p.sku = sku "
        "RETURN cname, p.name AS product").records.to_maps()
    assert rows == [{"cname": "c", "product": "x"}]


def test_graph_union_all(session):
    g1 = create_graph(session, "CREATE (:A {v: 1})")
    g2 = create_graph(session, "CREATE (:B {v: 2})")
    u = g1.union_all(g2)
    rows = u.cypher("MATCH (n) RETURN n.v AS v").records.to_maps()
    assert Bag(rows) == [{"v": 1}, {"v": 2}]


def test_construct_on_set_clone_replaces_original(session):
    """SET on a clone of an ON-graph entity must not leave a duplicate id
    in the union: the modified copy replaces the original (overlay)."""
    base = create_graph(session, "CREATE (:A {v: 1})-[:R]->(:A {v: 2})")
    session.catalog.store("base", base)
    out = session.cypher(
        "FROM GRAPH session.base MATCH (x:A) "
        "CONSTRUCT ON session.base CLONE x SET x.flag = true "
        "RETURN GRAPH").graph
    rows = out.cypher("MATCH (n:A) RETURN n.v AS v, n.flag AS f"
                      ).records.to_maps()
    assert Bag(rows) == [{"v": 1, "f": True}, {"v": 2, "f": True}]
    # relationships from the ON graph survive the overlay
    rels = out.cypher("MATCH (:A)-[r:R]->(:A) RETURN count(*) AS c"
                      ).records.to_maps()
    assert rels == [{"c": 1}]


@pytest.mark.parametrize("fmt", ["parquet", "csv", "orc"])
def test_fs_roundtrip_label_with_underscore(session, tmp_path, fmt):
    src = FSGraphSource(session, str(tmp_path), fmt=fmt)
    session.catalog.register_source(Namespace("fsu"), src)
    g = create_graph(session,
                     "CREATE (:My_Label {v: 1})-[:HAS_PART]->(:Other {v: 2})")
    session.catalog.store("fsu.g", g)
    loaded = session.catalog.graph("fsu.g")
    rows = loaded.cypher("MATCH (n:My_Label) RETURN n.v AS v"
                         ).records.to_maps()
    assert rows == [{"v": 1}]
    rels = loaded.cypher(
        "MATCH (:My_Label)-[r:HAS_PART]->(m) RETURN m.v AS v"
        ).records.to_maps()
    assert rels == [{"v": 2}]


def test_fs_orc_all_null_property(session, tmp_path):
    """ORC has no null type: an all-null property column must still
    round-trip (stored as null strings)."""
    src = FSGraphSource(session, str(tmp_path), fmt="orc")
    session.catalog.register_source(Namespace("fso"), src)
    g = create_graph(session, "CREATE (:P {x: 1}), (:P)")
    session.catalog.store("fso.g", g)
    loaded = session.catalog.graph("fso.g")
    rows = loaded.cypher("MATCH (n:P) RETURN n.x AS x").records.to_maps()
    assert Bag(rows) == [{"x": 1}, {"x": None}]


def test_fs_no_combo_collision(session, tmp_path):
    """('A_B',) and ('A','B') must store to distinct directories."""
    src = FSGraphSource(session, str(tmp_path), fmt="parquet")
    session.catalog.register_source(Namespace("fsc"), src)
    g = create_graph(session, "CREATE (:A_B {v: 1}), (n:A:B {v: 2})")
    session.catalog.store("fsc.g", g)
    loaded = session.catalog.graph("fsc.g")
    assert loaded.cypher("MATCH (n:A_B) RETURN n.v AS v"
                         ).records.to_maps() == [{"v": 1}]
    assert loaded.cypher("MATCH (n:A:B) RETURN n.v AS v"
                         ).records.to_maps() == [{"v": 2}]


@pytest.mark.parametrize("session_cls", [LocalCypherSession, TPUCypherSession])
def test_union_branches_rehydrate_from_their_own_graph(session_cls):
    """Round-5 review finding: entity access inside list expressions must
    resolve against the graph each UNION branch matched, not the planner's
    final current graph."""
    from caps_tpu.okapi.graph import QualifiedGraphName
    s = session_cls()
    g1 = create_graph(s, "CREATE (:A {v: 'g1'})")
    g2 = create_graph(s, "CREATE (:A {v: 'g2'})")
    s.catalog.store(QualifiedGraphName.parse("session.g1"), g1)
    s.catalog.store(QualifiedGraphName.parse("session.g2"), g2)
    r = s.cypher(
        "FROM GRAPH session.g1 MATCH (n:A) RETURN [x IN [n] | x.v] AS v "
        "UNION ALL "
        "FROM GRAPH session.g2 MATCH (m:A) RETURN [x IN [m] | x.v] AS v")
    assert Bag(r.to_maps()) == Bag([{"v": ["g1"]}, {"v": ["g2"]}])

"""Native host runtime (native/csrc/host_runtime.cpp): differential tests of the
C++ string pool / ingest / CSR against the pure-Python implementations
(SURVEY.md §2 native components — each native path keeps a Python twin)."""
import numpy as np
import pytest

from caps_tpu import native
from caps_tpu.backends.tpu.pool import NativeStringPool, StringPool

pytestmark = pytest.mark.skipif(not native.available(),
                                reason=f"no native lib: {native.build_error}")


VALUES = ["b", "a", None, "b", "", "ü", "a" * 100, None, "z"]


def test_pool_differential():
    py, nat = StringPool(), NativeStringPool()
    pc = py.encode_many(VALUES)
    nc = nat.encode_many(VALUES)
    np.testing.assert_array_equal(pc, nc)
    assert len(py) == len(nat)
    assert py.decode_many(pc) == nat.decode_many(nc) == [
        v for v in VALUES]
    np.testing.assert_array_equal(py.rank_array(), nat.rank_array())


def test_pool_single_encode_roundtrip():
    nat = NativeStringPool()
    a = nat.encode("x")
    assert nat.encode("x") == a
    assert nat.encode(None) == -1
    assert nat.decode(a) == "x"
    assert nat.decode(-1) is None


def test_pool_luts_match():
    py, nat = StringPool(), NativeStringPool()
    words = ["Apple", "apricot", "Banana", "avocado"]
    py.encode_many(words)
    nat.encode_many(words)
    np.testing.assert_array_equal(py.starts_with_lut("a"),
                                  nat.starts_with_lut("a"))
    np.testing.assert_array_equal(py.contains_lut("an"),
                                  nat.contains_lut("an"))
    np.testing.assert_array_equal(
        py.map_lut("upper", str.upper), nat.map_lut("upper", str.upper))
    assert py.decode_many(py.map_lut("upper", str.upper)) == \
        nat.decode_many(nat.map_lut("upper", str.upper))


def test_ingest_i64():
    d, v = native.lib.ingest_i64([1, None, -5, 2**40, True])
    np.testing.assert_array_equal(np.frombuffer(d, np.int64),
                                  [1, 0, -5, 2**40, 1])
    np.testing.assert_array_equal(np.frombuffer(v, np.uint8),
                                  [1, 0, 1, 1, 1])


def test_ingest_f64_and_bool():
    d, v = native.lib.ingest_f64([1.5, None, 2])
    np.testing.assert_allclose(np.frombuffer(d, np.float64), [1.5, 0.0, 2.0])
    d2, v2 = native.lib.ingest_bool([True, False, None, 1])
    np.testing.assert_array_equal(np.frombuffer(d2, np.uint8), [1, 0, 0, 1])
    np.testing.assert_array_equal(np.frombuffer(v2, np.uint8), [1, 1, 0, 1])


def test_ingest_rejects_bad_values():
    with pytest.raises(TypeError):
        native.lib.ingest_i64([1, "nope"])


def test_csr_build_matches_numpy():
    rng = np.random.RandomState(0)
    n_nodes, n_edges = 50, 400
    src = rng.randint(0, n_nodes, n_edges).astype(np.int64)
    dst = rng.randint(0, n_nodes, n_edges).astype(np.int64)
    off_b, perm_b = native.lib.csr_build(src.tobytes(), n_edges, n_nodes)
    off = np.frombuffer(off_b, np.int64)
    perm = np.frombuffer(perm_b, np.int64)
    # offsets = prefix histogram of sources
    np.testing.assert_array_equal(
        off, np.concatenate([[0], np.cumsum(np.bincount(src, minlength=n_nodes))]))
    # perm groups edges by source, stable within a source
    assert sorted(perm) == list(range(n_edges))
    np.testing.assert_array_equal(src[perm], np.sort(src, kind="stable"))
    order = np.argsort(src, kind="stable")
    np.testing.assert_array_equal(perm, order)


def test_csr_build_rejects_out_of_range():
    src = np.array([0, 9], np.int64)
    with pytest.raises(ValueError):
        native.lib.csr_build(src.tobytes(), 2, 5)


def test_ingest_i64_rejects_nonfinite_floats():
    # parity with int(v): NaN/inf raise instead of storing garbage
    for bad in (float("nan"), float("inf"), float("-inf")):
        with pytest.raises((TypeError, ValueError, OverflowError)):
            native.lib.ingest_i64([1, bad])
    d, v = native.lib.ingest_i64([1, 2.0])  # finite floats still tolerated
    np.testing.assert_array_equal(np.frombuffer(d, np.int64), [1, 2])


def test_make_column_native_matches_python(make_session, monkeypatch):
    """Whole-table ingest parity: native on vs off."""
    from caps_tpu.okapi.types import CTBoolean, CTFloat, CTInteger, CTString
    data = {"i": [1, None, 3], "f": [1.5, None, -2.0],
            "b": [True, None, False], "s": ["x", None, "y"]}
    types = {"i": CTInteger, "f": CTFloat, "b": CTBoolean, "s": CTString}
    s1 = make_session("tpu")
    rows1 = s1.table_factory.from_columns(data, types).rows()
    monkeypatch.setattr(native, "lib", None)
    s2 = make_session("tpu")
    rows2 = s2.table_factory.from_columns(data, types).rows()
    assert rows1 == rows2

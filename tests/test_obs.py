"""Observability subsystem tests (caps_tpu/obs/ — ISSUE 3).

Covers: EXPLAIN plans without executing (poisoned scan hook), PROFILE
row counts match actual result cardinalities on the local and TPU
backends (plan-cache hits and fused replay included), PROFILE through a
plan-cache hit reports plan-phase time 0 and never poisons the cache
key, disabled-tracer overhead is bounded, the metrics registry /
snapshot API, the span exporters, and the collective instrumentation.
"""
from __future__ import annotations

import json
import os

import pytest

from caps_tpu import obs
from caps_tpu.obs import clock
from caps_tpu.obs.metrics import MetricsRegistry, diff_snapshots
from caps_tpu.obs.tracer import NULL_SPAN, Tracer
from caps_tpu.testing.factory import create_graph

CREATE = """
    CREATE (a:Person {name: 'Ada', age: 30}),
           (b:Person {name: 'Bo', age: 40}),
           (c:Person {name: 'Cy', age: 50}),
           (a)-[:KNOWS]->(b), (b)-[:KNOWS]->(c), (a)-[:KNOWS]->(c)
"""
Q = ("MATCH (a:Person)-[:KNOWS]->(b) WHERE a.age > $min "
     "RETURN a.name AS a, b.name AS b ORDER BY a, b")


# -- EXPLAIN ----------------------------------------------------------------

@pytest.mark.parametrize("backend", ["local", "tpu"])
def test_explain_executes_nothing(make_session, backend, monkeypatch):
    session = make_session(backend)
    graph = create_graph(session, CREATE)

    # poison every execution entry point: any operator compute during
    # EXPLAIN means the plan executed
    from caps_tpu.relational import ops as R

    def poisoned(self):
        raise AssertionError("EXPLAIN must not execute operators")

    monkeypatch.setattr(R.ScanOp, "_compute", poisoned)
    monkeypatch.setattr(R.StartOp, "_compute", poisoned)

    res = graph.cypher("EXPLAIN " + Q, {"min": 0})
    assert res.records is None
    assert res.metrics["mode"] == "explain"
    for phase in ("ir", "logical", "relational"):
        assert phase in res.plans and res.plans[phase]
    assert "Scan" in res.plans["relational"]
    assert "=== RELATIONAL ===" in res.explain()


def test_explain_catalog_statements_do_not_mutate(make_session):
    session = make_session("local")
    graph = create_graph(session, CREATE)
    version0 = session.catalog.version
    res = graph.cypher(
        "EXPLAIN CATALOG CREATE GRAPH session.obs_explain { "
        "MATCH (n:Person) CONSTRUCT CLONE n RETURN GRAPH }")
    assert res.records is None
    # nothing stored, nothing evicted: the catalog fingerprint is unchanged
    assert session.catalog.version == version0
    with pytest.raises(Exception):
        session.cypher("FROM GRAPH session.obs_explain MATCH (n) "
                       "RETURN count(*) AS c")


# -- PROFILE ----------------------------------------------------------------

@pytest.mark.parametrize("backend", ["local", "tpu", "sharded"])
def test_profile_rows_match_cardinality(make_session, backend):
    session = make_session(backend)
    graph = create_graph(session, CREATE)
    res = graph.cypher("PROFILE " + Q, {"min": 35})
    rows = res.records.to_maps()
    assert rows == [{"a": "Bo", "b": "Cy"}]
    assert res.metrics["mode"] == "profile"
    assert res.profile is not None
    assert res.profile["rows"] == len(rows)
    # every executed node carries measurements
    def walk(node):
        yield node
        for c in node["children"]:
            yield from walk(c)
    executed = [n for n in walk(res.profile) if n["executed"]]
    assert executed, res.profile
    for n in executed:
        assert n["seconds"] >= 0.0 and n["rows"] >= 0
    # rendered tree rides the plans dict / explain()
    assert "rows=" in res.plans["profile"]
    assert "=== PROFILE ===" in res.explain()


def test_profile_fused_replay_rows_exact(make_session):
    """TPU path: PROFILE through fused replay (exact and generic) still
    reports the actual result cardinality, and labels the run mode."""
    session = make_session("tpu")
    graph = create_graph(session, CREATE)
    for min_age in (35, 25, 35):  # converge recordings / generic stream
        graph.cypher(Q, {"min": min_age})
    res = graph.cypher("PROFILE " + Q, {"min": 25})
    rows = res.records.to_maps()
    assert len(rows) == 3
    assert res.profile["rows"] == len(rows)
    assert res.metrics["fused_mode"] in ("record", "replay", "replay_gen",
                                         "eager")
    assert res.profile.get("timing") in ("device", "dispatch", "host")


def test_profile_aggregate_replay_span(make_session):
    """With per-op sync off, replayed PROFILE runs report device time as
    ONE per-replay aggregate and tag per-op numbers as dispatch-only —
    never silently wrong."""
    from caps_tpu.backends.tpu.session import TPUCypherSession
    from caps_tpu.okapi.config import EngineConfig
    session = TPUCypherSession(config=EngineConfig(
        profile_sync_each_op=False))
    graph = create_graph(session, CREATE)
    for _ in range(2):
        graph.cypher(Q, {"min": 25})
    res = graph.cypher("PROFILE " + Q, {"min": 25})
    assert res.metrics["fused_mode"] in ("replay", "replay_gen")
    assert res.profile["timing"] == "dispatch"
    assert res.metrics["replay_device_s"] >= 0.0
    assert res.profile["rows"] == len(res.records.to_maps())


@pytest.mark.parametrize("backend", ["local", "tpu"])
def test_profile_plan_cache_hit_not_poisoned(make_session, backend):
    session = make_session(backend)
    graph = create_graph(session, CREATE)
    r1 = graph.cypher(Q, {"min": 35})
    assert r1.metrics["plan_cache"] == "miss"
    entries = session.plan_cache.stats()["entries"]

    # PROFILE hits the SAME entry (prefix stripped before the key)...
    res = graph.cypher("PROFILE " + Q, {"min": 45})
    assert res.metrics["plan_cache"] == "hit"
    # ...reports plan-phase time 0 (nothing was re-planned)...
    assert res.metrics["parse_s"] == 0.0
    assert res.metrics["plan_s"] == 0.0
    assert res.metrics["relational_s"] == 0.0
    assert res.profile["rows"] == len(res.records.to_maps())
    # ...and stores no extra entry under a PROFILE-flavored key
    assert session.plan_cache.stats()["entries"] == entries

    # later plain runs are unaffected: still a hit, no profile leakage
    r3 = graph.cypher(Q, {"min": 35})
    assert r3.metrics["plan_cache"] == "hit"
    assert "profile" not in r3.plans and r3.profile is None


def test_profile_and_plain_queries_agree(make_session):
    session = make_session("local")
    graph = create_graph(session, CREATE)
    plain = graph.cypher(Q, {"min": 0}).records.to_maps()
    profiled = graph.cypher("PROFILE " + Q, {"min": 0}).records.to_maps()
    assert plain == profiled


# -- query_mode / frontend ---------------------------------------------------

def test_query_mode_stripping():
    from caps_tpu.frontend.parser import parse_query, query_mode
    assert query_mode("MATCH (n) RETURN n") == (None, "MATCH (n) RETURN n")
    mode, body = query_mode("  explain MATCH (n) RETURN n")
    assert mode == "explain" and body == "MATCH (n) RETURN n"
    mode, body = query_mode("/* c */ PROFILE\nMATCH (n) RETURN n")
    assert mode == "profile" and body == "MATCH (n) RETURN n"
    # prefixed text parses (prepare() validates the full string)
    parse_query("PROFILE MATCH (n) RETURN n")
    parse_query("EXPLAIN MATCH (n) RETURN n")
    # unlexable text passes through for the parser to report
    assert query_mode("MATCH 'unterminated")[0] is None


def test_prepared_profile(make_session):
    session = make_session("local")
    graph = create_graph(session, CREATE)
    prep = graph.prepare("PROFILE " + Q)
    res = prep.run({"min": 35})
    assert res.metrics["mode"] == "profile"
    assert res.profile["rows"] == len(res.records.to_maps())


# -- overhead ---------------------------------------------------------------

def test_disabled_tracer_overhead_bounded(make_session):
    """The disabled path must be a shared no-op span (one enabled check,
    no allocation) and must record nothing across a repeated query."""
    tr = Tracer(enabled=False)
    assert tr.span("x") is NULL_SPAN
    assert tr.span("y", kind="operator") is NULL_SPAN
    # the disabled call itself is cheap: 100k spans well under a second
    t0 = clock.now()
    for _ in range(100_000):
        with tr.span("hot"):
            pass
    assert clock.now() - t0 < 1.0
    assert tr.spans == [] and tr.dropped == 0

    session = make_session("local")
    graph = create_graph(session, CREATE)
    for _ in range(5):
        graph.cypher(Q, {"min": 25})
    assert session.tracer.enabled is False
    assert session.tracer.spans == []


# -- metrics registry / snapshots -------------------------------------------

def test_metrics_registry_instruments():
    reg = MetricsRegistry()
    reg.counter("c").inc()
    reg.counter("c").inc(2)
    reg.gauge("g").set(7)
    reg.gauge("live", fn=lambda: 42)
    reg.observe("h", 0.5)
    reg.observe("h", 1.5)
    snap = reg.snapshot()
    assert snap["c"] == 3 and snap["g"] == 7 and snap["live"] == 42
    assert snap["h.count"] == 2 and snap["h.sum"] == 2.0
    assert snap["h.min"] == 0.5 and snap["h.max"] == 1.5

    d = diff_snapshots({"c": 1, "x": 5}, {"c": 3, "y": 2, "s": "str"})
    assert d["c"] == 2 and d["y"] == 2 and d["s"] == "str"


def test_session_metrics_snapshot_absorbs_scattered_stats(make_session):
    session = make_session("tpu")
    graph = create_graph(session, CREATE)
    snap0 = session.metrics_snapshot()
    graph.cypher(Q, {"min": 25})
    graph.cypher(Q, {"min": 35})
    delta = diff_snapshots(snap0, session.metrics_snapshot())
    assert delta["plan_cache.misses"] == 1
    assert delta["plan_cache.hits"] == 1
    assert delta["query.execute_s.count"] == 2
    # the device/fused counters the registry absorbs
    for key in ("backend.ici_payload_bytes", "backend.syncs",
                "fused.recordings", "fused.replays"):
        assert key in delta, sorted(delta)


def test_plan_cache_invalidations_in_snapshot(make_session):
    session = make_session("local")
    graph = create_graph(session, CREATE)
    session.catalog.store("obs_snap", graph)
    # this plan DEPENDS on the catalog name; a graph-object plan would
    # survive catalog churn (scoped eviction)
    session.cypher("FROM GRAPH session.obs_snap MATCH (n:Person) "
                   "RETURN count(*) AS c")
    snap0 = session.metrics_snapshot()
    # mutating the referenced name evicts exactly its dependents
    session.catalog.store("obs_snap", create_graph(session, CREATE))
    delta = diff_snapshots(snap0, session.metrics_snapshot())
    assert delta["plan_cache.invalidations"] >= 1


# -- exporters ---------------------------------------------------------------

def test_exporters(make_session, tmp_path):
    session = make_session("local")
    graph = create_graph(session, CREATE)
    graph.cypher("PROFILE " + Q, {"min": 25})
    assert session.tracer.spans, "PROFILE must collect spans"

    chrome = session.export_trace(str(tmp_path / "trace.json"))
    doc = json.load(open(chrome))
    events = doc["traceEvents"]
    assert events
    names = {e["name"] for e in events}
    assert "query" in names and any(n.startswith("op.") for n in names)
    for e in events:
        assert e["ph"] in ("X", "i")
        assert e["ts"] >= 0

    jsonl = session.export_trace(str(tmp_path / "trace.jsonl"), fmt="jsonl")
    lines = [json.loads(l) for l in open(jsonl) if l.strip()]
    assert len(lines) == len(events)
    roots = [l for l in lines if l["parent_id"] == -1]
    assert roots and roots[0]["name"] == "query"
    # parent links resolve
    ids = {l["span_id"] for l in lines}
    assert all(l["parent_id"] in ids or l["parent_id"] == -1
               for l in lines)

    with pytest.raises(ValueError):
        session.export_trace(str(tmp_path / "x"), fmt="bogus")


def test_span_nesting_and_events():
    tr = Tracer(enabled=True)
    with tr.span("outer", kind="query") as outer:
        with tr.span("inner", kind="phase") as inner:
            tr.event("tick", bytes=10)
        outer.annotate(rows=5)
    assert len(tr.spans) == 1
    root = tr.spans[0]
    assert root.name == "outer" and root.rows == 5
    assert [c.name for c in root.children] == ["inner"]
    assert [c.name for c in root.children[0].children] == ["tick"]
    assert root.children[0].children[0].bytes == 10
    assert root.wall_s >= root.children[0].wall_s >= 0.0


# -- collectives instrumentation ---------------------------------------------

def test_collective_note_records_trace_time_counters():
    import numpy as np
    from caps_tpu.parallel.collectives import note_collective
    reg = obs.global_registry()
    snap0 = reg.snapshot()
    note_collective("unit_test_op", np.zeros((4, 4), np.int32))
    delta = diff_snapshots(snap0, reg.snapshot())
    assert delta["collectives.unit_test_op.calls"] == 1
    assert delta["collectives.unit_test_op.traced_bytes"] == 64


def test_sharded_query_counts_collectives(make_session):
    """A sharded var-expand compiles ring/exchange programs whose
    collective launches land in the process-global registry."""
    session = make_session("sharded")
    graph = create_graph(session, CREATE)
    rows = graph.cypher(
        "MATCH (a:Person)-[:KNOWS*1..2]->(f) RETURN count(*) AS c"
    ).records.to_maps()
    assert rows[0]["c"] > 0
    # trace-time counters tick once per XLA compile, so an earlier test
    # in this process may have paid the compile already — assert the
    # cumulative registry state, not a per-query delta
    snap = obs.global_registry().snapshot()
    traced = sum(v for k, v in snap.items()
                 if k.startswith("collectives.") and k.endswith(".calls")
                 and k != "collectives.unit_test_op.calls"
                 and isinstance(v, (int, float)))
    assert traced >= 1, sorted(k for k in snap if "collect" in k)

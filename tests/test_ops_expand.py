"""Differential tests for the expand-positions Pallas kernel and the
HBM-resident CSR adjacency (ops/expand.py) — the pattern of
tests/test_ops_pallas.py: every kernel result must equal its jnp twin
exactly, and the engine must produce identical results with the fast
paths on and off (SURVEY.md §7 step 6)."""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp
import pytest

from caps_tpu.ops.expand import (
    DeviceCSR, build_csr, expand_positions, expand_positions_ref,
    join_expand_via_positions,
)
from caps_tpu.backends.tpu import kernels as K
from caps_tpu.backends.tpu.session import TPUCypherSession
from caps_tpu.okapi.config import EngineConfig
from tests.util import make_graph


def _random_case(rng, cap_l, max_count, zero_frac):
    counts = rng.randint(0, max_count + 1, cap_l)
    counts = np.where(rng.rand(cap_l) < zero_frac, 0, counts)
    lo = rng.randint(0, 1 << 20, cap_l)
    return counts, lo


@pytest.mark.parametrize("cap_l,max_count,zero_frac", [
    (256, 4, 0.0),
    (256, 4, 0.9),
    (1024, 7, 0.5),
    (4096, 3, 0.97),
    (1024, 0, 1.0),      # fully empty
    (256, 1, 0.0),       # degree exactly 1 everywhere
])
def test_expand_positions_matches_twin(cap_l, max_count, zero_frac):
    rng = np.random.RandomState(cap_l + max_count)
    counts, lo = _random_case(rng, cap_l, max_count, zero_frac)
    total = int(counts.sum())
    out_cap = max(256, 1 << (max(1, total) - 1).bit_length())
    got = expand_positions(jnp.asarray(counts), jnp.asarray(lo), out_cap,
                           interpret=True)
    want = expand_positions_ref(jnp.asarray(counts), jnp.asarray(lo), out_cap)
    for g, w, name in zip(got, want, ("l_idx", "r_pos", "valid")):
        assert np.array_equal(np.asarray(g), np.asarray(w)), name


def test_expand_positions_heavy_skew():
    """One hub row holding almost all the output (the power-law case)."""
    cap_l = 1024
    counts = np.zeros(cap_l, np.int64)
    counts[7] = 2000
    counts[900] = 48
    lo = np.arange(cap_l)
    got = expand_positions(jnp.asarray(counts), jnp.asarray(lo), 2048,
                           interpret=True)
    want = expand_positions_ref(jnp.asarray(counts), jnp.asarray(lo), 2048)
    for g, w in zip(got, want):
        assert np.array_equal(np.asarray(g), np.asarray(w))


def test_join_expand_via_positions_matches_join_expand():
    rng = np.random.RandomState(3)
    cap_l, cap_r = 512, 1024
    n_r = 700
    r_key = rng.randint(0, 50, cap_r)
    r_ok = K.row_mask(cap_r, n_r)
    rk_sorted, perm = K.sort_right(jnp.asarray(r_key), r_ok)
    l_key = rng.randint(0, 60, cap_l)
    l_ok = jnp.asarray(rng.rand(cap_l) < 0.8)
    counts, lo = K.probe_count(jnp.asarray(l_key), l_ok, rk_sorted)
    for left_join in (False, True):
        total = int(K.join_total(counts, l_ok, left_join))
        out_cap = max(256, 1 << (max(1, total) - 1).bit_length())
        li1, ri1, v1, m1 = join_expand_via_positions(
            counts, lo, perm, l_ok, out_cap, left_join, interpret=True)
        li2, ri2, v2, m2, _ = K.join_expand(counts, lo, perm, l_ok,
                                            out_cap, left_join)
        assert np.array_equal(np.asarray(v1), np.asarray(v2))
        assert np.array_equal(np.asarray(m1), np.asarray(m2))
        v = np.asarray(v1)
        assert np.array_equal(np.asarray(li1)[v], np.asarray(li2)[v])
        m = np.asarray(m1)
        assert np.array_equal(np.asarray(ri1)[m], np.asarray(ri2)[m])


def test_build_csr_native_and_numpy_agree():
    rng = np.random.RandomState(11)
    cap, n = 2048, 1500
    keys = np.zeros(cap, np.int64)
    keys[:n] = rng.randint(0, 300, n)
    ok = np.zeros(cap, bool)
    ok[:n] = rng.rand(n) < 0.85
    a = build_csr(jnp.asarray(keys), jnp.asarray(ok), n, use_native=True)
    b = build_csr(jnp.asarray(keys), jnp.asarray(ok), n, use_native=False)
    assert np.array_equal(np.asarray(a.indptr), np.asarray(b.indptr))
    # perms may order rows within a key differently across builders; the
    # row *sets* per key must match
    ia, pa = np.asarray(a.indptr), np.asarray(a.perm)
    ib, pb = np.asarray(b.indptr), np.asarray(b.perm)
    for k in range(a.n_keys):
        assert set(pa[ia[k]:ia[k + 1]]) == set(pb[ib[k]:ib[k + 1]]), k


def test_build_csr_rejects_sparse_domain():
    keys = jnp.asarray(np.array([0, 5, 10**7], np.int64))
    ok = jnp.ones(3, bool)
    assert build_csr(keys, ok, 3) is None


def test_csr_probe_int64_keys_out_of_range():
    csr = DeviceCSR(jnp.asarray(np.array([0, 1, 2], np.int32)),
                    jnp.asarray(np.array([0, 1], np.int32)), 2)
    keys = jnp.asarray(np.array([0, 1, 2, -1, 2**40], np.int64))
    ok = jnp.ones(5, bool)
    counts, lo = csr.probe(keys, ok)
    assert list(np.asarray(counts)) == [1, 1, 0, 0, 0]


def _social(session):
    return make_graph(
        session,
        {("Person",): [{"_id": i, "name": f"p{i}"} for i in range(30)]},
        {"KNOWS": [(i, (i * 7 + 3) % 30, {}) for i in range(30)]
                  + [(i, (i * 11 + 1) % 30, {}) for i in range(0, 30, 2)]},
    )


QUERIES = [
    "MATCH (a:Person)-[:KNOWS]->(b) RETURN count(*) AS c",
    "MATCH (a:Person)-[:KNOWS]->(b)-[:KNOWS]->(c) RETURN count(*) AS c",
    "MATCH (a:Person) OPTIONAL MATCH (a)-[:KNOWS]->(b) "
    "RETURN a.name AS a, b.name AS b ORDER BY a, b",
    "MATCH (a:Person)-[:KNOWS]->(b) WHERE a.name = 'p3' "
    "RETURN b.name AS n ORDER BY n",
    "MATCH (a:Person)-[:KNOWS*1..3]->(b) WHERE a.name = 'p1' "
    "RETURN count(*) AS c",
    "MATCH (a:Person)<-[:KNOWS]-(b) WHERE a.name = 'p4' "
    "RETURN count(*) AS c",
]


@pytest.mark.parametrize("query", QUERIES)
def test_engine_parity_csr_on_off(query):
    on = TPUCypherSession(config=EngineConfig(use_csr=True))
    off = TPUCypherSession(config=EngineConfig(use_csr=False,
                                              use_pallas=False))
    got = _social(on).cypher(query).records.to_maps()
    want = _social(off).cypher(query).records.to_maps()
    assert got == want
    assert on.fallback_count == 0


def test_csr_attached_at_ingest():
    session = TPUCypherSession()
    g = _social(session)
    (rt,) = g.rel_tables
    src_col = rt.table._cols[rt.mapping.source_col]
    tgt_col = rt.table._cols[rt.mapping.target_col]
    assert getattr(src_col, "_csr", None) is not None
    assert getattr(tgt_col, "_csr", None) is not None
    assert src_col._csr[1] is not None  # suitable dense domain -> built


def test_distinct_and_group_do_not_collide_large_int64():
    """Keys >= 2^53 are distinct in int64 but equal in float64 — the
    boundary detection must compare them in their own dtype (round-1
    VERDICT weak #6)."""
    session = TPUCypherSession()
    big = 2 ** 53
    g = make_graph(
        session,
        {("N",): [{"_id": 1, "v": big}, {"_id": 2, "v": big + 1},
                  {"_id": 3, "v": big}]},
        {},
    )
    rows = g.cypher("MATCH (n:N) RETURN DISTINCT n.v AS v ORDER BY v"
                    ).records.to_maps()
    assert rows == [{"v": big}, {"v": big + 1}]
    rows = g.cypher("MATCH (n:N) RETURN n.v AS v, count(*) AS c ORDER BY v"
                    ).records.to_maps()
    assert rows == [{"v": big, "c": 2}, {"v": big + 1, "c": 1}]
    assert session.fallback_count == 0


def test_build_csr_refuses_negative_keys():
    keys = jnp.asarray(np.array([3, -5, 7, 0], np.int64))
    ok = jnp.asarray(np.array([True, True, True, False]))
    assert build_csr(keys, ok, 4) is None
    # a negative key hidden behind ok=False must NOT block the build
    keys2 = jnp.asarray(np.array([3, -5, 7, 0], np.int64))
    ok2 = jnp.asarray(np.array([True, False, True, True]))
    csr = build_csr(keys2, ok2, 4)
    assert csr is not None
    # live keys {3, 7, 0}: cumulative counts over domain [0, 8)
    assert list(np.asarray(csr.indptr)) == [0, 1, 1, 1, 2, 2, 2, 2, 3]


def test_expand_positions_non_tileable_out_cap():
    counts = jnp.asarray(np.array([2, 0, 3], np.int64))
    lo = jnp.asarray(np.array([10, 0, 20], np.int64))
    got = expand_positions(counts, lo, 100, interpret=True)
    want = expand_positions_ref(counts, lo, 100)
    for g, w in zip(got, want):
        assert np.array_equal(np.asarray(g), np.asarray(w))

"""Differential tests: Pallas kernels vs their jnp reference twins
(SURVEY.md §7 step 6 — every kernel keeps a jnp twin for testing).

Run in interpreter mode on CPU; the same kernel code compiles on TPU.
"""
import numpy as np
import jax.numpy as jnp
import pytest

from caps_tpu.ops import dense_segment_agg, dense_segment_agg_ref
from caps_tpu.backends.tpu import kernels as K

KINDS = ["count", "sum_f32", "sum_i32", "min_i32", "max_i32",
         "min_f32", "max_f32"]


def _case(rng, n, s):
    codes = rng.randint(0, s, n).astype(np.int32)
    ok = rng.rand(n) < 0.8
    return codes, ok


@pytest.mark.parametrize("kind", KINDS)
@pytest.mark.parametrize("n,s", [(1000, 7), (513, 130), (4096, 1),
                                 (100, 300), (1, 1),
                                 # multi-row-tile AND multi-segment-tile
                                 # (s > 1024 -> seg_tile 1024, grid j > 1)
                                 (5000, 1500)])
def test_dense_segment_agg_matches_ref(kind, n, s):
    # NB: deterministic seed — hash() is salted per process.
    rng = np.random.RandomState((len(kind) * 1009 + n * 31 + s) % 2**31)
    codes, ok = _case(rng, n, s)
    if kind.endswith("f32"):
        values = rng.randn(n).astype(np.float32)
    else:
        values = rng.randint(-1000, 1000, n).astype(np.int32)
    got = dense_segment_agg(jnp.asarray(codes), jnp.asarray(ok),
                            jnp.asarray(values), s, kind, interpret=True)
    want = dense_segment_agg_ref(jnp.asarray(codes), jnp.asarray(ok),
                                 jnp.asarray(values), s, kind)
    assert got.shape == want.shape == (s,)
    if kind.endswith("f32"):
        # f32 sums differ by reduction order; absolute tolerance scales
        # with segment population.
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-4, atol=1e-3 * np.sqrt(n))
    else:
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_dense_segment_agg_empty_input():
    got = dense_segment_agg(jnp.zeros(0, jnp.int32), jnp.zeros(0, bool),
                            jnp.zeros(0, jnp.int32), 5, "count",
                            interpret=True)
    np.testing.assert_array_equal(np.asarray(got), np.zeros(5))


def test_dense_segment_agg_all_masked():
    codes = jnp.asarray(np.array([0, 1, 2], np.int32))
    ok = jnp.zeros(3, bool)
    vals = jnp.asarray(np.array([5, 6, 7], np.int32))
    got = dense_segment_agg(codes, ok, vals, 3, "count", interpret=True)
    np.testing.assert_array_equal(np.asarray(got), np.zeros(3))
    got_min = dense_segment_agg(codes, ok, vals, 3, "min_i32", interpret=True)
    assert np.all(np.asarray(got_min) == np.iinfo(np.int32).max)


# -- bitonic multi-column sort (ops/sort.py) --------------------------------

def _adversarial_keys(rng, cap):
    """Key columns exercising every comparator edge: int64 beyond 2^53
    (float64 would collide them), NaN / +-0.0 / +-inf doubles, negated
    (descending) values, heavy duplicates, null sentinels."""
    k_int = rng.randint(-2**62, 2**62, cap).astype(np.int64)
    k_int[: cap // 8] = 2**53 + rng.randint(0, 3, cap // 8)
    k_int[cap // 8: cap // 4] = -(2**53) - rng.randint(0, 3, cap // 8)
    k_f = rng.rand(cap) * 100 - 50
    k_f[: cap // 16] = np.nan
    k_f[cap // 16: cap // 8] = -0.0
    k_f[cap // 8: 3 * cap // 16] = 0.0
    k_f[3 * cap // 16: cap // 5] = -np.inf
    k_f[cap // 5: cap // 4] = np.inf
    k_dup = rng.randint(0, 4, cap).astype(np.int64)
    k_null = (rng.rand(cap) < 0.3).astype(np.int64)  # null-first/last plane
    return [jnp.asarray(k_null), jnp.asarray(k_dup), jnp.asarray(-k_int),
            jnp.asarray(k_f)]


@pytest.mark.parametrize("cap", [256, 1024, 4096, 16384])
def test_bitonic_sort_perm_matches_lax(cap):
    """The bitonic network (XLA twin of the Pallas kernel body) must be
    bit-identical to the stable lax.sort path on adversarial keys."""
    from caps_tpu.ops.sort import (
        bitonic_sort_perm_twin, sort_cap_supported, split_planes,
    )
    assert sort_cap_supported(cap)
    rng = np.random.RandomState(cap)
    keys = _adversarial_keys(rng, cap)
    for nk in (1, 2, 4):
        sub = keys[:nk]
        want = np.asarray(K.sort_perm(sub, cap))
        got = np.asarray(bitonic_sort_perm_twin(tuple(split_planes(sub))))
        np.testing.assert_array_equal(got, want, err_msg=f"nk={nk}")


def test_bitonic_sort_pallas_interpret_smoke():
    """One small interpreter-mode pallas_call run to validate the kernel
    plumbing itself (the full network is exercised via the XLA twin —
    interpreter mode is far too slow for every shape)."""
    from caps_tpu.ops.sort import sort_perm_pallas
    cap = 256
    rng = np.random.RandomState(5)
    keys = [jnp.asarray(rng.randint(0, 7, cap).astype(np.int64))]
    want = np.asarray(K.sort_perm(keys, cap))
    got = np.asarray(sort_perm_pallas(keys, cap, interpret=True))
    np.testing.assert_array_equal(got, want)


def test_bitonic_sort_unsupported_caps():
    from caps_tpu.ops.sort import sort_cap_supported
    assert not sort_cap_supported(0)
    assert not sort_cap_supported(128)        # R=1
    assert not sort_cap_supported(384)        # R=3
    assert not sort_cap_supported(32768)      # R=256
    assert sort_cap_supported(256) and sort_cap_supported(16384)

"""Differential tests: Pallas kernels vs their jnp reference twins
(SURVEY.md §7 step 6 — every kernel keeps a jnp twin for testing).

Run in interpreter mode on CPU; the same kernel code compiles on TPU.
"""
import numpy as np
import jax.numpy as jnp
import pytest

from caps_tpu.ops import dense_segment_agg, dense_segment_agg_ref

KINDS = ["count", "sum_f32", "sum_i32", "min_i32", "max_i32",
         "min_f32", "max_f32"]


def _case(rng, n, s):
    codes = rng.randint(0, s, n).astype(np.int32)
    ok = rng.rand(n) < 0.8
    return codes, ok


@pytest.mark.parametrize("kind", KINDS)
@pytest.mark.parametrize("n,s", [(1000, 7), (513, 130), (4096, 1),
                                 (100, 300), (1, 1)])
def test_dense_segment_agg_matches_ref(kind, n, s):
    # NB: deterministic seed — hash() is salted per process.
    rng = np.random.RandomState((len(kind) * 1009 + n * 31 + s) % 2**31)
    codes, ok = _case(rng, n, s)
    if kind.endswith("f32"):
        values = rng.randn(n).astype(np.float32)
    else:
        values = rng.randint(-1000, 1000, n).astype(np.int32)
    got = dense_segment_agg(jnp.asarray(codes), jnp.asarray(ok),
                            jnp.asarray(values), s, kind, interpret=True)
    want = dense_segment_agg_ref(jnp.asarray(codes), jnp.asarray(ok),
                                 jnp.asarray(values), s, kind)
    assert got.shape == want.shape == (s,)
    if kind.endswith("f32"):
        # f32 sums differ by reduction order; absolute tolerance scales
        # with segment population.
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-4, atol=1e-3 * np.sqrt(n))
    else:
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_dense_segment_agg_empty_input():
    got = dense_segment_agg(jnp.zeros(0, jnp.int32), jnp.zeros(0, bool),
                            jnp.zeros(0, jnp.int32), 5, "count",
                            interpret=True)
    np.testing.assert_array_equal(np.asarray(got), np.zeros(5))


def test_dense_segment_agg_all_masked():
    codes = jnp.asarray(np.array([0, 1, 2], np.int32))
    ok = jnp.zeros(3, bool)
    vals = jnp.asarray(np.array([5, 6, 7], np.int32))
    got = dense_segment_agg(codes, ok, vals, 3, "count", interpret=True)
    np.testing.assert_array_equal(np.asarray(got), np.zeros(3))
    got_min = dense_segment_agg(codes, ok, vals, 3, "min_i32", interpret=True)
    assert np.all(np.asarray(got_min) == np.iinfo(np.int32).max)

"""Sharded execution on the 8-virtual-device CPU mesh (SURVEY.md §4:
mesh size is config; same program runs 1-chip or v5e-8)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from caps_tpu.parallel.mesh import make_mesh
from caps_tpu.parallel.query_step import (
    make_collectives_smoke, make_sharded_two_hop, two_hop_count_kernel,
)


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(8)


def _graph(n_nodes, n_edges, seed=7):
    rng = np.random.RandomState(seed)
    names = jnp.asarray(rng.randint(0, 5, n_nodes, dtype=np.int32))
    src = jnp.asarray(rng.randint(0, n_nodes, n_edges, dtype=np.int32))
    dst = jnp.asarray(rng.randint(0, n_nodes, n_edges, dtype=np.int32))
    ok = jnp.ones(n_edges, bool)
    return names, src, dst, ok


def _expected_paths(names, src, dst, seed_code):
    names, src, dst = map(np.asarray, (names, src, dst))
    cnt1 = np.bincount(dst[names[src] == seed_code], minlength=len(names))
    return int(cnt1[src].sum())


def test_sharded_two_hop_matches_reference(mesh):
    names, src, dst, ok = _graph(64, 8 * 32)
    step = make_sharded_two_hop(mesh, 64)
    total, cnt2 = step(names, src, dst, ok, jnp.int32(3))
    assert int(total) == _expected_paths(names, src, dst, 3)
    assert int(cnt2.sum()) == int(total)


def test_mesh_size_is_config(mesh):
    """The same kernel runs on a 1-device mesh and the 8-device mesh."""
    names, src, dst, ok = _graph(32, 8 * 8, seed=9)
    expected = _expected_paths(names, src, dst, 2)
    for n in (1, 2, 8):
        sub = make_mesh(n)
        step = make_sharded_two_hop(sub, 32)
        assert int(step(names, src, dst, ok, jnp.int32(2))[0]) == expected


def test_collectives_smoke(mesh):
    smoke = make_collectives_smoke(mesh)
    out = smoke(jnp.arange(8 * 8, dtype=jnp.int32))
    assert np.isfinite(int(out))


def test_graft_entry_points():
    import __graft_entry__ as g
    fn, args = g.entry()
    total, cnt2 = jax.jit(fn)(*args)
    assert int(total) >= 0
    g.dryrun_multichip(8)


def test_ring_khop_matches_reference():
    """Ring-rotated k-hop expansion (ppermute schedule) vs the dense
    single-device twin (SURVEY.md §5.7)."""
    import numpy as np
    import jax.numpy as jnp
    from caps_tpu.parallel.mesh import make_mesh
    from caps_tpu.parallel.ring import make_ring_khop, ring_khop_reference

    n_shards, n_nodes, n_edges, hops = 8, 64, 256, 3
    rng = np.random.RandomState(7)
    src = jnp.asarray(rng.randint(0, n_nodes, n_edges, dtype=np.int32))
    dst = jnp.asarray(rng.randint(0, n_nodes, n_edges, dtype=np.int32))
    ok = jnp.asarray(rng.rand(n_edges) < 0.9)
    seed = jnp.asarray((rng.rand(n_nodes) < 0.2).astype(np.int32))

    mesh = make_mesh(n_shards)
    total, blocks = make_ring_khop(mesh, n_nodes, hops)(seed, src, dst, ok)
    want_total, want_cnt = ring_khop_reference(seed, src, dst, ok, hops,
                                               n_nodes)
    assert int(total) == int(want_total)
    np.testing.assert_array_equal(np.asarray(blocks), np.asarray(want_cnt))


def test_ring_varexpand_matrix_matches_reference(mesh):
    """Matrix-frontier ring expansion (general VarExpand form) vs the
    single-device twin, including self-loops (the length-2 isomorphism
    correction) and masked targets."""
    from caps_tpu.parallel.ring import (
        make_ring_varexpand, ring_varexpand_reference,
    )

    n_nodes, n_edges, n_seeds = 64, 256, 9
    rng = np.random.RandomState(11)
    src = rng.randint(0, n_nodes, n_edges).astype(np.int32)
    dst = rng.randint(0, n_nodes, n_edges).astype(np.int32)
    # force a batch of self-loops so the correction has work to do
    src[:20] = dst[:20]
    ok = rng.rand(n_edges) < 0.9
    seeds = rng.choice(n_nodes, size=n_seeds, replace=False)
    f0 = np.zeros((n_seeds, n_nodes), dtype=np.int64)
    f0[np.arange(n_seeds), seeds] = 1
    tmask = (rng.rand(n_nodes) < 0.7).astype(np.int64)

    for lengths in [(1,), (2,), (1, 2), (0, 1, 2), (0,)]:
        fn = make_ring_varexpand(mesh, n_nodes, lengths)
        got = fn(jnp.asarray(f0), jnp.asarray(src), jnp.asarray(dst),
                 jnp.asarray(ok), jnp.asarray(tmask))
        want = ring_varexpand_reference(
            jnp.asarray(f0), jnp.asarray(src), jnp.asarray(dst),
            jnp.asarray(ok), jnp.asarray(tmask), lengths)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want),
                                      err_msg=f"lengths={lengths}")


def test_ring_varexpand_pathcount_oracle(mesh):
    """The ring multiplicity matrix equals brute-force path enumeration
    with relationship isomorphism (r2 != r1)."""
    from caps_tpu.parallel.ring import make_ring_varexpand

    n_nodes, n_edges = 16, 48
    rng = np.random.RandomState(3)
    src = rng.randint(0, n_nodes, n_edges).astype(np.int32)
    dst = rng.randint(0, n_nodes, n_edges).astype(np.int32)
    src[:6] = dst[:6]
    ok = np.ones(n_edges, bool)
    f0 = np.eye(n_nodes, dtype=np.int64)
    tmask = np.ones(n_nodes, dtype=np.int64)

    fn = make_ring_varexpand(mesh, n_nodes, (1, 2))
    got = np.asarray(fn(jnp.asarray(f0), jnp.asarray(src), jnp.asarray(dst),
                        jnp.asarray(ok), jnp.asarray(tmask)))
    want = np.zeros((n_nodes, n_nodes), dtype=np.int64)
    for e1 in range(n_edges):
        want[src[e1], dst[e1]] += 1  # length 1
        for e2 in range(n_edges):
            if e1 != e2 and dst[e1] == src[e2]:
                want[src[e1], dst[e2]] += 1  # length 2, r2 != r1
    np.testing.assert_array_equal(got, want)


def test_varexpand_rides_ring_on_mesh():
    """End-to-end: on a mesh, a var-length query whose rel variable is
    dead downstream executes with strategy=ring-matrix and matches the
    oracle; queries that need per-path rel data stay on joins."""
    from caps_tpu.backends.local.session import LocalCypherSession
    from caps_tpu.backends.tpu.session import TPUCypherSession
    from caps_tpu.okapi.config import EngineConfig
    from caps_tpu.testing.bag import Bag
    from caps_tpu.testing.factory import create_graph

    create = ("CREATE (a:Person {name:'Alice'}), (b:Person {name:'Bob'}), "
              "(c:Person {name:'Carol'}), (d {name:'Dave'}), "
              "(a)-[:KNOWS]->(b), (b)-[:KNOWS]->(c), (a)-[:KNOWS]->(c), "
              "(c)-[:KNOWS]->(d), (d)-[:KNOWS]->(d), (c)-[:LIKES]->(a)")
    sharded = TPUCypherSession(config=EngineConfig(mesh_shape=(8,)))
    oracle = LocalCypherSession()
    gs = create_graph(sharded, create, {})
    go = create_graph(oracle, create, {})
    cases = [
        ("MATCH (a)-[:KNOWS*1..2]->(b) RETURN a.name AS a, b.name AS b",
         "ring-matrix"),
        ("MATCH (a)<-[:KNOWS*1..2]-(b) RETURN a.name AS a, b.name AS b",
         "ring-matrix"),
        ("MATCH (a)-[:KNOWS*0..2]->(b:Person) RETURN b.name AS b",
         "ring-matrix"),
        ("MATCH (a:Person)-[*1..2]->(b) RETURN a.name AS a, b.name AS b",
         "ring-matrix"),
        # size(r)-only use is rewritten to a path-length column and
        # stays on the matrix path
        ("MATCH (a)-[r:KNOWS*1..2]->(b) RETURN a.name AS a, size(r) AS n",
         "ring-matrix"),
        # rel var VALUE returned -> per-path data -> join path
        ("MATCH (a)-[r:KNOWS*1..2]->(b) RETURN a.name AS a, r AS r",
         "join"),
        # undirected rides the ring too (symmetrized edges + degree
        # correction)
        ("MATCH (a)-[:KNOWS*1..2]-(b) RETURN a.name AS a, b.name AS b",
         "ring-matrix"),
        ("MATCH (a)-[*0..2]-(b:Person) RETURN b.name AS b",
         "ring-matrix"),
        ("MATCH (a)-[:KNOWS*1..3]->(b) RETURN a.name AS a, b.name AS b",
         "ring-matrix"),
        # beyond the 3-hop correction bound -> join path
        ("MATCH (a)-[:KNOWS*1..4]->(b) RETURN a.name AS a, b.name AS b",
         "join"),
    ]
    for q, want_strategy in cases:
        res = gs.cypher(q)
        got = res.records.to_maps()
        want = go.cypher(q).records.to_maps()
        assert Bag(got) == Bag(want), (q, got, want)
        ve = [m for m in res.metrics["operators"] if m["op"] == "VarExpand"]
        assert ve and ve[0]["strategy"] == want_strategy, (q, ve)
    assert sharded.fallback_count == 0, sharded.backend.fallback_reasons


def test_ring_varexpand_undirected_oracle(mesh):
    """Degree-form correction vs brute-force undirected path
    enumeration with relationship isomorphism (e2 != e1), including
    self-loops and parallel edges."""
    from caps_tpu.parallel.ring import (
        make_ring_varexpand, ring_varexpand_reference,
    )

    n_nodes, n_edges = 16, 40
    rng = np.random.RandomState(9)
    src = rng.randint(0, n_nodes, n_edges).astype(np.int32)
    dst = rng.randint(0, n_nodes, n_edges).astype(np.int32)
    src[:5] = dst[:5]               # self-loops
    src[5:8], dst[5:8] = src[8:11], dst[8:11]  # parallel edges

    # symmetrize exactly as the engine does
    nonloop = src != dst
    a = np.concatenate([src, dst[nonloop]])
    b = np.concatenate([dst, src[nonloop]])
    pad = (-len(a)) % 8
    a = np.concatenate([a, np.zeros(pad, np.int32)])
    b = np.concatenate([b, np.zeros(pad, np.int32)])
    okp = np.concatenate([np.ones(len(a) - pad, bool), np.zeros(pad, bool)])

    f0 = np.eye(n_nodes, dtype=np.int64)
    tmask = np.ones(n_nodes, dtype=np.int64)
    fn = make_ring_varexpand(mesh, n_nodes, (1, 2), correction="degree")
    got = np.asarray(fn(jnp.asarray(f0), jnp.asarray(a), jnp.asarray(b),
                        jnp.asarray(okp), jnp.asarray(tmask)))
    ref = np.asarray(ring_varexpand_reference(
        jnp.asarray(f0), jnp.asarray(a), jnp.asarray(b), jnp.asarray(okp),
        jnp.asarray(tmask), (1, 2), correction="degree"))
    np.testing.assert_array_equal(got, ref)

    # brute force: undirected steps carry (edge id, far end)
    steps = [[] for _ in range(n_nodes)]  # node -> [(eid, far)]
    for eid, (u, v) in enumerate(zip(src, dst)):
        steps[u].append((eid, v))
        if u != v:
            steps[v].append((eid, u))
    want = np.zeros((n_nodes, n_nodes), dtype=np.int64)
    for s0 in range(n_nodes):
        for e1, m in steps[s0]:
            want[s0, m] += 1                        # length 1
            for e2, t in steps[m]:
                if e2 != e1:
                    want[s0, t] += 1                # length 2
    np.testing.assert_array_equal(got, want)


def test_varexpand_matrix_single_chip():
    """Off-mesh, an eligible var-expand takes the single-device matrix
    strategy (same SpMV computation, no collectives) with oracle
    parity."""
    from caps_tpu.backends.local.session import LocalCypherSession
    from caps_tpu.backends.tpu.session import TPUCypherSession
    from caps_tpu.testing.bag import Bag
    from caps_tpu.testing.factory import create_graph

    create = ("CREATE (a:Person {name:'Alice'}), (b:Person {name:'Bob'}), "
              "(c:Person {name:'Carol'}), (a)-[:KNOWS]->(b), "
              "(b)-[:KNOWS]->(c), (c)-[:KNOWS]->(c)")
    tpu = TPUCypherSession()
    oracle = LocalCypherSession()
    gt = create_graph(tpu, create, {})
    go = create_graph(oracle, create, {})
    for q, strat in [
        ("MATCH (a)-[:KNOWS*1..2]->(b) RETURN a.name AS a, b.name AS b",
         "matrix"),
        ("MATCH (a)-[:KNOWS*1..2]-(b) RETURN a.name AS a, b.name AS b",
         "matrix"),
        ("MATCH (a)-[r:KNOWS*1..2]->(b) RETURN size(r) AS n", "matrix"),
        ("MATCH (a)-[r:KNOWS*1..2]->(b) RETURN r AS r", "join"),
    ]:
        res = gt.cypher(q)
        assert Bag(res.records.to_maps()) == \
            Bag(go.cypher(q).records.to_maps()), q
        ve = [m for m in res.metrics["operators"] if m["op"] == "VarExpand"]
        assert ve and ve[0]["strategy"] == strat, (q, ve)
    assert tpu.fallback_count == 0


def test_two_level_mesh_parity():
    """A 2-D (DCN x ICI) mesh — multi-slice topology — runs the full
    engine with GSPMD sharding over both axes and oracle parity; the
    hand-scheduled rings correctly stand down to partitioner paths."""
    from caps_tpu.backends.local.session import LocalCypherSession
    from caps_tpu.backends.tpu.session import TPUCypherSession
    from caps_tpu.okapi.config import EngineConfig
    from caps_tpu.testing.bag import Bag
    from caps_tpu.testing.factory import create_graph

    create = ("CREATE (a:Person {name:'Ada', age:30}), "
              "(b:Person {name:'Bo', age:40}), (c:Person {name:'Cy'}), "
              "(a)-[:KNOWS]->(b), (b)-[:KNOWS]->(c), (a)-[:KNOWS]->(c)")
    multi = TPUCypherSession(config=EngineConfig(mesh_shape=(2, 4)))
    assert multi.backend.mesh.axis_names == ("dcn", "shard")
    assert multi.backend.mesh.devices.shape == (2, 4)
    oracle = LocalCypherSession()
    gm = create_graph(multi, create, {})
    go = create_graph(oracle, create, {})
    queries = [
        "MATCH (a:Person)-[:KNOWS]->(b) RETURN a.name AS a, b.name AS b",
        "MATCH (a)-[:KNOWS*1..2]->(b) RETURN a.name AS a, b.name AS b",
        "MATCH (a:Person)-[:KNOWS]->(b)-[:KNOWS]->(c) "
        "WHERE a.name='Ada' RETURN count(*) AS c",
        "MATCH (p:Person) RETURN p.name AS n, min(p.age) AS a ORDER BY n",
    ]
    for q in queries:
        res = gm.cypher(q)
        assert Bag(res.records.to_maps()) == \
            Bag(go.cypher(q).records.to_maps()), q
    # var-expand must report the partitioner-backed matrix strategy
    res = gm.cypher("MATCH (a)-[:KNOWS*1..2]->(b) RETURN b.name AS b")
    ve = [m for m in res.metrics["operators"] if m["op"] == "VarExpand"]
    assert ve and ve[0]["strategy"] == "matrix", ve
    assert multi.fallback_count == 0, multi.backend.fallback_reasons


def test_varexpand_matrix_three_hops_oracle(mesh):
    """*1..3 / *3..3 / *0..3 on the matrix path — the 3-hop
    relationship-isomorphism inclusion-exclusion (W3 - A12 - A23 - A13
    + 2T) — against the join-path oracle, on a multigraph with
    self-loops and parallel edges, in all three directions, single-chip
    and ring."""
    from caps_tpu.backends.local.session import LocalCypherSession
    from caps_tpu.backends.tpu.session import TPUCypherSession
    from caps_tpu.okapi.config import EngineConfig
    from caps_tpu.testing.bag import Bag
    from caps_tpu.testing.factory import create_graph

    rng = np.random.RandomState(5)
    n = 7
    parts = [f"(n{i}:P {{v: {i}}})" for i in range(n)]
    edges = []
    for _ in range(14):
        u, v = rng.randint(0, n), rng.randint(0, n)
        edges.append(f"(n{u})-[:K]->(n{v})")
    edges += ["(n0)-[:K]->(n0)",                    # self-loop
              "(n1)-[:K]->(n2)", "(n1)-[:K]->(n2)"]  # parallel edges
    create = "CREATE " + ", ".join(parts + edges)

    oracle = LocalCypherSession()
    single = TPUCypherSession()
    sharded = TPUCypherSession(config=EngineConfig(mesh_shape=(8,)))
    go = create_graph(oracle, create, {})
    gt = create_graph(single, create, {})
    gs = create_graph(sharded, create, {})
    for pat in ["-[:K*1..3]->", "<-[:K*1..3]-", "-[:K*1..3]-",
                "-[:K*3..3]->", "-[:K*0..3]-", "-[:K*2..3]-"]:
        q = f"MATCH (a){pat}(b) RETURN a.v AS a, b.v AS b"
        want = go.cypher(q).records.to_maps()
        for name, g, strat in (("single", gt, "matrix"),
                               ("sharded", gs, "ring-matrix")):
            res = g.cypher(q)
            assert Bag(res.records.to_maps()) == Bag(want), (name, pat)
            ve = [m for m in res.metrics["operators"]
                  if m["op"] == "VarExpand"]
            assert ve[0]["strategy"] == strat, (name, pat, ve)
    assert single.fallback_count == 0 and sharded.fallback_count == 0


def test_ring_varexpand3_kernel_vs_twin(mesh):
    """Sharded 3-hop program vs the single-device twin on random
    weighted sparse corrections."""
    from caps_tpu.parallel.ring import (
        build_iso3_sparse, make_ring_varexpand3,
        ring_varexpand3_reference,
    )

    n_nodes, n_rels = 16, 30
    rng = np.random.RandomState(2)
    src = rng.randint(0, n_nodes, n_rels).astype(np.int32)
    dst = rng.randint(0, n_nodes, n_rels).astype(np.int32)
    src[:4] = dst[:4]
    rid = np.arange(n_rels)
    nonloop = src != dst
    frm = np.concatenate([src, dst[nonloop]]).astype(np.int32)
    to = np.concatenate([dst, src[nonloop]]).astype(np.int32)
    rids = np.concatenate([rid, rid[nonloop]])
    sp13, spt = build_iso3_sparse(frm, to, rids, n_nodes)

    def pad(xs, fill=0):
        p = (-len(xs[0])) % 8
        return tuple(np.concatenate([x, np.full(p, fill, x.dtype)])
                     for x in xs)

    frm_p, to_p = pad((frm, to))
    ok_p = np.arange(len(frm_p)) < len(frm)
    sp13_p = pad(sp13)
    spt_p = pad(spt)
    f0 = np.eye(n_nodes, dtype=np.int64)
    tmask = np.ones(n_nodes, dtype=np.int64)

    fn = make_ring_varexpand3(mesh, n_nodes, (1, 2, 3),
                              correction="degree")
    got = np.asarray(fn(jnp.asarray(f0), jnp.asarray(frm_p),
                        jnp.asarray(to_p), jnp.asarray(ok_p),
                        jnp.asarray(tmask),
                        *[jnp.asarray(x) for x in sp13_p],
                        *[jnp.asarray(x) for x in spt_p]))
    want = np.asarray(ring_varexpand3_reference(
        jnp.asarray(f0), jnp.asarray(frm_p), jnp.asarray(to_p),
        jnp.asarray(ok_p), jnp.asarray(tmask), (1, 2, 3),
        tuple(jnp.asarray(x) for x in sp13_p),
        tuple(jnp.asarray(x) for x in spt_p), correction="degree"))
    np.testing.assert_array_equal(got, want)
    assert got.sum() > 0


def test_varexpand_matrix_seed_blocking(monkeypatch):
    """Large seed sets run the matrix path in fixed-size chunks whose
    pair tables union — forced here by shrinking the working-set cap —
    with identical results and strategy."""
    from caps_tpu.backends.local.session import LocalCypherSession
    from caps_tpu.backends.tpu.session import TPUCypherSession
    from caps_tpu.relational.var_expand import VarExpandOp
    from caps_tpu.testing.bag import Bag
    from caps_tpu.testing.factory import create_graph

    rng = np.random.RandomState(3)
    n = 9
    parts = [f"(n{i}:P {{v: {i}}})" for i in range(n)]
    edges = [f"(n{rng.randint(0, n)})-[:K]->(n{rng.randint(0, n)})"
             for _ in range(18)]
    create = "CREATE " + ", ".join(parts + edges)
    q = "MATCH (a)-[:K*1..2]-(b) RETURN a.v AS a, b.v AS b"
    want = create_graph(LocalCypherSession(), create, {}
                        ).cypher(q).records.to_maps()

    # force chunking: the per-seed cost is ~bucket-capacity (256-padded
    # edge list), so a ~3-seed budget splits the 9 seeds into chunks
    monkeypatch.setattr(VarExpandOp, "_RING_MAX_MATRIX", 2000)
    tpu = TPUCypherSession()
    res = create_graph(tpu, create, {}).cypher(q)
    assert Bag(res.records.to_maps()) == Bag(want)
    ve = [m for m in res.metrics["operators"] if m["op"] == "VarExpand"]
    assert ve and ve[0]["strategy"] == "matrix", ve
    assert tpu.fallback_count == 0

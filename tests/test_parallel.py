"""Sharded execution on the 8-virtual-device CPU mesh (SURVEY.md §4:
mesh size is config; same program runs 1-chip or v5e-8)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from caps_tpu.parallel.mesh import make_mesh
from caps_tpu.parallel.query_step import (
    make_collectives_smoke, make_sharded_two_hop, two_hop_count_kernel,
)


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(8)


def _graph(n_nodes, n_edges, seed=7):
    rng = np.random.RandomState(seed)
    names = jnp.asarray(rng.randint(0, 5, n_nodes, dtype=np.int32))
    src = jnp.asarray(rng.randint(0, n_nodes, n_edges, dtype=np.int32))
    dst = jnp.asarray(rng.randint(0, n_nodes, n_edges, dtype=np.int32))
    ok = jnp.ones(n_edges, bool)
    return names, src, dst, ok


def _expected_paths(names, src, dst, seed_code):
    names, src, dst = map(np.asarray, (names, src, dst))
    cnt1 = np.bincount(dst[names[src] == seed_code], minlength=len(names))
    return int(cnt1[src].sum())


def test_sharded_two_hop_matches_reference(mesh):
    names, src, dst, ok = _graph(64, 8 * 32)
    step = make_sharded_two_hop(mesh, 64)
    total, cnt2 = step(names, src, dst, ok, jnp.int32(3))
    assert int(total) == _expected_paths(names, src, dst, 3)
    assert int(cnt2.sum()) == int(total)


def test_mesh_size_is_config(mesh):
    """The same kernel runs on a 1-device mesh and the 8-device mesh."""
    names, src, dst, ok = _graph(32, 8 * 8, seed=9)
    expected = _expected_paths(names, src, dst, 2)
    for n in (1, 2, 8):
        sub = make_mesh(n)
        step = make_sharded_two_hop(sub, 32)
        assert int(step(names, src, dst, ok, jnp.int32(2))[0]) == expected


def test_collectives_smoke(mesh):
    smoke = make_collectives_smoke(mesh)
    out = smoke(jnp.arange(8 * 8, dtype=jnp.int32))
    assert np.isfinite(int(out))


def test_graft_entry_points():
    import __graft_entry__ as g
    fn, args = g.entry()
    total, cnt2 = jax.jit(fn)(*args)
    assert int(total) >= 0
    g.dryrun_multichip(8)


def test_ring_khop_matches_reference():
    """Ring-rotated k-hop expansion (ppermute schedule) vs the dense
    single-device twin (SURVEY.md §5.7)."""
    import numpy as np
    import jax.numpy as jnp
    from caps_tpu.parallel.mesh import make_mesh
    from caps_tpu.parallel.ring import make_ring_khop, ring_khop_reference

    n_shards, n_nodes, n_edges, hops = 8, 64, 256, 3
    rng = np.random.RandomState(7)
    src = jnp.asarray(rng.randint(0, n_nodes, n_edges, dtype=np.int32))
    dst = jnp.asarray(rng.randint(0, n_nodes, n_edges, dtype=np.int32))
    ok = jnp.asarray(rng.rand(n_edges) < 0.9)
    seed = jnp.asarray((rng.rand(n_nodes) < 0.2).astype(np.int32))

    mesh = make_mesh(n_shards)
    total, blocks = make_ring_khop(mesh, n_nodes, hops)(seed, src, dst, ok)
    want_total, want_cnt = ring_khop_reference(seed, src, dst, ok, hops,
                                               n_nodes)
    assert int(total) == int(want_total)
    np.testing.assert_array_equal(np.asarray(blocks), np.asarray(want_cnt))

import pytest

from caps_tpu.frontend import ast
from caps_tpu.frontend.lexer import CypherSyntaxError
from caps_tpu.frontend.parser import parse_query
from caps_tpu.frontend.semantic import CypherSemanticError, check_statement
from caps_tpu.ir import exprs as E


def parse_checked(q):
    stmt = parse_query(q)
    check_statement(stmt)
    return stmt


def first_match(stmt):
    return stmt.clauses[0]


def test_simple_match_return():
    q = parse_checked("MATCH (a:Person) RETURN a.name")
    m, r = q.clauses
    assert isinstance(m, ast.MatchClause) and not m.optional
    node = m.pattern.parts[0].elements[0]
    assert node.var == "a" and node.labels == ("Person",)
    item = r.body.items[0]
    assert item.expr == E.Property(E.Var("a"), "name")


def test_two_hop_pattern():
    q = parse_checked(
        "MATCH (a:Person)-[:KNOWS]->(b)-[:KNOWS]->(c) WHERE a.name = 'Alice' RETURN c.name")
    m = first_match(q)
    part = m.pattern.parts[0]
    assert len(part.nodes) == 3 and len(part.rels) == 2
    assert part.rels[0].rel_types == ("KNOWS",)
    assert part.rels[0].direction == ast.Direction.OUTGOING
    assert m.where == E.Equals(E.Property(E.Var("a"), "name"), E.Lit("Alice"))


def test_directions():
    q = parse_checked("MATCH (a)<-[r:X]-(b), (c)-[s]-(d), (e)-->(f) RETURN a")
    parts = first_match(q).pattern.parts
    assert parts[0].rels[0].direction == ast.Direction.INCOMING
    assert parts[1].rels[0].direction == ast.Direction.BOTH
    assert parts[2].rels[0].direction == ast.Direction.OUTGOING
    assert parts[2].rels[0].var is None


def test_var_length():
    q = parse_checked("MATCH (a)-[r:KNOWS*1..3]->(b) RETURN b")
    rel = first_match(q).pattern.parts[0].rels[0]
    assert rel.var_length == (1, 3)
    q2 = parse_checked("MATCH (a)-[*]->(b) RETURN b")
    assert first_match(q2).pattern.parts[0].rels[0].var_length == (1, None)
    q3 = parse_checked("MATCH (a)-[*2]->(b) RETURN b")
    assert first_match(q3).pattern.parts[0].rels[0].var_length == (2, 2)
    q4 = parse_checked("MATCH (a)-[*..4]->(b) RETURN b")
    assert first_match(q4).pattern.parts[0].rels[0].var_length == (1, 4)


def test_multiple_rel_types():
    q = parse_checked("MATCH (a)-[r:KNOWS|LIKES]->(b) RETURN r")
    assert first_match(q).pattern.parts[0].rels[0].rel_types == ("KNOWS", "LIKES")


def test_node_properties_inline():
    q = parse_checked("MATCH (a:Person {name: 'Alice', age: 23}) RETURN a")
    node = first_match(q).pattern.parts[0].elements[0]
    assert node.properties == E.MapLit(("name", "age"), (E.Lit("Alice"), E.Lit(23)))


def test_operator_precedence():
    q = parse_checked("RETURN 1 + 2 * 3 AS x")
    expr = q.clauses[0].body.items[0].expr
    assert expr == E.Add(E.Lit(1), E.Multiply(E.Lit(2), E.Lit(3)))


def test_boolean_precedence():
    q = parse_checked("MATCH (n) WHERE n.a = 1 OR n.b = 2 AND NOT n.c = 3 RETURN n")
    w = first_match(q).where
    assert isinstance(w, E.Ors)
    assert isinstance(w.exprs[1], E.Ands)
    assert isinstance(w.exprs[1].exprs[1], E.Not)


def test_comparison_chain_becomes_ands():
    q = parse_checked("MATCH (n) WHERE 1 < n.x < 10 RETURN n")
    w = first_match(q).where
    assert isinstance(w, E.Ands) and len(w.exprs) == 2


def test_string_predicates_and_in():
    q = parse_checked(
        "MATCH (n) WHERE n.name STARTS WITH 'A' AND n.name ENDS WITH 'e' "
        "AND n.name CONTAINS 'li' AND n.age IN [1, 2, 3] RETURN n")
    w = first_match(q).where
    types = [type(e) for e in w.exprs]
    assert types == [E.StartsWith, E.EndsWith, E.Contains, E.In]


def test_is_null():
    q = parse_checked("MATCH (n) WHERE n.x IS NULL AND n.y IS NOT NULL RETURN n")
    w = first_match(q).where
    assert isinstance(w.exprs[0], E.IsNull)
    assert isinstance(w.exprs[1], E.IsNotNull)


def test_label_predicate_in_where():
    q = parse_checked("MATCH (n) WHERE n:Person:Admin RETURN n")
    w = first_match(q).where
    assert w == E.Ands((E.HasLabel(E.Var("n"), "Person"), E.HasLabel(E.Var("n"), "Admin")))


def test_aggregators():
    q = parse_checked(
        "MATCH (n) RETURN count(*) AS c, count(DISTINCT n.x) AS d, "
        "sum(n.a) AS s, collect(n.b) AS l, min(n.c) AS mn")
    items = q.clauses[1].body.items
    assert isinstance(items[0].expr, E.CountStar)
    assert items[1].expr == E.Count(E.Property(E.Var("n"), "x"), True)
    assert isinstance(items[2].expr, E.Sum)
    assert isinstance(items[3].expr, E.Collect)
    assert isinstance(items[4].expr, E.Min)


def test_functions():
    q = parse_checked("MATCH (n)-[r]->(m) RETURN id(n), labels(n), type(r), toUpper(n.name)")
    items = q.clauses[1].body.items
    assert items[0].expr == E.Id(E.Var("n"))
    assert items[1].expr == E.Labels(E.Var("n"))
    assert items[2].expr == E.Type(E.Var("r"))
    assert items[3].expr == E.FunctionExpr("toupper", (E.Property(E.Var("n"), "name"),))


def test_case_expression():
    q = parse_checked(
        "MATCH (n) RETURN CASE WHEN n.age > 18 THEN 'adult' ELSE 'minor' END AS cat")
    expr = q.clauses[1].body.items[0].expr
    assert isinstance(expr, E.CaseExpr)
    assert expr.default == E.Lit("minor")
    # simple form normalizes to searched form
    q2 = parse_checked("MATCH (n) RETURN CASE n.x WHEN 1 THEN 'a' END AS v")
    e2 = q2.clauses[1].body.items[0].expr
    assert isinstance(e2.conditions[0], E.Equals)


def test_with_order_skip_limit_distinct():
    q = parse_checked(
        "MATCH (n) WITH DISTINCT n.name AS name ORDER BY name DESC SKIP 1 LIMIT 2 "
        "WHERE name <> 'Bob' RETURN name")
    w = q.clauses[1]
    assert isinstance(w, ast.WithClause)
    assert w.body.distinct
    assert not w.body.order_by[0].ascending
    assert w.body.skip == E.Lit(1) and w.body.limit == E.Lit(2)
    assert w.where is not None


def test_unwind():
    q = parse_checked("UNWIND [1, 2, 3] AS x RETURN x")
    u = q.clauses[0]
    assert isinstance(u, ast.UnwindClause) and u.var == "x"


def test_union():
    q = parse_checked("MATCH (a:A) RETURN a.x AS v UNION MATCH (b:B) RETURN b.y AS v")
    assert isinstance(q, ast.UnionQuery) and not q.union_all
    q2 = parse_checked("RETURN 1 AS v UNION ALL RETURN 2 AS v")
    assert q2.union_all


def test_return_star():
    q = parse_checked("MATCH (n) RETURN *")
    assert q.clauses[1].body.star


def test_parameters():
    q = parse_checked("MATCH (n) WHERE n.name = $name RETURN n LIMIT $lim")
    assert first_match(q).where == E.Equals(E.Property(E.Var("n"), "name"), E.Param("name"))
    assert q.clauses[1].body.limit == E.Param("lim")


def test_list_comprehension():
    q = parse_checked("RETURN [x IN [1,2,3] WHERE x > 1 | x * 2] AS l")
    expr = q.clauses[0].body.items[0].expr
    assert isinstance(expr, E.ListComprehension)
    assert expr.var == "x" and expr.predicate is not None and expr.projection is not None


def test_create_clause():
    q = parse_query("CREATE (a:Person {name: 'Alice'})-[:KNOWS {since: 2020}]->(b:Person)")
    c = q.clauses[0]
    assert isinstance(c, ast.CreateClause)
    assert c.pattern.parts[0].rels[0].properties is not None


def test_optional_match():
    q = parse_checked("MATCH (a) OPTIONAL MATCH (a)-[r]->(b) RETURN a, b")
    assert q.clauses[1].optional


def test_from_graph_and_construct():
    q = parse_query(
        "FROM GRAPH fs.products MATCH (p:Product) "
        "CONSTRUCT ON fs.products CLONE p NEW (p)-[:TAGGED]->(:Tag) RETURN GRAPH")
    check_statement(q)
    fg, m, c, rg = q.clauses
    assert isinstance(fg, ast.FromGraphClause) and fg.qualified_name == "fs.products"
    assert isinstance(c, ast.ConstructClause)
    assert c.on_graphs == ("fs.products",)
    assert c.clones[0].var == "p"
    assert len(c.news) == 1
    assert isinstance(rg, ast.ReturnGraphClause)


def test_catalog_create_graph():
    q = parse_query("CATALOG CREATE GRAPH session.snapshot { FROM GRAPH session.g "
                    "MATCH (n) CONSTRUCT CLONE n RETURN GRAPH }")
    assert isinstance(q, ast.CatalogCreateGraph)
    assert q.qualified_name == "session.snapshot"


def test_named_path():
    q = parse_checked("MATCH p = (a)-[:X]->(b) RETURN p")
    assert first_match(q).pattern.parts[0].path_var == "p"


def test_syntax_error_reports_position():
    with pytest.raises(CypherSyntaxError) as ei:
        parse_query("MATCH (a RETURN a")
    assert "line 1" in str(ei.value)


def test_semantic_unbound_variable():
    with pytest.raises(CypherSemanticError):
        parse_checked("MATCH (a) RETURN b")


def test_semantic_with_requires_alias():
    with pytest.raises(CypherSemanticError):
        parse_checked("MATCH (a) WITH a.name RETURN 1 AS one")


def test_semantic_union_column_mismatch():
    with pytest.raises(CypherSemanticError):
        parse_checked("RETURN 1 AS a UNION RETURN 2 AS b")


def test_semantic_rebound_rel_var():
    with pytest.raises(CypherSemanticError):
        parse_checked("MATCH (a)-[r]->(b) MATCH (c)-[r]->(d) RETURN a")


def test_keywords_as_property_keys():
    q = parse_checked("MATCH (n) RETURN n.from AS f, n.end AS e")
    items = q.clauses[1].body.items
    assert items[0].expr == E.Property(E.Var("n"), "from")
    assert items[1].expr == E.Property(E.Var("n"), "end")

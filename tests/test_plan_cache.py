"""Prepared statements + the session-level LRU plan cache
(caps_tpu/relational/plan_cache.py).

Correctness contract under test: a cached plan executed with NEW
parameter bindings returns results identical to a fresh cold-path run of
the same query; catalog mutations invalidate dependent entries; the
determinism check passes through the cached path; eviction is LRU at
``plan_cache_size``.
"""
from __future__ import annotations

import pytest

import caps_tpu
from caps_tpu.okapi.config import EngineConfig
from caps_tpu.testing.factory import create_graph

SOCIAL = """
    CREATE (a:Person {name: 'Alice', age: 33}),
           (b:Person {name: 'Bob', age: 44}),
           (c:Person {name: 'Carol', age: 27}),
           (a)-[:KNOWS {since: 2011}]->(b),
           (b)-[:KNOWS {since: 2015}]->(c),
           (a)-[:KNOWS {since: 2019}]->(c)
"""


def _session(backend="local", **cfg):
    return caps_tpu.local_session(backend=backend,
                                  config=EngineConfig(**cfg) if cfg else None)


def _rows(result):
    return result.records.to_maps()


def _bag(rows):
    return sorted(sorted(r.items()) for r in rows)


# -- cached results == cold-path results, across param values --------------

@pytest.mark.parametrize("backend", ["local", "tpu"])
def test_cached_plan_matches_cold_run_per_binding(backend):
    session = _session(backend)
    graph = create_graph(session, SOCIAL)
    q = ("MATCH (a:Person)-[:KNOWS]->(b) WHERE a.age > $min "
         "RETURN a.name AS a, b.name AS b")
    for min_age in (30, 40, 20, 50, 30):
        got = graph.cypher(q, {"min": min_age})
        # fresh cold-path run of the SAME query and bindings
        session.plan_cache.enabled = False
        try:
            want = graph.cypher(q, {"min": min_age})
        finally:
            session.plan_cache.enabled = True
        assert _bag(_rows(got)) == _bag(_rows(want)), min_age
    stats = session.plan_cache.stats()
    assert stats["hits"] >= 4 and stats["misses"] == 1


def test_hit_skips_every_planning_phase():
    session = _session()
    graph = create_graph(session, SOCIAL)
    q = "MATCH (p:Person) WHERE p.age > $x RETURN p.name AS n ORDER BY n"
    miss = graph.cypher(q, {"x": 30})
    assert miss.metrics["plan_cache"] == "miss"
    assert miss.metrics["plan_s"] > 0
    hit = graph.cypher(q, {"x": 40})
    assert hit.metrics["plan_cache"] == "hit"
    assert (hit.metrics["parse_s"] + hit.metrics["ir_s"]
            + hit.metrics["plan_s"] + hit.metrics["relational_s"]) == 0.0
    assert hit.metrics["plan_cache_saved_s"] > 0
    assert _rows(hit) == [{"n": "Bob"}]
    # explain still works from the cached plans
    assert "=== RELATIONAL ===" in hit.explain()


def test_runtime_bound_params_in_limit_and_unwind():
    session = _session()
    graph = create_graph(session, SOCIAL)
    lim = "MATCH (p:Person) RETURN p.name AS n ORDER BY n LIMIT $k"
    assert [r["n"] for r in _rows(graph.cypher(lim, {"k": 1}))] == ["Alice"]
    res = graph.cypher(lim, {"k": 2})
    assert res.metrics["plan_cache"] == "hit"
    assert [r["n"] for r in _rows(res)] == ["Alice", "Bob"]

    unw = "UNWIND $xs AS x RETURN x ORDER BY x"
    assert [r["x"] for r in _rows(session.cypher(unw, {"xs": [3, 1, 2]}))] \
        == [1, 2, 3]
    res = session.cypher(unw, {"xs": [5, 4]})
    assert res.metrics["plan_cache"] == "hit"
    assert [r["x"] for r in _rows(res)] == [4, 5]


def test_param_signature_keys_by_coarse_type():
    session = _session()
    q = "RETURN $x AS x"
    assert _rows(session.cypher(q, {"x": 1})) == [{"x": 1}]
    assert _rows(session.cypher(q, {"x": "a"})) == [{"x": "a"}]
    assert _rows(session.cypher(q, {"x": 2})) == [{"x": 2}]
    stats = session.plan_cache.stats()
    # int and string signatures plan separately; the second int hits
    assert stats["misses"] == 2 and stats["hits"] == 1
    assert stats["entries"] == 2


def test_map_param_specializes_on_key_set():
    session = _session()
    graph = create_graph(session, SOCIAL)
    q = "MATCH (n:Person $props) RETURN n.age AS age"
    assert _rows(graph.cypher(q, {"props": {"name": "Alice"}})) \
        == [{"age": 33}]
    # same key set, different value: plan is shared
    res = graph.cypher(q, {"props": {"name": "Bob"}})
    assert res.metrics["plan_cache"] == "hit"
    assert _rows(res) == [{"age": 44}]
    # different key set: the specialized plan must NOT be served stale
    res = graph.cypher(q, {"props": {"age": 27}})
    assert res.metrics["plan_cache"] == "miss"
    assert _rows(res) == [{"age": 27}]
    # and the new specialization is itself cached
    res = graph.cypher(q, {"props": {"age": 44}})
    assert res.metrics["plan_cache"] == "hit"
    assert _rows(res) == [{"age": 44}]


# -- normalization ---------------------------------------------------------

def test_whitespace_and_comments_normalize_to_one_entry():
    session = _session()
    graph = create_graph(session, SOCIAL)
    r1 = graph.cypher("MATCH (p:Person) RETURN count(*) AS c")
    r2 = graph.cypher(
        "MATCH  (p:Person)  // comment\n   RETURN count(*)   AS c")
    assert r2.metrics["plan_cache"] == "hit"
    assert _rows(r1) == _rows(r2) == [{"c": 3}]


def test_string_literals_do_not_falsely_normalize():
    session = _session()
    r1 = session.cypher("RETURN 'a b' AS s")
    r2 = session.cypher("RETURN 'a  b' AS s")
    assert _rows(r1) == [{"s": "a b"}]
    assert _rows(r2) == [{"s": "a  b"}]


# -- invalidation ----------------------------------------------------------

def test_catalog_create_drop_invalidates():
    session = _session()
    g1 = create_graph(session, "CREATE (:Person {name: 'A'})")
    session.catalog.store("g", g1)
    q = "FROM GRAPH session.g MATCH (n:Person) RETURN count(*) AS c"
    assert _rows(session.cypher(q)) == [{"c": 1}]
    assert session.cypher(q).metrics["plan_cache"] == "hit"
    before = session.plan_cache.stats()

    # CATALOG mutation: replacing the stored graph bumps the fingerprint
    g2 = create_graph(session,
                      "CREATE (:Person {name: 'B'}), (:Person {name: 'C'})")
    session.catalog.store("g", g2)
    after = session.plan_cache.stats()
    assert after["invalidations"] > before["invalidations"]
    res = session.cypher(q)
    assert res.metrics["plan_cache"] == "miss"
    assert _rows(res) == [{"c": 2}]

    # CATALOG DELETE through the query surface also invalidates
    session.cypher("CATALOG DELETE GRAPH session.g")
    assert session.plan_cache.stats()["invalidations"] > after["invalidations"]
    with pytest.raises(Exception):
        session.cypher(q)


def test_catalog_mutation_eviction_is_scoped():
    """Catalog eviction is scoped per graph name: storing an UNRELATED
    graph leaves another name's dependents cached (the old behavior
    evicted everything on any mutation), while mutating the referenced
    name still invalidates its dependents."""
    session = _session()
    base = create_graph(session, "CREATE (:Person {name: 'A'})")
    session.catalog.store("base", base)
    q = "FROM GRAPH session.base MATCH (n) RETURN count(*) AS c"
    assert _rows(session.cypher(q)) == [{"c": 1}]
    entries_before = session.plan_cache.stats()["entries"]
    assert entries_before >= 1
    # an unrelated catalog mutation: session.base dependents SURVIVE
    session.cypher("CATALOG CREATE GRAPH copy { "
                   "FROM GRAPH session.base RETURN GRAPH }")
    assert session.plan_cache.stats()["entries"] == entries_before
    res = session.cypher(q)
    assert res.metrics["plan_cache"] == "hit"
    assert _rows(res) == [{"c": 1}]
    # mutating the REFERENCED name still evicts its dependents
    inv_before = session.plan_cache.stats()["invalidations"]
    session.catalog.store("base", create_graph(
        session, "CREATE (:Person {name: 'A'}), (:Person {name: 'B'})"))
    assert session.plan_cache.stats()["invalidations"] > inv_before
    res = session.cypher(q)
    assert res.metrics["plan_cache"] == "miss"
    assert _rows(res) == [{"c": 2}]


# -- LRU -------------------------------------------------------------------

def test_lru_eviction_at_plan_cache_size():
    session = _session(plan_cache_size=2)
    graph = create_graph(session, SOCIAL)
    q1 = "MATCH (n:Person) RETURN count(*) AS c"
    q2 = "MATCH (n:Person) WHERE n.age > 30 RETURN count(*) AS c"
    q3 = "MATCH (n:Person) WHERE n.age < 30 RETURN count(*) AS c"
    graph.cypher(q1)
    graph.cypher(q2)
    graph.cypher(q3)  # evicts q1 (LRU)
    stats = session.plan_cache.stats()
    assert stats["entries"] == 2 and stats["evictions"] == 1
    assert graph.cypher(q3).metrics["plan_cache"] == "hit"
    assert graph.cypher(q1).metrics["plan_cache"] == "miss"
    assert _rows(graph.cypher(q1)) == [{"c": 3}]


# -- determinism check / config toggles ------------------------------------

@pytest.mark.parametrize("backend", ["local", "tpu"])
def test_determinism_check_through_cached_path(backend):
    session = _session(backend, determinism_check=True)
    graph = create_graph(session, SOCIAL)
    q = ("MATCH (a:Person)-[:KNOWS]->(b) WHERE a.age > $min "
         "RETURN b.name AS n")
    first = graph.cypher(q, {"min": 30})
    assert "determinism_digest" in first.metrics
    again = graph.cypher(q, {"min": 20})  # replay runs through the cache
    assert "determinism_digest" in again.metrics
    assert session.plan_cache.stats()["hits"] >= 2


def test_plan_cache_disabled_by_config():
    session = _session(use_plan_cache=False)
    graph = create_graph(session, SOCIAL)
    q = "MATCH (n:Person) RETURN count(*) AS c"
    assert graph.cypher(q).metrics["plan_cache"] == "off"
    assert graph.cypher(q).metrics["plan_cache"] == "off"
    assert session.plan_cache.stats()["hits"] == 0


# -- prepared statement API ------------------------------------------------

@pytest.mark.parametrize("backend", ["local", "tpu"])
def test_prepared_query_api(backend):
    session = _session(backend)
    graph = create_graph(session, SOCIAL)
    prep = graph.prepare("MATCH (p:Person) WHERE p.age >= $min "
                         "RETURN p.name AS n ORDER BY n")
    assert [r["n"] for r in _rows(prep.run({"min": 40}))] == ["Bob"]
    res = prep.run({"min": 30})
    assert res.metrics["plan_cache"] == "hit"
    assert [r["n"] for r in _rows(res)] == ["Alice", "Bob"]
    # session.prepare on the ambient graph
    p2 = session.prepare("RETURN $v AS v")
    assert _rows(p2.run({"v": 7})) == [{"v": 7}]
    assert _rows(p2.run({"v": 8})) == [{"v": 8}]


def test_prepare_validates_syntax_eagerly():
    session = _session()
    with pytest.raises(Exception):
        session.prepare("MATCH (n RETURN n")


def test_stats_shape():
    session = _session()
    stats = session.plan_cache.stats()
    assert set(stats) >= {"entries", "hits", "misses", "evictions",
                          "invalidations", "hit_rate", "bytes", "saved_s"}

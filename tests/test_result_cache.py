"""Snapshot-keyed result & subplan caching (ISSUE 17).

The soundness story is version-keyed consistency: results are keyed by
``(result scope, normalized query, param digest)`` and checked against
the snapshot version at lookup, so writes never *invalidate* — they
open a new key space — and a superseded entry can only ever read as a
miss.  Covered here:

* key discipline: plan-family normal form, value-faithful param
  digests, refusal to cache what can't be keyed;
* hit/miss/eviction/stale counter EXACTNESS on a fake clock, including
  the cost-aware admission's half-life recency decay;
* digest parity cached-vs-uncached on both backends;
* write -> miss -> repopulate through the server, retirement on
  commit/compaction, family eviction on quarantine;
* budget never exceeded under an adversarial soak;
* subplan-prefix reuse across two plan families, proven via op metrics
  (the seeded prefix never re-executes, so it never re-appends);
* the ``stale_cache`` fault injector (a forged wrong-version entry is
  rejected, never served);
* fleet: read-your-writes with caching on, and the rejoin fencing
  regression — version gauges and retirement publish UNDER the commit
  lock, before the snapshot flip.
"""
from __future__ import annotations

import threading

import pytest

import caps_tpu
from caps_tpu.frontend.parser import normalize_query
from caps_tpu.obs import clock
from caps_tpu.obs.metrics import MetricsRegistry, merge_snapshots
from caps_tpu.relational.result_cache import (CachedRows, ResultCache,
                                              ResultCacheConfig,
                                              params_digest,
                                              result_cache_key,
                                              result_scope)
from caps_tpu.relational.updates import (delta_state_from_payload,
                                         delta_state_to_payload, versioned)
from caps_tpu.serve import QueryServer, ServerConfig
from caps_tpu.serve.fleet import (BackendSpec, FleetBackend, rows_digest)
from caps_tpu.serve.router import FleetRouter, RouterConfig
from caps_tpu.testing.factory import create_graph
from caps_tpu.testing.faults import failing_operator, stale_cache

SOCIAL = """
    CREATE (a:Person {name: 'Alice', age: 33}),
           (b:Person {name: 'Bob', age: 44}),
           (c:Person {name: 'Carol', age: 27}),
           (d:Person {name: 'Dana', age: 51}),
           (a)-[:KNOWS {since: 2011}]->(b),
           (b)-[:KNOWS {since: 2015}]->(c),
           (a)-[:KNOWS {since: 2019}]->(c),
           (c)-[:KNOWS {since: 2021}]->(d)
"""

Q_AGE = ("MATCH (p:Person) WHERE p.age > $min "
         "RETURN p.name AS n ORDER BY n")
Q_COUNT = "MATCH (p:Person) RETURN count(*) AS c"


def _session(backend="local"):
    return caps_tpu.local_session(backend=backend)


class FakeClock:
    """Same fake as tests/test_telemetry.py: ``sleep`` advances ``now``
    instantly."""

    def __init__(self, t0: float = 1_000.0):
        self._t = t0
        self._lock = threading.Lock()

    def now(self) -> float:
        with self._lock:
            return self._t

    def sleep(self, s: float) -> None:
        self.advance(s)

    def wait(self, event, timeout: float) -> bool:
        if event.is_set():
            return True
        self.advance(timeout)
        return event.is_set()

    def advance(self, s: float) -> None:
        with self._lock:
            self._t += s


@pytest.fixture()
def fake_clock(monkeypatch):
    fc = FakeClock()
    monkeypatch.setattr(clock, "now", fc.now)
    monkeypatch.setattr(clock, "sleep", fc.sleep)
    monkeypatch.setattr(clock, "wait", fc.wait)
    return fc


# -- key discipline ----------------------------------------------------------

def test_params_digest_is_value_faithful():
    a = params_digest({"min": 30, "name": "x"})
    b = params_digest({"name": "x", "min": 30})  # order-insensitive
    assert a == b
    assert params_digest({"min": 31, "name": "x"}) != a
    # an un-tokenizable value refuses to digest rather than collide
    assert params_digest({"min": object()}) is None


def test_result_cache_key_uses_plan_family_normal_form():
    s = _session()
    g = create_graph(s, SOCIAL)
    k1 = result_cache_key(g, Q_AGE, {"min": 30})
    k2 = result_cache_key(g, "  " + Q_AGE.replace(" WHERE", "\n WHERE"),
                          {"min": 30})
    assert k1 is not None and k1 == k2  # whitespace variants share a key
    assert k1[1] == normalize_query(Q_AGE)  # == the plan family string
    assert result_cache_key(g, Q_AGE, {"min": object()}) is None
    # both graphs of one scope agree; distinct graphs never collide
    assert result_cache_key(g, Q_AGE, {"min": 30}) == k1
    g2 = create_graph(s, SOCIAL)
    assert result_cache_key(g2, Q_AGE, {"min": 30})[0] != k1[0]


def test_versioned_lineage_shares_one_scope():
    s = _session()
    vg = versioned(s, create_graph(s, SOCIAL))
    snap0 = vg.current()
    vg.cypher("CREATE (e:Person {name: 'Eve', age: 61})")
    snap1 = vg.current()
    assert snap1.snapshot_version == snap0.snapshot_version + 1
    assert result_scope(snap0) == result_scope(snap1) == result_scope(vg)


def test_cached_rows_hands_out_fresh_copies():
    rows = [{"n": "Alice"}, {"n": "Bob"}]
    cr = CachedRows(rows)
    got = cr.to_maps()
    got[0]["n"] = "MUTATED"
    assert cr.to_maps()[0]["n"] == "Alice"


# -- counter exactness on a fake clock ---------------------------------------

def test_hit_miss_stale_counters_exact(fake_clock):
    rc = ResultCache(ResultCacheConfig(budget_bytes=1 << 20))
    key = (1, "q", ())
    assert rc.lookup(key, 0) is None  # cold
    assert rc.offer(key, 0, [{"c": 4}], nbytes=100, service_s=1.0)
    assert rc.lookup(key, 0) == [{"c": 4}]
    assert rc.lookup(key, 0) == [{"c": 4}]
    # a lookup at any OTHER version drops the entry and misses
    assert rc.lookup(key, 1) is None
    st = rc.stats()
    assert (st["hits"], st["misses"]) == (2, 2)
    assert st["stale_rejects"] == 1
    assert st["insertions"] == 1
    assert st["evictions"] == 1  # the stale drop reclaimed the bytes
    assert st["entries"] == 0 and st["bytes"] == 0
    assert st["hit_ratio"] == pytest.approx(0.5)


def test_cost_aware_admission_half_life_decay_exact(fake_clock):
    # min_benefit_per_byte high enough to discriminate decay steps
    rc = ResultCache(ResultCacheConfig(budget_bytes=1000, half_life_s=30.0,
                                       min_benefit_per_byte=1e-3))
    rows = [{"c": 1}]
    # zero observed service time saves nothing: rejected
    assert not rc.offer((1, "q0", ()), 0, rows, nbytes=100, service_s=0.0)
    # fresh key (one noted miss): p = 1/2, benefit/byte = .8*.5/100 =
    # 4e-3 >= 1e-3 -> admitted
    rc.lookup((1, "q1", ()), 0)
    assert rc.offer((1, "q1", ()), 0, rows, nbytes=100, service_s=0.8)
    # three half-lives of silence: p = .5 * .125, benefit/byte = 5e-4
    # < 1e-3 -> rejected, EXACTLY at the decayed estimate
    rc.lookup((1, "q2", ()), 0)
    fake_clock.advance(90.0)
    assert not rc.offer((1, "q2", ()), 0, rows, nbytes=100, service_s=0.8)
    # no single entry over max_entry_fraction of the budget
    assert not rc.offer((1, "q3", ()), 0, rows, nbytes=251, service_s=9.0)
    assert rc.stats()["admission_rejects"] == 3


def test_budget_never_exceeded_adversarial_soak(fake_clock):
    budget = 4096
    rc = ResultCache(ResultCacheConfig(budget_bytes=budget, max_entries=8,
                                       min_benefit_per_byte=1e-12))
    for i in range(50):
        key = (1, f"q{i}", ())
        rc.lookup(key, 0)  # note the miss (re-hit estimator state)
        rc.offer(key, 0, [{"i": i}], nbytes=1000, service_s=1.0)
        assert rc.bytes <= budget, (i, rc.bytes)
        assert rc.entries <= 8
    st = rc.stats()
    assert st["evictions"] > 0
    assert st["insertions"] == 50
    assert st["bytes"] <= budget


# -- serving integration -----------------------------------------------------

def _server(session, graph, **cfg):
    cfg.setdefault("workers", 1)
    cfg.setdefault("result_cache", ResultCacheConfig(budget_bytes=1 << 20))
    return QueryServer(session, graph=graph, config=ServerConfig(**cfg))


@pytest.mark.parametrize("backend", ["local", "tpu"])
def test_digest_parity_cached_vs_uncached(make_session, backend):
    session = make_session(backend)
    graph = create_graph(session, SOCIAL)
    want = rows_digest(graph.cypher(Q_AGE, {"min": 30})
                       .records.to_maps())  # uncached ground truth
    with _server(session, graph) as server:
        h1 = server.submit(Q_AGE, {"min": 30})
        d1 = rows_digest(h1.rows(timeout=30))
        h2 = server.submit(Q_AGE, {"min": 30})
        d2 = rows_digest(h2.rows(timeout=30))
        assert h1.info.get("cache") != "hit"
        assert h2.info["cache"] == "hit"
        # handle.result() works on hits too (CachedRows shim)
        assert h2.result().to_maps() == h1.rows()
    assert want == d1 == d2


def test_cache_hit_skips_queue_and_stamps_flight_record():
    session = _session()
    graph = create_graph(session, SOCIAL)
    with _server(session, graph) as server:
        server.run(Q_AGE, {"min": 30})
        h = server.submit(Q_AGE, {"min": 30})
        h.rows(timeout=30)
        assert h.info["cache"] == "hit"
        assert h.info["queue_wait_s"] == 0.0
        recs = [r for r in server.telemetry.recorder.snapshot()
                if r.get("outcome") == "cache_hit"]
        assert recs and recs[-1]["phase"] == "cache"
        assert recs[-1]["device"] is None  # no device dwell on a hit
        # the ledger gauge sees the resident bytes
        snap = session.metrics_snapshot()
        assert snap["mem.result_cache_bytes"] == server.result_cache.bytes
        assert snap["mem.result_cache_bytes"] > 0
        assert snap["rescache.hits"] >= 1


def test_write_new_version_misses_then_repopulates():
    session = _session()
    vg = versioned(session, create_graph(session, SOCIAL))
    with _server(session, vg) as server:
        h0 = server.submit(Q_AGE, {"min": 30})
        assert [r["n"] for r in h0.rows(timeout=30)] \
            == ["Alice", "Bob", "Dana"]
        h1 = server.submit(Q_AGE, {"min": 30})
        h1.rows(timeout=30)
        assert h1.info["cache"] == "hit"
        server.run("CREATE (e:Person {name: 'Zed', age: 70})")
        # the write opened a NEW key space: the read below must re-
        # execute at the new version, never serve the superseded rows
        h2 = server.submit(Q_AGE, {"min": 30})
        rows = h2.rows(timeout=30)
        assert h2.info.get("cache") != "hit"
        assert h2.info["snapshot_version"] \
            == vg.current().snapshot_version
        assert [r["n"] for r in rows] == ["Alice", "Bob", "Dana", "Zed"]
        # superseded-version entries were RETIRED by the commit...
        assert session.metrics_snapshot()["rescache.retired"] >= 1
        # ...and the new version repopulates
        h3 = server.submit(Q_AGE, {"min": 30})
        assert h3.rows(timeout=30) == rows
        assert h3.info["cache"] == "hit"


def test_commit_and_compaction_retire_superseded_entries():
    session = _session()
    rc = ResultCache(ResultCacheConfig(),
                     registry=session.metrics_registry)
    session.result_cache = rc
    vg = versioned(session, create_graph(session, SOCIAL))
    scope = result_scope(vg.current())
    v0 = vg.current().snapshot_version
    rc.lookup((scope, "fam", ()), v0)
    assert rc.offer((scope, "fam", ()), v0, [{"c": 1}], service_s=1.0)
    vg.cypher("CREATE (e:Person {name: 'Eve', age: 61})")
    assert rc.entries == 0  # the commit retired the version-0 entry
    assert rc.stats()["retired"] == 1
    v1 = vg.current().snapshot_version
    rc.lookup((scope, "fam", ()), v1)
    assert rc.offer((scope, "fam", ()), v1, [{"c": 2}], service_s=1.0)
    assert vg.compact() is True
    # compaction publishes a NEWER snapshot: version-1 entries retire
    assert rc.entries == 0
    assert rc.stats()["retired"] == 2
    assert vg.current().snapshot_version > v1


def test_quarantine_evicts_the_familys_results():
    session = _session()
    graph = create_graph(session, SOCIAL)
    graph.cypher(Q_AGE, {"min": 30})  # park a cached plan to poison
    with _server(session, graph) as server:
        rc = server.result_cache
        # resident entry for the SAME family, different binding (the
        # poisoned submission itself must miss, or it never executes)
        server.run(Q_AGE, {"min": 30})
        assert rc.entries == 1
        evicted0 = rc.stats()["evictions"]
        with failing_operator("OrderBy", exc=RuntimeError("poison"),
                              n_times=1):
            h = server.submit(Q_AGE, {"min": 40})
            assert [r["n"] for r in h.rows(timeout=30)] == ["Bob", "Dana"]
        snap = session.metrics_snapshot()
        assert snap["serve.quarantined"] >= 1
        # the quarantined family's resident results were evicted —
        # poisoned rows cannot linger.  (The degraded replan's OWN fresh
        # result may repopulate afterwards; that one is sound.)
        assert rc.stats()["evictions"] > evicted0
        h2 = server.submit(Q_AGE, {"min": 30})
        h2.rows(timeout=30)
        assert h2.info.get("cache") != "hit"  # re-executed, not served


def test_stale_cache_injector_is_rejected_not_served():
    session = _session()
    graph = create_graph(session, SOCIAL)
    with _server(session, graph) as server:
        want = server.run(Q_AGE, {"min": 30}).to_maps()
        h = server.submit(Q_AGE, {"min": 30})
        h.rows(timeout=30)
        assert h.info["cache"] == "hit"  # resident before the forgery
        before = session.metrics_snapshot()
        with stale_cache(n_times=1) as budget:
            h2 = server.submit(Q_AGE, {"min": 30})
            rows = h2.rows(timeout=30)
        assert budget.injected == 1
        # the forged wrong-version entry was REJECTED: the read re-
        # executed and still returned the right rows
        assert rows == want
        assert h2.info.get("cache") != "hit"
        delta_snap = session.metrics_snapshot()
        assert delta_snap["rescache.stale_rejects"] \
            == before.get("rescache.stale_rejects", 0) + 1
        from caps_tpu.obs.metrics import global_registry
        assert global_registry().snapshot()[
            "faults.injected.stale_cache"] >= 1


# -- subplan memoization -----------------------------------------------------

def test_subplan_prefix_reused_across_two_plan_families():
    session = _session()
    rc = ResultCache(ResultCacheConfig(),
                     registry=session.metrics_registry)
    session.result_cache = rc
    graph = create_graph(session, SOCIAL)
    r1 = graph.cypher(Q_COUNT)
    assert r1.records.to_maps() == [{"c": 4}]
    assert rc.stats()["subplan_entries"] >= 1  # the Scan prefix parked
    # a DIFFERENT plan family sharing the scan prefix: its op metrics
    # must show the prefix never re-executed (a seeded memo skips both
    # _compute and the metrics append — the observable proof)
    hits0 = rc.stats()["subplan_hits"]
    r2 = graph.cypher("MATCH (p:Person) RETURN p.age AS a ORDER BY a")
    assert [r["a"] for r in r2.records.to_maps()] == [27, 33, 44, 51]
    assert rc.stats()["subplan_hits"] == hits0 + 1
    ops_run = [m["op"] for m in r2.metrics["operators"]]
    assert not any(o.startswith("Scan") for o in ops_run), ops_run


def test_parameterized_filter_prefix_is_not_memoized():
    session = _session()
    rc = ResultCache(ResultCacheConfig(),
                     registry=session.metrics_registry)
    session.result_cache = rc
    graph = create_graph(session, SOCIAL)
    # $min reads a binding: the filter prefix computes different rows
    # per binding and must never cross-serve them
    a = graph.cypher(Q_AGE, {"min": 30}).records.to_maps()
    b = graph.cypher(Q_AGE, {"min": 40}).records.to_maps()
    assert [r["n"] for r in a] == ["Alice", "Bob", "Dana"]
    assert [r["n"] for r in b] == ["Bob", "Dana"]


# -- fleet -------------------------------------------------------------------

def test_merge_snapshots_recomputes_hit_ratio():
    a = {"rescache.hits": 8, "rescache.misses": 2,
         "rescache.hit_ratio": 0.8}
    b = {"rescache.hits": 0, "rescache.misses": 10,
         "rescache.hit_ratio": 0.0}
    merged = merge_snapshots([a, b])
    # summed hits/misses, ratio RECOMPUTED (not summed to 0.8)
    assert merged["rescache.hits"] == 8
    assert merged["rescache.misses"] == 12
    assert merged["rescache.hit_ratio"] == pytest.approx(0.4)


def test_install_state_publishes_under_lock_before_flip():
    """The rejoin fencing regression: ``on_install`` (gauge publication
    + retirement) runs BEFORE the reference swap, so no reader can be
    admitted at a version the gauges don't yet report."""
    s1 = _session()
    vg1 = versioned(s1, create_graph(s1, "CREATE (:Seed {k:-1, v:-1})"))
    vg1.cypher("CREATE (:Item {k: 1, v: 7})")
    payload = delta_state_to_payload(vg1.current().state)

    s2 = _session()
    rc = ResultCache(ResultCacheConfig(), registry=s2.metrics_registry)
    s2.result_cache = rc
    vg2 = versioned(s2, create_graph(s2, "CREATE (:Seed {k:-1, v:-1})"))
    scope = result_scope(vg2.current())
    rc.lookup((scope, "fam", ()), 0)
    assert rc.offer((scope, "fam", ()), 0, [{"c": 0}], service_s=1.0)

    seen = {}

    def publish(new_snap):
        # inside the commit lock: the new version must NOT be readable
        # yet, and the superseded entry must ALREADY be retired
        seen["flip_published"] = (vg2.current().snapshot_version
                                  == new_snap.snapshot_version)
        seen["retired_first"] = rc.entries == 0
        seen["version"] = new_snap.snapshot_version

    snap = vg2.install_state(delta_state_from_payload(payload), 1,
                             on_install=publish)
    assert seen == {"flip_published": False, "retired_first": True,
                    "version": 1}
    assert snap.snapshot_version == 1
    assert vg2.current().snapshot_version == 1
    # idempotent re-install still re-publishes (a rejoining peer's
    # gauges must not stay stale forever)
    seen.clear()
    vg2.install_state(delta_state_from_payload(payload), 1,
                      on_install=publish)
    assert seen["version"] == 1 and seen["flip_published"] is True


def test_fleet_read_your_writes_with_caching_on():
    spec = {"kind": "script", "create": SOCIAL}
    objs, backends = {}, {}
    for name in ("b0", "b1"):
        b = FleetBackend(BackendSpec(name=name, backend="local",
                                     graph=spec, versioned=True,
                                     result_cache_budget=1 << 20))
        objs[name] = b
        backends[name] = ("127.0.0.1", b.port)
    router = FleetRouter(backends, owner="b0",
                         config=RouterConfig(max_attempts=3),
                         registry=MetricsRegistry())
    try:
        # warm a family to cache residency on its affinity backend
        for _ in range(3):
            out = router.query(Q_AGE, {"min": 30}, family="hot")
        merged = merge_snapshots([b.session.metrics_registry.snapshot()
                                  for b in objs.values()])
        assert merged["rescache.hits"] >= 1
        # write -> ship: EVERY backend must serve the new version
        # immediately (read-your-writes), caching on — zero stale
        wrote = router.write("CREATE (e:Person {name: 'Eve', age: 61})")
        assert wrote["version"] == 1
        digests = set()
        for name, b in objs.items():
            rep = router._clients[name].call(
                "query", query=Q_AGE, params={"min": 30}, digest=True)
            assert rep["snapshot_version"] == 1
            assert any(r["n"] == "Eve" for r in rep["rows"])
            digests.add(rep["digest"])
            # the fencing publication: the gauge reports the version
            # every served read carries
            snap = b.session.metrics_registry.snapshot()
            if name != router.owner:
                assert snap["fleet.snapshot_version"] == 1.0
                assert snap["fleet.snapshots_installed"] >= 1
        assert len(digests) == 1
        # repeated reads at the new version become hits again
        for _ in range(2):
            out = router.query(Q_AGE, {"min": 30}, family="hot")
            assert any(r["n"] == "Eve" for r in out["rows"])
        merged = merge_snapshots([b.session.metrics_registry.snapshot()
                                  for b in objs.values()])
        assert merged["rescache.misses"] >= 1
        assert merged["rescache.hit_ratio"] == pytest.approx(
            merged["rescache.hits"]
            / (merged["rescache.hits"] + merged["rescache.misses"]))
    finally:
        router.close()
        for b in objs.values():
            b.shutdown(drain=False)


def test_shutdown_detaches_and_clears_the_cache():
    session = _session()
    graph = create_graph(session, SOCIAL)
    server = _server(session, graph)
    server.run(Q_AGE, {"min": 30})
    rc = server.result_cache
    assert session.result_cache is rc and rc.bytes > 0
    server.shutdown()
    assert session.result_cache is None
    assert rc.bytes == 0 and rc.entries == 0

from caps_tpu.okapi.schema import Schema
from caps_tpu.okapi.types import CTFloat, CTInteger, CTNumber, CTString


def test_node_property_keys_exact_combo():
    s = Schema.empty().with_node_property_keys(
        ["Person"], {"name": CTString, "age": CTInteger})
    assert s.node_property_keys(["Person"]) == {"name": CTString, "age": CTInteger}
    assert s.labels == frozenset({"Person"})


def test_union_over_combos_makes_missing_nullable():
    s = (Schema.empty()
         .with_node_property_keys(["Person"], {"name": CTString, "age": CTInteger})
         .with_node_property_keys(["Person", "Admin"], {"name": CTString, "level": CTInteger}))
    keys = s.node_property_keys(["Person"])
    assert keys["name"] == CTString
    assert keys["age"] == CTInteger.nullable
    assert keys["level"] == CTInteger.nullable
    # exact combo query only sees its own keys
    assert set(s.property_keys_for_combo(["Person"])) == {"name", "age"}


def test_same_combo_twice_joins_types():
    s = (Schema.empty()
         .with_node_property_keys(["A"], {"x": CTInteger})
         .with_node_property_keys(["A"], {"x": CTFloat, "y": CTString}))
    keys = s.node_property_keys(["A"])
    assert keys["x"] == CTNumber
    assert keys["y"] == CTString.nullable


def test_relationship_keys():
    s = (Schema.empty()
         .with_relationship_property_keys("KNOWS", {"since": CTInteger})
         .with_relationship_property_keys("LIKES", {"since": CTFloat, "how": CTString}))
    assert s.relationship_types == frozenset({"KNOWS", "LIKES"})
    both = s.relationship_property_keys()
    assert both["since"] == CTNumber
    assert both["how"] == CTString.nullable
    assert s.relationship_property_keys(["KNOWS"]) == {"since": CTInteger}


def test_schema_union():
    a = Schema.empty().with_node_property_keys(["A"], {"x": CTInteger})
    b = (Schema.empty()
         .with_node_property_keys(["A"], {"x": CTInteger, "y": CTString})
         .with_relationship_property_keys("R", {}))
    u = a + b
    assert u.node_property_keys(["A"])["y"] == CTString.nullable
    assert u.relationship_types == frozenset({"R"})


def test_combinations_for():
    s = (Schema.empty()
         .with_node_property_keys(["A"], {})
         .with_node_property_keys(["A", "B"], {})
         .with_node_property_keys(["C"], {}))
    assert set(s.combinations_for(["A"])) == {frozenset({"A"}), frozenset({"A", "B"})}
    assert set(s.combinations_for([])) == set(s.label_combinations)

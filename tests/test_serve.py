"""The serving tier (caps_tpu/serve/) and the thread-safety audit.

Acceptance contract under test (ISSUE 4): a stress run with >= 8 client
threads and >= 200 mixed prepared queries completes with zero errors and
results identical to sequential execution (order-insensitive bags); the
micro-batcher demonstrably coalesces (batch-size histogram max > 1); an
over-capacity burst sheds with typed ``Overloaded``; a deadline-injected
query fails with a phase-attributed error and trace span.  Plus the
satellite audit: PlanCache LRU mutation, catalog-subscription eviction,
and MetricsRegistry updates are safe under concurrent threads (these
direct two-thread stress tests fail on the unlocked seed code).
"""
from __future__ import annotations

import sys
import threading

import pytest

import caps_tpu
from caps_tpu.okapi.config import EngineConfig
from caps_tpu.serve import (BATCH, INTERACTIVE, Cancelled, CancelScope,
                            DeadlineExceeded, Overloaded, QueryServer,
                            ServerConfig, ServerClosed)
from caps_tpu.serve.admission import AdmissionController
from caps_tpu.serve.request import Request
from caps_tpu.testing.factory import create_graph
from caps_tpu.testing.faults import slow_operator

SOCIAL = """
    CREATE (a:Person {name: 'Alice', age: 33}),
           (b:Person {name: 'Bob', age: 44}),
           (c:Person {name: 'Carol', age: 27}),
           (d:Person {name: 'Dana', age: 51}),
           (a)-[:KNOWS {since: 2011}]->(b),
           (b)-[:KNOWS {since: 2015}]->(c),
           (a)-[:KNOWS {since: 2019}]->(c),
           (c)-[:KNOWS {since: 2021}]->(d)
"""

# The "mixed prepared queries" of the stress run: three distinct plan
# families, each with rotating bindings.
QUERIES = [
    ("MATCH (p:Person) WHERE p.age > $min RETURN p.name AS n ORDER BY n",
     [{"min": m} for m in (20, 30, 40, 50)]),
    ("MATCH (a:Person)-[:KNOWS]->(b) WHERE a.age > $min "
     "RETURN a.name AS a, b.name AS b",
     [{"min": m} for m in (25, 35, 45)]),
    ("MATCH (a:Person)-[k:KNOWS]->(b) WHERE k.since >= $y "
     "RETURN count(*) AS c", [{"y": y} for y in (2011, 2015, 2020)]),
]


def _session(backend="local", **cfg):
    return caps_tpu.local_session(backend=backend,
                                  config=EngineConfig(**cfg) if cfg else None)


def _bag(rows):
    return sorted(sorted(r.items()) for r in rows)


def _graph(session):
    return create_graph(session, SOCIAL)


def _expected(graph):
    """Sequential reference execution of every (query, binding)."""
    return {(q, i): _bag(graph.cypher(q, b).records.to_maps())
            for q, bindings in QUERIES for i, b in enumerate(bindings)}


# -- basic serving ---------------------------------------------------------

@pytest.mark.parametrize("backend", ["local", "tpu"])
def test_submit_and_rows(backend):
    session = _session(backend)
    graph = _graph(session)
    with QueryServer(session, graph=graph) as server:
        h = server.submit(QUERIES[0][0], {"min": 30})
        assert [r["n"] for r in h.rows(timeout=30)] == ["Alice", "Bob",
                                                        "Dana"]
        assert h.done() and h.exception() is None
        assert h.info["batch_size"] >= 1 and "latency_s" in h.info
        # blocking convenience call
        res = server.run(QUERIES[2][0], {"y": 2015})
        assert res.to_maps() == [{"c": 3}]


def test_submit_after_shutdown_raises():
    session = _session()
    server = QueryServer(session, graph=_graph(session))
    server.shutdown()
    with pytest.raises(ServerClosed):
        server.submit("MATCH (n) RETURN n")


def test_explain_and_profile_through_server_never_batched():
    session = _session()
    graph = _graph(session)
    q = QUERIES[0][0]
    server = QueryServer(session, graph=graph, start=False,
                         config=ServerConfig(workers=1, max_batch=8))
    plain = [server.submit(q, {"min": 20}) for _ in range(3)]
    prof = server.submit("PROFILE " + q, {"min": 20})
    expl = server.submit("EXPLAIN " + q, {"min": 20})
    server.start()
    server.shutdown()  # drain completes everything queued
    assert plain[0].info["batch_size"] == 3  # compatible plain ones coalesce
    assert prof.info["batch_size"] == 1      # PROFILE executes alone
    assert expl.info["batch_size"] == 1
    assert prof.result().profile is not None
    assert "relational" in expl.result().plans
    assert expl.result().records is None


# -- micro-batching --------------------------------------------------------

def test_batch_coalesces_compatible_only():
    session = _session()
    graph = _graph(session)
    server = QueryServer(session, graph=graph, start=False,
                         config=ServerConfig(workers=1, max_batch=16))
    same = [server.submit(QUERIES[0][0], {"min": m})
            for m in (20, 30, 40, 50)]
    other = server.submit(QUERIES[2][0], {"y": 2015})
    # same normalized text but different param SIGNATURE: incompatible
    diverged = server.submit(QUERIES[0][0], {"min": 30.5})
    server.start()
    server.shutdown()
    assert [h.info["batch_size"] for h in same] == [4, 4, 4, 4]
    assert other.info["batch_size"] == 1
    assert diverged.info["batch_size"] == 1
    assert [r["n"] for r in diverged.rows()] == ["Alice", "Bob", "Dana"]
    batch_max = session.metrics_registry.histogram("serve.batch_size").max
    assert batch_max == 4


def test_cypher_batch_isolates_member_failures():
    session = _session()
    graph = _graph(session)
    q = QUERIES[0][0]
    graph.cypher(q, {"min": 20})  # warm the plan cache
    expired = CancelScope(budget_s=0.0)
    live = CancelScope(budget_s=None)
    out = session.cypher_batch(graph, [(q, {"min": 20}), (q, {"min": 30})],
                               scopes=[expired, live])
    assert isinstance(out[0], DeadlineExceeded)
    assert [r["n"] for r in out[1].records.to_maps()] == ["Alice", "Bob",
                                                          "Dana"]


# -- admission control -----------------------------------------------------

def _mk_request(priority=INTERACTIVE, key=None, query="q"):
    return Request(query, {}, None, priority, CancelScope(), key, None)


def test_admission_priority_order_and_shed():
    from caps_tpu.obs.metrics import MetricsRegistry
    reg = MetricsRegistry()
    adm = AdmissionController(reg, max_queue=3,
                              per_priority_limits={BATCH: 1})
    lo = _mk_request(priority=BATCH)
    adm.offer(lo)
    with pytest.raises(Overloaded) as ex:  # per-priority cap, queue not full
        adm.offer(_mk_request(priority=BATCH))
    assert ex.value.retry_after_s > 0 and ex.value.priority == BATCH
    hi1, hi2 = _mk_request(), _mk_request()
    adm.offer(hi1)
    adm.offer(hi2)
    with pytest.raises(Overloaded):        # global bound
        adm.offer(_mk_request())
    assert reg.counter("serve.shed").value == 2
    # strict priority order, FIFO within a class
    assert adm.take(0) is hi1 and adm.take(0) is hi2 and adm.take(0) is lo
    assert adm.take(0) is None


def test_overload_burst_sheds_and_recovers():
    session = _session()
    graph = _graph(session)
    server = QueryServer(session, graph=graph, start=False,
                         config=ServerConfig(workers=2, max_queue=4))
    handles, sheds = [], []

    def client():
        try:
            handles.append(server.submit(QUERIES[0][0], {"min": 20}))
        except Overloaded as ex:
            sheds.append(ex)

    threads = [threading.Thread(target=client) for _ in range(12)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(handles) == 4 and len(sheds) == 8
    assert all(ex.retry_after_s > 0 for ex in sheds)
    server.start()
    server.shutdown()  # graceful drain: admitted work still completes
    for h in handles:
        assert [r["n"] for r in h.rows()] == ["Alice", "Bob", "Carol",
                                              "Dana"]
    snap = session.metrics_snapshot()
    assert snap["serve.shed"] == 8 and snap["serve.completed"] == 4


def test_shutdown_drains_never_started_server():
    session = _session()
    graph = _graph(session)
    server = QueryServer(session, graph=graph, start=False)
    h = server.submit(QUERIES[0][0], {"min": 30})
    server.shutdown()  # drain=True must still complete the backlog
    assert [r["n"] for r in h.rows(timeout=30)] == ["Alice", "Bob", "Dana"]


def test_two_servers_share_one_session_exec_lock():
    session = _session()
    graph = _graph(session)
    a = QueryServer(session, graph=graph)
    b = QueryServer(session, graph=graph)
    # per-session, not per-server: both servers' replica 0 serializes
    # through the one lock attached to the shared session
    assert a.devices.replicas[0].lock is b.devices.replicas[0].lock
    ha = a.submit(QUERIES[0][0], {"min": 30})
    hb = b.submit(QUERIES[0][0], {"min": 40})
    assert [r["n"] for r in ha.rows(timeout=30)] == ["Alice", "Bob",
                                                     "Dana"]
    assert [r["n"] for r in hb.rows(timeout=30)] == ["Bob", "Dana"]
    a.shutdown()
    # closing a controller releases the queue-depth gauge unless the
    # other server's controller took it over
    b.shutdown()
    assert session.metrics_snapshot()["serve.queue_depth"] == 0


def test_shutdown_without_drain_cancels_queued():
    session = _session()
    graph = _graph(session)
    server = QueryServer(session, graph=graph, start=False,
                         config=ServerConfig(workers=1))
    h = server.submit(QUERIES[0][0], {"min": 20})
    server.shutdown(drain=False)
    with pytest.raises(Cancelled):
        h.result(timeout=5)


# -- deadlines and cancellation --------------------------------------------

def test_deadline_expired_in_queue():
    session = _session()
    graph = _graph(session)
    with QueryServer(session, graph=graph) as server:
        h = server.submit(QUERIES[0][0], {"min": 20}, deadline_s=0.0)
        with pytest.raises(DeadlineExceeded) as ex:
            h.result(timeout=10)
        assert ex.value.phase == "queued"
        assert session.metrics_snapshot()["serve.deadline_exceeded"] == 1


def test_deadline_in_execute_phase_with_trace_span():
    session = _session(trace=True)
    graph = _graph(session)
    q = QUERIES[0][0]
    graph.cypher(q, {"min": 20})  # warm: expiry hits the cached-plan path
    with QueryServer(session, graph=graph) as server:
        with slow_operator("Filter", 0.2):
            h = server.submit(q, {"min": 20}, deadline_s=0.05)
            with pytest.raises(DeadlineExceeded) as ex:
                h.result(timeout=10)
    assert ex.value.phase == "execute"
    assert ex.value.budget_s == 0.05 and ex.value.elapsed_s >= 0.05

    def walk(spans):
        for sp in spans:
            yield sp
            yield from walk(sp.children)

    spans = list(walk(session.tracer.spans))
    events = [sp for sp in spans if sp.name == "deadline.exceeded"]
    assert events and events[0].attrs["phase"] == "execute"
    assert any(sp.attrs.get("error") == "DeadlineExceeded" for sp in spans)


def test_cancel_queued_request():
    session = _session()
    graph = _graph(session)
    server = QueryServer(session, graph=graph, start=False)
    h = server.submit(QUERIES[0][0], {"min": 20})
    assert h.cancel() is True
    server.start()
    with pytest.raises(Cancelled):
        h.result(timeout=10)
    server.shutdown()
    assert h.cancel() is False  # nothing left to cancel


def test_cancel_running_request_cooperatively():
    session = _session()
    graph = _graph(session)
    with QueryServer(session, graph=graph) as server:
        with slow_operator("Scan", 0.5):
            h = server.submit(QUERIES[0][0], {"min": 20})
            h.wait(timeout=0.1)  # let it reach the slow operator
            h.cancel()
            with pytest.raises(Cancelled) as ex:
                h.result(timeout=10)
    assert ex.value.phase == "execute"


def test_aborted_cached_execution_leaves_no_pinned_results():
    from caps_tpu.serve import cancel_scope
    session = _session()
    graph = _graph(session)
    q = QUERIES[0][0]
    graph.cypher(q, {"min": 20})  # warm: park a cached plan
    key = session._plan_cache_key(graph, q, {"min": 20})
    plan = session.plan_cache.lookup(key, {"min": 20})
    assert plan is not None
    with slow_operator("Filter", 0.05):
        with cancel_scope(CancelScope(budget_s=0.01)):
            with pytest.raises(DeadlineExceeded):
                graph.cypher(q, {"min": 20})
    # the abort unwound mid-tree, but the parked plan must retain no
    # operator result memos (they pin device tables between runs)
    stack, seen = [plan.root], set()
    while stack:
        op = stack.pop()
        if id(op) in seen:
            continue
        seen.add(id(op))
        assert op._result is None
        stack.extend(op.children)
    # and the plan still executes correctly afterwards
    assert [r["n"] for r in graph.cypher(q, {"min": 30}).records.to_maps()
            ] == ["Alice", "Bob", "Dana"]


def test_slow_operator_validates_and_restores():
    from caps_tpu.relational import ops as R
    orig = R.FilterOp._compute
    with pytest.raises(ValueError):
        with slow_operator("NoSuchOp", 0.1):
            pass
    with slow_operator("FilterOp", 0.0):
        assert R.FilterOp._compute is not orig
    assert R.FilterOp._compute is orig


# -- the acceptance stress run ---------------------------------------------

def _stress(backend: str, n_threads: int, per_thread: int,
            workers: int = 4) -> None:
    session = _session(backend)
    graph = _graph(session)
    expected = _expected(graph)
    server = QueryServer(session, graph=graph, start=False,
                         config=ServerConfig(workers=workers,
                                             max_queue=4096, max_batch=8))
    results: dict = {}
    failures: list = []

    def client(tid: int):
        try:
            flat = [(q, i, b) for q, bindings in QUERIES
                    for i, b in enumerate(bindings)]
            for j in range(per_thread):
                q, i, b = flat[(tid + j) % len(flat)]
                h = server.submit(q, b)
                results[(tid, j)] = ((q, i), h)
        except Exception as ex:  # pragma: no cover — the test must fail
            failures.append(ex)

    threads = [threading.Thread(target=client, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    # start mid-burst: some requests are served while others still queue
    server.start()
    for t in threads:
        t.join()
    server.shutdown()  # graceful drain
    assert not failures, failures
    assert len(results) == n_threads * per_thread
    for (q_i), handle in results.values():
        assert _bag(handle.rows(timeout=60)) == expected[q_i], q_i
    snap = session.metrics_snapshot()
    assert snap["serve.completed"] == n_threads * per_thread
    assert snap["serve.failed"] == 0 and snap["serve.shed"] == 0
    # the micro-batcher demonstrably coalesced
    assert snap["serve.batch_size.max"] > 1
    # served plans really came from the shared cache
    assert snap["plan_cache.hits"] > 0


def test_stress_eight_threads_two_hundred_queries():
    # ISSUE 4 acceptance: >= 8 client threads, >= 200 mixed prepared
    # queries, zero errors, results == sequential, batch max > 1.
    _stress("local", n_threads=8, per_thread=25)


@pytest.mark.slow
def test_stress_long_tpu_backend():
    _stress("tpu", n_threads=8, per_thread=40)


@pytest.mark.slow
def test_stress_long_sixteen_threads():
    _stress("local", n_threads=16, per_thread=64)


# -- thread-safety audit (satellite): fails on the unlocked seed code ------

@pytest.fixture()
def fast_switching():
    """Shrink the bytecode switch interval so read-modify-write races
    manifest reliably within a short test."""
    prev = sys.getswitchinterval()
    sys.setswitchinterval(1e-6)
    try:
        yield
    finally:
        sys.setswitchinterval(prev)


def _hammer(fn, n_threads=2, iters=20_000):
    threads = [threading.Thread(target=lambda: [fn() for _ in range(iters)])
               for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return n_threads * iters


def test_counter_concurrent_increments_exact(fast_switching):
    from caps_tpu.obs.metrics import MetricsRegistry
    reg = MetricsRegistry()
    c = reg.counter("t.c")
    total = _hammer(c.inc)
    assert c.value == total  # seed code loses updates (naked +=)


def test_histogram_concurrent_observes_exact(fast_switching):
    from caps_tpu.obs.metrics import MetricsRegistry
    reg = MetricsRegistry()
    h = reg.histogram("t.h")
    total = _hammer(lambda: h.observe(0.5), iters=10_000)
    snap = h.snapshot()
    assert snap["count"] == total and snap["sum"] == pytest.approx(
        0.5 * total)


def test_registry_get_or_create_race(fast_switching):
    from caps_tpu.obs.metrics import MetricsRegistry
    reg = MetricsRegistry()
    seen = []

    def one(i):
        def run():
            for j in range(2_000):
                reg.counter(f"t.{j % 97}").inc()
            seen.append(i)
        return run

    threads = [threading.Thread(target=one(i)) for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(seen) == 4
    # every name resolved to ONE instrument; totals are exact
    total = sum(reg.counter(f"t.{k}").value for k in range(97))
    assert total == 4 * 2_000


def test_plan_cache_concurrent_store_lookup_invariant(fast_switching):
    from caps_tpu.relational.plan_cache import CachedPlan, PlanCache

    class _Op:
        children = ()
        _result = None

    def entry():
        return CachedPlan(root=_Op(), result_fields=("x",), plans={},
                          records_graph=None, context=None, spec_key=(),
                          cold_phase_s=0.0, nbytes=64)

    # On the unlocked seed code this fails in two ways: a KeyError out
    # of store()'s move_to_end racing another thread's LRU popitem, and
    # a _count that drifts from the real entry total (store's
    # append/count/evict sequence interleaves) — verified against a
    # seed-shaped replica before locking landed.
    cache = PlanCache(max_size=50)
    errors = []

    def writer(base):
        try:
            for j in range(2_000):
                key = (f"q{base}-{j % 120}", 1, 0, ())
                cache.store(key, entry())
                cache.lookup(key, {})
        except Exception as ex:  # pragma: no cover
            errors.append(ex)

    threads = [threading.Thread(target=writer, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors
    # the LRU bookkeeping stayed consistent under concurrent mutation
    assert cache.size == sum(len(v) for v in cache._entries.values())
    assert cache.size <= cache.max_size


def test_catalog_mutation_concurrent_with_subscription_eviction(
        fast_switching):
    session = _session()
    graph = _graph(session)
    q = QUERIES[0][0]
    errors = []
    stop = threading.Event()

    def mutator():
        try:
            for i in range(200):
                session.catalog.store(f"session.g{i % 5}", graph)
                session.catalog.delete(f"session.g{i % 5}")
        except Exception as ex:  # pragma: no cover
            errors.append(ex)
        finally:
            stop.set()

    def querier():
        try:
            # at least one query even if the mutator wins every
            # timeslice (single-core schedulers can finish all 200
            # mutations before this thread first runs)
            ran_once = False
            while not ran_once or not stop.is_set():
                ran_once = True
                graph.cypher(q, {"min": 20}).records.to_maps()
        except Exception as ex:  # pragma: no cover
            errors.append(ex)

    threads = [threading.Thread(target=mutator),
               threading.Thread(target=querier)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors
    # Scoped eviction: the querier's plan has NO catalog dependencies
    # (it runs on the graph object, not FROM GRAPH), so 400 interleaved
    # catalog mutations must not have evicted it — and any entry that
    # DOES carry catalog deps validates against the live catalog
    # (stale-dep entries are dropped at lookup, never served).
    cache = session.plan_cache
    with cache._lock:
        plans = [p for ps in cache._entries.values() for p in ps]
    assert plans, "the hot query's plan should still be cached"
    for p in plans:
        for qgn, tok in p.catalog_deps:
            assert session.catalog.dep_token(qgn) == tok
    # and the cached plan still serves correct results afterwards
    res = graph.cypher(q, {"min": 20})
    assert res.metrics["plan_cache"] == "hit"
    assert [r["n"] for r in res.records.to_maps()
            ] == ["Alice", "Bob", "Carol", "Dana"]

"""Sharded serving (ISSUE 13): partitioned graphs behind QueryServer,
shard-group fault domains, and host-memory partition paging.

The contracts under test:

* partitioning — every node lands on exactly one partition (hashed by
  the partition property, stable across processes), relationships
  follow their source node, and every partition keeps the source
  graph's exact table structure (schema parity by construction);
* routing — a query provably resident on one shard (single node
  pattern + partition-property equality, nothing escaping the matched
  rows) executes on the owning member alone; everything else runs on
  the group's cross-shard session.  Either way results are
  digest-equal to the unsharded session's;
* the group health ladder — member loss → group degraded (healthy
  members keep serving) → background probe → rebuild onto a spare
  session from the host partition slices → reinstated → group healthy,
  exact on the fake clock; repeated rebuild failures quarantine the
  GROUP, whose traffic then sheds at admission with an honest
  retry_after while replica members keep serving;
* paging — partitions spill to host slices under a byte budget and
  fault back in on access, digest-equal either way, with honest
  ``paging.*`` counters;
* ``ReplicaSet.retry_target`` (satellite fix) — accepts every index
  that already failed, so a second retry can never land back on the
  first failed device;
* warmup — a cold-process sharded server warmed from the persistent
  plan store serves its first single-shard query with 0.0 compile
  seconds charged, and ``warmup_report()`` counts group-compiled
  families as covered;
* the acceptance soak — 8 clients, one shard member killed mid-run:
  availability 1.0, digest-equal results, victim's group degrades and
  rebuilds, replica members unaffected.
"""
from __future__ import annotations

import threading
import time

import pytest

import caps_tpu
from caps_tpu.obs import clock
from caps_tpu.relational.session import result_digest
from caps_tpu.serve import (Overloaded, QueryServer, RetryPolicy,
                            ServerConfig)
from caps_tpu.serve.devices import HEALTHY, ReplicaSet
from caps_tpu.serve.errors import ShardingUnsupported
from caps_tpu.serve.shards import (GROUP_DEGRADED, GROUP_HEALTHY,
                                   GROUP_QUARANTINED, MEMBER_HEALTHY,
                                   MEMBER_QUARANTINED, ShardGroup,
                                   ShardGroupConfig, executing_shard,
                                   hash_value, partition_graph)
from caps_tpu.testing.factory import create_graph
from caps_tpu.testing.faults import shard_loss, sick_shard

PEOPLE = """
    CREATE (a:Person {id: 1, name: 'Alice', age: 33}),
           (b:Person {id: 2, name: 'Bob', age: 44}),
           (c:Person {id: 3, name: 'Carol', age: 27}),
           (d:Person {id: 4, name: 'Dana', age: 51}),
           (e:Person {id: 5, name: 'Eve', age: 39}),
           (f:City {id: 6, name: 'Oslo'}),
           (a)-[:KNOWS {since: 2011}]->(b),
           (b)-[:KNOWS {since: 2015}]->(c),
           (a)-[:KNOWS {since: 2019}]->(c),
           (c)-[:KNOWS {since: 2021}]->(d),
           (d)-[:KNOWS {since: 2022}]->(e),
           (a)-[:LIVES_IN]->(f)
"""

Q_SINGLE = "MATCH (n:Person) WHERE n.id = $id RETURN n.name AS name"
Q_SINGLE_MAP = "MATCH (n:Person {id: $id}) RETURN n.age AS age"
Q_EDGE = ("MATCH (a:Person)-[:KNOWS]->(b) WHERE a.age > $min "
          "RETURN a.name AS a, b.name AS b")
Q_TWOHOP = ("MATCH (a:Person)-[:KNOWS]->(b)-[:KNOWS]->(c) "
            "WHERE a.id = $id RETURN c.name AS c")
Q_COUNT = ("MATCH (a:Person)-[k:KNOWS]->(b) WHERE k.since >= $y "
           "RETURN count(*) AS c")


def _session():
    return caps_tpu.local_session(backend="local")


def _graph(session):
    return create_graph(session, PEOPLE)


def _bag(rows):
    return sorted(sorted(r.items()) for r in rows)


def _group(session, graph, **over):
    kw = dict(name="g0", members=2, partitions_per_member=2,
              member_cooldown_s=1.0)
    kw.update(over)
    return ShardGroup(session, graph, ShardGroupConfig(**kw),
                      registry=session.metrics_registry)


def _drive(server, replica):
    batch = server.batcher.next_batch(timeout=0)
    if batch:
        server._execute_batch(batch, replica)
    return batch


class FakeClock:
    def __init__(self, t0: float = 1_000.0):
        self._t = t0
        self._lock = threading.Lock()

    def now(self) -> float:
        with self._lock:
            return self._t

    def sleep(self, s: float) -> None:
        with self._lock:
            self._t += s

    def wait(self, event, timeout: float) -> bool:
        if event.is_set():
            return True
        self.sleep(timeout)
        return event.is_set()

    def advance(self, s: float) -> None:
        with self._lock:
            self._t += s


@pytest.fixture()
def fake_clock(monkeypatch):
    fc = FakeClock()
    monkeypatch.setattr(clock, "now", fc.now)
    monkeypatch.setattr(clock, "sleep", fc.sleep)
    monkeypatch.setattr(clock, "wait", fc.wait)
    return fc


# -- partitioning -----------------------------------------------------------

def test_partitioner_covers_every_row_exactly_once():
    session = _session()
    graph = _graph(session)
    parts = partition_graph(graph, 4, "id")
    assert len(parts) == 4
    node_ids = []
    rel_ids = []
    for p in parts:
        for s in p.node_slices:
            node_ids.extend(s.data[s.mapping.id_col])
        for s in p.rel_slices:
            rel_ids.extend(s.data[s.mapping.id_col])
    all_nodes = [nid for nt in graph.node_tables
                 for nid in nt.table.column_values(nt.mapping.id_col)]
    all_rels = [rid for rt in graph.rel_tables
                for rid in rt.table.column_values(rt.mapping.id_col)]
    assert sorted(node_ids) == sorted(all_nodes)
    assert sorted(rel_ids) == sorted(all_rels)
    # every partition keeps the full table structure (schema parity):
    # one slice per source entity table, mappings identical
    for p in parts:
        assert len(p.node_slices) == len(graph.node_tables)
        assert len(p.rel_slices) == len(graph.rel_tables)
        assert {s.mapping.labels for s in p.node_slices} \
            == {nt.mapping.labels for nt in graph.node_tables}


def test_partitioner_edges_follow_source_node():
    session = _session()
    graph = _graph(session)
    parts = partition_graph(graph, 3, "id")
    home = {}
    for p in parts:
        for s in p.node_slices:
            for nid in s.data[s.mapping.id_col]:
                home[nid] = p.index
    for p in parts:
        for s in p.rel_slices:
            for src in s.data[s.mapping.source_col]:
                assert home[src] == p.index


def test_hash_value_stable_and_numeric_coherent():
    # process-independent (crc32, not salted hash()) — these pin the
    # cross-process partitioning contract
    assert hash_value(1) == hash_value(1)
    assert hash_value("x") == hash_value("x")
    # Cypher numeric equality: 5 = 5.0 is TRUE, so a float-typed
    # parameter must route to the shard that stored the int (review
    # regression: a type-sensitive hash silently returned empty)
    assert hash_value(5) == hash_value(5.0)
    assert hash_value(5.5) != hash_value(5)
    # booleans are not Cypher numbers; strings never equal numbers
    assert hash_value(True) != hash_value(1)
    assert hash_value("1") != hash_value(1)


def test_float_param_routes_to_int_stored_shard():
    session = _session()
    graph = _graph(session)
    group = _group(session, graph)
    for i in range(1, 6):
        got = group.execute(Q_SINGLE, {"id": float(i)})
        want = graph.cypher(Q_SINGLE, {"id": float(i)})
        assert result_digest(got) == result_digest(want), i
        assert got.to_maps(), i  # non-empty: routed to the right shard


def test_partition_rejects_non_scan_graphs():
    session = _session()
    with pytest.raises(ShardingUnsupported):
        partition_graph(session._ambient, 2)


def test_group_rejects_versioned_graphs():
    session = _session()
    graph = _graph(session)
    from caps_tpu.relational.updates import VersionedGraph
    vg = VersionedGraph(session, graph)
    with pytest.raises(ShardingUnsupported):
        _group(session, vg)


# -- routing ----------------------------------------------------------------

def test_route_detects_single_shard_queries():
    session = _session()
    group = _group(session, _graph(session))
    assert group._route(Q_SINGLE) == ("param", "id")
    assert group._route(Q_SINGLE_MAP) == ("param", "id")
    # reversed equality, extra conjuncts, aggregation, WITH — all still
    # resident (every matched row lives on the owning shard)
    assert group._route("MATCH (n:Person) WHERE $id = n.id "
                        "RETURN n.name AS name") == ("param", "id")
    assert group._route("MATCH (n:Person) WHERE n.id = $id AND "
                        "n.age > 30 RETURN count(*) AS c") \
        == ("param", "id")
    assert group._route("MATCH (n) WHERE n.id = $id WITH n.age AS a "
                        "RETURN a") == ("param", "id")
    assert group._route("MATCH (n:Person) WHERE n.id = 3 "
                        "RETURN n.name AS name") == ("lit", 3)


def test_route_rejects_cross_shard_queries():
    session = _session()
    group = _group(session, _graph(session))
    # relationships, multiple parts, OPTIONAL, other clauses, writes,
    # EXPLAIN, missing/wrong property — all cross-shard
    assert group._route(Q_EDGE) is None
    assert group._route(Q_TWOHOP) is None
    assert group._route("MATCH (n:Person), (m:City) WHERE n.id = $id "
                        "RETURN n.name AS a, m.name AS b") is None
    assert group._route("OPTIONAL MATCH (n:Person) WHERE n.id = $id "
                        "RETURN n.name AS name") is None
    assert group._route("UNWIND [1, 2] AS x MATCH (n) WHERE n.id = $id "
                        "RETURN n.name AS name, x") is None
    assert group._route("MATCH (n:Person) WHERE n.age = $id "
                        "RETURN n.name AS name") is None
    assert group._route("MATCH (n:Person) RETURN n.name AS name") is None
    assert group._route("EXPLAIN " + Q_SINGLE) is None
    assert group._route("CREATE (n:Person {id: 99})") is None


def test_single_and_cross_shard_digest_parity():
    session = _session()
    graph = _graph(session)
    group = _group(session, graph, partitions_per_member=3)
    cases = [(Q_SINGLE, {"id": i}) for i in range(1, 6)] + \
        [(Q_SINGLE_MAP, {"id": 2}),
         ("MATCH (n:Person) WHERE n.id = $id AND n.age > 30 "
          "RETURN count(*) AS c", {"id": 4}),
         (Q_EDGE, {"min": 25}), (Q_TWOHOP, {"id": 1}),
         (Q_COUNT, {"y": 2015}),
         ("MATCH (n) RETURN n.name AS name ORDER BY name", {})]
    for q, params in cases:
        got = group.execute(q, params)
        want = graph.cypher(q, params)
        assert result_digest(got) == result_digest(want), (q, params)
    s = group.summary()
    assert s["requests"]["total"] == 0  # server-side counters only
    reg = session.metrics_snapshot()
    assert reg["shard.requests.single"] >= 7
    assert reg["shard.requests.cross"] >= 4


def test_cross_shard_join_parity_on_meshed_backend():
    """The distributed-join path: the group's cross-shard session rides
    a real mesh (8 virtual CPU devices in the unit suite) and its join
    results are digest-equal to the unsharded session's."""
    from caps_tpu.backends.tpu.session import TPUCypherSession
    session = TPUCypherSession()
    graph = _graph(session)
    group = _group(session, graph)
    assert group.cross_meshed
    assert group.cross_session.backend.n_shards == 2
    for q, params in [(Q_EDGE, {"min": 25}), (Q_COUNT, {"y": 2011}),
                      (Q_TWOHOP, {"id": 1})]:
        assert result_digest(group.execute(q, params)) \
            == result_digest(graph.cypher(q, params)), q
    # single-shard routing on the device backend too
    assert result_digest(group.execute(Q_SINGLE, {"id": 3})) \
        == result_digest(graph.cypher(Q_SINGLE, {"id": 3}))


# -- the group health ladder ------------------------------------------------

def test_group_ladder_lifecycle_exact(fake_clock):
    """Member loss → group degraded (healthy members keep serving) →
    probe gated by the cooldown → rebuild onto a spare session →
    reinstated → group healthy, with exact counters on the fake
    clock."""
    session = _session()
    graph = _graph(session)
    server = QueryServer(session, graph=graph, start=False, config=ServerConfig(
        shards=2,
        shard_config=ShardGroupConfig(
            name="g0", partitions_per_member=2,
            member_failure_threshold=1, member_cooldown_s=10.0),
        retry=RetryPolicy(max_attempts=1, backoff_base_s=0.0, jitter=0.0),
        breaker_threshold=1000))
    group = server.shard_groups[0]
    # find one id per member so we can target each side
    by_member = {}
    for i in range(1, 6):
        _pidx, m = group.owning_member(i)
        by_member.setdefault(m.index, i)
    assert set(by_member) == {0, 1}
    victim, survivor = by_member[0], by_member[1]
    loss = shard_loss("g0", 0)
    budget = loss.__enter__()
    try:
        h = server.submit(Q_SINGLE, {"id": victim})
        _drive(server, group)
        assert h.exception(timeout=5) is not None   # max_attempts=1
        assert budget.injected == 1
        assert group.member_state(0) == MEMBER_QUARANTINED
        assert group.member_state(1) == MEMBER_HEALTHY
        assert group.health() == GROUP_DEGRADED
        assert server.health() == "degraded"
        # the healthy member keeps serving its shard
        h2 = server.submit(Q_SINGLE, {"id": survivor})
        _drive(server, group)
        assert result_digest(h2.result(timeout=5)) \
            == result_digest(graph.cypher(Q_SINGLE, {"id": survivor}))
        # cooldown not elapsed: the maintenance pass rebuilds nothing
        assert group.maintenance_tick() is False
        assert group.members[0].rebuilds == 0
        # cooldown elapsed, fault still active: the rebuild's canary
        # fails on the member's own stream and buys another cooldown
        fake_clock.advance(10.0)
        assert group.maintenance_tick() is False
        assert group.members[0].probes == 1
        assert group.member_state(0) == MEMBER_QUARANTINED
        assert group.health() == GROUP_DEGRADED
        reg = session.metrics_snapshot()
        assert reg["shard.rebuild_failures"] == 1
    finally:
        loss.__exit__(None, None, None)
    # fault lifted + cooldown elapsed: rebuild onto a spare session
    # succeeds, the canary passes, the member reinstates
    fake_clock.advance(10.0)
    assert group.maintenance_tick() is True
    assert group.member_state(0) == MEMBER_HEALTHY
    assert group.health() == GROUP_HEALTHY
    assert server.health() == "healthy"
    m0 = group.members[0]
    assert m0.rebuilds == 1 and m0.reinstates == 1
    assert m0.incarnation == 1
    assert m0.quarantines == 1 and m0.probes == 2
    # the rebuilt member serves its shard again, digest-equal
    h3 = server.submit(Q_SINGLE, {"id": victim})
    _drive(server, group)
    assert result_digest(h3.result(timeout=5)) \
        == result_digest(graph.cypher(Q_SINGLE, {"id": victim}))
    states = [t["state"] for t in group.summary()["transitions"]]
    assert states == [GROUP_HEALTHY, GROUP_DEGRADED, GROUP_HEALTHY]
    reg = session.metrics_snapshot()
    assert reg["shard.member.quarantined"] == 1
    assert reg["shard.member.reinstated"] == 1
    assert reg["shard.rebuilds"] == 1
    server.shutdown(drain=False)


def test_group_quarantine_sheds_and_requeues(fake_clock):
    """Group-level quarantine: rebuild failures past the group
    threshold open the group — new group traffic sheds at admission
    with the remaining cooldown as the retry hint, claimed batches
    requeue, and recovery re-opens the tap."""
    session = _session()
    graph = _graph(session)
    server = QueryServer(session, graph=graph, start=False, config=ServerConfig(
        shards=2,
        shard_config=ShardGroupConfig(
            name="gq", partitions_per_member=1,
            member_failure_threshold=1, member_cooldown_s=5.0,
            group_failure_threshold=1),
        retry=RetryPolicy(max_attempts=1, backoff_base_s=0.0, jitter=0.0),
        breaker_threshold=1000))
    group = server.shard_groups[0]
    victim_id = next(i for i in range(1, 6)
                     if group.owning_member(i)[1].index == 0)
    loss = shard_loss("gq", 0)
    loss.__enter__()
    try:
        h = server.submit(Q_SINGLE, {"id": victim_id})
        _drive(server, group)
        assert h.exception(timeout=5) is not None
        assert group.health() == GROUP_DEGRADED
        # one failed rebuild cycle >= group threshold: group quarantined
        fake_clock.advance(5.0)
        group.maintenance_tick()
        assert group.health() == GROUP_QUARANTINED
        assert server.devices.is_healthy(group) is False
        # new group-routed traffic sheds with an honest retry hint
        with pytest.raises(Overloaded) as exc_info:
            server.submit(Q_SINGLE, {"id": victim_id})
        assert exc_info.value.retry_after_s > 0
        assert session.metrics_snapshot()["shard.shed"] >= 1
        # a batch claimed toward the quarantined group requeues instead
        # of executing (submit went through BEFORE the quarantine —
        # simulate by injecting the request directly)
        from caps_tpu.serve import batcher as _batcher
        from caps_tpu.serve.deadline import CancelScope
        from caps_tpu.serve.request import Request
        mode, plan_key, key = _batcher.request_keys(
            graph, Q_SINGLE, {"id": victim_id})
        req = Request(Q_SINGLE, {"id": victim_id}, graph, 0,
                      CancelScope(None), key, mode, plan_key=plan_key)
        server.admission.requeue(req)
        depth_before = server.admission.depth()
        _drive(server, server.devices.replicas[0])
        assert not req.handle.done()
        assert server.admission.depth() == depth_before
        assert session.metrics_snapshot()["serve.requeued"] >= 2
    finally:
        loss.__exit__(None, None, None)
    # recovery: rebuild succeeds, member reinstates, group heals, the
    # requeued request drains and new submits are admitted again
    fake_clock.advance(5.0)
    assert group.maintenance_tick() is True
    assert group.health() == GROUP_HEALTHY
    _drive(server, group)
    assert result_digest(req.handle.result(timeout=5)) \
        == result_digest(graph.cypher(Q_SINGLE, {"id": victim_id}))
    h2 = server.submit(Q_SINGLE, {"id": victim_id})
    _drive(server, group)
    assert h2.result(timeout=5) is not None
    states = [t["state"] for t in group.summary()["transitions"]]
    assert states == [GROUP_HEALTHY, GROUP_DEGRADED, GROUP_QUARANTINED,
                      GROUP_HEALTHY]
    server.shutdown(drain=False)


def test_member_failures_are_consecutive_not_lifetime(fake_clock):
    """A served request ends the member's failure streak (review
    regression: two member faults days apart — each healed by the
    retry ladder — must not sum to a quarantine)."""
    session = _session()
    graph = _graph(session)
    group = _group(session, graph, member_failure_threshold=2)
    target = next(i for i in range(1, 6)
                  if group.owning_member(i)[1].index == 0)
    for _round in range(3):
        with shard_loss("g0", 0, n_times=1):
            with pytest.raises(Exception) as exc_info:
                group.execute(Q_SINGLE, {"id": target})
        group.record_failure(exc_info.value)  # what the server would do
        assert group.member_state(0) == MEMBER_HEALTHY, _round
        # a successful request in between resets the streak
        group.execute(Q_SINGLE, {"id": target})
    assert group.health() == GROUP_HEALTHY


def test_group_quarantined_by_cross_faults_recovers(fake_clock):
    """A group quarantined by UNATTRIBUTED cross-shard device faults
    has no tripped member to rebuild — the maintenance pass must probe
    the cross-shard session itself and clear the group trip (review
    regression: the group was bricked forever)."""
    session = _session()
    graph = _graph(session)
    group = _group(session, graph, group_failure_threshold=2,
                   member_cooldown_s=5.0)
    with sick_shard("g0", error_rate=1.0) as budget:
        for _ in range(2):
            with pytest.raises(Exception) as exc_info:
                group.execute(Q_EDGE, {"min": 25})
            assert getattr(exc_info.value, "caps_shard_member",
                           None) is None
            group.record_failure(exc_info.value)
        assert budget.injected == 2
    assert group.health() == GROUP_QUARANTINED
    assert all(s == MEMBER_HEALTHY
               for s in group.member_health().values())
    assert group.shed_retry_after() is not None
    # cooldown not elapsed: nothing probes yet
    assert group.maintenance_tick() is False
    assert group.health() == GROUP_QUARANTINED
    # cooldown elapsed, fault lifted: the cross canary passes and the
    # group un-quarantines — no member rebuild involved
    fake_clock.advance(5.0)
    assert group.maintenance_tick() is True
    assert group.health() == GROUP_HEALTHY
    assert result_digest(group.execute(Q_EDGE, {"min": 25})) \
        == result_digest(graph.cypher(Q_EDGE, {"min": 25}))


def test_shard_faults_scope_to_their_group():
    """``shard_loss(group, member)`` hits ONLY the targeted member's
    single-shard stream and the group's cross-shard programs — the
    other member and plain (un-bracketed) sessions never see it."""
    session = _session()
    graph = _graph(session)
    group = _group(session, graph)
    by_member = {}
    for i in range(1, 6):
        by_member.setdefault(group.owning_member(i)[1].index, i)
    with shard_loss("g0", 0) as budget:
        # un-bracketed execution (a replica member's stream): untouched
        assert graph.cypher(Q_SINGLE, {"id": by_member[0]}) is not None
        # the other member's stream: untouched
        assert group.execute(Q_SINGLE, {"id": by_member[1]}) is not None
        assert budget.injected == 0
        # the victim's stream: dead
        with pytest.raises(Exception) as exc_info:
            group.execute(Q_SINGLE, {"id": by_member[0]})
        assert "UNAVAILABLE" in str(exc_info.value)
        assert getattr(exc_info.value, "caps_shard_member", None) == 0
        # group-wide cross-shard programs span the dead device: dead
        with pytest.raises(Exception):
            group.execute(Q_EDGE, {"min": 25})
        assert budget.injected == 2
    assert executing_shard() is None


def test_sick_shard_deterministic_rate():
    session = _session()
    graph = _graph(session)
    group = _group(session, graph)
    target = next(i for i in range(1, 6)
                  if group.owning_member(i)[1].index == 1)
    errors = 0
    with sick_shard("g0", member=1, error_rate=0.5) as budget:
        for _ in range(8):
            try:
                group.execute(Q_SINGLE, {"id": target})
            except Exception:
                errors += 1
    assert errors == budget.injected == 4  # every 2nd, exactly


# -- paging -----------------------------------------------------------------

def test_paging_spill_and_fault_in_digest_parity():
    session = _session()
    n = 24
    graph = create_graph(session, "CREATE " + ", ".join(
        f"(p{i}:Person {{id: {i}, name: 'P{i}', age: {20 + i}}})"
        for i in range(1, n + 1)))
    probe = _group(session, graph, partitions_per_member=4)
    # budget ~ half a member's total: partitions must rotate through
    # device residency (spill + fault-in) as routed accesses move
    # across shards — correctness must be residency-independent
    member_sums = [sum(probe.partitions[p].host_nbytes()
                       for p in m.partitions) for m in probe.members]
    budget = min(member_sums) // 2
    assert budget > max(probe.partitions[p].host_nbytes()
                        for m in probe.members for p in m.partitions)
    paged = ShardGroup(
        session, graph,
        ShardGroupConfig(name="paged", members=2, partitions_per_member=4,
                         page_budget_bytes=budget),
        registry=session.metrics_registry)
    for m in paged.members:
        assert m.resident_bytes() <= budget
        assert len(m.resident) < len(m.partitions)  # some stayed cold
    for i in list(range(1, n + 1)) + list(range(1, n + 1)):
        with paged.lock:
            got = paged.execute(Q_SINGLE, {"id": i})
        assert result_digest(got) \
            == result_digest(graph.cypher(Q_SINGLE, {"id": i})), i
    summary = paged.summary()["paging"]
    assert summary["faults"] > 0
    assert summary["spills"] > 0
    assert summary["host_bytes"] > 0
    for m in paged.members:
        assert m.resident_bytes() <= budget
    reg = session.metrics_snapshot()
    assert reg["paging.faults"] == summary["faults"]
    assert reg["paging.spills"] == summary["spills"]
    assert reg["paging.resident_bytes"] > 0
    assert reg["paging.host_bytes"] > 0


def test_no_budget_means_fully_resident():
    session = _session()
    group = _group(session, _graph(session))
    for m in group.members:
        assert sorted(m.resident) == sorted(m.partitions)
    assert group.cold_host_bytes() == 0
    for _ in range(3):
        with group.lock:
            group.execute(Q_SINGLE, {"id": 2})
    assert sum(m.page_faults for m in group.members) == 0


# -- retry_target satellite fix --------------------------------------------

def test_retry_target_excludes_every_failed_index():
    session = _session()
    rs = ReplicaSet(session, n_devices=3,
                    registry=session.metrics_registry)
    # two failed devices: the only healthy survivor must ALWAYS win —
    # before the fix, retry_target(exclude_index=1) could round-robin
    # back onto already-failed device 0
    for _ in range(10):
        assert rs.retry_target([0, 1]).index == 2
    # int form still works (back-compat)
    for _ in range(10):
        assert rs.retry_target(0).index != 0
    # everything failed: fall back to the most recent failure
    assert rs.retry_target([2, 0, 1]).index == 1


def test_writes_served_on_group_graphs():
    """The durable-writes PR lifts the old write rejection: a CREATE
    through a sharded server commits on the group's internal lineage
    and is visible to both the routed and cross-shard read paths."""
    session = _session()
    graph = _graph(session)
    server = QueryServer(session, graph=graph, start=False,
                         config=ServerConfig(shards=2))
    h = server.submit("CREATE (n:Person {id: 99, name: 'Zed'})")
    _drive(server, server.shard_groups[0])
    assert h.exception(timeout=5) is None
    h2 = server.submit(Q_SINGLE, graph=graph, parameters={"id": 99})
    _drive(server, server.shard_groups[0])
    assert h2.result(timeout=5).to_maps() == [{"name": "Zed"}]
    server.shutdown(drain=False)


# -- warmup integration -----------------------------------------------------

def test_cold_process_sharded_server_first_query_zero_compile(tmp_path):
    """The cold-start round trip: a sharded server records its warm
    bindings on the GROUP's member sessions, persists them to the plan
    store at shutdown, and a fresh 'process' (fresh template session,
    freshly partitioned graph) warmed from that store serves its first
    single-shard client query with 0.0 compile seconds charged."""
    from caps_tpu.serve import WarmupConfig
    store = str(tmp_path / "plans.json")
    binding = {"id": 3}

    session_a = _session()
    graph_a = _graph(session_a)
    server_a = QueryServer(session_a, graph=graph_a, config=ServerConfig(
        shards=2, warmup=WarmupConfig(store_path=store, background=False)))
    try:
        first = server_a.run(Q_SINGLE, binding)
        assert first.metrics["compile_s_charged"] > 0.0  # cold
        warm = server_a.run(Q_SINGLE, binding)
        assert warm.metrics["compile_s_charged"] == 0.0
        server_a.run(Q_EDGE, {"min": 25})  # a cross-shard family too
    finally:
        assert server_a.shutdown()         # persists the store
    # a family that only compiled on the group is covered in the report
    report_a = server_a.warmup_report(
        families=[f["family"]
                  for f in server_a.shard_groups[0].warmup_bindings()])
    assert report_a["cold_families"] == []

    session_b = _session()
    graph_b = _graph(session_b)
    server_b = QueryServer(session_b, graph=graph_b, config=ServerConfig(
        shards=2, warmup=WarmupConfig(store_path=store, background=False)))
    try:
        wr = server_b.stats()["warmup"]
        assert wr["state"] == "done" and wr["completed"] >= 2, wr
        res = server_b.run(Q_SINGLE, binding)
        assert res.metrics["compile_s_charged"] == 0.0
        assert result_digest(res) \
            == result_digest(graph_b.cypher(Q_SINGLE, binding))
        cross = server_b.run(Q_EDGE, {"min": 25})
        assert cross.metrics["compile_s_charged"] == 0.0
    finally:
        server_b.shutdown()


# -- the acceptance soak ----------------------------------------------------

def _shard_loss_soak(per_thread: int):
    session = _session()
    default_graph = _graph(session)       # replica-served
    big = create_graph(session, PEOPLE)   # the group-served graph
    server = QueryServer(session, graph=default_graph, shard_graph=big,
                         config=ServerConfig(
                             devices=2, shards=2, max_queue=4096,
                             max_batch=4,
                             shard_config=ShardGroupConfig(
                                 name="soak", partitions_per_member=2,
                                 member_failure_threshold=1,
                                 member_cooldown_s=0.02),
                             device_failure_threshold=1000,
                             breaker_threshold=1000,
                             retry=RetryPolicy(max_attempts=40,
                                               backoff_base_s=0.002,
                                               backoff_max_s=0.02)))
    group = server.shard_groups[0]
    flat = [(big, Q_SINGLE, {"id": i}) for i in range(1, 6)] + \
        [(big, Q_EDGE, {"min": m}) for m in (25, 35)] + \
        [(big, Q_COUNT, {"y": 2015})] + \
        [(default_graph, Q_EDGE, {"min": m}) for m in (25, 45)]
    expected = {i: _bag(g.cypher(q, b).records.to_maps())
                for i, (g, q, b) in enumerate(flat)}
    n_threads = 8
    results: dict = {}
    submit_errors: list = []

    def run_phase(phase: int):
        def client(tid: int):
            try:
                for j in range(per_thread):
                    i = (tid * 7 + phase + j) % len(flat)
                    g, q, b = flat[i]
                    results[(phase, tid, j)] = (i, server.submit(
                        q, b, graph=g))
            except Exception as ex:  # pragma: no cover
                submit_errors.append(ex)
        threads = [threading.Thread(target=client, args=(t,))
                   for t in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for _i, handle in results.values():
            assert handle.wait(timeout=60)

    try:
        run_phase(0)                       # healthy warm-up phase
        assert group.health() == GROUP_HEALTHY
        # member 0 dies mid-run: a bounded loss — the background
        # rebuild's canary consumes the tail of the budget and heals
        # the member (the "recovered device")
        with shard_loss("soak", 0, n_times=6) as budget:
            run_phase(1)
        assert budget.injected >= 1
        # availability 1.0: every request of both phases resolved with
        # digest-equal rows — no typed give-ups, no untyped leaks
        assert not submit_errors, submit_errors
        assert len(results) == 2 * n_threads * per_thread
        for i, handle in results.values():
            ex = handle.exception()
            assert ex is None, (i, ex)
            assert _bag(handle.rows()) == expected[i], i
        # the victim's group degraded and rebuilt; replica members
        # (serving the default graph) were never touched
        summary = group.summary()
        states = [t["state"] for t in summary["transitions"]]
        assert GROUP_DEGRADED in states
        assert summary["members"][0]["quarantines"] >= 1
        assert summary["members"][0]["rebuilds"] >= 1
        deadline = time.time() + 10
        while group.health() != GROUP_HEALTHY and time.time() < deadline:
            time.sleep(0.02)
        assert group.health() == GROUP_HEALTHY
        assert all(h == HEALTHY
                   for h in server.device_health().values())
        snap = session.metrics_snapshot()
        assert snap["serve.completed"] == 2 * n_threads * per_thread
    finally:
        server.shutdown()
    return session.metrics_snapshot()


def test_soak_shard_member_killed_mid_run():
    from caps_tpu.obs.metrics import global_registry
    before = global_registry().snapshot().get(
        "faults.injected.shard_loss", 0)
    _shard_loss_soak(per_thread=4)
    assert global_registry().snapshot()["faults.injected.shard_loss"] \
        > before


@pytest.mark.slow
def test_soak_shard_member_killed_mid_run_long():
    _shard_loss_soak(per_thread=20)


# -- surfaces ---------------------------------------------------------------

def test_stats_and_health_report_expose_shards():
    session = _session()
    graph = _graph(session)
    server = QueryServer(session, graph=graph, start=False,
                         config=ServerConfig(shards=2))
    try:
        shards = server.stats()["shards"]
        assert len(shards) == 1
        s = shards[0]
        assert s["state"] == GROUP_HEALTHY
        assert {m["health"] for m in s["members"]} == {MEMBER_HEALTHY}
        assert "paging" in s and "transitions" in s
        hr = server.health_report()
        assert hr["shards"][0]["name"] == s["name"]
        # the shard/paging gauges ride the normal exposition
        text = server.metrics_text()
        assert "shard_groups 1" in text
        assert "paging_resident_bytes" in text
    finally:
        server.shutdown(drain=False)

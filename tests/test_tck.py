"""TCK-subset conformance: every scenario in caps_tpu/tck/features runs
against all backends, with per-backend blacklists (SURVEY.md §4.3 — the
reference's okapi-tck cucumber runner + failing_blacklist mechanism)."""
import os

import pytest

from caps_tpu.tck import load_blacklist, load_features, run_scenario
from caps_tpu.tck.runner import FEATURES_DIR
from caps_tpu.testing.sessions import BACKENDS, make_backend_session

SCENARIOS = load_features()
_BL_DIR = os.path.join(os.path.dirname(FEATURES_DIR), "blacklists")

_SESSIONS = {}


def _session(backend):
    if backend not in _SESSIONS:
        _SESSIONS[backend] = make_backend_session(backend)
    return _SESSIONS[backend]


def test_corpus_is_nontrivial():
    assert len(SCENARIOS) >= 300
    assert len({s.feature for s in SCENARIOS}) >= 15


@pytest.mark.parametrize("backend", BACKENDS)
@pytest.mark.parametrize("scenario", SCENARIOS, ids=lambda s: s.key)
def test_tck(backend, scenario):
    blacklist = load_blacklist(os.path.join(_BL_DIR, f"{backend}.txt"))
    if scenario.key in blacklist:
        pytest.xfail(f"blacklisted for {backend}")
    run_scenario(_session(backend), scenario)

"""Serving telemetry (ISSUE 9): windowed SLOs, the flight recorder, and
the observed-statistics store.

Window rotation, quantiles, and burn-rate math run against a fake
``caps_tpu.obs.clock`` so bucket expiry is asserted exactly with zero
real waiting.  The flight-recorder auto-dump triggers (breaker trip,
device quarantine, compaction failure) reuse the fault-injection
harness; the observed-statistics store is checked for fused-replay
parity against PROFILE's cardinalities; ``expose_text`` gets a golden
format test plus a line-grammar validation of a live server scrape.
"""
from __future__ import annotations

import re
import threading

import pytest

import caps_tpu
from caps_tpu.obs import clock
from caps_tpu.obs.metrics import MetricsRegistry
from caps_tpu.obs.telemetry import (FlightRecorder, OpStatsStore,
                                    RollingCounter, RollingHistogram,
                                    ServingTelemetry, SLOConfig)
from caps_tpu.serve import (QueryServer, RetryPolicy, ServerConfig)
from caps_tpu.serve.admission import AdmissionController
from caps_tpu.testing.factory import create_graph
from caps_tpu.testing.faults import device_loss, failing_operator

SOCIAL = """
    CREATE (a:Person {name: 'Alice', age: 33}),
           (b:Person {name: 'Bob', age: 44}),
           (c:Person {name: 'Carol', age: 27}),
           (d:Person {name: 'Dana', age: 51}),
           (a)-[:KNOWS {since: 2011}]->(b),
           (b)-[:KNOWS {since: 2015}]->(c),
           (a)-[:KNOWS {since: 2019}]->(c),
           (c)-[:KNOWS {since: 2021}]->(d)
"""

Q_ORDER = ("MATCH (p:Person) WHERE p.age > $min "
           "RETURN p.name AS n ORDER BY n")
Q_COUNT = "MATCH (p:Person) RETURN count(*) AS c"


def _session(backend="local"):
    return caps_tpu.local_session(backend=backend)


class FakeClock:
    """Same fake as tests/test_faults.py: ``sleep`` advances ``now``
    instantly; ``wait`` honors an already-fired event with no time
    passing."""

    def __init__(self, t0: float = 1_000.0):
        self._t = t0
        self._lock = threading.Lock()
        self.sleeps: list = []

    def now(self) -> float:
        with self._lock:
            return self._t

    def sleep(self, s: float) -> None:
        with self._lock:
            self._t += s
            self.sleeps.append(s)

    def wait(self, event, timeout: float) -> bool:
        if event.is_set():
            return True
        self.sleep(timeout)
        return event.is_set()

    def advance(self, s: float) -> None:
        with self._lock:
            self._t += s


@pytest.fixture()
def fake_clock(monkeypatch):
    fc = FakeClock()
    monkeypatch.setattr(clock, "now", fc.now)
    monkeypatch.setattr(clock, "sleep", fc.sleep)
    monkeypatch.setattr(clock, "wait", fc.wait)
    return fc


# -- rolling-window primitives (exact rotation semantics) --------------------

def test_rolling_counter_bucket_expiry_exact():
    c = RollingCounter(window_s=60.0, buckets=60)  # 1 s per slot
    t0 = 1_000.0
    c.inc(t0, 3)
    assert c.total(t0) == 3
    # anywhere inside the window the sample is live...
    assert c.total(t0 + 59.0) == 3
    # ...and the slot is cleared exactly when its epoch recycles
    assert c.total(t0 + 60.0) == 0
    # a gap far beyond the window clears everything in one step
    c.inc(t0 + 61.0, 5)
    assert c.total(t0 + 500.0) == 0


def test_rolling_counter_accumulates_across_slots():
    c = RollingCounter(window_s=10.0, buckets=10)
    t0 = 1_000.0
    for k in range(5):
        c.inc(t0 + k, 1)       # one per slot
    assert c.total(t0 + 4) == 5
    # advancing 6 more seconds expires exactly the first slot
    assert c.total(t0 + 10.0) == 4


def test_rolling_histogram_quantiles_and_rotation():
    h = RollingHistogram(window_s=60.0, buckets=60,
                         bounds=(0.001, 0.01, 0.1, 1.0))
    t0 = 1_000.0
    for _ in range(9):
        h.observe(t0, 0.0005)          # le 0.001 bucket
    h.observe(t0 + 30.0, 0.5)          # le 1.0 bucket, later slot
    assert h.count(t0 + 30.0) == 10
    # quantiles are bucket upper bounds: rank 5 of 10 falls in the
    # first bucket, rank 10 in the 1.0 bucket
    assert h.quantile(t0 + 30.0, 0.50) == 0.001
    assert h.quantile(t0 + 30.0, 0.99) == 1.0
    # rotate the early slot out: only the 0.5 sample survives
    assert h.count(t0 + 65.0) == 1
    assert h.quantile(t0 + 65.0, 0.50) == 1.0
    assert h.mean(t0 + 65.0) == 0.5
    # the +Inf tail serves the window max, not a fake bound
    h.observe(t0 + 65.0, 7.5)
    assert h.quantile(t0 + 65.0, 0.99) == 7.5
    # empty window: quantiles are None
    assert h.quantile(t0 + 300.0, 0.5) is None
    assert h.mean(t0 + 300.0) is None


# -- SLO / burn-rate math ----------------------------------------------------

def test_slo_burn_rate_math_exact(fake_clock):
    reg = MetricsRegistry()
    tel = ServingTelemetry(reg, window_s=60.0, buckets=60,
                           slo=SLOConfig(latency_target_s=0.1,
                                         latency_objective=0.9,
                                         availability_objective=0.9))
    for _ in range(8):
        tel.note_result("fam", 0.01, "ok")     # within target
    for _ in range(2):
        tel.note_result("fam", 0.5, "ok")      # over target
    for _ in range(2):
        tel.note_result("fam", 0.2, "error")
    rep = tel.slo_report()
    assert rep["latency_compliance"] == pytest.approx(0.8)
    # burn = (1 - 0.8) / (1 - 0.9) = 2.0: the error budget burns twice
    # as fast as it accrues
    assert rep["latency_burn_rate"] == pytest.approx(2.0)
    assert rep["availability"] == pytest.approx(10 / 12)
    assert rep["availability_burn_rate"] == pytest.approx(
        (1 - 10 / 12) / 0.1, rel=1e-3)
    assert rep["within_budget"] is False
    # the registry gauges serve the same numbers live
    snap = reg.snapshot()
    assert snap["slo.latency_burn_rate"] == pytest.approx(2.0)
    assert snap["slo.latency_compliance"] == pytest.approx(0.8)
    # ...and the incident rotates out of the window: budget stops burning
    fake_clock.advance(61.0)
    rep2 = tel.slo_report()
    assert rep2["latency_compliance"] == 1.0
    assert rep2["latency_burn_rate"] == 0.0
    assert rep2["within_budget"] is True


def test_slo_report_none_without_config(fake_clock):
    tel = ServingTelemetry(MetricsRegistry())
    tel.note_result("fam", 0.01, "ok")
    assert tel.slo_report() is None


def test_summary_rates_aborts_and_window_expiry(fake_clock):
    reg = MetricsRegistry()
    tel = ServingTelemetry(reg, window_s=60.0, buckets=60)
    for _ in range(6):
        tel.note_result("famA", 0.002, "ok")
    tel.note_result("famA", 0.002, "abort")
    tel.note_retry()
    tel.note_shed()
    s = tel.summary()
    assert s["requests"] == 7
    assert s["latency"]["count"] == 6        # aborts carry no latency
    assert s["rates_per_s"]["aborts"] > 0
    assert s["rates_per_s"]["shed"] > 0
    assert s["rates_per_s"]["retries"] > 0
    assert "famA" in s["families"]
    fake_clock.advance(61.0)
    s2 = tel.summary()
    assert s2["requests"] == 0 and s2["qps"] == 0.0
    assert s2["latency"]["count"] == 0 and s2["latency"]["p99_s"] is None


# -- the stale retry_after hint (satellite regression) -----------------------

def test_retry_after_prefers_window_over_stale_ema(fake_clock):
    reg = MetricsRegistry()
    tel = ServingTelemetry(reg, window_s=60.0, buckets=60)
    adm = AdmissionController(reg, workers=1, telemetry=tel)
    # a one-off slow burst: both the forever-EMA and the window see 10 s
    adm.observe_service(10.0)
    tel.note_service(10.0)
    assert adm.retry_after_s(depth=4) == pytest.approx(40.0)
    # load subsides; much later ONE fast request arrives.  The EMA still
    # remembers the burst (0.8 * 10 + 0.2 * 0.01 ≈ 8 s); the window has
    # rotated it out and reports the honest recent service time.
    fake_clock.advance(120.0)
    adm.observe_service(0.01)
    tel.note_service(0.01)
    assert adm.ema_service_s > 1.0                       # EMA is stale
    assert adm.retry_after_s(depth=4) == pytest.approx(0.04)
    adm.close()


def test_retry_after_falls_back_to_ema_without_samples(fake_clock):
    reg = MetricsRegistry()
    tel = ServingTelemetry(reg, window_s=60.0, buckets=60)
    adm = AdmissionController(reg, workers=1, telemetry=tel)
    adm.observe_service(2.0)
    # empty window (no note_service yet): the EMA carries the estimate
    assert adm.retry_after_s(depth=2) == pytest.approx(4.0)
    no_tel = AdmissionController(reg, workers=1)
    no_tel.observe_service(2.0)
    assert no_tel.retry_after_s(depth=2) == pytest.approx(4.0)
    adm.close()
    no_tel.close()


# -- flight recorder ---------------------------------------------------------

def test_flight_recorder_ring_bounds_and_dumps():
    fr = FlightRecorder(capacity=4, max_dumps=2)
    for k in range(6):
        fr.record({"i": k})
    snap = fr.snapshot()
    assert [r["i"] for r in snap] == [2, 3, 4, 5]   # oldest two evicted
    assert fr.recorded == 6
    d = fr.dump("manual")
    assert d["reason"] == "manual" and len(d["records"]) == 4
    # the dump is a copy: mutating it never touches the live ring
    d["records"].clear()
    assert len(fr.snapshot()) == 4
    assert list(fr.dumps) == []                     # store=False default
    for k in range(3):
        fr.dump(f"auto{k}", store=True)
    assert [x["reason"] for x in fr.dumps] == ["auto1", "auto2"]  # bounded


def test_breaker_trip_auto_dumps_with_attempt_histories():
    session = _session()
    graph = create_graph(session, SOCIAL)
    server = QueryServer(session, graph=graph, config=ServerConfig(
        workers=2, breaker_threshold=2, breaker_cooldown_s=30.0))
    try:
        graph.cypher(Q_ORDER, {"min": 0})  # warm the healthy plan
        with failing_operator("OrderBy", exc=RuntimeError("poison"),
                              n_times=None):
            for _ in range(2):             # threshold consecutive failures
                with pytest.raises(Exception):
                    server.run(Q_ORDER, {"min": 0})
        dumps = server.telemetry.flight_dumps
        assert dumps and dumps[-1]["reason"] == "breaker_trip"
        failing = [r for r in dumps[-1]["records"]
                   if r["outcome"] == "QueryFailed"]
        assert failing, dumps[-1]["records"]
        # the black box carries the full containment ladder per failure
        for rec in failing:
            assert rec["attempts"], rec
            assert {a["mode"] for a in rec["attempts"]} >= {"fused",
                                                            "replan"}
        assert session.metrics_snapshot()[
            "telemetry.flight_recorder.dumps"] >= 1
        # healthy traffic after the trip still records normally
        assert server.run(Q_COUNT).to_maps() == [{"c": 4}]
    finally:
        server.shutdown()


def test_device_quarantine_auto_dumps(fake_clock):
    session = _session()
    graph = create_graph(session, SOCIAL)
    server = QueryServer(session, graph=graph, start=False,
                         config=ServerConfig(
                             devices=2, device_failure_threshold=1,
                             device_cooldown_s=10.0,
                             retry=RetryPolicy(backoff_base_s=0.0,
                                               jitter=0.0)))
    r1 = server.devices.replicas[1]
    with device_loss(1):
        h = server.submit(Q_ORDER, {"min": 30})
        batch = server.batcher.next_batch(timeout=0)
        server._execute_batch(batch, r1)       # fails on 1, fails over
        assert [r["n"] for r in h.rows(timeout=5)] == ["Alice", "Bob",
                                                       "Dana"]
    reasons = [d["reason"] for d in server.telemetry.flight_dumps]
    assert "device_quarantine" in reasons
    server.shutdown()


def test_compaction_failure_auto_dumps(make_session):
    from caps_tpu.relational.updates import versioned
    from caps_tpu.testing.faults import flaky_compaction
    s = make_session("tpu")
    vg = versioned(s, create_graph(s, "CREATE (:Seed {k:-1})"))
    server = QueryServer(s, graph=vg, config=ServerConfig(
        workers=2, compaction_threshold_rows=2,
        compaction_interval_s=0.005))
    try:
        with flaky_compaction(s, error_rate=1.0, n_times=1) as budget:
            for i in range(4):
                server.submit(f"CREATE (:Item {{k:{i}}})").result(
                    timeout=30)
            deadline = clock.now() + 10.0
            while clock.now() < deadline and budget.injected == 0:
                clock.sleep(0.01)
        deadline = clock.now() + 5.0
        while clock.now() < deadline and not server.telemetry.flight_dumps:
            clock.sleep(0.01)
        assert budget.injected >= 1
        reasons = [d["reason"] for d in server.telemetry.flight_dumps]
        assert "compaction_failure" in reasons
    finally:
        server.shutdown()


# -- Prometheus text exposition ----------------------------------------------

#: one exposition sample line: name, optional labels, a value
_SAMPLE = re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[a-zA-Z0-9_]+="[^"]*"(,[a-zA-Z0-9_]+='
    r'"[^"]*")*\})? [0-9eE.+\-]+$')


def _validate_exposition(text: str) -> int:
    """Line-grammar check of the text format; returns the sample count."""
    samples = 0
    assert text.endswith("\n")
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("#"):
            assert re.match(r"^# TYPE [a-zA-Z_:][a-zA-Z0-9_:]* "
                            r"(counter|gauge|histogram)$", line), line
            continue
        assert _SAMPLE.match(line), line
        samples += 1
    return samples


def test_expose_text_golden():
    reg = MetricsRegistry()
    reg.counter("serve.completed").inc(3)
    reg.gauge("telemetry.window_qps").set(2.5)
    h = reg.histogram("serve.latency_s", buckets=(0.3, 1.0))
    for v in (0.25, 0.5, 5.0):
        h.observe(v)
    assert reg.expose_text() == (
        "# TYPE serve_completed counter\n"
        "serve_completed 3\n"
        "# TYPE telemetry_window_qps gauge\n"
        "telemetry_window_qps 2.5\n"
        "# TYPE serve_latency_s histogram\n"
        'serve_latency_s_bucket{le="0.3"} 1\n'
        'serve_latency_s_bucket{le="1.0"} 2\n'
        'serve_latency_s_bucket{le="+Inf"} 3\n'
        "serve_latency_s_sum 5.75\n"
        "serve_latency_s_count 3\n")
    # extra windowed values render as gauges; non-numerics are skipped
    text = reg.expose_text(extra={"telemetry.extra_p99_s": 0.125,
                                  "bogus.text": "nope"})
    assert "telemetry_extra_p99_s 0.125" in text
    assert "bogus" not in text
    assert _validate_exposition(text) >= 6


def test_server_metrics_text_scrape_parses():
    session = _session()
    graph = create_graph(session, SOCIAL)
    server = QueryServer(session, graph=graph, config=ServerConfig(
        workers=2, slo=SLOConfig(latency_target_s=1.0)))
    try:
        for _ in range(3):
            server.run(Q_COUNT)
        text = server.metrics_text()
        samples = _validate_exposition(text)
        assert samples > 20
        lines = text.splitlines()
        assert "# TYPE serve_completed counter" in lines
        assert "serve_completed 3" in lines
        # cumulative-le histogram series with the +Inf terminator
        assert any(l.startswith('serve_latency_s_bucket{le="') for l in lines)
        assert 'serve_latency_s_count 3' in lines
        # the windowed gauges ride the same scrape
        assert any(l.startswith("telemetry_window_qps ") for l in lines)
        assert any(l.startswith("slo_latency_burn_rate ") for l in lines)
        # bucket series are monotonically non-decreasing
        cum = [int(l.rsplit(" ", 1)[1]) for l in lines
               if l.startswith('serve_latency_s_bucket')]
        assert cum == sorted(cum)
    finally:
        server.shutdown()


# -- observed-statistics store -----------------------------------------------

def test_opstats_store_divergence_counters():
    reg = MetricsRegistry()
    store = OpStatsStore(registry=reg, max_families=2,
                         divergence_factor=4.0)
    entry = {"op": "Scan", "op_id": 1, "rows": 10, "bytes_in": 100,
             "seconds": 0.01}
    for _ in range(3):
        store.record("famA", [entry])
    st = store.stats("famA")["1:Scan"]
    assert st["executions"] == 3 and st["rows_mean"] == 10
    assert st["divergences"] == 0 and st["bytes_total"] == 300
    # a 100x cardinality surprise counts as estimate-vs-actual divergence
    store.record("famA", [dict(entry, rows=1000)])
    st = store.stats("famA")["1:Scan"]
    assert st["divergences"] == 1 and st["rows_last"] == 1000
    snap = reg.snapshot()
    assert snap["opstats.recorded"] == 4
    assert snap["opstats.divergences"] == 1
    assert snap["opstats.families"] == 1
    # family LRU: the cap evicts the oldest family, not the newest
    store.record("famB", [entry])
    store.record("famC", [entry])
    assert store.families() == ["famB", "famC"]
    assert store.summary()["families"] == 2


@pytest.mark.parametrize("backend", ["local", "tpu"])
def test_session_records_opstats_per_plan_family(make_session, backend):
    from caps_tpu.frontend.parser import normalize_query
    session = make_session(backend)
    graph = create_graph(session, SOCIAL)
    for min_age in (30, 40, 30):
        graph.cypher(Q_ORDER, {"min": min_age})
    fam = normalize_query(Q_ORDER)
    ops = session.op_stats.stats(fam)
    assert ops, session.op_stats.families()
    names = {st["op"] for st in ops.values()}
    assert "Scan" in names
    for st in ops.values():
        assert st["executions"] == 3
        assert st["wall_s_total"] > 0.0


def test_opstats_fused_replay_parity_with_profile(make_session):
    """The store's recorded cardinalities agree with PROFILE's annotated
    tree on the fused TPU path — both read the same per-op entries, so
    replay granularity carries over identically."""
    session = make_session("tpu")
    graph = create_graph(session, SOCIAL)
    for min_age in (30, 25, 30):   # converge recordings / generic stream
        graph.cypher(Q_ORDER, {"min": min_age})
    res = graph.cypher("PROFILE " + Q_ORDER, {"min": 25})
    from caps_tpu.frontend.parser import normalize_query
    ops = session.op_stats.stats(normalize_query(Q_ORDER))

    def walk(node):
        yield node
        for c in node["children"]:
            yield from walk(c)

    executed = [n for n in walk(res.profile) if n["executed"]]
    assert executed
    for node in executed:
        key = f"{node['op_id']}:{node['op']}"
        assert key in ops, (key, sorted(ops))
        assert ops[key]["rows_last"] == node["rows"], key


# -- batching occupancy in stats() -------------------------------------------

def test_stats_batching_occupancy(make_session):
    session = _session()
    graph = create_graph(session, SOCIAL)
    server = QueryServer(session, graph=graph, start=False,
                         config=ServerConfig(workers=1, max_batch=8))
    handles = [server.submit(Q_ORDER, {"min": 30}) for _ in range(4)]
    server.start()
    for h in handles:
        h.result(timeout=10)
    stats = server.stats()
    b = stats["batching"]
    assert b["batches"] == 1 and b["members"] == 4
    assert b["mean_occupancy"] == 4.0
    assert b["window_occupancy"] == 4.0
    server.shutdown()


def test_gauges_follow_live_servers_and_deregister_on_shutdown():
    """Review regression: the windowed gauges dispatch to the newest
    LIVE server and deregister on shutdown — a dead server must not
    keep serving (or stay pinned by) the registry callbacks, mirroring
    the admission depth gauge's lifecycle."""
    session = _session()
    graph = create_graph(session, SOCIAL)
    reg = session.metrics_registry
    a = QueryServer(session, graph=graph, config=ServerConfig(
        workers=1, slo=SLOConfig(latency_target_s=5.0)))
    for _ in range(2):
        a.run(Q_COUNT)
    assert reg.snapshot()["telemetry.window_qps"] > 0
    b = QueryServer(session, graph=graph,
                    config=ServerConfig(workers=1))
    # the newest live server (b, no traffic yet) owns the window gauges
    assert reg.snapshot()["telemetry.window_qps"] == 0.0
    assert b.shutdown()
    # b left the live set: gauges revert to a's still-live window
    assert reg.snapshot()["telemetry.window_qps"] > 0
    assert reg.snapshot()["slo.availability"] == 1.0
    assert a.shutdown()
    snap = reg.snapshot()
    assert snap["telemetry.window_qps"] == 0.0
    assert reg._telemetry_live == []


def test_deadline_expiry_counts_as_abort_not_availability_error():
    """Review regression: an expired budget is the budget's verdict,
    not the server's — it must not burn the availability SLO (the same
    CancellationError exemption the breaker and device ladder apply)."""
    from caps_tpu.serve import DeadlineExceeded
    from caps_tpu.testing.faults import slow_operator
    session = _session()
    graph = create_graph(session, SOCIAL)
    server = QueryServer(session, graph=graph, config=ServerConfig(
        workers=1, slo=SLOConfig(latency_target_s=5.0,
                                 availability_objective=0.9)))
    try:
        server.run(Q_ORDER, {"min": 0})       # warm the plan
        with slow_operator("Filter", 0.2):
            h = server.submit(Q_ORDER, {"min": 0}, deadline_s=0.05)
            with pytest.raises(DeadlineExceeded):
                h.result(timeout=10)
        rep = server.telemetry.slo_report()
        assert rep["availability"] == 1.0     # the abort never counted
        assert rep["availability_burn_rate"] == 0.0
        s = server.stats()["telemetry"]
        assert s["rates_per_s"]["aborts"] > 0
        assert s["rates_per_s"]["errors"] == 0.0
    finally:
        server.shutdown()


# -- chrome-trace device lanes -----------------------------------------------

def test_chrome_trace_pid_is_device_lane():
    from caps_tpu.obs import chrome_trace_events, tracer as tracer_mod
    from caps_tpu.obs.tracer import Tracer
    prev = tracer_mod._device_index_provider
    tracer_mod.set_device_index_provider(lambda: 3)
    try:
        tr = Tracer(enabled=True)
        with tr.span("query", kind="query"):
            with tr.span("op.Scan", kind="operator"):
                tr.event("tick")
    finally:
        tracer_mod.set_device_index_provider(prev)
    events = chrome_trace_events(tr.spans)
    assert {e["pid"] for e in events} == {3}
    # spans without a device attr inherit the parent's lane (fallback 0)
    from caps_tpu.obs.tracer import Span
    root = Span(name="q", kind="query", attrs={"device": 1}, wall_s=0.01)
    root.children.append(Span(name="op.child", kind="operator",
                              wall_s=0.005))
    lone = Span(name="solo", kind="phase", wall_s=0.001)
    events = chrome_trace_events([root, lone])
    by_name = {e["name"]: e["pid"] for e in events}
    assert by_name == {"q": 1, "op.child": 1, "solo": 0}


def test_serve_devices_installs_tracer_provider():
    from caps_tpu.obs import tracer as tracer_mod
    from caps_tpu.serve import devices
    assert tracer_mod._device_index_provider \
        is devices.executing_device_index


def test_multi_replica_trace_renders_parallel_lanes():
    from caps_tpu.obs import chrome_trace_events
    session = _session()
    graph = create_graph(session, SOCIAL)
    server = QueryServer(session, graph=graph, start=False,
                         config=ServerConfig(devices=2))
    r0, r1 = server.devices.replicas
    # each replica owns its session (and tracer): enable both
    r0.session.tracer.enabled = True
    r1.session.tracer.enabled = True
    try:
        for replica in (r0, r1):
            h = server.submit(Q_ORDER, {"min": 30})
            batch = server.batcher.next_batch(timeout=0)
            server._execute_batch(batch, replica)
            h.result(timeout=5)
    finally:
        r0.session.tracer.enabled = False
        r1.session.tracer.enabled = False
    # replica 1 executes on its CLONE session; collect spans from both
    spans = list(session.tracer.spans) + list(r1.session.tracer.spans)
    pids = {e["pid"] for e in chrome_trace_events(spans)}
    assert {0, 1} <= pids, pids
    server.shutdown()


# -- health_report / stats integration ---------------------------------------

def test_health_report_and_stats_telemetry(make_session):
    session = _session()
    graph = create_graph(session, SOCIAL)
    server = QueryServer(session, graph=graph, config=ServerConfig(
        workers=2, slo=SLOConfig(latency_target_s=5.0,
                                 latency_objective=0.95,
                                 availability_objective=0.99)))
    try:
        for _ in range(5):
            assert server.run(Q_COUNT).to_maps() == [{"c": 4}]
        report = server.health_report()
        assert report["status"] == "healthy"
        assert report["slo"]["within_budget"] is True
        assert report["slo"]["availability"] == 1.0
        win = report["window"]
        assert win["latency"]["count"] == 5
        assert win["latency"]["p99_s"] is not None
        assert win["qps"] > 0
        assert set(report) >= {"status", "slo", "window", "breakers",
                               "devices", "compaction"}
        stats = server.stats()
        assert stats["telemetry"]["requests"] == 5
        assert stats["slo"]["latency_burn_rate"] == 0.0
        # device 0 accumulated windowed busy time
        assert stats["telemetry"]["device_utilization"].get(0, 0) > 0
        # flight recorder saw every request
        dump = server.dump_flight_recorder()
        assert dump["reason"] == "manual"
        assert len(dump["records"]) == 5
        assert all(r["outcome"] == "ok" for r in dump["records"])
    finally:
        server.shutdown()


# -- telemetry window shape through config (ISSUE 10 satellite) --------------

def test_config_window_threads_through_at_non_default_shape(fake_clock):
    """``ServerConfig.telemetry_window_s``/``telemetry_buckets`` reach
    every rolling instrument: at a 10 s window a sample expires exactly
    at +10 s (not the 60 s default), compile seconds included."""
    session = _session("local")
    server = QueryServer(session, config=ServerConfig(
        telemetry_window_s=10.0, telemetry_buckets=10), start=False)
    try:
        tel = server.telemetry
        assert tel.window_s == 10.0 and tel.buckets == 10
        tel.note_result("fam", 0.2, "ok")
        tel.note_compile(0.7)
        assert tel.summary()["requests"] == 1
        assert tel.window_compile_s() == pytest.approx(0.7)
        fake_clock.advance(9.0)  # still inside the 10 s window
        assert tel.summary()["requests"] == 1
        assert tel.summary()["compile"]["events"] == 1
        fake_clock.advance(2.0)  # past it: everything expired
        assert tel.summary()["requests"] == 0
        assert tel.window_compile_s() == 0.0
        assert tel.summary()["compile"] == {"events": 0, "seconds": 0.0}
        # at the DEFAULT window the same +11 s advance would NOT expire:
        # prove the non-default shape actually took effect
        reg = MetricsRegistry()
        default = ServingTelemetry(reg)
        default.note_result("fam", 0.2, "ok")
        fake_clock.advance(11.0)
        assert default.summary()["requests"] == 1
        default.close()
    finally:
        server.shutdown()


def test_window_compile_seconds_accumulate_and_rotate(fake_clock):
    reg = MetricsRegistry()
    tel = ServingTelemetry(reg, window_s=60.0, buckets=60)
    tel.note_compile(0.5)
    fake_clock.advance(30.0)
    tel.note_compile(0.25)
    assert tel.window_compile_s() == pytest.approx(0.75)
    # the telemetry.compile_s gauge reads the live window
    assert reg.snapshot()["telemetry.compile_s"] == pytest.approx(0.75)
    fake_clock.advance(31.0)  # first charge expired, second still live
    assert tel.window_compile_s() == pytest.approx(0.25)
    fake_clock.advance(30.0)
    assert tel.window_compile_s() == 0.0
    tel.close()

"""TPU-backend specifics: differential parity vs the oracle and
zero-fallback guarantees on the hot path."""
import pytest

from caps_tpu.backends.local.session import LocalCypherSession
from caps_tpu.backends.tpu.session import TPUCypherSession
from caps_tpu.testing.bag import Bag
from caps_tpu.testing.factory import create_graph

SOCIAL = ("CREATE (a:Person {name: 'Alice', age: 23})-"
          "[:KNOWS {since: 2017}]->(b:Person {name: 'Bob', age: 42}), "
          "(b)-[:KNOWS {since: 2016}]->(c:Person {name: 'Carol', age: 1984})")

DIFFERENTIAL_QUERIES = [
    "MATCH (a:Person) RETURN a.name AS n, a.age AS age",
    "MATCH (a)-[:KNOWS]->(b)-[:KNOWS]->(c) RETURN a.name AS a, c.name AS c",
    "MATCH (a)-[k:KNOWS]-(b) WHERE k.since > 2016 RETURN a.name AS n",
    "MATCH (a:Person) WHERE a.name STARTS WITH 'A' OR a.age > 100 "
    "RETURN a.name AS n",
    "MATCH (a:Person) RETURN count(*) AS c, sum(a.age) AS s, avg(a.age) AS av,"
    " min(a.name) AS mn, max(a.name) AS mx",
    "MATCH (a:Person)-[:KNOWS]->(b) RETURN a.name AS n, count(*) AS c",
    "MATCH (a:Person) RETURN a.name AS n ORDER BY a.age DESC SKIP 1 LIMIT 1",
    "MATCH (a:Person) OPTIONAL MATCH (a)-[:KNOWS]->(b) "
    "RETURN a.name AS a, b.name AS b",
    "MATCH (a)-[rs:KNOWS*1..2]->(b) RETURN a.name AS a, b.name AS b, "
    "size(rs) AS hops",
    "UNWIND [3, 1, 2] AS x RETURN x ORDER BY x",
    "MATCH (a:Person) WITH DISTINCT a.age > 30 AS old RETURN old",
    "MATCH (a:Person) WHERE a.name IN ['Alice', 'Carol'] RETURN a.age AS v",
    "MATCH (a:Person) RETURN toUpper(a.name) AS u, size(a.name) AS s",
    "MATCH (a:Person), (b:Person) WHERE a.age < b.age "
    "RETURN a.name AS a, b.name AS b",
]


@pytest.fixture(scope="module")
def sessions():
    return LocalCypherSession(), TPUCypherSession()


@pytest.fixture(scope="module")
def graphs(sessions):
    local, tpu = sessions
    return create_graph(local, SOCIAL), create_graph(tpu, SOCIAL)


@pytest.mark.parametrize("query", DIFFERENTIAL_QUERIES)
def test_differential_parity(graphs, query):
    g_local, g_tpu = graphs
    expected = g_local.cypher(query).records.to_maps()
    actual = g_tpu.cypher(query).records.to_maps()
    assert Bag(actual) == Bag(expected), Bag(expected).diff(Bag(actual))


def test_hot_path_has_no_fallbacks():
    session = TPUCypherSession()
    g = create_graph(session, SOCIAL)
    before = session.fallback_count
    g.cypher("MATCH (a:Person)-[:KNOWS]->(b)-[:KNOWS]->(c) "
             "WHERE a.name = 'Alice' RETURN c.name AS n").records.to_maps()
    assert session.fallback_count == before, session.backend.fallback_reasons


def test_collect_stays_on_device():
    session = TPUCypherSession()
    g = create_graph(session, SOCIAL)
    before = session.fallback_count
    rows = g.cypher("MATCH (a:Person) RETURN collect(a.age) AS l").records.to_maps()
    assert sorted(rows[0]["l"]) == [23, 42, 1984]
    # collect gained a device path (table.py device collect); it must no
    # longer bounce the query to the oracle backend.
    assert session.fallback_count == before, session.backend.fallback_reasons


def test_string_pool_roundtrip():
    session = TPUCypherSession()
    g = create_graph(session, "CREATE ({s: 'zeta'}), ({s: 'alpha'}), ({s: 'beta'})")
    rows = g.cypher("MATCH (n) RETURN n.s AS s ORDER BY s").records.to_maps()
    assert [r["s"] for r in rows] == ["alpha", "beta", "zeta"]


def test_distinct_aggregates_stay_on_device():
    """DISTINCT aggregation has a device path (one extra stable sort per
    distinct column marks first occurrences — table.py _group_device);
    count/sum/avg/collect(DISTINCT x) must not bounce to the oracle
    (round-4 VERDICT item 6)."""
    session = TPUCypherSession()
    g = create_graph(session, "CREATE (:P {v: 1, g: 'a'}), (:P {v: 1, g: 'a'}), "
                              "(:P {v: 2, g: 'a'}), (:P {v: 2, g: 'b'}), "
                              "(:P {v: 3, g: 'b'})")
    before = session.fallback_count
    rows = g.cypher("MATCH (n:P) RETURN count(DISTINCT n.v) AS c, "
                    "sum(DISTINCT n.v) AS s, collect(DISTINCT n.v) AS l"
                    ).records.to_maps()
    assert rows[0]["c"] == 3 and rows[0]["s"] == 6
    assert sorted(rows[0]["l"]) == [1, 2, 3]
    rows = g.cypher("MATCH (n:P) RETURN n.g AS g, count(DISTINCT n.v) AS c "
                    "ORDER BY g").records.to_maps()
    assert rows == [{"g": "a", "c": 2}, {"g": "b", "c": 2}]
    assert session.fallback_count == before, session.backend.fallback_reasons

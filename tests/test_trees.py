import dataclasses

from caps_tpu.okapi.trees import TreeNode


@dataclasses.dataclass(frozen=True)
class Leaf(TreeNode):
    value: int


@dataclasses.dataclass(frozen=True)
class Add(TreeNode):
    lhs: TreeNode
    rhs: TreeNode


@dataclasses.dataclass(frozen=True)
class Sum(TreeNode):
    terms: tuple


def test_children_and_walk():
    t = Add(Leaf(1), Sum((Leaf(2), Leaf(3))))
    assert [type(n).__name__ for n in t.walk()] == ["Add", "Leaf", "Sum", "Leaf", "Leaf"]
    assert t.size == 5
    assert t.height == 3


def test_map_children_identity_preserves_sharing():
    t = Add(Leaf(1), Leaf(2))
    assert t.map_children(lambda c: c) is t


def test_transform_up_rewrites():
    t = Add(Leaf(1), Add(Leaf(2), Leaf(3)))

    def const_fold(n):
        if isinstance(n, Add) and isinstance(n.lhs, Leaf) and isinstance(n.rhs, Leaf):
            return Leaf(n.lhs.value + n.rhs.value)
        return n

    assert t.transform_up(const_fold) == Leaf(6)


def test_transform_down():
    t = Add(Leaf(1), Leaf(2))

    def bump(n):
        return Leaf(n.value + 10) if isinstance(n, Leaf) else n

    assert t.transform_down(bump) == Add(Leaf(11), Leaf(12))


def test_collect_and_exists():
    t = Add(Leaf(1), Sum((Leaf(2), Leaf(3))))
    assert len(t.collect(lambda n: isinstance(n, Leaf))) == 3
    assert t.exists(lambda n: isinstance(n, Leaf) and n.value == 3)
    assert not t.exists(lambda n: isinstance(n, Leaf) and n.value == 9)


def test_pretty_prints_all_nodes():
    t = Add(Leaf(1), Leaf(2))
    s = t.pretty()
    assert "Add" in s and s.count("Leaf") == 2

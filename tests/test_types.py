from caps_tpu.okapi.types import (
    CTAny, CTBoolean, CTFloat, CTInteger, CTList, CTMap, CTNode, CTNull,
    CTNumber, CTRelationship, CTString, CTVoid, from_python, join_all,
)


def test_nullable_material_roundtrip():
    assert CTInteger.nullable.material == CTInteger
    assert CTInteger.nullable.is_nullable
    assert not CTInteger.is_nullable
    assert CTNull.nullable == CTNull
    assert CTAny.nullable == CTAny


def test_join_numbers():
    assert CTInteger.join(CTFloat) == CTNumber
    assert CTInteger.join(CTInteger.nullable) == CTInteger.nullable
    assert CTInteger.join(CTString) == CTAny


def test_join_null_makes_nullable():
    assert CTInteger.join(CTNull) == CTInteger.nullable
    assert CTNull.join(CTString) == CTString.nullable


def test_void_is_bottom():
    assert CTVoid.join(CTBoolean) == CTBoolean
    assert join_all([]) == CTVoid
    assert CTVoid.meet(CTBoolean) == CTVoid


def test_node_label_join_intersects():
    ab = CTNode(["A", "B"])
    ac = CTNode(["A", "C"])
    assert ab.join(ac) == CTNode(["A"])
    assert ab.meet(ac) == CTNode(["A", "B", "C"])
    assert CTNode().join(ab) == CTNode()


def test_rel_type_join_unions():
    knows = CTRelationship(["KNOWS"])
    likes = CTRelationship(["LIKES"])
    assert knows.join(likes) == CTRelationship(["KNOWS", "LIKES"])
    assert knows.meet(CTRelationship()) == knows
    assert knows.meet(likes) == CTVoid
    # empty set = any relationship
    assert CTRelationship().join(knows) == CTRelationship()


def test_list_join():
    assert CTList(CTInteger).join(CTList(CTFloat)) == CTList(CTNumber)
    assert CTList(CTInteger).join(CTList(CTNull)) == CTList(CTInteger.nullable)


def test_subtype_and_could_be():
    assert CTInteger.subtype_of(CTNumber)
    assert CTInteger.subtype_of(CTAny)
    assert not CTNumber.subtype_of(CTInteger)
    assert CTNode(["A"]).subtype_of(CTNode())
    assert CTNumber.could_be(CTInteger)
    assert not CTString.could_be(CTInteger)


def test_from_python():
    assert from_python(None) == CTNull
    assert from_python(True) == CTBoolean
    assert from_python(3) == CTInteger
    assert from_python(3.5) == CTFloat
    assert from_python("x") == CTString
    assert from_python([1, 2.0]) == CTList(CTNumber)
    assert from_python({"a": 1}) == CTMap


def test_repr():
    assert repr(CTInteger.nullable) == "CTInteger?"
    assert repr(CTNode(["A", "B"])) == "CTNode(A:B)"
    assert repr(CTList(CTString)) == "CTList(CTString)"

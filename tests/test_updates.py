"""Live graph updates: snapshot isolation, failure-atomic writes,
compaction, and mixed read/write serving (ISSUE 8).

Covers the write path end to end:

* Cypher ``CREATE``/``SET``/``DELETE`` semantics on versioned graphs,
  on both the local oracle and the device backend;
* the programmatic ``graph.apply(updates)`` API;
* snapshot isolation: in-flight readers finish on the snapshot they
  started with, torn reads are impossible by construction;
* failure atomicity: an injected abort mid-commit rolls back the delta
  tables AND the string pool, and a retried write succeeds exactly once;
* compaction: digest parity between "apply then read" and "read the
  post-compaction snapshot", failure containment under
  ``flaky_compaction``, and the serve-tier background compactor;
* scoped plan-cache eviction: a write to one graph never evicts an
  unrelated graph's cached plans;
* the LDBC-interactive IU-style insert subset through the server;
* the acceptance soak: 8 clients at >= 20% writes under injected write
  aborts — availability 1.0, every reader digest-equal to a serial
  execution on its admission-time snapshot, at least one compaction
  completing under load.
"""
from __future__ import annotations

import threading

import pytest

from caps_tpu.relational.session import result_digest
from caps_tpu.relational.updates import (CreateNode, CreateRel, DeleteNode,
                                         DeleteRel, SetNodeProps,
                                         UpdateError, VersionedGraph,
                                         versioned)
from caps_tpu.testing.factory import create_graph

BACKENDS = ["local", "tpu"]

SOCIAL = ("CREATE (a:Person {name:'Alice', age:30})-[:KNOWS {since:2018}]->"
          "(b:Person {name:'Bob', age:25}), "
          "(b)-[:KNOWS {since:2020}]->(c:Person {name:'Carol', age:41})")


def _vg(session, create: str = SOCIAL) -> VersionedGraph:
    return versioned(session, create_graph(session, create))


def _rows(result):
    return result.records.to_maps() if result.records is not None else []


def _names(graph):
    return [r["n"] for r in _rows(graph.cypher(
        "MATCH (p:Person) RETURN p.name AS n ORDER BY n"))]


# -- Cypher write semantics --------------------------------------------------

@pytest.mark.parametrize("backend", BACKENDS)
def test_create_nodes_and_rels(make_session, backend):
    s = make_session(backend)
    vg = _vg(s)
    r = vg.cypher("CREATE (:Person {name:'Dave', age:$a})", {"a": 52})
    assert r.metrics["updates"]["created_nodes"] == 1
    assert r.metrics["snapshot_version"] == 1
    assert _names(vg) == ["Alice", "Bob", "Carol", "Dave"]
    # MATCH ... CREATE: one relationship per matched pair
    vg.cypher("MATCH (a:Person {name:'Alice'}), (d:Person {name:'Dave'}) "
              "CREATE (a)-[:KNOWS {since:$y}]->(d)", {"y": 2024})
    got = _rows(vg.cypher(
        "MATCH (:Person {name:'Alice'})-[r:KNOWS]->(t) "
        "RETURN t.name AS t, r.since AS y ORDER BY y"))
    assert got == [{"t": "Bob", "y": 2018}, {"t": "Dave", "y": 2024}]
    # whole-pattern CREATE with a fresh intermediate node
    vg.cypher("CREATE (:City {name:'Zurich'})<-[:LIVES_IN]-"
              "(:Person {name:'Erin', age:29})")
    assert _rows(vg.cypher(
        "MATCH (p:Person)-[:LIVES_IN]->(c:City) "
        "RETURN p.name AS p, c.name AS c")) == \
        [{"p": "Erin", "c": "Zurich"}]


@pytest.mark.parametrize("backend", BACKENDS)
def test_create_per_matched_row(make_session, backend):
    s = make_session(backend)
    vg = _vg(s)
    # CREATE executes once per matched row (Cypher semantics)
    r = vg.cypher("MATCH (p:Person) CREATE (:Shadow {of: p.name})")
    assert r.metrics["updates"]["created_nodes"] == 3
    assert _rows(vg.cypher("MATCH (s:Shadow) RETURN count(*) AS c")) == \
        [{"c": 3}]


@pytest.mark.parametrize("backend", BACKENDS)
def test_set_properties(make_session, backend):
    s = make_session(backend)
    vg = _vg(s)
    # computed SET value evaluates through the read pipeline
    vg.cypher("MATCH (p:Person {name:'Bob'}) "
              "SET p.age = p.age + 1, p.nick = 'bobby'")
    assert _rows(vg.cypher("MATCH (p:Person {name:'Bob'}) "
                           "RETURN p.age AS a, p.nick AS k")) == \
        [{"a": 26, "k": "bobby"}]
    # += merges, null removes
    vg.cypher("MATCH (p:Person {name:'Bob'}) SET p += $m",
              {"m": {"nick": None, "city": "Bern"}})
    assert _rows(vg.cypher("MATCH (p:Person {name:'Bob'}) "
                           "RETURN p.nick AS k, p.city AS c")) == \
        [{"k": None, "c": "Bern"}]
    # = replaces the whole property map
    vg.cypher("MATCH (p:Person {name:'Bob'}) SET p = $m",
              {"m": {"name": "Bob", "age": 30}})
    assert _rows(vg.cypher("MATCH (p:Person {name:'Bob'}) "
                           "RETURN p.age AS a, p.city AS c")) == \
        [{"a": 30, "c": None}]


@pytest.mark.parametrize("backend", BACKENDS)
def test_delete_semantics(make_session, backend):
    s = make_session(backend)
    vg = _vg(s)
    # deleting a connected node without DETACH is a constraint error,
    # and the failed write changes NOTHING (atomicity)
    v_before = vg.current().snapshot_version
    with pytest.raises(UpdateError):
        vg.cypher("MATCH (p:Person {name:'Bob'}) DELETE p")
    assert vg.current().snapshot_version == v_before
    assert _names(vg) == ["Alice", "Bob", "Carol"]
    # DETACH DELETE removes the node and its incident relationships
    r = vg.cypher("MATCH (p:Person {name:'Bob'}) DETACH DELETE p")
    assert r.metrics["updates"]["deleted_nodes"] == 1
    assert r.metrics["updates"]["deleted_rels"] == 2
    assert _names(vg) == ["Alice", "Carol"]
    assert _rows(vg.cypher("MATCH ()-[r:KNOWS]->() "
                           "RETURN count(*) AS c")) == [{"c": 0}]
    # relationship delete leaves endpoints
    vg.cypher("MATCH (a:Person {name:'Alice'}), (c:Person {name:'Carol'}) "
              "CREATE (a)-[:KNOWS {since:2025}]->(c)")
    vg.cypher("MATCH (:Person {name:'Alice'})-[r:KNOWS]->() DELETE r")
    assert _names(vg) == ["Alice", "Carol"]


@pytest.mark.parametrize("backend", BACKENDS)
def test_update_rejections(make_session, backend):
    s = make_session(backend)
    vg = _vg(s)
    plain = create_graph(s, "CREATE (:Person {name:'X'})")
    with pytest.raises(UpdateError):
        plain.cypher("CREATE (:Person {name:'Y'})")
    with pytest.raises(UpdateError):
        vg.current().cypher("CREATE (:Person {name:'Y'})")
    with pytest.raises(UpdateError):
        vg.cypher("CREATE (n:Person) RETURN n")
    with pytest.raises(UpdateError):
        vg.cypher("MATCH (n:Person) SET n:Admin")
    # failed statements committed nothing
    assert vg.current().snapshot_version == 0


def test_explain_update_commits_nothing(make_session):
    s = make_session("local")
    vg = _vg(s)
    res = s.cypher_on_graph(vg, "EXPLAIN MATCH (p:Person {name:'Alice'}) "
                                "CREATE (p)-[:LIKES]->(:Thing)")
    assert "CreateNode" in res.plans["updates"]
    assert "CreateRel" in res.plans["updates"]
    assert "relational" in res.plans
    assert vg.current().snapshot_version == 0


# -- programmatic apply ------------------------------------------------------

@pytest.mark.parametrize("backend", BACKENDS)
def test_programmatic_apply(make_session, backend):
    s = make_session(backend)
    vg = _vg(s)
    a = CreateNode(labels=("Person",), properties={"name": "Zed", "age": 7})
    info = vg.apply([a, CreateRel("KNOWS", a, 0, {"since": 2030})])
    assert info.created_nodes == 1 and info.created_rels == 1
    assert _rows(vg.cypher(
        "MATCH (z:Person {name:'Zed'})-[r:KNOWS]->(t) "
        "RETURN t.name AS t, r.since AS y")) == \
        [{"t": "Alice", "y": 2030}]
    vg.apply([SetNodeProps(a, {"age": 8})])
    assert _rows(vg.cypher("MATCH (z:Person {name:'Zed'}) "
                           "RETURN z.age AS a")) == [{"a": 8}]
    # validation failures are atomic no-ops
    v = vg.current().snapshot_version
    with pytest.raises(UpdateError):
        vg.apply([DeleteRel(999_999)])
    with pytest.raises(UpdateError):
        vg.apply([CreateRel("KNOWS", 0, 999_999)])
    assert vg.current().snapshot_version == v
    vg.apply([DeleteNode(a, detach=True)])
    assert _rows(vg.cypher("MATCH (z:Person {name:'Zed'}) "
                           "RETURN count(*) AS c")) == [{"c": 0}]


# -- snapshot isolation ------------------------------------------------------

@pytest.mark.parametrize("backend", BACKENDS)
def test_snapshot_isolation_unit(make_session, backend):
    s = make_session(backend)
    vg = _vg(s)
    snap = vg.current()
    before = result_digest(snap.cypher(
        "MATCH (p:Person) RETURN p.name AS n, p.age AS a"))
    vg.cypher("CREATE (:Person {name:'New', age:1})")
    vg.cypher("MATCH (p:Person {name:'Alice'}) SET p.age = 99")
    vg.cypher("MATCH (p:Person {name:'Carol'}) DETACH DELETE p")
    # the pinned snapshot still reads its version of the world
    assert result_digest(snap.cypher(
        "MATCH (p:Person) RETURN p.name AS n, p.age AS a")) == before
    # while the handle sees everything
    assert _names(vg) == ["Alice", "Bob", "New"]
    assert _rows(vg.cypher("MATCH (p:Person {name:'Alice'}) "
                           "RETURN p.age AS a")) == [{"a": 99}]


# -- failure atomicity -------------------------------------------------------

def test_abort_write_rolls_back_completely(make_session):
    from caps_tpu.testing.faults import abort_write
    s = make_session("tpu")
    vg = _vg(s)
    pool_before = len(s.backend.pool)
    v_before = vg.current().snapshot_version
    digest_before = result_digest(vg.cypher(
        "MATCH (p:Person) RETURN p.name AS n, p.age AS a"))
    with abort_write(s, after_n_columns=1, n_times=1) as budget:
        with pytest.raises(Exception):
            vg.cypher("CREATE (:Person {name:'Torn', age:1})")
    assert budget.injected == 1
    # nothing committed, nothing leaked: version, data, AND the string
    # pool (the fused replayability fence) are exactly as before
    assert vg.current().snapshot_version == v_before
    assert len(s.backend.pool) == pool_before
    assert result_digest(vg.cypher(
        "MATCH (p:Person) RETURN p.name AS n, p.age AS a")) == \
        digest_before
    assert s.metrics_snapshot()["updates.rolled_back"] >= 1
    # the SAME write retried (the serving tier's TRANSIENT path) lands
    # exactly once
    vg.cypher("CREATE (:Person {name:'Torn', age:1})")
    assert _rows(vg.cypher("MATCH (p:Person {name:'Torn'}) "
                           "RETURN count(*) AS c")) == [{"c": 1}]


def test_abort_between_delta_columns(make_session):
    """An abort AFTER some delta columns already placed (mid-table)
    still rolls back to a clean snapshot."""
    from caps_tpu.testing.faults import abort_write
    s = make_session("tpu")
    vg = _vg(s)
    with abort_write(s, after_n_columns=2, n_times=1):
        with pytest.raises(Exception):
            vg.cypher("CREATE (:Person {name:'A1', age:1}), "
                      "(:Person {name:'A2', age:2})")
    assert _names(vg) == ["Alice", "Bob", "Carol"]
    vg.cypher("CREATE (:Person {name:'A1', age:1})")
    assert "A1" in _names(vg)


# -- compaction --------------------------------------------------------------

@pytest.mark.parametrize("backend", BACKENDS)
def test_compaction_digest_parity(make_session, backend):
    s = make_session(backend)
    vg = _vg(s)
    vg.cypher("CREATE (:Person {name:'Dave', age:52})")
    vg.cypher("MATCH (p:Person {name:'Alice'}) SET p.age = 31")
    vg.cypher("MATCH (p:Person {name:'Carol'}) DETACH DELETE p")
    vg.cypher("MATCH (a:Person {name:'Alice'}), (d:Person {name:'Dave'}) "
              "CREATE (a)-[:KNOWS {since:2025}]->(d)")
    q = ("MATCH (a:Person)-[r:KNOWS]->(b:Person) "
         "RETURN a.name AS a, r.since AS y, b.name AS b, b.age AS age")
    before_nodes = result_digest(vg.cypher(
        "MATCH (p:Person) RETURN p.name AS n, p.age AS a"))
    before_edges = result_digest(vg.cypher(q))
    assert vg.delta_rows() > 0
    assert vg.compact() is True
    assert vg.delta_rows() == 0
    # "apply then read" is digest-equal to "read the post-compaction
    # snapshot"
    assert result_digest(vg.cypher(
        "MATCH (p:Person) RETURN p.name AS n, p.age AS a")) == before_nodes
    assert result_digest(vg.cypher(q)) == before_edges
    # ids survive compaction: more writes keep composing
    vg.cypher("MATCH (p:Person {name:'Dave'}) SET p.age = 53")
    assert _rows(vg.cypher("MATCH (p:Person {name:'Dave'}) "
                           "RETURN p.age AS a")) == [{"a": 53}]


def test_flaky_compaction_contained(make_session):
    from caps_tpu.testing.faults import flaky_compaction
    s = make_session("tpu")
    vg = _vg(s)
    vg.cypher("CREATE (:Person {name:'Dave', age:52})")
    digest = result_digest(vg.cypher("MATCH (p:Person) RETURN p.name AS n"))
    with flaky_compaction(s, error_rate=1.0, n_times=1) as budget:
        with pytest.raises(Exception):
            vg.compact()
    assert budget.injected == 1
    # the failed fold changed nothing; serving (reads AND writes)
    # continues; the next fold succeeds
    assert result_digest(vg.cypher(
        "MATCH (p:Person) RETURN p.name AS n")) == digest
    vg.cypher("CREATE (:Person {name:'Erin', age:29})")
    assert vg.compact() is True
    assert vg.delta_rows() == 0
    assert "Erin" in _names(vg)


def test_background_compactor_in_server(make_session):
    from caps_tpu.obs import clock
    from caps_tpu.serve import QueryServer, ServerConfig
    s = make_session("tpu")
    vg = _vg(s)
    server = QueryServer(s, graph=vg, config=ServerConfig(
        workers=2, compaction_threshold_rows=2,
        compaction_interval_s=0.005))
    try:
        for i in range(4):
            server.submit(f"CREATE (:Item {{k:{i}}})").result(timeout=30)
        deadline = clock.now() + 10.0
        while clock.now() < deadline:
            if s.metrics_snapshot().get("compaction.runs", 0) >= 1:
                break
            clock.sleep(0.01)
        stats = server.stats()
        assert s.metrics_snapshot()["compaction.runs"] >= 1
        assert stats["compaction"] is not None
        assert stats["compaction"]["state"] in ("idle", "running")
        rows = server.submit("MATCH (i:Item) RETURN count(*) AS c"
                             ).rows(timeout=30)
        assert rows == [{"c": 4}]
    finally:
        server.shutdown()


# -- scoped plan-cache eviction ----------------------------------------------

def test_unrelated_graph_plans_survive_a_write(make_session):
    """Satellite regression: a write to one graph evicts only THAT
    graph's superseded snapshot plans — an unrelated graph's cached
    plans keep hitting."""
    s = make_session("local")
    vg1 = _vg(s)
    vg2 = _vg(s, "CREATE (:Widget {sku:1}), (:Widget {sku:2})")
    other = create_graph(s, "CREATE (:Gadget {sn:7})")
    q2 = "MATCH (w:Widget) RETURN count(*) AS c"
    q3 = "MATCH (g:Gadget) RETURN count(*) AS c"
    assert _rows(vg2.cypher(q2)) == [{"c": 2}]
    assert _rows(other.cypher(q3)) == [{"c": 1}]
    assert vg2.cypher(q2).metrics["plan_cache"] == "hit"
    assert other.cypher(q3).metrics["plan_cache"] == "hit"
    hits_before = s.plan_cache.stats()["hits"]
    # write to vg1: neither vg2's snapshot plans nor the plain graph's
    # plans are touched
    vg1.cypher("CREATE (:Person {name:'New'})")
    assert vg2.cypher(q2).metrics["plan_cache"] == "hit"
    assert other.cypher(q3).metrics["plan_cache"] == "hit"
    assert s.plan_cache.stats()["hits"] == hits_before + 2
    # while vg1's own superseded snapshot plans were evicted (scoped)
    res = vg1.cypher("MATCH (p:Person) RETURN count(*) AS c")
    assert res.metrics["plan_cache"] == "miss"


def test_snapshot_reads_use_plan_cache_and_fuse(make_session):
    """Snapshots are real plan-cache/fused citizens: repeated reads of
    the SAME snapshot hit the cache; a commit moves readers to the new
    snapshot (a miss, by design), and old plans are evicted."""
    s = make_session("tpu")
    vg = _vg(s)
    q = "MATCH (p:Person) WHERE p.age > $min RETURN p.name AS n ORDER BY n"
    assert vg.cypher(q, {"min": 20}).metrics["plan_cache"] == "miss"
    assert vg.cypher(q, {"min": 28}).metrics["plan_cache"] == "hit"
    entries = s.plan_cache.stats()["entries"]
    assert entries >= 1
    vg.cypher("CREATE (:Person {name:'New', age:50})")
    res = vg.cypher(q, {"min": 20})
    assert res.metrics["plan_cache"] == "miss"
    assert [r["n"] for r in _rows(res)] == ["Alice", "Bob", "Carol", "New"]


# -- LDBC interactive update subset (IU-style inserts through the server) ----

def test_iu_insert_subset_through_server(make_session):
    """IU-1-style (insert person), IU-8-style (add friendship), and an
    IU-6-ish post insert, run through the server as parameterized write
    statements, with digest parity between 'apply then read' and 'read
    the post-compaction snapshot'."""
    from caps_tpu.serve import QueryServer, ServerConfig
    s = make_session("tpu")
    vg = versioned(s, create_graph(
        s, "CREATE (:Person {id:1, firstName:'Ada'}), "
           "(:Person {id:2, firstName:'Bo'})"))
    server = QueryServer(s, graph=vg, config=ServerConfig(workers=2))
    try:
        # IU-1: insert person
        server.run("CREATE (:Person {id:$id, firstName:$fn, "
                   "browserUsed:$b})",
                   {"id": 3, "fn": "Cy", "b": "Firefox"})
        # IU-8: add friendship between two existing persons
        server.run("MATCH (a:Person {id:$a}), (b:Person {id:$b}) "
                   "CREATE (a)-[:KNOWS {creationDate:$d}]->(b)",
                   {"a": 1, "b": 3, "d": 20260804})
        # IU-6-ish: insert a post by an existing person
        server.run("MATCH (p:Person {id:$p}) "
                   "CREATE (p)<-[:HAS_CREATOR]-"
                   "(:Post {id:$post, content:$c})",
                   {"p": 3, "post": 100, "c": "hello"})
        reads = [
            ("MATCH (p:Person) RETURN p.id AS id, p.firstName AS fn", {}),
            ("MATCH (a:Person)-[k:KNOWS]->(b:Person) "
             "RETURN a.id AS a, b.id AS b, k.creationDate AS d", {}),
            ("MATCH (m:Post)-[:HAS_CREATOR]->(p:Person) "
             "RETURN m.id AS m, m.content AS c, p.id AS p", {}),
        ]
        applied = [result_digest(server.run(q, params))
                   for q, params in reads]
        assert vg.compact() is True
        compacted = [result_digest(server.run(q, params))
                     for q, params in reads]
        assert applied == compacted
    finally:
        server.shutdown()


# -- the acceptance soak -----------------------------------------------------

def _mixed_soak(make_session, *, writers, readers, writes_each,
                reads_each, compaction_threshold):
    """8-client mixed read/write soak under ~20%+ write aborts.

    Asserts the ISSUE acceptance: availability 1.0 (every request
    resolves), ZERO torn reads (every reader's rows equal the serial
    state at its admission-time snapshot version), and at least one
    background compaction completing under load."""
    from caps_tpu.serve import QueryServer, RetryPolicy, ServeError, \
        ServerConfig
    from caps_tpu.testing.faults import abort_write
    s = make_session("tpu")
    vg = versioned(s, create_graph(s, "CREATE (:Seed {k:-1, v:-1})"))
    server = QueryServer(s, graph=vg, config=ServerConfig(
        workers=2, max_queue=4096,
        # 8 attempts: the every-5 injector is PERMANENT and a commit
        # places 3 columns, so adversarial thread phasing can land the
        # same write on the abort boundary several attempts running —
        # the retry budget must outlast the worst phase, not the mean
        retry=RetryPolicy(max_attempts=8, backoff_base_s=0.002,
                          backoff_max_s=0.05),
        compaction_threshold_rows=compaction_threshold,
        compaction_interval_s=0.005))
    write_log = {}       # version -> (k, v)
    write_log_lock = threading.Lock()
    observations = []    # (snapshot_version, frozenset of (k, v))
    obs_lock = threading.Lock()
    failures = []

    def writer(i):
        for j in range(writes_each):
            k = i * 1000 + j
            try:
                res = server.submit("CREATE (:Item {k:$k, v:$v})",
                                    {"k": k, "v": k * 7}).result(timeout=60)
                with write_log_lock:
                    write_log[res.metrics["snapshot_version"]] = (k, k * 7)
            except Exception as ex:
                failures.append(("write", k, ex))

    def reader(i):
        for _ in range(reads_each):
            try:
                h = server.submit(
                    "MATCH (n:Item) RETURN n.k AS k, n.v AS v")
                rows = h.rows(timeout=60)
                with obs_lock:
                    observations.append(
                        (h.info["snapshot_version"],
                         frozenset((r["k"], r["v"]) for r in rows)))
            except ServeError as ex:  # pragma: no cover — availability
                failures.append(("read-shed", i, ex))
            except Exception as ex:  # pragma: no cover
                failures.append(("read", i, ex))

    threads = [threading.Thread(target=writer, args=(i,))
               for i in range(writers)]
    threads += [threading.Thread(target=reader, args=(i,))
                for i in range(readers)]
    try:
        with abort_write(s, after_n_columns=1, n_times=None,
                         every_n=5) as budget:
            for t in threads:
                t.start()
            for t in threads:
                t.join()
    finally:
        server.shutdown()
    # availability 1.0: every one of the 8 clients' requests resolved
    assert not failures, failures[:5]
    assert len(write_log) == writers * writes_each
    assert budget.injected > 0, "the abort injector never fired"
    # zero torn reads: each reader's rows are EXACTLY the serial state
    # at its admission-time snapshot version — the set of writes whose
    # commit version <= the pinned version (compaction versions add no
    # writes, so the same fold applies)
    assert observations
    for version, seen in observations:
        expected = frozenset(kv for v, kv in write_log.items()
                             if v <= version)
        assert seen == expected, (
            f"torn read at snapshot v{version}: "
            f"unexpected={sorted(seen - expected)[:5]} "
            f"missing={sorted(expected - seen)[:5]}")
    # the final state digest matches a serial re-execution of the same
    # committed writes, in commit order, on a fresh engine
    s2 = make_session("tpu")
    vg2 = versioned(s2, create_graph(s2, "CREATE (:Seed {k:-1, v:-1})"))
    for _v, (k, v) in sorted(write_log.items()):
        vg2.cypher("CREATE (:Item {k:$k, v:$v})", {"k": k, "v": v})
    q = "MATCH (n:Item) RETURN n.k AS k, n.v AS v"
    assert result_digest(vg.cypher(q)) == result_digest(vg2.cypher(q))
    # at least one compaction completed UNDER LOAD
    assert s.metrics_snapshot()["compaction.runs"] >= 1
    assert s.metrics_snapshot()["updates.rolled_back"] >= 1


def test_soak_mixed_read_write_with_aborts(make_session):
    """Tier-1 soak: 8 clients, 3 writers (~27% writes) under injected
    write aborts."""
    _mixed_soak(make_session, writers=3, readers=5, writes_each=6,
                reads_each=8, compaction_threshold=6)


@pytest.mark.slow
def test_soak_mixed_read_write_long(make_session):
    _mixed_soak(make_session, writers=3, readers=5, writes_each=25,
                reads_each=40, compaction_threshold=12)


# -- multi-device snapshot serving ------------------------------------------

def test_snapshot_reads_replicate_across_devices(make_session):
    """Pinned snapshots replicate onto device replicas: the base
    re-ingests once per device, the delta overlay rebuilds per replica,
    and every device returns the same pinned-version rows."""
    from caps_tpu.serve import QueryServer, ServerConfig
    s = make_session("tpu")
    vg = _vg(s)
    server = QueryServer(s, graph=vg, config=ServerConfig(devices=2))
    try:
        server.submit("CREATE (:Person {name:'Dave', age:52})"
                      ).result(timeout=30)
        handles = [server.submit("MATCH (p:Person) RETURN count(*) AS c")
                   for _ in range(10)]
        results = [h.rows(timeout=30)[0]["c"] for h in handles]
        assert set(results) == {4}
        devices = {h.info.get("device") for h in handles}
        assert devices == {0, 1}, \
            f"both devices should serve snapshot reads, got {devices}"
    finally:
        server.shutdown()


# -- review regressions ------------------------------------------------------

def test_recreating_a_deleted_base_id_does_not_resurrect_it(make_session):
    """A create with an explicit id that tombstones a deleted base
    entity must keep the tombstone: dropping it would unmask the base
    row and scans would return BOTH the old and the new entity."""
    s = make_session("tpu")
    vg = _vg(s)
    vg.apply([DeleteNode(0, detach=True)])  # base id 0 = Alice
    vg.apply([CreateNode(labels=("Person",),
                         properties={"name": "Alice2", "age": 1}, id=0)])
    rows = _rows(vg.cypher("MATCH (p:Person) WHERE p.name STARTS WITH "
                           "'Alice' RETURN p.name AS n"))
    assert [r["n"] for r in rows] == ["Alice2"]
    # and the overlay survives compaction identically
    assert vg.compact() is True
    rows = _rows(vg.cypher("MATCH (p:Person) WHERE p.name STARTS WITH "
                           "'Alice' RETURN p.name AS n"))
    assert [r["n"] for r in rows] == ["Alice2"]


def test_explicit_ids_advance_the_allocator(make_session):
    s = make_session("local")
    vg = _vg(s)
    hi = vg._next_id + 5
    vg.apply([CreateNode(labels=("Marker",), id=hi)])
    # auto-allocated creates must skip past the explicit id
    for _ in range(7):
        vg.apply([CreateNode(labels=("Marker",))])
    assert _rows(vg.cypher("MATCH (m:Marker) RETURN count(*) AS c")) == \
        [{"c": 8}]


def test_failed_compaction_never_clobbers_a_concurrent_commit(
        make_session, monkeypatch):
    """The optimistic fold runs outside the commit lock; if a write
    commits while it runs and the fold then FAILS, the fold's pool
    rollback must be skipped — truncating the pool past the committed
    write's interned strings would corrupt published data."""
    import caps_tpu.relational.updates as U
    s = make_session("tpu")
    vg = _vg(s)
    vg.cypher("CREATE (:Person {name:'Delta', age:1})")  # non-empty delta
    orig = U.build_node_tables
    state = {"fired": False}

    def sabotage(factory, nodes):
        if U.in_compaction() and not state["fired"]:
            state["fired"] = True
            # a write lands mid-fold (commit lock is free), interning a
            # fresh string past the fold's pool mark ...
            vg.apply([CreateNode(labels=("Person",),
                                 properties={"name": "RacerUnique",
                                             "age": 2})])
            # ... then the fold fails
            raise RuntimeError("injected fold failure")
        return orig(factory, nodes)

    monkeypatch.setattr(U, "build_node_tables", sabotage)
    with pytest.raises(RuntimeError):
        vg.compact()
    monkeypatch.setattr(U, "build_node_tables", orig)
    assert state["fired"]
    # the concurrently committed write decodes intact
    rows = _rows(vg.cypher(
        "MATCH (p:Person {name:'RacerUnique'}) RETURN p.name AS n"))
    assert rows == [{"n": "RacerUnique"}]
    # and the next compaction succeeds
    assert vg.compact() is True
    rows = _rows(vg.cypher(
        "MATCH (p:Person {name:'RacerUnique'}) RETURN p.name AS n"))
    assert rows == [{"n": "RacerUnique"}]


# -- lock ordering of the scoped-eviction paths ------------------------------

def test_catalog_dep_validation_no_lock_cycle(monkeypatch):
    """Regression (caught live by the runtime lock graph): plan-cache
    lookup validates catalog dep tokens while holding the cache lock,
    and catalog mutations fan out into the cache while holding the
    catalog lock — dep_token must therefore be lock-free, or the two
    paths form a deadlockable cycle.  Strict mode raises mid-run if the
    cycle ever re-forms."""
    monkeypatch.setenv("CAPS_TPU_LOCK_GRAPH", "1")
    from caps_tpu.obs import lockgraph
    from caps_tpu.testing.sessions import make_backend_session
    lockgraph.reset()
    s = make_backend_session("local")  # locks created under strict mode
    g = create_graph(s, "CREATE (:A {x:1})")
    s.catalog.store("dep_cycle_probe", g)
    q = "FROM GRAPH session.dep_cycle_probe MATCH (n:A) RETURN count(*) AS c"
    errors = []

    def mutator():
        try:
            for i in range(60):
                s.catalog.store(f"other{i % 3}", g)
        except Exception as ex:  # pragma: no cover
            errors.append(ex)

    def querier():
        try:
            for _ in range(60):
                assert _rows(s.cypher(q)) == [{"c": 1}]
        except Exception as ex:  # pragma: no cover
            errors.append(ex)

    threads = [threading.Thread(target=mutator),
               threading.Thread(target=querier)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors
    assert lockgraph.find_cycle() is None


# -- drop_in (the tombstone-mask primitive) ----------------------------------

@pytest.mark.parametrize("backend", BACKENDS)
def test_table_drop_in(make_session, backend):
    from caps_tpu.okapi.types import CTInteger
    s = make_session(backend)
    t = s.table_factory.from_columns(
        {"id": [0, 1, 2, 3, None, 5], "x": [10, 11, 12, 13, 14, 15]},
        {"id": CTInteger.nullable, "x": CTInteger})
    out = t.drop_in("id", {1, 3, 5})
    pairs = list(zip(out.column_values("id"), out.column_values("x")))
    rows = sorted(pairs, key=lambda p: (p[0] is None, p[0] or 0))
    # matching ids drop; nulls are kept (null never matches)
    assert rows == [(0, 10), (2, 12), (None, 14)]
    assert t.drop_in("id", set()) is t

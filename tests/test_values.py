from caps_tpu.okapi.values import (
    CypherNode, CypherRelationship, cypher_equals, cypher_lt, is_truthy,
    order_key,
)


def test_node_identity_equality():
    a1 = CypherNode(1, ["Person"], {"name": "Alice"})
    a2 = CypherNode(1, ["Person"], {"name": "Changed"})
    b = CypherNode(2, ["Person"], {"name": "Alice"})
    assert a1 == a2
    assert a1 != b
    assert hash(a1) == hash(a2)


def test_equals_three_valued():
    assert cypher_equals(1, 1.0) is True
    assert cypher_equals(1, 2) is False
    assert cypher_equals(None, 1) is None
    assert cypher_equals(None, None) is None
    assert cypher_equals(True, 1) is False  # bool is not a number
    assert cypher_equals("a", "a") is True
    assert cypher_equals([1, None], [1, 2]) is None
    assert cypher_equals([1, None], [2, None]) is False
    assert cypher_equals([1, 2], [1, 2, 3]) is False
    assert cypher_equals({"a": 1}, {"a": 1}) is True
    assert cypher_equals({"a": None}, {"a": 1}) is None


def test_lt_three_valued():
    assert cypher_lt(1, 2) is True
    assert cypher_lt(2, 1) is False
    assert cypher_lt(1, None) is None
    assert cypher_lt(1, "a") is None  # incomparable types
    assert cypher_lt("a", "b") is True
    assert cypher_lt([1, 2], [1, 3]) is True


def test_order_key_nulls_last_and_cross_type():
    vals = [3, None, 1, "b", "a", True, 2.5]
    ordered = sorted(vals, key=order_key)
    # strings < booleans < numbers < null per openCypher global order
    assert ordered == ["a", "b", True, 1, 2.5, 3, None]


def test_is_truthy():
    assert is_truthy(True)
    assert not is_truthy(False)
    assert not is_truthy(None)


def test_duration_negative_components_render_and_arith():
    # round-5 review: negative components must not borrow across units
    from caps_tpu.okapi.values import CypherDate, CypherDuration
    d = CypherDuration(0, 0, -3670)
    assert d.iso() == "PT-1H-1M-10S"
    assert CypherDuration(0, 0, 10).plus(
        CypherDuration(0, 0, -40).negate().negate()).seconds == -30
    # date +/- sub-day durations stay symmetric
    day = CypherDate.parse("2020-03-01")
    one_s = CypherDuration(seconds=1)
    assert day.plus(one_s) == day
    assert day.plus(one_s.negate()) == day


def test_datetime_parse_offsets_normalize_to_utc():
    from caps_tpu.okapi.values import CypherDateTime
    a = CypherDateTime.parse("2020-01-01T12:00:00+05:00")
    b = CypherDateTime.parse("2020-01-01T07:00:00")
    c = CypherDateTime.parse("2020-01-01T07:00:00Z")
    assert a == b == c

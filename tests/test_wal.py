"""Durable writes (ISSUE 19): the WAL, the epoch-fenced lease, crash
recovery, owner failover, and the sharded commit protocol.

The contracts under test:

* the commit log — append/recover round-trips the exact cumulative
  delta payload; recovery takes the single highest intact entry;
  a torn or CRC-bad tail is dropped WHOLE and counted
  (``wal.torn_entries``), then truncated physically so the retried
  append lands where the last intact frame ended; duplicate versions
  skip (idempotent peer installs); segments rotate and checkpoints
  truncate them; an unreadable checkpoint refuses loudly instead of
  silently forgetting acked writes;
* failure honesty — a failed fsync raises the typed transient
  :class:`WalWriteError` (``caps_transient`` + ``caps_wal_fault``) and
  the commit rolls back through the string-pool mark: never a silent
  ack, and the graph is bit-for-bit untouched;
* the lease — epoch-fenced ownership through the shared store: a live
  lease blocks rivals, expiry allows a steal at a HIGHER epoch, the
  O_EXCL claim file makes the epoch a compare-and-swap;
* fleet failover — kill the write owner, the router elects the peer
  with the longest replayed log, every acknowledged write survives,
  and a zombie owner's stale-epoch frame is fenced with
  :class:`StaleEpoch` naming the true owner;
* sharded commits — Cypher CREATE/SET/DELETE through a shard group is
  digest-equal to an unsharded versioned session, routed single-shard
  reads see the writes, a mid-commit fault (WAL append or member
  prepare) leaves NO shard partially applied, and a fresh group over
  the same group WAL recovers to full parity.
"""
from __future__ import annotations

import os
import time

import pytest

import caps_tpu
from caps_tpu.durability import (CommitLog, LeaseStore,
                                 compose_delta_payloads, empty_payload,
                                 scan_durable_dir)
from caps_tpu.obs.metrics import MetricsRegistry
from caps_tpu.relational.session import result_digest
from caps_tpu.relational.updates import (VersionedGraph,
                                         delta_state_from_payload,
                                         delta_state_to_payload)
from caps_tpu.serve.errors import StaleEpoch, WalWriteError
from caps_tpu.serve.fleet import BackendSpec, FleetBackend
from caps_tpu.serve.router import FleetRouter, RouterConfig
from caps_tpu.serve.shards import ShardGroup, ShardGroupConfig
from caps_tpu.serve.wire import WireClient
from caps_tpu.testing.factory import create_graph
from caps_tpu.testing.faults import failing_fsync, torn_wal

PEOPLE = """
    CREATE (a:Person {id: 1, name: 'Alice', age: 33}),
           (b:Person {id: 2, name: 'Bob', age: 44}),
           (c:Person {id: 3, name: 'Carol', age: 27}),
           (a)-[:KNOWS {since: 2011}]->(b),
           (b)-[:KNOWS {since: 2015}]->(c)
"""

WRITES = (
    ("CREATE (n:Person {id: 9, name: 'Zed', age: 20})", {}),
    ("MATCH (p:Person {id: 2}) SET p.age = 45", {}),
    ("MATCH (p:Person {id: 9}) "
     "CREATE (p)-[:KNOWS {since: 2026}]->(q:Person {id: 10, name: 'Yan'})",
     {}),
    ("MATCH (p:Person {id: 3}) DETACH DELETE p", {}),
)

READS = (
    ("MATCH (n:Person) RETURN n.id AS id, n.name AS name, n.age AS age",
     {}),
    ("MATCH (a:Person)-[k:KNOWS]->(b) "
     "RETURN a.id AS a, b.id AS b, k.since AS s", {}),
    ("MATCH (n:Person) WHERE n.id = $id RETURN n.name AS name", {"id": 9}),
    ("MATCH (n:Person) WHERE n.id = $id RETURN n.name AS name", {"id": 3}),
    ("MATCH (n:Person) WHERE n.id = $id RETURN n.age AS age", {"id": 2}),
)


def _payload(node_id: int):
    """A minimal cumulative delta payload: one appended node."""
    p = empty_payload()
    p["nodes"] = [[node_id, ["Person"], [["name", f"n{node_id}"]]]]
    return p


def _digests(run):
    return [result_digest(run(q, p)) for q, p in READS]


# -- commit log: append / recover --------------------------------------------

def test_empty_log_recovers_to_version_zero(tmp_path):
    rec = CommitLog(str(tmp_path)).recover()
    assert rec.version == 0
    assert rec.entries == 0
    assert rec.torn_entries == 0
    assert rec.state == empty_payload()


def test_append_recover_round_trips_the_exact_payload(tmp_path):
    log = CommitLog(str(tmp_path))
    assert log.append(1, _payload(1)) is True
    log.close()
    rec = CommitLog(str(tmp_path)).recover()
    assert rec.version == 1
    assert rec.entries == 1
    assert rec.state == _payload(1)


def test_recovery_takes_the_highest_intact_entry(tmp_path):
    log = CommitLog(str(tmp_path))
    for v in (1, 2, 3):
        log.append(v, _payload(v))
    log.close()
    rec = CommitLog(str(tmp_path)).recover()
    assert rec.version == 3
    assert rec.entries == 3
    assert rec.state == _payload(3)


def test_duplicate_version_append_skips_idempotently(tmp_path):
    reg = MetricsRegistry()
    log = CommitLog(str(tmp_path), registry=reg)
    assert log.append(1, _payload(1)) is True
    # an idempotent re-install (peer catch-up replay) must not
    # double-log or regress the version
    assert log.append(1, _payload(1)) is False
    assert reg.snapshot()["wal.skipped_appends"] == 1
    log.close()
    assert CommitLog(str(tmp_path)).recover().entries == 1


def test_segments_rotate_under_the_byte_budget(tmp_path):
    reg = MetricsRegistry()
    log = CommitLog(str(tmp_path), segment_max_bytes=1, registry=reg)
    for v in (1, 2, 3):
        log.append(v, _payload(v))
    log.close()
    assert reg.snapshot()["wal.rotations"] == 2
    rec = CommitLog(str(tmp_path)).recover()
    assert rec.segments == 3
    assert rec.version == 3


def test_checkpoint_only_store_recovers(tmp_path):
    log = CommitLog(str(tmp_path))
    log.checkpoint(5, _payload(5))
    log.close()
    rec = CommitLog(str(tmp_path)).recover()
    assert rec.version == 5
    assert rec.checkpoint_version == 5
    assert rec.entries == 0
    assert rec.state == _payload(5)


def test_checkpoint_truncates_covered_segments(tmp_path):
    log = CommitLog(str(tmp_path), segment_max_bytes=1)
    for v in (1, 2, 3):
        log.append(v, _payload(v))
    assert log.checkpoint(3, _payload(3)) == 3
    # appends keep landing after the truncation, in fresh segments
    assert log.append(4, _payload(4)) is True
    log.close()
    rec = CommitLog(str(tmp_path)).recover()
    assert rec.version == 4
    assert rec.checkpoint_version == 3
    assert rec.segments == 1


def test_replay_is_idempotent(tmp_path):
    log = CommitLog(str(tmp_path))
    for v in (1, 2):
        log.append(v, _payload(v))
    first = log.recover()
    second = log.recover()
    assert (first.version, first.state) == (second.version, second.state)
    log.close()


def test_unreadable_checkpoint_refuses_loudly(tmp_path):
    log = CommitLog(str(tmp_path))
    log.append(1, _payload(1))
    log.close()
    # older entries may have been truncated against the checkpoint, so
    # pretending a damaged one was empty would silently lose acked
    # writes — recovery must refuse instead
    with open(os.path.join(str(tmp_path), "checkpoint.json"), "w") as f:
        f.write("{not json")
    with pytest.raises(WalWriteError):
        CommitLog(str(tmp_path)).recover()


def test_compose_delta_payloads_overrides_and_unions():
    a = {"hidden_nodes": [1], "hidden_rels": [],
         "nodes": [[2, ["P"], [["k", "a"]]], [3, ["P"], []]], "rels": []}
    b = {"hidden_nodes": [3], "hidden_rels": [4],
         "nodes": [[2, ["P"], [["k", "b"]]]], "rels": []}
    out = compose_delta_payloads(a, b)
    assert out["hidden_nodes"] == [1, 3]
    assert out["hidden_rels"] == [4]
    # b's record overrides a's; a's record deleted by b drops out
    assert out["nodes"] == [[2, ["P"], [["k", "b"]]]]


# -- commit log: torn tails and fsync faults ---------------------------------

def test_torn_tail_is_dropped_whole_and_counted(tmp_path):
    reg = MetricsRegistry()
    log = CommitLog(str(tmp_path), registry=reg)
    log.append(1, _payload(1))
    with torn_wal(n_bytes=6) as budget:
        with pytest.raises(RuntimeError) as exc_info:
            log.append(2, _payload(2))
    assert budget.injected == 1
    assert getattr(exc_info.value, "caps_wal_fault", None) is True
    log.close()
    rec = CommitLog(str(tmp_path), registry=reg).recover()
    assert rec.version == 1
    assert rec.torn_entries == 1
    assert rec.state == _payload(1)
    assert reg.snapshot()["wal.torn_entries"] == 1


def test_torn_tail_truncated_so_retried_append_lands(tmp_path):
    log = CommitLog(str(tmp_path))
    log.append(1, _payload(1))
    with torn_wal(n_bytes=6):
        with pytest.raises(RuntimeError):
            log.append(2, _payload(2))
    log.close()
    healed = CommitLog(str(tmp_path))
    assert healed.recover().torn_entries == 1
    # recovery truncated the garbage PHYSICALLY: the retried append
    # must land where the last intact frame ended, or it would sit
    # unreachable behind the torn bytes and be silently lost
    assert healed.append(2, _payload(2)) is True
    healed.close()
    rec = CommitLog(str(tmp_path)).recover()
    assert rec.version == 2
    assert rec.torn_entries == 0


def test_failover_scan_never_truncates_a_peer_log(tmp_path):
    peer_dir = str(tmp_path / "wal-b0")
    log = CommitLog(peer_dir)
    log.append(1, _payload(1))
    with torn_wal(n_bytes=6):
        with pytest.raises(RuntimeError):
            log.append(2, _payload(2))
    log.close()
    seg = os.path.join(peer_dir, "wal-00000000.log")
    size_before = os.path.getsize(seg)
    best = scan_durable_dir(str(tmp_path))
    assert best is not None and best.version == 1
    # reading a peer's store must never write to it
    assert os.path.getsize(seg) == size_before


def test_fsync_failure_is_typed_transient_never_silent(tmp_path):
    log = CommitLog(str(tmp_path))
    with failing_fsync() as budget:
        with pytest.raises(WalWriteError) as exc_info:
            log.append(1, _payload(1))
    assert budget.injected == 1
    assert exc_info.value.caps_transient is True
    assert getattr(exc_info.value, "caps_wal_fault", None) is True
    # the partial frame was truncated away: the retried append lands
    assert log.append(1, _payload(1)) is True
    log.close()
    assert CommitLog(str(tmp_path)).recover().version == 1


# -- the lease ---------------------------------------------------------------

def test_lease_acquire_renew_and_conflict(tmp_path):
    reg = MetricsRegistry()
    store = LeaseStore(str(tmp_path), ttl_s=30.0, registry=reg)
    assert store.acquire("a") == 1
    assert store.holder("a") == 1
    assert store.holder("b") is None
    # a live lease blocks rivals and survives renewal at the SAME epoch
    assert store.acquire("b") is None
    assert store.renew("a") is True
    assert store.renew("b") is False
    assert store.acquire("a") == 1
    assert reg.snapshot()["wal.lease_conflicts"] >= 1


def test_expired_lease_steals_at_a_higher_epoch(tmp_path):
    store = LeaseStore(str(tmp_path), ttl_s=0.05)
    assert store.acquire("a") == 1
    time.sleep(0.12)
    # the epoch is the fence: ownership NEVER changes at the same epoch
    assert store.acquire("b") == 2
    assert store.holder("a") is None
    assert store.holder("b") == 2


def test_epoch_claim_is_a_compare_and_swap(tmp_path):
    store = LeaseStore(str(tmp_path), ttl_s=0.05)
    assert store.acquire("a") == 1
    time.sleep(0.12)
    # a rival already holds the O_EXCL claim for the next epoch: the
    # CAS loses and nobody publishes a second epoch-2 lease
    rival_claim = store._claim_path(2)
    with open(rival_claim, "w"):
        pass
    assert store.acquire("b") is None
    # a claim older than the TTL with no published lease is a crashed
    # claimant — it is broken and the next acquire goes through
    time.sleep(0.12)
    assert store.acquire("b") is None  # this call unlinks the wedge
    assert store.acquire("b") == 2


# -- lease edges (ISSUE 20 satellite): fake-clock TTL arithmetic, wedge
#    vs live renewal, and the same-epoch CAS race ----------------------------

class _LeaseClock:
    """Fake for ``clock.now`` only: lease expiry is monotonic
    arithmetic on the renewal stamp; ``clock.wall`` stays real because
    the wedged-claim sweep ages claim FILES (mtime is wall time)."""

    def __init__(self, t0: float = 1_000.0):
        self.t = t0

    def now(self) -> float:
        return self.t

    def advance(self, s: float) -> None:
        self.t += s


@pytest.fixture()
def lease_clock(monkeypatch):
    from caps_tpu.obs import clock
    lc = _LeaseClock()
    monkeypatch.setattr(clock, "now", lc.now)
    return lc


def test_renewal_stamp_governs_expiry_not_acquisition_time(tmp_path,
                                                           lease_clock):
    store = LeaseStore(str(tmp_path), ttl_s=5.0)
    rival = LeaseStore(str(tmp_path), ttl_s=5.0)
    assert store.acquire("a") == 1
    lease_clock.advance(4.0)
    assert rival.acquire("b") is None
    assert store.renew("a") is True        # the stamp moves to NOW
    lease_clock.advance(4.0)
    # 8s since acquisition but only 4s since the renewal stamp: the
    # TTL is measured from the stamp on the monotonic clock, so a
    # renewing owner can never be deposed by clock arithmetic that
    # reaches back to its original claim (skew-free by construction)
    assert rival.acquire("b") is None
    assert store.holder("a") == 1
    lease_clock.advance(1.1)               # NOW the renewal is stale
    assert store.holder("a") is None
    assert rival.acquire("b") == 2


def test_wedged_claim_waits_out_a_live_renewal(tmp_path, lease_clock):
    """A claimant that crashed between winning the O_EXCL claim and
    publishing the lease leaves a wedge — but while the OWNER's lease
    is live, the wedge is unreachable (the conflict path returns before
    the claim CAS, and renewals never sweep).  Only after the owner
    expires does the steal path break the wedge and go through."""
    store = LeaseStore(str(tmp_path), ttl_s=5.0)
    rival = LeaseStore(str(tmp_path), ttl_s=5.0)
    assert store.acquire("a") == 1
    wedge = rival._claim_path(2)
    with open(wedge, "w"):
        pass
    past = time.time() - 60.0              # older than any TTL
    os.utime(wedge, (past, past))
    assert rival.acquire("b") is None      # live lease: conflict, no CAS
    assert store.renew("a") is True
    assert os.path.exists(wedge)           # renewal swept NOTHING
    lease_clock.advance(6.0)               # the owner dies
    assert rival.acquire("b") is None      # first attempt breaks the wedge
    assert not os.path.exists(wedge)
    assert rival.acquire("b") == 2


def test_two_claimants_cas_the_same_epoch_one_wins(tmp_path, lease_clock):
    """Both claimants read the expired lease and compute next_epoch=2;
    the O_EXCL claim file is the CAS.  Interleave the loser BETWEEN the
    winner's claim and its publish — the worst-case window — and
    exactly one epoch-2 lease exists afterwards."""
    store_b = LeaseStore(str(tmp_path), ttl_s=5.0)
    store_c = LeaseStore(str(tmp_path), ttl_s=5.0)
    assert store_b.acquire("a") == 1
    lease_clock.advance(6.0)
    results = {}
    orig_write = store_b._write

    def publish_hook(record):
        if record["owner"] == "b" and "c" not in results:
            # c races in AFTER b won the O_EXCL claim for epoch 2 but
            # BEFORE b published lease.json: c sees the expired epoch-1
            # lease, computes the SAME next epoch, and loses the CAS
            results["c"] = store_c.acquire("c")
        orig_write(record)

    store_b._write = publish_hook
    results["b"] = store_b.acquire("b")
    assert results == {"b": 2, "c": None}
    lease = store_c.read()
    assert (lease["owner"], lease["epoch"]) == ("b", 2)
    # the loser retries against the now-live epoch-2 lease: conflict,
    # never a second epoch-2 publication
    assert store_c.acquire("c") is None


# -- commit integration: append-before-acknowledge ---------------------------

@pytest.fixture
def versioned():
    session = caps_tpu.local_session(backend="local")
    graph = create_graph(session, PEOPLE)
    return session, VersionedGraph(session, graph)


def test_commit_rolls_back_when_the_wal_append_fails(tmp_path, versioned):
    session, vg = versioned
    log = CommitLog(str(tmp_path))
    vg.pre_publish = lambda snap: log.append(
        snap.snapshot_version, delta_state_to_payload(snap.state))
    before = _digests(lambda q, p: session.cypher_on_graph(vg, q, p))
    with failing_fsync():
        with pytest.raises(WalWriteError):
            session.cypher_on_graph(vg, *WRITES[0])
    # never a silent ack: the graph is untouched, the version did not
    # move, and nothing leaked into the string pool
    assert vg.current().snapshot_version == 0
    assert _digests(lambda q, p: session.cypher_on_graph(vg, q, p)) \
        == before
    assert session.metrics_snapshot()["updates.rolled_back"] >= 1
    # the SAME write retried lands exactly once
    session.cypher_on_graph(vg, *WRITES[0])
    assert vg.current().snapshot_version == 1
    assert CommitLog(str(tmp_path)).recover().version == 1


def test_wal_recovery_rebuilds_the_graph_exactly(tmp_path, versioned):
    session, vg = versioned
    log = CommitLog(str(tmp_path))
    vg.pre_publish = lambda snap: log.append(
        snap.snapshot_version, delta_state_to_payload(snap.state))
    for q, p in WRITES:
        session.cypher_on_graph(vg, q, p)
    want = _digests(lambda q, p: session.cypher_on_graph(vg, q, p))
    log.close()

    # a fresh process: spec-build the base graph, replay the log
    s2 = caps_tpu.local_session(backend="local")
    vg2 = VersionedGraph(s2, create_graph(s2, PEOPLE))
    rec = CommitLog(str(tmp_path)).recover()
    assert rec.version == len(WRITES)
    vg2.install_state(delta_state_from_payload(rec.state), rec.version)
    assert _digests(lambda q, p: s2.cypher_on_graph(vg2, q, p)) == want


# -- fleet failover ----------------------------------------------------------

FLEET_CREATE = """
    CREATE (a:Person {name: 'Alice', age: 33}),
           (b:Person {name: 'Bob', age: 44})
"""
Q_NAMES = "MATCH (p:Person) RETURN p.name AS n ORDER BY n"


def _durable_spec(name, store):
    return BackendSpec(name=name, backend="local",
                       graph={"kind": "script", "create": FLEET_CREATE},
                       versioned=True, durable_dir=store,
                       wal_fsync="always", lease_ttl_s=0.4)


@pytest.fixture
def durable_fleet(tmp_path):
    store = str(tmp_path / "store")
    objs = {}
    backends = {}
    for name in ("b0", "b1", "b2"):
        b = FleetBackend(_durable_spec(name, store))
        objs[name] = b
        backends[name] = ("127.0.0.1", b.port)
    router = FleetRouter(backends, owner="b0",
                         config=RouterConfig(max_attempts=3,
                                             failover_wait_s=5.0),
                         registry=MetricsRegistry())
    yield router, objs, store
    router.close()
    for b in objs.values():
        b.shutdown(drain=False)


def test_acked_write_survives_backend_crash(durable_fleet, tmp_path):
    router, objs, store = durable_fleet
    out = router.write("CREATE (e:Person {name: 'Eve', age: 61})")
    assert out["version"] == 1
    assert out["epoch"] == 1  # first write claimed the lease
    # crash everything; a fresh owner process recovers from ITS log
    router.close()
    for b in objs.values():
        b.shutdown(drain=False)
    objs.clear()
    reborn = FleetBackend(_durable_spec("b0", store))
    try:
        assert reborn.graph.current().snapshot_version == 1
        with WireClient("127.0.0.1", reborn.port) as client:
            rows = client.call("query", query=Q_NAMES)["rows"]
        assert [r["n"] for r in rows] == ["Alice", "Bob", "Eve"]
    finally:
        reborn.shutdown(drain=False)


def test_owner_failover_elects_peer_and_keeps_acked_writes(durable_fleet):
    router, objs, _store = durable_fleet
    router.write("CREATE (e:Person {name: 'Eve', age: 61})")
    # SIGKILL-equivalent: the owner vanishes without drain
    objs["b0"].shutdown(drain=False)
    router.mark_dead("b0")
    out = router.write("CREATE (f:Person {name: 'Fay', age: 22})")
    # the peer with the longest replayed log won the epoch-fenced lease
    assert router.owner in ("b1", "b2")
    assert out["version"] == 2
    assert out["epoch"] == 2
    assert router.registry.snapshot()["router.failovers"] == 1
    # zero acked-write loss: both writes visible on the new owner
    rep = router._clients[router.owner].call("query", query=Q_NAMES)
    assert [r["n"] for r in rep["rows"]] == ["Alice", "Bob", "Eve", "Fay"]


def test_zombie_owner_is_fenced_by_epoch(durable_fleet):
    from caps_tpu.obs import clock
    router, objs, store = durable_fleet
    router.write("CREATE (e:Person {name: 'Eve', age: 61})")
    # depose b0 behind its back: the shared lease now names b1/epoch 2
    LeaseStore(store)._write({"owner": "b1", "epoch": 2,
                              "renewed_t": clock.now()})
    with WireClient("127.0.0.1", objs["b0"].port) as client:
        with pytest.raises(StaleEpoch) as exc_info:
            client.call("write", epoch=1,
                        query="CREATE (z:Person {name: 'Zed', age: 1})")
    # the fence names the true owner so the router can adopt it
    assert exc_info.value.epoch == 1
    assert exc_info.value.lease_epoch == 2
    assert exc_info.value.owner == "b1"
    # the zombie's write never executed OR logged
    assert objs["b0"].graph.current().snapshot_version == 1
    assert objs["b0"].wal.recover().version == 1


# -- sharded commits ---------------------------------------------------------

def _sharded(tmp_path=None, session=None):
    session = session or caps_tpu.local_session(backend="local")
    graph = create_graph(session, PEOPLE)
    cfg = ShardGroupConfig(name="g0", members=2, partitions_per_member=2,
                           wal_dir=None if tmp_path is None
                           else str(tmp_path), wal_fsync="always")
    return session, ShardGroup(session, graph, cfg,
                               registry=session.metrics_registry)


def _oracle_digests():
    session = caps_tpu.local_session(backend="local")
    vg = VersionedGraph(session, create_graph(session, PEOPLE))
    for q, p in WRITES:
        session.cypher_on_graph(vg, q, p)
    return _digests(lambda q, p: session.cypher_on_graph(vg, q, p))


def test_sharded_writes_digest_parity_with_unsharded(tmp_path):
    session, group = _sharded(tmp_path)
    try:
        for q, p in WRITES:
            group.execute(q, p)
        assert _digests(group.execute) == _oracle_digests()
        snap = session.metrics_registry.snapshot()
        assert snap["shard.requests.write"] == len(WRITES)
        assert snap["shard.commits"] == len(WRITES)
        assert snap["wal.appends"] == len(WRITES)
        # the point lookups above routed to owning members, overlays on
        assert snap["shard.requests.single"] >= 3
        assert group.summary()["version"] == len(WRITES)
        assert group.summary()["durable"] is True
    finally:
        group.close()


def test_sharded_group_recovers_from_the_group_wal(tmp_path):
    _session, group = _sharded(tmp_path)
    try:
        for q, p in WRITES:
            group.execute(q, p)
    finally:
        group.close()
    # a fresh process: new session, spec-built graph, same group WAL
    _s2, reborn = _sharded(tmp_path)
    try:
        assert reborn.summary()["version"] == len(WRITES)
        assert _digests(reborn.execute) == _oracle_digests()
    finally:
        reborn.close()


def test_sharded_commit_atomic_on_wal_failure(tmp_path):
    session, group = _sharded(tmp_path)
    try:
        group.execute(*WRITES[0])
        before = _digests(group.execute)
        with failing_fsync():
            with pytest.raises(WalWriteError):
                group.execute("CREATE (x:Person {id: 11, name: 'X'})")
        # the group WAL append is the commit point: its failure rolled
        # EVERY member back — no shard partially applied, version held
        assert group.summary()["version"] == 1
        assert _digests(group.execute) == before
        snap = session.metrics_registry.snapshot()
        assert snap["shard.commit_rollbacks"] == 1
        # the SAME write retried commits exactly once
        group.execute("CREATE (x:Person {id: 11, name: 'X'})")
        assert group.summary()["version"] == 2
        rows = group.execute(
            "MATCH (n:Person {id: 11}) RETURN count(*) AS c").to_maps()
        assert rows == [{"c": 1}]
    finally:
        group.close()


def test_sharded_commit_atomic_on_member_prepare_failure(monkeypatch):
    session, group = _sharded()
    orig = ShardGroup.__dict__["_overlay_graph"].__func__
    state = {"armed": False, "injected": 0}

    def poisoned(sess, base, st, version):
        if state["armed"]:
            state["armed"] = False
            state["injected"] += 1
            raise RuntimeError("injected member prepare fault")
        return orig(sess, base, st, version)

    monkeypatch.setattr(ShardGroup, "_overlay_graph",
                        staticmethod(poisoned))
    try:
        group.execute(*WRITES[0])
        before = _digests(group.execute)
        state["armed"] = True
        with pytest.raises(Exception):
            group.execute(*WRITES[1])
        assert state["injected"] == 1
        # one member's prepare died mid-round: every member's pool mark
        # rolled back, no shard shows a half-applied overlay
        assert group.summary()["version"] == 1
        assert _digests(group.execute) == before
        assert session.metrics_registry.snapshot()[
            "shard.commit_rollbacks"] == 1
        group.execute(*WRITES[1])  # the retry lands
        assert group.summary()["version"] == 2
    finally:
        group.close()


def test_routed_single_shard_reads_see_writes():
    session, group = _sharded()
    try:
        for q, p in WRITES:
            group.execute(q, p)
        snap0 = session.metrics_registry.snapshot()
        routed0 = snap0.get("shard.requests.single", 0)
        # a created delta node, a SET node, and a deleted node — all
        # answered by the owning member's overlay, not the cross session
        q = "MATCH (n:Person) WHERE n.id = $id RETURN n.name AS name"
        assert group.execute(q, {"id": 9}).to_maps() == [{"name": "Zed"}]
        assert group.execute(q, {"id": 3}).to_maps() == []
        q_age = "MATCH (n:Person) WHERE n.id = $id RETURN n.age AS age"
        assert group.execute(q_age, {"id": 2}).to_maps() == [{"age": 45}]
        snap1 = session.metrics_registry.snapshot()
        assert snap1["shard.requests.single"] == routed0 + 3
    finally:
        group.close()

"""The warm-path subsystem (ISSUE 11): shape-bucket lattice, ragged
bucket batching, deterministic compile-cliff faults, the persistent
plan store's honesty contract, and the AOT warmup round trip.

Acceptance contract under test: a store-warmed fresh session serves its
first client queries with ZERO compile charge (plan-cache hit + fused
generic replay, proven by the compile ledger); a corrupt / truncated /
version-mismatched / unwritable store degrades to cold compile with a
structured ``planstore.rejected`` event while the server keeps serving;
ragged batching coalesces DISTINCT query texts sharing a shape bucket
with exact per-member results and per-member failure isolation.
"""
from __future__ import annotations

import json
import os
import threading

import pytest

import caps_tpu
from caps_tpu.obs import clock
from caps_tpu.okapi.config import EngineConfig
from caps_tpu.relational.plan_store import (PlanStore, collect_warm_state,
                                            deserialize_stream,
                                            store_fingerprint)
from caps_tpu.relational.shapes import (ShapeBucketLattice,
                                        param_shape_signature,
                                        signature_text)
from caps_tpu.serve import QueryServer, ServerConfig, WarmupConfig
from caps_tpu.serve.batcher import request_keys
from caps_tpu.testing.factory import create_graph
from caps_tpu.testing.faults import failing_operator, slow_compile

SOCIAL = """
    CREATE (a:Person {name: 'Alice', age: 33}),
           (b:Person {name: 'Bob', age: 44}),
           (c:Person {name: 'Carol', age: 27}),
           (d:Person {name: 'Dana', age: 51}),
           (a)-[:KNOWS {since: 2011}]->(b),
           (b)-[:KNOWS {since: 2015}]->(c),
           (a)-[:KNOWS {since: 2019}]->(c),
           (c)-[:KNOWS {since: 2021}]->(d)
"""

Q_AGE = ("MATCH (p:Person) WHERE p.age > $min "
         "RETURN p.name AS n ORDER BY n")
Q_KNOWS = ("MATCH (a:Person)-[:KNOWS]->(b) WHERE a.age > $min "
           "RETURN a.name AS a, b.name AS b")


def _session(backend="local", **cfg):
    return caps_tpu.local_session(backend=backend,
                                  config=EngineConfig(**cfg) if cfg else None)


def _graph(session):
    return create_graph(session, SOCIAL)


# -- shape-bucket lattice (relational/shapes.py) ----------------------------

def test_lattice_default_rounding_matches_config():
    cfg = EngineConfig()
    lat = ShapeBucketLattice(cfg.bucket_sizes)
    for n in (0, 1, 255, 256, 257, 5000, 1048576, 3_000_000):
        assert lat.bucket(n) == max(1, cfg.bucket_for(n))


def test_lattice_seeding_refines_and_is_bounded():
    lat = ShapeBucketLattice((256, 4096), max_buckets=4)
    assert lat.bucket(300) == 4096
    assert lat.seed([300]) == 1          # pow2 ceil of 300 = 512
    assert lat.bucket(300) == 512
    assert lat.seed([300]) == 0          # idempotent
    assert lat.seed([60, 1500]) == 1     # bounded: only ONE more fits
    assert len(lat.boundaries()) == 4
    assert lat.signature(300) == "b512"


def test_lattice_seed_from_op_stats():
    s = _session()
    g = _graph(s)
    g.cypher(Q_AGE, {"min": 20})
    # observed max rows are tiny; their pow2 ceilings become boundaries
    added = s.seed_shape_buckets()
    assert added >= 1
    assert min(s.shape_lattice.boundaries()) < 256


def test_param_shape_signature_value_independent():
    a = param_shape_signature({"min": 20, "name": "Alice"})
    b = param_shape_signature({"min": 99, "name": "Bob"})
    assert a == b
    # coarse type changes the shape
    assert param_shape_signature({"min": 1.5}) != \
        param_shape_signature({"min": 1})
    # container LENGTH buckets, not values
    lat = ShapeBucketLattice((4, 16))
    assert param_shape_signature({"xs": [1, 2]}, lat) == \
        param_shape_signature({"xs": [7, 8]}, lat)
    assert param_shape_signature({"xs": [1] * 10}, lat) != \
        param_shape_signature({"xs": [1, 2]}, lat)
    # map KEY SETS are part of the shape (plans specialize on them)
    assert param_shape_signature({"m": {"k": 1}}) != \
        param_shape_signature({"m": {"j": 1}})
    assert signature_text(a)  # printable label for the compile ledger


# -- ragged batch keys (serve/batcher.py) -----------------------------------

def test_request_keys_plan_vs_bucket():
    s = _session()
    g = _graph(s)
    mode, plan_a, key_a = request_keys(g, Q_AGE, {"min": 20}, ragged=True)
    _m, plan_b, key_b = request_keys(g, Q_KNOWS, {"min": 30}, ragged=True)
    assert mode is None
    assert plan_a != plan_b              # distinct plan families ...
    assert key_a == key_b                # ... sharing one bucket key
    # un-ragged: batch key IS the plan key (the pre-PR behavior)
    _m, plan_a2, key_a2 = request_keys(g, Q_AGE, {"min": 20})
    assert plan_a2 == key_a2 == plan_a
    # a diverging coarse type diverges the bucket too
    _m, _p, key_f = request_keys(g, Q_AGE, {"min": 20.5}, ragged=True)
    assert key_f != key_a
    # writes / EXPLAIN never batch, ragged or not
    assert request_keys(g, "EXPLAIN " + Q_AGE, {}, ragged=True)[2] is None


def test_ragged_batch_coalesces_distinct_texts_exactly():
    s = _session()
    g = _graph(s)
    texts = [Q_AGE, Q_KNOWS,
             "MATCH (p:Person) WHERE p.age > $min RETURN count(*) AS c"]
    for t in texts:
        g.cypher(t, {"min": 20})  # warm each family's plan
    server = QueryServer(s, graph=g, start=False, config=ServerConfig(
        workers=1, max_batch=16, ragged_batching=True))
    hs = [server.submit(texts[i % 3], {"min": 20 + 10 * (i % 2)})
          for i in range(9)]
    server.start()
    server.shutdown()
    sizes = [h.info["batch_size"] for h in hs]
    assert max(sizes) > 1, sizes  # distinct texts coalesced
    for i, h in enumerate(hs):    # every member's result stays exact
        want = g.cypher(texts[i % 3],
                        {"min": 20 + 10 * (i % 2)}).records.to_maps()
        assert h.rows() == want
    assert server.stats()["batching"]["mean_occupancy"] > 1


def test_ragged_batch_member_isolation_and_breaker_scope():
    """A poisoned family inside a ragged batch fails only ITS members;
    siblings from other families in the same shared batch succeed, and
    the breaker keys on the exact plan family (Request.plan_key), not
    the bucket."""
    s = _session()
    g = _graph(s)
    g.cypher(Q_AGE, {"min": 20})
    g.cypher(Q_KNOWS, {"min": 20})
    server = QueryServer(s, graph=g, start=False, config=ServerConfig(
        workers=1, max_batch=16, ragged_batching=True,
        breaker_threshold=2, breaker_cooldown_s=60.0))
    with failing_operator("OrderBy", exc=RuntimeError("poison"),
                          n_times=None):
        bad = [server.submit(Q_AGE, {"min": m}) for m in (20, 30, 40)]
        good = [server.submit(Q_KNOWS, {"min": m}) for m in (20, 30)]
        server.start()
        server.shutdown()
    for h in good:
        assert h.rows() == g.cypher(
            Q_KNOWS, {"min": h._request.params["min"]}).records.to_maps()
    failures = [h.exception() for h in bad]
    assert all(f is not None for f in failures), failures
    # the poisoned family tripped ITS breaker; the healthy family's is
    # closed (scoped per plan family even though they share the bucket)
    assert server.breaker.open_count() >= 1


# -- slow_compile (testing/faults.py) ---------------------------------------

class FakeClock:
    def __init__(self, t0: float = 1_000.0):
        self._t = t0
        self._lock = threading.Lock()
        self.sleeps: list = []

    def now(self) -> float:
        with self._lock:
            return self._t

    def sleep(self, s: float) -> None:
        with self._lock:
            self._t += s
            self.sleeps.append(s)

    def wait(self, event, timeout: float) -> bool:
        if event.is_set():
            return True
        self.sleep(timeout)
        return event.is_set()


@pytest.fixture()
def fake_clock(monkeypatch):
    fc = FakeClock()
    monkeypatch.setattr(clock, "now", fc.now)
    monkeypatch.setattr(clock, "sleep", fc.sleep)
    monkeypatch.setattr(clock, "wait", fc.wait)
    return fc


def test_slow_compile_deterministic_on_fake_clock(fake_clock):
    from caps_tpu.obs.metrics import global_registry
    s = _session()
    g = _graph(s)
    before = global_registry().snapshot().get("faults.injected.slow_compile",
                                              0)
    with slow_compile(35.0, n_times=1) as budget:
        r1 = s.cypher_on_graph(g, Q_AGE, {"min": 20})
        # budget spent: the next cold family compiles at normal speed
        r2 = s.cypher_on_graph(g, Q_KNOWS, {"min": 20})
    assert budget.injected == 1
    assert r1.metrics["compile_s_charged"] >= 35.0
    assert r2.metrics["compile_s_charged"] < 35.0
    assert 35.0 in fake_clock.sleeps  # wall time advanced on the fake clock
    after = global_registry().snapshot()["faults.injected.slow_compile"]
    assert after == before + 1
    # ledger agrees with the inflated charge
    fam = [f for f in s.compile_ledger.families()][0]
    assert s.compile_ledger.seconds_for(fam) >= 35.0


def test_slow_compile_kind_filter(fake_clock):
    s = _session()
    g = _graph(s)
    with slow_compile(5.0, kinds=("fused_record",)):
        r = s.cypher_on_graph(g, Q_AGE, {"min": 20})
    # local backend never crosses a fused_record boundary: no delay
    assert r.metrics["compile_s_charged"] < 5.0


# -- plan store honesty (relational/plan_store.py) --------------------------

def _served_through(store_path, tmp_path):
    """A server configured against ``store_path`` must keep serving and
    report the rejection; returns (server, session)."""
    s = _session(backend="tpu")
    g = _graph(s)
    server = QueryServer(s, graph=g, config=ServerConfig(
        warmup=WarmupConfig(store_path=str(store_path), background=False,
                            save_on_shutdown=False)))
    rows = server.run(Q_AGE, {"min": 30}).to_maps()
    assert [r["n"] for r in rows] == ["Alice", "Bob", "Dana"]
    return server, s


@pytest.mark.parametrize("damage", ["corrupt", "truncated", "mismatch",
                                    "malformed"])
def test_bad_store_degrades_to_cold_with_event(tmp_path, damage):
    path = tmp_path / "plans.json"
    if damage == "corrupt":
        path.write_text("{not json at all", encoding="utf-8")
    elif damage == "truncated":
        s0 = _session(backend="tpu")
        g0 = _graph(s0)
        s0.cypher_on_graph(g0, Q_AGE, {"min": 20})
        PlanStore(str(path)).save(collect_warm_state(s0, graph=g0))
        full = path.read_text(encoding="utf-8")
        path.write_text(full[:len(full) // 2], encoding="utf-8")
    elif damage == "mismatch":
        payload = {"fingerprint": dict(store_fingerprint(),
                                       package="some-other-version"),
                   "lattice": [], "families": []}
        path.write_text(json.dumps(payload), encoding="utf-8")
    else:  # malformed families section
        payload = {"fingerprint": store_fingerprint(), "lattice": [],
                   "families": [{"query": 42}]}
        path.write_text(json.dumps(payload), encoding="utf-8")
    server, s = _served_through(path, tmp_path)
    events = server.events("planstore.rejected")
    assert len(events) == 1 and events[0]["path"] == str(path)
    assert s.metrics_registry.snapshot()["planstore.rejected"] == 1
    report = server.warmer.report()
    assert report["state"] == "done"
    assert report["store"]["loaded"] is False
    assert report["store"]["rejected"]
    server.shutdown()


def test_unwritable_store_rejects_save_and_server_survives(tmp_path):
    blocker = tmp_path / "blocker"
    blocker.write_text("a file where a directory must go",
                       encoding="utf-8")
    path = blocker / "sub" / "plans.json"  # parent dir can never exist
    s = _session(backend="tpu")
    g = _graph(s)
    server = QueryServer(s, graph=g, config=ServerConfig(
        warmup=WarmupConfig(store_path=str(path), background=False)))
    assert server.run(Q_AGE, {"min": 30}).to_maps()
    server.shutdown()  # save_on_shutdown fires against the bad path
    assert s.metrics_registry.snapshot()["planstore.rejected"] >= 1
    store = PlanStore(str(path))
    assert store.save({"fingerprint": store_fingerprint(),
                       "families": []}) is False
    assert store.last_rejection.startswith("unwritable")


def test_missing_store_is_not_an_error(tmp_path):
    path = tmp_path / "never-written.json"
    server, s = _served_through(path, tmp_path)
    assert server.events("planstore.rejected") == []
    assert s.metrics_registry.snapshot().get("planstore.rejected", 0) == 0
    server.shutdown()


def test_stream_serialization_round_trip():
    raw = [["rows", 7], ["size", 3, "cap"], ["size", 1, "exact"]]
    assert deserialize_stream(raw) == [("rows", 7), ("size", 3, "cap"),
                                       ("size", 1, "exact")]
    assert deserialize_stream([["rows", "x"]]) is None
    assert deserialize_stream([["__obj__", {}]]) is None
    assert deserialize_stream("nope") is None


# -- the warm-path round trip -----------------------------------------------

def test_store_warmup_round_trip_zero_compile_charge(tmp_path):
    """Serve traffic, persist, restart into a 'fresh process' (new
    session, same data): warmup from the store covers every hot family
    through the REAL compile boundaries, and the first client query of
    each family — including new bindings within the same shape bucket —
    charges zero compile seconds."""
    path = tmp_path / "plans.json"
    s1 = _session(backend="tpu")
    g1 = _graph(s1)
    server1 = QueryServer(s1, graph=g1, config=ServerConfig(
        warmup=WarmupConfig(store_path=str(path), background=False)))
    for params in ({"min": 30}, {"min": 35}):
        server1.run(Q_AGE, params)
        server1.run(Q_KNOWS, params)
    server1.shutdown()  # save_on_shutdown persists the warm state
    assert path.exists()

    s2 = _session(backend="tpu")
    g2 = _graph(s2)
    server2 = QueryServer(s2, graph=g2, config=ServerConfig(
        warmup=WarmupConfig(store_path=str(path), background=False)))
    report = server2.warmer.report()
    assert report["state"] == "done"
    assert report["completed"] == report["families_total"] == 2
    assert report["failures"] == []
    assert report["store"]["loaded"] is True
    assert report["converged"] is True
    # the ledger proves coverage: no hot family is cold on this process
    assert server2.warmup_report()["cold_families"] == []
    # first client queries — warmed bindings AND fresh within-bucket
    # bindings — all charge zero compile seconds
    for query, params in [(Q_AGE, {"min": 30}), (Q_AGE, {"min": 25}),
                          (Q_KNOWS, {"min": 50})]:
        h = server2.submit(query, params)
        assert h.rows() == g2.cypher(query, params).records.to_maps()
        assert h.info["ledger"]["compile_s"] == 0.0, (params,
                                                      h.info["ledger"])
    assert server2.stats()["warmup"]["state"] == "done"
    assert server2.health_report()["warmup"]["state"] == "done"
    server2.shutdown()


def test_explicit_family_list_warmup():
    s = _session(backend="tpu")
    g = _graph(s)
    paramless = "MATCH (p:Person) RETURN count(*) AS c"
    server = QueryServer(s, graph=g, config=ServerConfig(
        warmup=WarmupConfig(families=((Q_AGE, {"min": 20}), paramless),
                            background=False)))
    report = server.warmer.report()
    assert report["completed"] == 2
    assert server.warmup_report()["cold_families"] == []
    h = server.submit(Q_AGE, {"min": 30})
    assert h.rows()
    assert h.info["ledger"]["compile_s"] == 0.0
    server.shutdown()


def test_warmup_family_failure_is_contained():
    s = _session(backend="tpu")
    g = _graph(s)
    server = QueryServer(s, graph=g, config=ServerConfig(
        warmup=WarmupConfig(families=(("THIS IS NOT CYPHER", {}),
                                      (Q_AGE, {"min": 20})),
                            background=False)))
    report = server.warmer.report()
    assert report["state"] == "done"
    assert report["completed"] == 1
    assert len(report["failures"]) == 1
    assert server.events("warmup.family_failed")
    assert server.run(Q_AGE, {"min": 30}).to_maps()  # still serving
    server.shutdown()


def test_background_warmup_reports_progress():
    s = _session(backend="tpu")
    g = _graph(s)
    server = QueryServer(s, graph=g, config=ServerConfig(
        warmup=WarmupConfig(families=((Q_AGE, {"min": 20}),),
                            background=True)))
    assert server.warmer.wait(timeout=120)
    assert server.warmer.report()["state"] == "done"
    assert [e["event"] for e in server.events()].count("warmup.done") == 1
    server.shutdown()


def test_warmup_resolves_versioned_graph_for_replicas():
    """Regression: warmup must execute against the pinned snapshot, not
    the writable VersionedGraph handle — replicas cannot replicate the
    handle, and a multi-device warmup would otherwise fail every
    family."""
    s = _session(backend="tpu")
    vg = s.create_versioned_graph()
    s.cypher_on_graph(vg, "CREATE (:Person {name: 'Ada', age: 36})")
    server = QueryServer(s, graph=vg, config=ServerConfig(
        devices=2,
        warmup=WarmupConfig(families=((Q_AGE, {"min": 20}),),
                            background=False)))
    report = server.warmer.report()
    assert report["state"] == "done"
    assert report["failures"] == [], report["failures"]
    assert report["completed"] == 1
    assert server.run(Q_AGE, {"min": 20}).to_maps() == [{"n": "Ada"}]
    server.shutdown()


def test_warmup_converges_in_one_pass_without_fused_streams():
    """Regression: a target that can never hold a param-generic fused
    stream (use_fused off) is ABSENT, not stale — warmup must not burn
    every convergence pass and report a false non-convergence."""
    s = _session(backend="tpu", use_fused=False)
    g = _graph(s)
    server = QueryServer(s, graph=g, config=ServerConfig(
        warmup=WarmupConfig(families=((Q_AGE, {"min": 20}),),
                            background=False)))
    report = server.warmer.report()
    assert report["state"] == "done" and report["completed"] == 1
    assert report["converged"] is True
    assert report["passes"] == 1, report
    server.shutdown()


def test_ragged_bucket_key_uses_session_lattice():
    """Regression: container params bucket through the SESSION lattice
    (the one padding and compile labels use), not the process default."""
    s = _session(backend="tpu")
    g = _graph(s)
    s.shape_lattice.seed([300])  # 512 boundary only the session knows
    server = QueryServer(s, graph=g, start=False, config=ServerConfig(
        ragged_batching=True))
    q = "MATCH (p:Person) WHERE p.age IN $xs RETURN p.name AS n"
    h_small = server.submit(q, {"xs": list(range(300))})
    h_big = server.submit(q, {"xs": list(range(600))})
    # 300 -> 512, 600 -> 1024 on the seeded session lattice: different
    # buckets, so these must NOT share a ragged batch key (the default
    # lattice would have merged both into 1024)
    assert h_small._request.batch_key != h_big._request.batch_key
    server.start()
    server.shutdown()


def test_fused_stream_export_is_pool_current_only():
    s = _session(backend="tpu")
    g = _graph(s)
    s.cypher_on_graph(g, Q_AGE, {"min": 20})
    exported = s.fused.export_streams(g)
    assert Q_AGE in exported
    # a violation-disabled stream is known-divergent: never exported
    s.fused._generic[(g._fused_epoch, Q_AGE)][2] = 3
    assert Q_AGE not in s.fused.export_streams(g)
    s.fused._generic[(g._fused_epoch, Q_AGE)][2] = 0
    assert Q_AGE in s.fused.export_streams(g)
    # simulate pool growth: the stale stream must drop out of the export
    s.backend.pool.encode("a-brand-new-string")
    assert Q_AGE not in s.fused.export_streams(g)


def test_sibling_server_shutdown_keeps_memory_accounting():
    """Regression: a short-lived sibling server sharing the graph must
    not drop the live server's memory-ledger slot on shutdown."""
    s = _session(backend="tpu")
    g = _graph(s)
    main = QueryServer(s, graph=g)
    sibling = QueryServer(s, graph=g)
    sibling.shutdown()
    mem = main.stats()["memory"]
    assert mem["graphs"].get("default", {}).get("bytes", 0) > 0
    main.shutdown()
    assert s.memory_ledger.report()["graphs"] == {}  # all owners gone


def test_finalize_cancels_pending_warmup(tmp_path):
    """Regression: the warmer's cooperative cancel bounds a run at the
    next family boundary (no family executes past it), and a cancelled
    run never emits into a possibly-closed event-log sink."""
    path = tmp_path / "plans.json"
    s = _session(backend="tpu")
    g = _graph(s)
    server = QueryServer(s, graph=g, start=False, config=ServerConfig(
        warmup=WarmupConfig(store_path=str(path), background=False,
                            families=((Q_AGE, {"min": 20}),))))
    server.warmer._stop.set()  # cancel BEFORE the run starts
    server.start()
    report = server.warmer.report()
    assert report["truncated"] is True and report["completed"] == 0
    assert server.events("warmup.done") == []  # no late sink write
    server.shutdown()


def test_seed_generic_never_clobbers_live_streams():
    s = _session(backend="tpu")
    g = _graph(s)
    s.cypher_on_graph(g, Q_AGE, {"min": 20})
    assert s.fused.seed_generic(g, Q_AGE, 99, [("rows", 1)]) is False
    # a different query seeds fine, and a pool-stale seed simply never
    # replays (the gate) — execution degrades to an honest record
    assert s.fused.seed_generic(g, Q_KNOWS, 99, [("rows", 1)]) is True
    r = s.cypher_on_graph(g, Q_KNOWS, {"min": 20})
    assert r.metrics["compile_s_charged"] > 0.0  # recorded, not misled

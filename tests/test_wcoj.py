"""Worst-case-optimal multiway joins for cyclic patterns (ops/wcoj.py +
relational/wcoj.py, ROADMAP item 4): kernel-level sorted-adjacency
intersection, MultiwayJoinOp enumeration parity against the local oracle
AND the forced binary cascade, snapshot delta-overlay parity, the
degraded fallback ladder under injected WCOJ faults, cost-model
selection rendered in EXPLAIN, and fused-replay compile accounting.

Correctness contract throughout: the WCOJ path is a physical-plan
choice — it must NEVER change results.  Every behavioural test asserts
digest parity against a WCOJ-blind execution of the same query.
"""
from __future__ import annotations

import numpy as np
import pytest

from caps_tpu.backends.local.session import LocalCypherSession
from caps_tpu.backends.tpu.session import TPUCypherSession
from caps_tpu.okapi.config import EngineConfig
from caps_tpu.relational.session import result_digest
from caps_tpu.testing import faults
from tests.util import make_graph


def _random_graph(session, n=40, e=200, seed=7, self_loops=True,
                  parallel=True, rel2=True):
    rng = np.random.RandomState(seed)
    nodes = {("P",): [{"_id": i, "name": f"n{i % 11}"} for i in range(n)]}
    edges = [(int(rng.randint(n)), int(rng.randint(n)), {})
             for _ in range(e)]
    if not self_loops:
        edges = [(a, b, p) for a, b, p in edges if a != b]
    if parallel:
        edges += edges[:12]
    rels = {"K": edges}
    if rel2:
        rels["L"] = edges[::3]
    return make_graph(session, nodes, rels)


def _ops(result):
    return [m["op"] for m in result.metrics["operators"]]


def _wcoj_strategy(result):
    return [m.get("strategy") for m in result.metrics["operators"]
            if m["op"] == "MultiwayJoin"]


TRIANGLE_ENUM = ("MATCH (a:P)-[r1:K]->(b)-[r2:K]->(c), (a)-[r3:K]->(c) "
                 "RETURN id(a) AS x, id(b) AS y, id(c) AS z")

CYCLIC_QUERIES = [
    TRIANGLE_ENUM,
    # closing edge written in the reverse orientation
    "MATCH (a:P)-[r1:K]->(b)-[r2:K]->(c), (c)-[r3:K]->(a) "
    "RETURN id(a) AS x, id(b) AS y, id(c) AS z",
    # closing edge as an incoming mention on a
    "MATCH (a:P)-[r1:K]->(b)-[r2:K]->(c), (a)<-[r3:K]-(c) "
    "RETURN id(a) AS x, id(b) AS y, id(c) AS z",
    # mixed rel types + mixed chain directions
    "MATCH (a:P)-[r1:K]->(b)<-[r2:L]-(c), (a)-[r3:K]->(c) "
    "RETURN id(b) AS x, id(c) AS y",
    # diamond: two 2-hop paths meeting (one closing edge)
    "MATCH (a:P)-[r1:K]->(b)-[r2:K]->(d), (a)-[r3:K]->(c)-[r4:K]->(d) "
    "RETURN id(a) AS w, id(b) AS x, id(c) AS y, id(d) AS z",
    # 4-cycle
    "MATCH (a:P)-[r1:K]->(b)-[r2:K]->(c)-[r3:K]->(d), (d)-[r4:K]->(a) "
    "RETURN id(a) AS w, id(b) AS x, id(c) AS y, id(d) AS z",
    # predicates on multiple pattern vars
    "MATCH (a:P)-[r1:K]->(b)-[r2:K]->(c), (a)-[r3:K]->(c) "
    "WHERE a.name = 'n3' AND c.name = 'n5' RETURN id(b) AS x, id(c) AS y",
    # full entity materialization through the gather path
    "MATCH (a:P)-[r1:K]->(b)-[r2:K]->(c), (a)-[r3:L]->(c) RETURN a, r3, c",
    # cyclic count WITHOUT the count-pushdown triangle shape (diamond):
    # the aggregate rides the MultiwayJoin output
    "MATCH (a:P)-[r1:K]->(b)-[r2:K]->(d), (a)-[r3:K]->(c)-[r4:K]->(d) "
    "RETURN count(*) AS c",
]


# -- kernel layer ------------------------------------------------------------


def _np_sorted(frm, to, ok, n):
    import jax.numpy as jnp
    from caps_tpu.backends.tpu import kernels as K
    from caps_tpu.ops import wcoj as W
    keys = W.edge_keys(jnp.asarray(frm), jnp.asarray(to),
                       jnp.asarray(ok), jnp.int64(n))
    perm = K.sort_perm([keys], keys.shape[0])
    return keys[perm], perm


def test_probe_adj_counts_and_order():
    """Adjacency counts/offsets vs a numpy oracle, including duplicate
    edges, skew (one hub), and masked rows; neighbours within a segment
    come out sorted — the leapfrog ordering guarantee."""
    n = 8
    frm = np.array([0, 0, 0, 5, 5, 1, 2, 0], np.int64)
    to = np.array([3, 1, 3, 7, 0, 6, 2, 4], np.int64)
    ok = np.array([1, 1, 1, 1, 1, 1, 1, 0], bool)  # last edge dead
    ks, perm = _np_sorted(frm, to, ok, n)
    import jax.numpy as jnp
    from caps_tpu.ops import wcoj as W
    u = jnp.asarray(np.arange(n, dtype=np.int64))
    counts, lo = W.probe_adj(ks, u, jnp.ones(n, bool), jnp.int64(n))
    want = [np.sum((frm == i) & ok) for i in range(n)]
    assert list(np.asarray(counts)) == want
    # neighbours of 0 in sorted order: 1, 3, 3 (duplicate edge kept)
    seg = np.asarray(ks)[int(lo[0]):int(lo[0]) + int(counts[0])] % n
    assert list(seg) == [1, 3, 3]


def test_probe_pair_multiplicity_and_empty():
    import jax.numpy as jnp
    from caps_tpu.ops import wcoj as W
    n = 4
    frm = np.array([1, 1, 1, 2], np.int64)
    to = np.array([2, 2, 3, 0], np.int64)
    ks, _ = _np_sorted(frm, to, np.ones(4, bool), n)
    u = jnp.asarray(np.array([1, 1, 2, 3], np.int64))
    v = jnp.asarray(np.array([2, 3, 0, 3], np.int64))
    counts, _lo = W.probe_pair(ks, u, v, jnp.ones(4, bool), jnp.int64(n))
    assert list(np.asarray(counts)) == [2, 1, 1, 0]
    # fully-masked edge table: every probe misses
    ks0, _ = _np_sorted(frm, to, np.zeros(4, bool), n)
    c0, _ = W.probe_pair(ks0, u, v, jnp.ones(4, bool), jnp.int64(n))
    assert list(np.asarray(c0)) == [0, 0, 0, 0]


@pytest.mark.parametrize("out_cap", [256, 512, 300],
                         ids=["tile256", "tile512", "odd-cap"])
def test_extend_enumerates_each_edge(out_cap):
    """The extend step yields one output slot per (frontier row,
    incident edge) — duplicates included — with exact prefix validity
    at tileable AND non-tileable (jnp-twin) capacities."""
    import jax.numpy as jnp
    from caps_tpu.ops import wcoj as W
    n = 6
    frm = np.array([0, 0, 2, 2, 2, 4], np.int64)
    to = np.array([1, 1, 3, 5, 3, 0], np.int64)
    ks, perm = _np_sorted(frm, to, np.ones(6, bool), n)
    u = jnp.asarray(np.array([0, 2, 3], np.int64))
    valid = jnp.asarray(np.array([1, 1, 1], bool))
    l_idx, cand, erow, ok = W.extend(ks, perm, u, valid, n, out_cap)
    got = sorted((int(l), int(c)) for l, c, o in
                 zip(np.asarray(l_idx), np.asarray(cand), np.asarray(ok))
                 if o)
    assert got == [(0, 1), (0, 1), (1, 3), (1, 3), (1, 5)]
    # edge rows are genuine scan rows of the probed edges
    rows = sorted(int(r) for r, o in zip(np.asarray(erow), np.asarray(ok))
                  if o)
    assert rows == [0, 1, 2, 3, 4]
    assert int(np.asarray(ok).sum()) == 5  # exact live prefix
    assert list(np.asarray(ok)[:5]) == [True] * 5


def test_close_expands_parallel_edges():
    import jax.numpy as jnp
    from caps_tpu.ops import wcoj as W
    n = 4
    frm = np.array([1, 1, 3], np.int64)
    to = np.array([2, 2, 0], np.int64)
    ks, perm = _np_sorted(frm, to, np.ones(3, bool), n)
    u = jnp.asarray(np.array([1, 3, 0], np.int64))
    v = jnp.asarray(np.array([2, 0, 1], np.int64))
    l_idx, erow, ok = W.close(ks, perm, u, v,
                              jnp.ones(3, bool), n, 256)
    got = [(int(l), int(r)) for l, r, o in
           zip(np.asarray(l_idx), np.asarray(erow), np.asarray(ok)) if o]
    # row 0 closes twice (parallel edges 0 and 1), row 1 once, row 2 never
    assert sorted(got) == [(0, 0), (0, 1), (1, 2)]


# -- enumeration parity ------------------------------------------------------


@pytest.mark.parametrize("query", CYCLIC_QUERIES)
def test_enumeration_matches_oracle_and_cascade(query):
    """Digest-exact three ways: WCOJ == local oracle == forced cascade
    (use_wcoj=False), on a graph with self-loops, parallel edges, and a
    second relationship type."""
    oracle = _random_graph(LocalCypherSession())
    want = result_digest(oracle.cypher(query))
    s = TPUCypherSession()
    g = _random_graph(s)
    res = g.cypher(query)
    assert "MultiwayJoin" in _ops(res), res.plans["relational"]
    assert _wcoj_strategy(res) == ["wcoj"]
    assert result_digest(res) == want
    s2 = TPUCypherSession(config=EngineConfig(use_wcoj=False))
    g2 = _random_graph(s2)
    res2 = g2.cypher(query)
    assert "MultiwayJoin" not in _ops(res2)
    assert result_digest(res2) == want


def test_param_rebinding_through_plan_cache():
    """Cached-plan re-execution with fresh bindings: the same planned
    MultiwayJoinOp serves every $seed value, parity per binding."""
    q = ("MATCH (a:P)-[r1:K]->(b)-[r2:K]->(c), (a)-[r3:K]->(c) "
         "WHERE a.name = $seed RETURN id(b) AS x, id(c) AS y")
    oracle = _random_graph(LocalCypherSession())
    s = TPUCypherSession()
    g = _random_graph(s)
    for seed in ("n1", "n4", "n1", "n9"):
        res = g.cypher(q, {"seed": seed})
        assert result_digest(res) == result_digest(
            oracle.cypher(q, {"seed": seed})), seed
    assert s.plan_cache.stats()["hits"] >= 2


def test_uniqueness_pairs_absorbed_same_type():
    """A triangle over ONE rel type carries isomorphism filters between
    all three rels; whether pushed into the segment (absorbed as uniq
    pairs) or left above (plain FilterOps), results match the oracle."""
    q = ("MATCH (a:P)-[r1:K]->(b)-[r2:K]->(c), (a)-[r3:K]->(c) "
         "RETURN id(r1) AS x, id(r2) AS y, id(r3) AS z")
    oracle = _random_graph(LocalCypherSession())
    s = TPUCypherSession()
    g = _random_graph(s)
    res = g.cypher(q)
    assert "MultiwayJoin" in _ops(res)
    assert result_digest(res) == result_digest(oracle.cypher(q))
    rows = res.records.to_maps()
    assert all(len({r["x"], r["y"], r["z"]}) == 3 for r in rows)


def test_delta_overlay_parity_after_live_writes():
    """The operator reads scans through the snapshot seam: writes that
    create NEW triangles after planning must appear (masked base ∪
    delta), digest-equal to the cascade on the same snapshot."""
    nodes = {("P",): [{"_id": i, "name": f"n{i}"} for i in range(6)]}
    edges = [(0, 1, {}), (1, 2, {})]
    s = TPUCypherSession()
    base = make_graph(s, nodes, {"K": edges})
    from caps_tpu.relational.updates import versioned
    vg = versioned(s, base)
    q = TRIANGLE_ENUM
    res0 = s.cypher_on_graph(vg, q)
    assert res0.records.size() == 0
    # close the triangle live, then add a second (parallel) closing edge
    s.cypher_on_graph(
        vg, "MATCH (a:P), (c:P) WHERE a.name = 'n0' AND c.name = 'n2' "
            "CREATE (a)-[:K]->(c)")
    res1 = s.cypher_on_graph(vg, q)
    assert "MultiwayJoin" in _ops(res1)
    assert _wcoj_strategy(res1) == ["wcoj"]
    assert res1.records.size() == 1
    s.cypher_on_graph(
        vg, "MATCH (a:P), (c:P) WHERE a.name = 'n0' AND c.name = 'n2' "
            "CREATE (a)-[:K]->(c)")
    res2 = s.cypher_on_graph(vg, q)
    assert res2.records.size() == 2  # parallel closing edges: 2 matches
    # cascade parity on the live snapshot
    s2 = TPUCypherSession(config=EngineConfig(use_wcoj=False))
    base2 = make_graph(s2, nodes, {"K": edges})
    vg2 = versioned(s2, base2)
    for w in ("MATCH (a:P), (c:P) WHERE a.name = 'n0' AND c.name = 'n2' "
              "CREATE (a)-[:K]->(c)",) * 2:
        s2.cypher_on_graph(vg2, w)
    assert result_digest(res2) == result_digest(s2.cypher_on_graph(vg2, q))


def test_mesh_sharded_session_parity():
    """On a mesh-sharded (cross-shard) session the WCOJ path defers to
    the okapi distributed joins — the op falls back, results stay
    digest-equal."""
    oracle = _random_graph(LocalCypherSession(), self_loops=False)
    s = TPUCypherSession(config=EngineConfig(mesh_shape=(8,)))
    g = _random_graph(s, self_loops=False)
    res = g.cypher(TRIANGLE_ENUM)
    assert result_digest(res) == result_digest(oracle.cypher(TRIANGLE_ENUM))
    strat = _wcoj_strategy(res)
    assert strat in ([], ["fallback-cascade"])


def test_multi_closing_pattern_substitutes_once():
    """A segment with TWO closing edges yields ONE MultiwayJoinOp —
    never a second one buried in the first one's fallback cascade — and
    EXPLAIN carries exactly one wcoj_strategy decision line."""
    q = ("MATCH (a:P)-[r1:K]->(b)-[r2:K]->(c), (a)-[r3:K]->(c), "
         "(b)-[r4:L]->(c) RETURN id(a) AS x, id(b) AS y, id(c) AS z")
    oracle = _random_graph(LocalCypherSession())
    s = TPUCypherSession()
    g = _random_graph(s)
    sub0 = s.metrics_registry.snapshot().get("wcoj.substituted", 0)
    exp = g.cypher("EXPLAIN " + q)
    assert exp.plans["relational"].count("MultiwayJoin") == 1, \
        exp.plans["relational"]
    assert exp.plans["cost"].count("wcoj_strategy") == 1
    assert s.metrics_registry.snapshot()["wcoj.substituted"] == sub0 + 1
    res = g.cypher(q)
    assert result_digest(res) == result_digest(oracle.cypher(q))
    assert _wcoj_strategy(res) == ["wcoj"]


# -- degraded fallback -------------------------------------------------------


def test_failing_wcoj_falls_back_then_heals():
    """The degraded ladder, deterministic: an injected WCOJ fault serves
    the SAME answer via the embedded cascade (wcoj.fallbacks +
    faults.injected.wcoj tick), and the NEXT execution takes the fast
    path again."""
    from caps_tpu.obs.metrics import global_registry
    oracle = _random_graph(LocalCypherSession())
    want = result_digest(oracle.cypher(TRIANGLE_ENUM))
    s = TPUCypherSession()
    g = _random_graph(s)
    before = s.metrics_registry.snapshot().get("wcoj.fallbacks", 0)
    inj0 = global_registry().snapshot().get("faults.injected.wcoj", 0)
    with faults.failing_wcoj(n_times=1) as budget:
        res = g.cypher(TRIANGLE_ENUM)
        assert result_digest(res) == want
        assert _wcoj_strategy(res) == ["fallback-cascade"]
    assert budget.injected == 1
    assert global_registry().snapshot()["faults.injected.wcoj"] == inj0 + 1
    assert s.metrics_registry.snapshot()["wcoj.fallbacks"] == before + 1
    healed = g.cypher(TRIANGLE_ENUM)
    assert _wcoj_strategy(healed) == ["wcoj"]
    assert result_digest(healed) == want


def test_failing_wcoj_permanent_keeps_serving():
    s = TPUCypherSession()
    g = _random_graph(s)
    oracle = _random_graph(LocalCypherSession())
    want = result_digest(oracle.cypher(TRIANGLE_ENUM))
    with faults.failing_wcoj(n_times=None) as budget:
        for _ in range(3):
            res = g.cypher(TRIANGLE_ENUM)
            assert result_digest(res) == want
            assert _wcoj_strategy(res) == ["fallback-cascade"]
    assert budget.injected == 3


# -- cost-model selection & EXPLAIN ------------------------------------------


def test_explain_renders_wcoj_choice_before_execution():
    """EXPLAIN must show the substituted operator AND the model's
    wcoj-vs-cascade decision line without executing anything."""
    s = TPUCypherSession()
    g = _random_graph(s)
    exp = g.cypher("EXPLAIN " + TRIANGLE_ENUM)
    assert exp.records is None
    assert "MultiwayJoin" in exp.plans["relational"]
    assert "wcoj_strategy" in exp.plans["cost"]
    assert "wcoj_cost" in exp.plans["cost"]
    assert "cascade_cost" in exp.plans["cost"]
    # the relational line carries the chosen anchors + strategy tag
    assert "anchors=" in exp.plans["relational"]


def test_wcoj_decision_surface_prices_both_sides():
    from caps_tpu.ir.pattern import Direction
    from caps_tpu.relational.cost import CostModel
    from caps_tpu.relational.stats import GraphStatistics
    s = TPUCypherSession()
    g = _random_graph(s, n=60, e=600)
    from caps_tpu.relational.stats import graph_statistics
    model = CostModel(graph_statistics(g), lattice=s.shape_lattice)
    ext = [(("K",), Direction.OUTGOING, frozenset(), 1.0, ()),
           (("K",), Direction.OUTGOING, frozenset(), 1.0, (("K",),))]
    use, est, info = model.wcoj_vs_cascade(
        frozenset({"P"}), 1.0, ext, [("K",)])
    assert use is True  # dense cyclic pattern: intersection must win
    assert info["wcoj_cost"] < info["cascade_cost"]
    assert est >= 1.0
    assert model.decisions[-1]["kind"] == "wcoj_strategy"


def test_use_wcoj_off_is_the_cascade_everywhere():
    s = TPUCypherSession(config=EngineConfig(use_wcoj=False))
    g = _random_graph(s)
    exp = g.cypher("EXPLAIN " + TRIANGLE_ENUM)
    assert "MultiwayJoin" not in exp.plans["relational"]
    assert "Join" in exp.plans["relational"]


def test_model_off_still_substitutes():
    """With the cost model disabled the detected shape substitutes
    unconditionally (the heuristic default) — and stays correct."""
    oracle = _random_graph(LocalCypherSession())
    s = TPUCypherSession(config=EngineConfig(use_cost_model=False))
    g = _random_graph(s)
    res = g.cypher(TRIANGLE_ENUM)
    assert "MultiwayJoin" in _ops(res)
    assert result_digest(res) == result_digest(oracle.cypher(TRIANGLE_ENUM))


def test_est_rows_feed_op_stats():
    """The operator stamps its model estimate so the observed-statistics
    divergence loop (re-planning) sees WCOJ executions like any other
    operator's."""
    s = TPUCypherSession()
    g = _random_graph(s)
    res = g.cypher(TRIANGLE_ENUM)
    entry = [m for m in res.metrics["operators"]
             if m["op"] == "MultiwayJoin"][0]
    assert "est_rows" in entry and entry["rows"] >= 0
    from caps_tpu.frontend.parser import normalize_query
    fam_stats = s.op_stats.stats(normalize_query(TRIANGLE_ENUM))
    wcoj_keys = [k for k in fam_stats if k.endswith(":MultiwayJoin")]
    assert wcoj_keys, fam_stats  # the WCOJ op's actuals recorded
    assert fam_stats[wcoj_keys[0]].get("est_rows") is not None


# -- compile accounting ------------------------------------------------------


def test_wcoj_charges_compile_kind_once_then_zero():
    """First execution charges the ``wcoj`` compile kind for its
    first-seen step shapes; the SAME shapes never charge again — and a
    fused replay of the whole query charges zero compile seconds."""
    s = TPUCypherSession()
    g = _random_graph(s)
    r1 = g.cypher(TRIANGLE_ENUM)
    kinds1 = {c["kind"] for c in r1.metrics.get("compile_charges", ())}
    assert "wcoj" in kinds1
    replays0 = s.fused.replays + s.fused.generic_replays
    r2 = g.cypher(TRIANGLE_ENUM)  # fused replay
    assert r2.metrics["compile_s_charged"] == 0.0
    assert s.fused.replays + s.fused.generic_replays == replays0 + 1
    # a second graph with the same shape buckets reuses the compiled
    # steps: no new wcoj charges
    g2 = _random_graph(s, seed=9)
    r3 = g2.cypher(TRIANGLE_ENUM)
    kinds3 = [c for c in r3.metrics.get("compile_charges", ())
              if c["kind"] == "wcoj"]
    assert kinds3 == []


def test_cyclic_count_family_unseen_binding_compiles_nothing():
    """The item-1 tail, closed for the cycle family (PR 12 converted the
    main count path): count-fused closures are keyed by the parameter
    SHAPE signature, so an unseen binding of a warmed cyclic count
    family charges compile_s == 0.0 — predicate masks rebuild as eager
    device args, nothing re-traces."""
    from caps_tpu.relational.count_pattern import CountCycleOp
    # the sweep: no count-family op may override the shape-keyed contract
    assert "_value_keyed" not in CountCycleOp.__dict__
    s = TPUCypherSession()
    g = _random_graph(s, self_loops=False)
    q = ("MATCH (a:P)-[:K]->(b)-[:K]->(c), (a)-[:K]->(c) "
         "WHERE a.name = $seed RETURN count(*) AS c")
    first = g.cypher(q, {"seed": "n1"})
    assert [m for m in first.metrics["operators"]
            if m["op"] == "CountCycle"][0]["strategy"] == "cycle-probe"
    assert first.metrics["compile_s_charged"] > 0.0
    oracle = _random_graph(LocalCypherSession(), self_loops=False)
    for seed in ("n2", "n7"):  # unseen bindings: zero compile charged
        res = g.cypher(q, {"seed": seed})
        assert res.metrics["compile_s_charged"] == 0.0, seed
        assert res.records.to_maps() == \
            oracle.cypher(q, {"seed": seed}).records.to_maps()


# -- planner/optimizer analysis ----------------------------------------------


def test_match_cyclic_segment_shapes():
    from caps_tpu.logical.optimizer import match_cyclic_segment
    s = TPUCypherSession()
    g = _random_graph(s)

    def seg_of(query):
        # plan logically, then find the top into-Expand
        from caps_tpu.frontend.parser import parse_query
        from caps_tpu.ir.builder import IRBuilder
        from caps_tpu.logical.planner import LogicalPlanner
        from caps_tpu.logical.optimizer import LogicalOptimizer
        from caps_tpu.logical import ops as L
        from caps_tpu.relational.plan_cache import PlanParams
        ir = IRBuilder(g.schema, None, PlanParams({})).process(
            parse_query(query))
        logical = LogicalOptimizer(None).process(
            LogicalPlanner(g.schema, None, PlanParams({})).process(ir))
        found = []

        def walk(op):
            if isinstance(op, L.Expand) and op.into:
                found.append(op)
            for c in op.children:
                if isinstance(c, L.LogicalOperator):
                    walk(c)

        walk(logical.root)
        return match_cyclic_segment(found[0]) if found else None

    seg = seg_of(TRIANGLE_ENUM)
    assert seg is not None
    assert seg.order == ("a", "b", "c")
    assert sum(1 for e in seg.edges if e.closing) == 1
    # var-length in the chain: not a WCOJ shape
    assert seg_of("MATCH (a:P)-[r1:K*1..2]->(b), (a)-[r3:K]->(b) "
                  "RETURN id(b) AS x") is None
    # BOTH-direction closing edge: not a WCOJ shape
    assert seg_of("MATCH (a:P)-[r1:K]->(b)-[r2:K]->(c), (a)-[r3:K]-(c) "
                  "RETURN id(b) AS x") is None


def test_plan_steps_anchor_choice():
    """Anchors follow the model's expected degree: with no model the
    introducing edge anchors; the deferred closing edge semi-filters
    and closes."""
    from caps_tpu.logical.optimizer import match_cyclic_segment
    from caps_tpu.relational.wcoj import plan_steps
    from caps_tpu.logical import ops as L
    from caps_tpu.ir.pattern import Direction
    scan = L.NodeScan(L.Start(), "a", frozenset({"P"}),
                      fields=(("a", None),))
    e1 = L.Expand(scan, "a", "r1", ("K",), "b", frozenset(),
                  Direction.OUTGOING, fields=())
    e2 = L.Expand(e1, "b", "r2", ("K",), "c", frozenset(),
                  Direction.OUTGOING, fields=())
    e3 = L.Expand(e2, "a", "r3", ("K",), "c", frozenset(),
                  Direction.OUTGOING, into=True, fields=())
    seg = match_cyclic_segment(e3)
    assert seg is not None
    extends, closes = plan_steps(seg, model=None)
    assert [s.var for s in extends] == ["b", "c"]
    assert extends[1].anchor.rel == "r2"
    assert [c.rel_types for c in extends[1].checks] == [("K",)]
    assert [c.edge.rel for c in closes] == ["r3"]

"""Shared test helpers: build graphs from plain Python data."""
from __future__ import annotations

from typing import Any, Dict, Iterable, List, Mapping, Tuple

from caps_tpu.okapi.types import CypherType, from_python, join_all, CTNull
from caps_tpu.relational.entity_tables import (
    NodeMapping, NodeTable, RelationshipMapping, RelationshipTable,
)


def _infer_types(rows: List[Mapping[str, Any]]) -> Dict[str, CypherType]:
    keys = sorted({k for r in rows for k in r})
    out = {}
    for k in keys:
        vals = [r.get(k) for r in rows]
        t = join_all(from_python(v) for v in vals if v is not None)
        if any(v is None or k not in r for v, r in zip(vals, rows)):
            t = t.nullable
        out[k] = t
    return out


def make_graph(session, nodes: Mapping[Tuple[str, ...], List[dict]],
               rels: Mapping[str, List[Tuple[int, int, dict]]],
               start_rel_id: int = 1000):
    """nodes: {labels-tuple: [{'_id': int, **props}]};
    rels: {TYPE: [(src, tgt, props)]} — rel ids auto-assigned."""
    factory = session.table_factory
    node_tables = []
    for labels, rows in nodes.items():
        props = _infer_types([{k: v for k, v in r.items() if k != "_id"}
                              for r in rows])
        data = {"_id": [r["_id"] for r in rows]}
        for k in props:
            data[k] = [r.get(k) for r in rows]
        from caps_tpu.okapi.types import CTInteger
        types = {"_id": CTInteger, **props}
        table = factory.from_columns(data, types)
        mapping = NodeMapping.on("_id").with_implied_labels(*labels)
        for k in props:
            mapping = mapping.with_property(k)
        node_tables.append(NodeTable(mapping, table))
    rel_tables = []
    rid = start_rel_id
    for rel_type, edges in rels.items():
        props = _infer_types([e[2] for e in edges])
        data = {"_id": [], "_src": [], "_tgt": []}
        for k in props:
            data[k] = []
        for src, tgt, p in edges:
            data["_id"].append(rid)
            rid += 1
            data["_src"].append(src)
            data["_tgt"].append(tgt)
            for k in props:
                data[k].append(p.get(k))
        from caps_tpu.okapi.types import CTInteger
        types = {"_id": CTInteger, "_src": CTInteger, "_tgt": CTInteger, **props}
        table = factory.from_columns(data, types)
        mapping = RelationshipMapping.on(rel_type)
        for k in props:
            mapping = mapping.with_property(k)
        rel_tables.append(RelationshipTable(mapping, table))
    return session.create_graph(node_tables, rel_tables)


def social_graph(session):
    """The bundled SocialNetworkExample data (benchmark config 1): Alice,
    Bob, Carol connected by KNOWS edges."""
    return make_graph(
        session,
        nodes={
            ("Person",): [
                {"_id": 1, "name": "Alice", "age": 23},
                {"_id": 2, "name": "Bob", "age": 42},
                {"_id": 3, "name": "Carol", "age": 1984},
            ],
        },
        rels={
            "KNOWS": [
                (1, 2, {"since": 2017}),
                (2, 3, {"since": 2016}),
            ],
        },
    )


def bag(rows: Iterable[Mapping[str, Any]]):
    """Multiset of result rows for order-insensitive comparison."""
    from caps_tpu.testing.bag import Bag
    return Bag(rows)
